"""AST-based custom lint for the spartan_tpu codebase itself.

Eighteen repo-specific rules that generic linters cannot know:

1. ``shard_map`` must be imported ONLY through the version-compat shim
   ``spartan_tpu/utils/compat.py`` (PR 1): importing it from jax
   directly (``jax.shard_map`` / ``jax.experimental.shard_map``) at a
   call site reintroduces the cross-version breakage the shim exists
   to absorb.

2. Every concrete ``Expr`` subclass must provide ``_sig`` and
   ``replace_children`` somewhere below the ``Expr`` base — a subclass
   relying on the base's ``NotImplementedError`` stubs silently breaks
   the structural compile/plan caches and the optimizer rewrite
   machinery the moment such a node lands in a DAG.

3. No raw wall-clock timing (``time.perf_counter()`` and friends)
   outside ``spartan_tpu/obs/`` and ``spartan_tpu/utils/profiling.py``
   (the observability PR): ALL in-package timing must ride the
   span/phase/stopwatch API so every measured interval lands in the
   trace ring and the metrics registry — a raw clock pair is
   invisible to ``st.trace_export``/``st.metrics`` and silently
   escapes the trace.

4. No raw ``jax.debug.callback`` / ``jax.debug.print`` outside
   ``spartan_tpu/obs/`` and ``spartan_tpu/expr/loop.py`` (the
   numerics-sentinel PR): ALL device->host telemetry must flow
   through the sentinel API (``obs/numerics.probe`` /
   ``guard_finite`` / ``record_loop_health``, ``obs/trace``'s
   loop-step marks) so it is session-collected, metrics-fed and
   trace-visible — a raw callback is invisible to ``st.audit`` and
   the crash-dump machinery, and its host cost escapes every
   overhead gate.

5. No broad exception handling (bare ``except:``, ``except
   Exception``, ``except RuntimeError``) around compile/dispatch
   calls (``evaluate`` / ``force`` / ``recompute`` / ``_dispatch`` /
   ``jit``) outside ``spartan_tpu/resilience/`` (the resilient-
   execution PR): ad-hoc catch-and-retry around the dispatch path is
   exactly the blind-retry bug class the classifier + policy engine
   replaced — it retries deterministic errors, bypasses the per-plan
   retry budget, and its failures are invisible to the
   ``resilience_*`` metrics and crash-dump forensics. The TWO
   sanctioned shapes outside ``resilience/`` are a handler that routes
   straight into the engine (calls ``handle_failure``) — how
   ``expr/base.evaluate`` wires the boundary — and a handler that
   hands the classified, already-retried failure to its caller
   through a serve future (calls ``_reject`` / ``set_exception``) —
   how ``serve/engine`` wires the worker boundary. Neither retries.

6. No direct access to the shared evaluation caches
   (``_plan_cache`` / ``_compile_cache`` / ``_cache_lock``) outside
   ``spartan_tpu/expr/base.py``, and none to the metrics registry's
   internal tables (``_counters`` / ``_gauges`` / ``_hists``) outside
   ``spartan_tpu/obs/metrics.py`` (the concurrent-serving PR): these
   are hot SHARED state with a documented locking discipline, and a
   bare dict poke from another module bypasses the lock, the LRU
   recency order and the eviction accounting. Go through the
   accessors (``lookup_plan`` / ``store_plan`` / ``cached_executable``
   / ``clear_*``; ``REGISTRY.counter()/gauge()/histogram()``).

7. No mesh object stored in module globals or class attributes
   outside ``spartan_tpu/parallel/`` (the elastic-recovery PR): a
   ``get_mesh()``/``build_mesh()``/``Mesh(...)`` result captured in a
   long-lived global outlives a ``rebuild_mesh`` — after device loss
   the mesh epoch advances and every cached mesh (and any sharding
   derived from it) points at dead devices, invisible to the
   epoch fence that protects ``get_mesh()`` callers. Flagged shapes:
   module-level and class-body assignments whose value calls one of
   those constructors, and function-body assignments to names
   declared ``global``. Instance attributes (a DistArray's birth
   mesh) are fine — they carry the birth EPOCH alongside, and
   cross-epoch use raises ``StaleMeshError``.

8. No direct ``.memory_stats()`` calls outside ``obs/metrics.py``,
   ``parallel/mesh.py`` and ``resilience/memory.py`` (the memory-
   governor PR): the HBM-budget auto-detect and every exported memory
   gauge must agree on ONE aggregated read-out across all local
   devices — a stray per-device read reintroduces the
   only-device-0 blind spot the governor PR fixed, and its numbers
   silently disagree with ``FLAGS.hbm_budget_bytes`` auto-detection
   and the ``device_*`` gauges. Go through
   ``obs.metrics.device_memory_aggregate()``.

9. No raw ``jax.profiler`` use outside ``obs/trace.py`` and
   ``obs/profile.py`` (tightened by the device-time attribution PR:
   the tracer owns the capture seam, the profiler is the ONE new
   sanctioned consumer), and no direct ``.cost_analysis()`` /
   ``.memory_analysis()`` calls outside ``obs/explain.py`` and
   ``resilience/memory.py`` (the cost-ledger PR): every device-time
   measurement and compiled-program introspection must flow through
   the sanctioned entry points (``obs.trace.device_profile`` /
   ``.annotate``, ``obs.profile.profile``,
   ``obs.explain.compiled_cost_analysis``,
   ``resilience.memory.validate_plan``) so the reading lands in the
   cost ledger next to the model's prediction — a stray profiler
   capture or cost read-out produces numbers the calibration loop
   never sees and cannot be compared against the committed gates.

10. No raw ``jax.lax.with_sharding_constraint`` outside
    ``parallel/redistribute.py`` and ``expr/base.py`` (the
    redistribution-planner PR): every sharding-constraint call site is
    a reshard edge the cost-modeled planner must see — a raw
    constraint is invisible to the planner (its edge is never priced,
    never eligible for the explicit collective lowering, and absent
    from ``st.explain``'s schedule report). Go through
    ``parallel.redistribute.constrain()`` (pass ``src=`` when the
    producing layout is known so the edge is plannable); the two
    allowed files are the planner itself and the ``Expr.lower`` /
    jit-output seam that defines the fallback.

11. No raw ``jax.named_scope`` outside ``expr/base.py`` and ``obs/``
    (the device-time attribution PR): the per-node scopes
    ``Expr.lower`` emits carry the structural-signature digest the
    profiler's trace-parse tier JOINS on (``obs/profile.scope_name``),
    and ``obs.trace.named_scope`` is the sanctioned wrapper for fixed
    labels — a raw scope elsewhere invents names the attribution
    report can never map back to an expr node.

12. No ``jax.experimental.pallas`` import (or ``pallas_call`` use)
    outside ``spartan_tpu/kernels/`` (the partitionable-kernel PR):
    every Pallas kernel goes through the kernel layer so its grid
    derives from the committed tiling and its backend choice is
    keyed, selectable and explainable (docs/KERNELS.md).

13. No JAX AOT executable-serialization use
    (``jax.experimental.serialize_executable`` — ``serialize`` /
    ``deserialize_and_load``) and no direct ``FLAGS.persist_cache_dir``
    reads outside ``spartan_tpu/persist/`` (the warm-start PR): the
    store owns the fingerprint rule, the CRC/atomic-write discipline,
    the lease-writer protocol and the degrade-to-recompile contract
    (docs/WARMSTART.md) — a stray serialize call produces bytes no
    fingerprint protects, and a stray dir read bypasses the store
    singleton's failure handling. Go through ``spartan_tpu.persist``
    (``active()`` / ``lookup()`` / ``maybe_store()`` / ``prewarm()``).

14. No stores to a DistArray's private buffer/lineage state
    (``._jax`` / ``._lineage`` / ``._version``) outside
    ``spartan_tpu/array/`` and the incremental seam
    (``spartan_tpu/expr/incremental.py``) — the delta-aware PR: the
    incremental result cache trusts the Lineage mutation log as the
    ONLY way data changes under a stable leaf identity
    (docs/INCREMENTAL.md); a stray buffer poke makes a dirty tile
    look clean and the cache serves stale results, bit-INequal to a
    recompute. Mutate through ``DistArray.update()`` / ``st.assign``.

15. No ``lax.dynamic_slice`` / ``lax.dynamic_update_slice`` outside
    the incremental seam (``spartan_tpu/expr/incremental.py``) — the
    plan-auditor PR: with traced start indices GSPMD cannot prove the
    slice stays inside one shard, so it ALL-GATHERS the full sharded
    operand onto every chip before slicing — the pathological
    communication class ``st.audit_plan`` exists to flag
    (analysis/plan_audit.py, finding kind ``full_gather``). The
    incremental engine's stash path is the ONE sanctioned
    construction site: it pays the gather knowingly, on the
    delta-sized stash, never the full operand (docs/INCREMENTAL.md).
    The static-bound forms (``dynamic_slice_in_dim`` on unsharded
    axes, ``lax.slice``) are fine and not flagged.

16. No background-thread construction (``threading.Thread`` /
    ``threading.Timer``) outside the three sanctioned concurrency
    seams — ``spartan_tpu/serve/`` (the worker pool),
    ``spartan_tpu/resilience/`` (recovery drills), and the named
    daemon files ``obs/monitor.py`` (the sampler),
    ``obs/numerics.py`` (the dispatch watchdog) and
    ``persist/__init__.py`` (store prewarm) — the closed-loop
    telemetry PR: every long-lived thread must be one the monitor's
    epoch fence, the serve drain barrier and the crash-dump span
    tree know about. A stray thread elsewhere dodges the mesh-epoch
    fence (it can dispatch on a dead-device mesh after
    ``rebuild_mesh``), never appears in ``st.status()``'s health
    section, and leaks past ``shutdown()``. Locks / Events /
    Conditions are fine everywhere — the rule is about threads of
    execution, not synchronization primitives.

17. No raw ``addressable_shards`` iteration outside the shard-walk
    seam (``obs/skew.local_shards`` / ``per_shard_stats``), the array
    layer that owns the buffers, and the checkpoint serializer — the
    skew-observatory PR: every per-tile read-out must agree on device
    labels, index formatting and host-fetch behavior, or straggler
    attribution, tile health and checkpoints disagree about which
    shard is which.

18. No per-shard checksum walks or shard-buffer bit surgery
    (``shard_checksums`` / ``flip_bit``) outside the integrity seam —
    ``resilience/integrity.py`` (the SDC sentinel that owns both) and
    ``resilience/faults.py`` (the chaos injector that delegates its
    ``sdc`` corruption to it) — the SDC-sentinel PR: a checksum
    computed elsewhere drifts on shard ordering and byte layout, so
    its verdicts stop matching the sentinel's detect/attribute
    pipeline, and a buffer flip outside the seam is silent data
    corruption the sentinel cannot distinguish from the real thing.

Run stand-alone (``python tools/lint_repo.py``; exit 1 on findings;
``--json`` emits the findings as a JSON array for CI tooling) or as a
module (``python -m tools.lint_repo``) or through the tier-1 suite
(tests/test_lint_repo.py).
"""

from __future__ import annotations

import ast
import os
import sys
from typing import Dict, List, Optional, Set, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "spartan_tpu")

# the one module allowed to touch jax's shard_map export directly
SHARD_MAP_SHIM = os.path.join("spartan_tpu", "utils", "compat.py")

# abstract Expr layers that intentionally leave the hooks to subclasses
_ABSTRACT_EXPRS = {"Expr"}

# the only places allowed to read the raw wall clock (rule 3): the
# observability layer itself and the profiling facade over it
_TIMING_ALLOWED_DIRS = (os.path.join("spartan_tpu", "obs") + os.sep,)
_TIMING_ALLOWED_FILES = {os.path.join("spartan_tpu", "utils",
                                      "profiling.py")}
_CLOCK_FNS = {"perf_counter", "perf_counter_ns", "monotonic",
              "monotonic_ns"}

# the only places allowed to emit raw device->host debug callbacks
# (rule 4): the sentinel/tracer themselves, and the loop lowering that
# wires the per-iteration marks into them
_DEBUG_CB_ALLOWED_DIRS = (os.path.join("spartan_tpu", "obs") + os.sep,)
_DEBUG_CB_ALLOWED_FILES = {os.path.join("spartan_tpu", "expr",
                                        "loop.py")}
_DEBUG_CB_FNS = {"callback", "print"}

# rule 5: the only place allowed to catch broadly around the
# compile/dispatch path is the resilience subsystem itself
_RECOVERY_ALLOWED_DIRS = (os.path.join("spartan_tpu", "resilience")
                          + os.sep,)
_BROAD_HANDLERS = {"Exception", "BaseException", "RuntimeError"}
_DISPATCH_CALLS = {"evaluate", "force", "recompute", "_dispatch", "jit"}
# a handler that immediately routes into the policy engine
# (expr/base.evaluate) or hands the terminal failure to the caller
# through a serve future (serve/engine workers) is a sanctioned
# boundary shape — neither retries
_ENGINE_ROUTES = {"handle_failure", "_handle_failure",
                  "_reject", "set_exception",
                  # the incremental engine's honest-fallback seam
                  # (expr/incremental.py): the handler records the
                  # reason and returns NOT_HANDLED so the ordinary
                  # full dispatch runs — whose failures DO route
                  # through the policy engine. It never retries.
                  "degrade_to_full"}

# rule 6: owners of the hot shared state; everyone else goes through
# the accessors so locking/LRU/eviction stay in one place
_CACHE_NAMES = {"_plan_cache", "_compile_cache", "_cache_lock"}
_CACHE_OWNER = os.path.join("spartan_tpu", "expr", "base.py")
_REGISTRY_INTERNALS = {"_counters", "_gauges", "_hists"}
_METRICS_OWNER = os.path.join("spartan_tpu", "obs", "metrics.py")

# rule 8: the only modules allowed to read device memory_stats
# directly — budget auto-detect and memory gauges stay single-sourced
_MEMSTATS_ALLOWED_FILES = {
    os.path.join("spartan_tpu", "obs", "metrics.py"),
    os.path.join("spartan_tpu", "parallel", "mesh.py"),
    os.path.join("spartan_tpu", "resilience", "memory.py"),
}

# rule 9: device-time instrumentation single-sourcing, per entry
# point. Raw jax.profiler use lives in the tracer's capture seam plus
# the attribution profiler (its ONE sanctioned new consumer); compiled
# cost/memory introspection lives with explain's normalizer and the
# memory governor's validate_plan — so every reading can land in the
# cost ledger
_PROFILER_ALLOWED_FILES = {
    os.path.join("spartan_tpu", "obs", "trace.py"),
    os.path.join("spartan_tpu", "obs", "profile.py"),
}
_ANALYSIS_ALLOWED_FILES = {
    os.path.join("spartan_tpu", "obs", "explain.py"),
    os.path.join("spartan_tpu", "resilience", "memory.py"),
}
_ANALYSIS_CALLS = {"cost_analysis", "memory_analysis"}

# rule 11: raw jax.named_scope sites — the digest-carrying per-node
# scopes (expr/base.Expr.lower via obs/profile.scope_name) and the
# obs layer's own wrapper; everyone else goes through
# obs.trace.named_scope so scope names stay joinable by the profiler
_NAMED_SCOPE_ALLOWED_DIRS = (os.path.join("spartan_tpu", "obs")
                             + os.sep,)
_NAMED_SCOPE_ALLOWED_FILES = {
    os.path.join("spartan_tpu", "expr", "base.py"),
}

# rule 10: the only places allowed to call with_sharding_constraint
# directly — the redistribution planner (which decides explicit
# schedule vs GSPMD fallback per edge) and the expr/base lowering seam
# that routes through it
_WSC_ALLOWED_FILES = {
    os.path.join("spartan_tpu", "parallel", "redistribute.py"),
    os.path.join("spartan_tpu", "expr", "base.py"),
}

# rule 7: mesh constructors whose results must not live in module
# globals / class attributes outside the owning package — a captured
# mesh outlives rebuild_mesh and dodges the epoch fence
_MESH_MAKERS = {"get_mesh", "build_mesh", "rebuild_mesh", "Mesh"}
_MESH_ALLOWED_DIRS = (os.path.join("spartan_tpu", "parallel") + os.sep,)

# rule 13: the warm-start store (spartan_tpu/persist) is the only
# owner of JAX AOT executable serialization and of the persist
# directory itself — everyone else goes through the persist API so
# fingerprints, CRCs, leases and degrade-to-recompile stay in one
# place
_PERSIST_ALLOWED_DIRS = (os.path.join("spartan_tpu", "persist")
                         + os.sep,)
_PERSIST_SERIALIZE_NAMES = {"serialize_executable",
                            "deserialize_and_load"}

# rule 12: Pallas is the kernel layer's private dependency. A raw
# pallas_call outside spartan_tpu/kernels/ bypasses the selection
# policy (kernels.select), the tiling->grid derivation, the
# plan/compile-key separation and the interpret-mode parity contract
# (docs/KERNELS.md) — exactly the single-device dead ends the seed's
# ops/kmeans.py and ops/segment.py kernels were.
_PALLAS_ALLOWED_DIRS = (os.path.join("spartan_tpu", "kernels")
                        + os.sep,)

# rule 14: a DistArray's buffer/lineage state (_jax, _lineage,
# _version) is the incremental engine's ground truth — a write from
# anywhere but the array layer or the incremental seam silently
# detaches the mutation log from the data, and the result cache then
# serves stale tiles as "clean" (docs/INCREMENTAL.md).
_MUTATION_ALLOWED_DIRS = (os.path.join("spartan_tpu", "array")
                          + os.sep,)
_MUTATION_ALLOWED_FILES = (
    os.path.join("spartan_tpu", "expr", "incremental.py"),)
_MUTATION_ATTRS = {"_jax", "_lineage", "_version"}

# rule 15: a traced-start dynamic slice on a sharded operand lowers
# to a FULL all-gather of that operand (GSPMD cannot bound traced
# indices to one shard) — the worst communication shape the plan
# auditor flags (analysis/plan_audit.py). Only the incremental
# engine's stash path may construct one, and only on delta-sized
# data (docs/INCREMENTAL.md). Exact-name match: the *_in_dim
# helpers and lax.slice have static bounds and are fine.
_DYNSLICE_ALLOWED_FILES = (
    os.path.join("spartan_tpu", "expr", "incremental.py"),)
_DYNSLICE_ATTRS = {"dynamic_slice", "dynamic_update_slice"}

# rule 16: the sanctioned concurrency seams — every background thread
# in the package is one the monitor's epoch fence, the serve drain
# barrier and the crash-dump span tree account for. Thread/Timer
# CONSTRUCTION only; Lock/Event/Condition are synchronization, not
# threads of execution, and are fine everywhere.
_THREAD_ALLOWED_DIRS = (
    os.path.join("spartan_tpu", "serve") + os.sep,
    os.path.join("spartan_tpu", "resilience") + os.sep,
)
_THREAD_ALLOWED_FILES = {
    os.path.join("spartan_tpu", "obs", "monitor.py"),
    os.path.join("spartan_tpu", "obs", "numerics.py"),
    os.path.join("spartan_tpu", "persist", "__init__.py"),
}
_THREAD_CTORS = {"Thread", "Timer"}

# rule 17: raw ``addressable_shards`` iteration is the shard-walk
# seam — every per-tile read-out must agree on device labels, index
# formatting and host-fetch behavior, or the skew observatory's
# imbalance attribution, numerics tile-health and checkpointing
# disagree about which shard is which. One sanctioned walk
# (obs/skew.local_shards / per_shard_stats), the array layer that
# owns the buffers, and the checkpoint serialization seam.
_SHARDS_ALLOWED_DIRS = (os.path.join("spartan_tpu", "array") + os.sep,)
_SHARDS_ALLOWED_FILES = {
    os.path.join("spartan_tpu", "obs", "skew.py"),
    os.path.join("spartan_tpu", "utils", "checkpoint.py"),
}

# rule 18: per-shard checksum walks and shard-buffer bit surgery are
# the integrity seam — the SDC sentinel owns both ends (detect AND
# inject), so checksums never drift on shard ordering/byte layout and
# every deliberate flip is one the sentinel can account for
_CHECKSUM_ALLOWED_FILES = {
    os.path.join("spartan_tpu", "resilience", "integrity.py"),
    os.path.join("spartan_tpu", "resilience", "faults.py"),
}
_CHECKSUM_NAMES = {"shard_checksums", "flip_bit"}


class Finding:
    def __init__(self, path: str, line: int, rule: str, message: str):
        self.path = os.path.relpath(path, REPO)
        self.line = line
        self.rule = rule
        self.message = message

    def __str__(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    __repr__ = __str__


def _iter_py_files(root: str = PACKAGE) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        out.extend(os.path.join(dirpath, f) for f in filenames
                   if f.endswith(".py"))
    return sorted(out)


def _is_shim(path: str) -> bool:
    return os.path.relpath(path, REPO) == SHARD_MAP_SHIM


def lint_shard_map_imports(path: str, tree: ast.AST) -> List[Finding]:
    """Rule 1: no direct jax shard_map import outside the shim."""
    if _is_shim(path):
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            binds = any(a.name == "shard_map" or a.asname == "shard_map"
                        for a in node.names)
            from_shim = mod.endswith("utils.compat") or mod == "compat"
            if "shard_map" in mod and not from_shim:
                findings.append(Finding(
                    path, node.lineno, "shard-map-shim",
                    f"import from {mod!r}: import shard_map from "
                    "spartan_tpu.utils.compat (the version shim), "
                    "not from jax directly"))
            elif binds and not from_shim:
                findings.append(Finding(
                    path, node.lineno, "shard-map-shim",
                    f"binds shard_map from {mod!r}: only "
                    "spartan_tpu.utils.compat may import it from jax"))
        elif isinstance(node, ast.Import):
            for a in node.names:
                if "shard_map" in a.name:
                    findings.append(Finding(
                        path, node.lineno, "shard-map-shim",
                        f"import {a.name}: use the "
                        "spartan_tpu.utils.compat shim"))
        elif isinstance(node, ast.Attribute) and node.attr == "shard_map":
            # jax.experimental.shard_map / jax.shard_map attribute use
            root = node.value
            parts = []
            while isinstance(root, ast.Attribute):
                parts.append(root.attr)
                root = root.value
            if isinstance(root, ast.Name) and root.id == "jax":
                findings.append(Finding(
                    path, node.lineno, "shard-map-shim",
                    "attribute access on jax's shard_map: use the "
                    "spartan_tpu.utils.compat shim"))
    return findings


def lint_raw_timing(path: str, tree: ast.AST) -> List[Finding]:
    """Rule 3: no raw wall-clock timing outside obs/ + the profiling
    facade — timing that bypasses the span/phase/stopwatch API never
    reaches the trace ring or the metrics registry."""
    rel = os.path.relpath(path, REPO)
    if rel in _TIMING_ALLOWED_FILES or any(
            rel.startswith(d) for d in _TIMING_ALLOWED_DIRS):
        return []
    findings: List[Finding] = []

    def flag(node: ast.AST, what: str) -> None:
        findings.append(Finding(
            path, getattr(node, "lineno", 0), "raw-timing",
            f"{what}: time all in-package work through the span/phase "
            "API (utils/profiling.phase / .stopwatch / obs.trace.span) "
            "so it lands in the trace ring and metrics registry"))

    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) and node.attr in _CLOCK_FNS:
            root = node.value
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id in ("time", "_time"):
                flag(node, f"raw {root.id}.{node.attr}() timing")
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "") == "time":
                for a in node.names:
                    if a.name in _CLOCK_FNS:
                        flag(node, f"binds time.{a.name} directly")
    return findings


def lint_debug_callbacks(path: str, tree: ast.AST) -> List[Finding]:
    """Rule 4: no raw jax.debug.callback / jax.debug.print outside
    obs/ + expr/loop.py — device->host telemetry that bypasses the
    sentinel API is invisible to st.audit, the metrics registry and
    the crash-dump machinery."""
    rel = os.path.relpath(path, REPO)
    if rel in _DEBUG_CB_ALLOWED_FILES or any(
            rel.startswith(d) for d in _DEBUG_CB_ALLOWED_DIRS):
        return []
    findings: List[Finding] = []

    def flag(node: ast.AST, what: str) -> None:
        findings.append(Finding(
            path, getattr(node, "lineno", 0), "raw-debug-callback",
            f"{what}: route device->host telemetry through the "
            "numerics sentinel (obs/numerics.probe / guard_finite / "
            "record_loop_health) so it is audit-collected, "
            "metrics-fed and crash-dump-visible"))

    for node in ast.walk(tree):
        if (isinstance(node, ast.Attribute)
                and node.attr in _DEBUG_CB_FNS
                and isinstance(node.value, ast.Attribute)
                and node.value.attr == "debug"):
            root = node.value.value
            if isinstance(root, ast.Name) and root.id == "jax":
                flag(node, f"raw jax.debug.{node.attr}")
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.startswith("jax.debug"):
                flag(node, f"import from {mod!r}")
            elif mod == "jax" and any(
                    a.name == "debug" for a in node.names):
                flag(node, "binds jax.debug directly")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith("jax.debug"):
                    flag(node, f"import {a.name}")
    return findings


def _call_names(nodes) -> Set[str]:
    """Function names called anywhere under ``nodes`` (Name or the
    final Attribute segment: ``jax.jit`` -> ``jit``)."""
    out: Set[str] = set()
    for stmt in nodes:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Call):
                fn = node.func
                if isinstance(fn, ast.Name):
                    out.add(fn.id)
                elif isinstance(fn, ast.Attribute):
                    out.add(fn.attr)
    return out


def lint_bare_recovery(path: str, tree: ast.AST) -> List[Finding]:
    """Rule 5: no broad except around compile/dispatch calls outside
    resilience/ — blind catch-and-retry bypasses the classifier, the
    retry budget and the resilience metrics/forensics."""
    rel = os.path.relpath(path, REPO)
    if any(rel.startswith(d) for d in _RECOVERY_ALLOWED_DIRS):
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Try):
            continue
        guarded = _call_names(node.body) & _DISPATCH_CALLS
        if not guarded:
            continue
        for handler in node.handlers:
            t = handler.type
            if t is None:
                caught = {"<bare>"}
            else:
                elts = t.elts if isinstance(t, ast.Tuple) else [t]
                caught = set()
                for e in elts:
                    if isinstance(e, ast.Name):
                        caught.add(e.id)
                    elif isinstance(e, ast.Attribute):
                        caught.add(e.attr)
            broad = ({"<bare>"} & caught) or (caught & _BROAD_HANDLERS)
            if not broad:
                continue
            if _call_names(handler.body) & _ENGINE_ROUTES:
                continue  # routes into the policy engine: sanctioned
            findings.append(Finding(
                path, handler.lineno, "bare-recovery",
                f"broad except ({', '.join(sorted(broad))}) around "
                f"{'/'.join(sorted(guarded))}: recovery decisions "
                "belong to spartan_tpu/resilience (classifier + "
                "policy engine) — catch a specific exception, or "
                "route the failure into "
                "resilience.engine.handle_failure"))
    return findings


def lint_shared_state(path: str, tree: ast.AST) -> List[Finding]:
    """Rule 6: the plan/compile caches and the metrics registry's
    internal tables are touched only by their owning modules — any
    other access bypasses the locking discipline, the LRU recency
    order and the eviction accounting the serving engine relies on."""
    rel = os.path.relpath(path, REPO)
    cache_owner = rel == _CACHE_OWNER
    metrics_owner = rel == _METRICS_OWNER
    findings: List[Finding] = []

    def check(node: ast.AST, name: str) -> None:
        if name in _CACHE_NAMES and not cache_owner:
            findings.append(Finding(
                path, getattr(node, "lineno", 0), "shared-state",
                f"direct access to {name}: the plan/compile caches "
                "are shared hot state owned by expr/base.py — go "
                "through lookup_plan / store_plan / cached_executable "
                "/ clear_plan_cache / clear_compile_cache so the "
                "locking discipline, LRU order and eviction "
                "accounting hold"))
        elif name in _REGISTRY_INTERNALS and not metrics_owner:
            findings.append(Finding(
                path, getattr(node, "lineno", 0), "shared-state",
                f"direct access to registry internals ({name}): use "
                "REGISTRY.counter()/gauge()/histogram()/snapshot() — "
                "the instrument tables are lock-guarded shared state "
                "owned by obs/metrics.py"))

    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute):
            check(node, node.attr)
        elif isinstance(node, ast.Name):
            check(node, node.id)
    return findings


def _calls_mesh_maker(value: ast.AST) -> Optional[str]:
    """The mesh-constructor name called anywhere under ``value``, or
    None. Matches ``get_mesh()``, ``mesh_mod.build_mesh(...)``,
    ``Mesh(arr, axes)`` — by the final name segment."""
    for node in ast.walk(value):
        if isinstance(node, ast.Call):
            fn = node.func
            name = (fn.id if isinstance(fn, ast.Name)
                    else fn.attr if isinstance(fn, ast.Attribute)
                    else None)
            if name in _MESH_MAKERS:
                return name
    return None


def lint_mesh_capture(path: str, tree: ast.AST) -> List[Finding]:
    """Rule 7: no mesh object captured in module globals or class
    attributes outside parallel/ — a stored mesh outlives
    rebuild_mesh and dodges the epoch fence (elastic recovery)."""
    rel = os.path.relpath(path, REPO)
    if any(rel.startswith(d) for d in _MESH_ALLOWED_DIRS):
        return []
    findings: List[Finding] = []

    def flag(node: ast.AST, maker: str, where: str) -> None:
        findings.append(Finding(
            path, getattr(node, "lineno", 0), "mesh-capture",
            f"{maker}() result stored in a {where}: a captured mesh "
            "outlives rebuild_mesh (device loss bumps the mesh epoch "
            "and the stored mesh points at dead devices). Call "
            "get_mesh() at use time, or store the mesh on an instance "
            "TOGETHER with its birth epoch (as DistArray does)"))

    def scan_block(body, where: str) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                value = stmt.value
                if value is None:
                    continue
                maker = _calls_mesh_maker(value)
                if maker:
                    flag(stmt, maker, where)

    scan_block(tree.body, "module global")
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            scan_block(node.body, "class attribute")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            declared_global = {
                n for stmt in ast.walk(node)
                if isinstance(stmt, ast.Global) for n in stmt.names}
            if not declared_global:
                continue
            for stmt in ast.walk(node):
                if not isinstance(stmt, ast.Assign):
                    continue
                targets = {t.id for t in stmt.targets
                           if isinstance(t, ast.Name)}
                if targets & declared_global:
                    maker = _calls_mesh_maker(stmt.value)
                    if maker:
                        flag(stmt, maker, "module global (via "
                             "`global` declaration)")
    return findings


def lint_raw_memory_stats(path: str, tree: ast.AST) -> List[Finding]:
    """Rule 8: no direct ``.memory_stats()`` calls outside the three
    sanctioned modules — the budget auto-detect and the device gauges
    must read ONE aggregated source across all local devices."""
    rel = os.path.relpath(path, REPO)
    if rel in _MEMSTATS_ALLOWED_FILES:
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "memory_stats"):
            findings.append(Finding(
                path, node.lineno, "raw-memory-stats",
                "direct .memory_stats() call: device memory read-outs "
                "are single-sourced (obs/metrics.py, parallel/mesh.py, "
                "resilience/memory.py) so the HBM budget auto-detect "
                "and the device_* gauges agree — use "
                "obs.metrics.device_memory_aggregate() (all local "
                "devices, max+sum), not a per-device probe"))
    return findings


def lint_dynamic_slices(path: str, tree: ast.AST) -> List[Finding]:
    """Rule 15: no ``dynamic_slice`` / ``dynamic_update_slice``
    outside the incremental engine's stash seam — with traced starts
    on a sharded operand the lowering is a full all-gather, the
    communication class the plan auditor flags as ``full_gather``."""
    rel = os.path.relpath(path, REPO)
    if rel in _DYNSLICE_ALLOWED_FILES:
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        attr = None
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _DYNSLICE_ATTRS):
            attr = node.func.attr
        elif (isinstance(node, (ast.ImportFrom, ast.Import))):
            names = {a.name for a in node.names}
            hit = names & _DYNSLICE_ATTRS
            if hit and getattr(node, "module", "") in (
                    "jax.lax", "jax", "lax"):
                attr = sorted(hit)[0]
        if attr is not None:
            findings.append(Finding(
                path, node.lineno, "traced-start-slice",
                f"{attr} outside the incremental seam: a traced-start "
                "slice of a sharded operand lowers to a FULL "
                "all-gather of that operand (st.audit_plan flags it "
                "as full_gather) — only expr/incremental.py's "
                "delta-sized stash path may pay that knowingly "
                "(docs/INCREMENTAL.md); use static-bound slicing "
                "(lax.slice / dynamic_slice_in_dim on unsharded "
                "axes) or the incremental API instead"))
    return findings


def lint_shard_walks(path: str, tree: ast.AST) -> List[Finding]:
    """Rule 17: no raw ``addressable_shards`` access outside the
    shard-walk seam (obs/skew.py), the array layer and the checkpoint
    serializer — per-tile read-outs that bypass
    ``obs.skew.per_shard_stats`` / ``local_shards`` drift on device
    labels and fetch behavior, and the skew observatory's straggler
    attribution stops matching what the other surfaces report."""
    rel = os.path.relpath(path, REPO)
    if rel in _SHARDS_ALLOWED_FILES or any(
            rel.startswith(d) for d in _SHARDS_ALLOWED_DIRS):
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        if (isinstance(node, ast.Attribute)
                and node.attr == "addressable_shards"):
            findings.append(Finding(
                path, node.lineno, "shard-walk",
                "raw .addressable_shards access outside the shard-walk "
                "seam: per-tile reads are single-sourced through "
                "obs.skew.per_shard_stats(arr) / local_shards(jarr) "
                "(plus the array layer and utils/checkpoint.py's "
                "serializer) so device labels, shard indices and "
                "host-fetch behavior agree across the skew "
                "observatory, tile health and checkpoints — use those "
                "helpers instead (docs/OBSERVABILITY.md)"))
    return findings


def lint_checksum_walks(path: str, tree: ast.AST) -> List[Finding]:
    """Rule 18: no ``shard_checksums`` / ``flip_bit`` references
    outside the integrity seam (resilience/integrity.py owns both, the
    chaos injector in resilience/faults.py delegates to it) — a
    checksum walk elsewhere drifts on shard ordering and byte layout
    so its verdicts stop matching the SDC sentinel's, and bit surgery
    outside the seam is corruption the sentinel cannot attribute."""
    rel = os.path.relpath(path, REPO)
    if rel in _CHECKSUM_ALLOWED_FILES:
        return []
    findings: List[Finding] = []
    for node in ast.walk(tree):
        name = None
        if isinstance(node, ast.Attribute) and node.attr in _CHECKSUM_NAMES:
            name = node.attr
        elif isinstance(node, ast.Name) and node.id in _CHECKSUM_NAMES:
            name = node.id
        if name is not None:
            findings.append(Finding(
                path, node.lineno, "checksum-walk",
                f"{name} outside the integrity seam: per-shard "
                "checksums and shard-buffer bit surgery are "
                "single-sourced in resilience/integrity.py (the SDC "
                "sentinel) with resilience/faults.py's chaos injector "
                "as the one delegating caller — route detection "
                "through integrity.maybe_check and injection through "
                "the sdc chaos kind (docs/RESILIENCE.md)"))
    return findings


def lint_raw_profiling(path: str, tree: ast.AST) -> List[Finding]:
    """Rule 9: no raw jax.profiler use outside obs/trace.py +
    obs/profile.py, and no direct cost_analysis / memory_analysis
    calls outside obs/explain.py + resilience/memory.py — a
    measurement that bypasses the sanctioned entry points never
    reaches the cost ledger, so it can't be compared against the
    models it should be validating."""
    rel = os.path.relpath(path, REPO)
    profiler_ok = rel in _PROFILER_ALLOWED_FILES
    analysis_ok = rel in _ANALYSIS_ALLOWED_FILES
    if profiler_ok and analysis_ok:
        return []
    findings: List[Finding] = []

    def flag(node: ast.AST, what: str) -> None:
        findings.append(Finding(
            path, getattr(node, "lineno", 0), "raw-profiling",
            f"{what}: device-time measurement and compiled-program "
            "introspection are single-sourced so readings land in the "
            "cost ledger — use obs.trace.device_profile/.annotate, "
            "obs.profile.profile (the attribution profiler), "
            "obs.explain.compiled_cost_analysis, or "
            "resilience.memory.validate_plan"))

    for node in ast.walk(tree):
        if profiler_ok:
            pass
        elif isinstance(node, ast.Attribute) and node.attr == "profiler":
            root = node.value
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id == "jax":
                flag(node, "raw jax.profiler use")
        elif isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.startswith("jax.profiler"):
                flag(node, f"import from {mod!r}")
            elif mod == "jax" and any(a.name == "profiler"
                                      for a in node.names):
                flag(node, "binds jax.profiler directly")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if a.name.startswith("jax.profiler"):
                    flag(node, f"import {a.name}")
        if (not analysis_ok and isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _ANALYSIS_CALLS):
            flag(node, f"direct .{node.func.attr}() call")
    return findings


def lint_named_scopes(path: str, tree: ast.AST) -> List[Finding]:
    """Rule 11: no raw jax.named_scope outside expr/base.py + obs/ —
    scope names are the profiler's join key (the digest-carrying
    per-node scopes), so an ad-hoc scope elsewhere is a device-trace
    name the attribution report can never map to an expr node."""
    rel = os.path.relpath(path, REPO)
    if rel in _NAMED_SCOPE_ALLOWED_FILES or any(
            rel.startswith(d) for d in _NAMED_SCOPE_ALLOWED_DIRS):
        return []
    findings: List[Finding] = []

    def flag(node: ast.AST, what: str) -> None:
        findings.append(Finding(
            path, getattr(node, "lineno", 0), "raw-named-scope",
            f"{what}: trace-time scope names are the device-time "
            "profiler's join key — use obs.trace.named_scope for a "
            "fixed label (expr/base.Expr.lower owns the per-node "
            "digest-carrying scopes)"))

    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) \
                and node.attr == "named_scope":
            root = node.value
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id == "jax":
                flag(node, "raw jax.named_scope use")
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "").startswith("jax") and any(
                    a.name == "named_scope"
                    or a.asname == "named_scope" for a in node.names):
                flag(node, "binds jax.named_scope directly")
    return findings


def lint_sharding_constraints(path: str, tree: ast.AST) -> List[Finding]:
    """Rule 10: no raw ``with_sharding_constraint`` outside the
    redistribution planner and the expr/base lowering seam — a raw
    constraint is a reshard edge the cost-modeled planner never sees
    (not priced, never explicit, absent from st.explain's schedule
    report)."""
    rel = os.path.relpath(path, REPO)
    if rel in _WSC_ALLOWED_FILES:
        return []
    findings: List[Finding] = []

    def flag(node: ast.AST, what: str) -> None:
        findings.append(Finding(
            path, getattr(node, "lineno", 0), "raw-sharding-constraint",
            f"{what}: sharding-constraint seams belong to the "
            "redistribution planner — call "
            "parallel.redistribute.constrain() (pass src= when the "
            "producing layout is known) so the edge is priced, "
            "eligible for the explicit collective lowering, and "
            "visible in st.explain's schedule report"))

    for node in ast.walk(tree):
        if isinstance(node, ast.Attribute) \
                and node.attr == "with_sharding_constraint":
            flag(node, "raw with_sharding_constraint use")
        elif isinstance(node, ast.ImportFrom):
            if any(a.name == "with_sharding_constraint"
                   or a.asname == "with_sharding_constraint"
                   for a in node.names):
                flag(node, "binds with_sharding_constraint directly")
    return findings


def lint_pallas_imports(path: str, tree: ast.AST) -> List[Finding]:
    """Rule 12: no ``jax.experimental.pallas`` import (or
    ``pallas_call`` use) outside ``spartan_tpu/kernels/`` — every
    Pallas kernel goes through the kernel layer so its grid derives
    from the committed tiling and its backend choice is keyed,
    selectable and explainable."""
    rel = os.path.relpath(path, REPO)
    if any(rel.startswith(d) for d in _PALLAS_ALLOWED_DIRS):
        return []
    findings: List[Finding] = []

    def flag(node: ast.AST, what: str) -> None:
        findings.append(Finding(
            path, getattr(node, "lineno", 0), "pallas-outside-kernels",
            f"{what}: Pallas kernels live in spartan_tpu/kernels/ "
            "(docs/KERNELS.md) — add the kernel there, derive its "
            "grid from the committed Tiling (kernels.registry.derive) "
            "and route callers through kernels.select"))

    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if "pallas" in mod.split("."):
                flag(node, f"import from {mod!r}")
            elif any(a.name == "pallas" or a.name.startswith("pallas.")
                     for a in node.names):
                flag(node, "binds the pallas module")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if "pallas" in a.name.split("."):
                    flag(node, f"import {a.name}")
        elif isinstance(node, ast.Attribute) \
                and node.attr == "pallas_call":
            # pl.pallas_call / pallas.pallas_call — the call seam
            flag(node, "pallas_call use")
        elif isinstance(node, ast.Attribute) and node.attr == "pallas":
            # jax.experimental.pallas attribute chains (not arbitrary
            # objects with a .pallas property, e.g. kernels.Selection)
            root = node.value
            while isinstance(root, ast.Attribute):
                root = root.value
            if isinstance(root, ast.Name) and root.id == "jax":
                flag(node, "attribute access on jax's pallas")
    return findings


def lint_persist_seam(path: str, tree: ast.AST) -> List[Finding]:
    """Rule 13: JAX AOT executable serialization
    (``jax.experimental.serialize_executable``) and direct
    ``persist_cache_dir`` flag access only inside
    ``spartan_tpu/persist/`` — the store owns the fingerprint /
    CRC / lease / degrade contract (docs/WARMSTART.md)."""
    rel = os.path.relpath(path, REPO)
    if any(rel.startswith(d) for d in _PERSIST_ALLOWED_DIRS):
        return []
    findings: List[Finding] = []

    def flag(node: ast.AST, what: str) -> None:
        findings.append(Finding(
            path, getattr(node, "lineno", 0), "persist-seam",
            f"{what}: the warm-start store (spartan_tpu/persist, "
            "docs/WARMSTART.md) owns AOT serialization and the "
            "persist directory — go through spartan_tpu.persist "
            "(active()/lookup()/maybe_store()/prewarm())"))

    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if "serialize_executable" in mod.split("."):
                flag(node, f"import from {mod!r}")
            elif any(a.name in _PERSIST_SERIALIZE_NAMES
                     for a in node.names):
                flag(node, "binds the AOT serialization API")
        elif isinstance(node, ast.Import):
            for a in node.names:
                if "serialize_executable" in a.name.split("."):
                    flag(node, f"import {a.name}")
        elif isinstance(node, ast.Attribute) \
                and node.attr in _PERSIST_SERIALIZE_NAMES:
            flag(node, f"attribute use of {node.attr}")
        elif isinstance(node, ast.Attribute) \
                and node.attr == "persist_cache_dir":
            # FLAGS.persist_cache_dir reads/writes outside the store:
            # the path must be resolved through persist.active() so a
            # broken directory degrades instead of erroring ad hoc
            flag(node, "direct persist_cache_dir access")
    return findings


def lint_buffer_mutation(path: str, tree: ast.AST) -> List[Finding]:
    """Rule 14: no stores to a DistArray's private buffer/lineage
    slots (``._jax`` / ``._lineage`` / ``._version``) outside
    ``spartan_tpu/array/`` and the incremental seam
    (``spartan_tpu/expr/incremental.py``) — every mutation must go
    through ``DistArray.update()`` / ``st.assign`` so the Lineage log
    stays truthful and the incremental result cache can never serve a
    silently-mutated buffer as clean (docs/INCREMENTAL.md)."""
    rel = os.path.relpath(path, REPO)
    if (any(rel.startswith(d) for d in _MUTATION_ALLOWED_DIRS)
            or rel in _MUTATION_ALLOWED_FILES):
        return []
    findings: List[Finding] = []

    def flag(node: ast.AST, attr: str) -> None:
        findings.append(Finding(
            path, getattr(node, "lineno", 0), "buffer-mutation",
            f"store to DistArray private state '.{attr}' outside the "
            "array layer / incremental seam: mutate through "
            "DistArray.update() or st.assign so the lineage log "
            "(docs/INCREMENTAL.md) records the delta"))

    def targets(node: ast.AST) -> List[ast.expr]:
        if isinstance(node, ast.Assign):
            return list(node.targets)
        if isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            return [node.target]
        if isinstance(node, ast.Delete):
            return list(node.targets)
        return []

    for node in ast.walk(tree):
        for t in targets(node):
            for sub in ast.walk(t):
                if (isinstance(sub, ast.Attribute)
                        and sub.attr in _MUTATION_ATTRS):
                    flag(node, sub.attr)
    return findings


def lint_background_threads(path: str, tree: ast.AST) -> List[Finding]:
    """Rule 16: no ``threading.Thread`` / ``threading.Timer``
    construction outside the sanctioned concurrency seams (serve/,
    resilience/, the monitor sampler, the dispatch watchdog, the
    persist prewarm) — a stray background thread dodges the
    mesh-epoch fence, is invisible to st.status()'s health section
    and leaks past shutdown()."""
    rel = os.path.relpath(path, REPO)
    if rel in _THREAD_ALLOWED_FILES or any(
            rel.startswith(d) for d in _THREAD_ALLOWED_DIRS):
        return []
    findings: List[Finding] = []

    def flag(node: ast.AST, what: str) -> None:
        findings.append(Finding(
            path, getattr(node, "lineno", 0), "background-thread",
            f"{what}: background threads live in the sanctioned "
            "concurrency seams (serve/ worker pool, resilience/, "
            "obs/monitor.py sampler, obs/numerics.py watchdog, "
            "persist prewarm) where the epoch fence, the drain "
            "barrier and the crash-dump span tree account for them — "
            "run the work on an existing seam (serve workers, the "
            "monitor's tick) instead of spawning a thread"))

    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _THREAD_CTORS):
            root = node.func.value
            if isinstance(root, ast.Name) and root.id == "threading":
                flag(node, f"threading.{node.func.attr}(...) "
                     "construction")
        elif isinstance(node, ast.ImportFrom):
            if (node.module or "") == "threading":
                for a in node.names:
                    if a.name in _THREAD_CTORS:
                        flag(node, f"binds threading.{a.name} "
                             "directly")
    return findings


def _collect_classes(files: List[str]
                     ) -> Dict[str, Tuple[List[str], Set[str], str, int]]:
    """name -> (base names, methods defined in the body, path, line).

    Simple-name resolution: class names are unique across the package
    (enforced here — a duplicate would make the lint ambiguous)."""
    table: Dict[str, Tuple[List[str], Set[str], str, int]] = {}
    for path in files:
        with open(path) as f:
            try:
                tree = ast.parse(f.read(), filename=path)
            except SyntaxError:
                continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef):
                continue
            bases = []
            for b in node.bases:
                if isinstance(b, ast.Name):
                    bases.append(b.id)
                elif isinstance(b, ast.Attribute):
                    bases.append(b.attr)
            methods = {n.name for n in node.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            if node.name not in table:
                table[node.name] = (bases, methods, path, node.lineno)
    return table


def lint_expr_subclasses(files: List[str]) -> List[Finding]:
    """Rule 2: every Expr subclass defines _sig and replace_children
    somewhere in its chain below the Expr base."""
    table = _collect_classes(files)

    def is_expr(name: str, seen: Optional[Set[str]] = None) -> bool:
        if name in _ABSTRACT_EXPRS:
            return True
        if name not in table:
            return False
        seen = seen or set()
        if name in seen:
            return False
        seen.add(name)
        return any(is_expr(b, seen) for b in table[name][0])

    def defines(name: str, method: str) -> bool:
        """Defined in `name` or any ancestor below the Expr base."""
        if name in _ABSTRACT_EXPRS or name not in table:
            return False
        bases, methods, _, _ = table[name]
        if method in methods:
            return True
        return any(defines(b, method) for b in bases)

    findings: List[Finding] = []
    for name, (bases, methods, path, line) in sorted(table.items()):
        if name in _ABSTRACT_EXPRS or not is_expr(name):
            continue
        for hook in ("_sig", "replace_children"):
            if not defines(name, hook):
                findings.append(Finding(
                    path, line, "expr-subclass-hooks",
                    f"Expr subclass {name} never defines {hook}; the "
                    "base stub raises NotImplementedError and breaks "
                    "the structural caches / optimizer rewrites"))
    return findings


def run_lint(root: str = PACKAGE) -> List[Finding]:
    files = _iter_py_files(root)
    findings: List[Finding] = []
    for path in files:
        with open(path) as f:
            try:
                tree = ast.parse(f.read(), filename=path)
            except SyntaxError as e:
                findings.append(Finding(path, e.lineno or 0, "syntax",
                                        str(e)))
                continue
        findings.extend(lint_shard_map_imports(path, tree))
        findings.extend(lint_raw_timing(path, tree))
        findings.extend(lint_debug_callbacks(path, tree))
        findings.extend(lint_bare_recovery(path, tree))
        findings.extend(lint_shared_state(path, tree))
        findings.extend(lint_mesh_capture(path, tree))
        findings.extend(lint_raw_memory_stats(path, tree))
        findings.extend(lint_raw_profiling(path, tree))
        findings.extend(lint_named_scopes(path, tree))
        findings.extend(lint_sharding_constraints(path, tree))
        findings.extend(lint_pallas_imports(path, tree))
        findings.extend(lint_persist_seam(path, tree))
        findings.extend(lint_buffer_mutation(path, tree))
        findings.extend(lint_dynamic_slices(path, tree))
        findings.extend(lint_background_threads(path, tree))
        findings.extend(lint_shard_walks(path, tree))
        findings.extend(lint_checksum_walks(path, tree))
    findings.extend(lint_expr_subclasses(files))
    return findings


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    findings = run_lint()
    if "--json" in argv:
        import json
        print(json.dumps([{"path": f.path, "line": f.line,
                           "rule": f.rule, "message": f.message}
                          for f in findings], indent=2))
        return 1 if findings else 0
    for f in findings:
        print(f)
    if findings:
        print(f"{len(findings)} finding(s)", file=sys.stderr)
        return 1
    print("lint_repo: clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
