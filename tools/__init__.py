"""Repo tooling: the custom AST lint (``python -m tools.lint_repo``)."""
