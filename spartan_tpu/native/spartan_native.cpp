// Native host-runtime components for spartan_tpu.
//
// TPU-native equivalents of the reference's Cython extensions
// (SURVEY.md §2.5):
//   * extent batch algebra  <- fast region math (the possible Cython
//     extent twin): batched intersection / overlap masks / coverage
//     checks used by the metadata plane (shuffle planning, fetch
//     assembly) where Python-level loops are O(n^2).
//   * parallel blob IO      <- serialization_buffer.pyx's role on the
//     host side: the device data path is XLA, so the native surface
//     that matters is moving checkpoint shards between pinned host
//     buffers and disk without Python overhead; a std::thread pool
//     writes/reads all shards of a DistArray concurrently.
//
// Exposed as a C ABI for ctypes (pybind11 is not in this image).
//
// Build: g++ -O3 -shared -fPIC -std=c++17 -pthread
//        spartan_native.cpp -o libspartan_native.so

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------
// Extent algebra (half-open boxes [ul, lr) of rank nd, int64 coords)
// ---------------------------------------------------------------------

// Intersect every box i with the query box; out_ul/out_lr receive the
// intersection (undefined where empty); out_mask[i] = 1 if non-empty.
// Returns number of non-empty intersections.
int64_t extent_intersect_batch(const int64_t* uls, const int64_t* lrs,
                               int64_t n, int64_t nd,
                               const int64_t* q_ul, const int64_t* q_lr,
                               int64_t* out_ul, int64_t* out_lr,
                               uint8_t* out_mask) {
  int64_t hits = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t* ul = uls + i * nd;
    const int64_t* lr = lrs + i * nd;
    int64_t* oul = out_ul + i * nd;
    int64_t* olr = out_lr + i * nd;
    uint8_t ok = 1;
    for (int64_t d = 0; d < nd; ++d) {
      int64_t a = ul[d] > q_ul[d] ? ul[d] : q_ul[d];
      int64_t b = lr[d] < q_lr[d] ? lr[d] : q_lr[d];
      oul[d] = a;
      olr[d] = b;
      if (a >= b) ok = 0;
    }
    out_mask[i] = ok;
    hits += ok;
  }
  return hits;
}

// Pairwise overlap test over n boxes: returns 1 if ANY pair overlaps
// (the all_nonoverlapping check, O(n^2) but branch-light).
int32_t extent_any_overlap(const int64_t* uls, const int64_t* lrs,
                           int64_t n, int64_t nd) {
  for (int64_t i = 0; i < n; ++i) {
    for (int64_t j = i + 1; j < n; ++j) {
      const int64_t* ul_i = uls + i * nd;
      const int64_t* lr_i = lrs + i * nd;
      const int64_t* ul_j = uls + j * nd;
      const int64_t* lr_j = lrs + j * nd;
      int overlap = 1;
      for (int64_t d = 0; d < nd; ++d) {
        int64_t a = ul_i[d] > ul_j[d] ? ul_i[d] : ul_j[d];
        int64_t b = lr_i[d] < lr_j[d] ? lr_i[d] : lr_j[d];
        if (a >= b) {
          overlap = 0;
          break;
        }
      }
      if (overlap) return 1;
    }
  }
  return 0;
}

// Sum of box volumes (the is_complete coverage check pairs this with
// extent_any_overlap).
int64_t extent_total_volume(const int64_t* uls, const int64_t* lrs,
                            int64_t n, int64_t nd) {
  int64_t total = 0;
  for (int64_t i = 0; i < n; ++i) {
    int64_t vol = 1;
    for (int64_t d = 0; d < nd; ++d) {
      vol *= lrs[i * nd + d] - uls[i * nd + d];
    }
    total += vol;
  }
  return total;
}

// ---------------------------------------------------------------------
// Parallel blob IO (checkpoint shards)
// ---------------------------------------------------------------------

static int write_one(const char* path, const uint8_t* data, int64_t size) {
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  size_t wrote = std::fwrite(data, 1, (size_t)size, f);
  std::fclose(f);
  return wrote == (size_t)size ? 0 : -2;
}

static int read_one(const char* path, uint8_t* data, int64_t size) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  size_t got = std::fread(data, 1, (size_t)size, f);
  std::fclose(f);
  return got == (size_t)size ? 0 : -2;
}

// Write n blobs concurrently with nthreads workers. paths: array of
// C strings; ptrs/sizes parallel arrays. Returns 0 on success, else the
// first nonzero worker status.
int32_t blob_write_parallel(const char** paths, const uint8_t** ptrs,
                            const int64_t* sizes, int64_t n,
                            int32_t nthreads) {
  if (nthreads < 1) nthreads = 1;
  std::atomic<int64_t> next(0);
  std::atomic<int32_t> status(0);
  auto work = [&]() {
    for (;;) {
      int64_t i = next.fetch_add(1);
      if (i >= n) break;
      int rc = write_one(paths[i], ptrs[i], sizes[i]);
      if (rc != 0) {
        int32_t expected = 0;
        status.compare_exchange_strong(expected, rc);
      }
    }
  };
  std::vector<std::thread> threads;
  int32_t tcount = (int32_t)(n < nthreads ? n : nthreads);
  threads.reserve(tcount);
  for (int32_t t = 0; t < tcount; ++t) threads.emplace_back(work);
  for (auto& th : threads) th.join();
  return status.load();
}

int32_t blob_read_parallel(const char** paths, uint8_t** ptrs,
                           const int64_t* sizes, int64_t n,
                           int32_t nthreads) {
  if (nthreads < 1) nthreads = 1;
  std::atomic<int64_t> next(0);
  std::atomic<int32_t> status(0);
  auto work = [&]() {
    for (;;) {
      int64_t i = next.fetch_add(1);
      if (i >= n) break;
      int rc = read_one(paths[i], ptrs[i], sizes[i]);
      if (rc != 0) {
        int32_t expected = 0;
        status.compare_exchange_strong(expected, rc);
      }
    }
  };
  std::vector<std::thread> threads;
  int32_t tcount = (int32_t)(n < nthreads ? n : nthreads);
  threads.reserve(tcount);
  for (int32_t t = 0; t < tcount; ++t) threads.emplace_back(work);
  for (auto& th : threads) th.join();
  return status.load();
}

}  // extern "C"
