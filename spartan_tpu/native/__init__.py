"""ctypes loader for the native C++ runtime components.

Compiles ``spartan_native.cpp`` on first import (g++, cached .so) and
exposes typed wrappers. Falls back gracefully (``lib() is None``) when no
toolchain is available; callers keep their pure-Python paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "spartan_native.cpp")
_SO = os.path.join(_DIR, "libspartan_native.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           _SRC, "-o", _SO]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def lib() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        fresh = (not os.path.exists(_SO)
                 or os.path.getmtime(_SO) < os.path.getmtime(_SRC))
        if fresh and not _build():
            return None
        try:
            l = ctypes.CDLL(_SO)
        except OSError:
            return None
        i64p = ctypes.POINTER(ctypes.c_int64)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        l.extent_intersect_batch.restype = ctypes.c_int64
        l.extent_intersect_batch.argtypes = [
            i64p, i64p, ctypes.c_int64, ctypes.c_int64, i64p, i64p,
            i64p, i64p, u8p]
        l.extent_any_overlap.restype = ctypes.c_int32
        l.extent_any_overlap.argtypes = [i64p, i64p, ctypes.c_int64,
                                         ctypes.c_int64]
        l.extent_total_volume.restype = ctypes.c_int64
        l.extent_total_volume.argtypes = [i64p, i64p, ctypes.c_int64,
                                          ctypes.c_int64]
        charpp = ctypes.POINTER(ctypes.c_char_p)
        l.blob_write_parallel.restype = ctypes.c_int32
        l.blob_write_parallel.argtypes = [
            charpp, ctypes.POINTER(u8p), i64p, ctypes.c_int64,
            ctypes.c_int32]
        l.blob_read_parallel.restype = ctypes.c_int32
        l.blob_read_parallel.argtypes = [
            charpp, ctypes.POINTER(u8p), i64p, ctypes.c_int64,
            ctypes.c_int32]
        _lib = l
        return _lib


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))


def intersect_batch(uls: np.ndarray, lrs: np.ndarray,
                    q_ul: Sequence[int], q_lr: Sequence[int]
                    ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Batched box intersection. uls/lrs: (n, nd) int64. Returns
    (mask (n,) bool, out_ul (n, nd), out_lr (n, nd))."""
    l = lib()
    uls = np.ascontiguousarray(uls, np.int64)
    lrs = np.ascontiguousarray(lrs, np.int64)
    n, nd = uls.shape
    q_ul = np.ascontiguousarray(q_ul, np.int64)
    q_lr = np.ascontiguousarray(q_lr, np.int64)
    out_ul = np.empty_like(uls)
    out_lr = np.empty_like(lrs)
    mask = np.zeros(n, np.uint8)
    if l is not None:
        l.extent_intersect_batch(
            _i64p(uls), _i64p(lrs), n, nd, _i64p(q_ul), _i64p(q_lr),
            _i64p(out_ul), _i64p(out_lr),
            mask.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)))
    else:  # NumPy fallback
        iul = np.maximum(uls, q_ul)
        ilr = np.minimum(lrs, q_lr)
        out_ul, out_lr = iul, ilr
        mask = (iul < ilr).all(axis=1).astype(np.uint8)
    return mask.astype(bool), out_ul, out_lr


def any_overlap(uls: np.ndarray, lrs: np.ndarray) -> bool:
    l = lib()
    uls = np.ascontiguousarray(uls, np.int64)
    lrs = np.ascontiguousarray(lrs, np.int64)
    n, nd = uls.shape
    if l is not None:
        return bool(l.extent_any_overlap(_i64p(uls), _i64p(lrs), n, nd))
    for i in range(n):
        iul = np.maximum(uls[i], uls[i + 1:])
        ilr = np.minimum(lrs[i], lrs[i + 1:])
        if len(iul) and (iul < ilr).all(axis=1).any():
            return True
    return False


def total_volume(uls: np.ndarray, lrs: np.ndarray) -> int:
    l = lib()
    uls = np.ascontiguousarray(uls, np.int64)
    lrs = np.ascontiguousarray(lrs, np.int64)
    n, nd = uls.shape
    if l is not None:
        return int(l.extent_total_volume(_i64p(uls), _i64p(lrs), n, nd))
    return int((lrs - uls).prod(axis=1).sum())


def write_blobs(paths: List[str], arrays: List[np.ndarray],
                nthreads: int = 8) -> None:
    """Write each array's raw bytes to its path, concurrently in C++."""
    l = lib()
    arrays = [np.ascontiguousarray(a) for a in arrays]
    if l is None:
        for p, a in zip(paths, arrays):
            with open(p, "wb") as f:
                f.write(a.tobytes())
        return
    n = len(paths)
    c_paths = (ctypes.c_char_p * n)(*[p.encode() for p in paths])
    u8p = ctypes.POINTER(ctypes.c_uint8)
    c_ptrs = (u8p * n)(*[a.ctypes.data_as(u8p) for a in arrays])
    c_sizes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrays])
    rc = l.blob_write_parallel(c_paths, c_ptrs, c_sizes, n, nthreads)
    if rc != 0:
        raise IOError(f"native blob write failed (rc={rc})")


def read_blobs(paths: List[str], arrays: List[np.ndarray],
               nthreads: int = 8) -> None:
    """Fill each (preallocated, contiguous) array from its path."""
    l = lib()
    if l is None:
        for p, a in zip(paths, arrays):
            with open(p, "rb") as f:
                buf = f.read(a.nbytes)
            a[...] = np.frombuffer(buf, a.dtype).reshape(a.shape)
        return
    n = len(paths)
    for a in arrays:
        if not a.flags["C_CONTIGUOUS"]:
            raise ValueError("read_blobs needs contiguous targets")
    c_paths = (ctypes.c_char_p * n)(*[p.encode() for p in paths])
    u8p = ctypes.POINTER(ctypes.c_uint8)
    c_ptrs = (u8p * n)(*[a.ctypes.data_as(u8p) for a in arrays])
    c_sizes = (ctypes.c_int64 * n)(*[a.nbytes for a in arrays])
    rc = l.blob_read_parallel(c_paths, c_ptrs, c_sizes, n, nthreads)
    if rc != 0:
        raise IOError(f"native blob read failed (rc={rc})")
