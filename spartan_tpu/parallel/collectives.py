"""Explicit collective operations over the device mesh.

The TPU-native replacement for the reference's RPC layer (SURVEY.md §2.7):
the data plane is XLA collectives over ICI. These wrappers are used inside
``shard_map`` kernels (ring attention, explicit GEMMs, user map2 kernels)
and at the host level for resharding. Names follow the reference's
conceptual ops: reduce -> all_reduce, shuffle -> all_to_all, tile fetch ->
all_gather, rotation -> ring_permute.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..array.tiling import Tiling
from . import mesh as mesh_mod

# -- in-kernel collectives (call inside shard_map) ----------------------


def all_reduce(x: Any, axis: str = mesh_mod.AXIS_ROW, op: str = "add"):
    """The lowering of the reference's reducer-merge (SURVEY.md §3.2)."""
    if op == "add":
        return lax.psum(x, axis)
    if op == "max":
        return lax.pmax(x, axis)
    if op == "min":
        return lax.pmin(x, axis)
    if op == "mean":
        return lax.pmean(x, axis)
    raise ValueError(f"unknown all_reduce op {op!r}")


def all_gather(x: Any, axis: str = mesh_mod.AXIS_ROW, *,
               gather_axis: int = 0, tiled: bool = True):
    """The lowering of the reference's remote tile fetch (SURVEY.md §3.5)."""
    return lax.all_gather(x, axis, axis=gather_axis, tiled=tiled)


def reduce_scatter(x: Any, axis: str = mesh_mod.AXIS_ROW, *,
                   scatter_axis: int = 0):
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_axis,
                            tiled=True)


def all_to_all(x: Any, axis: str = mesh_mod.AXIS_ROW, *,
               split_axis: int, concat_axis: int):
    """The lowering of the reference's shuffle (SURVEY.md §2.6)."""
    return lax.all_to_all(x, axis, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def ring_permute(x: Any, axis: str = mesh_mod.AXIS_ROW, shift: int = 1):
    """Rotate shards around the ring (the substrate of ring attention and
    pipeline stages). shift=+1 sends to the next device."""
    n = mesh_mod.get_mesh().shape[axis]
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: str = mesh_mod.AXIS_ROW):
    return lax.axis_index(axis)


def axis_size(axis: str = mesh_mod.AXIS_ROW) -> int:
    return mesh_mod.get_mesh().shape[axis]


# -- host-level resharding ---------------------------------------------


def reshard(arr: jax.Array, tiling: Tiling) -> jax.Array:
    """General redistribution: XLA emits the minimal collective
    (cf. the redistribution paper, PAPERS.md:5)."""
    return jax.device_put(arr, tiling.sharding(mesh_mod.get_mesh()))


def ulysses_swap(arr: jax.Array, seq_axis: int, head_axis: int,
                 mesh_axis: str = mesh_mod.AXIS_ROW) -> jax.Array:
    """Ulysses-style axis swap: move the mesh shard from ``seq_axis`` to
    ``head_axis`` with one all-to-all (SURVEY.md §2.6 SP row)."""
    from ..utils.compat import shard_map

    mesh = mesh_mod.get_mesh()
    ndim = arr.ndim
    in_axes = [None] * ndim
    in_axes[seq_axis] = mesh_axis
    out_axes = [None] * ndim
    out_axes[head_axis] = mesh_axis
    in_t, out_t = Tiling(in_axes), Tiling(out_axes)

    def kern(x):
        return all_to_all(x, mesh_axis, split_axis=head_axis,
                          concat_axis=seq_axis)

    arr = jax.device_put(arr, in_t.sharding(mesh))
    return jax.jit(shard_map(kern, mesh=mesh, in_specs=(in_t.spec(),),
                             out_specs=out_t.spec()))(arr)
