from . import collectives, mesh, pipeline, redistribute  # noqa: F401
