from . import collectives, mesh, pipeline  # noqa: F401
