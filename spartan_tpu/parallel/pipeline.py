"""Pipeline parallelism: GPipe-style microbatch streaming over a mesh
axis.

The reference has no pipeline parallelism (SURVEY.md §2.6 marks PP
"optional later via shard_map stages + collective_permute"); this module
provides exactly that TPU-native construction. Stages are sharded over a
mesh axis (stage s's parameters live on device s); microbatches enter at
stage 0 and ride the ICI ring via ``ppermute`` one hop per tick, so at
steady state every stage computes concurrently — the classic GPipe
schedule with ``n_micro + n_stages - 1`` ticks.

Everything is a single jitted ``shard_map`` program: the driver-side
loop of the reference's world (ship tile, compute, ship on) collapses
into a ``lax.fori_loop`` of compute + collective_permute.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from . import mesh as mesh_mod


def pipeline_apply(stage_fn: Callable[[Any, jax.Array], jax.Array],
                   stage_params: Any,
                   microbatches: jax.Array,
                   *,
                   mesh=None,
                   axis: str = mesh_mod.AXIS_ROW) -> jax.Array:
    """Run ``n_micro`` microbatches through a pipeline of stages.

    ``stage_fn(params_s, act) -> act`` is one stage's computation; it
    must preserve the activation shape (classic homogeneous-stage
    pipeline). ``stage_params`` is a pytree whose leaves have a leading
    ``n_stages`` axis (sharded over ``axis``); ``microbatches`` is
    ``(n_micro, mb, ...)``. Returns ``(n_micro, mb, ...)`` outputs.

    Grad-friendly: ``jax.grad`` through the returned value
    differentiates the whole pipeline (ppermute is linear).
    ``stage_fn`` is applied to every stage's carry on every tick
    (bubble values included, seeded from the first microbatch), so it
    should be finite on activation-shaped data.
    """
    mesh = mesh or mesh_mod.get_mesh()
    n_stages = mesh.shape[axis]
    n_micro = microbatches.shape[0]
    if n_micro < 1:
        raise ValueError("need at least one microbatch")
    ticks = n_micro + n_stages - 1
    fwd = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    from ..utils.compat import shard_map

    params_spec = jax.tree.map(lambda _: P(axis), stage_params)

    def shard_fn(params, x):
        # params leaves: (1, ...) — this stage's slice; x: full batch
        # (microbatches replicated: cheap relative to weights, and stage
        # 0 needs random access into them)
        params = jax.tree.map(lambda p: p[0], params)
        stage = lax.axis_index(axis)
        # warm-up activations are real data, not zeros: stage_fn is
        # applied to every stage's carry each tick (masking selects the
        # emitted values), and a fn that is non-finite at zeros would
        # otherwise poison grads through 0*NaN cotangents
        act0 = x[0]
        out0 = jnp.zeros_like(x)

        def tick(t, carry):
            act, out = carry
            # stage 0 ingests microbatch t (while available)
            inj = x[jnp.minimum(t, n_micro - 1)]
            act = jnp.where(jnp.logical_and(stage == 0, t < n_micro),
                            inj, act)
            act = stage_fn(params, act)
            # last stage emits the microbatch that entered at t-(S-1)
            m = t - (n_stages - 1)
            emit = jnp.logical_and(stage == n_stages - 1, m >= 0)
            out = lax.dynamic_update_index_in_dim(
                out, jnp.where(emit, act, out[jnp.maximum(m, 0)]),
                jnp.maximum(m, 0), 0)
            act = lax.ppermute(act, axis, fwd)
            return act, out

        _, out = lax.fori_loop(0, ticks, tick, (act0, out0))
        # outputs live on the last stage; share them with everyone
        keep = (stage == n_stages - 1).astype(out.dtype)
        return lax.psum(out * keep, axis)

    fn = shard_map(shard_fn, mesh=mesh,
                   in_specs=(params_spec, P()), out_specs=P(),
                   check_vma=False)
    return fn(stage_params, microbatches)


def pipeline_loss(stage_fn: Callable[[Any, jax.Array], jax.Array],
                  loss_fn: Callable[[jax.Array, jax.Array], jax.Array],
                  stage_params: Any,
                  microbatches: jax.Array,
                  targets: jax.Array,
                  *,
                  mesh=None,
                  axis: str = mesh_mod.AXIS_ROW) -> jax.Array:
    """Mean loss over microbatches run through the pipeline."""
    out = pipeline_apply(stage_fn, stage_params, microbatches,
                         mesh=mesh, axis=axis)
    return jnp.mean(jax.vmap(loss_fn)(out, targets))


def pipeline_grad(stage_fn, loss_fn, stage_params, microbatches, targets,
                  *, mesh=None, axis: str = mesh_mod.AXIS_ROW):
    """(loss, grads) for one pipelined training step — grads have the
    same stage-sharded structure as ``stage_params``."""
    return jax.value_and_grad(
        lambda p: pipeline_loss(stage_fn, loss_fn, p, microbatches,
                                targets, mesh=mesh, axis=axis)
    )(stage_params)
