"""Cost-modeled redistribution planner: explicit collective decomposition
of reshard edges.

Every tiling -> tiling transition in the stack used to be an implicit
``with_sharding_constraint`` that GSPMD lowered however it liked. This
module makes the redistribution an explicitly *planned* operation (the
portable-collectives decomposition of "Memory-efficient array
redistribution through portable collective communication", PAPERS.md):

1. **Enumeration** (:func:`schedules`): legal decompositions of a
   ``src -> dst`` Tiling transition into sequences of the
   :mod:`parallel.collectives` step vocabulary —

   * ``all_gather`` (un-shard an array axis: mesh axis released),
   * ``all_to_all`` (move a mesh axis between two array axes in ONE
     exchange — each chip keeps ``1/p`` of its shard),
   * ``slice`` (dynamic-slice a replicated axis onto a free mesh axis:
     zero wire traffic, each chip carves its own destination shard).

   ``reduce_scatter`` completes the vocabulary but never appears in a
   plain reshard schedule: it sums partial values, which only psum
   edges (contraction outputs) carry — those are owned by the
   contraction lowering and priced by the DP's psum term (decomposed
   into its reduce-scatter + all-gather halves for calibration when
   the planner is on). ``ring_permute`` covers grid-shift
   realignments, which aligned ``NamedSharding`` grids never need.

2. **Pricing** (:meth:`Schedule.cost`): per-chip receive bytes on ICI
   per step, weighted by the per-collective calibrated factor
   (``obs/ledger`` profile classes ``all_gather`` / ``all_to_all`` /
   ``reduce_scatter``), plus the schedule's PEAK staging bytes (the
   largest intermediate any chip materializes) weighted by
   ``FLAGS.tiling_memory_weight``. The modeled cost is clamped at the
   receive-bytes floor (``tiling_cost.reshard_cost`` — the minimum any
   correct redistribution must deliver), so the planner can reorder
   schedules but never claim free communication.

3. **Decision + lowering** (:func:`decide`, :func:`constrain`): the
   cheapest schedule is compared against the canonical
   gather-everything-then-slice reference (the model of GSPMD's
   generic lowering). Where the model predicts a strict win AND every
   intermediate tiling divides the shape evenly, :func:`constrain`
   emits the explicit shard_map program; otherwise it falls back to
   ``with_sharding_constraint`` — the GSPMD path stays the portable
   default, so CPU CI and exotic meshes are never worse off.

Everything is behind ``FLAGS.redistribution_planner`` (default OFF; one
flag read per constrained edge when off — gated by
``benchmarks/redistribution.py``). The flag is fingerprinted into
``expr/base._opt_flags_key``, so planned and GSPMD-implicit plans never
alias in the plan/compile caches. Consumers: the tiling DP's edge cost
(:func:`edge_cost` from ``expr/tiling_cost``), the lowering seams
(``expr/base.Expr.lower``, ``expr/dot``, ``expr/contract``,
``expr/map2`` via :func:`constrain` — lint rule 10 forbids raw
``with_sharding_constraint`` elsewhere), ``st.explain``'s reshard-edge
report (:func:`decide`), and the memory governor's staging estimate
(:func:`staging_frac`). See docs/REDISTRIBUTION.md.
"""

from __future__ import annotations

from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import numpy as np
from jax import lax

from ..array.tiling import Tiling
from ..utils import profiling as prof
from ..utils.config import FLAGS
from . import mesh as mesh_mod

# define() returns the Flag; hot paths read ._value directly (one
# attribute load per constrained edge when the planner is off).
_PLANNER_FLAG = FLAGS.define_bool(
    "redistribution_planner", False,
    "Plan every tiling->tiling reshard edge as an explicit collective "
    "schedule (all_gather / all_to_all / slice) chosen by a cost "
    "model: the tiling DP prices edges by the modeled schedule, the "
    "lowering emits the explicit sequence where the model predicts a "
    "win over GSPMD's generic lowering (falling back to "
    "with_sharding_constraint otherwise), st.explain names the chosen "
    "schedule per edge, and the memory governor prices reshard "
    "staging by the schedule's actual peak. Keyed into the plan/"
    "compile caches: planned and implicit plans never alias.")


def planner_on() -> bool:
    """One flag read — the hot-path gate every consumer shares."""
    return _PLANNER_FLAG._value


class Step(NamedTuple):
    """One collective in a redistribution schedule.

    ``kind`` is 'all_gather' (release ``mesh_axis`` from array axis
    ``axis``), 'all_to_all' (move ``mesh_axis`` from array axis
    ``axis`` to ``to_axis``), or 'slice' (carve array axis ``axis``
    onto ``mesh_axis`` locally)."""

    kind: str
    axis: int
    mesh_axis: str
    to_axis: Optional[int] = None

    def describe(self) -> str:
        if self.kind == "all_to_all":
            return (f"all_to_all[{self.mesh_axis}:"
                    f"{self.axis}->{self.to_axis}]")
        if self.kind == "transfer":  # the cross-grid hop has no axis
            return "transfer"
        return f"{self.kind}[{self.mesh_axis}:{self.axis}]"


class Schedule:
    """A priced decomposition of one ``src -> dst`` redistribution.

    Byte quantities are stored as FRACTIONS of the full array's bytes
    (they scale linearly), so one enumeration per ``(src, dst, mesh
    shape)`` serves every array size: ``comm_frac`` maps collective
    class -> per-chip receive fraction, ``peak_frac`` is the largest
    per-chip intermediate any step materializes (the staging memory
    the redistribution paper trades against bytes), ``states`` the
    intermediate tilings (divisibility is checked against them before
    the explicit lowering is allowed)."""

    __slots__ = ("steps", "comm_frac", "peak_frac", "states")

    def __init__(self, steps: Tuple[Step, ...],
                 comm_frac: Dict[str, float], peak_frac: float,
                 states: Tuple[Tuple, ...]):
        self.steps = steps
        self.comm_frac = comm_frac
        self.peak_frac = peak_frac
        self.states = states

    def comm_bytes(self, nbytes: float,
                   factors: Optional[Dict[str, float]] = None) -> float:
        """Per-chip receive bytes, each collective class under its
        calibrated factor (identity without a profile)."""
        total = 0.0
        for cls, frac in self.comm_frac.items():
            f = factors.get(cls, 1.0) if factors else 1.0
            total += frac * nbytes * f
        return total

    def cost(self, nbytes: float,
             factors: Optional[Dict[str, float]] = None,
             mem_weight: Optional[float] = None) -> float:
        """The planner's objective: factored ICI bytes + peak staging
        bytes under ``FLAGS.tiling_memory_weight``."""
        if mem_weight is None:
            mem_weight = float(
                getattr(FLAGS, "tiling_memory_weight", 0.0) or 0.0)
        return (self.comm_bytes(nbytes, factors)
                + mem_weight * self.peak_frac * nbytes)

    def describe(self) -> str:
        return " + ".join(s.describe() for s in self.steps) or "noop"

    def to_dict(self) -> Dict[str, Any]:
        return {"steps": [s.describe() for s in self.steps],
                "comm_frac": {k: round(v, 6)
                              for k, v in self.comm_frac.items()},
                "peak_frac": round(self.peak_frac, 6)}

    def __repr__(self) -> str:
        return f"Schedule({self.describe()})"


def _axis_size(sizes: Dict[str, int], ax: Any) -> int:
    if ax is None:
        return 1
    if isinstance(ax, tuple):  # multi-axis split (flat_row)
        p = 1
        for sub in ax:
            p *= sizes.get(sub, 1)
        return p
    return sizes.get(ax, 1)


def _parallelism(state: Tuple, sizes: Dict[str, int]) -> int:
    p = 1
    for a in state:
        p *= _axis_size(sizes, a)
    return p


# (src axes, dst axes, sorted mesh items) -> tuple of Schedules. The
# vocabulary is tiny (candidate tilings squared per mesh shape), so the
# memo never needs eviction; fractions are size-independent.
_sched_memo: Dict[Tuple, Tuple[Schedule, ...]] = {}


def _enumerate(src_axes: Tuple, dst_axes: Tuple,
               sizes: Dict[str, int]) -> Tuple[Schedule, ...]:
    """DFS over tiling states from ``src`` to ``dst`` with the three
    productive moves (gather a mismatched axis, slice a wanted axis
    onto a free mesh axis, all_to_all a mesh axis straight to where
    the destination wants it). Every simple path is a legal schedule;
    the caller prices and picks."""
    ndim = len(src_axes)
    out: List[Schedule] = []
    max_depth = 2 * ndim + 2

    def dfs(state: Tuple, steps: Tuple[Step, ...],
            comm: Dict[str, float], peak: float,
            states: Tuple[Tuple, ...], seen: frozenset) -> None:
        if state == dst_axes:
            out.append(Schedule(steps, dict(comm), peak, states))
            return
        if len(steps) >= max_depth or len(out) >= 64:
            return
        p_all = _parallelism(state, sizes)
        local = 1.0 / p_all
        used = {a for a in state if a is not None}
        for i in range(ndim):
            cur, want = state[i], dst_axes[i]
            if cur is not None and cur != want:
                m, p = cur, _axis_size(sizes, cur)
                # all_gather: release m from axis i — each chip
                # receives the (p-1) peer shards of the gathered axis
                nxt = state[:i] + (None,) + state[i + 1:]
                if nxt not in seen:
                    c = dict(comm)
                    c["all_gather"] = (c.get("all_gather", 0.0)
                                       + (p - 1) / p_all)
                    dfs(nxt, steps + (Step("all_gather", i, m),),
                        c, max(peak, local * p), states + (nxt,),
                        seen | {nxt})
                # all_to_all: move m to the axis j the destination
                # wants it on — each chip keeps 1/p of its shard
                for j in range(ndim):
                    if j == i or state[j] is not None \
                            or dst_axes[j] != m:
                        continue
                    nxt = list(state)
                    nxt[i], nxt[j] = None, m
                    nxt = tuple(nxt)
                    if nxt in seen:
                        continue
                    c = dict(comm)
                    c["all_to_all"] = (c.get("all_to_all", 0.0)
                                       + (p - 1) / p * local)
                    dfs(nxt, steps + (Step("all_to_all", i, m, j),),
                        c, max(peak, local), states + (nxt,),
                        seen | {nxt})
            elif cur is None and want is not None and want not in used:
                # slice: carve axis i onto the free mesh axis the
                # destination wants — no wire traffic
                nxt = state[:i] + (want,) + state[i + 1:]
                if nxt in seen:
                    continue
                p = _axis_size(sizes, want)
                dfs(nxt, steps + (Step("slice", i, want),),
                    dict(comm), max(peak, local / p),
                    states + (nxt,), seen | {nxt})

    dfs(src_axes, (), {}, 0.0, (), frozenset({src_axes}))
    return tuple(out)


def schedules(src: Tiling, dst: Tiling, mesh) -> Tuple[Schedule, ...]:
    """Every legal decomposition of ``src -> dst`` on ``mesh`` (empty
    when the transition is a no-op, uses tuple-sharded mesh axes the
    step vocabulary cannot express, or mismatches rank)."""
    if src.axes == dst.axes or len(src.axes) != len(dst.axes):
        return ()
    if any(isinstance(a, tuple) for a in src.axes + dst.axes):
        return ()  # multi-axis splits: GSPMD owns these
    key = (src.axes, dst.axes, tuple(sorted(mesh.shape.items())))
    hit = _sched_memo.get(key)
    if hit is None:
        hit = _sched_memo[key] = _enumerate(
            src.axes, dst.axes, dict(mesh.shape))
    return hit


def _canonical_frac(src_axes: Tuple, dst_axes: Tuple,
                    sizes: Dict[str, int]) -> float:
    """The gather-everything-then-slice reference — the model of
    GSPMD's generic lowering: every mismatched sharded source axis is
    fully gathered (in axis order), destination shards carved locally
    after. Returns the per-chip receive fraction."""
    state = list(src_axes)
    frac = 0.0
    for i, (cur, want) in enumerate(zip(src_axes, dst_axes)):
        if cur is not None and cur != want:
            p_all = 1
            for a in state:
                p_all *= _axis_size(sizes, a)
            frac += (_axis_size(sizes, cur) - 1) / p_all
            state[i] = None
    return frac


class Decision(NamedTuple):
    """What the planner chose for one reshard edge: the best
    ``schedule``, whether the ``explicit`` lowering should be emitted,
    the modeled ``cost`` / ``gspmd_cost`` (bytes-equivalent, factored),
    and a human ``reason`` for the explain report."""

    schedule: Schedule
    explicit: bool
    cost: float
    gspmd_cost: float
    reason: str


def decide(src: Tiling, dst: Tiling, shape: Tuple[int, ...], dtype: Any,
           mesh, factors: Optional[Dict[str, float]] = None
           ) -> Optional[Decision]:
    """Plan one edge: cheapest schedule + the explicit-vs-fallback
    call. None when the transition needs no schedule (same layout /
    rank mismatch / inexpressible). ``factors`` are the calibration
    profile's per-collective multipliers (``obs/ledger.factors()``) —
    the same dict the tiling DP prices with, so the lowering and the
    DP always agree on the winner."""
    scheds = schedules(src, dst, mesh)
    if not scheds:
        return None
    nbytes = float(int(np.prod(shape)) if shape else 1) \
        * np.dtype(dtype).itemsize
    best = min(scheds, key=lambda s: (s.cost(nbytes, factors),
                                      len(s.steps), s.describe()))
    gspmd = _canonical_frac(src.axes, dst.axes, dict(mesh.shape))
    g_f = factors.get("all_gather", 1.0) if factors else 1.0
    gspmd_cost = gspmd * nbytes * g_f
    cost = best.cost(nbytes, factors)
    if mesh_mod.device_count(mesh) <= 1:
        return Decision(best, False, cost, gspmd_cost,
                        "single device: nothing to move")
    if cost >= gspmd_cost or not best.steps:
        return Decision(best, False, cost, gspmd_cost,
                        "no modeled win over generic lowering")
    if len(best.steps) != 1 or best.steps[0].kind != "all_to_all":
        # The explicit lowering is emitted ONLY for the one-step
        # all_to_all transition (a mesh axis moving between two array
        # axes): that is the decomposition GSPMD's generic lowering
        # misses — it materializes the gathered axis — and the ONLY
        # shape the per-edge CPU A/B (benchmarks/redistribution.py
        # edge_ab) measures at or below the GSPMD arm. Gather/slice
        # routes and multi-step mixes measured WORSE: XLA fuses its
        # own gathers/slices better than an opaque shard_map can.
        # The DP still PRICES the full schedule (the model is about
        # edge cost, not lowering), and explain reports it.
        return Decision(best, False, cost, gspmd_cost,
                        "multi-step schedule: GSPMD's fused lowering "
                        "measured cheaper; modeled price kept")
    for state in (src.axes,) + best.states:
        if not Tiling(state).divisible(shape, mesh):
            return Decision(best, False, cost, gspmd_cost,
                            "indivisible intermediate: GSPMD pads")
    return Decision(best, True, cost, gspmd_cost,
                    f"modeled {cost:.0f} < gspmd {gspmd_cost:.0f} "
                    "bytes-equivalent")


def edge_cost(src: Tiling, dst: Tiling, nbytes: float, mesh,
              factors: Optional[Dict[str, float]] = None) -> float:
    """The tiling DP's planned edge price: the cheapest schedule's
    modeled cost (per-collective factors applied), clamped at the
    receive-bytes floor — the modeled cost can reorder schedules but
    never under-bids the bytes a correct redistribution must deliver.
    Falls back to the floor (under the legacy 'reshard' factor) for
    transitions the step vocabulary cannot express."""
    from ..expr.tiling_cost import reshard_cost  # lazy: layer order

    floor = reshard_cost(src, dst, nbytes, mesh)
    if floor <= 0.0:
        return floor  # same layout, or local carve: nothing to plan
    scheds = schedules(src, dst, mesh)
    if not scheds:
        f = factors.get("reshard", 1.0) if factors else 1.0
        return floor * f
    best = min(s.cost(nbytes, factors) for s in scheds)
    return max(best, floor)


def edge_components(src: Tiling, dst: Tiling, nbytes: float, mesh
                    ) -> Dict[str, float]:
    """Per-collective byte decomposition of one planned edge — the
    calibration vector ``tiling_cost.class_components`` records so
    ``obs/ledger.fit_profile`` can fit each collective's factor
    independently. Uncalibrated by construction (raw schedule bytes);
    falls back to the legacy lump 'reshard' class when unplannable."""
    from ..expr.tiling_cost import reshard_cost  # lazy: layer order

    scheds = schedules(src, dst, mesh)
    if scheds:
        best = min(scheds, key=lambda s: s.cost(nbytes))
        return {cls: frac * nbytes
                for cls, frac in best.comm_frac.items() if frac > 0}
    moved = reshard_cost(src, dst, nbytes, mesh)
    return {"reshard": moved} if moved > 0 else {}


def staging_frac(src: Tiling, dst: Tiling, mesh) -> Optional[float]:
    """Peak per-chip staging of the chosen schedule, as a fraction of
    the full array's bytes — the memory governor's schedule-derived
    reshard-staging price (``resilience/memory._staging_bytes``).
    None when no schedule exists (the layout-fraction fallback
    applies)."""
    scheds = schedules(src, dst, mesh)
    if not scheds:
        return None
    return min(scheds, key=lambda s: s.cost(1.0)).peak_frac


def _cal_factors() -> Optional[Dict[str, float]]:
    """The active calibration profile's factors (lazy import: obs sits
    beside, not below, the parallel layer)."""
    from ..obs import ledger

    return ledger.factors()


# -- cross-MESH-SHAPE transitions (elastic re-tiling) ---------------------
#
# Everything above plans src -> dst transitions on ONE mesh. An elastic
# recovery (parallel/mesh.rebuild_mesh after host/device loss) changes
# the mesh SHAPE: an M-device grid becomes an N-device survivor grid,
# and every live array and restored loop carry must be re-partitioned
# across grids. The same decomposition idea applies ("Memory-efficient
# array redistribution", PAPERS.md), with one extra step kind:
#
#   * ``transfer`` — the cross-grid hop itself: each destination chip
#     receives its shard of the CURRENT tiling state under the
#     destination grid's sizes. A fully-replicated state transfers for
#     free onto a survivor subset (every survivor already holds a full
#     copy); a sharded state re-fetches one destination-local shard per
#     chip (shard boundaries shift when the grid size changes).
#
# A cross-mesh schedule is then [gathers on the source grid]* +
# transfer + [local slices on the destination grid]*. The degenerate
# all-gather-everything + transfer(free) + slice route is the model of
# the gather fallback (host round-trip / GSPMD re-tile) — the route
# :meth:`DistArray.rehome` always had; the planner's job is to emit
# the cheaper direct repartition where every intermediate state
# divides the shape on its grid, and a REASONED fallback otherwise
# (tuple-sharded ``flat_row`` axes stay fallback: the step vocabulary
# cannot express a two-axis peel, and the reason says so).


class MigrationDecision(NamedTuple):
    """The planner's verdict for one cross-mesh-shape migration:
    ``schedule`` (None when nothing was plannable), ``route`` —
    ``direct`` (divisible repartition: executed as a sharding-to-
    sharding transfer), ``gather`` (replicate-then-carve fallback) or
    ``noop`` — the modeled per-chip wire ``cost`` (factored), total
    modeled ``bytes`` on the wire, and a human ``reason`` for the
    recovery span / ``st.explain`` migrations section."""

    schedule: Optional[Schedule]
    route: str
    cost: float
    bytes: float
    reason: str


# (src axes, dst axes, src grid items, dst grid items) -> schedules.
_cross_memo: Dict[Tuple, Tuple[Schedule, ...]] = {}


def _enumerate_cross(src_axes: Tuple, dst_axes: Tuple,
                     src_sizes: Dict[str, int],
                     dst_sizes: Dict[str, int]) -> Tuple[Schedule, ...]:
    """DFS over cross-grid schedules: phase 0 releases source-grid
    shardings (``all_gather`` priced on the SOURCE sizes), one
    ``transfer`` hops grids (receive = the state's local fraction on
    the DESTINATION sizes; free when replicated — survivors hold a
    full copy), phase 1 carves destination shardings (``slice``,
    free). ``states`` records (phase, axes) so divisibility is checked
    against the right grid."""
    ndim = len(src_axes)
    out: List[Schedule] = []

    def local(state: Tuple, sizes: Dict[str, int]) -> float:
        return 1.0 / _parallelism(state, sizes)

    def dfs_dst(state: Tuple, steps: Tuple[Step, ...],
                comm: Dict[str, float], peak: float,
                states: Tuple[Tuple, ...]) -> None:
        if state == dst_axes:
            out.append(Schedule(steps, dict(comm), peak, states))
            return
        if len(steps) >= 2 * ndim + 3 or len(out) >= 64:
            return
        used = {a for a in state if a is not None}
        for i in range(ndim):
            cur, want = state[i], dst_axes[i]
            if cur is None and want is not None and want not in used:
                nxt = state[:i] + (want,) + state[i + 1:]
                dfs_dst(nxt, steps + (Step("slice", i, want),),
                        comm, max(peak, local(nxt, dst_sizes)),
                        states + (("dst", nxt),))

    def dfs_src(state: Tuple, steps: Tuple[Step, ...],
                comm: Dict[str, float], peak: float,
                states: Tuple[Tuple, ...]) -> None:
        if len(out) >= 64:
            return
        # the transfer hop is legal from any state every destination
        # axis of which is either already right or still carvable:
        # phase 1 only ADDS shardings, never releases them
        ok = all(c is None or c == w
                 for c, w in zip(state, dst_axes))
        if ok:
            frac = (0.0 if all(a is None for a in state)
                    else local(state, dst_sizes))
            c = dict(comm)
            if frac > 0:
                c["transfer"] = c.get("transfer", 0.0) + frac
            dfs_dst(state,
                    steps + (Step("transfer", -1, "grid"),),
                    c, max(peak, local(state, dst_sizes)),
                    states + (("dst", state),))
        if len(steps) >= ndim + 1:
            return
        for i in range(ndim):
            cur = state[i]
            if cur is None:
                continue
            # release this source-grid sharding (all_gather on src)
            p = _axis_size(src_sizes, cur)
            nxt = state[:i] + (None,) + state[i + 1:]
            c = dict(comm)
            c["all_gather"] = (c.get("all_gather", 0.0)
                               + (p - 1) / _parallelism(state,
                                                        src_sizes))
            dfs_src(nxt, steps + (Step("all_gather", i, cur),),
                    c, max(peak, local(nxt, src_sizes)),
                    states + (("src", nxt),))

    dfs_src(src_axes, (), {}, local(src_axes, src_sizes),
            (("src", src_axes),))
    return tuple(out)


def cross_mesh_schedules(src: Tiling, src_sizes: Dict[str, int],
                         dst: Tiling, dst_sizes: Dict[str, int]
                         ) -> Tuple[Schedule, ...]:
    """Every legal cross-grid decomposition of ``src`` on the
    ``src_sizes`` grid -> ``dst`` on the ``dst_sizes`` grid. Empty for
    rank mismatches and tuple-sharded (flat_row) axes — the step
    vocabulary cannot peel a two-axis split, so those take the gather
    fallback with a recorded reason (:func:`plan_transition`)."""
    if len(src.axes) != len(dst.axes):
        return ()
    if any(isinstance(a, tuple) for a in src.axes + dst.axes):
        return ()
    key = (src.axes, dst.axes, tuple(sorted(src_sizes.items())),
           tuple(sorted(dst_sizes.items())))
    hit = _cross_memo.get(key)
    if hit is None:
        hit = _cross_memo[key] = _enumerate_cross(
            src.axes, dst.axes, dict(src_sizes), dict(dst_sizes))
    return hit


def _divides(axes: Tuple, shape: Tuple[int, ...],
             sizes: Dict[str, int]) -> bool:
    for d, a in zip(shape, axes):
        p = _axis_size(sizes, a)
        if p > 1 and int(d) % p != 0:
            return False
    return True


def plan_transition(src: Tiling, dst: Tiling,
                    src_sizes: Dict[str, int],
                    dst_sizes: Dict[str, int],
                    shape: Tuple[int, ...], dtype: Any,
                    factors: Optional[Dict[str, float]] = None
                    ) -> MigrationDecision:
    """Plan ONE cross-mesh-shape migration (elastic re-tiling): the
    cheapest schedule and whether the direct repartition route is
    safe, or the reasoned gather fallback. Never raises — migration
    planning is advisory; the executor (``DistArray.rehome``,
    checkpoint restore) always has the gather route."""
    nbytes = float(int(np.prod(shape)) if shape else 1) \
        * np.dtype(dtype).itemsize
    same_grid = dict(src_sizes) == dict(dst_sizes)
    if src.axes == dst.axes and same_grid:
        return MigrationDecision(None, "noop", 0.0, 0.0,
                                 "same tiling on the same grid")
    if any(isinstance(a, tuple) for a in src.axes + dst.axes):
        # flat_row and friends: a tuple-sharded axis needs a two-axis
        # peel the step vocabulary cannot express — documented status
        # (docs/REDISTRIBUTION.md), reasoned fallback, not a crash
        p_src = _parallelism(src.axes, src_sizes)
        moved = nbytes * (1.0 - 1.0 / max(p_src, 1))
        return MigrationDecision(
            None, "gather", moved, moved,
            "tuple-sharded (flat_row) axes: outside the step "
            "vocabulary; gather fallback")
    scheds = cross_mesh_schedules(src, src_sizes, dst, dst_sizes)
    if not scheds:
        p_src = _parallelism(src.axes, src_sizes)
        moved = nbytes * (1.0 - 1.0 / max(p_src, 1))
        return MigrationDecision(
            None, "gather", moved, moved,
            "no cross-grid schedule (rank/axis mismatch): gather "
            "fallback")
    best = min(scheds, key=lambda s: (s.cost(nbytes, factors),
                                      len(s.steps), s.describe()))
    # divisibility per phase: pre-transfer states must divide on the
    # SOURCE grid, post-transfer states on the DESTINATION grid — an
    # indivisible intermediate means padded shards whose boundaries
    # the direct repartition would mis-slice
    for phase, axes in best.states:
        sizes = src_sizes if phase == "src" else dst_sizes
        if not _divides(axes, shape, sizes):
            moved = best.comm_bytes(nbytes)
            return MigrationDecision(
                best, "gather", best.cost(nbytes, factors), moved,
                f"indivisible intermediate {axes} on the "
                f"{'survivor' if phase == 'dst' else 'source'} grid: "
                "gather fallback")
    moved = best.comm_bytes(nbytes)
    return MigrationDecision(
        best, "direct", best.cost(nbytes, factors), moved,
        f"planned {best.describe()} "
        f"(~{int(moved)} modeled wire bytes)")


def plan_rehome(arr: Any, dst_mesh) -> Tuple[Tiling, MigrationDecision]:
    """Plan one live array's migration onto ``dst_mesh`` (the elastic
    recovery path): the destination tiling is the source tiling
    sanitized for the survivor grid (axes that no longer divide are
    dropped), the decision is :func:`plan_transition` under the active
    calibration factors."""
    from ..array import tiling as tiling_mod

    shape = tuple(int(s) for s in arr.shape)
    dst_t = tiling_mod.sanitize(arr.tiling, shape, dst_mesh)
    dec = plan_transition(
        arr.tiling, dst_t, {k: int(v) for k, v in arr.mesh.shape.items()},
        {k: int(v) for k, v in dst_mesh.shape.items()},
        shape, arr.dtype, _cal_factors())
    return dst_t, dec


def apply_schedule(val: Any, schedule: Schedule, src: Tiling,
                   dst: Tiling, mesh) -> Any:
    """Emit the explicit shard_map program for one schedule: constrain
    the value to ``src`` (the layout the plan priced from), then run
    the collective steps over local blocks. Callers must have checked
    divisibility (``decide`` does)."""
    from ..utils.compat import shard_map

    val = jax.lax.with_sharding_constraint(val, src.sharding(mesh))
    sizes = dict(mesh.shape)

    def kern(x):
        for step in schedule.steps:
            if step.kind == "all_gather":
                x = lax.all_gather(x, step.mesh_axis, axis=step.axis,
                                   tiled=True)
            elif step.kind == "all_to_all":
                x = lax.all_to_all(x, step.mesh_axis,
                                   split_axis=step.to_axis,
                                   concat_axis=step.axis, tiled=True)
            else:  # slice: carve this chip's destination shard
                p = sizes[step.mesh_axis]
                size = x.shape[step.axis] // p
                idx = lax.axis_index(step.mesh_axis)
                x = lax.dynamic_slice_in_dim(x, idx * size, size,
                                             axis=step.axis)
        return x

    # check_rep off: the slice step's axis_index makes replication
    # tracking version-dependent; out_specs already pins the contract
    mapped = shard_map(kern, mesh=mesh, in_specs=(src.spec(),),
                       out_specs=dst.spec(), check_rep=False)
    return mapped(val)


def constrain(val: Any, tiling: Tiling, mesh=None,
              src: Optional[Tiling] = None) -> Any:
    """THE sharding-constraint seam (lint rule 10): request ``tiling``
    for a traced value. With the planner on and the producing layout
    known (``src`` — the DP's committed child tiling at reshard
    edges), edges where the model predicts a win over GSPMD's generic
    lowering are emitted as the explicit collective schedule;
    everything else — planner off, unknown source, inexpressible or
    indivisible transitions, no predicted win — falls back to
    ``with_sharding_constraint`` (the portable default)."""
    if mesh is None:
        mesh = mesh_mod.get_mesh()
    if _PLANNER_FLAG._value and src is not None \
            and src.axes != tiling.axes:
        shape = tuple(int(s) for s in getattr(val, "shape", ()))
        d = decide(src, tiling, shape, val.dtype, mesh,
                   _cal_factors())
        if d is not None and d.explicit:
            prof.count("redistribute_explicit")
            return apply_schedule(val, d.schedule, src, tiling, mesh)
        if d is not None:
            prof.count("redistribute_fallback")
    return jax.lax.with_sharding_constraint(val, tiling.sharding(mesh))
