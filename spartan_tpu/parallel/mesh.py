"""Device mesh management.

Replaces the reference's cluster bring-up entirely (SURVEY.md §3.1: master
spawn + worker registration + BlobCtx install collapses to mesh
construction). A single ambient mesh plays the role the ambient ``BlobCtx``
played: every DistArray is sharded over it.

Mesh axes:
  * ``"x"`` — the primary tiling axis (rows / batch). Data-parallel axis.
  * ``"y"`` — the secondary tiling axis (cols / model). Tensor-parallel axis.

A 2-D mesh is built by default whenever the device count is composite, so
row (``P('x', None)``), col (``P(None, 'y')``) and block (``P('x', 'y')``)
tilings are all expressible — the reference's tiling vocabulary
(SURVEY.md §2.6). On one device the mesh is 1×1 and every spec degrades to
replicated, so code is mesh-size agnostic (SURVEY.md §7 hard part 6).
"""

from __future__ import annotations

import math
import os
import threading
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.config import FLAGS

AXIS_ROW = "x"
AXIS_COL = "y"

_state = threading.local()


def _factor_2d(n: int) -> Tuple[int, int]:
    """Split n devices into the most-square (rows, cols) grid, favoring
    more rows (the batch axis carries most parallelism in the workloads)."""
    best = (n, 1)
    for c in range(1, int(math.isqrt(n)) + 1):
        if n % c == 0:
            best = (n // c, c)
    return best


def build_mesh(devices: Optional[Sequence[jax.Device]] = None,
               shape: Optional[Tuple[int, int]] = None) -> Mesh:
    """Build an (x, y) mesh over ``devices`` (default: all)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if FLAGS.default_mesh_1d and FLAGS.default_mesh_1d > 0:
        n = min(n, FLAGS.default_mesh_1d)
        devices = devices[:n]
    if shape is None:
        shape = _factor_2d(n)
    if shape[0] * shape[1] != n:
        raise ValueError(f"mesh shape {shape} != {n} devices")
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, (AXIS_ROW, AXIS_COL))


def set_mesh(mesh: Optional[Mesh]) -> None:
    _state.mesh = mesh


def get_mesh() -> Mesh:
    mesh = getattr(_state, "mesh", None)
    if mesh is None:
        mesh = build_mesh()
        _state.mesh = mesh
    return mesh


class use_mesh:
    """Context manager pinning the ambient mesh (tests use a CPU mesh)."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self._prev: Optional[Mesh] = None

    def __enter__(self) -> Mesh:
        self._prev = getattr(_state, "mesh", None)
        _state.mesh = self.mesh
        return self.mesh

    def __exit__(self, *exc) -> None:
        _state.mesh = self._prev


def mesh_axis_sizes(mesh: Optional[Mesh] = None) -> Tuple[int, int]:
    mesh = mesh or get_mesh()
    return (mesh.shape[AXIS_ROW], mesh.shape[AXIS_COL])


def replicated_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    return NamedSharding(mesh or get_mesh(), P())


def named_sharding(spec: P, mesh: Optional[Mesh] = None) -> NamedSharding:
    return NamedSharding(mesh or get_mesh(), spec)


def device_count(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    return int(np.prod(list(mesh.shape.values())))


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> bool:
    """Multi-host bring-up: ``jax.distributed`` plays the role the
    reference's master played (registration/barrier over DCN —
    SURVEY.md §2.7). No-op (returns False) when single-host: args absent
    and no cluster environment detected."""
    import jax

    try:
        if coordinator_address is not None:
            jax.distributed.initialize(coordinator_address,
                                       num_processes, process_id)
            return True
        # Auto-detection ONLY on an explicit coordinator address: a
        # bare SLURM_JOB_ID must not trigger it — a single-process run
        # inside a multi-task allocation would start the coordinator
        # and BLOCK waiting for peers that never register. SLURM/pod
        # users launched on every task call this with explicit args or
        # set COORDINATOR_ADDRESS.
        if os.environ.get("COORDINATOR_ADDRESS"):
            jax.distributed.initialize()
            return True
    except Exception as e:  # pragma: no cover - env-dependent
        from ..utils.log import log_warn

        log_warn("jax.distributed initialization failed: %s", e)
    return False


def status() -> dict:
    """Cluster status snapshot (the observability analogue of the
    reference's worker-status heartbeats — SURVEY.md §5)."""
    import jax

    mesh = get_mesh()
    devs = jax.devices()
    mem = {}
    try:
        mem = dict(jax.local_devices()[0].memory_stats() or {})
    except Exception:
        pass
    return {
        "platform": devs[0].platform if devs else "none",
        "num_devices": len(devs),
        "num_local_devices": len(jax.local_devices()),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "mesh": {k: int(v) for k, v in mesh.shape.items()},
        "memory_stats": mem,
    }
