"""Device mesh management.

Replaces the reference's cluster bring-up entirely (SURVEY.md §3.1: master
spawn + worker registration + BlobCtx install collapses to mesh
construction). A single ambient mesh plays the role the ambient ``BlobCtx``
played: every DistArray is sharded over it.

Mesh axes:
  * ``"x"`` — the primary tiling axis (rows / batch). Data-parallel axis.
  * ``"y"`` — the secondary tiling axis (cols / model). Tensor-parallel axis.

A 2-D mesh is built by default whenever the device count is composite, so
row (``P('x', None)``), col (``P(None, 'y')``) and block (``P('x', 'y')``)
tilings are all expressible — the reference's tiling vocabulary
(SURVEY.md §2.6). On one device the mesh is 1×1 and every spec degrades to
replicated, so code is mesh-size agnostic (SURVEY.md §7 hard part 6).
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils.config import FLAGS

AXIS_ROW = "x"
AXIS_COL = "y"

_state = threading.local()

# -- mesh epoch (elastic recovery) ---------------------------------------
#
# A monotonic process-wide generation counter, bumped by every
# ``rebuild_mesh`` (device/host loss shrinks the mesh). Everything that
# binds to a mesh — DistArrays at construction, plan/compile-cache keys
# at signing time (expr/base._mesh_key) — records the epoch it was born
# under, so an artifact from a dead mesh can never dispatch: stale
# plans simply miss the cache, and stale DistArrays raise
# :class:`StaleMeshError` at arg-gather time instead of handing XLA a
# buffer on a device that no longer exists. Reads are unlocked (one
# module-attribute load on the hot path); writes hold ``_epoch_lock``.

_EPOCH = 0
_epoch_lock = threading.Lock()
_global_mesh: Optional[Mesh] = None
_excluded_ids: Tuple[int, ...] = ()

# epoch -> {axis: size} of the mesh that generation ran on. Recorded
# by rebuild_mesh (both the dying and the rebuilt shape), so the
# cross-mesh migration planner (parallel/redistribute.plan_transition)
# and the recovery spans can name the source grid of an artifact whose
# mesh object is gone — e.g. a loop carry restored from a snapshot
# written two epochs ago.
_shape_history: dict = {}


def mesh_epoch() -> int:
    """The current mesh generation (bumped by ``rebuild_mesh``)."""
    return _EPOCH


def mesh_shape_at(epoch: int) -> Optional[dict]:
    """The {axis: size} grid of mesh generation ``epoch``, when known
    (rebuild_mesh records both sides of every transition)."""
    return _shape_history.get(int(epoch))


class StaleMeshError(RuntimeError):
    """A mesh-bound artifact (DistArray, plan) from a previous mesh
    epoch was used after ``rebuild_mesh``: its device buffers live on
    a mesh that no longer exists. Carries the offending arrays on
    ``.arrays`` so elastic recovery (``resilience/elastic.rehome``)
    can migrate the ones that are still fetchable."""

    def __init__(self, msg: str, arrays: Sequence = ()):
        super().__init__(msg)
        self.arrays = list(arrays)


def _factor_2d(n: int) -> Tuple[int, int]:
    """Split n devices into the most-square (rows, cols) grid, favoring
    more rows (the batch axis carries most parallelism in the workloads)."""
    best = (n, 1)
    for c in range(1, int(math.isqrt(n)) + 1):
        if n % c == 0:
            best = (n // c, c)
    return best


def build_mesh(devices: Optional[Sequence[jax.Device]] = None,
               shape: Optional[Tuple[int, int]] = None) -> Mesh:
    """Build an (x, y) mesh over ``devices`` (default: all)."""
    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if FLAGS.default_mesh_1d and FLAGS.default_mesh_1d > 0:
        n = min(n, FLAGS.default_mesh_1d)
        devices = devices[:n]
    if shape is None:
        shape = _factor_2d(n)
    if shape[0] * shape[1] != n:
        raise ValueError(f"mesh shape {shape} != {n} devices")
    arr = np.array(devices).reshape(shape)
    return Mesh(arr, (AXIS_ROW, AXIS_COL))


def set_mesh(mesh: Optional[Mesh]) -> None:
    _state.mesh = mesh
    _state.epoch = _EPOCH


def get_mesh() -> Mesh:
    """The ambient mesh, epoch-fenced: a thread-local pin (``set_mesh``
    / ``use_mesh``) from a previous epoch is discarded — after a
    ``rebuild_mesh`` every thread sees the rebuilt mesh, including
    threads parked inside a ``use_mesh`` of the dead one."""
    mesh = getattr(_state, "mesh", None)
    if mesh is not None and getattr(_state, "epoch", 0) == _EPOCH:
        return mesh
    global _global_mesh
    mesh = _global_mesh
    if mesh is None:
        with _epoch_lock:
            if _global_mesh is None:
                _global_mesh = _build_surviving()
            mesh = _global_mesh
    _state.mesh = mesh
    _state.epoch = _EPOCH
    return mesh


class use_mesh:
    """Context manager pinning the ambient mesh (tests use a CPU mesh).

    The pin is epoch-scoped: if ``rebuild_mesh`` runs inside the
    context, ``get_mesh`` stops honoring the (now-dead) pinned mesh."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self._prev: Optional[Mesh] = None
        self._prev_epoch: int = 0

    def __enter__(self) -> Mesh:
        self._prev = getattr(_state, "mesh", None)
        self._prev_epoch = getattr(_state, "epoch", _EPOCH)
        _state.mesh = self.mesh
        _state.epoch = _EPOCH
        return self.mesh

    def __exit__(self, *exc) -> None:
        _state.mesh = self._prev
        _state.epoch = self._prev_epoch


def _build_surviving(shape: Optional[Tuple[int, int]] = None) -> Mesh:
    """Build a mesh over every device NOT excluded by a prior
    ``rebuild_mesh`` (the current survivor set)."""
    devices = [d for d in jax.devices() if d.id not in _excluded_ids]
    if not devices:
        raise RuntimeError("rebuild_mesh excluded every device")
    return build_mesh(devices, shape=shape)


def rebuild_mesh(exclude_devices: Sequence = (),
                 shape: Optional[Tuple[int, int]] = None) -> Mesh:
    """Shrink (or reshape) the mesh after persistent device/host loss
    and bump the mesh epoch — the terminal rung of the resilience
    ladder (docs/RESILIENCE.md, elastic recovery).

    ``exclude_devices`` are devices (or device ids) to REMOVE from the
    survivor set, cumulative with previous rebuilds. The epoch bump
    invalidates every mesh-bound artifact: plan/compile-cache keys
    carry the epoch (stale plans miss), DistArrays record their birth
    epoch (cross-epoch use raises :class:`StaleMeshError`), and
    ``get_mesh``'s thread-local pins are fenced. The caller
    (``resilience/elastic``) is responsible for draining dispatches
    first and evicting the dead epoch's cache entries after."""
    global _EPOCH, _global_mesh, _excluded_ids
    with _epoch_lock:
        if _global_mesh is not None:
            _shape_history.setdefault(
                _EPOCH, {k: int(v) for k, v in _global_mesh.shape.items()})
        excluded = set(_excluded_ids)
        for d in exclude_devices:
            excluded.add(d if isinstance(d, int) else d.id)
        _excluded_ids = tuple(sorted(excluded))
        _EPOCH += 1
        _global_mesh = _build_surviving(shape)
        _shape_history[_EPOCH] = {k: int(v)
                                  for k, v in _global_mesh.shape.items()}
        _state.mesh = _global_mesh
        _state.epoch = _EPOCH
        from ..utils.log import log_warn

        log_warn("mesh epoch %d: rebuilt over %d surviving device(s)"
                 "%s", _EPOCH, _global_mesh.devices.size,
                 f" (excluded ids {_excluded_ids})" if _excluded_ids
                 else "")
        return _global_mesh


def reset_epoch_for_tests() -> None:
    """Restore the full-device, epoch-0 world (test isolation only:
    production epochs are monotonic by design)."""
    global _EPOCH, _global_mesh, _excluded_ids
    with _epoch_lock:
        _EPOCH = 0
        _global_mesh = None
        _excluded_ids = ()
        _shape_history.clear()
        _state.mesh = None
        _state.epoch = 0


def mesh_axis_sizes(mesh: Optional[Mesh] = None) -> Tuple[int, int]:
    mesh = mesh or get_mesh()
    return (mesh.shape[AXIS_ROW], mesh.shape[AXIS_COL])


def replicated_sharding(mesh: Optional[Mesh] = None) -> NamedSharding:
    return NamedSharding(mesh or get_mesh(), P())


def named_sharding(spec: P, mesh: Optional[Mesh] = None) -> NamedSharding:
    return NamedSharding(mesh or get_mesh(), spec)


def device_count(mesh: Optional[Mesh] = None) -> int:
    mesh = mesh or get_mesh()
    return int(np.prod(list(mesh.shape.values())))


def rotated_mesh(mesh: Optional[Mesh] = None, k: int = 1
                 ) -> Optional[Mesh]:
    """A mesh with the SAME shape and axis names but the device
    assignment rotated by ``k`` positions — every logical coordinate
    maps to a different physical chip. The integrity sentinel
    (resilience/integrity.py) re-executes sampled plans on a rotated
    assignment so a per-shard checksum disagreement separates "this
    chip computes wrong bits" from "this value is wrong wherever it is
    computed". Returns None for a single-device mesh (no rotation
    exists). Never installed or cached: callers build one per check
    and drop it (the epoch/staleness machinery only governs the one
    global mesh)."""
    mesh = mesh or get_mesh()
    devs = list(mesh.devices.flat)
    n = len(devs)
    if n < 2:
        return None
    k = k % n
    if k == 0:
        k = 1
    rot = devs[k:] + devs[:k]
    return Mesh(np.array(rot).reshape(mesh.devices.shape),
                mesh.axis_names)


_dist_initialized = False
_dist_lock = threading.Lock()

# "already initialized" phrasings across jax versions: the re-entrant
# fast path treats them as success, not failure
_ALREADY_INIT = ("already initialized", "already been initialized",
                 "initialize should be called once")


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None,
                           max_attempts: int = 3,
                           backoff_s: float = 0.5) -> bool:
    """Multi-host bring-up: ``jax.distributed`` plays the role the
    reference's master played (registration/barrier over DCN —
    SURVEY.md §2.7). No-op (returns False) when single-host: args absent
    and no cluster environment detected.

    Re-entrant: a second call (e.g. from elastic recovery after a host
    loss, or ``st.initialize`` called twice) returns True without
    re-dialing the coordinator. Transient connect failures
    (UNAVAILABLE / DEADLINE_EXCEEDED / refused connections — a
    coordinator restarting after the same host loss that triggered the
    reconnect) retry up to ``max_attempts`` times with doubling
    ``backoff_s``; anything else fails once, loudly."""
    import jax

    from ..utils.log import log_warn

    global _dist_initialized
    want = (coordinator_address is not None
            or bool(os.environ.get("COORDINATOR_ADDRESS")))
    if not want:
        # Auto-detection ONLY on an explicit coordinator address: a
        # bare SLURM_JOB_ID must not trigger it — a single-process run
        # inside a multi-task allocation would start the coordinator
        # and BLOCK waiting for peers that never register. SLURM/pod
        # users launched on every task call this with explicit args or
        # set COORDINATOR_ADDRESS.
        return False
    with _dist_lock:
        if _dist_initialized:
            return True
        delay = backoff_s
        for attempt in range(max(1, max_attempts)):
            try:
                if coordinator_address is not None:
                    jax.distributed.initialize(coordinator_address,
                                               num_processes, process_id)
                else:
                    jax.distributed.initialize()
                _dist_initialized = True
                return True
            except Exception as e:  # pragma: no cover - env-dependent
                text = str(e).lower()
                if any(m in text for m in _ALREADY_INIT):
                    _dist_initialized = True
                    return True
                transient = any(m in text for m in (
                    "unavailable", "deadline", "connection refused",
                    "connection reset", "failed to connect", "timed out"))
                if transient and attempt + 1 < max(1, max_attempts):
                    log_warn("jax.distributed connect attempt %d/%d "
                             "failed (%s); retrying in %.2fs",
                             attempt + 1, max_attempts, str(e)[:120],
                             delay)
                    time.sleep(delay)
                    delay *= 2
                    continue
                log_warn("jax.distributed initialization failed: %s", e)
                return False
    return False


def status() -> dict:
    """Cluster status snapshot (the observability analogue of the
    reference's worker-status heartbeats — SURVEY.md §5)."""
    import jax

    mesh = get_mesh()
    devs = jax.devices()
    # memory_stats aggregated across ALL local devices — the reading
    # from device 0 alone hid the hottest chip's high-water on
    # multi-chip hosts. Per key: max (the chip that OOMs first) + sum.
    mem: dict = {}
    try:
        for d in jax.local_devices():
            stats = d.memory_stats() or {}
            for key, v in stats.items():
                try:
                    v = float(v)
                except (TypeError, ValueError):
                    continue
                cur = mem.get(key)
                if cur is None:
                    mem[key] = {"max": v, "sum": v}
                else:
                    cur["max"] = max(cur["max"], v)
                    cur["sum"] += v
    except Exception:
        pass
    return {
        "platform": devs[0].platform if devs else "none",
        "num_devices": len(devs),
        "num_local_devices": len(jax.local_devices()),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "mesh": {k: int(v) for k, v in mesh.shape.items()},
        "memory_stats": mem,
    }
