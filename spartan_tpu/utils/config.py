"""Typed global FLAGS registry.

Capability parity with the reference's flag/config system (SURVEY.md §2.1:
``[U] spartan/config.py`` — global ``FLAGS``, typed flags, per-subsystem
registration, per-optimizer-pass toggles). Re-designed for the TPU build:
no cluster-topology flags (there is no master/worker runtime); instead the
flags gate optimizer passes, mesh construction and profiling, which is what
the benchmark ablations need (SURVEY.md §5 "Config / flag system").
"""

from __future__ import annotations

import argparse
import os
import threading
from typing import Any, Callable, Dict, Iterator, List, Optional


# Global flag-mutation counter: bumped on every value change (set /
# parse / reset) so hot paths can memoize flag-derived keys (e.g.
# expr/base._opt_flags_key) and invalidate on ANY flag write instead
# of re-reading the registry per call. Monotonic; reads are unlocked
# (a stale read just recomputes once).
_mutations = 0


def mutation_count() -> int:
    return _mutations


def _bump() -> None:
    global _mutations
    _mutations += 1


class Flag:
    """A single typed flag with a default and an env-var override."""

    def __init__(self, name: str, default: Any, help: str = "",
                 parser: Callable[[str], Any] = str):
        self.name = name
        self.default = default
        self.help = help
        self.parser = parser
        self._value = default
        env = os.environ.get("SPARTAN_TPU_" + name.upper())
        if env is not None:
            self._value = parser(env)
        # reset() restores the value as configured at definition time
        # (env override included), not the compiled-in default.
        self._initial = self._value

    @property
    def value(self) -> Any:
        return self._value

    @value.setter
    def value(self, v: Any) -> None:
        self._value = v
        _bump()

    def parse(self, text: str) -> None:
        self._value = self.parser(text)
        _bump()

    def reset(self) -> None:
        self._value = self._initial
        _bump()


def _parse_bool(text: str) -> bool:
    return text.lower() in ("1", "true", "yes", "on")


def _parse_int_list(text: str) -> List[int]:
    return [int(x) for x in text.split(",") if x]


class FlagRegistry:
    """Global registry; modules register flags at import time.

    Access as attributes: ``FLAGS.opt_map_fusion``.
    """

    def __init__(self) -> None:
        object.__setattr__(self, "_flags", {})
        object.__setattr__(self, "_lock", threading.Lock())

    def define(self, name: str, default: Any, help: str = "",
               parser: Optional[Callable[[str], Any]] = None) -> Flag:
        with self._lock:
            if name in self._flags:
                return self._flags[name]
            if parser is None:
                if isinstance(default, bool):
                    parser = _parse_bool
                elif isinstance(default, int):
                    parser = int
                elif isinstance(default, float):
                    parser = float
                else:
                    parser = str
            flag = Flag(name, default, help, parser)
            self._flags[name] = flag
            return flag

    def define_bool(self, name: str, default: bool, help: str = "") -> Flag:
        return self.define(name, default, help, _parse_bool)

    def define_int(self, name: str, default: int, help: str = "") -> Flag:
        return self.define(name, default, help, int)

    def define_float(self, name: str, default: float, help: str = "") -> Flag:
        return self.define(name, default, help, float)

    def define_str(self, name: str, default: str, help: str = "") -> Flag:
        return self.define(name, default, help, str)

    def define_int_list(self, name: str, default: List[int],
                        help: str = "") -> Flag:
        return self.define(name, default, help, _parse_int_list)

    def __getattr__(self, name: str) -> Any:
        flags: Dict[str, Flag] = object.__getattribute__(self, "_flags")
        if name in flags:
            return flags[name].value
        raise AttributeError(f"undefined flag: {name}")

    def __setattr__(self, name: str, value: Any) -> None:
        flags: Dict[str, Flag] = object.__getattribute__(self, "_flags")
        if name not in flags:
            raise AttributeError(
                f"undefined flag: {name}; call FLAGS.define() first")
        flags[name].value = value

    def __contains__(self, name: str) -> bool:
        return name in self._flags

    def __iter__(self) -> Iterator[Flag]:
        return iter(self._flags.values())

    def parse_args(self, argv: Optional[List[str]] = None) -> List[str]:
        """Parse ``--flag=value`` / ``--flag value`` CLI args; returns leftovers."""
        parser = argparse.ArgumentParser(add_help=False)
        for flag in self._flags.values():
            parser.add_argument("--" + flag.name, type=str, default=None,
                                help=flag.help)
        ns, rest = parser.parse_known_args(argv)
        for flag in self._flags.values():
            text = getattr(ns, flag.name, None)
            if text is not None:
                flag.parse(text)
        return rest

    def reset_all(self) -> None:
        for flag in self._flags.values():
            flag.reset()

    def snapshot(self) -> Dict[str, Any]:
        return {f.name: f.value for f in self._flags.values()}

    def snapshot_nondefault(self) -> Dict[str, Any]:
        """Flags whose value differs from the compiled-in default —
        the compact attribution record every committed benchmark
        carries (a BENCH_r05 TPU regression must be attributable to
        flag state vs compile-cache growth without rerunning)."""
        return {f.name: f.value for f in self._flags.values()
                if f.value != f.default}


FLAGS = FlagRegistry()

# Core flags, registered up front so every subsystem can rely on them.
FLAGS.define_bool("opt_map_fusion", True,
                  "Fuse chained elementwise map exprs into one kernel.")
FLAGS.define_bool("opt_reduce_fusion", True,
                  "Fuse a map producer into a consuming reduce.")
FLAGS.define_bool("opt_collapse_cached", True,
                  "Collapse already-evaluated sub-DAGs into leaves.")
FLAGS.define_bool("opt_auto_tiling", True,
                  "Smart-tiling pass: pick shardings via the cost model.")
FLAGS.define_bool(
    "plan_cache", True,
    "Cache the complete evaluation plan (leaf order, out tilings, "
    "compiled executable) keyed on the RAW DAG's structural signature, "
    "so steady-state evaluate() skips the optimizer stack and "
    "re-signing entirely (one traversal + dispatch).")
FLAGS.define_float(
    "tiling_compute_weight", 0.0,
    "Bytes-priced compute weight for NON-contraction nodes in the "
    "smart-tiling cost model (0 = built-in default).")
FLAGS.define_float(
    "tiling_flop_weight", 0.0,
    "Bytes-equivalent cost of one contraction FLOP in the smart-tiling "
    "cost model (0 = per-platform default; calibrate with "
    "tiling_cost.calibrate_flop_weight).")
FLAGS.define_float(
    "tiling_operand_move_weight", 0.0,
    "Weight on GEMM operand-reshard bytes vs output-psum bytes in the "
    "smart-tiling cost model (0 = built-in calibrated default).")
FLAGS.define_float(
    "tiling_memory_weight", 0.0,
    "Soft memory term in the smart-tiling cost model: each candidate "
    "tiling's cost gains weight x its per-chip OUTPUT bytes, so plans "
    "near the HBM budget prefer finer (more parallel) tilings before "
    "the memory governor has to force a full degradation rung. 0 = "
    "off (pure speed). Part of the plan/compile cache keys. See "
    "docs/MEMORY.md.")
FLAGS.define_bool("opt_fold_slices", True,
                  "Fold slice-of-slice and slice-of-map expressions.")
FLAGS.define_int("log_level", 2, "0=debug 1=info 2=warn 3=error")
# The legacy FLAGS.profile whole-dispatch jax.profiler wrap is gone:
# profiling is one entry point now — st.profile(expr) for one-shot
# attribution and FLAGS.profile_sample_every (obs/profile.py) for
# sampled continuous profiling in production; ad-hoc captures go
# through utils/profiling.profile_trace (obs.trace.device_profile).
# The observability layer's own switches (spartan_tpu/obs/) are defined
# where they are consumed and documented here for discoverability:
#   trace                (obs/trace.py, default True)  — record host spans
#       (evaluate/sign/optimize/per-pass/tiling/compile/dispatch/fetch)
#       into the in-memory ring for st.trace_export; <=5% overhead on a
#       steady-state evaluate (benchmarks/obs_overhead.py gate).
#   trace_ring           (obs/trace.py, default 4096)  — max spans kept;
#       older spans drop when the ring wraps.
#   metrics              (obs/metrics.py, default True) — feed the typed
#       counter/gauge/histogram registry behind st.metrics().
#   metrics_hist_window  (obs/metrics.py, default 2048) — samples per
#       histogram for the p50/p95 estimates.
#   audit_numerics       (obs/numerics.py, default False) — compile
#       device-side health words + host callbacks into every node's
#       lowering (st.audit first-bad-node attribution); part of the
#       plan/compile cache keys; zero callbacks compiled when off
#       (benchmarks/numerics_overhead.py <=1% off-path gate).
#   dispatch_timeout_s   (obs/numerics.py, default 0)  — dispatch
#       watchdog: a run exceeding this dumps the in-flight span tree +
#       plan report + last health word to crash_dump_path.
#   crash_dump_path      (obs/numerics.py, default "") — crash-report
#       destination (empty = spartan_tpu_crash_<pid>.json in tmp).
#   cost_ledger          (obs/ledger.py, default True) — record
#       predicted-vs-measured cost per plan (st.ledger); disabled it
#       costs one flag read per dispatch (calibration_overhead gate).
#   cost_ledger_max / calibration_drift_tol (obs/ledger.py, defaults
#       256 / log 2) — ledger entry bound; drift tolerance on
#       |log(pred/actual)| per model before the drift counter bumps.
#   cost_calibration     (obs/ledger.py, default False) — multiply the
#       active profile's per-op-class factors into the tiling DP;
#       cost_calibration_fingerprint (set by st.load_profile) keys
#       calibrated plans apart in the plan/compile caches.
#   flightrec / flightrec_ring (obs/flight.py, defaults True / 4096)
#       — per-request serve-path flight recorder (st.flightrec):
#       submit -> queue -> coalesce -> dispatch -> resolve -> fetch
#       events, ring-bounded, no new locks on the hot paths.
#   profile_sample_every (obs/profile.py, default 0) — sampled
#       continuous device-time profiling: every Nth warm dispatch of a
#       plan is attributed per expr node and folded into the ledger's
#       device columns / plan report / flight recorder; 0 = off (one
#       flag read per dispatch; benchmarks/profile_overhead.py gate).
#   profile_tier (obs/profile.py, default "auto") — attribution tier:
#       auto (XPlane capture-parse, replay fallback) | xplane | replay.
#   profile_max_nodes (obs/profile.py, default 128) — replay-tier
#       node budget per plan.
#   serve_slo_classes / serve_slo_tenants / serve_slo_window
#       (obs/slo.py, defaults "" / "" / 256) — per-tenant latency SLO
#       classes ('name=target_s@objective[:queue_share]'), the tenant
#       -> class map, and the per-class violation window behind the
#       slo_burn_rate gauges + serve SLO-share admission
#       (docs/SERVING.md).
#   monitor / monitor_interval_s / monitor_window (obs/monitor.py,
#       defaults False / 1.0 / 512) — the continuous sampler thread,
#       its cadence, and the bounded time-series store
#       (benchmarks/monitor_overhead.py <=1% off-path gate).
#   monitor_autotune / monitor_drift_patience / monitor_swap_margin /
#       monitor_cooldown_s (obs/monitor.py, defaults False / 3 / 0.05
#       / 30.0) — the closed-loop re-calibration daemon: sustained-
#       drift patience, the modeled-win hysteresis a refitted profile
#       must clear to hot-swap, and the post-attempt cooldown
#       (docs/OBSERVABILITY.md).
#   monitor_burn_threshold / monitor_fallback_rate (obs/monitor.py,
#       defaults 1.0 / 5.0) — detector thresholds for SLO burn and
#       fallback-counter spikes.
#   monitor_fleet_dir    (obs/monitor.py, default "") — rank-snapshot
#       directory behind st.fleet_status() (atomic per-rank files,
#       rank-0 merge).
#   skew_warn_ratio      (obs/skew.py, default 1.5) — shard-imbalance
#       ratio (hottest shard / mesh mean, per node) above which
#       st.skew prints the advisory re-tiling suggestion and the
#       monitor's sustained-imbalance detector counts a breach; the
#       skew observatory itself rides profile_sample_every
#       (benchmarks/skew_overhead.py <=1% off-path gate).
#   serve_model_pricing  (serve/engine.py, default True) — price
#       deadline shedding + the ledger's service rows with the
#       calibrated cost model instead of the raw queue EMA (falls
#       back per request until the DP scale warms).
# The resilience layer's switches (spartan_tpu/resilience/) likewise
# live with their consumers (docs/RESILIENCE.md):
#   resilience           (engine.py, default True)  — master switch for
#       the in-evaluate policy engine (classify + retry + OOM degrade).
#   retry_max / retry_backoff_s / retry_backoff_max_s / retry_budget
#       (engine.py, defaults 3 / 0.05 / 2.0 / 32) — transient-retry
#       policy: attempts per episode, jittered exponential backoff,
#       lifetime budget per plan.
#   oom_degrade          (degrade.py, default True)  — walk the
#       finer-tiling -> fusion-off -> chunked ladder on OOM; each rung
#       keyed into the plan/compile caches.
#   degrade_chunks       (degrade.py, default 0)     — row blocks for
#       the chunked rung (0 = one per mesh device).
#   fault_inject / fault_seed (faults.py, defaults "" / 0) — seeded
#       chaos spec ('transient@2,oom@4x3,slow@1=0.5,io@0'), installed
#       by st.initialize() or st.chaos().
#   hbm_budget_bytes / memory_governor (memory.py, defaults 0 / True)
#       — predictive memory governor (docs/MEMORY.md): per-plan
#       peak-HBM model, ladder rung chosen BEFORE the first dispatch
#       when the prediction exceeds the budget, serve reservation
#       ledger. 0 = auto-detect from device memory_stats (None on
#       CPU: governor inert unless set explicitly).
#   loop_restore_max     (loop_ckpt.py, default 3)   — checkpoint
#       restores per checkpointed st.loop before the failure escapes.
#   integrity_check      (integrity.py, default False) — the SDC
#       sentinel: sampled per-shard checksum + redundant re-execution
#       on a rotated device assignment (rides profile_sample_every);
#       a disagreement discards the result (class 'sdc') and strikes
#       the implicated devices (benchmarks/integrity_overhead.py <=1%
#       off-path gate).
#   sdc_quarantine_strikes (integrity.py, default 3) — in-window
#       strikes that confirm a suspect device and trigger its planned
#       quarantine (rebuild_mesh exclusion + planner-priced rehome).
FLAGS.define_bool(
    "trace_annotations", True,
    "Wrap every expr node's kernel body in jax.named_scope during "
    "tracing, so device profiles (jax.profiler / Perfetto) attribute "
    "XLA ops back to expr nodes. Trace-time-only cost; turn off to "
    "shave cold-compile time.")
FLAGS.define_bool(
    "trace_loop_steps", False,
    "Emit one host callback per st.loop iteration (jax.debug.callback "
    "on the step index): the trace ring gains per-step 'loop_step' "
    "spans with REAL per-iteration dispatch times instead of one "
    "opaque fori_loop blob. Changes the lowered program (the flag is "
    "part of the loop's structural signature), so toggling recompiles; "
    "off by default — per-step callbacks serialize device->host.")
FLAGS.define_str(
    "profile_dir", "/tmp/spartan_tpu_profile",
    "Default destination for EXPLICIT device-profile captures "
    "(utils/profiling.profile_trace -> obs.trace.device_profile; view "
    "in TensorBoard/Perfetto). st.profile's XPlane tier and the "
    "profile_sample_every sampler capture into throwaway temp dirs — "
    "they parse and delete, never writing here.")
FLAGS.define_str(
    "compilation_cache_dir", "",
    "Enable JAX's persistent compilation cache at this path (empty = "
    "off): compiled XLA programs survive process restarts, amortizing "
    "long compiles like the Pallas-in-loop sparse iteration.")
FLAGS.define_int("default_mesh_1d", 0,
                 "If >0, force the default mesh to this many devices.")
FLAGS.define_str("placement", "auto",
                 "Tile placement strategy: auto|row|col|block|replicated")
FLAGS.define_bool("check_determinism", False,
                  "Debug mode: evaluate twice and assert bitwise equality.")
FLAGS.define_bool("use_cpp_extent", True,
                  "Use the C++ extent-algebra extension when built.")
_verify_passes_flag = FLAGS.define_bool(
    "verify_passes", False,
    "Bracket every optimizer pass with the invariant checker "
    "(analysis/passes.py): shape/dtype/leaf preservation + DAG "
    "well-formedness, failures naming the offending pass. Runs only "
    "on plan-cache misses. Also honored via SPARTAN_VERIFY_PASSES=1; "
    "the test suite enables it by default.")
FLAGS.define_bool(
    "verify_evaluate", False,
    "Run st.check (DAG verifier + plan-time lints: use-after-donate, "
    "double-donation, tiling consistency) on evaluate()'s plan-cache "
    "MISS path, before the optimizer. Hits stay dispatch-bound.")

# The documented switch is SPARTAN_VERIFY_PASSES (no package prefix);
# honor it with the same precedence as the prefixed env var, and make
# it survive FLAGS.reset_all() like any definition-time override.
_env = os.environ.get("SPARTAN_VERIFY_PASSES")
if _env is not None and "SPARTAN_TPU_VERIFY_PASSES" not in os.environ:
    _verify_passes_flag._value = _parse_bool(_env)
    _verify_passes_flag._initial = _verify_passes_flag._value
del _verify_passes_flag, _env
