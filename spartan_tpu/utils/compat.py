"""Version-compat shims for the JAX surface this framework uses.

``shard_map`` moved from ``jax.experimental.shard_map`` to a top-level
``jax.shard_map`` export, and its replication-check kwarg was renamed
``check_rep`` -> ``check_vma`` along the way. Every internal call site
imports it from here; the wrapper translates whichever spelling the
pinned jax does not understand (call sites pass ``mesh=``/``in_specs=``/
``out_specs=`` by keyword, which both generations accept).
"""

from __future__ import annotations

import inspect

try:
    from jax import shard_map as _shard_map  # jax with the top-level export
except ImportError:  # pragma: no cover - depends on the pinned jax
    from jax.experimental.shard_map import shard_map as _shard_map

try:
    _PARAMS = frozenset(inspect.signature(_shard_map).parameters)
except (TypeError, ValueError):  # pragma: no cover - exotic wrappers
    _PARAMS = frozenset()


def shard_map(f, **kwargs):
    if _PARAMS:
        if "check_vma" in kwargs and "check_vma" not in _PARAMS:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        elif "check_rep" in kwargs and "check_rep" not in _PARAMS:
            kwargs["check_vma"] = kwargs.pop("check_rep")
    return _shard_map(f, **kwargs)


def pcast(x, axes, to="varying"):
    """``lax.pcast`` across jax generations: falls back to ``pvary``
    (its predecessor), and on jax without either the varying-ness
    type system doesn't exist — the value itself is unchanged, so
    identity is the correct lowering."""
    from jax import lax

    fn = getattr(lax, "pcast", None)
    if fn is not None:
        return fn(x, axes, to=to)
    pvary = getattr(lax, "pvary", None)
    if pvary is not None and to == "varying":
        return pvary(x, axes)
    return x


__all__ = ["shard_map", "pcast"]
