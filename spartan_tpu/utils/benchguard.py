"""Benchmark regression guard (round-4 verdict Weak #2: only the
bench.py dot chain was machine-checked; PageRank / k-means / logreg /
SSVD could regress silently).

``benchmarks/thresholds.json`` commits per-platform floors (min for
rates, max for durations) at ~0.7x the round's measured value for
dispatch-amortized metrics; :func:`check` grades a metrics dict
against them. Consumed by ``benchmarks/run_all.py`` (full report) and
``bench.py``'s aux stage (the driver-parsed artifact), and unit-tested
without any heavy runs (tests/test_bench_guard.py)."""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

THRESHOLDS_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "benchmarks", "thresholds.json")


def load_thresholds(platform: str,
                    path: Optional[str] = None) -> Dict[str, Any]:
    """The committed thresholds for ``platform`` (e.g. 'cpu', 'tpu');
    empty when the file or platform entry is missing (unguarded
    platforms grade as all-pass with a note)."""
    p = path or THRESHOLDS_PATH
    try:
        with open(p) as f:
            table = json.load(f)
    except (OSError, ValueError):
        return {}
    entry = table.get(platform, {})
    return entry if isinstance(entry, dict) else {}


def check(metrics: Dict[str, float], platform: str,
          path: Optional[str] = None) -> Dict[str, Any]:
    """Grade ``metrics`` against the committed thresholds.

    Returns ``{"pass": bool, "checked": n, "results": {metric:
    {"value", "min"|"max", "pass"}}}``. Metrics without a committed
    threshold are reported unchecked rather than failed — a new metric
    must not break old rounds' artifacts."""
    thr = load_thresholds(platform, path)
    results: Dict[str, Any] = {}
    ok = True
    checked = 0
    for name, value in metrics.items():
        rule = thr.get(name)
        if not isinstance(rule, dict) or value is None:
            results[name] = {"value": value, "pass": None}
            continue
        entry: Dict[str, Any] = {"value": value}
        good = True
        if "min" in rule:
            entry["min"] = rule["min"]
            good = good and value >= rule["min"]
        if "max" in rule:
            entry["max"] = rule["max"]
            good = good and value <= rule["max"]
        entry["pass"] = good
        results[name] = entry
        checked += 1
        ok = ok and good
    return {"pass": ok, "checked": checked, "results": results}
