"""Tracing / profiling / cost analysis.

Parity with the reference's FLAGS-gated profiling (SURVEY.md §5: cProfile
dumps, timer spans, per-expr error attribution), re-based on the TPU
stack: ``jax.profiler`` traces (TensorBoard/Perfetto), a fetch-forced
timing harness (``block_until_ready`` returns early on tunneled
platforms), per-expr HLO cost from ``compiled.cost_analysis()``, and
device memory stats.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import numpy as np

from .config import FLAGS
from .log import log_info

# -- plan-cache counters and per-phase timers ----------------------------
#
# The evaluate() fast path (expr/base.py) is instrumented with named
# counters (plan_hits / plan_misses / compiles / donated_dispatches /
# evaluations) and per-phase wall-time accumulators:
#
#   sign      structural signing (raw-DAG plan signature + optimized-DAG
#             compile signature)
#   optimize  the optimizer pass stack (plus per-pass ``pass:<name>``)
#   compile   jit wrapper creation + the first call (trace + XLA compile)
#   dispatch  steady-state execution of an already-compiled program
#   build     Python-side assembly around dispatch: plan lookup, leaf
#             arg gathering, DistArray result wrapping
#
# Counters are process-global; tests and benchmarks bracket a region
# with reset_counters() and read counters() after.

_stats_lock = threading.Lock()
_counters: Dict[str, int] = {}
_phase_seconds: Dict[str, float] = {}


def count(name: str, n: int = 1) -> None:
    with _stats_lock:
        _counters[name] = _counters.get(name, 0) + n


def record_phase(name: str, seconds: float) -> None:
    with _stats_lock:
        _phase_seconds[name] = _phase_seconds.get(name, 0.0) + seconds


@contextlib.contextmanager
def phase(name: str) -> Iterator[None]:
    t0 = time.perf_counter()
    try:
        yield
    finally:
        record_phase(name, time.perf_counter() - t0)


def counters() -> Dict[str, int]:
    """Snapshot of the named counters (plan_hits, plan_misses, ...);
    absent counters read as 0 via .get()."""
    with _stats_lock:
        return dict(_counters)


def phase_seconds() -> Dict[str, float]:
    """Snapshot of accumulated per-phase wall time in seconds."""
    with _stats_lock:
        return dict(_phase_seconds)


def reset_counters() -> None:
    with _stats_lock:
        _counters.clear()
        _phase_seconds.clear()


def plan_cache_stats() -> Dict[str, Any]:
    """Hit/miss view of the evaluate() plan cache, with the hit rate
    the acceptance gate asserts (None before any lookup)."""
    c = counters()
    hits = c.get("plan_hits", 0)
    misses = c.get("plan_misses", 0)
    total = hits + misses
    return {
        "plan_hits": hits,
        "plan_misses": misses,
        "compiles": c.get("compiles", 0),
        "donated_dispatches": c.get("donated_dispatches", 0),
        "hit_rate": (hits / total) if total else None,
    }


@contextlib.contextmanager
def profile_trace(trace_dir: Optional[str] = None) -> Iterator[None]:
    """Capture a jax.profiler trace (view in TensorBoard/Perfetto)."""
    trace_dir = trace_dir or FLAGS.profile_dir
    with jax.profiler.trace(trace_dir):
        yield
    log_info("profiler trace written to %s", trace_dir)


def _compiled(expr):
    """Optimize + lower + compile an expr exactly the way ``evaluate``
    would, returning the jax Compiled object (for HLO inspection)."""
    from ..expr import base as expr_base
    from ..expr.optimize import optimize

    dag = optimize(expr)
    ctx = expr_base._SigCtx()
    ctx.of(dag)
    leaves = ctx.leaves
    leaf_ids = tuple(l._id for l in leaves)

    def traced(*args):
        env = dict(zip(leaf_ids, args))
        return dag.lower(env)

    lowered = jax.jit(traced).lower(
        *[expr_base._leaf_arg(l) for l in leaves])
    return lowered.compile()


def cost_analysis(expr) -> Dict[str, float]:
    """FLOPs / bytes-accessed estimate of an expr's compiled program
    (the per-expr HLO cost hook of SURVEY.md §5)."""
    analysis = _compiled(expr).cost_analysis()
    if isinstance(analysis, list):
        analysis = analysis[0] if analysis else {}
    return dict(analysis or {})


def hlo_text(expr) -> str:
    """Compiled (post-SPMD-partitioning) HLO of an expr — lets tests
    and benchmarks count the collectives a plan actually emits."""
    return _compiled(expr).as_text()


def benchmark(fn: Callable[[], Any], iters: int = 5,
              warmup: int = 1) -> Dict[str, float]:
    """Timing harness. ``fn`` must force its result (e.g. ``.glom()`` or
    a scalar fetch) — on the tunneled axon platform only a fetch
    guarantees the device work finished."""
    for _ in range(warmup):
        fn()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    arr = np.asarray(times)
    return {"best": float(arr.min()), "mean": float(arr.mean()),
            "std": float(arr.std()), "iters": iters}


def device_memory_stats() -> Dict[str, Any]:
    try:
        stats = jax.local_devices()[0].memory_stats()
        return dict(stats or {})
    except Exception:
        return {}


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named span visible in profiler traces."""
    with jax.profiler.TraceAnnotation(name):
        yield
