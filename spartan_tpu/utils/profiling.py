"""Tracing / profiling / cost analysis.

Parity with the reference's FLAGS-gated profiling (SURVEY.md §5: cProfile
dumps, timer spans, per-expr error attribution), re-based on the TPU
stack: ``jax.profiler`` traces (TensorBoard/Perfetto), a fetch-forced
timing harness (``block_until_ready`` returns early on tunneled
platforms), per-expr HLO cost from ``compiled.cost_analysis()``, and
device memory stats.

Profiling entry points (one funnel since the device-time attribution
PR): ``st.profile(expr)`` / ``FLAGS.profile_sample_every``
(``obs/profile.py``) are THE way to measure where device time goes —
the legacy ``FLAGS.profile`` whole-dispatch wrap is gone.
:func:`profile_trace` remains for explicit raw captures (a TensorBoard
session over a driver loop) and writes to ``FLAGS.profile_dir``; the
attribution tiers capture into throwaway temp dirs instead.

Since the observability PR this module is a thin facade over
``spartan_tpu/obs``: counters and per-phase timers live in the typed
metrics registry (``obs.metrics.REGISTRY``; snapshot via
``st.metrics()``), and :func:`phase` both feeds the per-phase
histograms AND emits a span into the trace ring buffer
(``st.trace_export``). The PR-1 API (``count`` / ``counters`` /
``record_phase`` / ``phase_seconds`` / ``reset_counters`` /
``plan_cache_stats``) is kept as shims so existing tests, benchmarks
and ``bench.py`` read identical shapes.

All wall-clock measurement in the package goes through this module or
``obs/`` (:func:`phase`, :func:`stopwatch`, ``obs.trace.span``) —
``tools/lint_repo.py`` forbids raw ``time.perf_counter()`` timing
anywhere else, so no timing escapes the trace.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import numpy as np

from ..obs.metrics import METRICS_FLAG as _METRICS_FLAG
from ..obs.metrics import REGISTRY
from ..obs.trace import SpanCtx as _SpanCtx
from ..obs.trace import annotate as _obs_annotate
from ..obs.trace import device_profile as _obs_device_profile
from ..obs.trace import span as _obs_span
from .config import FLAGS
from .log import log_info

# re-exported so call sites can say ``prof.span(...)`` /
# ``prof.device_profile(...)`` without importing obs directly
# (obs.trace owns the one span implementation AND the one sanctioned
# jax.profiler entry points — lint rule 9)
span = _obs_span
device_profile = _obs_device_profile

# -- plan-cache counters and per-phase timers ----------------------------
#
# The evaluate() fast path (expr/base.py) is instrumented with named
# counters (plan_hits / plan_misses / compiles / donated_dispatches /
# evaluations) and per-phase wall-time histograms:
#
#   sign      structural signing (raw-DAG plan signature + optimized-DAG
#             compile signature)
#   optimize  the optimizer pass stack (plus per-pass ``pass:<name>``
#             and the smart-tiling ``tiling`` sub-phase)
#   compile   jit wrapper creation + the first call (trace + XLA compile)
#   dispatch  steady-state execution of an already-compiled program
#   build     Python-side assembly around dispatch: plan lookup, leaf
#             arg gathering, DistArray result wrapping
#   fetch     device -> host result transfer (DistArray.glom)
#
# Counters are process-global; tests and benchmarks bracket a region
# with reset_counters() and read counters() after.

_PHASE_PREFIX = "phase:"


def count(name: str, n: int = 1) -> None:
    if _METRICS_FLAG._value:
        REGISTRY.counter(name).inc(n)


# phase-name -> Histogram handle; registry reset() zeroes instruments
# in place (it never replaces them), so cached handles stay valid
_phase_hists: Dict[str, Any] = {}


def record_phase(name: str, seconds: float) -> None:
    if _METRICS_FLAG._value:
        h = _phase_hists.get(name)
        if h is None:
            h = REGISTRY.histogram(_PHASE_PREFIX + name)
            _phase_hists[name] = h
        h.observe(seconds)


class _PhaseCtx(_SpanCtx):
    """The span context of :func:`phase`: a SpanCtx (one allocation,
    two clock reads) whose measured ``.seconds`` also feeds the
    per-phase histogram on exit — the hot dispatch path runs several
    of these per evaluate."""

    __slots__ = ()

    def __init__(self, name: str):
        super().__init__(name, None)

    def __exit__(self, et, ev, tb) -> bool:
        r = super().__exit__(et, ev, tb)
        record_phase(self.name, self.seconds)
        return r


def phase(name: str) -> _PhaseCtx:
    """Time a named phase: a span in the trace ring (marked
    ``error=True`` with the exception type if the block raises — the
    elapsed time is recorded either way, so failed evaluates stay
    visible) plus an observation in the per-phase histogram. Yields
    the span; ``.seconds`` holds the elapsed time after exit."""
    return _PhaseCtx(name)


class Stopwatch:
    """Result of :func:`stopwatch`: ``.elapsed`` seconds after exit."""

    __slots__ = ("elapsed",)

    def __init__(self) -> None:
        self.elapsed = 0.0


@contextlib.contextmanager
def stopwatch() -> Iterator[Stopwatch]:
    """Bare timing context for measurement harnesses (calibration,
    benchmark loops): no span, no histogram — just ``.elapsed``. The
    sanctioned alternative to raw ``time.perf_counter()`` pairs, which
    the repo lint forbids outside ``obs/`` and this module."""
    sw = Stopwatch()
    t0 = time.perf_counter()
    try:
        yield sw
    finally:
        sw.elapsed = time.perf_counter() - t0


def counters() -> Dict[str, int]:
    """Snapshot of the named counters (plan_hits, plan_misses, ...);
    absent counters read as 0 via .get()."""
    return REGISTRY.counter_values()


def phase_seconds() -> Dict[str, float]:
    """Snapshot of accumulated per-phase wall time in seconds (the
    histograms' exact sums; p50/p95/max via ``st.metrics()``)."""
    snap = REGISTRY.snapshot()["histograms"]
    return {name[len(_PHASE_PREFIX):]: h["sum"]
            for name, h in snap.items()
            if name.startswith(_PHASE_PREFIX)}


def reset_counters() -> None:
    """Zero every instrument in the registry (registrations survive,
    so snapshots keep stable keys across a reset)."""
    REGISTRY.reset()


def plan_cache_stats() -> Dict[str, Any]:
    """Hit/miss view of the evaluate() plan cache, with the hit rate
    the acceptance gate asserts (None before any lookup)."""
    c = counters()
    hits = c.get("plan_hits", 0)
    misses = c.get("plan_misses", 0)
    total = hits + misses
    return {
        "plan_hits": hits,
        "plan_misses": misses,
        "compiles": c.get("compiles", 0),
        "donated_dispatches": c.get("donated_dispatches", 0),
        "hit_rate": (hits / total) if total else None,
    }


@contextlib.contextmanager
def profile_trace(trace_dir: Optional[str] = None) -> Iterator[None]:
    """Capture a device profiler trace (view in TensorBoard/Perfetto)
    via the sanctioned ``obs.trace.device_profile`` entry point."""
    trace_dir = trace_dir or FLAGS.profile_dir
    with _obs_device_profile(trace_dir):
        yield
    log_info("profiler trace written to %s", trace_dir)


def _compiled(expr):
    """Optimize + lower + compile an expr exactly the way ``evaluate``
    would, returning the jax Compiled object (for HLO inspection)."""
    from ..expr import base as expr_base
    from ..expr.optimize import optimize

    dag = optimize(expr)
    ctx = expr_base._SigCtx()
    ctx.of(dag)
    leaves = ctx.leaves
    leaf_ids = tuple(l._id for l in leaves)

    def traced(*args):
        env = dict(zip(leaf_ids, args))
        return dag.lower(env)

    lowered = jax.jit(traced).lower(
        *[expr_base._leaf_arg(l) for l in leaves])
    return lowered.compile()


def cost_analysis(expr) -> Dict[str, float]:
    """FLOPs / bytes-accessed estimate of an expr's compiled program
    (the per-expr HLO cost hook of SURVEY.md §5). The read-out goes
    through ``obs.explain.compiled_cost_analysis`` — the one
    sanctioned ``cost_analysis()`` call site (lint rule 9)."""
    from ..obs.explain import compiled_cost_analysis

    return compiled_cost_analysis(_compiled(expr))


def hlo_text(expr) -> str:
    """Compiled (post-SPMD-partitioning) HLO of an expr — lets tests
    and benchmarks count the collectives a plan actually emits."""
    return _compiled(expr).as_text()


def benchmark(fn: Callable[[], Any], iters: int = 5,
              warmup: int = 1) -> Dict[str, float]:
    """Timing harness. ``fn`` must force its result (e.g. ``.glom()`` or
    a scalar fetch) — on the tunneled axon platform only a fetch
    guarantees the device work finished."""
    for _ in range(warmup):
        fn()
    times = []
    for _ in range(iters):
        with stopwatch() as sw:
            fn()
        times.append(sw.elapsed)
    arr = np.asarray(times)
    return {"best": float(arr.min()), "mean": float(arr.mean()),
            "std": float(arr.std()), "iters": iters}


def device_memory_stats() -> Dict[str, Any]:
    """Per-key {max, sum} memory stats across ALL local devices —
    delegates to the sanctioned obs/metrics aggregate (lint rule 8
    keeps raw ``memory_stats()`` reads single-sourced)."""
    from ..obs.metrics import device_memory_aggregate

    return device_memory_aggregate()


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named span visible in profiler traces (delegates to the
    sanctioned ``obs.trace.annotate``)."""
    with _obs_annotate(name):
        yield
