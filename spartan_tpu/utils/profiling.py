"""Tracing / profiling / cost analysis.

Parity with the reference's FLAGS-gated profiling (SURVEY.md §5: cProfile
dumps, timer spans, per-expr error attribution), re-based on the TPU
stack: ``jax.profiler`` traces (TensorBoard/Perfetto), a fetch-forced
timing harness (``block_until_ready`` returns early on tunneled
platforms), per-expr HLO cost from ``compiled.cost_analysis()``, and
device memory stats.
"""

from __future__ import annotations

import contextlib
import time
from typing import Any, Callable, Dict, Iterator, Optional

import jax
import numpy as np

from .config import FLAGS
from .log import log_info


@contextlib.contextmanager
def profile_trace(trace_dir: Optional[str] = None) -> Iterator[None]:
    """Capture a jax.profiler trace (view in TensorBoard/Perfetto)."""
    trace_dir = trace_dir or FLAGS.profile_dir
    with jax.profiler.trace(trace_dir):
        yield
    log_info("profiler trace written to %s", trace_dir)


def _compiled(expr):
    """Optimize + lower + compile an expr exactly the way ``evaluate``
    would, returning the jax Compiled object (for HLO inspection)."""
    from ..expr import base as expr_base
    from ..expr.optimize import optimize

    dag = optimize(expr)
    ctx = expr_base._SigCtx()
    ctx.of(dag)
    leaves = ctx.leaves
    leaf_ids = tuple(l._id for l in leaves)

    def traced(*args):
        env = dict(zip(leaf_ids, args))
        return dag.lower(env)

    lowered = jax.jit(traced).lower(
        *[expr_base._leaf_arg(l) for l in leaves])
    return lowered.compile()


def cost_analysis(expr) -> Dict[str, float]:
    """FLOPs / bytes-accessed estimate of an expr's compiled program
    (the per-expr HLO cost hook of SURVEY.md §5)."""
    analysis = _compiled(expr).cost_analysis()
    if isinstance(analysis, list):
        analysis = analysis[0] if analysis else {}
    return dict(analysis or {})


def hlo_text(expr) -> str:
    """Compiled (post-SPMD-partitioning) HLO of an expr — lets tests
    and benchmarks count the collectives a plan actually emits."""
    return _compiled(expr).as_text()


def benchmark(fn: Callable[[], Any], iters: int = 5,
              warmup: int = 1) -> Dict[str, float]:
    """Timing harness. ``fn`` must force its result (e.g. ``.glom()`` or
    a scalar fetch) — on the tunneled axon platform only a fetch
    guarantees the device work finished."""
    for _ in range(warmup):
        fn()
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    arr = np.asarray(times)
    return {"best": float(arr.min()), "mean": float(arr.mean()),
            "std": float(arr.std()), "iters": iters}


def device_memory_stats() -> Dict[str, Any]:
    try:
        stats = jax.local_devices()[0].memory_stats()
        return dict(stats or {})
    except Exception:
        return {}


@contextlib.contextmanager
def annotate(name: str) -> Iterator[None]:
    """Named span visible in profiler traces."""
    with jax.profiler.TraceAnnotation(name):
        yield
