"""Checkpoint / resume: per-shard save and load of DistArrays.

Parity with the reference's per-tile array IO (SURVEY.md §5 "Checkpoint /
resume": per-tile save/load of DistArrays, parallel from_file/write
paths). Each shard of the sharded ``jax.Array`` is written as one raw
blob (the Tile -> file mapping of the reference), concurrently through
the native C++ IO pool (:mod:`spartan_tpu.native`), plus a JSON manifest
with shape/dtype/tiling/mesh and per-shard extents. Loading re-assembles
and re-shards onto the *current* mesh, so checkpoints move between mesh
sizes (the elastic-restart story).
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional, Union

import jax
import numpy as np

from .. import native
from ..array import distarray as da
from ..array import tiling as tiling_mod
from ..array.distarray import DistArray
from ..array.extent import TileExtent
from ..parallel import mesh as mesh_mod

_MANIFEST = "manifest.json"


def _axes_to_json(axes):
    return [list(a) if isinstance(a, tuple) else a for a in axes]


def _axes_from_json(axes):
    return tuple(tuple(a) if isinstance(a, list) else a for a in axes)


def save(path: str, array: Union[DistArray, "np.ndarray"],
         nthreads: int = 8) -> None:
    """Write one DistArray (or Expr, forced first): shard blobs +
    manifest under ``path``/."""
    if not isinstance(array, DistArray):
        if hasattr(array, "evaluate"):  # an Expr: force it
            array = array.evaluate()
        else:
            array = da.from_numpy(np.asarray(array))
    os.makedirs(path, exist_ok=True)
    shards = []
    paths = []
    arrays = []
    seen = set()
    for shard in array.jax_array.addressable_shards:
        idx = tuple((s.start or 0,
                     s.stop if s.stop is not None else dim)
                    for s, dim in zip(shard.index, array.shape))
        if idx in seen:  # replicated shards: write once
            continue
        seen.add(idx)
        fname = "shard_" + "_".join(f"{a}-{b}" for a, b in idx) + ".bin"
        shards.append({"ul": [a for a, _ in idx],
                       "lr": [b for _, b in idx],
                       "file": fname})
        paths.append(os.path.join(path, fname))
        arrays.append(np.ascontiguousarray(shard.data))
    manifest = {
        "shape": list(array.shape),
        "dtype": str(array.dtype),
        "tiling": _axes_to_json(array.tiling.axes),
        "mesh": {k: int(v) for k, v in array.mesh.shape.items()},
        "shards": shards,
    }
    native.write_blobs(paths, arrays, nthreads)
    with open(os.path.join(path, _MANIFEST), "w") as f:
        json.dump(manifest, f)


def _load_host(path: str, nthreads: int = 8):
    """Read a checkpoint into a host array (no device transfer)."""
    with open(os.path.join(path, _MANIFEST)) as f:
        manifest = json.load(f)
    shape = tuple(manifest["shape"])
    dtype = np.dtype(manifest["dtype"])
    full = np.empty(shape, dtype)
    paths = []
    targets = []
    for rec in manifest["shards"]:
        ext = TileExtent(rec["ul"], rec["lr"], shape)
        buf = np.empty(ext.shape, dtype)
        paths.append(os.path.join(path, rec["file"]))
        targets.append((ext, buf))
    native.read_blobs(paths, [b for _, b in targets], nthreads)
    for ext, buf in targets:
        full[ext.to_slice()] = buf
    return full, manifest


def load(path: str, tiling: Optional[tiling_mod.Tiling] = None,
         nthreads: int = 8) -> DistArray:
    """Read a checkpoint and re-shard it onto the current mesh."""
    full, manifest = _load_host(path, nthreads)
    if tiling is None:
        saved = _axes_from_json(manifest["tiling"])
        t = tiling_mod.Tiling(saved)
        t = tiling_mod.sanitize(t, full.shape)
    else:
        t = tiling
    return da.from_numpy(full, tiling=t)


def save_tree(path: str, arrays: Dict[str, Union[DistArray, np.ndarray]],
              nthreads: int = 8) -> None:
    """Save a named collection (a model/driver state dict)."""
    os.makedirs(path, exist_ok=True)
    for name, arr in arrays.items():
        save(os.path.join(path, name), arr, nthreads)
    with open(os.path.join(path, "tree.json"), "w") as f:
        json.dump({"names": sorted(arrays)}, f)


def load_tree(path: str, nthreads: int = 8) -> Dict[str, DistArray]:
    with open(os.path.join(path, "tree.json")) as f:
        names = json.load(f)["names"]
    return {n: load(os.path.join(path, n), nthreads=nthreads)
            for n in names}


def save_sparse(path: str, sp, nthreads: int = 8) -> None:
    """Checkpoint a SparseDistArray: the three entry-sharded component
    arrays via the per-shard blob writer plus sparse metadata (shape,
    nnz) — the sparse-tile analogue of the reference's per-tile IO."""
    from ..array.sparse import _entry_tiling

    os.makedirs(path, exist_ok=True)
    t = _entry_tiling(sp.mesh)  # the components' actual layout
    for name, arr in (("data", sp.data), ("rows", sp.rows),
                      ("cols", sp.cols)):
        save(os.path.join(path, name),
             DistArray(arr, t, sp.mesh), nthreads)
    with open(os.path.join(path, "sparse.json"), "w") as f:
        json.dump({"shape": list(sp.shape), "nnz": int(sp.nnz)}, f)


def load_sparse(path: str, nthreads: int = 8):
    """Load a sparse checkpoint, re-sharding the entry axis onto the
    current mesh (elastic restart, same as dense load).

    The saved padding divided the SAVE-time mesh; rebuilding through
    ``from_coo`` on the real (unpadded) entries re-pads for the
    CURRENT mesh — wrapping the raw arrays would leave an entry count
    the new mesh cannot shard evenly."""
    from ..array.sparse import SparseDistArray

    with open(os.path.join(path, "sparse.json")) as f:
        meta = json.load(f)
    # host-only blob reads: from_coo does the single device_put
    parts = {name: _load_host(os.path.join(path, name), nthreads)[0]
             for name in ("data", "rows", "cols")}
    nnz = int(meta["nnz"])
    return SparseDistArray.from_coo(parts["rows"][:nnz],
                                    parts["cols"][:nnz],
                                    parts["data"][:nnz],
                                    tuple(meta["shape"]))
