"""Checkpoint / resume: per-shard save and load of DistArrays.

Parity with the reference's per-tile array IO (SURVEY.md §5 "Checkpoint /
resume": per-tile save/load of DistArrays, parallel from_file/write
paths). Each shard of the sharded ``jax.Array`` is written as one raw
blob (the Tile -> file mapping of the reference), concurrently through
the native C++ IO pool (:mod:`spartan_tpu.native`), plus a JSON manifest
with shape/dtype/tiling/mesh and per-shard extents. Loading re-assembles
and re-shards onto the *current* mesh, so checkpoints move between mesh
sizes (the elastic-restart story).
"""

from __future__ import annotations

import json
import os
import shutil
import zlib
from typing import Dict, Optional, Union

import jax
import numpy as np

from .. import native
from ..array import distarray as da
from ..array import tiling as tiling_mod
from ..array.distarray import DistArray
from ..array.extent import TileExtent
from ..parallel import mesh as mesh_mod

_MANIFEST = "manifest.json"


def _fire_checkpoint_fault() -> None:
    """Chaos seam (resilience/faults.py): an installed plan's ``io``
    tokens raise OSError here, so checkpoint-failure recovery paths
    are exercisable in CI. One module-attribute read when off."""
    from ..resilience import faults as _faults

    if _faults._ACTIVE is not None:
        _faults.fire("checkpoint")


def _swap_into_place(tmp: str, path: str) -> None:
    """Atomically promote the fully-written ``tmp`` dir to ``path``:
    a reader (or a crash) can only ever observe the old complete
    checkpoint or the new complete one, never a partial write."""
    if os.path.isdir(path):
        old = path + f".old-{os.getpid()}"
        shutil.rmtree(old, ignore_errors=True)
        os.replace(path, old)
        os.replace(tmp, path)
        shutil.rmtree(old, ignore_errors=True)
    else:
        parent = os.path.dirname(os.path.abspath(path))
        os.makedirs(parent, exist_ok=True)
        os.replace(tmp, path)


def _axes_to_json(axes):
    return [list(a) if isinstance(a, tuple) else a for a in axes]


def _axes_from_json(axes):
    return tuple(tuple(a) if isinstance(a, list) else a for a in axes)


def save(path: str, array: Union[DistArray, "np.ndarray"],
         nthreads: int = 8) -> None:
    """Write one DistArray (or Expr, forced first): shard blobs +
    manifest under ``path``/.

    Crash-safe (single-process): everything is written into a temp
    dir next to ``path`` and atomically ``os.replace``d into place,
    so a process killed mid-save can never leave a half-written
    checkpoint where a complete one (or nothing) is expected, and the
    manifest carries a per-shard CRC32 that :func:`load` verifies —
    a corrupt blob fails loudly, naming the shard file.

    Multi-process aware (SURVEY.md §5 on multi-host): the manifest
    enumerates the GLOBAL shard grid; each process writes only the
    blobs whose owning device (the lowest-id device holding that
    extent, so replicas are written exactly once cluster-wide) is
    local, and only process 0 writes the manifest — the manifest is
    the commit marker there (processes write into ``path`` in place;
    CRCs cover only rank-0-local shards). ``path`` must be a
    filesystem every process reaches."""
    if not isinstance(array, DistArray):
        if hasattr(array, "evaluate"):  # an Expr: force it
            array = array.evaluate()
        else:
            array = da.from_numpy(np.asarray(array))
    _fire_checkpoint_fault()
    single = jax.process_count() == 1
    # single-process: stage in a temp dir and swap; multi-process:
    # in place (every process must target the SAME dir, and the
    # barrier+manifest ordering below is the commit protocol)
    dest = (os.path.abspath(path) + f".tmp-{os.getpid()}"
            if single else path)
    if single:
        shutil.rmtree(dest, ignore_errors=True)
    os.makedirs(dest, exist_ok=True)
    jarr = array.jax_array
    idx_map = jarr.sharding.devices_indices_map(tuple(array.shape))
    local = {s.device: s for s in jarr.addressable_shards}
    shards = []
    paths = []
    arrays = []
    seen = set()
    for dev in sorted(idx_map, key=lambda d: d.id):
        idx = tuple((s.start or 0,
                     s.stop if s.stop is not None else dim)
                    for s, dim in zip(idx_map[dev], array.shape))
        if idx in seen:  # replicated shards: owned by the first device
            continue
        seen.add(idx)
        fname = "shard_" + "_".join(f"{a}-{b}" for a, b in idx) + ".bin"
        rec = {"ul": [a for a, _ in idx],
               "lr": [b for _, b in idx],
               "file": fname}
        if dev in local:
            buf = np.ascontiguousarray(local[dev].data)
            paths.append(os.path.join(dest, fname))
            arrays.append(buf)
            rec["crc32"] = zlib.crc32(buf)
        shards.append(rec)
    native.write_blobs(paths, arrays, nthreads)
    if not single:
        # the manifest is the checkpoint's commit marker: it must not
        # land before every process's blobs have — barrier first
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("spartan_tpu_ckpt_save")
    if jax.process_index() == 0:
        manifest = {
            "shape": list(array.shape),
            "dtype": str(array.dtype),
            "tiling": _axes_to_json(array.tiling.axes),
            "mesh": {k: int(v) for k, v in array.mesh.shape.items()},
            "shards": shards,
        }
        with open(os.path.join(dest, _MANIFEST), "w") as f:
            json.dump(manifest, f)
    if single:
        _swap_into_place(dest, path)
    else:
        # no rank may report the save complete before the commit
        # marker exists — a premature teardown on rank 1's return
        # would otherwise race rank 0's manifest write
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices("spartan_tpu_ckpt_commit")


def _load_host(path: str, nthreads: int = 8):
    """Read a checkpoint into a host array (no device transfer)."""
    with open(os.path.join(path, _MANIFEST)) as f:
        manifest = json.load(f)
    shape = tuple(manifest["shape"])
    dtype = np.dtype(manifest["dtype"])
    full = np.empty(shape, dtype)
    paths = []
    targets = []
    for rec in manifest["shards"]:
        ext = TileExtent(rec["ul"], rec["lr"], shape)
        buf = np.empty(ext.shape, dtype)
        paths.append(os.path.join(path, rec["file"]))
        targets.append((ext, buf))
    native.read_blobs(paths, [b for _, b in targets], nthreads)
    for rec, (ext, buf) in zip(manifest["shards"], targets):
        want = rec.get("crc32")
        if want is not None:
            got = zlib.crc32(np.ascontiguousarray(buf))
            if got != want:
                raise ValueError(
                    f"checkpoint shard {rec['file']!r} under {path!r} "
                    f"failed CRC32 verification (manifest {want}, "
                    f"read {got}): the blob is corrupt or truncated")
        full[ext.to_slice()] = buf
    return full, manifest


def load(path: str, tiling: Optional[tiling_mod.Tiling] = None,
         nthreads: int = 8) -> DistArray:
    """Read a checkpoint and re-shard it onto the current mesh.

    Shards carrying a manifest CRC32 (every single-process save) are
    verified as read; a corrupt blob raises ``ValueError`` naming the
    shard file.

    Cross-mesh-shape restores (the checkpoint was written on a
    different grid — an elastic shrink, or a world-size change across
    restarts) are PLANNED migrations: the transition from the saved
    tiling on the saved grid to the chosen tiling on the current grid
    goes through ``parallel/redistribute.plan_transition``, and the
    schedule / modeled wire bytes / reason land on the returned
    array's ``_migration`` record (fed into ``elastic_*`` metrics by
    the loop driver, and into ``st.explain``'s migrations section)."""
    _fire_checkpoint_fault()
    full, manifest = _load_host(path, nthreads)
    saved_axes = _axes_from_json(manifest["tiling"])
    if tiling is None:
        t = tiling_mod.Tiling(saved_axes)
        t = tiling_mod.sanitize(t, full.shape)
    else:
        t = tiling
    arr = da.from_numpy(full, tiling=t)
    saved_mesh = {k: int(v)
                  for k, v in (manifest.get("mesh") or {}).items()}
    cur_mesh = {k: int(v) for k, v in arr.mesh.shape.items()}
    if saved_mesh and saved_mesh != cur_mesh:
        try:  # advisory: a migration record must never fail a load
            from ..parallel import redistribute as redist_mod

            dec = redist_mod.plan_transition(
                tiling_mod.Tiling(saved_axes), arr.tiling,
                saved_mesh, cur_mesh, full.shape, full.dtype)
            arr._migration = {
                "route": "restore", "bytes": int(dec.bytes),
                "schedule": (dec.schedule.describe()
                             if dec.schedule is not None else None),
                "planned_route": dec.route, "reason": dec.reason,
                "shape": tuple(full.shape),
                "src_tiling": saved_axes, "dst_tiling": arr.tiling.axes,
                "src_mesh": saved_mesh, "dst_mesh": cur_mesh,
            }
        except Exception:  # noqa: BLE001
            pass
    return arr


def save_tree(path: str, arrays: Dict[str, Union[DistArray, np.ndarray]],
              nthreads: int = 8) -> None:
    """Save a named collection (a model/driver state dict).

    Multi-process: every rank writes its local shards (``save``
    barriers per array); only rank 0 writes ``tree.json`` — identical
    content, but N concurrent writers of one small file can tear."""
    os.makedirs(path, exist_ok=True)
    for name, arr in arrays.items():
        save(os.path.join(path, name), arr, nthreads)
    if jax.process_index() == 0:
        with open(os.path.join(path, "tree.json"), "w") as f:
            json.dump({"names": sorted(arrays)}, f)


def load_tree(path: str, nthreads: int = 8) -> Dict[str, DistArray]:
    with open(os.path.join(path, "tree.json")) as f:
        names = json.load(f)["names"]
    return {n: load(os.path.join(path, n), nthreads=nthreads)
            for n in names}


def save_sparse(path: str, sp, nthreads: int = 8) -> None:
    """Checkpoint a SparseDistArray: the three entry-sharded component
    arrays via the per-shard blob writer plus sparse metadata (shape,
    nnz) — the sparse-tile analogue of the reference's per-tile IO."""
    from ..array.sparse import _entry_tiling

    os.makedirs(path, exist_ok=True)
    t = _entry_tiling(sp.mesh)  # the components' actual layout
    for name, arr in (("data", sp.data), ("rows", sp.rows),
                      ("cols", sp.cols)):
        save(os.path.join(path, name),
             DistArray(arr, t, sp.mesh), nthreads)
    with open(os.path.join(path, "sparse.json"), "w") as f:
        json.dump({"shape": list(sp.shape), "nnz": int(sp.nnz)}, f)


def _read_range(dirpath: str, manifest: dict, start: int, stop: int,
                dtype: np.dtype, nthreads: int = 8) -> np.ndarray:
    """Elements ``[start, stop)`` of a saved 1-D array, reading only
    the overlapping byte ranges of its shard blobs (concurrently, up
    to ``nthreads``) — the host never holds more than one target shard
    (exposed as a module function so tests can assert the bounded
    residency)."""
    from concurrent.futures import ThreadPoolExecutor

    out = np.zeros(stop - start, dtype)
    isz = dtype.itemsize
    jobs = []
    for rec in manifest["shards"]:
        a, b = int(rec["ul"][0]), int(rec["lr"][0])
        lo, hi = max(a, start), min(b, stop)
        if lo < hi:
            jobs.append((rec["file"], a, lo, hi))

    def read_one(job):
        fname, a, lo, hi = job
        with open(os.path.join(dirpath, fname), "rb") as f:
            f.seek((lo - a) * isz)
            buf = f.read((hi - lo) * isz)
        out[lo - start:hi - start] = np.frombuffer(buf, dtype)

    if len(jobs) <= 1:
        for j in jobs:
            read_one(j)
    else:
        with ThreadPoolExecutor(max(1, min(nthreads,
                                           len(jobs)))) as pool:
            list(pool.map(read_one, jobs))
    return out


def load_sparse(path: str, nthreads: int = 8):
    """Load a sparse checkpoint DEVICE-RESIDENT: each entry shard of
    the three component arrays is read straight to its device
    (``jax.make_array_from_callback`` + byte-range blob reads, bounded
    host residency), then the canonical sort/dedup/repad for the
    CURRENT mesh runs on device (``from_coo_device`` — round-4 verdict
    Missing #4: the old path materialized full nnz on host). Elastic:
    the save-time padding rides along as out-of-range rows, which the
    device dedup rewrites to the current mesh's canonical padding."""
    from ..array.sparse import SparseDistArray, _entry_tiling

    with open(os.path.join(path, "sparse.json")) as f:
        meta = json.load(f)
    shape = tuple(meta["shape"])
    mesh = mesh_mod.get_mesh()
    n_dev = mesh_mod.device_count(mesh)
    t = _entry_tiling(mesh)

    def component(name, fill):
        dirpath = os.path.join(path, name)
        with open(os.path.join(dirpath, _MANIFEST)) as f:
            manifest = json.load(f)
        saved_n = int(manifest["shape"][0])
        dtype = np.dtype(manifest["dtype"])
        total = -(-saved_n // max(n_dev, 1)) * max(n_dev, 1)

        def cb(idx):
            sl = idx[0] if idx else slice(0, total)
            start = sl.start or 0
            stop = sl.stop if sl.stop is not None else total
            out = np.full(stop - start, fill, dtype)
            read_hi = min(stop, saved_n)
            if start < read_hi:
                out[:read_hi - start] = _read_range(
                    dirpath, manifest, start, read_hi, dtype, nthreads)
            return out

        return jax.make_array_from_callback(
            (total,), t.sharding(mesh), cb)

    # rows beyond the saved length read as out-of-range (padding);
    # from_coo_device's dedup rewrites all padding canonically
    rows = component("rows", fill=shape[0])
    cols = component("cols", fill=0)
    data = component("data", fill=0)
    return SparseDistArray.from_coo_device(rows, cols, data, shape,
                                           mesh=mesh)
