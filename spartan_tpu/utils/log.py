"""Leveled logging, assertion helpers and timers.

Parity with the reference's ``[U] spartan/util.py`` (SURVEY.md §2.1: leveled
logging, ``Assert`` helpers heavily used by tests, timers, ``divup``).
"""

from __future__ import annotations

import logging
from contextlib import contextmanager
from typing import Any, Iterator

import numpy as np

from .config import FLAGS

_logger = logging.getLogger("spartan_tpu")
if not _logger.handlers:
    _handler = logging.StreamHandler()
    _handler.setFormatter(
        logging.Formatter("%(asctime)s %(levelname).1s %(message)s"))
    _logger.addHandler(_handler)
    # Level filtering happens via FLAGS.log_level in _enabled(); the stdlib
    # logger must not filter on top of it.
    _logger.setLevel(logging.DEBUG)


def _enabled(level: int) -> bool:
    return level >= FLAGS.log_level


def log_debug(msg: str, *args: Any) -> None:
    if _enabled(0):
        _logger.debug(msg, *args)


def log_info(msg: str, *args: Any) -> None:
    if _enabled(1):
        _logger.info(msg, *args)


def log_warn(msg: str, *args: Any) -> None:
    if _enabled(2):
        _logger.warning(msg, *args)


def log_error(msg: str, *args: Any) -> None:
    _logger.error(msg, *args)


def divup(a: int, b: int) -> int:
    return (a + b - 1) // b


class Assert:
    """Assertion helpers mirroring the reference's test idioms."""

    @staticmethod
    def all_eq(a: Any, b: Any, tol: float = 0.0) -> None:
        a = np.asarray(a)
        b = np.asarray(b)
        if a.shape != b.shape:
            raise AssertionError(f"shape mismatch: {a.shape} vs {b.shape}")
        if tol > 0:
            np.testing.assert_allclose(a, b, rtol=tol, atol=tol)
        else:
            np.testing.assert_array_equal(a, b)

    @staticmethod
    def all_close(a: Any, b: Any, rtol: float = 1e-5, atol: float = 1e-6) -> None:
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=rtol, atol=atol)

    @staticmethod
    def eq(a: Any, b: Any) -> None:
        if not a == b:
            raise AssertionError(f"{a!r} != {b!r}")

    @staticmethod
    def true(cond: Any, msg: str = "") -> None:
        if not cond:
            raise AssertionError(msg or "expected truthy value")

    @staticmethod
    def isinstance_(obj: Any, cls: type) -> None:
        if not isinstance(obj, cls):
            raise AssertionError(f"{obj!r} is not a {cls.__name__}")


@contextmanager
def timer_ctx(name: str = "span") -> Iterator[None]:
    # timing rides the sanctioned stopwatch API (lazy import: profiling
    # imports this module for log_info) so the repo's raw-timing lint
    # holds package-wide
    from .profiling import stopwatch

    sw = None
    try:
        with stopwatch() as sw:
            yield
    finally:
        if sw is not None:
            log_info("%s: %.3f ms", name, sw.elapsed * 1e3)


class Timer:
    """Accumulating timer for benchmark harnesses."""

    def __init__(self) -> None:
        self.elapsed = 0.0
        self.count = 0

    @contextmanager
    def measure(self) -> Iterator[None]:
        from .profiling import stopwatch

        try:
            with stopwatch() as sw:
                yield
        finally:
            self.elapsed += sw.elapsed
            self.count += 1

    @property
    def mean(self) -> float:
        return self.elapsed / max(self.count, 1)
