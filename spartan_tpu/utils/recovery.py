"""Failure detection + lineage recovery (SURVEY.md §5).

The reference's master marked workers dead on missed heartbeats and
could at best recompute lost tiles from the expression DAG. In the
single-controller XLA runtime, DETECTION is the runtime error the
failed dispatch raises (device loss / preemption surfaces as an
exception from the blocking call — there is no silent partial state,
because arrays are immutable and a failed program commits nothing),
and RECOVERY is recompute-from-lineage: exprs are deterministic, so
dropping the cached result and re-forcing the DAG rebuilds it — the
reference's recompute-lost-tiles story without per-tile bookkeeping.

This module packages that loop; the fault-injection test
(tests/test_aux.py) exercises it end to end.
"""

from __future__ import annotations

import time
from typing import Any, Callable, Optional, Tuple

from .log import log_warn

# Exception types that indicate a (possibly transient) runtime/device
# failure rather than a user error. jax's device-side faults
# (XlaRuntimeError/JaxRuntimeError) subclass RuntimeError; OSError
# covers the IO layer during checkpoint reads. ValueError/TypeError
# etc. are USER errors and must not be retried.
_DEFAULT_RETRYABLE: Tuple[type, ...] = (RuntimeError, OSError)


def evaluate_with_recovery(expr: Any, retries: int = 2,
                           backoff_s: float = 0.0,
                           retryable: Optional[Tuple[type, ...]] = None,
                           on_failure: Optional[Callable] = None):
    """Force ``expr`` with detection + lineage recovery.

    On a retryable runtime failure: drop the cached partial result
    (``invalidate`` — lineage, i.e. the DAG itself, is the recovery
    log), optionally call ``on_failure(attempt, exc)`` (hook for
    re-initializing a backend or reloading a checkpoint), and
    re-force. Non-retryable exceptions propagate immediately.
    """
    if retryable is None:
        retryable = _DEFAULT_RETRYABLE
    for attempt in range(retries + 1):
        try:
            return expr.evaluate()
        except retryable as e:  # detection: the failed dispatch raises
            log_warn("evaluate failed (attempt %d/%d): %s",
                     attempt + 1, retries + 1, e)
            if attempt == retries:  # no further attempt: fail fast
                raise
            expr.invalidate()
            if on_failure is not None:
                on_failure(attempt, e)
            if backoff_s:
                time.sleep(backoff_s * (2 ** attempt))
