"""DEPRECATED façade over :mod:`spartan_tpu.resilience`.

This module used to be the whole recovery story: a blind
retry-on-``RuntimeError`` loop around ``evaluate()``. PR 5 replaced
it with the in-evaluate policy engine — ``evaluate()`` itself now
classifies every dispatch failure (transient → backoff retry under a
per-plan budget, OOM → the degradation ladder, deterministic → fail
fast with the plan report attached) and ``st.loop`` checkpoints and
resumes — so callers normally need NOTHING: a plain ``evaluate()``
already recovers. See docs/RESILIENCE.md.

:func:`evaluate_with_recovery` is kept as a thin deprecated shim for
driver-level lineage retry (invalidate + re-force across the whole
plan, e.g. after reloading a checkpoint in ``on_failure``), delegating
to :func:`spartan_tpu.resilience.engine.retry_evaluate`. One behavior
change, per the classifier: with the default ``retryable=None`` the
CLASSIFIER decides — deterministic user/compile errors are no longer
retried (the old default retried any ``RuntimeError``). Passing an
explicit ``retryable`` tuple keeps the legacy isinstance behavior.
"""

from __future__ import annotations

import warnings
from typing import Any, Callable, Optional, Tuple

# kept for back-compat importers; the classifier supersedes it
_DEFAULT_RETRYABLE: Tuple[type, ...] = (RuntimeError, OSError)


def evaluate_with_recovery(expr: Any, retries: int = 2,
                           backoff_s: float = 0.0,
                           retryable: Optional[Tuple[type, ...]] = None,
                           on_failure: Optional[Callable] = None):
    """Force ``expr`` with driver-level detection + lineage recovery.

    .. deprecated::
        ``evaluate()`` now runs the resilience policy engine itself
        (classifier + retry + OOM degradation, ``resilience_*``
        metrics, crash-dump forensics); use it directly, or
        ``resilience.engine.retry_evaluate`` for an explicit
        driver-level loop. This shim delegates there and will be
        removed.

    On a retryable failure: drop the cached partial result
    (``invalidate`` — lineage, i.e. the DAG itself, is the recovery
    log), optionally call ``on_failure(attempt, exc)`` (hook for
    re-initializing a backend or reloading a checkpoint), and
    re-force. With ``retryable=None`` the resilience classifier
    decides retryability; an explicit tuple keeps isinstance
    semantics. Non-retryable exceptions propagate immediately.
    """
    warnings.warn(
        "evaluate_with_recovery is deprecated: evaluate() now runs "
        "the resilience policy engine itself (classifier + retry + "
        "OOM degradation; docs/RESILIENCE.md). For an explicit "
        "driver-level loop use "
        "spartan_tpu.resilience.engine.retry_evaluate.",
        DeprecationWarning, stacklevel=2)
    from ..resilience.engine import retry_evaluate

    return retry_evaluate(expr, retries=retries, backoff_s=backoff_s,
                          retryable=retryable, on_failure=on_failure)
