"""Sample-sort partition exchange: the send-buffer pack kernel.

The sample sort's padded transport (ops/sort.py) builds a ``(p, m)``
send buffer where bucket run j — a contiguous slice
``xs[starts[j] : starts[j] + counts[j]]`` of the locally-sorted shard
— lands in row j at positions ``[0, counts[j])``. The seed lowered
that as an XLA scatter (``.at[dst, pos].set``), the slowest lowering
class on TPU. Because runs are contiguous, the scatter is exactly a
batch of dynamic slices; this kernel does it with one VMEM-resident
pass per destination:

* the sublane part of each dynamic start is a ``pl.ds`` row slice;
* the lane part is a one-hot permutation matmul on the MXU — exact
  for EVERY 32-bit pattern (NaN payloads included) because the value
  is split into two 16-bit halves, rolled as exact f32 integers, and
  reassembled (a float matmul on raw bits would launder NaNs).

Validity needs no kernel: ``t < counts[j]`` is an iota compare.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from . import registry

LANE = registry.LANE


def partition_pack(xs: jax.Array, starts: jax.Array,
                   counts: jax.Array, p: int,
                   sel: registry.Selection) -> jax.Array:
    """(p, m) send buffer from one shard's sorted stream ``xs`` (m,).

    ``starts``/``counts`` (p,) i32 name each destination's contiguous
    run. Slots past a run's count are zeroed (the validity channel —
    built outside — governs them). Any 4-byte dtype, bit-exact."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m = xs.shape[0]
    dt = xs.dtype
    mr = -(-m // LANE)                       # destination row blocks
    src_rows = -(-m // LANE) + mr + 1        # slice reach: start + m
    xs_u = jax.lax.bitcast_convert_type(
        jnp.zeros((src_rows * LANE,), dt).at[:m].set(xs), jnp.uint32)
    xs2 = xs_u.reshape(src_rows, LANE)

    def kernel(s_ref, c_ref, x_ref, out_ref):
        j = pl.program_id(0)
        s = s_ref[j]
        a = s // LANE
        b = s % LANE
        x = x_ref[pl.ds(a, mr + 1), :]
        hi = (x >> 16).astype(jnp.float32)
        lo = (x & 0xFFFF).astype(jnp.float32)
        # P[c, l] = 1 iff c == (b + l) % 128: Y = X @ P rolls lanes
        # left by b; both halves are < 2**16, exact in f32 at HIGHEST
        row = jax.lax.broadcasted_iota(jnp.int32, (LANE, LANE), 0)
        col = jax.lax.broadcasted_iota(jnp.int32, (LANE, LANE), 1)
        perm = ((col + b) % LANE == row).astype(jnp.float32)
        yhi = jax.lax.dot_general(
            hi, perm, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)
        ylo = jax.lax.dot_general(
            lo, perm, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)
        y = ((yhi.astype(jnp.uint32) << 16)
             | ylo.astype(jnp.uint32))
        lane = jax.lax.broadcasted_iota(jnp.int32, (mr, LANE), 1)
        # element (r, l) of row j is xs[s + r*128 + l]: lane l came
        # from source row a+r when b+l < 128, else a+r+1 (the carry)
        yv = jnp.where(b + lane < LANE, y[:mr, :], y[1:mr + 1, :])
        t = (jax.lax.broadcasted_iota(jnp.int32, (mr, LANE), 0) * LANE
             + lane)
        out_ref[:] = jnp.where(t < c_ref[j], yv, 0).astype(jnp.uint32)

    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(p,),
            in_specs=[
                pl.BlockSpec((src_rows, LANE), lambda j, s, c: (0, 0)),
            ],
            out_specs=pl.BlockSpec((mr, LANE), lambda j, s, c: (j, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((p * mr, LANE), jnp.uint32),
        interpret=sel.interpret,
    )(starts.astype(jnp.int32), counts.astype(jnp.int32), xs2)
    out = jax.lax.bitcast_convert_type(out.reshape(p, mr * LANE), dt)
    return out[:, :m]
