"""Per-shard top-k selection kernel (distributed_topk's local stage).

XLA's ``lax.top_k`` on TPU lowers through a full sort of the operand;
this kernel streams the shard through VMEM once and keeps a running
sorted candidate row instead. Per grid step it merges one block into
the running best-k by iterated extraction: take the max of
``block ∪ best`` (ties toward the LOWEST index — ``lax.top_k``'s
documented tie-break, which the sample-sort sentinel invariant in
ops/sort.py depends on), emit it into the next candidate slot, remove
exactly that element, repeat k times. Winners come out sorted
best-first by construction.

Keys are the caller's RANKING keys (ops/sort.py flips them for
smallest-k and masks ragged tails with the sentinel before calling);
the index payload is the LOCAL slot index, so the caller's gather /
global-offset bookkeeping is identical to the lax.top_k path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from . import registry

_IDX_INF = np.int32(2 ** 30)  # index sentinel for lifted padding slots


def shard_topk(key: jax.Array, k: int, sentinel,
               sel: registry.Selection) -> tuple:
    """(keys (k,), local indices (k,) i32) of one shard's top-k.

    ``key`` is 1-D; slots the caller already invalidated carry
    ``sentinel`` (they keep their real index — the tail-position
    invariant orders them behind every valid tie). Rows are lifted to
    ``(rows, 128)`` and padded per the derived schedule; lifted
    padding carries ``(sentinel, _IDX_INF)`` and can never displace a
    real candidate."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m = key.shape[0]
    dt = key.dtype
    sched = sel.schedule
    brows = sched.block[0]
    rows = sched.padded[0]
    grid = sched.grid[0]
    total = rows * 128
    kpad = 128

    keyp = jnp.full((total,), sentinel, dt).at[:m].set(key)
    idxp = jnp.where(jnp.arange(total, dtype=jnp.int32) < m,
                     jnp.arange(total, dtype=jnp.int32), _IDX_INF)
    key2 = keyp.reshape(rows, 128)
    idx2 = idxp.reshape(rows, 128)

    def kernel(k_ref, i_ref, outv_ref, outi_ref, work, widx, newv, newi):
        b = pl.program_id(0)

        @pl.when(b == 0)
        def _init():
            outv_ref[:] = jnp.full_like(outv_ref, sentinel)
            outi_ref[:] = jnp.full_like(outi_ref, _IDX_INF)

        work[:] = k_ref[:]
        widx[:] = i_ref[:]
        newv[:] = jnp.full_like(newv, sentinel)
        newi[:] = jnp.full_like(newi, _IDX_INF)
        lane = jax.lax.broadcasted_iota(jnp.int32, (1, kpad), 1)

        def extract(j, _):
            m1 = jnp.maximum(jnp.max(work[:]), jnp.max(outv_ref[:]))
            c1 = jnp.min(jnp.where(work[:] == m1, widx[:], _IDX_INF))
            c2 = jnp.min(jnp.where(outv_ref[:] == m1, outi_ref[:],
                                   _IDX_INF))
            mi = jnp.minimum(c1, c2)
            newv[:] = jnp.where(lane == j, m1, newv[:])
            newi[:] = jnp.where(lane == j, mi, newi[:])
            hit_w = (work[:] == m1) & (widx[:] == mi)
            work[:] = jnp.where(hit_w, sentinel, work[:])
            widx[:] = jnp.where(hit_w, _IDX_INF, widx[:])
            hit_b = (outv_ref[:] == m1) & (outi_ref[:] == mi)
            outv_ref[:] = jnp.where(hit_b, sentinel, outv_ref[:])
            outi_ref[:] = jnp.where(hit_b, _IDX_INF, outi_ref[:])
            return 0

        jax.lax.fori_loop(0, k, extract, 0)
        outv_ref[:] = newv[:]
        outi_ref[:] = newi[:]

    outv, outi = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((brows, 128), lambda b: (b, 0)),
            pl.BlockSpec((brows, 128), lambda b: (b, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, kpad), lambda b: (0, 0)),
            pl.BlockSpec((1, kpad), lambda b: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, kpad), dt),
            jax.ShapeDtypeStruct((1, kpad), jnp.int32),
        ],
        scratch_shapes=[
            pltpu.VMEM((brows, 128), dt),
            pltpu.VMEM((brows, 128), jnp.int32),
            pltpu.VMEM((1, kpad), dt),
            pltpu.VMEM((1, kpad), jnp.int32),
        ],
        interpret=sel.interpret,
    )(key2, idx2)
    # clamp the index payload so downstream gathers stay in bounds even
    # for sentinel candidates (they never win a slot)
    return outv[0, :k], jnp.minimum(outi[0, :k], m - 1)
