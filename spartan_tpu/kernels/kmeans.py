"""Fused k-means iteration kernel — now partitionable.

The seed's ``ops/kmeans.py`` Pallas kernel was explicitly single-TPU
("the pallas_call is not partitionable"). Migrated onto the kernel
layer: the SAME per-block kernel (Gram matrix vs VMEM-resident
centers on the MXU, lane-wise argmin, one-hot accumulate of sums and
counts) now runs per shard under ``shard_map`` over the row tiling
the planner commits for the point matrix, and the per-shard ``(k, d)``
sums / ``(k,)`` counts merge with one ``psum`` over the mesh row
axis. Row-validity masking is per shard (each shard masks global rows
``>= valid_rows``), so driver padding behaves identically to the
single-device kernel.

Constraints (selection falls back to the expr/XLA path otherwise):
f32 points, d a multiple of 128, k <= 128, per-shard rows a multiple
of the 1024-point block.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..array import tiling as tiling_mod
from ..parallel import mesh as mesh_mod
from ..parallel import redistribute as redist_mod
from . import registry

_BLOCK = 1024
_KPAD = 128


def supports(n: int, d: int, k: int, mesh=None) -> bool:
    """Can the Pallas path run this problem here? Multi-chip meshes
    are supported now — the kernel shard_maps over the row tiling."""
    mesh = mesh or mesh_mod.get_mesh()
    sel = registry.select("kmeans", (n, d), np.float32,
                          tiling_mod.row(2), mesh, k=k, block=_BLOCK)
    return sel.pallas


def _block_kernel(points: jax.Array, cpad: jax.Array, cnorm: jax.Array,
                  limit: jax.Array, interpret: bool
                  ) -> tuple:
    """One shard's fused pass: (kpad, d) sums and (1, kpad) counts.

    ``points`` (m, d) f32 with m % 1024 == 0; ``cpad`` (kpad, d)
    zero-padded centers whose padding rows carry +inf norms in
    ``cnorm`` so the argmin never selects them; local rows at index
    >= ``limit`` (driver padding) are masked out of the accumulation."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, d = points.shape
    kpad = _KPAD
    nsteps = m // _BLOCK
    lim2 = jnp.full((1, kpad), limit, jnp.int32)

    def kernel(p_ref, c_ref, cn_ref, lim_ref, sums_ref, cnt_ref,
               acc, cacc):
        b = pl.program_id(0)

        @pl.when(b == 0)
        def _init():
            acc[:] = jnp.zeros_like(acc)
            cacc[:] = jnp.zeros_like(cacc)

        p = p_ref[:]                                   # (B, d)
        gram = jax.lax.dot_general(
            p, c_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)       # (B, kpad)
        score = cn_ref[0, :][None, :] - 2.0 * gram
        assign = jnp.argmin(score, axis=1)             # (B,)
        oh = (assign[:, None] == jax.lax.broadcasted_iota(
            jnp.int32, (_BLOCK, kpad), 1)).astype(jnp.float32)
        row = (b * _BLOCK
               + jax.lax.broadcasted_iota(jnp.int32, (_BLOCK, kpad), 0))
        oh = oh * (row < lim_ref[0, 0]).astype(jnp.float32)
        acc[:] += jax.lax.dot_general(
            oh, p, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)       # (kpad, d)
        cacc[0, :] += jnp.sum(oh, axis=0)

        @pl.when(b == pl.num_programs(0) - 1)
        def _flush():
            sums_ref[:] = acc[:]
            cnt_ref[:] = cacc[:]

    return pl.pallas_call(
        kernel,
        grid=(nsteps,),
        in_specs=[
            pl.BlockSpec((_BLOCK, d), lambda b: (b, 0)),
            pl.BlockSpec((kpad, d), lambda b: (0, 0)),
            pl.BlockSpec((1, kpad), lambda b: (0, 0)),
            pl.BlockSpec((1, kpad), lambda b: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((kpad, d), lambda b: (0, 0)),
            pl.BlockSpec((1, kpad), lambda b: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((kpad, d), jnp.float32),
            jax.ShapeDtypeStruct((1, kpad), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((kpad, d), jnp.float32),
            pltpu.VMEM((1, kpad), jnp.float32),
        ],
        interpret=interpret,
    )(points, cpad, cnorm, lim2)


def assign_accumulate(points: jax.Array, centers: jax.Array, k: int,
                      valid_rows=None, mesh=None) -> tuple:
    """One fused pass over the whole (sharded) point matrix: (k, d)
    cluster sums and (k,) counts. Traceable — the k-means drivers run
    all iterations as one dispatch with this inside ``fori_loop``."""
    from ..utils.compat import shard_map

    mesh = mesh or mesh_mod.get_mesh()
    n, d = points.shape
    kpad = _KPAD
    interpret = registry.interpret_mode()
    cpad = jnp.zeros((kpad, d), jnp.float32).at[:k].set(centers)
    cnorm = jnp.full((kpad,), jnp.inf, jnp.float32).at[:k].set(
        jnp.sum(centers * centers, axis=1))[None, :]
    valid = n if valid_rows is None else int(valid_rows)
    axis = tiling_mod.AXIS_ROW
    p = int(mesh.shape.get(axis, 1))
    if p <= 1 or n % p or (n // p) % _BLOCK:
        # single-kernel path (the seed's semantics): whole point
        # matrix through one grid — direct callers with shard-
        # indivisible row counts keep working; the DRIVERS pad to
        # p * _BLOCK so they always take the shard_map path below
        sums, cnt = _block_kernel(points, cpad, cnorm,
                                  jnp.int32(valid), interpret)
        return sums[:k], cnt[0, :k]

    t = tiling_mod.row(2)
    points = redist_mod.constrain(points, t, mesh)
    ms = n // p

    def shard_fn(pts_l, cp, cn):
        me = jax.lax.axis_index(axis)
        limit = jnp.clip(valid - me.astype(jnp.int32) * ms, 0, ms)
        sums, cnt = _block_kernel(pts_l, cp, cn, limit, interpret)
        return jax.lax.psum(sums, axis), jax.lax.psum(cnt, axis)

    rep = tiling_mod.replicated(2)
    mapped = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(t.spec(), rep.spec(), rep.spec()),
        out_specs=(rep.spec(), rep.spec()), check_rep=False)
    sums, cnt = mapped(points, cpad, cnorm)
    return sums[:k], cnt[0, :k]


@functools.partial(jax.jit, static_argnames=("k", "valid_rows"))
def step(points: jax.Array, centers: jax.Array, k: int,
         valid_rows=None) -> jax.Array:
    """One k-means update: new centers from one fused pass."""
    sums, cnt = assign_accumulate(points, centers, k, valid_rows)
    return sums / jnp.maximum(cnt, 1.0)[:, None]


@functools.partial(jax.jit, static_argnames=("k", "valid_rows"))
def run(points: jax.Array, centers: jax.Array, k: int,
        iters: jax.Array, valid_rows=None) -> jax.Array:
    """All iterations in one dispatch (traced loop bound)."""
    def body(_, c):
        sums, cnt = assign_accumulate(points, c, k, valid_rows)
        return sums / jnp.maximum(cnt, 1.0)[:, None]

    return jax.lax.fori_loop(0, iters, body, centers)
