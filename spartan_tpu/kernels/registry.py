"""Kernel registry + selection policy for the partitionable Pallas layer.

The one place that decides, per op / shape / tiling / platform, whether
an irregular op lowers through a shard_map-wrapped Pallas TPU kernel or
through the portable GSPMD formulation (ROADMAP open item 1; TileLoom's
planning stance in PAPERS.md: the kernel's grid/block schedule is
*derived from the tiling the DP already chose*, never re-derived per
kernel).

Three pieces:

* :func:`derive` — the tiling->grid rule. The committed ``Tiling`` of
  the op's operand names the per-chip shard; the block shape is that
  shard quantized to TPU lane/sublane tiles (last dim to 128 lanes,
  leading rows to the dtype's sublane quantum), and the grid is the
  ceil-division of the shard by the block. One function, property-
  tested over the whole tiling vocabulary (tests/test_kernels.py).
* :func:`select` — the policy. ``FLAGS.native_kernels`` gates the
  layer (``auto``: Pallas on TPU only, GSPMD elsewhere — CPU lowering
  is provably unchanged; ``on``: Pallas everywhere, ``interpret=True``
  off-TPU so CPU CI exercises every kernel; ``off``: GSPMD always).
  Per-op constraint checks fall back to GSPMD with the reason
  recorded, and ops whose Pallas form *measured worse* than XLA keep
  the portable lowering in ``auto`` (the measured-win contract —
  ``redistribution.py``'s schedule-gating pattern).
* :func:`policy_key` — what the plan- and compile-cache keys carry
  (the audit/redistribution pattern): a Pallas-lowered executable must
  never alias the GSPMD executable of the same expr structure.

``select`` is a pure function of (op, shapes, tilings, flags,
platform), so ``st.explain`` recomputes the exact decision the
lowering seam will take (:func:`node_selection` / :func:`plan_entries`)
without tracing anything.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import numpy as np

from ..array import tiling as tiling_mod
from ..parallel import mesh as mesh_mod
from ..utils.config import FLAGS

FLAGS.define_str(
    "native_kernels", "auto",
    "Partitionable Pallas kernel layer (spartan_tpu/kernels): "
    "auto = Pallas on TPU only (CPU lowering unchanged), on = Pallas "
    "everywhere (interpret mode off-TPU: the CPU CI parity path), "
    "off = GSPMD lowerings always. Part of the plan/compile cache "
    "keys. See docs/KERNELS.md.")

LANE = 128
# min sublane tile by itemsize (f32/i32: 8, bf16: 16, i8/fp8: 32)
_SUBLANE = {8: 8, 4: 8, 2: 16, 1: 32}
# conservative per-kernel VMEM budget (16 MB parts; leave headroom for
# double buffering and the compiler's own scratch)
VMEM_BUDGET = 8 * 1024 * 1024


def _platform() -> str:
    import jax

    try:
        return jax.devices()[0].platform
    except Exception:  # noqa: BLE001 - no backend yet
        return "cpu"


def mode() -> str:
    """Resolved backend family: ``pallas`` or ``gspmd``."""
    v = FLAGS.native_kernels
    if v == "off":
        return "gspmd"
    if v == "on":
        return "pallas"
    return "pallas" if _platform() == "tpu" else "gspmd"


def interpret_mode() -> bool:
    """Pallas interpret mode: required anywhere but a real TPU."""
    return _platform() != "tpu"


def policy_key() -> Tuple:
    """The kernel-policy component of the plan/compile cache keys: a
    Pallas-lowered plan must never alias its GSPMD twin (and an
    interpret-mode executable must never alias a Mosaic one)."""
    return (mode(), interpret_mode())


def sublane(dtype: Any) -> int:
    return _SUBLANE.get(np.dtype(dtype).itemsize, 8)


@dataclasses.dataclass(frozen=True)
class Schedule:
    """A derived grid/block schedule over ONE shard of the operand.

    ``shard`` is the per-chip shape the committed Tiling induces
    (1-D shards are lifted to ``(rows, 128)`` lane-major); ``block``
    is the per-grid-step tile (lane/sublane quantized); ``padded`` is
    the shard shape after quantization padding — kernels mask the
    padding, they never double-count it; ``grid`` is the ceil-division
    of the padded shard's rows by the block rows."""

    grid: Tuple[int, ...]
    block: Tuple[int, ...]
    shard: Tuple[int, ...]
    padded: Tuple[int, ...]
    lifted: bool

    def describe(self) -> str:
        return (f"grid={self.grid} block={self.block} "
                f"shard={self.shard}")


def derive(shape: Tuple[int, ...], tiling: tiling_mod.Tiling,
           dtype: Any, mesh=None, rows_per_block: int = 1024
           ) -> Tuple[Optional[Schedule], str]:
    """Tiling->grid derivation (the TileLoom move): block shape =
    per-chip shard shape quantized to TPU lane/sublane tiles, grid =
    blocks covering the shard exactly. Returns ``(None, reason)`` for
    shards the rule cannot cover (indivisible tilings, empty dims)."""
    mesh = mesh or mesh_mod.get_mesh()
    shape = tuple(int(s) for s in shape)
    if not shape or any(s == 0 for s in shape):
        return None, "empty operand"
    tiles = tiling.tiles_per_dim(mesh)
    for d, t in zip(shape, tiles):
        if t > 1 and d % t:
            return None, (f"tiling {tiling.axes} does not divide shape "
                          f"{shape} over mesh {dict(mesh.shape)}")
    shard = tuple(d // t for d, t in zip(shape, tiles))
    lifted = False
    if len(shard) == 1:
        shard = (-(-shard[0] // LANE), LANE)
        lifted = True
    q = sublane(dtype)
    rows = shard[0]
    brows = min(int(rows_per_block), rows)
    brows = -(-brows // q) * q
    grid = -(-rows // brows)
    last = -(-shard[-1] // LANE) * LANE
    block = (brows,) + shard[1:-1] + (last,)
    padded = (grid * brows,) + shard[1:-1] + (last,)
    return Schedule((grid,), block, shard, padded, lifted), ""


@dataclasses.dataclass(frozen=True)
class Selection:
    """One selection decision: which backend lowers this op here."""

    op: str
    backend: str                     # "pallas" | "gspmd"
    reason: str
    schedule: Optional[Schedule] = None
    interpret: bool = False

    @property
    def pallas(self) -> bool:
        return self.backend == "pallas"


def _fallback(op: str, reason: str) -> Selection:
    return Selection(op, "gspmd", reason)


# ops whose Pallas form measured WORSE than the XLA lowering on the
# real chip keep the portable path in auto mode — a kernel only wins
# its slot by measurement (redistribution.py's gating contract).
# FLAGS.native_kernels=on (and explicit impl= overrides) still select
# them: that is the ablation / parity-test path.
_MEASURED_OFF: Dict[str, str] = {
    "segment_sum": (
        "measured worse than XLA scatter on v5e (1M x 128, k=64: "
        "pallas 71ms vs xla 33ms — ops/segment.py r0 note); kept as "
        "ablation, select with segment_impl=pallas or "
        "native_kernels=on"),
}


def _sel_bincount(shape, dtype, tiling, mesh, params) -> Selection:
    op = "bincount"
    length = int(params["length"])
    if len(shape) != 1:
        return _fallback(op, "only 1-D operands (ravel falls back)")
    if not np.issubdtype(np.dtype(dtype), np.integer):
        return _fallback(op, f"ids dtype {np.dtype(dtype)} not integral")
    if length > 4096:
        return _fallback(op, f"length {length} > 4096 (one-hot block "
                             "exceeds the VMEM budget)")
    p = _collective_size(tiling, mesh)
    n_pad = -(-shape[0] // max(p, 1)) * max(p, 1)
    sched, why = derive((n_pad,), _row_tiling(tiling, mesh, 1), dtype,
                        mesh, rows_per_block=2)
    if sched is None:
        return _fallback(op, why)
    k_total = -(-length // LANE) * LANE
    # one-hot block (block_e, k_total) f32 + ids table + counts row
    be = sched.block[0] * LANE
    need = 4 * (be * k_total + sched.padded[0] * LANE + k_total)
    if need > VMEM_BUDGET:
        return _fallback(op, f"one-hot working set {need}B > VMEM "
                             f"budget {VMEM_BUDGET}B")
    return Selection(op, "pallas", "selected", sched, interpret_mode())


def _sel_segment(shape, dtype, tiling, mesh, params) -> Selection:
    op = "segment_sum"
    k = int(params["num_segments"])
    if np.dtype(dtype) != np.float32:
        return _fallback(op, f"vals dtype {np.dtype(dtype)} != float32")
    if len(shape) not in (1, 2):
        return _fallback(op, "only 1-D/2-D value streams")
    d = shape[1] if len(shape) == 2 else 1
    p = _collective_size(tiling, mesh)
    n_pad = -(-shape[0] // max(p, 1)) * max(p, 1)
    sched, why = derive((n_pad, d) if len(shape) == 2 else (n_pad,),
                        _row_tiling(tiling, mesh, len(shape)), dtype,
                        mesh, rows_per_block=512)
    if sched is None:
        return _fallback(op, why)
    k_pad = -(-k // 8) * 8
    d_pad = -(-d // LANE) * LANE
    be = sched.block[0] if not sched.lifted else sched.block[0] * LANE
    need = 4 * (be * k_pad + k_pad * d_pad + be * d_pad)
    if need > VMEM_BUDGET:
        return _fallback(op, f"one-hot working set {need}B > VMEM "
                             f"budget {VMEM_BUDGET}B")
    return Selection(op, "pallas", "selected", sched, interpret_mode())


def _sel_topk(shape, dtype, tiling, mesh, params) -> Selection:
    op = "topk"
    k = int(params["k"])
    if len(shape) != 1:
        return _fallback(op, "only 1-D operands")
    if np.dtype(dtype).itemsize != 4:
        return _fallback(op, f"dtype {np.dtype(dtype)} is not 4-byte "
                             "(extraction keys are f32/i32 lanes)")
    if k > LANE:
        return _fallback(op, f"k {k} > 128 (candidate row exceeds one "
                             "lane tile; the sample argsort handles it)")
    p = _collective_size(tiling, mesh)
    m = -(-shape[0] // max(p, 1))
    sched, why = derive((m * max(p, 1),),
                        _row_tiling(tiling, mesh, 1), dtype, mesh,
                        rows_per_block=512)
    if sched is None:
        return _fallback(op, why)
    return Selection(op, "pallas", "selected", sched, interpret_mode())


def _sel_exchange(shape, dtype, tiling, mesh, params) -> Selection:
    op = "sort_exchange"
    m = int(params["m"])
    p = int(params["p"])
    if p < 2:
        return _fallback(op, "single shard: no exchange to pack")
    if np.dtype(dtype).itemsize != 4:
        return _fallback(op, f"dtype {np.dtype(dtype)} is not 4-byte "
                             "(the exact lane-roll splits 16-bit halves)")
    sched, why = derive((m * p,), _row_tiling(tiling, mesh, 1), dtype,
                        mesh, rows_per_block=512)
    if sched is None:
        return _fallback(op, why)
    mr = -(-m // LANE)
    # resident source rows + one destination row block (+1 carry row)
    need = 4 * LANE * (sched.padded[0] + 2 * (mr + 1))
    if need > VMEM_BUDGET:
        return _fallback(op, f"shard working set {need}B > VMEM "
                             f"budget {VMEM_BUDGET}B")
    return Selection(op, "pallas", "selected", sched, interpret_mode())


def _sel_stencil(shape, dtype, tiling, mesh, params) -> Selection:
    op = "stencil"
    if len(shape) != 4:
        return _fallback(op, "only NHWC operands")
    if np.dtype(dtype) != np.float32:
        return _fallback(op, f"dtype {np.dtype(dtype)} != float32")
    if tuple(params.get("stride", (1, 1))) != (1, 1):
        return _fallback(op, "only stride 1 (strided shards misalign "
                             "with the halo rule)")
    if params.get("padding", "SAME") != "SAME":
        return _fallback(op, "only SAME padding (halo ppermute zeros "
                             "match SAME's zero pad)")
    h_axis = tiling.axes[1]
    if not isinstance(h_axis, str) or int(mesh.shape.get(h_axis, 1)) < 2:
        return _fallback(op, "H axis not mesh-sharded: GSPMD needs no "
                             "halo exchange here")
    if any(a is not None for a in (tiling.axes[2], tiling.axes[3])):
        return _fallback(op, "W/C axes must be unsharded")
    p = int(mesh.shape[h_axis])
    n, h, w, c = shape
    if h % p:
        return _fallback(op, f"H {h} not divisible by {p} shards")
    kh, kw = params["kshape"]
    hs = h // p
    if hs < kh:
        return _fallback(op, f"shard H {hs} smaller than filter {kh}")
    # grid over H row-blocks of the shard (the halo axis); the kernel
    # adds the image index as a leading grid dim
    sched, why = derive((h, w, c), tiling.drop_axis(0), dtype, mesh,
                        rows_per_block=max(8, min(64, hs)))
    if sched is None:
        return _fallback(op, why)
    wp = w + kw - 1
    need = 4 * ((hs + kh - 1) * wp * c + kh * kw * c *
                int(params["out_channels"]))
    if need > VMEM_BUDGET:
        return _fallback(op, f"per-image working set {need}B > VMEM "
                             f"budget {VMEM_BUDGET}B")
    return Selection(op, "pallas", "selected", sched, interpret_mode())


def _sel_kmeans(shape, dtype, tiling, mesh, params) -> Selection:
    op = "kmeans"
    n, d = shape
    k = int(params["k"])
    if np.dtype(dtype) != np.float32:
        return _fallback(op, f"dtype {np.dtype(dtype)} != float32")
    if d % LANE:
        return _fallback(op, f"d {d} not a multiple of 128")
    if k > LANE:
        return _fallback(op, f"k {k} > 128 padded centers")
    p = _collective_size(tiling, mesh)
    if n % max(p, 1):
        return _fallback(op, f"n {n} not divisible by {p} shards")
    block = int(params.get("block", 1024))
    if (n // max(p, 1)) % block:
        return _fallback(op, f"shard rows {n // max(p, 1)} not a "
                             f"multiple of the {block} point block")
    sched, why = derive(shape, _row_tiling(tiling, mesh, 2), dtype,
                        mesh, rows_per_block=block)
    if sched is None:
        return _fallback(op, why)
    need = 4 * (block * d + 2 * LANE * d + 2 * LANE)
    if need > VMEM_BUDGET:
        return _fallback(op, f"point block working set {need}B > VMEM "
                             f"budget {VMEM_BUDGET}B")
    return Selection(op, "pallas", "selected", sched, interpret_mode())


_CHECKS = {
    "bincount": _sel_bincount,
    "segment_sum": _sel_segment,
    "topk": _sel_topk,
    "sort_exchange": _sel_exchange,
    "stencil": _sel_stencil,
    "kmeans": _sel_kmeans,
}


def _row_tiling(tiling: Optional[tiling_mod.Tiling], mesh,
                ndim: int) -> tiling_mod.Tiling:
    """The leading-axis row tiling every kernel shard_maps over (the
    collective axis); the operand's committed tiling when it already
    rides the mesh row axis, else the canonical row placement."""
    del tiling  # kernels always exchange over the row axis today
    del mesh
    return tiling_mod.row(ndim)


def _collective_size(tiling: Optional[tiling_mod.Tiling], mesh) -> int:
    return int(mesh.shape.get(tiling_mod.AXIS_ROW, 1))


def select(op: str, shape, dtype, tiling: Optional[tiling_mod.Tiling],
           mesh=None, force: bool = False, **params) -> Selection:
    """The per-op backend decision (pure: flags + platform + static
    shapes/tilings only — ``st.explain`` calls this with the same
    inputs the lowering does and prints the same answer).

    ``force=True`` skips the measured-off table (explicit ``impl=``
    overrides, ablation benchmarks) but never the constraint checks —
    a kernel that cannot cover the shard still falls back."""
    if op not in _CHECKS:
        raise KeyError(f"unknown kernel op {op!r}; known: "
                       f"{sorted(_CHECKS)}")
    mesh = mesh or mesh_mod.get_mesh()
    if not force:
        m = mode()
        if m == "gspmd":
            why = ("FLAGS.native_kernels=off" if FLAGS.native_kernels
                   == "off" else "platform is not TPU "
                                 "(native_kernels=auto)")
            return _fallback(op, why)
        if FLAGS.native_kernels == "auto" and op in _MEASURED_OFF:
            return _fallback(op, _MEASURED_OFF[op])
    shape = tuple(int(s) for s in shape)
    return _CHECKS[op](shape, np.dtype(dtype), tiling, mesh, params)


# -- explain integration ------------------------------------------------


def node_selection(node: Any) -> Optional[Selection]:
    """The Selection an expr node's lowering will ask for — None when
    the node type never routes through the kernel layer. Matched by
    class name so this module stays import-light (no expr imports)."""
    name = type(node).__name__
    mesh = mesh_mod.get_mesh()
    try:
        if name == "TopKExpr":
            return select("topk", node.x.shape, node.x.dtype,
                          tiling_mod.row(1), mesh, k=node.k)
        if name == "BincountExpr":
            return select("bincount", node.x.shape, node.x.dtype,
                          node.x.out_tiling(), mesh, length=node.length)
        if name == "SampleSortExpr":
            from ..ops import sort as sort_ops

            moved = (node._moved_in_tiling() if node.x.ndim > 1
                     else node.x.out_tiling())
            axis = sort_ops.collective_axis(moved, mesh)
            p = int(mesh.shape.get(axis, 1))
            n = node.x.shape[-1] if node.x.ndim else 0
            m = -(-n // p) if p else n
            sel = select("sort_exchange", (n,), node.x.dtype, moved,
                         mesh, p=p, m=m)
            if sel.pallas and node.x.ndim == 1 \
                    and not interpret_mode():
                # 1-D sorts on the real chip ride the payload-only
                # ragged_all_to_all transport (ops/sort.py) — there is
                # no padded send buffer to pack
                return _fallback("sort_exchange",
                                 "ragged transport carries 1-D TPU "
                                 "sorts (no padded buffer to pack)")
            return sel
        if name == "StencilExpr":
            return select(
                "stencil", node.x.shape, node.x.dtype,
                node.x.out_tiling(), mesh,
                stride=node.stride, padding=node.padding,
                kshape=node.w.shape[:2], out_channels=node.w.shape[3])
    except Exception:  # noqa: BLE001 - advisory surface only
        return None
    return None


def plan_entries(dag: Any) -> list:
    """Kernel-selection entries for every kernel-eligible node of an
    optimized DAG — the ``kernels`` section of the plan report
    (obs/explain.py), mirroring the decisions lowering will make."""
    from ..expr.optimize import dag_nodes

    out = []
    for n in dag_nodes(dag):
        sel = node_selection(n)
        if sel is None:
            continue
        entry: Dict[str, Any] = {
            "node": f"{type(n).__name__}#{n._id}",
            "op": sel.op, "backend": sel.backend,
        }
        if sel.schedule is not None and sel.pallas:
            entry["grid"] = tuple(sel.schedule.grid)
            entry["block"] = tuple(sel.schedule.block)
        if not sel.pallas:
            entry["reason"] = sel.reason
        if sel.interpret and sel.pallas:
            entry["interpret"] = True
        out.append(entry)
    return out
