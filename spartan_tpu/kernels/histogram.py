"""Histogram / bincount kernel: per-shard one-hot count + psum reduce.

The histogram's bincount reduction is a scatter-add of ones — XLA's
generic scatter on TPU. Here each shard streams its id blocks through
VMEM, reduces the ``(block_e, k)`` one-hot over its entry axis (VPU)
into a resident ``(1, k)`` counts row, and the per-shard rows merge
with one ``psum`` over the mesh row axis. Matches ``jnp.bincount``:
negative ids clip to bucket 0, ids >= length are dropped.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..array import tiling as tiling_mod
from ..parallel import mesh as mesh_mod
from ..parallel import redistribute as redist_mod
from . import registry

_BLOCK_E = 512


def bincount_block(ids: jax.Array, length: int,
                   interpret: bool = False,
                   block_e: int = _BLOCK_E) -> jax.Array:
    """One shard's bincount: f32 counts of ``ids`` in [0, length)."""
    from jax.experimental import pallas as pl

    e = ids.shape[0]
    e_pad = -e % block_e
    if e_pad:
        # out-of-range sentinel: padded slots count nowhere
        ids = jnp.pad(ids, (0, e_pad), constant_values=length)
    # jnp.bincount parity: negatives land in bucket 0
    ids = jnp.maximum(ids.astype(jnp.int32), 0)
    n_blocks = ids.shape[0] // block_e
    k_total = -(-length // 128) * 128
    ids2d = ids.reshape(n_blocks, block_e)

    def kernel(ids_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        seg = jax.lax.broadcasted_iota(jnp.int32, (block_e, k_total), 1)
        onehot = (ids_ref[step, :][:, None] == seg).astype(jnp.float32)
        out_ref[:] += jnp.sum(onehot, axis=0)[None, :]

    out = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[pl.BlockSpec((n_blocks, block_e), lambda i: (0, 0))],
        out_specs=pl.BlockSpec((1, k_total), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, k_total), jnp.float32),
        interpret=interpret,
    )(ids2d)
    return out[0, :length]


def bincount_sharded(ids: jax.Array, length: int,
                     sel: registry.Selection, mesh=None) -> jax.Array:
    """Distributed bincount: row-shard the id stream, count per shard
    with :func:`bincount_block`, ``psum`` the count rows. Returns
    int32 (jnp.bincount parity; counts are exact in f32 to 2**24 and
    each shard holds far fewer entries than that)."""
    from ..utils.compat import shard_map

    mesh = mesh or mesh_mod.get_mesh()
    axis = tiling_mod.AXIS_ROW
    p = int(mesh.shape.get(axis, 1))
    interpret = sel.interpret
    if p <= 1:
        return bincount_block(ids, length,
                              interpret=interpret).astype(jnp.int32)
    e = ids.shape[0]
    e_pad = -e % p
    if e_pad:
        ids = jnp.pad(ids, (0, e_pad), constant_values=length)
    ids = ids.astype(jnp.int32)
    t = tiling_mod.row(1)
    ids = redist_mod.constrain(ids, t, mesh)

    def shard_fn(i):
        part = bincount_block(i, length, interpret=interpret)
        return jax.lax.psum(part, axis)

    mapped = shard_map(shard_fn, mesh=mesh, in_specs=(t.spec(),),
                       out_specs=tiling_mod.replicated(1).spec(),
                       check_rep=False)
    return mapped(ids).astype(jnp.int32)
