"""Partitionable Pallas kernel layer (docs/KERNELS.md).

shard_map-wrapped Pallas TPU kernels for the ops GSPMD lowers poorly —
segment reductions, histogram/bincount, distributed top-k, the sample
sort's partition exchange, halo stencils, the fused k-means pass —
with every kernel's grid/block schedule derived from the Tiling the
planner already committed (registry.derive), selected per
op/shape/platform by :func:`registry.select`, and keyed into the
plan/compile caches via :func:`registry.policy_key` so native and
fallback executables never alias.

Pallas imports live ONLY under this package (lint rule 12).
"""

from __future__ import annotations

from . import registry
from .registry import (Schedule, Selection, derive, interpret_mode,
                       mode, node_selection, plan_entries, policy_key,
                       select)

__all__ = [
    "registry", "Schedule", "Selection", "derive", "interpret_mode",
    "mode", "node_selection", "plan_entries", "policy_key", "select",
]
