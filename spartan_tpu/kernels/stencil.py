"""Halo-exchange stencil: manual ppermute halos + blocked conv kernel.

GSPMD partitions a spatially-sharded convolution with generic halo
collectives it re-derives per program. Here the exchange is explicit:
under ``shard_map`` over the H-sharded tiling, each shard ppermutes
its boundary rows to its neighbours (un-received edges come back zero
— exactly SAME padding's zeros), concatenates the halos, and runs a
VALID convolution over its own rows. The inner conv is a blocked
Pallas kernel — grid over (image, H row-block), each step contracting
the ``KH x KW`` shifted input slices against the filter taps on the
MXU — with a local ``lax.conv`` fallback for shapes the kernel's
constraints exclude (the two-level fallback contract, docs/KERNELS.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..array import tiling as tiling_mod
from ..parallel import mesh as mesh_mod
from ..parallel import redistribute as redist_mod
from . import registry


def _same_pad(k: int) -> tuple:
    """XLA SAME padding split for stride 1: total k-1, low half first."""
    lo = (k - 1) // 2
    return lo, k - 1 - lo


def conv_block(x: jax.Array, w: jax.Array, hb: int,
               interpret: bool) -> jax.Array:
    """VALID conv of ``x`` (N, Hp, Wp, C) against ``w`` (KH, KW, C, O)
    via shifted-slice MXU contractions, grid over (image, H block)."""
    from jax.experimental import pallas as pl

    n, hp, wp, c = x.shape
    kh, kw, _, o = w.shape
    ho = hp - kh + 1
    wo = wp - kw + 1
    nh = -(-ho // hb)
    # pad rows so the last block's input reach stays in bounds
    need = nh * hb + kh - 1
    if need > hp:
        x = jnp.pad(x, ((0, 0), (0, need - hp), (0, 0), (0, 0)))

    def kernel(x_ref, w_ref, out_ref):
        hbi = pl.program_id(1)
        acc = jnp.zeros((hb * wo, o), jnp.float32)
        for dh in range(kh):
            for dw in range(kw):
                patch = x_ref[0, pl.ds(hbi * hb + dh, hb),
                              dw:dw + wo, :]
                acc += jax.lax.dot_general(
                    patch.reshape(hb * wo, c), w_ref[dh, dw],
                    (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                    precision=jax.lax.Precision.HIGHEST)
        out_ref[0] = acc.reshape(hb, wo, o)

    out = pl.pallas_call(
        kernel,
        grid=(n, nh),
        in_specs=[
            pl.BlockSpec((1, x.shape[1], wp, c), lambda i, j: (i, 0, 0, 0)),
            pl.BlockSpec((kh, kw, c, o), lambda i, j: (0, 0, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, hb, wo, o), lambda i, j: (i, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, nh * hb, wo, o), jnp.float32),
        interpret=interpret,
    )(x, w)
    return out[:, :ho]


def halo_stencil(x: jax.Array, w: jax.Array, tiling,
                 sel: registry.Selection, mesh=None) -> jax.Array:
    """SAME-padded stride-1 NHWC conv with the H axis mesh-sharded:
    manual ppermute halo exchange feeding the blocked kernel."""
    from ..utils.compat import shard_map

    mesh = mesh or mesh_mod.get_mesh()
    axis = tiling.axes[1]
    p = int(mesh.shape[axis])
    kh, kw = int(w.shape[0]), int(w.shape[1])
    hlo, hhi = _same_pad(kh)
    wlo, whi = _same_pad(kw)
    hb = sel.schedule.block[0]
    interpret = sel.interpret
    x = redist_mod.constrain(x, tiling, mesh)

    def shard_fn(xl, wl):
        hs = xl.shape[1]
        parts = []
        if hlo:
            # my top halo = the previous shard's last hlo rows; shard 0
            # receives nothing -> zeros, which IS the SAME zero pad
            parts.append(jax.lax.ppermute(
                xl[:, hs - hlo:], axis,
                perm=[(i, i + 1) for i in range(p - 1)]))
        parts.append(xl)
        if hhi:
            parts.append(jax.lax.ppermute(
                xl[:, :hhi], axis,
                perm=[(i + 1, i) for i in range(p - 1)]))
        xpad = jnp.concatenate(parts, axis=1)
        xpad = jnp.pad(xpad, ((0, 0), (0, 0), (wlo, whi), (0, 0)))
        return conv_block(xpad, wl, hb, interpret)

    out_t = tiling.with_axis(2, None).with_axis(3, None)
    mapped = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(tiling.spec(), tiling_mod.replicated(4).spec()),
        out_specs=out_t.spec(), check_rep=False)
    return mapped(x, w.astype(jnp.float32))
