"""Segment-sum kernels: blocked one-hot accumulation on the MXU.

Owns every Pallas call the segment/scatter-add family uses (lint rule
12). Two kernels:

* :func:`segment_sum_block` — the per-shard blocked one-hot kernel
  (promoted from the seed's single-device ``ops/segment.py``
  ``_segment_sum_pallas``): the entry stream is tiled over a
  sequential grid, each tile builds its one-hot block in VMEM and
  accumulates ``block.T @ vals`` into the output block.
* :func:`windowed_segsum` — SegmentPlan's windowed sorted-segment
  kernel (moved verbatim from ops/segment.py; host-planned layout).

:func:`segment_sum_sharded` is the partitionable form: the operand is
row-sharded over the mesh row axis, every shard runs
:func:`segment_sum_block` on its local entries under ``shard_map``,
and the per-shard ``(k, d)`` partials merge with ``psum_scatter``
(k divisible by the shard count — each chip keeps its k/p output
rows) or a plain ``psum`` otherwise.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..array import tiling as tiling_mod
from ..parallel import mesh as mesh_mod
from ..parallel import redistribute as redist_mod
from . import registry


def segment_sum_block(vals: jax.Array, ids: jax.Array,
                      num_segments: int, block_e: int = 512,
                      interpret: bool = False) -> jax.Array:
    """Blocked one-hot accumulation over ONE shard's entry stream.

    Grid over entry blocks (sequential on TPU); the output block is
    revisited every step and accumulated in VMEM. ``num_segments`` and
    the feature dim are padded to lane/sublane multiples; ids outside
    ``[0, num_segments)`` are dropped (XLA segment_sum semantics)."""
    from jax.experimental import pallas as pl

    squeeze = vals.ndim == 1
    if squeeze:
        vals = vals[:, None]
    e, d = vals.shape
    k = num_segments
    # pad to TPU tiling: entries to block_e, segments/features to 128/8
    e_pad = -e % block_e
    if e_pad:
        vals = jnp.pad(vals, ((0, e_pad), (0, 0)))
        ids = jnp.pad(ids, (0, e_pad), constant_values=k)  # out of range
    k_pad = -k % 8
    d_pad = -d % 128
    vals = jnp.pad(vals, ((0, 0), (0, d_pad)))
    n_blocks = vals.shape[0] // block_e
    k_total = k + k_pad
    # ids as (n_blocks, block_e): 2-D blocks match the XLA layout Mosaic
    # expects (1-D s32 operands hit a T(1024)/T(512) tiling mismatch)
    ids2d = ids.astype(jnp.int32).reshape(n_blocks, block_e)

    def kernel(ids_ref, vals_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        seg = jax.lax.broadcasted_iota(jnp.int32, (block_e, k_total), 1)
        onehot = (ids_ref[step, :][:, None] == seg).astype(vals_ref.dtype)
        out_ref[:] += jnp.dot(onehot.T, vals_ref[:],
                              preferred_element_type=out_ref.dtype,
                              precision="highest")

    out = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[
            # whole ids table resident (Mosaic requires sublane-divisible
            # or full blocks); the kernel row-indexes it by step
            pl.BlockSpec((n_blocks, block_e), lambda i: (0, 0)),
            pl.BlockSpec((block_e, vals.shape[1]), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((k_total, vals.shape[1]), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((k_total, vals.shape[1]),
                                       vals.dtype),
        interpret=interpret,
    )(ids2d, vals)
    out = out[:k, :d]
    return out[:, 0] if squeeze else out


def segment_sum_sharded(vals: jax.Array, ids: jax.Array,
                        num_segments: int,
                        sel: registry.Selection,
                        mesh=None, block_e: int = 512) -> jax.Array:
    """The partitionable segment sum: row-shard the entry stream, run
    :func:`segment_sum_block` per shard, merge partials with
    ``psum_scatter`` (when the shard count divides ``num_segments``)
    or ``psum``. Output is replicated either way — the scatter merge
    finishes with an all-gather of the k/p slices, which together cost
    one all-reduce's bytes (the rs+ag decomposition)."""
    from ..utils.compat import shard_map

    mesh = mesh or mesh_mod.get_mesh()
    axis = tiling_mod.AXIS_ROW
    p = int(mesh.shape.get(axis, 1))
    interpret = sel.interpret
    if p <= 1:
        return segment_sum_block(vals, ids, num_segments, block_e,
                                 interpret=interpret)
    squeeze = vals.ndim == 1
    if squeeze:
        vals = vals[:, None]
    e, d = vals.shape
    e_pad = -e % p
    if e_pad:
        vals = jnp.pad(vals, ((0, e_pad), (0, 0)))
        ids = jnp.pad(ids, (0, e_pad), constant_values=num_segments)
    ids = ids.astype(jnp.int32)
    t_vals = tiling_mod.row(2)
    t_ids = tiling_mod.row(1)
    vals = redist_mod.constrain(vals, t_vals, mesh)
    ids = redist_mod.constrain(ids, t_ids, mesh)
    scatter = num_segments % p == 0

    def shard_fn(v, i):
        part = segment_sum_block(v, i, num_segments, block_e,
                                 interpret=interpret)
        if scatter:
            # psum-scatter merge: each chip reduces and keeps its own
            # k/p output rows, then the gather makes it whole — same
            # wire bytes as one all-reduce, half of psum+broadcast
            part = jax.lax.psum_scatter(part, axis, tiled=True)
            return jax.lax.all_gather(part, axis, tiled=True)
        return jax.lax.psum(part, axis)

    mapped = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(t_vals.spec(), t_ids.spec()),
        out_specs=tiling_mod.replicated(2).spec(),
        check_rep=False)
    out = mapped(vals, ids)
    return out[:, 0] if squeeze else out


def windowed_segsum(vals: jax.Array, ids2d: jax.Array, wb: jax.Array,
                    *, rows_pad: int, nsteps: int, outblk: int,
                    sub: int) -> jax.Array:
    """SegmentPlan's windowed sorted-segment kernel (ops/segment.py
    docstring has the algorithm); always Pallas — interpret mode off
    TPU."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nout = rows_pad // outblk
    vals2d = vals.astype(jnp.float32).reshape(-1, 128)
    # flush runs on dedicated trailing grid steps AFTER all accumulation
    # steps: every output block is flushed (including a trailing partial
    # one — rows_pad is padded to outblk), and no entry can arrive after
    # its block was written out, regardless of id skew
    grid = nsteps + nout

    def kernel(wb_ref, ids_ref, vals_ref, out_ref, scratch):
        b = pl.program_id(0)

        @pl.when(b == 0)
        def _init():
            scratch[:] = jnp.zeros_like(scratch)

        @pl.when(b < nsteps)
        def _accumulate():
            lane_iota = jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0)
            sub_iota = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 0)
            for j in range(sub):
                acc = jnp.zeros((8, 128), jnp.float32)
                for s in range(8):
                    ids_s = ids_ref[j * 8 + s, :]
                    lo = ids_s & 127
                    hi = ids_s >> 7
                    # entries live on lanes in both one-hots: no relayouts
                    a = (jnp.broadcast_to(lo[None, :], (128, 128))
                         == lane_iota).astype(jnp.float32)   # (lane, entry)
                    bmat = (jnp.broadcast_to(hi[None, :], (8, 128))
                            == sub_iota).astype(jnp.float32)  # (subrow, e)
                    bmat = bmat * vals_ref[j * 8 + s, :][None, :]
                    acc = acc + jax.lax.dot_general(
                        bmat, a, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST)
                w = wb_ref[b * sub + j]
                scratch[pl.ds(w * 8, 8), :] += acc

        @pl.when(b >= nsteps)
        def _flush():
            k = jnp.maximum(b - nsteps, 0)
            out_ref[:] = scratch[pl.ds(k * outblk, outblk), :]

    def in_map(b, wb_ref):
        return (jnp.minimum(b, nsteps - 1), 0)

    f = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((sub * 8, 128), in_map),
                pl.BlockSpec((sub * 8, 128), in_map),
            ],
            out_specs=pl.BlockSpec(
                (outblk, 128),
                lambda b, wb_ref: (jnp.maximum(b - nsteps, 0), 0)),
            scratch_shapes=[pltpu.VMEM((rows_pad, 128), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((rows_pad, 128), jnp.float32),
        interpret=registry.interpret_mode(),
    )
    return f(wb, ids2d, vals2d)
