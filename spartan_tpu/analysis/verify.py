"""DAG well-formedness verifier: one traversal, no compile, no FLOPs.

Every ``Expr`` subclass already encodes its shape/dtype derivation in
its constructor (``eval_shape_of`` or explicit arithmetic), and its
``replace_children`` is a pure re-construction over new children. The
verifier exploits that: rebuilding a node over its OWN children
re-derives shape and dtype from scratch, so any divergence between the
declared ``_shape``/``_dtype`` and what the children actually imply —
a corrupted rewrite, a broken fusion, a stale axis — surfaces as a
mismatch, and an illegal node (bad broadcast, out-of-range axis,
wrong ``replace_children`` arity) surfaces as a constructor error.
Violations carry the ``_user_site()`` provenance recorded at build
time (expr/base.py), so the report names the user line that built the
offending expression.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from ..expr.base import Expr, ExprError


class VerificationError(ExprError):
    """Static verification failed; the message lists every violation
    with node provenance."""


class Violation:
    """One well-formedness violation, attributed to a node."""

    __slots__ = ("kind", "message", "node_repr", "site")

    def __init__(self, kind: str, message: str,
                 node: Optional[Expr] = None):
        self.kind = kind
        self.message = message
        self.node_repr = repr(node) if node is not None else ""
        self.site = getattr(node, "_site", None)

    def __str__(self) -> str:
        loc = (f" [built at {self.site[0]}:{self.site[1]} "
               f"(in {self.site[2]})]" if self.site else "")
        on = f" on {self.node_repr}" if self.node_repr else ""
        return f"{self.kind}: {self.message}{on}{loc}"

    __repr__ = __str__


class _ProbeCtx:
    """Minimal signing context: exercises ONE node's ``_sig`` without
    recursing into children (``of`` returns an opaque placeholder)."""

    def leaf_pos(self, leaf: Expr) -> int:
        return 0

    def of(self, node: Expr) -> Tuple:
        return ("probe", node._id)


def walk(root: Expr) -> Tuple[List[Expr], Optional[Expr]]:
    """Post-order node list plus the first back-edge target if the
    'DAG' is cyclic (cycle-safe: never loops forever)."""
    VISITING, DONE = 1, 2
    order: List[Expr] = []
    cycle: Optional[Expr] = None
    state: Dict[int, int] = {}
    stack: List[Tuple[Expr, Optional[Iterator]]] = [(root, None)]
    while stack:
        node, it = stack.pop()
        if it is None:
            st = state.get(node._id)
            if st is not None:
                continue
            state[node._id] = VISITING
            try:
                kids: Tuple = tuple(node.children())
            except Exception:
                kids = ()  # reported per-node by verify_node
            it = iter(kids)
        descended = False
        for k in it:
            if not isinstance(k, Expr):
                continue  # reported per-node by verify_node
            st = state.get(k._id)
            if st == VISITING:
                if cycle is None:
                    cycle = k
                continue
            if st == DONE:
                continue
            stack.append((node, it))
            stack.append((k, None))
            descended = True
            break
        if not descended:
            state[node._id] = DONE
            order.append(node)
    return order, cycle


def _derived_slice_shape(node: Expr) -> Optional[Tuple[int, ...]]:
    """SliceExpr threads its declared shape through replace_children,
    so re-derive it independently: abstract-index the input spec."""
    import jax

    idx = tuple(i if i is not None else np.newaxis for i in node.index)
    out = jax.eval_shape(
        lambda x: x[idx],
        jax.ShapeDtypeStruct(node.input.shape, node.input.dtype))
    return tuple(out.shape)


def verify_node(node: Expr) -> List[Violation]:
    """Well-formedness checks for ONE node (children assumed checked)."""
    from ..expr.reduce import GeneralReduceExpr, ReduceExpr
    from ..expr.reshape import TransposeExpr
    from ..expr.slice import SliceExpr

    vios: List[Violation] = []

    # declared metadata sanity
    if not all(isinstance(s, int) and s >= 0 for s in node._shape):
        vios.append(Violation(
            "bad_shape", f"declared shape {node._shape!r} is not a tuple "
            "of non-negative ints", node))
    if not isinstance(node._dtype, np.dtype):
        vios.append(Violation(
            "bad_dtype", f"declared dtype {node._dtype!r} is not a "
            "numpy dtype", node))

    # children must be Exprs
    try:
        kids = tuple(node.children())
    except Exception as e:
        vios.append(Violation(
            "children_error",
            f"children() raised {type(e).__name__}: {e}", node))
        return vios
    for i, k in enumerate(kids):
        if not isinstance(k, Expr):
            vios.append(Violation(
                "bad_child",
                f"child {i} is {type(k).__name__}, not an Expr", node))
            return vios

    # every node must sign (structural cache keys depend on it)
    try:
        sig = node._sig(_ProbeCtx())
        if not isinstance(sig, tuple):
            vios.append(Violation(
                "bad_sig", f"_sig returned {type(sig).__name__}, "
                "expected a tuple", node))
    except NotImplementedError:
        vios.append(Violation(
            "missing_sig",
            f"{type(node).__name__} does not implement _sig", node))
    except Exception as e:
        vios.append(Violation(
            "sig_error", f"_sig raised {type(e).__name__}: {e}", node))

    # forced tiling (smart-tiling output) must match the node's rank
    ft = node._forced_tiling
    if ft is not None and ft.ndim != node.ndim:
        vios.append(Violation(
            "forced_tiling_rank",
            f"_forced_tiling rank {ft.ndim} != node rank {node.ndim}",
            node))

    # axis-bounds checks that reconstruction alone cannot catch
    # (constructors normalize axes modulo ndim, masking corruption)
    if isinstance(node, (ReduceExpr, GeneralReduceExpr)):
        nd = (len(node._pre_shape) if isinstance(node, ReduceExpr)
              else node.inputs[0].ndim if hasattr(node, "inputs")
              else node.input.ndim)
        if node.axis is not None and not all(
                0 <= a < max(nd, 1) for a in node.axis):
            vios.append(Violation(
                "bad_axis", f"reduction axis {node.axis} out of bounds "
                f"for rank-{nd} operand", node))
    if isinstance(node, TransposeExpr):
        if tuple(sorted(node.perm)) != tuple(range(node.input.ndim)):
            vios.append(Violation(
                "bad_axis", f"transpose perm {node.perm} is not a "
                f"permutation of rank {node.input.ndim}", node))

    # re-derive shape/dtype by rebuilding the node over its own
    # children; the constructor is the derivation rule, so divergence
    # means the declared metadata no longer matches the children
    try:
        clone = node.replace_children(kids)
    except NotImplementedError:
        vios.append(Violation(
            "missing_replace_children",
            f"{type(node).__name__} does not implement "
            "replace_children", node))
        return vios
    except Exception as e:
        vios.append(Violation(
            "rebuild_failed",
            "reconstructing this node over its own children raised "
            f"{type(e).__name__}: {e} (illegal broadcast / axis / "
            "operand combination)", node))
        return vios
    if clone is not node:
        if tuple(clone.shape) != tuple(node.shape):
            vios.append(Violation(
                "shape_mismatch",
                f"declared shape {node.shape} != shape {clone.shape} "
                "derived from children", node))
        if np.dtype(clone.dtype) != np.dtype(node.dtype):
            vios.append(Violation(
                "dtype_mismatch",
                f"declared dtype {node.dtype} != dtype {clone.dtype} "
                "derived from children", node))
        try:
            if len(tuple(clone.children())) != len(kids):
                vios.append(Violation(
                    "arity_mismatch",
                    "replace_children changed the child count "
                    f"({len(kids)} -> {len(tuple(clone.children()))})",
                    node))
        except Exception:
            pass  # clone children_error would re-report the same root cause
    if isinstance(node, SliceExpr):
        # declared shape is threaded through replace_children; derive
        # it independently from the index
        try:
            derived = _derived_slice_shape(node)
        except Exception as e:
            vios.append(Violation(
                "bad_axis", f"slice index {node.index!r} is illegal for "
                f"input shape {node.input.shape}: {e}", node))
        else:
            if derived != tuple(node.shape):
                vios.append(Violation(
                    "shape_mismatch",
                    f"declared shape {node.shape} != shape {derived} "
                    "derived from the slice index", node))
    return vios


def verify_dag(root: Expr) -> List[Violation]:
    """Verify a whole DAG; returns ALL violations (empty = well-formed).

    Acyclicity is checked first — a cyclic graph is reported as one
    ``cycle`` violation and not traversed further (per-node checks
    could recurse forever through the back edge).
    """
    order, cycle = walk(root)
    if cycle is not None:
        return [Violation(
            "cycle", "expression graph contains a cycle (a node is "
            "reachable from itself); evaluation would never terminate",
            cycle)]
    vios: List[Violation] = []
    for node in order:
        vios.extend(verify_node(node))
    return vios
