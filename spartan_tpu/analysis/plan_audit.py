"""Static communication audit of a compiled plan (no execution).

The fourth analysis tier (docs/ANALYSIS.md): where :mod:`verify` and
:mod:`lints` see the DAG and :mod:`passes` sees the optimizer, the
plan auditor sees the program XLA will actually run. It AOT-lowers a
plan's traced function over abstract sharded arg specs (the
obs/explain.py ``cost_analysis`` pattern — shapes and shardings, no
buffers), compiles, and walks the post-GSPMD module text
(analysis/hlo.py) to produce a structured :class:`PlanAudit`:

* every collective with participant count and modeled per-chip wire
  bytes, attributed to its expr node through the ``__sg_<digest>``
  named-scope marks (obs/profile.py) riding ``metadata.op_name``;
* findings — ``full_gather`` (an ``all-gather`` that materializes the
  entire logical payload of a sharded leaf: the PR 16 traced-start
  dynamic-slice class), ``replicated_intermediate`` (a gather above
  ``FLAGS.replication_warn_bytes``), and ``missed_donation`` (a
  requested donation the executable's ``input_output_alias`` header
  proves was silently dropped);
* the communication total ``comm_bytes`` that serve admission compares
  against ``FLAGS.comm_budget_bytes`` and the golden-audit benchmark
  gates (benchmarks/plan_audit.py) regress against.

The verdict is cached on ``plan.report["audit"]`` (JSON-safe), rides
the persist store's plan metadata (spartan_tpu/persist) so a warm
restart never re-audits, and renders in ``st.explain`` as the
per-node collective table. ``FLAGS.verify_evaluate`` runs the audit on
the compile-miss path only — cache hits stay dispatch-bound, and with
the flag off the evaluate path reads zero audit code.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils import profiling as prof
from ..utils.config import FLAGS
from ..utils.log import log_warn
from . import hlo

_REPLICATION_WARN_FLAG = FLAGS.define_int(
    "replication_warn_bytes", 64 << 20,
    "Plan-audit threshold: an all-gather whose (per-chip, fully "
    "materialized) result exceeds this many bytes is flagged as a "
    "replicated_intermediate finding — each chip holds a whole copy "
    "of something the tiling meant to shard. 0 disables the check.")


class AuditFinding:
    """One plan-audit finding (styled after analysis/lints.py
    ``LintFinding``; audit findings are advisory — the auditor never
    fails the evaluation that triggered it)."""

    __slots__ = ("severity", "kind", "message", "node", "source",
                 "bytes")

    def __init__(self, severity: str, kind: str, message: str,
                 node: Optional[str] = None,
                 source: Optional[str] = None,
                 nbytes: Optional[float] = None):
        self.severity = severity
        self.kind = kind
        self.message = message
        self.node = node
        self.source = source
        self.bytes = nbytes

    def __str__(self) -> str:
        on = f" on {self.node}" if self.node else ""
        at = f" [{self.source}]" if self.source else ""
        return f"{self.kind}: {self.message}{on}{at}"

    __repr__ = __str__

    def to_dict(self) -> Dict[str, Any]:
        return {"severity": self.severity, "kind": self.kind,
                "message": self.message, "node": self.node,
                "source": self.source, "bytes": self.bytes}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "AuditFinding":
        return cls(d.get("severity", "warning"), d.get("kind", "?"),
                   d.get("message", ""), d.get("node"),
                   d.get("source"), d.get("bytes"))


class PlanAudit:
    """Structured audit of one compiled plan.

    ``collectives`` — per-instruction dicts (kind, group_size,
    bytes_moved, node, source); ``multiset`` — ``{kind: count}``;
    ``comm_bytes`` — modeled per-chip wire total; ``findings`` —
    :class:`AuditFinding` list; ``donation`` — requested vs actually
    aliased argument positions.
    """

    def __init__(self, collectives: List[Dict[str, Any]],
                 findings: List[AuditFinding],
                 donation: Optional[Dict[str, Any]] = None):
        self.collectives = collectives
        self.findings = findings
        self.donation = donation or {"requested": [], "aliased": []}

    @property
    def comm_bytes(self) -> float:
        return float(sum(c.get("bytes_moved", 0.0)
                         for c in self.collectives))

    @property
    def multiset(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for c in self.collectives:
            out[c["kind"]] = out.get(c["kind"], 0) + 1
        return out

    def per_node(self) -> List[Dict[str, Any]]:
        """The st.explain collective table: one row per attributed
        expr node (``<unattributed>`` for collectives GSPMD invented
        with no scope mark — e.g. leaf resharding), heaviest first."""
        rows: Dict[str, Dict[str, Any]] = {}
        for c in self.collectives:
            node = c.get("node") or "<unattributed>"
            row = rows.setdefault(node, {"node": node, "kinds": {},
                                         "bytes_moved": 0.0})
            row["kinds"][c["kind"]] = row["kinds"].get(c["kind"], 0) + 1
            row["bytes_moved"] += float(c.get("bytes_moved", 0.0))
        return sorted(rows.values(), key=lambda r: -r["bytes_moved"])

    def to_dict(self) -> Dict[str, Any]:
        return {"collectives": list(self.collectives),
                "multiset": self.multiset,
                "comm_bytes": self.comm_bytes,
                "findings": [f.to_dict() for f in self.findings],
                "donation": dict(self.donation)}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "PlanAudit":
        return cls(list(d.get("collectives") or ()),
                   [AuditFinding.from_dict(f)
                    for f in d.get("findings") or ()],
                   dict(d.get("donation") or {}))

    def __str__(self) -> str:
        from ..obs.explain import _fmt_bytes

        lines = [f"plan audit: {len(self.collectives)} collective(s), "
                 f"~{_fmt_bytes(self.comm_bytes)}/chip modeled, "
                 f"{len(self.findings)} finding(s)"]
        if self.collectives:
            lines.append(f"  {'node':<34} {'collective':<20} "
                         f"{'g':>3} {'bytes/chip':>12}")
            for row in self.per_node():
                kinds = ", ".join(f"{k}x{n}" if n > 1 else k
                                  for k, n in sorted(row["kinds"].items()))
                g = max((c["group_size"] for c in self.collectives
                         if (c.get("node") or "<unattributed>")
                         == row["node"]), default=1)
                lines.append(f"  {row['node']:<34} {kinds:<20} "
                             f"{g:>3} "
                             f"{_fmt_bytes(row['bytes_moved']):>12}")
        for f in self.findings:
            lines.append(f"  finding: {f}")
        return "\n".join(lines)

    __repr__ = __str__


def _sharded_arg_specs(report: Dict[str, Any], mesh) -> List[Any]:
    """Abstract specs carrying each leaf's committed sharding — what
    makes the AOT compile a REAL 8-way SPMD partition instead of the
    single-device module explain's FLOPs estimate settles for."""
    import jax

    from ..array import tiling as tiling_mod

    specs: List[Any] = []
    for spec, entry in zip(report.get("arg_specs") or (),
                           report.get("leaves") or ()):
        axes = entry.get("tiling") if isinstance(entry, dict) else None
        if axes is None or not hasattr(spec, "shape"):
            specs.append(spec)
            continue
        t = tiling_mod.Tiling(tuple(
            tuple(a) if isinstance(a, list) else a for a in axes))
        try:
            specs.append(jax.ShapeDtypeStruct(
                spec.shape, spec.dtype, sharding=t.sharding(mesh)))
        except Exception:  # degenerate tiling for this mesh: unsharded
            specs.append(spec)
    return specs


def _sharded_leaf_bytes(report: Dict[str, Any]) -> List[Tuple[int, float]]:
    """(leaf position, full logical bytes) of every SHARDED leaf — the
    candidates a full-operand gather re-materializes."""
    out: List[Tuple[int, float]] = []
    for entry in report.get("leaves") or ():
        if not isinstance(entry, dict) or entry.get("tiling") is None:
            continue
        axes = entry["tiling"]
        if not any(a is not None for a in axes):
            continue  # replicated leaf: gathering it moves nothing new
        n = 1
        for d in entry.get("shape") or ():
            n *= int(d)
        nbytes = float(n) * np.dtype(entry.get("dtype", "f4")).itemsize
        out.append((int(entry.get("pos", -1)), nbytes))
    return out


def _attribute(op: hlo.CollectiveOp,
               scope_digests: Dict[str, Any]) -> Dict[str, Any]:
    d = op.to_dict()
    node = None
    if op.scope_digest:
        hit = scope_digests.get(op.scope_digest)
        if isinstance(hit, dict):
            node = hit.get("node")
            if d.get("source") is None:
                d["source"] = hit.get("site")
    d["node"] = node
    return d


def _count_metrics(audit: "PlanAudit") -> None:
    from ..obs.metrics import METRICS_FLAG, REGISTRY

    if not METRICS_FLAG._value:
        return
    REGISTRY.counter(
        "audit_runs", "plan audits executed (AOT compile + "
        "HLO walk; miss path or st.audit_plan only)").inc()
    REGISTRY.counter(
        "audit_collectives",
        "collective instructions seen by plan audits").inc(
        len(audit.collectives))
    if audit.findings:
        REGISTRY.counter(
            "audit_findings",
            "plan-audit findings (full_gather / "
            "replicated_intermediate / missed_donation)").inc(
            len(audit.findings))
    REGISTRY.gauge(
        "audit_last_comm_bytes",
        "modeled per-chip wire bytes of the last audited "
        "plan").set(audit.comm_bytes)


def audit_built_plan(plan: Any, mesh: Any = None,
                     donate_argnums: Sequence[int] = (),
                     force: bool = False) -> PlanAudit:
    """Audit an already-built ``_Plan``. The no-donation verdict is
    memoized on ``plan.report["audit"]`` (and from there rides the
    persist store), so repeat audits — and the serve admission check —
    are a dict read. Donation-aware calls always lower fresh: the
    aliasing verdict depends on ``donate_argnums``."""
    import jax

    from ..parallel import mesh as mesh_mod

    report = plan.report if plan is not None else None
    if report is None:
        return PlanAudit([], [])
    donate = tuple(sorted(int(i) for i in donate_argnums))
    cached = report.get("audit")
    if cached is not None and not donate and not force:
        from ..obs.metrics import METRICS_FLAG, REGISTRY

        if METRICS_FLAG._value:
            REGISTRY.counter(
                "audit_cached",
                "plan audits served from the memoized (or "
                "persist-restored) verdict without recompiling").inc()
        return PlanAudit.from_dict(cached)
    if mesh is None:
        mesh = mesh_mod.get_mesh()
    specs = _sharded_arg_specs(report, mesh)

    prev = FLAGS.trace_annotations
    FLAGS.trace_annotations = True  # scope digests must reach the HLO
    try:
        with prof.phase("audit_lower"):
            compiled = jax.jit(plan.traced, donate_argnums=donate
                               ).lower(*specs).compile()
    finally:
        FLAGS.trace_annotations = prev
    text = compiled.as_text()

    scope_digests = report.get("scope_digests") or {}
    ops = hlo.parse_collectives(text)
    collectives = [_attribute(op, scope_digests) for op in ops]

    findings: List[AuditFinding] = []
    warn_bytes = _REPLICATION_WARN_FLAG._value
    sharded = _sharded_leaf_bytes(report)
    for c in collectives:
        if c["kind"] != "all-gather":
            continue
        full = c.get("result_bytes") or 0.0
        hit = next((p for p, b in sharded if b and full >= b), None)
        if hit is not None:
            findings.append(AuditFinding(
                "warning", "full_gather",
                f"all-gather materializes the ENTIRE logical payload "
                f"of sharded leaf #{hit} "
                f"(~{int(full)} bytes per chip) — the sharding buys "
                "nothing here; this is the traced-start dynamic-slice "
                "gather class (docs/INCREMENTAL.md)", c.get("node"),
                c.get("source"), full))
        if warn_bytes and full > warn_bytes:
            findings.append(AuditFinding(
                "warning", "replicated_intermediate",
                f"all-gather result of ~{int(full)} bytes exceeds "
                f"FLAGS.replication_warn_bytes ({warn_bytes}); every "
                "chip holds a full replica of this intermediate",
                c.get("node"), c.get("source"), full))

    aliased = hlo.parse_input_output_alias(text)
    for pos in donate:
        if pos not in aliased:
            findings.append(AuditFinding(
                "warning", "missed_donation",
                f"argument {pos} was requested for donation but the "
                "executable's input_output_alias header does not "
                "alias it — the runtime will silently copy instead "
                "of reusing the buffer"))
    donation = {"requested": list(donate), "aliased": list(aliased)}

    audit = PlanAudit(collectives, findings, donation)
    _count_metrics(audit)
    if not donate:
        report["audit"] = audit.to_dict()
    return audit


def audit_on_miss(plan: Any, mesh: Any) -> None:
    """The ``FLAGS.verify_evaluate`` compile-miss hook
    (expr/base.evaluate). Advisory by contract: findings are logged +
    counted, never raised — a pathological lowering still evaluates
    correctly, it just stops being silent. A persist-restored verdict
    (``report["audit"]`` pre-seeded) skips the recompile entirely."""
    try:
        audit = audit_built_plan(plan, mesh)
    except Exception as e:  # noqa: BLE001 - the audit must never make
        # evaluate() less available than it is with the flag off
        log_warn("plan audit failed (%s: %s); continuing without a "
                 "verdict", type(e).__name__, str(e)[:200])
        return
    for f in audit.findings:
        log_warn("plan audit: %s", f)


def audit_plan(expr: Any, donate: Sequence[Any] = (),
               mesh: Any = None) -> PlanAudit:
    """Audit the plan an expression would evaluate with (``st.audit_plan``).

    Follows ``st.explain``'s skeleton: sign the raw DAG, reuse the
    cached plan on a hit, build (and cache) the plan on a miss —
    WITHOUT dispatching. ``donate`` takes the same DistArray list as
    ``evaluate(donate=...)``; the audit maps each to its executable
    argument slot and verifies the compiled module actually aliases
    it."""
    from ..expr import base

    from ..parallel import mesh as mesh_mod

    if mesh is None:
        mesh = mesh_mod.get_mesh()
    root = base.as_expr(expr)
    plan_key, rctx = base.plan_signature(root, mesh)
    plan = base.lookup_plan(plan_key)
    if plan is None:
        plan, _dag, _leaves = base._build_plan(root, mesh, rctx,
                                               plan_key)
        # prefer the stored (raw arg order) variant: its arg_order
        # indexes rctx.leaves, which is what the donate mapping needs
        stored = base.lookup_plan(plan_key)
        if stored is not None:
            plan = stored
            # the tiling DP stamps forced tilings onto raw nodes
            # during the build, so the NEXT signature of this same
            # expr differs from plan_key; store the audited plan
            # under that stable key too, so a following st.explain /
            # evaluate finds the verdict instead of rebuilding a
            # fresh (audit-less) plan
            k2, _ = base.plan_signature(root, mesh)
            if k2 != plan_key and base.lookup_plan(k2) is None:
                base.store_plan(k2, stored)
    if plan is None:
        # the optimizer collapsed the whole DAG onto a cached result:
        # nothing compiles, nothing communicates
        return PlanAudit([], [])

    donate_argnums: List[int] = []
    donated = base._norm_donate(donate)
    if donated:
        for i, j in enumerate(plan.arg_order):
            if j >= len(rctx.leaves):
                continue
            arr = base._leaf_array(rctx.leaves[j])
            if arr is not None and any(arr is d for d in donated):
                donate_argnums.append(i)
    return audit_built_plan(plan, mesh, donate_argnums)
