"""Plan-time lints: donation protocol and tiling-consistency checks.

These run over the RAW DAG before anything is compiled, catching at
plan time what PR 1's donation protocol only catches mid-execution
(use-after-donate reading a released buffer) or silently tolerates
(double-donation — the dispatch quietly skips donating an array that
feeds two argument slots), plus the declared-tiling vs kernel
``out_specs`` divergence class of ADVICE r5 #1: a ``SampleSortExpr``
whose forced output tiling contradicts the collective axis / batch
axes its kernel will actually produce forces a spurious reshard.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..expr.base import Expr, ScalarExpr, ValExpr
from .verify import walk


class LintWarning(UserWarning):
    """Category for warning-level lint findings surfaced via
    ``warnings.warn`` (e.g. by the smart-tiling pass)."""


class LintFinding:
    """One lint finding. ``severity`` is ``"error"`` (``st.check``
    raises) or ``"warning"`` (reported, never fatal)."""

    __slots__ = ("severity", "kind", "message", "node_repr", "site")

    def __init__(self, severity: str, kind: str, message: str,
                 node: Optional[Expr] = None):
        self.severity = severity
        self.kind = kind
        self.message = message
        self.node_repr = repr(node) if node is not None else ""
        self.site = getattr(node, "_site", None)

    def __str__(self) -> str:
        loc = (f" [built at {self.site[0]}:{self.site[1]} "
               f"(in {self.site[2]})]" if self.site else "")
        on = f" on {self.node_repr}" if self.node_repr else ""
        return f"{self.kind}: {self.message}{on}{loc}"

    __repr__ = __str__


def _fmt_site(site) -> str:
    return (f"{site[0]}:{site[1]} (in {site[2]})" if site
            else "<unknown site>")


def _leaf_array(leaf: Expr):
    from ..array.distarray import DistArray

    if isinstance(leaf, ValExpr):
        return leaf.value
    if isinstance(leaf, ScalarExpr):
        return None
    r = leaf._result
    return r if isinstance(r, DistArray) else None


def plan_frontier(root: Expr) -> List[Expr]:
    """The nodes the plan signature treats as leaves — actual
    ``ValExpr``/``ScalarExpr`` leaves plus any interior node carrying a
    cached ``_result`` (the collapse frontier): exactly the argument
    slots a dispatch will gather from (expr/base.py ``_PlanSigCtx``)."""
    from ..array.distarray import DistArray

    out: List[Expr] = []
    seen: set = set()
    stack = [root]
    while stack:
        n = stack.pop()
        if n._id in seen:
            continue
        seen.add(n._id)
        if (isinstance(n, (ValExpr, ScalarExpr))
                or isinstance(n._result, DistArray)):
            out.append(n)
            continue
        try:
            stack.extend(k for k in n.children() if isinstance(k, Expr))
        except Exception:
            pass
    return out


def donation_findings(root: Expr,
                      donate: Sequence[Any] = ()) -> List[LintFinding]:
    """Use-after-donate and double-donation, detected by DAG walk
    before compile (PR 1 catches the former only when the dispatch
    actually reads the dead buffer, and silently un-donates the
    latter)."""
    from ..array.distarray import DistArray
    from ..expr.base import _norm_donate

    findings: List[LintFinding] = []
    donated_args = _norm_donate(donate)

    # donate=[x, x]: the same buffer released twice in one call
    seen_args: List[DistArray] = []
    for d in donated_args:
        if any(d is s for s in seen_args):
            findings.append(LintFinding(
                "error", "double_donation",
                f"{d!r} appears more than once in donate=[...]; one "
                "buffer cannot be released twice"))
        else:
            seen_args.append(d)

    # leaf census: every DistArray behind a plan-frontier slot
    slots: Dict[int, Tuple[DistArray, List[Expr]]] = {}
    for n in plan_frontier(root):
        arr = _leaf_array(n)
        if arr is None:
            continue
        ent = slots.setdefault(id(arr), (arr, []))
        ent[1].append(n)

    for arr, leaves in slots.values():
        if arr.is_donated:
            site = _fmt_site(getattr(arr, "_donate_site", None))
            findings.append(LintFinding(
                "error", "use_after_donate",
                f"leaf reads {arr!r} whose buffer was already released "
                f"by a donating dispatch (donated at {site}); rebuild "
                "the array or keep a copy instead of reusing the "
                "donated handle", leaves[0]))
            continue
        marked = (arr._donate_next
                  or any(arr is d for d in donated_args))
        if marked and len(leaves) > 1:
            findings.append(LintFinding(
                "error", "double_donation",
                f"{arr!r} is marked for donation but feeds "
                f"{len(leaves)} distinct leaf slots of this DAG; one "
                "buffer cannot back two donated arguments (the "
                "dispatch would silently skip donating it) — donate a "
                "single shared leaf, or drop the donation", leaves[0]))
    # donating an array the DAG never reads donates nothing
    for d in seen_args:
        if id(d) not in slots and not d.is_donated:
            findings.append(LintFinding(
                "warning", "donation_unused",
                f"donate includes {d!r}, which is not a leaf of this "
                "DAG; its buffer will not be released by this "
                "evaluation"))
    return findings


def tiling_findings(nodes: List[Expr]) -> List[LintFinding]:
    """Declared-tiling consistency: sort out_specs cross-check plus
    unresolvable / degenerate tiling warnings."""
    from ..array import tiling as tiling_mod
    from ..expr.builtins import SampleSortExpr
    from ..parallel import mesh as mesh_mod

    mesh = mesh_mod.get_mesh()
    findings: List[LintFinding] = []
    for n in nodes:
        try:
            t = n.out_tiling()
        except NotImplementedError:
            findings.append(LintFinding(
                "error", "missing_tiling",
                f"{type(n).__name__} implements no _default_tiling and "
                "has no forced tiling", n))
            continue
        except Exception:
            continue  # tiling derivable only in a richer context
        if t.ndim != n.ndim:
            findings.append(LintFinding(
                "error", "tiling_rank",
                f"out_tiling rank {t.ndim} != node rank {n.ndim}", n))
            continue

        # unresolvable: names a mesh axis the ambient mesh lacks
        names = [a for ax in t.axes if ax is not None
                 for a in (ax if isinstance(ax, tuple) else (ax,))]
        unknown = [a for a in names if a not in mesh.shape]
        if unknown:
            findings.append(LintFinding(
                "warning", "unresolvable_tiling",
                f"tiling {t.axes} names mesh axes {unknown} absent "
                f"from the ambient mesh {dict(mesh.shape)}; the "
                "constraint cannot be honored", n))
            continue
        # degenerate / non-dividing tiles: sanitize would drop the axis
        for i, (d, parts) in enumerate(zip(n.shape,
                                           t.tiles_per_dim(mesh))):
            if parts > 1 and d < parts:
                findings.append(LintFinding(
                    "warning", "degenerate_tile",
                    f"axis {i} (size {d}) is split {parts} ways — "
                    "fewer elements than shards; the layout degrades "
                    "to padding/replication", n))
            elif parts > 1 and d % parts != 0:
                findings.append(LintFinding(
                    "warning", "unresolvable_tiling",
                    f"axis {i} (size {d}) does not divide into "
                    f"{parts} shards; GSPMD will pad and the planned "
                    "layout will not materialize exactly", n))

        # ADVICE r5 #1 class: a sort whose DECLARED output tiling
        # contradicts the collective axis / batch axes the kernel's
        # out_specs will actually produce forces a spurious reshard
        if isinstance(n, SampleSortExpr) and n._forced_tiling is not None:
            expected = n._default_tiling()
            if t.axes != expected.axes:
                findings.append(LintFinding(
                    "error", "sort_tiling_mismatch",
                    f"declared/forced output tiling {t.axes} diverges "
                    f"from the sort kernel's out_specs {expected.axes} "
                    "(collective axis / batch axes are fixed by the "
                    "kernel — ops/sort.py collective_axis/batch_axes); "
                    "the constraint forces a spurious reshard after "
                    "the collective pipeline", n))
    return findings


def forced_tiling_findings(root: Expr) -> List[LintFinding]:
    """Tiling-pass output check: warnings for forced tilings the
    mesh/shape cannot honor (consumed by SmartTilingPass's verify
    mode and by :func:`lint`)."""
    nodes, cycle = walk(root)
    if cycle is not None:
        return []
    out = []
    for f in tiling_findings([n for n in nodes
                              if n._forced_tiling is not None]):
        out.append(f)
    return out


def lint(expr: Any, donate: Sequence[Any] = ()) -> List[LintFinding]:
    """All plan-time lint findings for a DAG (never raises)."""
    from ..expr.base import Expr, as_expr

    root = expr if isinstance(expr, Expr) else as_expr(expr)
    nodes, cycle = walk(root)
    if cycle is not None:
        return [LintFinding(
            "error", "cycle",
            "expression graph contains a cycle", cycle)]
    findings = donation_findings(root, donate)
    findings.extend(tiling_findings(nodes))
    return findings
