"""Lowered-program introspection: parse compiled (post-SPMD) HLO text.

The plan auditor (analysis/plan_audit.py) works on the program XLA
will actually run — the partitioned module AFTER GSPMD propagation —
because that is where the framework's worst performance bugs live: an
innocuous expr op that GSPMD can only lower by whole-operand
``all-gather`` or by materializing a replicated intermediate (the PR 16
traced-start dynamic-slice class). Nothing in the raw StableHLO shows
those; the compiled text does, instruction by instruction.

This module is pure text analysis: given ``compiled.as_text()`` it
extracts

* every collective instruction (``all-reduce``, ``all-gather``,
  ``all-to-all``, ``collective-permute``, ``reduce-scatter``, plus
  their async ``-start`` halves) with its result/operand shapes,
  participant group size, a modeled per-chip wire-byte figure, and the
  ``__sg_<digest>`` scope mark (obs/profile.py naming sessions) its
  ``metadata.op_name`` carries — the join key back to the expr node;
* the module's ``input_output_alias`` header — which parameters XLA
  ACTUALLY aliased into outputs, so a requested-but-silently-dropped
  donation is machine-detectable.

The byte model is deliberately simple and stable (ring algorithms,
uniform links): per participant of a ``g``-way group moving ``B``
payload bytes, ``all-gather``/``reduce-scatter``/``all-to-all`` cost
``B*(g-1)/g``, ``all-reduce`` costs ``2*B*(g-1)/g`` (reduce-scatter +
all-gather), ``collective-permute`` costs ``B`` (one point-to-point
send per chip). Golden audits gate on these figures, so what matters
is that the model is deterministic, monotone in payload, and platform
independent — not that it matches a particular fabric's microseconds.

No jax import, no compilation, no execution happens here; callers hand
in text. Compiled-object cost/memory queries stay where lint rule 9
sanctions them (obs/explain.py).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple

#: HLO shorthand dtype -> bytes per element (fractions for packed
#: 4-bit types round the product, not the element count).
_DTYPE_BYTES = {
    "pred": 1, "s4": 0.5, "u4": 0.5, "s8": 1, "u8": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "f8e4m3fn": 1, "f8e5m2": 1,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

_COLLECTIVE_KINDS = ("all-reduce", "all-gather", "all-to-all",
                     "collective-permute", "reduce-scatter")

# `%name = <result> <opcode>(<operands>), ...` — result is either one
# `f32[8,64]{1,0}` or a tuple `(f32[...], f32[...])`; async halves
# appear as `<opcode>-start` (skip `-done`: same traffic, counted once)
_INSTR_RX = re.compile(
    r"=\s+(?P<result>\([^)]*\)|\S+)\s+"
    r"(?P<kind>all-reduce|all-gather|all-to-all|collective-permute|"
    r"reduce-scatter)(?:-start)?"
    r"\((?P<operands>.*?)\)(?P<tail>.*)$")

_SHAPE_RX = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")

# replica_groups={{0,1},{2,3}} (explicit) or [2,4]<=[8] (iota v2:
# ngroups x group_size)
_GROUPS_EXPLICIT_RX = re.compile(r"replica_groups=\{\{([0-9,{} ]*)\}\}")
_GROUPS_IOTA_RX = re.compile(r"replica_groups=\[(\d+),(\d+)\]<=")
_PAIRS_RX = re.compile(r"source_target_pairs=\{([0-9,{} ]*)\}")
_SCOPE_RX = re.compile(r"__sg_([0-9a-f]{4,16})")
_OPNAME_RX = re.compile(r'op_name="([^"]*)"')
_SOURCE_RX = re.compile(r'source_file="([^"]*)"(?:\s+source_line=(\d+))?')

# module-header donation record: input_output_alias={ {1}: (0, {},
# may-alias), ... } — the tuple's first element is the PARAMETER number
_ALIAS_BLOCK_RX = re.compile(r"input_output_alias=\{(.*?)\}\s*,?\s*entry",
                             re.DOTALL)
_ALIAS_PARAM_RX = re.compile(r"\(\s*(\d+)\s*,")


def shape_bytes(token: str) -> float:
    """Total bytes of one HLO shape token (``f32[8,64]``); tuples are
    handled by the caller summing elements. Scalars (``f32[]``) count
    one element; unknown dtypes assume 4 bytes."""
    m = _SHAPE_RX.search(token)
    if m is None:
        return 0.0
    dtype, dims = m.groups()
    n = 1
    if dims:
        for d in dims.split(","):
            n *= int(d)
    return float(n) * float(_DTYPE_BYTES.get(dtype, 4))


def _all_shape_bytes(text: str) -> float:
    """Sum the bytes of every shape token in a fragment (tuple results,
    multi-operand calls)."""
    total = 0.0
    for m in _SHAPE_RX.finditer(text):
        n = 1
        if m.group(2):
            for d in m.group(2).split(","):
                n *= int(d)
        total += float(n) * float(_DTYPE_BYTES.get(m.group(1), 4))
    return total


def _group_size(tail: str) -> int:
    """Participants per group of this collective, from either
    replica_groups spelling; 1 when unparseable (degenerate group —
    zero modeled traffic, still reported)."""
    m = _GROUPS_IOTA_RX.search(tail)
    if m is not None:
        return max(1, int(m.group(2)))
    m = _GROUPS_EXPLICIT_RX.search(tail)
    if m is not None:
        first = m.group(1).split("}")[0]
        return max(1, len([t for t in first.split(",") if t.strip()]))
    m = _PAIRS_RX.search(tail)
    if m is not None:  # collective-permute: pairs, not groups
        pairs = [p for p in m.group(1).split("}") if p.strip(", {")]
        return max(1, len(pairs))
    return 1


def modeled_bytes(kind: str, payload_bytes: float, group: int) -> float:
    """Per-chip modeled wire bytes (ring model; see module docstring)."""
    if group <= 1:
        return 0.0
    ring = payload_bytes * (group - 1) / group
    if kind == "all-reduce":
        return 2.0 * ring
    if kind == "collective-permute":
        return payload_bytes
    return ring  # all-gather / reduce-scatter / all-to-all


class CollectiveOp:
    """One collective instruction of a compiled module."""

    __slots__ = ("kind", "result_bytes", "operand_bytes", "group_size",
                 "bytes_moved", "scope_digest", "op_name", "source")

    def __init__(self, kind: str, result_bytes: float,
                 operand_bytes: float, group_size: int,
                 scope_digest: Optional[str], op_name: Optional[str],
                 source: Optional[str]):
        self.kind = kind
        self.result_bytes = result_bytes
        self.operand_bytes = operand_bytes
        self.group_size = group_size
        # payload: what each participant contributes — the operand
        # side for reducing/scattering ops, the (gathered) result for
        # all-gather, where the output is what travels
        payload = (result_bytes if kind == "all-gather"
                   else max(operand_bytes, result_bytes)
                   if kind == "all-to-all" else operand_bytes
                   or result_bytes)
        self.bytes_moved = modeled_bytes(kind, payload, group_size)
        self.scope_digest = scope_digest
        self.op_name = op_name
        self.source = source

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "result_bytes": self.result_bytes,
                "operand_bytes": self.operand_bytes,
                "group_size": self.group_size,
                "bytes_moved": self.bytes_moved,
                "scope_digest": self.scope_digest,
                "op_name": self.op_name, "source": self.source}

    def __repr__(self) -> str:
        who = f" @{self.scope_digest}" if self.scope_digest else ""
        return (f"<{self.kind} g={self.group_size} "
                f"~{self.bytes_moved:.0f}B{who}>")


def parse_collectives(hlo_text: str) -> List[CollectiveOp]:
    """Every collective instruction of a compiled module, in program
    order. ``-done`` halves are skipped (their ``-start`` was counted);
    computation definitions (``to_apply`` bodies) contain no collective
    opcodes, so a line scan is exact."""
    out: List[CollectiveOp] = []
    for line in hlo_text.splitlines():
        m = _INSTR_RX.search(line)
        if m is None:
            continue
        kind = m.group("kind")
        tail = m.group("tail")
        result_bytes = _all_shape_bytes(m.group("result"))
        operand_bytes = _all_shape_bytes(m.group("operands"))
        scope = None
        op_name = None
        source = None
        nm = _OPNAME_RX.search(tail)
        if nm is not None:
            op_name = nm.group(1)
            sm = _SCOPE_RX.search(op_name)
            if sm is not None:
                scope = sm.group(1)
        srcm = _SOURCE_RX.search(tail)
        if srcm is not None:
            source = srcm.group(1)
            if srcm.group(2):
                source += f":{srcm.group(2)}"
        out.append(CollectiveOp(kind, result_bytes, operand_bytes,
                                _group_size(tail), scope, op_name,
                                source))
    return out


def parse_input_output_alias(hlo_text: str) -> Tuple[int, ...]:
    """Parameter numbers the compiled module ACTUALLY aliases into
    outputs (the executable's donation verdict). Empty when the header
    carries no ``input_output_alias`` — every requested donation was
    dropped."""
    head = hlo_text[:4096]
    m = _ALIAS_BLOCK_RX.search(head)
    if m is None:
        return ()
    return tuple(sorted({int(p) for p in
                         _ALIAS_PARAM_RX.findall(m.group(1))}))


def collective_multiset(ops: List[CollectiveOp]) -> Dict[str, int]:
    """``{kind: count}`` over the module — the golden-audit shape
    committed in benchmarks/thresholds.json."""
    out: Dict[str, int] = {}
    for op in ops:
        out[op.kind] = out.get(op.kind, 0) + 1
    return out
