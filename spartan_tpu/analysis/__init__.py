"""Static analysis over the lazy expression DAG — the checking layer
for the optimizer pipeline (ISSUE 2: graph sanitizer).

Four coordinated tools, none of which execute anything:

* :mod:`verify` — the DAG well-formedness verifier. One traversal
  re-derives every node's shape/dtype from its children (via the
  node's own ``replace_children`` constructor, which IS the shape
  rule) and validates structure: acyclicity, child types, broadcast
  legality, axis bounds, ``_sig`` coverage.
* :mod:`passes` — optimizer-pass invariant checking. When
  ``FLAGS.verify_passes`` is on (``SPARTAN_VERIFY_PASSES=1``; the
  test suite turns it on by default), ``optimize()`` snapshots the
  DAG around every registered ``Pass`` and asserts shape/dtype/leaf
  preservation plus well-formedness, naming the offending pass.
* :mod:`lints` — plan-time lints: use-after-donate and
  double-donation caught before compile instead of mid-execution,
  declared-tiling vs sort-kernel ``out_specs`` cross-checks (the
  ADVICE r5 #1 bug class), and unresolvable/degenerate tiling
  warnings.
* :mod:`plan_audit` (+ :mod:`hlo`) — the static communication audit
  one layer further down: AOT-lower + compile a plan (no dispatch)
  and walk the post-GSPMD module for every collective with modeled
  wire bytes, full-operand-gather / replicated-intermediate /
  missed-donation findings, each attributed back to its expr node
  via the digest-carrying named scopes (docs/ANALYSIS.md).

Public surface (re-exported as ``st.check`` / ``st.lint`` /
``st.audit_plan``):

* ``check(expr, donate=())`` — raise :class:`VerificationError` on
  any violation or error-severity lint; returns the warning-level
  findings otherwise.
* ``lint(expr, donate=())`` — return ALL findings without raising.
* ``audit_plan(expr, donate=())`` — return the :class:`PlanAudit`
  of the plan this expression would evaluate with (compiles, never
  dispatches; findings are advisory).
"""

from .verify import (VerificationError, Violation, verify_dag, walk)
from .lints import LintFinding, LintWarning, lint
from .passes import PassInvariantError
from .plan_audit import AuditFinding, PlanAudit, audit_plan

from typing import Any, List, Sequence

__all__ = ["check", "lint", "verify_dag", "walk", "Violation",
           "LintFinding", "LintWarning", "VerificationError",
           "PassInvariantError", "PlanAudit", "AuditFinding",
           "audit_plan"]


def check(expr: Any, donate: Sequence[Any] = ()) -> List[LintFinding]:
    """Statically verify an expression DAG (no compile, no execute).

    Runs the well-formedness verifier plus the plan-time lints and
    raises :class:`VerificationError` — annotated with each offending
    node's user build site — if anything error-severity surfaces.
    Returns the warning-level findings (possibly empty) otherwise.
    """
    from ..expr.base import Expr, as_expr

    root = expr if isinstance(expr, Expr) else as_expr(expr)
    problems: List[str] = []
    vios = verify_dag(root)
    problems.extend(str(v) for v in vios)
    findings: List[LintFinding] = []
    if not any(v.kind == "cycle" for v in vios):
        # lints traverse out_tiling()/children; unsafe over a cyclic DAG
        findings = lint(root, donate)
        problems.extend(str(f) for f in findings if f.severity == "error")
    if problems:
        raise VerificationError(
            "expression DAG failed static verification:\n  "
            + "\n  ".join(problems))
    return [f for f in findings if f.severity != "error"]
