"""Optimizer-pass invariant checking (``SPARTAN_VERIFY_PASSES=1``).

``optimize()`` (expr/optimize.py) calls in here when
``FLAGS.verify_passes`` is on: the DAG is snapshotted before the pass
stack and re-checked after every registered ``Pass``. A pass must

* preserve the root's shape and dtype (rewrites change programs,
  never the value computed),
* keep the graph acyclic and well-formed (the full
  :func:`~spartan_tpu.analysis.verify.verify_dag` battery),
* introduce no leaf without a pre-pass counterpart — a new leaf must
  be a ``ValExpr`` wrapping data that already existed in the DAG (a
  leaf's array, or a node's cached ``_result`` — the collapse
  rewrite), never invented data,
* drop no leaf, unless the pass declares ``preserves_leaves = False``
  (``CollapseCachedPass`` legitimately prunes entire sub-DAGs below a
  cached node).

Failures raise :class:`PassInvariantError` naming the offending pass
and node — turning a silent miscompile into a loud plan-time error.
The per-pass snapshot cost is bounded by one traversal; it is paid
only on plan-cache MISSES (the same place the optimizer itself runs),
so steady-state dispatch stays check-free.
"""

from __future__ import annotations

from typing import Any, List, Set

from ..expr.base import Expr, ExprError, ScalarExpr, ValExpr
from .verify import verify_dag, walk


class PassInvariantError(ExprError):
    """An optimizer pass violated a structural invariant; the message
    names the pass and the offending node."""


class _Snapshot:
    __slots__ = ("shape", "dtype", "leaves", "leaf_ids", "leaf_data_ids",
                 "data_ids", "scalar_values")

    def __init__(self, shape, dtype, leaves: List[Expr],
                 leaf_ids: Set[int], leaf_data_ids: Set[int],
                 data_ids: Set[int], scalar_values: List[Any]):
        self.shape = shape
        self.dtype = dtype
        self.leaves = leaves
        self.leaf_ids = leaf_ids
        self.leaf_data_ids = leaf_data_ids
        self.data_ids = data_ids
        self.scalar_values = scalar_values


def _leaf_data_id(leaf: Expr) -> Any:
    from ..array.distarray import DistArray

    if isinstance(leaf, ValExpr):
        return id(leaf.value)
    if isinstance(leaf._result, DistArray):
        return id(leaf._result)
    return None


def snapshot(root: Expr, context: str = "input DAG") -> _Snapshot:
    """Capture the invariant-relevant state of a DAG: root shape/dtype,
    the leaf set (by object identity AND by backing-array identity),
    and every DistArray reachable as a cached result (legal collapse
    substitutes)."""
    from ..array.distarray import DistArray

    nodes, cycle = walk(root)
    if cycle is not None:
        raise PassInvariantError(
            f"{context} contains a cycle at {cycle!r}")
    leaves = [n for n in nodes if not n.children()]
    leaf_ids = {id(n) for n in leaves}
    leaf_data_ids = set()
    for n in leaves:
        d = _leaf_data_id(n)
        if d is not None:
            leaf_data_ids.add(d)
    data_ids = set(leaf_data_ids)
    for n in nodes:
        if isinstance(n._result, DistArray):
            data_ids.add(id(n._result))
    scalar_values = [n.pyvalue for n in leaves
                     if isinstance(n, ScalarExpr)]
    return _Snapshot(tuple(root.shape), root.dtype, leaves, leaf_ids,
                     leaf_data_ids, data_ids, scalar_values)


def check_pass(p: Any, pre: _Snapshot, post_root: Expr) -> _Snapshot:
    """Assert the pass invariants over ``post_root`` against the
    pre-pass snapshot; returns the post snapshot (the next pass's
    ``pre``). Raises :class:`PassInvariantError` naming ``p``."""
    name = getattr(p, "name", type(p).__name__)

    post = snapshot(post_root, context=f"DAG after pass '{name}'")

    if post.shape != pre.shape:
        raise PassInvariantError(
            f"pass '{name}' changed the root shape: {pre.shape} -> "
            f"{post.shape} (rewrites must preserve the computed value)")
    import numpy as np

    if np.dtype(post.dtype) != np.dtype(pre.dtype):
        raise PassInvariantError(
            f"pass '{name}' changed the root dtype: {pre.dtype} -> "
            f"{post.dtype}")

    # no invented data: every post leaf must trace back to the pre DAG
    for leaf in post.leaves:
        if id(leaf) in pre.leaf_ids:
            continue
        d = _leaf_data_id(leaf)
        if d is not None and d in pre.data_ids:
            continue  # ValExpr over a pre-existing array / cached result
        if isinstance(leaf, ScalarExpr) and any(
                type(v) is type(leaf.pyvalue) and v == leaf.pyvalue
                for v in pre.scalar_values):
            continue  # re-wrapped scalar constant: same value, ok
        raise PassInvariantError(
            f"pass '{name}' introduced leaf {leaf!r} with no pre-pass "
            "counterpart (neither a prior leaf, a cached result, nor "
            "an existing scalar constant)")

    # no dropped inputs (unless the pass legitimately prunes, like
    # the cached-collapse rewrite)
    if getattr(p, "preserves_leaves", True):
        post_ids = {id(n) for n in post.leaves}
        for leaf in pre.leaves:
            if id(leaf) in post_ids:
                continue
            d = _leaf_data_id(leaf)
            if d is not None and d in post.leaf_data_ids:
                continue
            raise PassInvariantError(
                f"pass '{name}' dropped leaf {leaf!r}: an input the "
                "computation read before the rewrite is no longer "
                "reachable (semantics changed)")

    vios = verify_dag(post_root)
    if vios:
        raise PassInvariantError(
            f"pass '{name}' broke DAG well-formedness:\n  "
            + "\n  ".join(str(v) for v in vios))
    return post
