"""Model zoo: the framework's estimator-level surface.

The reference is an array framework whose "models" are its application
algorithms (``[U] spartan/examples/`` + ``examples/sklearn/`` — SURVEY.md
§2.4); this namespace collects them as the stable, importable model API
so users don't reach into ``examples``:

    from spartan_tpu.models import KMeans, LogisticRegression
    from spartan_tpu.models import pagerank, ssvd, als

Estimators follow the sklearn fit/predict convention; functional
algorithms (pagerank, ssvd, lanczos SVD, als, cg, matrix factorization,
decompositions, lda, lsh) are re-exported directly.
"""

from ..examples.als import als  # noqa: F401
from ..examples.conj_gradient import conj_gradient as conjugate_gradient  # noqa: F401,E501
from ..examples.decomposition import (cholesky,  # noqa: F401
                                      netflix_sgd, qr, tsqr)
from ..examples.fuzzy_kmeans import fuzzy_kmeans  # noqa: F401
from ..examples.kmeans import assign_points, kmeans  # noqa: F401
from ..examples.lanczos import lanczos_svd  # noqa: F401
from ..examples.lda import lda  # noqa: F401
from ..examples.lsh import candidate_pairs as lsh_candidate_pairs  # noqa: F401,E501
from ..examples.matrix_fact import sgd_matrix_factorization  # noqa: F401
from ..examples.naive_bayes import fit as fit_naive_bayes  # noqa: F401
from ..examples.pagerank import pagerank  # noqa: F401
from ..examples.regression import (linear_regression,  # noqa: F401
                                   logistic_regression, ridge_regression)
from ..examples.sklearn.cluster import KMeans  # noqa: F401
from ..examples.sklearn.linear_model import (LinearRegression,  # noqa: F401
                                             LogisticRegression, Ridge,
                                             SGDSVC)
from ..examples.sklearn.naive_bayes import MultinomialNB  # noqa: F401
from ..examples.ssvd import ssvd  # noqa: F401
from ..examples.svm import svm as svm_fit  # noqa: F401

__all__ = [
    "als", "conjugate_gradient", "cholesky", "qr", "tsqr", "netflix_sgd",
    "fuzzy_kmeans", "kmeans", "assign_points", "lanczos_svd", "lda",
    "lsh_candidate_pairs",
    "sgd_matrix_factorization", "fit_naive_bayes", "pagerank",
    "linear_regression", "logistic_regression", "ridge_regression",
    "ssvd", "svm_fit",
    "KMeans", "LinearRegression", "LogisticRegression", "Ridge",
    "SGDSVC", "MultinomialNB",
]
