"""Distributed sample sort (1-D and batched axis sort, any length).

Parity with the reference's sampling-based distributed sort
(``[U] spartan/expr/sort.py``, SURVEY.md §2.3 misc ops). The reference
sampled per-tile splitters, shuffled elements to the worker owning
their splitter range, and locally sorted. TPU-native redesign: the
whole algorithm is ONE traced ``shard_map`` program with static shapes
(XLA-friendly — no data-dependent sizes anywhere):

1. local two-key ``lax.sort`` per shard — ``(is_padding, value)`` so
   ragged tails (``n % p != 0`` pads to the next multiple, a validity
   channel rides the whole pipeline) sort behind every real element;
2. ``s`` evenly-spaced samples from the shard's VALID prefix,
   ``all_gather`` + sort -> ``p - 1`` global splitters;
3. bucket exchange: each shard scatters its sorted elements into a
   fixed ``(p, m)`` send buffer (bucket run *j* goes to row *j*,
   cannot overflow: a shard holds only ``m`` slots) with a parallel
   validity buffer, one ``all_to_all`` for each;
4. local merge: two-key ``lax.sort`` (validity, value) over the
   received ``p * m`` slots — real elements first, in order — giving
   this device the full contents of its splitter range (capacity-safe
   under ANY skew: a bucket can never exceed ``p * m``);
5. rebalance to even row shards: VALID bucket sizes are shared with
   one ``all_gather``; each device cuts the overlap of its bucket's
   global rank range with every output shard's ``[j*m, (j+1)*m)``
   range, exchanges the chunks with a second ``all_to_all``, and
   scatters into its ``m``-element output shard. Globally the valid
   elements occupy ranks ``[0, n)`` so the caller just slices the
   padding back off.

Batched axis sort (:func:`sample_sort_axis`): the same kernel
``jax.vmap``-ed over the unsharded leading axes — an N-d array sharded
ALONG its sort axis sorts without ever gathering that axis (the traced
``jnp.sort`` fallback would all-gather it).

Bandwidth: both exchanges move O(n/p) real payload per device inside
O(n) padded buffers — the static-shape price; prefer this path when p
is moderate.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..array import tiling as tiling_mod
from ..parallel import mesh as mesh_mod
from ..parallel import redistribute as redist_mod

_SAMPLES = 64  # per-shard splitter samples (capped at shard size)


def _kernel(xs: jax.Array, axis, p: int, s: int, n: int,
            with_indices: bool = False, ragged: bool = False,
            pack_sel=None):
    """One shard's sample sort over its ``m``-slot row of the padded
    array; ``n`` is the true (unpadded) global length, so slots with
    global index >= n form the validity channel. With ``with_indices``
    the element's global source index rides the pipeline as a sort
    payload and the function returns ``(values, indices)`` — the
    distributed argsort (padding sits at the array's end, so a valid
    element's padded index IS its original index).

    ``ragged`` selects the transport for both exchanges (the routing
    math — counts, offsets, chunk cuts — is identical either way):

    * padded (default): fixed ``(p, m)`` ``all_to_all`` buffers — O(n)
      wire bytes per device for O(n/p) payload, but supported on every
      backend (round-4 verdict Weak #7's p-fold inflation);
    * ragged: two-phase — per-peer counts ride an ``all_gather``
      (p x p ints), then ``lax.ragged_all_to_all`` moves ONLY the
      payload bytes. TPU-only: XLA:CPU has no ragged-all-to-all
      thunk, so the CPU test mesh exercises the padded transport and
      the shared routing math (the primitive's offset semantics are
      validated on the real chip in tests/test_sort.py)."""
    m = xs.shape[0]
    dt = xs.dtype
    me = jax.lax.axis_index(axis)
    gidx = me.astype(jnp.int32) * m + jnp.arange(m, dtype=jnp.int32)
    inv = (gidx >= n).astype(jnp.int32)  # 1 = padding slot
    if with_indices:
        inv_s, xs_sorted, order = jax.lax.sort(
            (inv, xs, jnp.arange(m, dtype=jnp.int32)), num_keys=2)
        src_idx = me.astype(jnp.int32) * m + order  # global indices
    else:  # plain sort: cheaper than argsort + gather
        inv_s, xs_sorted = jax.lax.sort((inv, xs), num_keys=2)
        src_idx = None
    mv = (m - jnp.sum(inv)).astype(jnp.int32)  # my valid count

    # -- splitters: s evenly-spaced samples over the valid prefix ------
    samp_idx = jnp.clip((jnp.arange(s) * mv) // s, 0, m - 1)
    samples = xs_sorted[samp_idx]
    alls = jnp.sort(jax.lax.all_gather(samples, axis, tiled=True))
    splitters = alls[jnp.arange(1, p) * s]             # (p-1,)

    def exchange(mat):
        return jax.lax.all_to_all(mat, axis, split_axis=0,
                                  concat_axis=0, tiled=True)

    def ragged_exchange(vals, out_size, in_off, sizes, out_off, rsizes):
        return jax.lax.ragged_all_to_all(
            vals, jnp.zeros((out_size,), vals.dtype),
            in_off.astype(jnp.int32), sizes.astype(jnp.int32),
            out_off.astype(jnp.int32), rsizes.astype(jnp.int32),
            axis_name=axis)

    # -- bucket exchange -------------------------------------------------
    # valid elements are the sorted prefix, so per-destination runs are
    # contiguous: counts/starts drive both transports
    dst = jnp.searchsorted(splitters, xs_sorted,
                           side="right").astype(jnp.int32)
    dst = jnp.where(inv_s == 1, p, dst)     # padding: routed nowhere
    counts = jnp.bincount(dst, length=p + 1)[:p]
    starts = (jnp.cumsum(counts) - counts).astype(jnp.int32)
    if ragged:
        C = jax.lax.all_gather(counts, axis)        # C[i, j]: i -> j
        rsizes = C[:, me]
        out_off = (jnp.cumsum(C, axis=0) - C)[me]   # pack by sender
        k = jnp.sum(rsizes)
        vals = ragged_exchange(xs_sorted, p * m, starts, counts,
                               out_off, rsizes)
        valid_key = (jnp.arange(p * m) >= k).astype(jnp.int32)
        if with_indices:
            ridx = ragged_exchange(src_idx, p * m, starts, counts,
                                   out_off, rsizes)
        else:
            ridx = None
    elif pack_sel is not None:
        # kernel-layer pack (spartan_tpu/kernels/exchange.py): bucket
        # runs are contiguous in the sorted stream, so the send buffer
        # is a batch of dynamic slices — the Pallas kernel replaces
        # the XLA scatter this branch used to lower through. Validity
        # is an iota compare: row j holds counts[j] leading slots.
        from ..kernels import exchange as kexchange

        send = kexchange.partition_pack(xs_sorted, starts, counts, p,
                                        pack_sel)
        vals = exchange(send).ravel()
        valid_send = (jnp.arange(m, dtype=jnp.int32)[None, :]
                      < counts[:, None]).astype(jnp.int32)
        rvalid = exchange(valid_send)
        valid_key = (1 - rvalid).ravel()
        k = jnp.sum(rvalid)
        ridx = (exchange(kexchange.partition_pack(
            src_idx, starts, counts, p, pack_sel)).ravel()
            if with_indices else None)
    else:
        pos = jnp.arange(m, dtype=jnp.int32) - starts[
            jnp.minimum(dst, p - 1)]
        ok = (dst < p)
        posc = jnp.where(ok, pos, m)  # padding scatters out of range
        vals = exchange(jnp.zeros((p, m), dt)
                        .at[jnp.minimum(dst, p - 1), posc]
                        .set(xs_sorted, mode="drop")).ravel()
        rvalid = exchange(jnp.zeros((p, m), jnp.int32)
                          .at[jnp.minimum(dst, p - 1), posc]
                          .set(1, mode="drop"))
        valid_key = (1 - rvalid).ravel()
        k = jnp.sum(rvalid)
        ridx = (exchange(jnp.zeros((p, m), jnp.int32)
                         .at[jnp.minimum(dst, p - 1), posc]
                         .set(src_idx, mode="drop")).ravel()
                if with_indices else None)

    # -- local merge: (invalid, value) two-key sort keeps padding last
    # even when the data itself contains +inf; indices ride as payload -
    if with_indices:
        _, bucket, bidx = jax.lax.sort(
            (valid_key, vals, ridx), num_keys=2)
    else:
        _, bucket = jax.lax.sort((valid_key, vals), num_keys=2)
        bidx = None

    # -- rebalance to even output shards --------------------------------
    ks = jax.lax.all_gather(k[None], axis, tiled=True)  # (p,)
    off = (jnp.cumsum(ks) - ks)[me]                    # my global offset
    out_starts = jnp.arange(p, dtype=ks.dtype) * m
    lo = jnp.maximum(off, out_starts)
    hi = jnp.minimum(off + k, out_starts + m)
    cnt = jnp.maximum(hi - lo, 0).astype(jnp.int32)    # (p,) chunk sizes
    st = jnp.clip((lo - out_starts), 0, m).astype(jnp.int32)
    if ragged:
        in_off = jnp.clip(lo - off, 0, p * m - 1).astype(jnp.int32)
        C2 = jax.lax.all_gather(cnt, axis)             # C2[i, j]: i -> j
        rsz = C2[:, me]
        out_vals = ragged_exchange(bucket, m, in_off, cnt, st, rsz)
        if not with_indices:
            return out_vals
        out_idx = ragged_exchange(bidx, m, in_off, cnt, st, rsz)
        return out_vals, out_idx
    gather_idx = jnp.clip(lo[:, None] - off + jnp.arange(m)[None, :],
                          0, p * m - 1).astype(jnp.int32)
    rchunks = exchange(bucket[gather_idx])             # (p, m)
    rcnt = exchange(cnt)
    rst = exchange(st)
    t = jnp.arange(m, dtype=jnp.int32)[None, :]
    positions = jnp.where(t < rcnt[:, None], rst[:, None] + t, m)
    out_vals = (jnp.zeros((m,), dt)
                .at[positions.ravel()].set(rchunks.ravel(), mode="drop"))
    if not with_indices:
        return out_vals
    richunks = exchange(bidx[gather_idx])
    out_idx = (jnp.zeros((m,), jnp.int32)
               .at[positions.ravel()].set(richunks.ravel(), mode="drop"))
    return out_vals, out_idx


def _padded(x: jax.Array, n: int, p: int):
    """Pad the last axis to the next multiple of ``p`` (slot count per
    shard ``m``); padded VALUES are irrelevant — the validity channel
    governs ordering and output placement."""
    m = -(-n // p)
    n_pad = m * p
    if n_pad != n:
        widths = [(0, 0)] * (x.ndim - 1) + [(0, n_pad - n)]
        x = jnp.pad(x, widths)
    return x, m


def _uses(mesh_axis, name) -> bool:
    """Does a Tiling axis entry involve mesh axis ``name``?"""
    if mesh_axis == name:
        return True
    return isinstance(mesh_axis, tuple) and name in mesh_axis


def collective_axis(in_tiling, mesh=None) -> str:
    """The mesh axis the sample sort communicates over: the sort
    (last) axis's existing placement when that is a real (size > 1)
    mesh axis — no reshard — else the mesh row axis.

    Shared by :func:`_run` and ``SampleSortExpr._default_tiling``
    (expr/builtins.py) so the DECLARED output tiling can never diverge
    from the kernel's actual ``out_specs`` (ADVICE round 5, finding 1:
    the declared tiling used to skip the size check and mis-clear
    tuple-sharded batch axes, causing a spurious reshard)."""
    mesh = mesh or mesh_mod.get_mesh()
    name = tiling_mod.AXIS_ROW
    if in_tiling is not None and isinstance(in_tiling.axes[-1], str) \
            and int(mesh.shape.get(in_tiling.axes[-1], 1)) > 1:
        name = in_tiling.axes[-1]
    return name


def batch_axes(in_tiling, name: str, ndim: int):
    """Leading (batch) axis shardings with any use of the collective
    axis ``name`` cleared — tuple-aware via :func:`_uses`, so a batch
    axis sharded on ``('x', 'y')`` clears when ``name`` is either.
    The companion of :func:`collective_axis` (same sharing rationale)."""
    if in_tiling is None:
        return (None,) * (ndim - 1)
    return tuple(None if _uses(a, name) else a
                 for a in in_tiling.axes[:-1])


def _run(x: jax.Array, mesh, with_indices: bool,
         in_tiling=None) -> jax.Array:
    """Shared driver for every sample-sort entry point: pad the last
    axis, pick the collective mesh axis, shard_map the (possibly
    vmapped) kernel, unpad. N-d inputs keep their BATCH-axis shardings
    (minus any use of the collective axis) — a batch-sharded array is
    never replicated to sort it."""
    from ..utils.compat import shard_map

    mesh = mesh or mesh_mod.get_mesh()
    n = int(x.shape[-1])
    name = collective_axis(in_tiling, mesh)
    p = int(mesh.shape.get(name, 1))
    if p <= 1 or n == 0:
        return (jnp.argsort(x, axis=-1).astype(jnp.int32)
                if with_indices else jnp.sort(x, axis=-1))
    xp, m = _padded(x, n, p)
    batch = batch_axes(in_tiling, name, x.ndim)
    t = tiling_mod.Tiling(batch + (name,))
    xp = redist_mod.constrain(xp, t, mesh)
    s = min(_SAMPLES, m)
    # payload-only exchanges where the backend has the ragged thunk;
    # the vmapped (batched) path keeps the padded transport (no
    # batching rule for ragged_all_to_all)
    ragged = (x.ndim == 1
              and next(iter(mesh.devices.flat)).platform == "tpu")
    pack_sel = None
    if not ragged:
        # padded transport: the kernel layer may pack the send buffer
        # with the Pallas dynamic-slice kernel instead of XLA scatter
        # (batched sorts vmap it — pallas_call carries the batch as an
        # extra grid dim). 1-D TPU sorts never get here: the ragged
        # transport already moves payload-only bytes.
        from ..kernels import registry as kernels_mod

        sel = kernels_mod.select("sort_exchange", (n,), x.dtype, t,
                                 mesh, p=p, m=m)
        pack_sel = sel if sel.pallas else None

    def row_fn(r):
        out = _kernel(r, name, p, s, n, with_indices=with_indices,
                      ragged=ragged, pack_sel=pack_sel)
        return out[1] if with_indices else out

    def block_fn(v):  # local block: batch axes (locally) whole
        if v.ndim == 1:
            return row_fn(v)
        rows = v.reshape((-1, m))
        return jax.vmap(row_fn)(rows).reshape(v.shape[:-1] + (m,))

    # the replication checker has no rule for pallas_call; only the
    # kernel-packed variant relaxes it, so the GSPMD lowering stays
    # byte-identical with the kernel layer off
    kw = {"check_rep": False} if pack_sel is not None else {}
    mapped = shard_map(block_fn, mesh=mesh,
                       in_specs=(t.spec(),), out_specs=t.spec(), **kw)
    out = mapped(xp)
    return out[..., :n] if m * p != n else out


def sample_sort(x: jax.Array, mesh=None) -> jax.Array:
    """Sort a 1-D array of ANY length, sharded over the mesh row axis
    (ragged tails ride the validity channel). Traceable (usable under
    an outer jit)."""
    return _run(x, mesh, with_indices=False)


def sample_argsort(x: jax.Array, mesh=None) -> jax.Array:
    """Indices that sort a 1-D sharded array of any length
    (distributed argsort: global source indices ride the sample-sort
    pipeline as a sort payload)."""
    return _run(x, mesh, with_indices=True)


def sample_sort_axis(x: jax.Array, mesh=None, with_indices: bool =
                     False, in_tiling=None) -> jax.Array:
    """Sort an N-d array along its LAST axis — the 1-D kernel
    ``vmap``-ed over the (locally whole) leading axes, so the sort
    axis is never gathered and batch shardings survive. Callers
    moveaxis before/after for other axes; ``in_tiling`` names the
    operand's current layout so the collective axis follows the sort
    axis's existing placement. Indices are within-row positions
    (``jnp.argsort`` semantics)."""
    return _run(x, mesh, with_indices=with_indices,
                in_tiling=in_tiling)


def _extreme(dtype, lo: bool):
    """The dtype's most extreme value (lo=True: minimum) — the sentinel
    masking padded slots out of a top-k."""
    dt = np.dtype(dtype)
    if dt == np.bool_:
        return np.bool_(not lo)
    if np.issubdtype(dt, np.floating):
        return dt.type(-np.inf if lo else np.inf)
    info = np.iinfo(dt)
    return dt.type(info.min if lo else info.max)


def distributed_topk(x: jax.Array, k: int, largest: bool = True,
                     mesh=None):
    """(values, indices) of the k largest (or smallest) elements of a
    1-D array, values sorted best-first — the reference-free analogue
    of ``lax.top_k`` at mesh scale. Per shard: a LOCAL ``lax.top_k``
    keeps k candidates; one ``all_gather`` moves the p*k candidates
    (not the array); a final top-k picks the winners, replicated on
    every device. Only k*p values + indices cross the wire. Requires
    ``k <= ceil(n/p)`` (callers route bigger k through the full sort);
    ragged lengths ride the same sentinel masking as the sample sort.
    Smallest-k runs largest-k on the ORDER-FLIPPED key (sentinel
    masked), so int dtypes need no negation."""
    from ..utils.compat import shard_map

    mesh = mesh or mesh_mod.get_mesh()
    axis = tiling_mod.AXIS_ROW
    p = int(mesh.shape.get(axis, 1))
    n = int(x.shape[0])
    k = int(k)
    if not 1 <= k <= n:
        raise ValueError(f"topk needs 1 <= k <= {n}, got {k}")
    if p <= 1:
        _, idx = jax.lax.top_k(x if largest else _flip_key(x), k)
        return x[idx], idx.astype(jnp.int32)
    xp, m = _padded(x, n, p)
    if k > m:
        raise ValueError(
            f"distributed_topk requires k <= shard size {m}; got {k}")
    row = tiling_mod.row(1)
    xp = redist_mod.constrain(xp, row, mesh)
    sentinel = _extreme(x.dtype, lo=largest)
    # kernel-layer per-shard selection (spartan_tpu/kernels/topk.py):
    # replaces the local lax.top_k (a full sort on TPU) with the
    # streaming extraction kernel; the candidate gather + final merge
    # stay identical, so the sentinel/tie-break invariant below holds
    # for both backends (the kernel ties toward the LOWER index too)
    from ..kernels import registry as kernels_mod

    topk_sel = kernels_mod.select("topk", (n,), x.dtype, row, mesh,
                                  k=k)

    def kern(xs):
        me = jax.lax.axis_index(axis)
        gidx = me.astype(jnp.int32) * m + jnp.arange(
            m, dtype=jnp.int32)
        valid = gidx < n
        vv = jnp.where(valid, xs, sentinel)
        # smallest-k = largest-k on the flipped ranking key; the VALUE
        # payload stays untransformed, so ints survive exactly
        key = vv if largest else _flip_key(vv)
        # INVARIANT the sentinel masking depends on: lax.top_k breaks
        # ties toward the LOWER index. Padding slots carry the
        # sentinel extreme; when real data ALSO equals the sentinel
        # (-inf with largest=True, INT_MIN, ...) the padding occupies
        # the global tail [n, n_pad), so in both this local top_k and
        # the post-gather top_k below every tied VALID slot sits at a
        # lower index than every tied padding slot — a padding
        # candidate can never displace a real sentinel-valued element,
        # and every returned index stays < n. (Shard 0 alone holds
        # >= k valid slots since k <= m <= n, so the k winners always
        # exist among valid candidates.) Tested with sentinel-extreme
        # data on a ragged last shard in tests/test_sort.py.
        if topk_sel.pallas:
            from ..kernels import topk as ktopk

            lk, li = ktopk.shard_topk(key, k, _extreme(key.dtype,
                                                       lo=True),
                                      topk_sel)
        else:
            lk, li = jax.lax.top_k(key, k)
        lv = vv[li]
        gk = jax.lax.all_gather(lk, axis, tiled=True)       # (p*k,)
        gv = jax.lax.all_gather(lv, axis, tiled=True)
        gi = jax.lax.all_gather(gidx[li], axis, tiled=True)
        _, win = jax.lax.top_k(gk, k)
        return gv[win][None], gi[win][None].astype(jnp.int32)

    kw = {"check_rep": False} if topk_sel.pallas else {}
    mapped = shard_map(
        kern, mesh=mesh, in_specs=(row.spec(),),
        out_specs=(tiling_mod.Tiling((axis, None)).spec(),) * 2, **kw)
    vals, idx = mapped(xp)
    # every shard computed the same winners: shard 0's row is the answer
    return vals[0], idx[0]


def _flip_key(v: jax.Array) -> jax.Array:
    """An order-reversing, order-preserving-under-top_k transform:
    floats negate; ints flip via bitwise NOT against the unsigned
    midpoint (exact for the whole range, INT_MIN included)."""
    if np.issubdtype(np.dtype(v.dtype), np.floating):
        return -v
    return jnp.invert(v)

