"""Distributed 1-D sample sort.

Parity with the reference's sampling-based distributed sort
(``[U] spartan/expr/sort.py``, SURVEY.md §2.3 misc ops). The reference
sampled per-tile splitters, shuffled elements to the worker owning
their splitter range, and locally sorted. TPU-native redesign: the
whole algorithm is ONE traced ``shard_map`` program with static shapes
(XLA-friendly — no data-dependent sizes anywhere):

1. local ``jnp.sort`` per shard (bitonic on TPU);
2. ``s`` evenly-spaced samples per shard, ``all_gather`` + sort ->
   ``p - 1`` global splitters;
3. bucket exchange: each shard scatters its sorted elements into a
   fixed ``(p, m)`` send buffer (bucket run *j* goes to row *j*,
   cannot overflow: a shard holds only ``m`` elements) with a parallel
   validity mask, one ``all_to_all`` for each;
4. local merge: two-key ``lax.sort`` (validity, value) over the
   received ``p * m`` slots — real elements first, in order — giving
   this device the full contents of its splitter range (capacity-safe
   under ANY skew: a bucket can never exceed ``p * m = n``);
5. rebalance to even row shards: bucket sizes are shared with one
   ``all_gather``; each device cuts the overlap of its bucket's global
   rank range with every output shard's ``[j*m, (j+1)*m)`` range (a
   contiguous run of at most ``m`` elements -> fixed-capacity chunks),
   exchanges them with a second ``all_to_all``, and scatters into its
   ``m``-element output shard.

Bandwidth: both exchanges move O(n/p) real payload per device inside
O(n) padded buffers — the static-shape price; the padding compresses
to nothing on ICI-bound workloads only in the sense that it is
sequential HBM traffic, so prefer this path when p is moderate.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..array import tiling as tiling_mod
from ..parallel import mesh as mesh_mod

_SAMPLES = 64  # per-shard splitter samples (capped at shard size)


def _kernel(xs: jax.Array, axis, p: int, s: int,
            with_indices: bool = False):
    """One shard's sample sort; with ``with_indices`` the element's
    GLOBAL source index rides the whole pipeline as a sort payload and
    the function returns ``(values, indices)`` — the distributed
    argsort."""
    m = xs.shape[0]
    dt = xs.dtype
    me = jax.lax.axis_index(axis)
    if with_indices:
        order = jnp.argsort(xs).astype(jnp.int32)
        xs_sorted = xs[order]
        src_idx = me.astype(jnp.int32) * m + order     # global indices
    else:  # plain sort: cheaper than argsort + gather
        xs_sorted = jnp.sort(xs)
        src_idx = None

    # -- splitters ------------------------------------------------------
    samp_idx = (jnp.arange(s) * m) // s
    samples = xs_sorted[samp_idx]
    alls = jnp.sort(jax.lax.all_gather(samples, axis, tiled=True))
    splitters = alls[jnp.arange(1, p) * s]             # (p-1,)

    def exchange(mat):
        return jax.lax.all_to_all(mat, axis, split_axis=0,
                                  concat_axis=0, tiled=True)

    # -- bucket exchange (static capacity m per destination) ------------
    dst = jnp.searchsorted(splitters, xs_sorted,
                           side="right").astype(jnp.int32)
    counts = jnp.bincount(dst, length=p)
    starts = (jnp.cumsum(counts) - counts).astype(jnp.int32)
    pos = jnp.arange(m, dtype=jnp.int32) - starts[dst]
    recv = exchange(jnp.zeros((p, m), dt).at[dst, pos].set(xs_sorted))
    rvalid = exchange(jnp.zeros((p, m), jnp.int32).at[dst, pos].set(1))
    ridx = exchange(jnp.zeros((p, m), jnp.int32)
                    .at[dst, pos].set(src_idx)) if with_indices else None

    # -- local merge: (invalid, value) two-key sort keeps padding last
    # even when the data itself contains +inf; indices ride as payload -
    pad_key = (1 - rvalid).ravel()
    if with_indices:
        _, bucket, bidx = jax.lax.sort(
            (pad_key, recv.ravel(), ridx.ravel()), num_keys=2)
    else:
        _, bucket = jax.lax.sort((pad_key, recv.ravel()), num_keys=2)
        bidx = None
    k = jnp.sum(rvalid)                                # my bucket size

    # -- rebalance to even output shards --------------------------------
    ks = jax.lax.all_gather(k[None], axis, tiled=True)  # (p,)
    off = (jnp.cumsum(ks) - ks)[me]                    # my global offset
    out_starts = jnp.arange(p, dtype=ks.dtype) * m
    lo = jnp.maximum(off, out_starts)
    hi = jnp.minimum(off + k, out_starts + m)
    cnt = jnp.maximum(hi - lo, 0).astype(jnp.int32)    # (p,) chunk sizes
    st = (lo - out_starts).astype(jnp.int32)           # start in dest
    gather_idx = jnp.clip(lo[:, None] - off + jnp.arange(m)[None, :],
                          0, p * m - 1).astype(jnp.int32)
    rchunks = exchange(bucket[gather_idx])             # (p, m)
    rcnt = exchange(cnt)
    rst = exchange(st)
    t = jnp.arange(m, dtype=jnp.int32)[None, :]
    positions = jnp.where(t < rcnt[:, None], rst[:, None] + t, m)
    out_vals = (jnp.zeros((m,), dt)
                .at[positions.ravel()].set(rchunks.ravel(), mode="drop"))
    if not with_indices:
        return out_vals
    richunks = exchange(bidx[gather_idx])
    out_idx = (jnp.zeros((m,), jnp.int32)
               .at[positions.ravel()].set(richunks.ravel(), mode="drop"))
    return out_vals, out_idx


def sample_sort(x: jax.Array, mesh=None) -> jax.Array:
    """Sort a 1-D array, row-sharded over the mesh row axis.

    Traceable (usable under an outer jit). Requires
    ``x.shape[0] % p == 0``; callers fall back to a plain traced
    ``jnp.sort`` otherwise."""
    from jax import shard_map

    mesh = mesh or mesh_mod.get_mesh()
    axis = tiling_mod.AXIS_ROW
    p = int(mesh.shape[axis])
    n = int(x.shape[0])
    if p <= 1 or n % p != 0:
        # the divisibility decision was made against the expr-build-time
        # mesh; under a different evaluation mesh, fall back rather
        # than raise (same result, traced jnp.sort)
        return jnp.sort(x)
    row = tiling_mod.row(1)
    x = jax.lax.with_sharding_constraint(x, row.sharding(mesh))
    s = min(_SAMPLES, n // p)
    mapped = shard_map(lambda v: _kernel(v, axis, p, s), mesh=mesh,
                       in_specs=(row.spec(),), out_specs=row.spec())
    return mapped(x)


def sample_argsort(x: jax.Array, mesh=None) -> jax.Array:
    """Indices that sort a 1-D row-sharded array (distributed argsort:
    global source indices ride the sample-sort pipeline as a sort
    payload). Same divisibility fallback as :func:`sample_sort`."""
    from jax import shard_map

    mesh = mesh or mesh_mod.get_mesh()
    axis = tiling_mod.AXIS_ROW
    p = int(mesh.shape[axis])
    n = int(x.shape[0])
    if p <= 1 or n % p != 0:
        return jnp.argsort(x).astype(jnp.int32)
    row = tiling_mod.row(1)
    x = jax.lax.with_sharding_constraint(x, row.sharding(mesh))
    s = min(_SAMPLES, n // p)
    mapped = shard_map(
        lambda v: _kernel(v, axis, p, s, with_indices=True)[1],
        mesh=mesh, in_specs=(row.spec(),), out_specs=row.spec())
    return mapped(x)
