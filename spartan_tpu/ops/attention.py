"""Long-context attention: blockwise, ring (sequence-parallel), Ulysses.

The reference has no attention (SURVEY.md §2.6/§5: its mechanism for "an
axis too big for one node" is tiling + shuffle). This module supplies the
first-class long-context capability the TPU build requires: the sequence
axis is sharded over the mesh and attention runs either

* :func:`blockwise_attention` — single-shard online-softmax over KV
  blocks via ``lax.scan`` (memory-efficient; the substrate),
* :func:`ring_attention` — KV shards rotate around the ring via
  ``ppermute`` while each device accumulates its queries' online softmax
  (communication overlaps compute; seq length scales with mesh size),
* :func:`ulysses_attention` — one ``all_to_all`` swaps the shard from
  the sequence axis to the head axis, local full attention, swap back.

All variants accumulate in f32 and match the dense oracle.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..utils.compat import pcast, shard_map

from ..array.tiling import Tiling
from ..parallel import collectives as coll
from ..parallel import mesh as mesh_mod

_NEG_INF = -1e30


def dense_attention(q, k, v, causal: bool = False):
    """Oracle: plain softmax attention. q,k,v: (L, H, D)."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("qhd,khd->hqk", q, k) * scale
    if causal:
        lq, lk = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((lq, lk), bool))
        scores = jnp.where(mask[None], scores, _NEG_INF)
    w = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
    return jnp.einsum("hqk,khd->qhd", w.astype(q.dtype), v)


def _online_block(q, k, v, acc, m, denom, q_off, k_off, causal):
    """One KV block of online softmax. q: (Lq,H,D); k,v: (Lk,H,D);
    acc: (Lq,H,D) f32; m, denom: (H, Lq) f32."""
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("qhd,khd->hqk", q, k).astype(jnp.float32) * scale
    if causal:
        q_pos = q_off + jnp.arange(q.shape[0])
        k_pos = k_off + jnp.arange(k.shape[0])
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None], scores, _NEG_INF)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    corr = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    denom = denom * corr + p.sum(axis=-1)
    pv = jnp.einsum("hqk,khd->qhd", p.astype(v.dtype), v).astype(jnp.float32)
    acc = acc * corr.T[..., None] + pv
    return acc, m_new, denom


def blockwise_attention(q, k, v, block_size: int = 512,
                        causal: bool = False):
    """(L, H, D) attention scanning KV blocks; O(L * block) memory."""
    lq, h, d = q.shape
    lk = k.shape[0]
    bs = min(block_size, lk)
    pad = -lk % bs
    if pad:
        k = jnp.pad(k, ((0, pad), (0, 0), (0, 0)))
        v = jnp.pad(v, ((0, pad), (0, 0), (0, 0)))
    nb = k.shape[0] // bs
    kb = k.reshape(nb, bs, h, d)
    vb = v.reshape(nb, bs, h, d)

    acc0 = jnp.zeros((lq, h, d), jnp.float32)
    m0 = jnp.full((h, lq), _NEG_INF, jnp.float32)
    den0 = jnp.zeros((h, lq), jnp.float32)

    def body(carry, blk):
        acc, m, den, koff = carry
        kk, vv = blk
        # padding keys sit past lk: causal=False must drop them too
        k_pos = koff + jnp.arange(bs)
        valid = k_pos < lk
        kk = jnp.where(valid[:, None, None], kk, 0.0)
        acc2, m2, den2 = _online_block(
            q, kk, vv, acc, m, den, 0, koff,
            causal) if causal else _masked_block(
                q, kk, vv, acc, m, den, valid)
        return (acc2, m2, den2, koff + bs), None

    (acc, m, den, _), _ = lax.scan(body, (acc0, m0, den0, 0), (kb, vb))
    return (acc / den.T[..., None]).astype(q.dtype)


def _masked_block(q, k, v, acc, m, denom, valid):
    scale = 1.0 / np.sqrt(q.shape[-1])
    scores = jnp.einsum("qhd,khd->hqk", q, k).astype(jnp.float32) * scale
    scores = jnp.where(valid[None, None, :], scores, _NEG_INF)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    corr = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    denom = denom * corr + p.sum(axis=-1)
    pv = jnp.einsum("hqk,khd->qhd", p.astype(v.dtype), v).astype(jnp.float32)
    acc = acc * corr.T[..., None] + pv
    return acc, m_new, denom


def ring_attention(q, k, v, causal: bool = False,
                   mesh_axis: str = mesh_mod.AXIS_ROW):
    """Sequence-parallel attention: (L, H, D) arrays sharded on L over
    ``mesh_axis``; KV shards rotate around the ring (ppermute) while each
    device accumulates its local queries' online softmax."""
    mesh = mesh_mod.get_mesh()
    n = mesh.shape[mesh_axis]
    l = q.shape[0]
    if l % max(n, 1):
        raise ValueError(f"sequence length {l} must divide over "
                         f"{n} devices")
    seq_t = Tiling((mesh_axis, None, None))
    spec = seq_t.spec()
    shard_l = l // n

    def kernel(ql, kl, vl):
        my = lax.axis_index(mesh_axis)
        q_off = my * shard_l
        # pcast-to-varying: these carries become device-varying once
        # the ring runs, so the initial values must be marked varying
        # too (pvary was deprecated in favor of pcast)
        acc = pcast(jnp.zeros(ql.shape, jnp.float32), (mesh_axis,),
                        to="varying")
        m = pcast(jnp.full((ql.shape[1], ql.shape[0]), _NEG_INF,
                               jnp.float32), (mesh_axis,), to="varying")
        den = pcast(jnp.zeros((ql.shape[1], ql.shape[0]), jnp.float32),
                        (mesh_axis,), to="varying")

        def body(s, carry):
            acc, m, den, kk, vv = carry
            # block s came from device (my - s) mod n
            src = (my - s) % n
            k_off = src * shard_l
            acc, m, den = _online_block(ql, kk, vv, acc, m, den,
                                        q_off, k_off, causal)
            kk = coll.ring_permute(kk, mesh_axis, 1)
            vv = coll.ring_permute(vv, mesh_axis, 1)
            return (acc, m, den, kk, vv)

        acc, m, den, _, _ = lax.fori_loop(
            0, n, body, (acc, m, den, kl, vl))
        return (acc / den.T[..., None]).astype(ql.dtype)

    q = jax.device_put(q, seq_t.sharding(mesh))
    k = jax.device_put(k, seq_t.sharding(mesh))
    v = jax.device_put(v, seq_t.sharding(mesh))
    fn = shard_map(kernel, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec)
    return jax.jit(fn)(q, k, v)


def ulysses_attention(q, k, v, causal: bool = False,
                      mesh_axis: str = mesh_mod.AXIS_ROW):
    """SP via axis swap: inputs seq-sharded (L, H, D); one all_to_all
    re-shards to head-sharded, full-sequence attention runs locally per
    head group, and the inverse all_to_all restores seq sharding."""
    mesh = mesh_mod.get_mesh()
    n = mesh.shape[mesh_axis]
    if q.shape[1] % max(n, 1):
        raise ValueError(f"head count {q.shape[1]} must divide over "
                         f"{n} devices")
    seq_t = Tiling((mesh_axis, None, None))
    spec = seq_t.spec()

    def kernel(ql, kl, vl):
        # (L/n, H, D) -> (L, H/n, D)
        qh = coll.all_to_all(ql, mesh_axis, split_axis=1, concat_axis=0)
        kh = coll.all_to_all(kl, mesh_axis, split_axis=1, concat_axis=0)
        vh = coll.all_to_all(vl, mesh_axis, split_axis=1, concat_axis=0)
        out = dense_attention(qh, kh, vh, causal)
        return coll.all_to_all(out, mesh_axis, split_axis=0, concat_axis=1)

    q = jax.device_put(q, seq_t.sharding(mesh))
    k = jax.device_put(k, seq_t.sharding(mesh))
    v = jax.device_put(v, seq_t.sharding(mesh))
    fn = shard_map(kernel, mesh=mesh, in_specs=(spec, spec, spec),
                   out_specs=spec)
    return jax.jit(fn)(q, k, v)
