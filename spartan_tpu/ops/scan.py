"""Distributed blocked prefix scan.

The reference family's prefix-scan (``scan`` builtin, exercised by SSVD
per BASELINE.json:11) over a SHARDED axis. A traced ``jnp.cumsum`` on a
row-sharded operand makes GSPMD all-gather the axis (3 all-gathers in
the compiled HLO) and run the whole scan replicated — measured minutes
at 4M elements on the 8-device CPU mesh. The classic blocked
decomposition is one shard_map program with static shapes:

1. local inclusive scan per shard;
2. ``all_gather`` of the per-shard totals (p scalars per scanned
   column — tiny);
3. exclusive scan of the totals on every device (p elements);
4. combine my shard's local scan with my exclusive offset.

Supports add / mul / max / min (the combine in step 4 uses the same
associative op), scanning axis 0 of row-sharded arrays of any rank
(trailing-axis sharding is preserved through the shard_map specs).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..array import tiling as tiling_mod
from ..parallel import mesh as mesh_mod
from ..parallel import redistribute as redist_mod

_LOCAL = {
    "add": jnp.cumsum,
    "mul": jnp.cumprod,
    "max": lambda v, axis: jax.lax.cummax(v, axis=axis),
    "min": lambda v, axis: jax.lax.cummin(v, axis=axis),
}
_COMBINE = {
    "add": jnp.add,
    "mul": jnp.multiply,
    "max": jnp.maximum,
    "min": jnp.minimum,
}
_IDENTITY = {"add": 0.0, "mul": 1.0, "max": -jnp.inf, "min": jnp.inf}


def _identity_for(op: str, dtype):
    if op in ("max", "min") and jnp.issubdtype(dtype, jnp.integer):
        info = jnp.iinfo(dtype)
        return info.min if op == "max" else info.max
    return _IDENTITY[op]


def _kernel(xs: jax.Array, axis_name, p: int, op: str) -> jax.Array:
    local = _LOCAL[op](xs, axis=0)
    tot = local[-1][None]                              # (1, ...) totals
    alls = jax.lax.all_gather(tot, axis_name, tiled=True)   # (p, ...)
    # exclusive scan of totals: shift the inclusive scan by identity
    incl = _LOCAL[op](alls, axis=0)
    ident = jnp.full_like(alls[:1], _identity_for(op, xs.dtype))
    excl = jnp.concatenate([ident, incl[:-1]], axis=0)
    me = jax.lax.axis_index(axis_name)
    return _COMBINE[op](local, excl[me])


def scan_axes(in_axes, ndim: int):
    """The sharding the blocked scan runs under: scan axis on the mesh
    row axis, trailing axes KEEPING their existing mesh assignment
    (the kernel is independent per trailing-axis shard — de-sharding
    columns of a block-tiled operand would all-gather them for
    nothing). A trailing axis that conflicts with the row axis is
    dropped to replicated."""
    row = tiling_mod.AXIS_ROW
    trailing = list(tuple(in_axes or ())[1:]) + [None] * ndim
    axes = [row]
    for a in trailing[:ndim - 1]:
        conflict = a == row or (isinstance(a, tuple) and row in a)
        axes.append(None if conflict else a)
    return tiling_mod.Tiling(axes)


def blocked_scan(x: jax.Array, op: str = "add", mesh=None,
                 in_axes=None) -> jax.Array:
    """Inclusive prefix scan along axis 0, distributed over the mesh
    row axis. ``in_axes`` (the operand's tiling axes, when known)
    keeps trailing-axis sharding intact. Traceable; falls back to the
    local cumulative op when the axis does not shard evenly (same
    contract as sample_sort)."""
    from ..utils.compat import shard_map

    if op not in _LOCAL:
        raise ValueError(f"unknown scan op {op!r}")
    mesh = mesh or mesh_mod.get_mesh()
    axis = tiling_mod.AXIS_ROW
    p = int(mesh.shape[axis])
    n = int(x.shape[0])
    if p <= 1 or n == 0 or n % p != 0:
        return _LOCAL[op](x, axis=0)
    t = scan_axes(in_axes, x.ndim)
    t = tiling_mod.sanitize(t, x.shape, mesh)
    if t.mesh_axis_of(0) is None:  # sanitize dropped the scan axis
        return _LOCAL[op](x, axis=0)
    x = redist_mod.constrain(x, t, mesh)
    mapped = shard_map(lambda v: _kernel(v, axis, p, op), mesh=mesh,
                       in_specs=(t.spec(),), out_specs=t.spec())
    return mapped(x)
