"""Segment-sum / scatter-add merge kernels.

The TPU-native equivalent of the reference's Cython sparse-merge kernel
(SURVEY.md §2.5: ``spartan/sparse_update.pyx`` -> "Pallas TPU kernel ...
for scatter-add / segment-sum merges"). Three paths:

* ``xla`` — ``jax.ops.segment_sum`` (XLA scatter; always correct).
* ``onehot`` — one-hot matmul: ``onehot(ids).T @ vals``. Turns the
  scatter into an MXU matmul — the TPU-first trick for small segment
  counts (k-means' k=64 centers, histogram merges).
* ``pallas`` — blocked one-hot accumulation kernel: the entry stream is
  tiled over a sequential grid, each tile builds its one-hot block in
  VMEM and accumulates ``block.T @ vals`` into the output block (MXU),
  avoiding XLA's general scatter. TPU only; falls back to ``onehot``
  elsewhere.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.config import FLAGS

FLAGS.define_str("segment_impl", "auto",
                 "segment-sum path: auto|xla|onehot|pallas")

# one-hot is profitable only when num_segments is small
_ONEHOT_MAX_SEGMENTS = 4096


def _segment_sum_xla(vals: jax.Array, ids: jax.Array,
                     num_segments: int, sorted_ids: bool = False
                     ) -> jax.Array:
    return jax.ops.segment_sum(vals, ids, num_segments=num_segments,
                               indices_are_sorted=sorted_ids)


def _segment_sum_onehot(vals: jax.Array, ids: jax.Array,
                        num_segments: int) -> jax.Array:
    onehot = (ids[:, None] == jnp.arange(num_segments)[None, :])
    onehot = onehot.astype(vals.dtype)
    # 'highest' so the MXU doesn't round the merge through bf16
    return jnp.matmul(onehot.T, vals, precision="highest")


def _pallas_available() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _segment_sum_pallas(vals: jax.Array, ids: jax.Array,
                        num_segments: int,
                        block_e: int = 512) -> jax.Array:
    """Blocked one-hot accumulation on TPU.

    Grid over entry blocks (sequential on TPU); the output block is
    revisited every step and accumulated in VMEM. ``num_segments`` and the
    feature dim are padded to lane/sublane multiples.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    squeeze = vals.ndim == 1
    if squeeze:
        vals = vals[:, None]
    e, d = vals.shape
    k = num_segments
    # pad to TPU tiling: entries to block_e, segments/features to 128/8
    e_pad = -e % block_e
    if e_pad:
        vals = jnp.pad(vals, ((0, e_pad), (0, 0)))
        ids = jnp.pad(ids, (0, e_pad), constant_values=k)  # out of range
    k_pad = -k % 8
    d_pad = -d % 128
    vals = jnp.pad(vals, ((0, 0), (0, d_pad)))
    n_blocks = vals.shape[0] // block_e
    k_total = k + k_pad
    # ids as (n_blocks, block_e): 2-D blocks match the XLA layout Mosaic
    # expects (1-D s32 operands hit a T(1024)/T(512) tiling mismatch)
    ids2d = ids.astype(jnp.int32).reshape(n_blocks, block_e)

    def kernel(ids_ref, vals_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        seg = jax.lax.broadcasted_iota(jnp.int32, (block_e, k_total), 1)
        onehot = (ids_ref[step, :][:, None] == seg).astype(vals_ref.dtype)
        out_ref[:] += jnp.dot(onehot.T, vals_ref[:],
                              preferred_element_type=out_ref.dtype,
                              precision="highest")

    out = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[
            # whole ids table resident (Mosaic requires sublane-divisible
            # or full blocks); the kernel row-indexes it by step
            pl.BlockSpec((n_blocks, block_e), lambda i: (0, 0)),
            pl.BlockSpec((block_e, vals.shape[1]), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((k_total, vals.shape[1]), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((k_total, vals.shape[1]),
                                       vals.dtype),
    )(ids2d, vals)
    out = out[:k, :d]
    return out[:, 0] if squeeze else out


def segment_sum(vals: jax.Array, ids: jax.Array, num_segments: int,
                impl: Optional[str] = None,
                sorted_ids: bool = False) -> jax.Array:
    """Sum ``vals`` rows into ``num_segments`` buckets by ``ids``.

    ids outside [0, num_segments) are dropped (XLA segment_sum
    semantics), which the padding paths rely on. ``sorted_ids`` unlocks
    XLA's sorted-scatter fast path (the SparseDistArray invariant)."""
    impl = impl or FLAGS.segment_impl
    if impl == "auto":
        # measured on v5e (1M x 128, k=64): xla scatter 33ms,
        # onehot 67ms, pallas 71ms (highest-precision merges) — XLA's
        # native scatter wins; the matmul paths stay as ablations
        impl = "xla"
    if impl == "pallas":
        if not _pallas_available():
            impl = "onehot"
        else:
            return _segment_sum_pallas(vals, ids, num_segments)
    if impl == "onehot":
        return _segment_sum_onehot(vals, ids, num_segments)
    return _segment_sum_xla(vals, ids, num_segments, sorted_ids)


def segment_count(ids: jax.Array, num_segments: int,
                  dtype=jnp.float32, impl: Optional[str] = None
                  ) -> jax.Array:
    return segment_sum(jnp.ones(ids.shape, dtype), ids, num_segments, impl)
