"""Segment-sum / scatter-add merge kernels.

The TPU-native equivalent of the reference's Cython sparse-merge kernel
(SURVEY.md §2.5: ``spartan/sparse_update.pyx`` -> "Pallas TPU kernel ...
for scatter-add / segment-sum merges"). Three paths:

* ``xla`` — ``jax.ops.segment_sum`` (XLA scatter; always correct).
* ``onehot`` — one-hot matmul: ``onehot(ids).T @ vals``. Turns the
  scatter into an MXU matmul — the TPU-first trick for small segment
  counts (k-means' k=64 centers, histogram merges).
* ``pallas`` — the kernel layer's blocked one-hot accumulation kernel
  (spartan_tpu/kernels/segment.py), shard_map-wrapped over the mesh
  row axis with a psum-scatter merge on multi-device meshes.

Backend selection is the kernel layer's policy (``kernels.select``,
docs/KERNELS.md), not a per-call platform probe: ``auto`` keeps XLA's
native scatter (it measured FASTER than the one-hot kernels on v5e —
1M x 128, k=64: xla 33ms, onehot 67ms, pallas 71ms), and the Pallas
path stays selectable explicitly (``impl="pallas"`` /
``FLAGS.segment_impl``) or via ``FLAGS.native_kernels=on`` — the CPU
CI parity mode that runs it in interpret mode.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..array import tiling as tiling_mod
from ..kernels import registry as kernels_mod
from ..utils.config import FLAGS

FLAGS.define_str("segment_impl", "auto",
                 "segment-sum path: auto|xla|onehot|pallas")

# one-hot is profitable only when num_segments is small
_ONEHOT_MAX_SEGMENTS = 4096


def _segment_sum_xla(vals: jax.Array, ids: jax.Array,
                     num_segments: int, sorted_ids: bool = False
                     ) -> jax.Array:
    return jax.ops.segment_sum(vals, ids, num_segments=num_segments,
                               indices_are_sorted=sorted_ids)


def _segment_sum_onehot(vals: jax.Array, ids: jax.Array,
                        num_segments: int) -> jax.Array:
    onehot = (ids[:, None] == jnp.arange(num_segments)[None, :])
    onehot = onehot.astype(vals.dtype)
    # 'highest' so the MXU doesn't round the merge through bf16
    return jnp.matmul(onehot.T, vals, precision="highest")


def _pallas_available() -> bool:
    """Back-compat probe (array/sparse.py): is the NATIVE Mosaic path
    available here? The selection policy proper is kernels.select."""
    return not kernels_mod.interpret_mode()


def _select(vals: jax.Array, num_segments: int,
            force: bool = False) -> kernels_mod.Selection:
    return kernels_mod.select(
        "segment_sum", vals.shape, vals.dtype,
        tiling_mod.row(max(vals.ndim, 1)), force=force,
        num_segments=num_segments)


def segment_sum(vals: jax.Array, ids: jax.Array, num_segments: int,
                impl: Optional[str] = None,
                sorted_ids: bool = False) -> jax.Array:
    """Sum ``vals`` rows into ``num_segments`` buckets by ``ids``.

    ids outside [0, num_segments) are dropped (XLA segment_sum
    semantics), which the padding paths rely on. ``sorted_ids`` unlocks
    XLA's sorted-scatter fast path (the SparseDistArray invariant)."""
    from ..kernels import segment as ksegment

    impl = impl or FLAGS.segment_impl
    forced = impl == "pallas"
    if impl == "auto":
        # the kernel-layer policy: XLA's native scatter measured
        # faster than both matmul paths on v5e (module docstring), so
        # auto selects pallas only under FLAGS.native_kernels=on (the
        # parity/ablation mode)
        sel = _select(vals, num_segments)
        impl = "pallas" if sel.pallas else "xla"
    if impl == "pallas":
        sel = _select(vals, num_segments, force=forced)
        if not sel.pallas:
            impl = "onehot"  # constraint fallback (reason: sel.reason)
        else:
            return ksegment.segment_sum_sharded(vals, ids,
                                                num_segments, sel)
    if impl == "onehot":
        return _segment_sum_onehot(vals, ids, num_segments)
    return _segment_sum_xla(vals, ids, num_segments, sorted_ids)


def segment_count(ids: jax.Array, num_segments: int,
                  dtype=jnp.float32, impl: Optional[str] = None
                  ) -> jax.Array:
    return segment_sum(jnp.ones(ids.shape, dtype), ids, num_segments, impl)


class SegmentPlan:
    """Host-precomputed layout for the windowed sorted-segment kernel.

    XLA's scatter lowering is the TPU sparse bottleneck (measured 199 ms
    for a 16M->1M sorted segment-sum on v5e, and far worse inside
    ``fori_loop``). This plan turns the scatter into dense one-hot
    algebra: entries are grouped by aligned ``W``-wide output windows and
    padded to 1024-entry subblocks; the kernel keeps the whole output
    resident in a VMEM scratch and, per subblock, builds two small
    one-hots from each id's lane (``id & 127``) and sublane (``id >> 7``)
    halves, contracts them with one (8,128)x(128,128) MXU dot, and
    accumulates the (8,128) window block at a dynamic scratch offset.
    Measured 34 ms standalone (~20 ms fused) for the same 16M->1M merge —
    ~6x over XLA — and it does not degrade inside ``lax.fori_loop``.

    The plan is built once per static id structure (e.g. a sparse
    matrix's rows); runtime value streams must be produced in plan order
    (use :meth:`reorder` on the host-side companion arrays at build
    time). Scratch residency bounds ``num_segments`` to ~2M on a 16 MB
    VMEM part. The kernel itself lives in spartan_tpu/kernels/segment.py
    (lint rule 12: Pallas only under the kernel layer).
    """

    W = 1024          # output window (one (8,128) f32 block)
    EB = 1024         # entries per subblock
    SUB = 8           # subblocks per grid step

    def __init__(self, ids: np.ndarray, num_segments: int):
        ids = np.asarray(ids)
        if ids.ndim != 1:
            raise ValueError("SegmentPlan ids must be 1-D")
        if np.any(np.diff(ids) < 0):
            raise ValueError("SegmentPlan requires sorted ids")
        n = int(num_segments)
        W, EB, SUB = self.W, self.EB, self.SUB
        self.num_segments = n
        self.n_pad = -(-max(n, 1) // W) * W
        n_windows = self.n_pad // W
        # Out-of-range ids are dropped on both ends (matching
        # jax.ops.segment_sum): sorted => negatives are a prefix and
        # ids >= n a suffix, so the valid run is a contiguous slice.
        neg = int(np.searchsorted(ids, 0))
        e = int(np.searchsorted(ids, n))
        ids_v = ids[neg:e].astype(np.int64)
        e -= neg
        wb_all = ids_v // W
        counts = np.bincount(wb_all, minlength=n_windows)
        padded = -(-counts // EB) * EB
        total = int(padded.sum())
        rows_out = self.n_pad // 128
        self.outblk = min(1024, rows_out)
        self.rows_pad = -(-rows_out // self.outblk) * self.outblk
        step = SUB * EB
        total_steps = max(-(-total // step), 1)
        grand = total_steps * step
        starts = np.zeros(n_windows, np.int64)
        starts[1:] = np.cumsum(padded)[:-1]
        src_starts = np.zeros(n_windows, np.int64)
        src_starts[1:] = np.cumsum(counts)[:-1]
        # position of each source entry in the padded stream (vectorized)
        pos = starts[wb_all] + (np.arange(e) - src_starts[wb_all])
        ids_local = np.full(grand, W, np.int32)      # sentinel: no match
        ids_local[pos] = (ids_v - wb_all * W).astype(np.int32)
        self.perm = pos                     # valid entry -> padded slot
        self._lo = neg                      # first valid source index
        self.padded_size = grand
        self.nsteps = total_steps
        wb = np.zeros(grand // EB, np.int32)
        wb[:total // EB] = np.repeat(
            np.arange(n_windows, dtype=np.int32), padded // EB)
        self._ids2d = jnp.asarray(ids_local.reshape(-1, 128))
        self._wb = jnp.asarray(wb)

    def reorder(self, arr: np.ndarray, fill=0) -> np.ndarray:
        """Host-side: lay a per-entry companion array out in plan order."""
        arr = np.asarray(arr)
        out = np.full((self.padded_size,) + arr.shape[1:], fill, arr.dtype)
        out[self.perm] = arr[self._lo:self._lo + self.perm.size]
        return out

    def segment_sum(self, vals: jax.Array) -> jax.Array:
        """Sum a plan-ordered f32 value stream into segments. Traceable
        (usable inside jit / fori_loop / other kernels)."""
        from ..kernels.segment import windowed_segsum

        out2d = windowed_segsum(vals, self._ids2d, self._wb,
                                rows_pad=self.rows_pad,
                                nsteps=self.nsteps,
                                outblk=self.outblk, sub=self.SUB)
        return out2d.reshape(-1)[:self.num_segments]


def _windowed_segsum(vals: jax.Array, ids2d: jax.Array, wb: jax.Array,
                     **kw) -> jax.Array:
    """Back-compat alias (array/sparse.py, examples/pagerank.py): the
    kernel proper moved to spartan_tpu/kernels/segment.py."""
    from ..kernels.segment import windowed_segsum

    return windowed_segsum(vals, ids2d, wb, **kw)
