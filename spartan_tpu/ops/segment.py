"""Segment-sum / scatter-add merge kernels.

The TPU-native equivalent of the reference's Cython sparse-merge kernel
(SURVEY.md §2.5: ``spartan/sparse_update.pyx`` -> "Pallas TPU kernel ...
for scatter-add / segment-sum merges"). Three paths:

* ``xla`` — ``jax.ops.segment_sum`` (XLA scatter; always correct).
* ``onehot`` — one-hot matmul: ``onehot(ids).T @ vals``. Turns the
  scatter into an MXU matmul — the TPU-first trick for small segment
  counts (k-means' k=64 centers, histogram merges).
* ``pallas`` — blocked one-hot accumulation kernel: the entry stream is
  tiled over a sequential grid, each tile builds its one-hot block in
  VMEM and accumulates ``block.T @ vals`` into the output block (MXU),
  avoiding XLA's general scatter. TPU only; falls back to ``onehot``
  elsewhere.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.config import FLAGS

FLAGS.define_str("segment_impl", "auto",
                 "segment-sum path: auto|xla|onehot|pallas")

# one-hot is profitable only when num_segments is small
_ONEHOT_MAX_SEGMENTS = 4096


def _segment_sum_xla(vals: jax.Array, ids: jax.Array,
                     num_segments: int, sorted_ids: bool = False
                     ) -> jax.Array:
    return jax.ops.segment_sum(vals, ids, num_segments=num_segments,
                               indices_are_sorted=sorted_ids)


def _segment_sum_onehot(vals: jax.Array, ids: jax.Array,
                        num_segments: int) -> jax.Array:
    onehot = (ids[:, None] == jnp.arange(num_segments)[None, :])
    onehot = onehot.astype(vals.dtype)
    # 'highest' so the MXU doesn't round the merge through bf16
    return jnp.matmul(onehot.T, vals, precision="highest")


def _pallas_available() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _segment_sum_pallas(vals: jax.Array, ids: jax.Array,
                        num_segments: int,
                        block_e: int = 512) -> jax.Array:
    """Blocked one-hot accumulation on TPU.

    Grid over entry blocks (sequential on TPU); the output block is
    revisited every step and accumulated in VMEM. ``num_segments`` and the
    feature dim are padded to lane/sublane multiples.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    squeeze = vals.ndim == 1
    if squeeze:
        vals = vals[:, None]
    e, d = vals.shape
    k = num_segments
    # pad to TPU tiling: entries to block_e, segments/features to 128/8
    e_pad = -e % block_e
    if e_pad:
        vals = jnp.pad(vals, ((0, e_pad), (0, 0)))
        ids = jnp.pad(ids, (0, e_pad), constant_values=k)  # out of range
    k_pad = -k % 8
    d_pad = -d % 128
    vals = jnp.pad(vals, ((0, 0), (0, d_pad)))
    n_blocks = vals.shape[0] // block_e
    k_total = k + k_pad
    # ids as (n_blocks, block_e): 2-D blocks match the XLA layout Mosaic
    # expects (1-D s32 operands hit a T(1024)/T(512) tiling mismatch)
    ids2d = ids.astype(jnp.int32).reshape(n_blocks, block_e)

    def kernel(ids_ref, vals_ref, out_ref):
        step = pl.program_id(0)

        @pl.when(step == 0)
        def _init():
            out_ref[:] = jnp.zeros_like(out_ref)

        seg = jax.lax.broadcasted_iota(jnp.int32, (block_e, k_total), 1)
        onehot = (ids_ref[step, :][:, None] == seg).astype(vals_ref.dtype)
        out_ref[:] += jnp.dot(onehot.T, vals_ref[:],
                              preferred_element_type=out_ref.dtype,
                              precision="highest")

    out = pl.pallas_call(
        kernel,
        grid=(n_blocks,),
        in_specs=[
            # whole ids table resident (Mosaic requires sublane-divisible
            # or full blocks); the kernel row-indexes it by step
            pl.BlockSpec((n_blocks, block_e), lambda i: (0, 0)),
            pl.BlockSpec((block_e, vals.shape[1]), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((k_total, vals.shape[1]), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((k_total, vals.shape[1]),
                                       vals.dtype),
    )(ids2d, vals)
    out = out[:k, :d]
    return out[:, 0] if squeeze else out


class SegmentPlan:
    """Host-precomputed layout for the windowed sorted-segment kernel.

    XLA's scatter lowering is the TPU sparse bottleneck (measured 199 ms
    for a 16M->1M sorted segment-sum on v5e, and far worse inside
    ``fori_loop``). This plan turns the scatter into dense one-hot
    algebra: entries are grouped by aligned ``W``-wide output windows and
    padded to 1024-entry subblocks; the kernel keeps the whole output
    resident in a VMEM scratch and, per subblock, builds two small
    one-hots from each id's lane (``id & 127``) and sublane (``id >> 7``)
    halves, contracts them with one (8,128)x(128,128) MXU dot, and
    accumulates the (8,128) window block at a dynamic scratch offset.
    Measured 34 ms standalone (~20 ms fused) for the same 16M->1M merge —
    ~6x over XLA — and it does not degrade inside ``lax.fori_loop``.

    The plan is built once per static id structure (e.g. a sparse
    matrix's rows); runtime value streams must be produced in plan order
    (use :meth:`reorder` on the host-side companion arrays at build
    time). Scratch residency bounds ``num_segments`` to ~2M on a 16 MB
    VMEM part.
    """

    W = 1024          # output window (one (8,128) f32 block)
    EB = 1024         # entries per subblock
    SUB = 8           # subblocks per grid step

    def __init__(self, ids: np.ndarray, num_segments: int):
        ids = np.asarray(ids)
        if ids.ndim != 1:
            raise ValueError("SegmentPlan ids must be 1-D")
        if np.any(np.diff(ids) < 0):
            raise ValueError("SegmentPlan requires sorted ids")
        n = int(num_segments)
        W, EB, SUB = self.W, self.EB, self.SUB
        self.num_segments = n
        self.n_pad = -(-max(n, 1) // W) * W
        n_windows = self.n_pad // W
        # Out-of-range ids are dropped on both ends (matching
        # jax.ops.segment_sum): sorted => negatives are a prefix and
        # ids >= n a suffix, so the valid run is a contiguous slice.
        neg = int(np.searchsorted(ids, 0))
        e = int(np.searchsorted(ids, n))
        ids_v = ids[neg:e].astype(np.int64)
        e -= neg
        wb_all = ids_v // W
        counts = np.bincount(wb_all, minlength=n_windows)
        padded = -(-counts // EB) * EB
        total = int(padded.sum())
        rows_out = self.n_pad // 128
        self.outblk = min(1024, rows_out)
        self.rows_pad = -(-rows_out // self.outblk) * self.outblk
        step = SUB * EB
        total_steps = max(-(-total // step), 1)
        grand = total_steps * step
        starts = np.zeros(n_windows, np.int64)
        starts[1:] = np.cumsum(padded)[:-1]
        src_starts = np.zeros(n_windows, np.int64)
        src_starts[1:] = np.cumsum(counts)[:-1]
        # position of each source entry in the padded stream (vectorized)
        pos = starts[wb_all] + (np.arange(e) - src_starts[wb_all])
        ids_local = np.full(grand, W, np.int32)      # sentinel: no match
        ids_local[pos] = (ids_v - wb_all * W).astype(np.int32)
        self.perm = pos                     # valid entry -> padded slot
        self._lo = neg                      # first valid source index
        self.padded_size = grand
        self.nsteps = total_steps
        wb = np.zeros(grand // EB, np.int32)
        wb[:total // EB] = np.repeat(
            np.arange(n_windows, dtype=np.int32), padded // EB)
        self._ids2d = jnp.asarray(ids_local.reshape(-1, 128))
        self._wb = jnp.asarray(wb)

    def reorder(self, arr: np.ndarray, fill=0) -> np.ndarray:
        """Host-side: lay a per-entry companion array out in plan order."""
        arr = np.asarray(arr)
        out = np.full((self.padded_size,) + arr.shape[1:], fill, arr.dtype)
        out[self.perm] = arr[self._lo:self._lo + self.perm.size]
        return out

    def segment_sum(self, vals: jax.Array) -> jax.Array:
        """Sum a plan-ordered f32 value stream into segments. Traceable
        (usable inside jit / fori_loop / other kernels)."""
        out2d = _windowed_segsum(vals, self._ids2d, self._wb,
                                 rows_pad=self.rows_pad,
                                 nsteps=self.nsteps,
                                 outblk=self.outblk, sub=self.SUB)
        return out2d.reshape(-1)[:self.num_segments]


def _windowed_segsum(vals: jax.Array, ids2d: jax.Array, wb: jax.Array,
                     *, rows_pad: int, nsteps: int, outblk: int,
                     sub: int) -> jax.Array:
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    nout = rows_pad // outblk
    vals2d = vals.astype(jnp.float32).reshape(-1, 128)
    # flush runs on dedicated trailing grid steps AFTER all accumulation
    # steps: every output block is flushed (including a trailing partial
    # one — rows_pad is padded to outblk), and no entry can arrive after
    # its block was written out, regardless of id skew
    grid = nsteps + nout

    def kernel(wb_ref, ids_ref, vals_ref, out_ref, scratch):
        b = pl.program_id(0)

        @pl.when(b == 0)
        def _init():
            scratch[:] = jnp.zeros_like(scratch)

        @pl.when(b < nsteps)
        def _accumulate():
            lane_iota = jax.lax.broadcasted_iota(jnp.int32, (128, 128), 0)
            sub_iota = jax.lax.broadcasted_iota(jnp.int32, (8, 128), 0)
            for j in range(sub):
                acc = jnp.zeros((8, 128), jnp.float32)
                for s in range(8):
                    ids_s = ids_ref[j * 8 + s, :]
                    lo = ids_s & 127
                    hi = ids_s >> 7
                    # entries live on lanes in both one-hots: no relayouts
                    a = (jnp.broadcast_to(lo[None, :], (128, 128))
                         == lane_iota).astype(jnp.float32)   # (lane, entry)
                    bmat = (jnp.broadcast_to(hi[None, :], (8, 128))
                            == sub_iota).astype(jnp.float32)  # (subrow, e)
                    bmat = bmat * vals_ref[j * 8 + s, :][None, :]
                    acc = acc + jax.lax.dot_general(
                        bmat, a, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32,
                        precision=jax.lax.Precision.HIGHEST)
                w = wb_ref[b * sub + j]
                scratch[pl.ds(w * 8, 8), :] += acc

        @pl.when(b >= nsteps)
        def _flush():
            k = jnp.maximum(b - nsteps, 0)
            out_ref[:] = scratch[pl.ds(k * outblk, outblk), :]

    def in_map(b, wb_ref):
        return (jnp.minimum(b, nsteps - 1), 0)

    f = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((sub * 8, 128), in_map),
                pl.BlockSpec((sub * 8, 128), in_map),
            ],
            out_specs=pl.BlockSpec(
                (outblk, 128),
                lambda b, wb_ref: (jnp.maximum(b - nsteps, 0), 0)),
            scratch_shapes=[pltpu.VMEM((rows_pad, 128), jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct((rows_pad, 128), jnp.float32),
        interpret=not _pallas_available(),
    )
    return f(wb, ids2d, vals2d)


def segment_sum(vals: jax.Array, ids: jax.Array, num_segments: int,
                impl: Optional[str] = None,
                sorted_ids: bool = False) -> jax.Array:
    """Sum ``vals`` rows into ``num_segments`` buckets by ``ids``.

    ids outside [0, num_segments) are dropped (XLA segment_sum
    semantics), which the padding paths rely on. ``sorted_ids`` unlocks
    XLA's sorted-scatter fast path (the SparseDistArray invariant)."""
    impl = impl or FLAGS.segment_impl
    if impl == "auto":
        # measured on v5e (1M x 128, k=64): xla scatter 33ms,
        # onehot 67ms, pallas 71ms (highest-precision merges) — XLA's
        # native scatter wins; the matmul paths stay as ablations
        impl = "xla"
    if impl == "pallas":
        if not _pallas_available():
            impl = "onehot"
        else:
            return _segment_sum_pallas(vals, ids, num_segments)
    if impl == "onehot":
        return _segment_sum_onehot(vals, ids, num_segments)
    return _segment_sum_xla(vals, ids, num_segments, sorted_ids)


def segment_count(ids: jax.Array, num_segments: int,
                  dtype=jnp.float32, impl: Optional[str] = None
                  ) -> jax.Array:
    return segment_sum(jnp.ones(ids.shape, dtype), ids, num_segments, impl)
