"""Fused k-means iteration kernel (config 3, BASELINE.json:9).

The expr-level iteration (examples/kmeans.py) lowers to XLA ops that
materialize the (n, k) distance matrix in HBM several times (distance,
argmin, one-hot merge) — measured 18.6 ms/iter at 1M x 128, k=64 on
v5e against a ~1 ms HBM floor (points are read once: 512 MB).

This Pallas kernel streams point blocks through VMEM once per
iteration: per (B, d) block it computes the Gram matrix against the
VMEM-resident centers on the MXU, takes the lane-wise argmin, builds the
assignment one-hot, and accumulates ``one_hot.T @ points`` (MXU) and the
counts into VMEM scratch, flushing (sums | counts) once at the end.
``argmin(d2)`` needs only ``-2 G + |c|^2`` (the point norms are constant
per row), so the distance matrix never exists anywhere.

Constraints: f32 points, d a multiple of 128, k <= 128 (padded centers
get +inf norms so the argmin never selects them), n a multiple of the
block size (drivers pad the point array once). All matmuls run at
HIGHEST precision so assignments match the f32 oracle.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

_BLOCK = 1024
_KPAD = 128


def _available() -> bool:
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def supports(n: int, d: int, k: int) -> bool:
    """Single TPU device only: the pallas_call is not partitionable, so
    on a multi-chip mesh the distributed expr path stays the default."""
    from ..parallel import mesh as mesh_mod

    return (_available() and d % 128 == 0 and 0 < k <= _KPAD
            and n % _BLOCK == 0
            and mesh_mod.device_count(mesh_mod.get_mesh()) == 1)


def assign_accumulate(points: jax.Array, centers: jax.Array, k: int,
                      valid_rows: int | None = None
                      ) -> tuple[jax.Array, jax.Array]:
    """One fused pass: (k, d) cluster sums and (k,) counts.

    ``points`` (n, d) f32 with n % 1024 == 0; ``centers`` (k, d).
    Rows at index >= ``valid_rows`` (driver padding) are masked out of
    the accumulation. Traceable (usable inside fori_loop — the k-means
    driver runs all iterations as one dispatch)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n, d = points.shape
    kpad = _KPAD
    # padded centers: zero rows with +inf norm so argmin skips them
    cpad = jnp.zeros((kpad, d), jnp.float32).at[:k].set(centers)
    cnorm = jnp.full((kpad,), jnp.inf, jnp.float32).at[:k].set(
        jnp.sum(centers * centers, axis=1))
    nsteps = n // _BLOCK
    n_valid = n if valid_rows is None else int(valid_rows)

    def kernel(p_ref, c_ref, cn_ref, sums_ref, cnt_ref, acc, cacc):
        b = pl.program_id(0)

        @pl.when(b == 0)
        def _init():
            acc[:] = jnp.zeros_like(acc)
            cacc[:] = jnp.zeros_like(cacc)

        p = p_ref[:]                                   # (B, d)
        gram = jax.lax.dot_general(
            p, c_ref[:], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)       # (B, kpad)
        score = cn_ref[0, :][None, :] - 2.0 * gram
        assign = jnp.argmin(score, axis=1)             # (B,)
        oh = (assign[:, None] == jax.lax.broadcasted_iota(
            jnp.int32, (_BLOCK, kpad), 1)).astype(jnp.float32)
        if n_valid < n:
            row = (b * _BLOCK
                   + jax.lax.broadcasted_iota(jnp.int32, (_BLOCK, kpad), 0))
            oh = oh * (row < n_valid).astype(jnp.float32)
        acc[:] += jax.lax.dot_general(
            oh, p, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)       # (kpad, d)
        cacc[0, :] += jnp.sum(oh, axis=0)

        @pl.when(b == pl.num_programs(0) - 1)
        def _flush():
            sums_ref[:] = acc[:]
            cnt_ref[:] = cacc[:]

    sums, cnt = pl.pallas_call(
        kernel,
        grid=(nsteps,),
        in_specs=[
            pl.BlockSpec((_BLOCK, d), lambda b: (b, 0)),
            pl.BlockSpec((kpad, d), lambda b: (0, 0)),
            pl.BlockSpec((1, kpad), lambda b: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((kpad, d), lambda b: (0, 0)),
            pl.BlockSpec((1, kpad), lambda b: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((kpad, d), jnp.float32),
            jax.ShapeDtypeStruct((1, kpad), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((kpad, d), jnp.float32),
            pltpu.VMEM((1, kpad), jnp.float32),
        ],
        interpret=not _available(),
    )(points, cpad, cnorm[None, :])
    return sums[:k], cnt[0, :k]


@functools.partial(jax.jit, static_argnames=("k", "valid_rows"))
def step(points: jax.Array, centers: jax.Array, k: int,
         valid_rows: int | None = None) -> jax.Array:
    """One k-means update: new centers from one fused pass."""
    sums, cnt = assign_accumulate(points, centers, k, valid_rows)
    return sums / jnp.maximum(cnt, 1.0)[:, None]


@functools.partial(jax.jit, static_argnames=("k", "valid_rows"))
def run(points: jax.Array, centers: jax.Array, k: int,
        iters: jax.Array, valid_rows: int | None = None) -> jax.Array:
    """All iterations in one dispatch (traced loop bound)."""
    def body(_, c):
        sums, cnt = assign_accumulate(points, c, k, valid_rows)
        return sums / jnp.maximum(cnt, 1.0)[:, None]

    return jax.lax.fori_loop(0, iters, body, centers)
