"""Fused k-means iteration kernel (config 3, BASELINE.json:9).

The kernel itself now lives on the partitionable kernel layer
(``spartan_tpu/kernels/kmeans.py``, docs/KERNELS.md): the seed's
single-device Pallas pass was promoted to a per-shard kernel under
``shard_map`` over the row tiling with a psum merge, so multi-chip
meshes run it too. This module keeps the historical entry points the
drivers and benchmarks import (``supports`` / ``assign_accumulate`` /
``step`` / ``run``); Pallas imports are confined to the kernel layer
(lint rule 12).
"""

from __future__ import annotations

from ..kernels.kmeans import (_BLOCK, _KPAD, assign_accumulate, run,
                              step, supports)

__all__ = ["supports", "assign_accumulate", "step", "run",
           "_BLOCK", "_KPAD"]
