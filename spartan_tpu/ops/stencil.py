"""2-D stencil / pooling ops (``[U] spartan/expr/stencil.py`` [LOW] —
SURVEY.md §2.3: convnet stencil/maxpool in some reference versions).

TPU-native: the stencil is ``lax.conv_general_dilated`` (MXU) and pooling
is ``lax.reduce_window`` (VPU), traced into the consuming jit like any
map — no halo-exchange bookkeeping, GSPMD partitions spatial dims with
halo transfers when the inputs are sharded.
"""

from __future__ import annotations

from typing import Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..expr.base import Expr, as_expr
from ..expr.map2 import map2

Stride = Union[int, Tuple[int, int]]


def _pair(v: Stride) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


def stencil(images, filters, stride: Stride = 1,
            padding: str = "SAME") -> Expr:
    """images (N, H, W, C), filters (KH, KW, C, O) -> (N, H', W', O)."""
    images = as_expr(images)
    filters = as_expr(filters)
    s = _pair(stride)

    def kern(x, w):
        return jax.lax.conv_general_dilated(
            x, w, window_strides=s, padding=padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    return map2([images, filters], kern)


def maxpool(images, window: Stride = 2, stride: Stride = None,
            padding: str = "VALID") -> Expr:
    """images (N, H, W, C) max-pooled over spatial dims."""
    images = as_expr(images)
    w = _pair(window)
    s = _pair(stride) if stride is not None else w

    def kern(x):
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max,
            window_dimensions=(1,) + w + (1,),
            window_strides=(1,) + s + (1,),
            padding=padding)

    return map2([images], kern)


def avgpool(images, window: Stride = 2, stride: Stride = None,
            padding: str = "VALID") -> Expr:
    images = as_expr(images)
    w = _pair(window)
    s = _pair(stride) if stride is not None else w
    denom = float(w[0] * w[1])

    def kern(x):
        summed = jax.lax.reduce_window(
            x, 0.0, jax.lax.add,
            window_dimensions=(1,) + w + (1,),
            window_strides=(1,) + s + (1,),
            padding=padding)
        return summed / denom

    return map2([images], kern)
