"""2-D stencil / pooling ops (``[U] spartan/expr/stencil.py`` [LOW] —
SURVEY.md §2.3: convnet stencil/maxpool in some reference versions).

TPU-native: the stencil is ``lax.conv_general_dilated`` (MXU) and pooling
is ``lax.reduce_window`` (VPU), traced into the consuming jit like any
map. :func:`stencil` now lowers through a dedicated :class:`StencilExpr`
node: when the committed tiling shards the H axis, the kernel layer
(``spartan_tpu/kernels/stencil.py``, docs/KERNELS.md) replaces GSPMD's
generic halo collectives with an explicit ``ppermute`` halo exchange
feeding a blocked Pallas conv kernel; every other case (stride > 1,
non-SAME padding, unsharded spatial dims, non-f32) keeps the traced
conv, where GSPMD partitions spatial dims with its own halo transfers.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple, Union

import jax
import jax.numpy as jnp

from ..array.tiling import Tiling
from ..expr.base import Expr, as_expr, eval_shape_of
from ..expr.map2 import map2

Stride = Union[int, Tuple[int, int]]


def _pair(v: Stride) -> Tuple[int, int]:
    return (v, v) if isinstance(v, int) else tuple(v)


class StencilExpr(Expr):
    """NHWC convolution with a kernel-layer lowering seam.

    ``kernels.select('stencil', ...)`` decides per shape/tiling/
    platform whether this node runs the manual-halo Pallas path or the
    traced ``lax.conv`` (GSPMD halos); ``st.explain`` prints the
    decision and the derived grid for the plan (docs/KERNELS.md)."""

    def __init__(self, x: Expr, w: Expr, stride: Tuple[int, int],
                 padding: str):
        self.x = x
        self.w = w
        self.stride = tuple(int(s) for s in stride)
        self.padding = str(padding)
        out = eval_shape_of(
            lambda xv, wv: self._conv(xv, wv), x, w,
            cache_key=("stencil", self.stride, self.padding))
        super().__init__(out.shape, out.dtype)

    def children(self) -> Tuple[Expr, ...]:
        return (self.x, self.w)

    def replace_children(self, new_children) -> "StencilExpr":
        return StencilExpr(new_children[0], new_children[1],
                           self.stride, self.padding)

    def _conv(self, xv: Any, wv: Any) -> Any:
        return jax.lax.conv_general_dilated(
            xv, wv, window_strides=self.stride, padding=self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def _lower(self, env: Dict[int, Any]) -> Any:
        from ..kernels import registry as kernels_mod

        xv = self.x.lower(env)
        wv = self.w.lower(env)
        sel = kernels_mod.node_selection(self)
        if sel is not None and sel.pallas:
            from ..kernels import stencil as kstencil

            return kstencil.halo_stencil(xv, wv, self.x.out_tiling(),
                                         sel)
        return self._conv(xv, wv)

    def _sig(self, ctx) -> Tuple:
        return ("stencil", self.stride, self.padding,
                ctx.of(self.x), ctx.of(self.w))

    def _default_tiling(self) -> Tiling:
        # batch/H shardings carry through (the halo path preserves
        # them); the W window and output channels stay whole. The plan
        # sanitizes H away when the output height stops dividing.
        tx = self.x.out_tiling()
        return Tiling((tx.axes[0], tx.axes[1], None, None))


def stencil(images, filters, stride: Stride = 1,
            padding: str = "SAME") -> Expr:
    """images (N, H, W, C), filters (KH, KW, C, O) -> (N, H', W', O)."""
    return StencilExpr(as_expr(images), as_expr(filters),
                       _pair(stride), padding)


def maxpool(images, window: Stride = 2, stride: Stride = None,
            padding: str = "VALID") -> Expr:
    """images (N, H, W, C) max-pooled over spatial dims."""
    images = as_expr(images)
    w = _pair(window)
    s = _pair(stride) if stride is not None else w

    def kern(x):
        return jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max,
            window_dimensions=(1,) + w + (1,),
            window_strides=(1,) + s + (1,),
            padding=padding)

    return map2([images], kern)


def avgpool(images, window: Stride = 2, stride: Stride = None,
            padding: str = "VALID") -> Expr:
    images = as_expr(images)
    w = _pair(window)
    s = _pair(stride) if stride is not None else w
    denom = float(w[0] * w[1])

    def kern(x):
        summed = jax.lax.reduce_window(
            x, 0.0, jax.lax.add,
            window_dimensions=(1,) + w + (1,),
            window_strides=(1,) + s + (1,),
            padding=padding)
        return summed / denom

    return map2([images], kern)
