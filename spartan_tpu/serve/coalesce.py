"""Signature-level request coalescing: many clients, one program.

Requests whose one-traversal RAW-DAG signature matches (the plan-cache
key, ``expr/base.plan_signature``) within the batching window share one
cached plan and are batched along a NEW LEADING CLIENT AXIS at the
leaves — the DrJAX vmap-over-clients construction: one compile, one
dispatch, N responses. Two batching modes:

* ``vmap`` (default) — the plan's traced function is ``jax.vmap``-ed
  over the stacked leaves; XLA sees one batched program (elementwise
  chains become one wider kernel, matmuls one batched contraction) and
  GSPMD shards the per-client program exactly as the solo plan did.
* ``unroll`` — the traced function is replayed per client inside ONE
  jitted program (bit-identical to solo by construction). The
  automatic fallback when a plan's lowering cannot be vmapped (e.g. a
  ``shard_map`` kernel without a batching rule): a DETERMINISTIC
  failure of the vmap variant demotes the plan to ``unroll``, and a
  second deterministic failure disables coalescing for that plan.

Either way the batch is split back into per-client outputs INSIDE the
jitted program, so one dispatch produces N separate result buffers and
no per-client slice dispatches are paid on the host.

The batch size and mode are keyed into the compile cache
(``plan.key + ('serve', B, mode)``) so coalesced and solo executables
never collide, and the batch is recorded on the plan report — a
cache-hit ``st.explain`` names the coalesced batch.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp

from ..expr import base
from ..obs import numerics as numerics_mod
from ..obs.explain import key_hash
from ..obs.metrics import METRICS_FLAG as _METRICS_FLAG
from ..obs.metrics import REGISTRY
from ..resilience import classify as cls
from ..resilience import faults as faults_mod
from ..utils import profiling as prof
from ..utils.config import FLAGS

FLAGS.define_str(
    "serve_coalesce_mode", "vmap",
    "Leading-axis batching mode for coalesced requests: 'vmap' (one "
    "batched program; auto-demotes per plan to 'unroll' on a "
    "deterministic vmap failure) or 'unroll' (the traced function "
    "replayed per client inside one jitted program; bit-identical to "
    "solo by construction).")

# per-plan mode overrides learned from deterministic batch failures:
# plan.key -> 'unroll' | 'off'. Guarded by its own lock; never held
# while compiling or dispatching.
_mode_lock = threading.Lock()
_mode_override: Dict[Tuple, str] = {}


def reset_modes() -> None:
    """Forget learned per-plan demotions (test isolation)."""
    with _mode_lock:
        _mode_override.clear()


def mode_for(plan: Any) -> str:
    """'vmap' / 'unroll' / 'off' for this plan."""
    with _mode_lock:
        override = _mode_override.get(plan.key)
    if override is not None:
        return override
    mode = FLAGS.serve_coalesce_mode
    return mode if mode in ("vmap", "unroll") else "vmap"


def demote(plan: Any) -> str:
    """Walk the plan one rung down after a deterministic batched
    failure: vmap -> unroll -> off. Returns the new mode."""
    with _mode_lock:
        cur = _mode_override.get(plan.key)
        if cur is None and FLAGS.serve_coalesce_mode == "unroll":
            cur = "unroll"
        new = "unroll" if cur is None else "off"
        _mode_override[plan.key] = new
    return new


def _make_batched(traced: Callable, B: int, nargs: int, mode: str,
                  shared: Tuple[bool, ...]) -> Callable:
    """The batched traced function. ``shared[j]`` marks an argument
    position where every request passes the IDENTICAL buffer (common:
    requests over the same model/dataset arrays differing only in
    per-request inputs); those are passed ONCE and vmapped with
    ``in_axes=None`` — the flat argument list is position-major, one
    entry for a shared position, B entries otherwise. Deduplication is
    the difference between a batched call whose host-side argument
    processing costs B× the solo call (measured: jit-call overhead is
    linear in argument count) and one that amortizes; it also stops
    the program physically broadcasting a shared leaf into a B-times
    larger device buffer every dispatch. Returns a B-tuple of
    per-request outputs — the split happens inside the program, so the
    host sees N result buffers from one dispatch."""

    def columns(flat: Any) -> List[Any]:
        cols: List[Any] = []
        i = 0
        for j in range(nargs):
            if shared[j]:
                cols.append(flat[i])
                i += 1
            else:
                cols.append(list(flat[i:i + B]))
                i += B
        return cols

    if mode == "vmap":

        def batched(*flat: Any) -> Tuple[Any, ...]:
            cols = columns(flat)
            if all(shared):
                # degenerate batch: every request is the same
                # computation — run it once, share the result buffers
                outs = traced(*cols)
                return (outs,) * B
            in_axes = tuple(None if s else 0 for s in shared)
            stacked = [c if s else jnp.stack(c)
                       for s, c in zip(shared, cols)]
            outs = jax.vmap(traced, in_axes=in_axes)(*stacked)
            return tuple(
                jax.tree_util.tree_map(lambda o, i=i: o[i], outs)
                for i in range(B))

        return batched

    def unrolled(*flat: Any) -> Tuple[Any, ...]:
        cols = columns(flat)
        return tuple(
            traced(*[c if s else c[i]
                     for s, c in zip(shared, cols)])
            for i in range(B))

    return unrolled


def dispatch_batch(plan: Any, requests: List[Any], mesh) -> List[Any]:
    """One coalesced dispatch for ``requests`` (all sharing
    ``plan``'s signature): gather each request's leaves, run the
    batched executable, wrap each request's outputs and seed its
    expr's result cache. Raises on failure — the engine falls back to
    solo dispatches (where the resilience policy engine handles
    classification, per-tenant budgets and retries)."""
    B = len(requests)
    order = plan.arg_order
    nargs = len(order)
    mode = mode_for(plan)
    if mode == "off":
        raise RuntimeError("coalescing disabled for this plan")

    with prof.phase("build"):
        per_req: List[List[Any]] = []
        for r in requests:
            args, _darrs, dpos = base._gather_args(r.leaves, order, [])
            if dpos:  # engine routing bug: donating requests are solo
                raise RuntimeError(
                    "donating request reached the coalescer")
            per_req.append(args)
        first = per_req[0]
        shared = tuple(
            all(a[j] is first[j] for a in per_req[1:])
            for j in range(nargs))
        flat: List[Any] = []
        for j in range(nargs):
            if shared[j]:
                flat.append(first[j])
            else:
                flat.extend(a[j] for a in per_req)

    # the dedup pattern is part of the executable: a batch where a
    # position stops being shared compiles (and caches) its own variant
    ex = base.cached_executable(
        plan.key + ("serve", B, mode, shared),
        lambda: jax.jit(
            _make_batched(plan.traced, B, nargs, mode, shared)))

    fresh = not ex.warm
    phase_name = "compile" if fresh else "dispatch"
    with prof.span("serve_batch", batch=B, mode=mode,
                   plan=key_hash(plan.key)):
        with prof.phase(phase_name):
            # same watchdog + chaos seams as expr/base._dispatch: a
            # hung batched dispatch dumps in-flight forensics, and an
            # installed chaos plan injects BEFORE the executable runs
            with numerics_mod.watchdog(phase_name, plan.report):
                if faults_mod._ACTIVE is not None:
                    faults_mod.fire(phase_name)
                # same launch serialization as base._dispatch: XLA:CPU
                # collectives deadlock under concurrent launches
                with base.launch_guard():
                    outs = ex.jitted(*flat)
    ex.warm = True

    with prof.phase("build"):  # ONE timed phase for the whole batch
        results = [base._wrap_result(r.expr, plan, o, [], [], mesh,
                                     timed=False)
                   for r, o in zip(requests, outs)]

    # metrics + plan-report annotation: coalesced requests count as
    # plan hits (the plan WAS reused) so hit-rate views stay honest
    prof.count("evaluations", B)
    prof.count("plan_hits", B)
    if _METRICS_FLAG._value:
        REGISTRY.counter(
            "serve_coalesced_requests",
            "requests served through a coalesced batch").inc(B)
        REGISTRY.counter(
            "serve_coalesced_batches",
            "coalesced batched dispatches").inc()
        REGISTRY.histogram(
            "serve:batch_size",
            "clients per coalesced dispatch").observe(float(B))
    if plan.report is not None:
        sv = plan.report.setdefault(
            "serve", {"batches": 0, "requests": 0, "last_batch": None,
                      "mode": mode})
        sv["batches"] += 1
        sv["requests"] += B
        sv["last_batch"] = B
        sv["mode"] = mode
    return results


def classify_batch_failure(exc: BaseException, plan: Any) -> str:
    """Engine hook after a failed batched dispatch: deterministic
    failures demote the plan's batching mode (a vmap that cannot trace
    will never trace); transient/oom/io leave the mode alone — the
    solo fallback's resilience engine owns those."""
    kind = cls.classify(exc)
    if kind == cls.DETERMINISTIC:
        new = demote(plan)
        if _METRICS_FLAG._value:
            REGISTRY.counter(
                "serve_mode_demotions",
                "plans demoted vmap->unroll->off after deterministic "
                "batched failures").inc()
        return new
    return mode_for(plan)
