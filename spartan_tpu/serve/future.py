"""Request futures + the serving error vocabulary.

An :class:`EvalFuture` is the handle ``evaluate_async`` returns: the
submitting thread gets it immediately, a serve worker resolves it after
the (possibly coalesced) dispatch. Resolution happens at dispatch
completion — JAX execution is asynchronous, so the resolved
``DistArray`` is an in-flight device handle and only a fetch
(``.glom()``) blocks on the actual computation; donated input buffers
are invalidated at the same resolution point (the serving analogue of
``evaluate()``'s dispatch epilogue).

Thread-safety: one ``threading.Event`` per future; ``_resolve`` /
``_reject`` are called exactly once by the owning worker (double
resolution is ignored, first writer wins), callbacks run on the
resolving thread.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional


class ServeError(RuntimeError):
    """Base class for serving-layer failures."""


class Backpressure(ServeError):
    """Admission control rejected the request: the submission queue is
    past its high-water mark. ``retry_after_s`` is the engine's
    estimate of when capacity frees up (queue depth x recent service
    time per worker) — the reject-with-retry-after contract clients
    are expected to honor instead of hammering the queue."""

    def __init__(self, depth: int, retry_after_s: float):
        super().__init__(
            f"serve queue full ({depth} request(s) queued); "
            f"retry after ~{retry_after_s:.3f}s")
        self.depth = depth
        self.retry_after_s = retry_after_s


class DeadlineExceeded(ServeError):
    """The request's deadline expired before its dispatch started (it
    was shed from the queue) or before its result resolved."""


class CommBudgetExceeded(ServeError):
    """Admission control rejected the request because its plan's
    audited communication total (analysis/plan_audit.py, cached on the
    plan report) exceeds ``FLAGS.comm_budget_bytes``. NOT retryable —
    resubmitting the same expression meets the same plan; restructure
    the computation (or raise the budget). The finding lands in the
    flight record (``st.flightrec``) with the modeled bytes."""

    def __init__(self, comm_bytes: float, budget_bytes: int,
                 detail: str = ""):
        super().__init__(
            f"plan's modeled communication ~{comm_bytes:.0f} bytes/chip "
            f"exceeds FLAGS.comm_budget_bytes={budget_bytes}"
            + (f" ({detail})" if detail else ""))
        self.comm_bytes = comm_bytes
        self.budget_bytes = budget_bytes


class MeshReconfiguring(ServeError):
    """The mesh is being rebuilt after persistent device/host loss
    (elastic recovery): this request was drained, or arrived during
    the drain, and was NOT dispatched. Retryable — resubmit after
    ``retry_after_s``; the rebuild is host-side work, so the engine is
    admitting again almost immediately, with plans re-built for the
    surviving devices. Inputs that lived on the dead mesh must be
    re-created (or ``.rehome()``d) before resubmitting — a stale
    resubmission fails with ``StaleMeshError`` naming them."""

    def __init__(self, retry_after_s: float, detail: str = ""):
        super().__init__(
            "mesh reconfiguring after device loss; retry after "
            f"~{retry_after_s:.3f}s" + (f" ({detail})" if detail else ""))
        self.retry_after_s = retry_after_s


class EvalFuture:
    """Resolution handle for one submitted evaluation.

    ``result(timeout)`` blocks until the worker resolves the future and
    returns the ``DistArray`` (or tuple, for ``TupleExpr`` roots) — or
    raises the failure the evaluation produced (after the resilience
    engine's retries ran their course). ``glom(timeout)`` additionally
    fetches to the host, which is where asynchronous device execution
    is actually awaited."""

    __slots__ = ("_event", "_result", "_exc", "_callbacks", "_lock",
                 "tenant", "coalesced", "t_submit", "t_resolved", "rid")

    def __init__(self, tenant: Optional[str] = None):
        self._event = threading.Event()
        self._result: Any = None
        self._exc: Optional[BaseException] = None
        self._callbacks: List[Callable[["EvalFuture"], None]] = []
        self._lock = threading.Lock()
        self.tenant = tenant
        # set by the worker: how many requests shared this dispatch
        # (1 = solo); observability for tests and clients
        self.coalesced = 0
        # engine-stamped tracer-clock timestamps (obs.trace.now):
        # t_resolved - t_submit is the request's serving latency
        self.t_submit: float = 0.0
        self.t_resolved: float = 0.0
        # flight-recorder request id (obs/flight.py), minted at submit
        # and shared with every event of this request's lifecycle;
        # 0 = not a recorded request (bare futures)
        self.rid: int = 0

    # -- caller side ----------------------------------------------------

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"EvalFuture.result timed out after {timeout}s")
        if self._exc is not None:
            raise self._exc
        return self._result

    def exception(self, timeout: Optional[float] = None
                  ) -> Optional[BaseException]:
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"EvalFuture.exception timed out after {timeout}s")
        return self._exc

    def glom(self, timeout: Optional[float] = None) -> Any:
        """Resolve AND fetch: the one call that blocks on device
        execution (``result()`` returns an async array handle). The
        fetch wall time is the last hop of this request's flight
        record (per-tenant ``serve_fetch_s`` histogram)."""
        out = self.result(timeout)
        from ..obs import flight as flight_mod
        from ..obs import trace as trace_mod

        t0 = trace_mod.now()
        if isinstance(out, tuple):
            fetched: Any = tuple(o.glom() for o in out)
        else:
            fetched = out.glom()
        flight_mod.note_fetch(self.rid, self.tenant,
                              trace_mod.now() - t0)
        return fetched

    def add_done_callback(self, fn: Callable[["EvalFuture"], None]
                          ) -> None:
        """Run ``fn(self)`` when the future resolves (immediately if it
        already has). Runs on the resolving worker thread; exceptions
        from callbacks are swallowed (a client callback must not kill
        a worker)."""
        run_now = False
        with self._lock:
            if self._event.is_set():
                run_now = True
            else:
                self._callbacks.append(fn)
        if run_now:
            try:
                fn(self)
            except Exception:
                pass

    # -- worker side ----------------------------------------------------

    def _fire_callbacks(self) -> None:
        with self._lock:
            cbs, self._callbacks = self._callbacks, []
        for fn in cbs:
            try:
                fn(self)
            except Exception:
                pass  # client callbacks must not kill the worker

    def _stamp(self) -> None:
        from ..obs import trace as trace_mod

        self.t_resolved = trace_mod.now()

    def _resolve(self, result: Any) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._result = result
            self._stamp()
            self._event.set()
        self._fire_callbacks()

    def _reject(self, exc: BaseException) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._exc = exc
            self._stamp()
            self._event.set()
        self._fire_callbacks()
