"""The concurrent serving engine: workers, batching window, dispatch.

One :class:`ServeEngine` owns a bounded :class:`AdmissionQueue` and a
small pool of worker threads. The request lifecycle:

1. **submit** (caller thread): normalize donation, capture the ambient
   mesh, sign the raw DAG once (``base.plan_signature`` — the same
   traversal ``evaluate()`` would do), then enqueue. Admission past
   the high-water mark raises ``Backpressure(retry_after_s=...)``
   instead of queueing unbounded latency; with an HBM budget known
   (predictive memory governor, docs/MEMORY.md) a submission whose
   predicted peak cannot fit next to the in-flight memory
   reservations is rejected the same way.
2. **batch** (worker): pop the head request, pull every queued request
   with the same plan signature, linger one batching window
   (``FLAGS.serve_batch_window_s``) for stragglers, and re-pull.
3. **dispatch**: a batch of one (or a donating / uncacheable-plan /
   unknown-plan request) goes through plain ``evaluate()`` under the
   request's tenant scope + deadline scope; a batch of N goes through
   the coalescer (one compile, one dispatch, N responses). A failed
   coalesced dispatch falls back to solo dispatches, where the
   resilience policy engine applies classification, per-tenant retry
   budgets and backoff per request.
4. **resolve**: each request's future resolves with its DistArray
   (device execution may still be in flight — fetch blocks); donated
   buffers were invalidated by the dispatch epilogue.

Deadlines: a request whose deadline expires in the queue is shed with
``DeadlineExceeded`` (never dispatched); the remaining time of a live
request propagates into the PR-4 dispatch watchdog
(``obs/numerics.deadline_scope``), so a dispatch that would blow the
deadline dumps in-flight forensics.

Tenancy: ``tenant=`` labels flow into per-tenant metrics
(``serve_requests{tenant="..."}`` in the Prometheus export) and into
the resilience engine's per-tenant retry accounts
(``engine.tenant_scope``).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

from .. import persist as persist_mod
from ..expr import base
from ..obs import flight as flight_mod
from ..obs import ledger as ledger_mod
from ..obs import numerics as numerics_mod
from ..obs import profile as profile_mod
from ..obs import skew as skew_mod
from ..obs import trace as trace_mod
from ..obs.explain import key_hash
from ..obs import slo as slo_mod
from ..obs.metrics import METRICS_FLAG as _METRICS_FLAG
from ..obs.metrics import REGISTRY, labeled
from ..parallel import mesh as mesh_mod
from ..resilience import engine as resilience_engine
from ..resilience import integrity as integrity_mod
from ..resilience import memory as memory_mod
from ..utils import profiling as prof
from ..utils.config import FLAGS
from ..utils.log import log_warn
from ..resilience import classify as resilience_classify
from . import coalesce
from .future import (Backpressure, CommBudgetExceeded, DeadlineExceeded,
                     EvalFuture, MeshReconfiguring)
from .queue import AdmissionQueue


def _sdc_in_chain(e: Optional[BaseException]) -> bool:
    """True when this failure originated in an integrity violation:
    either it IS the sentinel's IntegrityError (class 'sdc'), or it is
    the StaleMeshError the policy engine's post-quarantine retry
    surfaced while handling one (implicit exception chaining keeps the
    IntegrityError on __context__)."""
    seen = 0
    while e is not None and seen < 8:
        if resilience_classify.classify(e) == resilience_classify.SDC:
            return True
        e = e.__cause__ or e.__context__
        seen += 1
    return False

FLAGS.define_int(
    "serve_workers", 2,
    "Worker threads in the default serve engine's dispatch pool.")
FLAGS.define_int(
    "serve_queue_max", 1024,
    "Admission-control high-water mark: submissions past this queue "
    "depth are rejected with Backpressure(retry_after_s=...) instead "
    "of queueing unbounded latency.")
FLAGS.define_float(
    "serve_batch_window_s", 0.002,
    "Coalescing linger: after popping a request, a worker waits up to "
    "this long for more identical-signature submissions before "
    "dispatching the batch. 0 = dispatch immediately (coalesce only "
    "what is already queued).")
FLAGS.define_int(
    "serve_max_batch", 32,
    "Maximum clients coalesced into one batched dispatch (the batch "
    "size is part of the compile-cache key; a new size compiles a new "
    "variant).")
FLAGS.define_bool(
    "serve_coalesce", True,
    "Coalesce identical-signature requests into leading-axis batched "
    "dispatches (one compile, one dispatch, N responses). Off = every "
    "request dispatches solo (still async, still admission-controlled).")
_MODEL_PRICING_FLAG = FLAGS.define_bool(
    "serve_model_pricing", True,
    "Price service-time predictions (deadline shedding, the ledger's "
    "service rows) with the calibrated cost model "
    "(ledger.predict_service_s: the plan's DP cost through the warmed "
    "seconds-per-cost-unit scale) instead of the raw queue EMA. Falls "
    "back to the EMA per request until the scale warms or when the "
    "plan has no priced entry.")
_COMM_BUDGET_FLAG = FLAGS.define_int(
    "comm_budget_bytes", 0,
    "Communication-aware admission: when > 0, a submission whose plan "
    "carries an audit verdict (analysis/plan_audit.py — the compile "
    "miss ran under FLAGS.verify_evaluate, or st.audit_plan was "
    "called) with modeled per-chip wire bytes above this budget is "
    "rejected with CommBudgetExceeded and the finding in its flight "
    "record. 0 = off (one flag read per submit).")


def _pow2_chunks(batch: List["_Request"]) -> List[List["_Request"]]:
    """Split a batch into largest-power-of-two-first chunks."""
    out: List[List["_Request"]] = []
    i = 0
    while i < len(batch):
        size = 1 << ((len(batch) - i).bit_length() - 1)
        out.append(batch[i:i + size])
        i += size
    return out


class _MemoryLedger:
    """In-flight memory reservations (the admission tier of the
    predictive memory governor, docs/MEMORY.md): each dispatch
    reserves its predicted per-chip peak when a worker picks it up and
    releases it at future resolution, so ``submit`` can reject
    combinations of requests whose modeled working sets cannot fit in
    HBM together — with a retryable ``Backpressure`` instead of a
    device OOM that trips the whole engine. One leaf lock; never held
    while dispatching."""

    __slots__ = ("_lock", "_reserved")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._reserved = 0

    def reserved(self) -> int:
        return self._reserved

    def reserve(self, n: int) -> None:
        if n <= 0:
            return
        with self._lock:
            self._reserved += n
            now = self._reserved
        if _METRICS_FLAG._value:
            REGISTRY.gauge(
                "serve_mem_reserved_bytes",
                "predicted per-chip bytes reserved by in-flight serve "
                "dispatches (high-water tracked)").set(float(now))

    def release(self, n: int) -> None:
        if n <= 0:
            return
        with self._lock:
            self._reserved = max(0, self._reserved - n)
            now = self._reserved
        if _METRICS_FLAG._value:
            REGISTRY.gauge(
                "serve_mem_reserved_bytes",
                "predicted per-chip bytes reserved by in-flight serve "
                "dispatches (high-water tracked)").set(float(now))


class _Request:
    """One queued evaluation. Signed at submit time (caller thread) so
    workers can group by plan signature without re-traversing. Minted
    with a flight-recorder request id (obs/flight.py) that every
    lifecycle event — queue, coalesce, dispatch, resolve, fetch —
    carries; ``t_taken``/``t_dispatch`` stamps feed the per-tenant
    latency decomposition."""

    __slots__ = ("expr", "donate", "tenant", "deadline", "future",
                 "plan_key", "leaves", "mesh", "coalescable",
                 "t_submit", "taken", "mem_bytes", "rid", "t_taken",
                 "t_dispatch", "via")

    def __init__(self, expr: Any, donate: List[Any],
                 tenant: Optional[str], deadline_s: Optional[float],
                 mesh) -> None:
        self.expr = expr
        self.donate = donate
        self.tenant = tenant
        self.t_submit = trace_mod.now()
        self.deadline = (self.t_submit + deadline_s
                         if deadline_s is not None else None)
        self.future = EvalFuture(tenant)
        self.future.t_submit = self.t_submit
        self.mesh = mesh
        self.taken = False  # queue bookkeeping (AdmissionQueue)
        self.mem_bytes = 0  # predicted peak (memory-aware admission)
        self.plan_key, sig_ctx = base.plan_signature(expr, mesh)
        self.leaves = sig_ctx.leaves
        # donating requests never coalesce: buffer aliasing is a
        # per-dispatch contract the batched program cannot honor
        self.coalescable = (not donate and not any(
            arr is not None and arr._donate_next
            for arr in (base._leaf_array(l) for l in self.leaves)))
        self.rid = flight_mod.mint_rid()
        self.t_taken = 0.0
        self.t_dispatch = 0.0
        self.via = "head"  # how a batch got this request (flight rec)
        self.future.rid = self.rid
        if flight_mod._FLIGHT_FLAG._value:
            flight_mod.note(self.rid, "submit", tenant=tenant,
                            plan=key_hash(self.plan_key))

    def remaining_s(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return self.deadline - trace_mod.now()


class ServeEngine:
    """A worker pool + admission queue + coalescer. Usable as a
    context manager; ``stop()`` drains (rejects) the backlog."""

    def __init__(self, workers: Optional[int] = None,
                 queue_max: Optional[int] = None,
                 batch_window_s: Optional[float] = None,
                 max_batch: Optional[int] = None,
                 coalesce_requests: Optional[bool] = None):
        self.workers = int(workers if workers is not None
                           else FLAGS.serve_workers)
        self.batch_window_s = float(
            batch_window_s if batch_window_s is not None
            else FLAGS.serve_batch_window_s)
        self.max_batch = int(max_batch if max_batch is not None
                             else FLAGS.serve_max_batch)
        self.coalesce_requests = bool(
            coalesce_requests if coalesce_requests is not None
            else FLAGS.serve_coalesce)
        self.queue = AdmissionQueue(
            queue_max if queue_max is not None else FLAGS.serve_queue_max)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._lock = threading.Lock()
        # in-flight memory reservations (predictive governor tier 3)
        self.ledger = _MemoryLedger()
        # elastic recovery gate: while the mesh rebuilds, submissions
        # fail fast with MeshReconfiguring(retry_after_s=this value)
        # instead of queueing onto a dead mesh. None = admitting.
        self._reconfiguring: Optional[float] = None

    # -- lifecycle ------------------------------------------------------

    @property
    def running(self) -> bool:
        return bool(self._threads) and not self._stop.is_set()

    def start(self) -> "ServeEngine":
        with self._lock:
            if self._threads:
                return self
            self._stop.clear()
            self.queue.reopen()
            for i in range(max(1, self.workers)):
                t = threading.Thread(
                    target=self._worker, name=f"spartan-serve-{i}",
                    daemon=True)
                t.start()
                self._threads.append(t)
        return self

    def stop(self, timeout: float = 5.0) -> None:
        with self._lock:
            threads, self._threads = self._threads, []
        self._stop.set()
        self.queue.close()  # wakes idle workers blocked on the CV
        for r in self.queue.drain():
            flight_mod.note(r.rid, "drain", reason="stop")
            r.future._reject(RuntimeError("serve engine stopped"))
        for t in threads:
            t.join(timeout)

    def __enter__(self) -> "ServeEngine":
        return self.start()

    def __exit__(self, *exc: Any) -> None:
        self.stop()

    # -- warm start (spartan_tpu/persist, docs/WARMSTART.md) ------------

    def prewarm(self, manifest: Any = "all",
                timeout_s: Optional[float] = None) -> Dict[str, Any]:
        """Restore a configured plan set from the warm-start store at
        startup, OFF the request path: entries land in the store's
        in-memory prewarm table, so the first request for each plan
        pays neither XLA compile nor disk IO/deserialize.

        ``manifest``: a path to a JSON ``{"entries": [digest, ...]}``
        file (see ``persist.write_manifest`` — the rolling-restart
        runbook's capture step), the dict/list itself, or ``"all"``
        (every entry in the store). Per-entry timeout
        (``timeout_s`` / ``FLAGS.persist_prewarm_timeout_s``) + error
        isolation: a missing, corrupt or slow entry is counted
        (``persist_prewarm_*`` metrics) and skipped — prewarm can
        never crash or stall engine startup indefinitely. No-op with
        the store off. Returns ``{loaded, missing, errors, total}``."""
        stats = persist_mod.prewarm(manifest, timeout_s)
        if _METRICS_FLAG._value:
            REGISTRY.gauge(
                "persist_prewarmed_plans",
                "plans resident in the warm-start prewarm table"
            ).set(float(persist_mod.stats().get("preloaded", 0)))
        return stats

    # -- elastic recovery (resilience/elastic.py) -----------------------

    def drain_reconfiguring(self, retry_after_s: float) -> int:
        """Stop admitting and fail the queued backlog with a retryable
        :class:`MeshReconfiguring` — called by elastic recovery before
        the mesh rebuild so nothing else dispatches onto the dead
        mesh. Workers stay up (their in-flight failures are mapped to
        MeshReconfiguring by ``_solo``); ``resume_admission`` reopens
        the door after the rebuild. Returns requests drained.

        Re-entrant: a recovery interrupted by a mid-recovery fault
        (the chaos ``recover`` seam) re-drains on its next attempt —
        draining an already-draining engine just empties whatever
        queued since."""
        self._reconfiguring = float(retry_after_s)
        drained = self.queue.drain()
        for r in drained:
            flight_mod.note(r.rid, "drain", reason="reconfiguring")
            r.future._reject(MeshReconfiguring(
                retry_after_s, "request drained before dispatch"))
        if drained and _METRICS_FLAG._value:
            REGISTRY.counter(
                "serve_mesh_drained",
                "queued requests drained by elastic mesh "
                "recovery").inc(len(drained))
        return len(drained)

    def resume_admission(self) -> None:
        """Reopen admission after the mesh rebuild completed.
        Idempotent — the finish tail of an interrupted recovery calls
        it again; reopening an open door is a no-op."""
        if self._reconfiguring is None:
            return
        self._reconfiguring = None
        trace_mod.instant("serve_admission_reopened")
        if _METRICS_FLAG._value:
            REGISTRY.counter(
                "serve_admission_reopened",
                "admission reopenings after elastic recovery").inc()

    # -- submission -----------------------------------------------------

    def submit(self, expr: Any, donate: Sequence[Any] = (),
               tenant: Optional[str] = None,
               deadline_s: Optional[float] = None) -> EvalFuture:
        """Admit one evaluation; returns its future immediately.
        Raises :class:`Backpressure` past the queue's high-water mark."""
        expr = base.as_expr(expr)
        gate = self._reconfiguring
        if gate is not None:
            raise MeshReconfiguring(gate, "admission paused")
        if _METRICS_FLAG._value:
            REGISTRY.counter(
                "serve_requests", "requests submitted to the serve "
                "engine").inc()
            if tenant:
                REGISTRY.counter(
                    labeled("serve_requests", tenant=tenant),
                    "per-tenant submissions").inc()
        if expr._result is not None:  # already evaluated: no dispatch
            fut = EvalFuture(tenant)
            fut.t_submit = trace_mod.now()
            fut._resolve(expr._result)
            return fut
        donated = base._norm_donate(donate)
        req = _Request(expr, donated, tenant, deadline_s,
                       mesh_mod.get_mesh())
        # SLO-class admission (obs/slo.py, docs/SERVING.md): a class
        # with a queue share below 1.0 may only occupy that fraction
        # of the admission queue — a bulk class cannot queue the
        # latency class out. Same retryable Backpressure contract as
        # depth shedding. One memoized-parse check when no classes
        # are configured.
        cls = slo_mod.class_for(tenant)
        if cls is not None and cls.share < 1.0:
            cap = max(1, int(self.queue.maxsize * cls.share))
            if self.queue.depth() >= cap:
                if _METRICS_FLAG._value:
                    REGISTRY.counter(
                        labeled("serve_slo_rejected",
                                slo_class=cls.name),
                        "submissions shed because their SLO class's "
                        "queue share was exhausted").inc()
                flight_mod.note(req.rid, "reject",
                                reason="slo_admission",
                                slo_class=cls.name, share=cls.share)
                raise Backpressure(
                    self.queue.depth(),
                    self.queue.retry_after_s(self.workers))
        # memory-aware admission (docs/MEMORY.md): when a budget is
        # known, a submission whose predicted peak cannot fit next to
        # the in-flight reservations is rejected with the SAME
        # retryable Backpressure contract as queue-depth shedding —
        # the client backs off instead of the whole engine OOMing.
        budget = (memory_mod.hbm_budget_bytes()
                  if memory_mod._GOVERNOR_FLAG._value else None)
        if budget:
            req.mem_bytes = memory_mod.request_bytes(
                base.lookup_plan(req.plan_key), req.leaves, req.mesh)
            if req.mem_bytes + self.ledger.reserved() > budget:
                if _METRICS_FLAG._value:
                    REGISTRY.counter(
                        "serve_mem_rejected",
                        "submissions shed because their predicted "
                        "peak would overflow the HBM budget").inc()
                flight_mod.note(req.rid, "reject", reason="memory")
                raise Backpressure(
                    self.queue.depth(),
                    self.queue.retry_after_s(self.workers))
        # communication-aware admission (docs/ANALYSIS.md): a plan
        # whose AUDITED wire total exceeds the budget is rejected
        # before it queues — non-retryable (the same expr meets the
        # same plan), with the worst finding in the flight record.
        # Unaudited plans pass: the budget gates verdicts, it does not
        # force an AOT compile onto the submit path.
        comm_budget = _COMM_BUDGET_FLAG._value
        if comm_budget:
            plan = base.lookup_plan(req.plan_key)
            verdict = (plan.report.get("audit")
                       if plan is not None and plan.report is not None
                       else None)
            if verdict and verdict.get("comm_bytes", 0.0) > comm_budget:
                if _METRICS_FLAG._value:
                    REGISTRY.counter(
                        "serve_comm_rejected",
                        "submissions rejected because their plan's "
                        "audited communication exceeds "
                        "FLAGS.comm_budget_bytes").inc()
                worst = max(
                    verdict.get("collectives") or [{}],
                    key=lambda c: c.get("bytes_moved", 0.0))
                finding = (f"{worst.get('kind', '?')} on "
                           f"{worst.get('node') or '<unattributed>'} "
                           f"~{worst.get('bytes_moved', 0.0):.0f}B/chip")
                flight_mod.note(
                    req.rid, "reject", reason="comm_budget",
                    comm_bytes=verdict.get("comm_bytes"),
                    budget_bytes=comm_budget, finding=finding)
                raise CommBudgetExceeded(
                    float(verdict.get("comm_bytes", 0.0)), comm_budget,
                    finding)
        if not self.running:
            self.start()
        try:
            self.queue.put(req, workers=self.workers)
        except Backpressure:
            flight_mod.note(req.rid, "reject", reason="backpressure")
            raise
        flight_mod.note(req.rid, "enqueue", depth=self.queue.depth())
        return req.future

    def stats(self) -> Dict[str, Any]:
        c = REGISTRY.counter_values()
        total = c.get("serve_requests", 0)
        coal = c.get("serve_coalesced_requests", 0)
        return {
            "queue_depth": self.queue.depth(),
            "mem_reserved_bytes": self.ledger.reserved(),
            "mem_rejected": c.get("serve_mem_rejected", 0),
            "requests": total,
            "coalesced_requests": coal,
            "coalesced_batches": c.get("serve_coalesced_batches", 0),
            "rejected": c.get("serve_rejected", 0),
            "deadline_expired": c.get("serve_deadline_expired", 0),
            "solo_fallbacks": c.get("serve_solo_fallbacks", 0),
            "coalesce_hit_ratio": (coal / total) if total else 0.0,
        }

    # -- worker side ----------------------------------------------------

    def _worker(self) -> None:
        while not self._stop.is_set():
            # blocking pop: an idle worker parks on the queue's CV and
            # costs zero CPU until a put or close() wakes it
            req = self.queue.pop()
            if req is None:
                continue
            req.t_taken = trace_mod.now()
            # the service-time PREDICTION for this request: the
            # calibrated model's price for this plan when it has one
            # (FLAGS.serve_model_pricing), else the queue EMA as of
            # pop — exactly what a Backpressure retry-after would have
            # quoted; the cost ledger pairs it with the measured
            # service below either way, so the monitor's drift
            # detector judges whichever predictor actually ran
            predicted_s = self._predict_service_s(req)
            with prof.stopwatch() as sw:
                try:
                    self._service(req)
                except Exception as e:  # belt: _service resolves futures
                    req.future._reject(e)
            self.queue.note_service_time(sw.elapsed)
            if ledger_mod._LEDGER_FLAG._value:
                ledger_mod.note_service(key_hash(req.plan_key),
                                        predicted_s, sw.elapsed)
            if profile_mod._SAMPLE_FLAG._value > 0:
                # the sampled profiler ran on THIS worker thread during
                # the dispatch: stamp the request's flight record so
                # sampled requests are identifiable after the fact
                samp = profile_mod.take_last_sample()
                if samp is not None:
                    flight_mod.note(req.rid, "profiled", **samp)
                    # the skew observatory rode the same sample: its
                    # per-shard summary lands as its own event
                    sk = skew_mod.take_last_sample()
                    if sk is not None:
                        flight_mod.note(req.rid, "skew", **sk)
            if integrity_mod._CHECK_FLAG._value:
                # the SDC sentinel's verdicts for this request's
                # dispatch (including violations discarded and retried
                # by the policy engine mid-evaluate): flight-recorded
                # so a corrupt-then-retried request is auditable
                ic = integrity_mod.take_last_check()
                if ic is not None:
                    flight_mod.note(req.rid, "integrity", **ic)

    def _predict_service_s(self, r: "_Request") -> float:
        """This request's service-time prediction: the calibrated
        model's plan price when available, the queue EMA otherwise."""
        if _MODEL_PRICING_FLAG._value:
            p = ledger_mod.predict_service_s(key_hash(r.plan_key))
            if p is not None and p > 0:
                return p
        return self.queue.ema_service_s()

    def _shed_expired(self, batch: List[_Request]) -> List[_Request]:
        live: List[_Request] = []
        for r in batch:
            rem = r.remaining_s()
            if rem is not None and rem <= 0:
                if _METRICS_FLAG._value:
                    REGISTRY.counter(
                        "serve_deadline_expired",
                        "requests shed because their deadline expired "
                        "before dispatch").inc()
                flight_mod.note(r.rid, "shed", reason="deadline")
                r.future._reject(DeadlineExceeded(
                    f"deadline expired {-rem * 1e3:.1f}ms before "
                    f"dispatch (queued {trace_mod.now() - r.t_submit:.3f}s)"))
                continue
            if rem is not None and _MODEL_PRICING_FLAG._value:
                # predictive shed: the calibrated model says this
                # dispatch cannot finish inside the remaining budget —
                # shed NOW instead of burning a doomed dispatch slot
                # (the EMA-era behavior only shed already-expired
                # requests). Model-priced only: the EMA's blend over
                # unrelated plans is too blunt to pre-reject on.
                pred = ledger_mod.predict_service_s(
                    key_hash(r.plan_key))
                if pred is not None and pred > rem:
                    if _METRICS_FLAG._value:
                        REGISTRY.counter(
                            "serve_predicted_shed",
                            "requests shed because the calibrated "
                            "model priced their dispatch past the "
                            "remaining deadline").inc()
                    flight_mod.note(r.rid, "shed", reason="predicted",
                                    predicted_s=round(pred, 6),
                                    remaining_s=round(rem, 6))
                    r.future._reject(DeadlineExceeded(
                        f"predicted service {pred * 1e3:.1f}ms exceeds "
                        f"remaining deadline {rem * 1e3:.1f}ms"))
                    continue
            live.append(r)
        return live

    def _take(self, req: _Request, limit: int,
              via: str) -> List[_Request]:
        """Pull same-signature companions for ``req``'s batch, stamping
        each with its taken time and HOW it joined ('queued' = already
        waiting at pop time, 'window' = arrived during the linger) —
        the flight recorder's coalescing provenance."""
        more = self.queue.take_matching(req.plan_key, limit)
        if more:
            now = trace_mod.now()
            for r in more:
                r.t_taken = now
                r.via = via
        return more

    def _service(self, req: _Request) -> None:
        batch = [req]
        if self.coalesce_requests and req.coalescable:
            batch += self._take(req, self.max_batch - len(batch),
                                "queued")
            if len(batch) < self.max_batch and self.batch_window_s > 0:
                # linger once for stragglers inside the batching window
                self.queue.wait_for_more(self.batch_window_s)
                batch += self._take(req, self.max_batch - len(batch),
                                    "window")
        batch = self._shed_expired(batch)
        if not batch:
            return

        if len(batch) == 1 or not self.coalesce_requests:
            for r in batch:
                self._solo(r)
            return

        plan = base.lookup_plan(req.plan_key)
        if plan is None:
            # plan-cache miss: build it by evaluating the head request
            # solo (optimize + compile once), then coalesce the rest
            self._solo(batch[0])
            batch = self._shed_expired(batch[1:])
            plan = base.lookup_plan(req.plan_key)
        if not batch:
            return
        if (plan is None or plan.arg_order is None
                or coalesce.mode_for(plan) == "off" or len(batch) == 1):
            # uncacheable plan / demoted plan / single survivor
            for r in batch:
                self._solo(r)
            return
        # quantize to power-of-two chunks (13 -> 8+4+1): the batch size
        # is part of the compile-cache key, so free-running sizes would
        # compile a variant per observed size — quantized, a plan gains
        # at most log2(serve_max_batch) batched variants ever
        for chunk in _pow2_chunks(batch):
            if len(chunk) == 1:
                self._solo(chunk[0])
                continue
            try:
                self._coalesced(plan, chunk)
            except Exception as e:
                mode = coalesce.classify_batch_failure(e, plan)
                if _METRICS_FLAG._value:
                    REGISTRY.counter(
                        "serve_solo_fallbacks",
                        "batches that fell back to solo dispatches "
                        "after a batched failure").inc()
                log_warn("serve: coalesced dispatch failed (%s: %s); "
                         "falling back to %d solo dispatch(es), "
                         "mode=%s", type(e).__name__, str(e)[:120],
                         len(chunk), mode)
                if flight_mod._FLIGHT_FLAG._value:
                    for r in chunk:
                        flight_mod.note(r.rid, "fallback",
                                        reason=type(e).__name__,
                                        mode=mode)
                for r in chunk:
                    self._solo(r)

    def _coalesced(self, plan: Any, batch: List[_Request]) -> None:
        deadlines = [r.remaining_s() for r in batch]
        tightest = min((d for d in deadlines if d is not None),
                       default=None)
        # one dispatch span id for the whole batch: every member's
        # flight record names WHICH dispatch resolved it and why it
        # was in this batch (its 'via' stamp from _take / head pop)
        span = flight_mod.mint_span()
        t0 = trace_mod.now()
        record = flight_mod._FLIGHT_FLAG._value
        for r in batch:
            r.t_dispatch = t0
            if record:
                flight_mod.note(r.rid, "coalesce", span=span,
                                batch=len(batch), via=r.via)
        # one reservation for the whole batch: each request brings its
        # own predicted peak (the leading client axis scales working
        # sets ~linearly; the batch program is not re-modeled —
        # docs/MEMORY.md blind spots)
        reserved = sum(r.mem_bytes for r in batch)
        self.ledger.reserve(reserved)
        try:
            with mesh_mod.use_mesh(batch[0].mesh), \
                    numerics_mod.deadline_scope(tightest):
                results = coalesce.dispatch_batch(plan, batch,
                                                  batch[0].mesh)
        finally:
            self.ledger.release(reserved)
        for r, res in zip(batch, results):
            r.future.coalesced = len(batch)
            r.future._resolve(res)
            self._flight_resolve(r, span, len(batch), "ok")

    def _flight_resolve(self, r: _Request, span: int, batch: int,
                        status: str) -> None:
        """One resolution record: the request's latency decomposition
        (queue-wait / coalesce-wait / dispatch) lands in its flight
        record and the per-tenant histograms; the end-to-end latency
        feeds the tenant's SLO class (obs/slo.py) regardless of the
        flight-recorder flag."""
        if r.future.t_resolved is not None:
            slo_mod.observe(r.tenant,
                            r.future.t_resolved - r.t_submit)
        if not flight_mod._FLIGHT_FLAG._value:
            return
        flight_mod.record_resolution(
            rid=r.rid, tenant=r.tenant, span=span, batch=batch,
            status=status, t_submit=r.t_submit,
            t_taken=r.t_taken or r.t_submit,
            t_dispatch=r.t_dispatch or r.t_taken or r.t_submit,
            t_resolved=r.future.t_resolved)

    def _solo(self, r: _Request) -> None:
        span = flight_mod.mint_span()
        r.t_dispatch = trace_mod.now()
        if flight_mod._FLIGHT_FLAG._value:
            flight_mod.note(r.rid, "dispatch", span=span, batch=1,
                            via=r.via)
        self.ledger.reserve(r.mem_bytes)
        try:
            self._solo_inner(r)
        finally:
            self.ledger.release(r.mem_bytes)
        # warm-start provenance: if this dispatch built its plan, name
        # whether the executable came from the persist store (disk) or
        # a fresh XLA compile — the flight-record half of the
        # st.explain persist line. None on plan-cache hits and with
        # the store off; popped unconditionally so a stale outcome
        # can never stamp a later request.
        src = persist_mod.take_build_source()
        if src is not None and flight_mod._FLIGHT_FLAG._value:
            flight_mod.note(r.rid, "persist",
                            **{k: v for k, v in src.items()
                               if v is not None})
        self._flight_resolve(
            r, span, 1, "ok" if r.future._exc is None else "error")

    def _solo_inner(self, r: _Request) -> None:
        with mesh_mod.use_mesh(r.mesh), \
                resilience_engine.tenant_scope(r.tenant), \
                numerics_mod.deadline_scope(r.remaining_s()):
            try:
                result = base.evaluate(r.expr, donate=r.donate)
            except Exception as e:
                # the resilience engine already ran (classified,
                # retried under the tenant's budget); hand the terminal
                # failure to the caller through its future. A fatal
                # mesh failure is the one remap: elastic recovery has
                # already rebuilt the mesh by the time the engine
                # re-raised, so the caller gets the retryable
                # MeshReconfiguring-with-retry-after contract instead
                # of the raw device-death status.
                if (resilience_classify.classify(e)
                        == resilience_classify.FATAL_MESH):
                    mr = MeshReconfiguring(
                        FLAGS.elastic_retry_after_s,
                        "dispatch hit device loss; mesh rebuilt")
                    mr.__cause__ = e
                    r.future._reject(mr)
                    return
                if _sdc_in_chain(e):
                    # the integrity sentinel discarded this request's
                    # result (and may have quarantined the suspect,
                    # surfacing stale_mesh on the engine's retry): the
                    # client NEVER sees the corrupt value — retry once
                    # on the CURRENT (post-quarantine) mesh, rehoming
                    # stale leaves through the planner-priced elastic
                    # path, flight-recorded either way.
                    self._sdc_retry(r, e)
                    return
                r.future._reject(e)
                return
        r.future.coalesced = 1
        r.future._resolve(result)

    def _sdc_retry(self, r: _Request, exc: Exception) -> None:
        from ..resilience import elastic as elastic_mod

        if flight_mod._FLIGHT_FLAG._value:
            flight_mod.note(
                r.rid, "sdc_retry",
                quarantined=getattr(exc, "quarantined", None))
        try:
            with mesh_mod.use_mesh(mesh_mod.get_mesh()), \
                    resilience_engine.tenant_scope(r.tenant), \
                    numerics_mod.deadline_scope(r.remaining_s()):
                for _ in range(3):  # rehome passes, like st.loop's
                    try:
                        result = base.evaluate(r.expr, donate=r.donate)
                        break
                    except mesh_mod.StaleMeshError as se:
                        elastic_mod.rehome(getattr(se, "arrays", ()))
                else:
                    result = base.evaluate(r.expr, donate=r.donate)
        except Exception as e2:
            r.future._reject(e2)
            return
        r.future.coalesced = 1
        r.future._resolve(result)


# -- the default engine (st.evaluate_async) ------------------------------

_default_lock = threading.Lock()
_default: Optional[ServeEngine] = None


def default_engine() -> ServeEngine:
    """The process's shared engine, started lazily on first use."""
    global _default
    with _default_lock:
        if _default is None:
            _default = ServeEngine()
        return _default.start()


def peek_default() -> Optional[ServeEngine]:
    """The default engine if one exists — WITHOUT starting it (elastic
    recovery drains the engine only if there is one to drain)."""
    with _default_lock:
        return _default


def shutdown_default() -> None:
    """Stop (and forget) the default engine; the next
    ``evaluate_async`` starts a fresh one."""
    global _default
    with _default_lock:
        eng, _default = _default, None
    if eng is not None:
        eng.stop()


def evaluate_async(expr: Any, donate: Sequence[Any] = (),
                   tenant: Optional[str] = None,
                   deadline_s: Optional[float] = None) -> EvalFuture:
    """Submit ``expr`` to the default serve engine: returns an
    :class:`EvalFuture` immediately. Identical-signature requests from
    concurrent callers coalesce into one batched dispatch; the
    resilience engine's retries and the dispatch watchdog apply per
    request. See docs/SERVING.md."""
    return default_engine().submit(expr, donate=donate, tenant=tenant,
                                   deadline_s=deadline_s)
