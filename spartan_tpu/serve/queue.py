"""Bounded submission queue with admission control.

The front door of the serve engine: callers ``put()`` requests (fast,
one lock), workers ``pop()`` them and pull same-signature companions
with ``take_matching`` for coalescing. Past the high-water mark
(``FLAGS.serve_queue_max``) admission REJECTS with
:class:`~spartan_tpu.serve.future.Backpressure` carrying a
retry-after estimate — shedding at the door instead of letting latency
grow unboundedly inside (the queue never blocks a submitter).

Structure: one FIFO deque (arrival order) plus a per-plan-signature
bucket index for the coalescer — ``take_matching`` pops from its
bucket in O(taken) instead of scanning the whole backlog (measured
~12µs/request at depth ~500 for the scan it replaces). A request
taken from a bucket stays in the FIFO with its ``taken`` flag set and
is skipped lazily; both views converge under one condition variable.

Idle workers BLOCK on the condition variable (no poll timeout): an
idle engine costs zero steady-state CPU — this is what keeps the
serve-off overhead gate at ~0 — and ``close()`` wakes every waiter
for shutdown.

Locking: the one condition variable guards the deque, the buckets and
the depth count; ``put``/``pop``/``take_matching`` never call out of
the module while holding it.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Any, Deque, Dict, List, Optional

from ..obs.metrics import METRICS_FLAG as _METRICS_FLAG
from ..obs.metrics import REGISTRY
from .future import Backpressure


class AdmissionQueue:
    """FIFO of :class:`~spartan_tpu.serve.engine._Request` objects with
    a hard depth bound and per-signature buckets for the coalescer."""

    def __init__(self, maxsize: int):
        self.maxsize = max(1, int(maxsize))
        self._cv = threading.Condition(threading.Lock())
        self._items: Deque[Any] = deque()
        self._by_key: Dict[Any, Deque[Any]] = {}
        self._depth = 0  # live (not-taken) requests
        self._closed = False
        # recent per-request service seconds (EMA, worker-updated) —
        # the basis of the Backpressure retry-after estimate
        self._ema_service_s = 0.001

    def depth(self) -> int:
        return self._depth

    def note_service_time(self, seconds: float) -> None:
        """EMA update from a worker after each completed request."""
        with self._cv:
            self._ema_service_s += 0.2 * (seconds - self._ema_service_s)

    def ema_service_s(self) -> float:
        """The queue's service-time PREDICTION: the current EMA of
        per-request service seconds. Read by the Backpressure
        retry-after estimate and — before each service — by the cost
        ledger, so the prediction the client's backoff was based on is
        recorded next to the measured service time (unlocked read: a
        stale EMA is still the value the estimate used)."""
        return self._ema_service_s

    def retry_after_s(self, workers: int) -> float:
        """Expected time until the current backlog drains one slot."""
        return max(0.001,
                   self._depth * self._ema_service_s / max(1, workers))

    def put(self, req: Any, workers: int = 1) -> None:
        """Admit or reject (never blocks the submitter)."""
        with self._cv:
            if self._closed:
                raise RuntimeError("serve engine stopped")
            if self._depth >= self.maxsize:
                if _METRICS_FLAG._value:
                    REGISTRY.counter(
                        "serve_rejected",
                        "requests shed by admission control "
                        "(Backpressure)").inc()
                raise Backpressure(self._depth,
                                   self.retry_after_s(workers))
            self._items.append(req)
            if req.coalescable:
                bucket = self._by_key.get(req.plan_key)
                if bucket is None:
                    bucket = self._by_key[req.plan_key] = deque()
                bucket.append(req)
            self._depth += 1
            if _METRICS_FLAG._value:
                REGISTRY.gauge(
                    "serve_queue_depth",
                    "submission queue depth (high-water tracked)"
                ).set(float(self._depth))
            self._cv.notify()

    def pop(self, timeout: Optional[float] = None) -> Optional[Any]:
        """Blocking head pop (arrival order, bucket-taken requests
        skipped). ``timeout=None`` blocks until an item arrives or the
        queue is closed — an idle worker costs nothing; returns None
        on close or timeout."""
        with self._cv:
            while True:
                while self._items and self._items[0].taken:
                    self._items.popleft()  # lazily drop bucket-taken
                if self._items:
                    req = self._items.popleft()
                    req.taken = True
                    self._depth -= 1
                    self._unbucket(req)
                    return req
                if self._closed:
                    return None
                if not self._cv.wait(timeout):
                    return None

    def _unbucket(self, req: Any) -> None:
        """Drop a head-popped request's bucket entry (cheap when it is
        the bucket head, which FIFO order makes the common case)."""
        bucket = self._by_key.get(req.plan_key)
        if not bucket:
            return
        while bucket and bucket[0].taken and bucket[0] is not req:
            bucket.popleft()
        if bucket and bucket[0] is req:
            bucket.popleft()
        if not bucket:
            del self._by_key[req.plan_key]

    def take_matching(self, plan_key: Any, limit: int) -> List[Any]:
        """Remove up to ``limit`` queued coalescable requests with the
        given plan signature (O(taken), via the bucket index); the
        FIFO keeps their husks and skips them lazily."""
        if limit <= 0:
            return []
        out: List[Any] = []
        with self._cv:
            bucket = self._by_key.get(plan_key)
            while bucket and len(out) < limit:
                r = bucket.popleft()
                if not r.taken:
                    r.taken = True
                    self._depth -= 1
                    out.append(r)
            if bucket is not None and not bucket:
                self._by_key.pop(plan_key, None)
        return out

    def wait_for_more(self, window_s: float) -> None:
        """The coalescing linger: block up to ``window_s`` for another
        submission to arrive (woken by ``put``'s notify)."""
        with self._cv:
            self._cv.wait(window_s)

    def drain(self) -> List[Any]:
        """Remove everything live (engine shutdown: reject the
        backlog)."""
        with self._cv:
            out = [r for r in self._items if not r.taken]
            for r in out:
                r.taken = True
            self._items.clear()
            self._by_key.clear()
            self._depth = 0
            return out

    def close(self) -> None:
        """Reject future puts and wake every blocked worker."""
        with self._cv:
            self._closed = True
            self._cv.notify_all()

    def reopen(self) -> None:
        """Accept puts again (engine restart after stop())."""
        with self._cv:
            self._closed = False
