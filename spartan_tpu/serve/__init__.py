"""Concurrent serving front end: async evaluate, admission control,
request coalescing.

The north star demands many concurrent callers; ``evaluate()`` is a
synchronous single-caller path. This package puts a serving engine in
front of the existing plan machinery — no new execution semantics,
just concurrency:

* **async evaluation** — ``st.evaluate_async(expr)`` /
  ``expr.evaluate_async()`` return an :class:`EvalFuture` immediately;
  a worker dispatches and the future resolves with the (async-device)
  ``DistArray``. Fetch (``.glom()``) is where execution is awaited.
* **admission control** — a bounded queue; past the high-water mark
  submissions are rejected with :class:`Backpressure` carrying a
  ``retry_after_s`` estimate. Deadlines shed expired requests and
  propagate into the dispatch watchdog.
* **signature-level coalescing** — requests whose raw-DAG signature
  matches (the PR-1 plan-cache key) within the batching window share
  one cached plan and batch along a new leading client axis: one
  compile, one dispatch, N responses (the DrJAX vmap-over-clients
  construction). ``st.explain`` names the coalesced batch.
* **tenancy** — per-tenant request counters in the Prometheus export
  and per-tenant retry budgets in the resilience engine.
* **elastic drain** — during a mesh rebuild after device loss
  (``resilience/elastic``), admission pauses and queued/in-flight
  requests fail with the retryable :class:`MeshReconfiguring`
  carrying a retry-after; clients resubmit onto the rebuilt mesh.

Locking discipline (the concurrency contract of the whole hot path;
see also expr/base.py's shared-state comment):

* ``expr/base._cache_lock`` guards the plan + compile caches; held for
  dict ops only, accessed ONLY through ``lookup_plan`` /
  ``store_plan`` / ``cached_executable`` (lint rule 6).
* the metrics registry, trace ring, chaos plan, retry budgets and the
  coalescer's mode table each take their own leaf lock; no module
  calls out of itself while holding one, so the lock graph is acyclic.
* per-request state (tenant, deadline) rides thread-locals
  (``resilience.engine.tenant_scope``, ``obs.numerics.deadline_scope``)
  set by the worker around each dispatch.
* futures are resolved exactly once by their owning worker; callers
  only wait on an Event.

See docs/SERVING.md for the full queue/backpressure/coalescing
contract and benchmarks/serving_latency.py for the acceptance gates.
"""

from .coalesce import reset_modes
from .engine import (ServeEngine, default_engine, evaluate_async,
                     peek_default, shutdown_default)
from .future import (Backpressure, CommBudgetExceeded, DeadlineExceeded,
                     EvalFuture, MeshReconfiguring, ServeError)
from .queue import AdmissionQueue

__all__ = [
    "ServeEngine", "AdmissionQueue", "EvalFuture", "ServeError",
    "Backpressure", "CommBudgetExceeded", "DeadlineExceeded",
    "MeshReconfiguring", "evaluate_async", "default_engine",
    "peek_default", "shutdown_default", "reset_modes",
]
