"""Process-stable entry addressing for the persistent plan store.

The in-memory caches key on Python object hashes (tuples holding code
objects, interned strings, frozensets) — fast, but meaningless across
a process restart: ``hash(str)`` is randomized per process and code
objects hash by identity-adjacent fields. The on-disk store therefore
addresses entries by :func:`stable_digest` — a SHA-256 walk over the
SAME plan-key tuple ``evaluate()`` computes, with every component
reduced to its structural content:

* scalars / strings / bytes feed their type tag + value;
* tuples/lists/dicts/frozensets feed tagged, (sorted where unordered)
  recursions;
* code objects feed their bytecode, consts, names and arity — two
  processes compiling the same ``def`` digest identically;
* functions feed ``module.qualname`` (stable for module-level and
  locally-defined kernels at the same definition site);
* anything else raises :class:`UnstableKeyError` — the plan is simply
  not persistable (``persist_unstable_keys`` counts it, evaluation is
  untouched).

A digest alone must never authorize a load: :func:`env_fingerprint`
captures everything OUTSIDE the plan key that changes what a compiled
executable means — jax/jaxlib/python versions, platform, device
count, mesh shape + epoch, the optimizer-flags key and the kernel
policy — and the store validates the manifest's fingerprint verbatim
on every load, so a stale or foreign entry can never alias even under
a digest collision.
"""

from __future__ import annotations

import hashlib
import sys
import types
from typing import Any, Dict, Tuple

import numpy as np

FORMAT_VERSION = 1


class UnstableKeyError(TypeError):
    """A plan-key component has no process-stable byte representation;
    the plan cannot be addressed on disk (and is not persisted)."""


def _feed(h, obj: Any) -> None:
    # type tags keep 1 and 1.0 and "1" and True apart
    if obj is None:
        h.update(b"\x00N")
    elif obj is True:
        h.update(b"\x00T")
    elif obj is False:
        h.update(b"\x00F")
    elif isinstance(obj, int):
        h.update(b"\x00i" + str(obj).encode())
    elif isinstance(obj, float):
        h.update(b"\x00f" + repr(obj).encode())
    elif isinstance(obj, str):
        b = obj.encode()
        h.update(b"\x00s" + str(len(b)).encode() + b":" + b)
    elif isinstance(obj, bytes):
        h.update(b"\x00b" + str(len(obj)).encode() + b":" + obj)
    elif isinstance(obj, (tuple, list)):
        h.update(b"\x00(" if isinstance(obj, tuple) else b"\x00[")
        for item in obj:
            _feed(h, item)
        h.update(b"\x00)")
    elif isinstance(obj, (frozenset, set)):
        h.update(b"\x00{")
        for d in sorted(stable_digest(item) for item in obj):
            h.update(d.encode())
        h.update(b"\x00}")
    elif isinstance(obj, dict):
        h.update(b"\x00d")
        for k in sorted(obj, key=lambda k: stable_digest(k)):
            _feed(h, k)
            _feed(h, obj[k])
        h.update(b"\x00e")
    elif isinstance(obj, np.dtype):
        h.update(b"\x00y" + str(obj).encode())
    elif isinstance(obj, (np.integer, np.floating, np.bool_)):
        h.update(b"\x00n" + str(obj.dtype).encode() + b":"
                 + repr(obj.item()).encode())
    elif isinstance(obj, types.CodeType):
        # structural identity, mirroring fn_key's intent: the same def
        # compiled in another process digests the same
        h.update(b"\x00c")
        _feed(h, (obj.co_name, obj.co_argcount, obj.co_kwonlyargcount,
                  obj.co_nlocals, obj.co_flags, obj.co_code,
                  obj.co_names, obj.co_varnames, obj.co_freevars,
                  obj.co_cellvars, obj.co_consts))
    elif isinstance(obj, (types.FunctionType, types.BuiltinFunctionType,
                          types.MethodType)):
        # module-qualified name: stable for module-level kernels and
        # for local defs at the same definition site
        mod = getattr(obj, "__module__", None)
        qual = getattr(obj, "__qualname__", getattr(obj, "__name__", None))
        if not mod or not qual:
            raise UnstableKeyError(
                f"unnameable callable in plan key: {obj!r}")
        h.update(b"\x00q" + f"{mod}.{qual}".encode())
    elif isinstance(obj, type):
        h.update(b"\x00t" + f"{obj.__module__}.{obj.__qualname__}".encode())
    else:
        raise UnstableKeyError(
            f"plan-key component {type(obj).__name__} has no stable "
            "byte representation; plan is not persistable")


def stable_digest(obj: Any) -> str:
    """Process-stable SHA-256 hex digest of a (nested) plan-key
    component. Raises :class:`UnstableKeyError` for components with no
    stable representation (the caller skips persistence)."""
    h = hashlib.sha256()
    _feed(h, obj)
    return h.hexdigest()[:40]


def env_fingerprint(mesh: Any) -> Dict[str, Any]:
    """Everything outside the plan key that decides whether a
    serialized executable is meaningful in THIS process. Validated
    verbatim (dict equality after a JSON round trip) on every load —
    version skew, a different platform, a foreign mesh shape or a dead
    mesh epoch can never alias a live entry. JSON-clean by
    construction."""
    import jax
    import jaxlib

    from ..parallel import mesh as mesh_mod

    # lazy: expr.base imports this package at module init; by the time
    # a fingerprint is computed the expr layer is fully loaded
    from ..expr import base as expr_base
    from ..kernels import registry as kernels_mod

    return {
        "format": FORMAT_VERSION,
        "python": list(sys.version_info[:3]),
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": jax.default_backend(),
        "device_count": len(jax.devices()),
        "mesh_shape": [[str(k), int(v)]
                       for k, v in sorted(mesh.shape.items())],
        "mesh_epoch": int(mesh_mod._EPOCH),
        "opt_flags": stable_digest(expr_base._opt_flags_key()),
        "kernels_policy": stable_digest(kernels_mod.policy_key()),
    }


def entry_digest(plan_key: Tuple, fingerprint: Dict[str, Any]) -> str:
    """The on-disk address of one plan: the raw-DAG plan key extended
    with the full environment fingerprint. Raises UnstableKeyError
    when the plan key cannot be stably represented."""
    return stable_digest((plan_key, fingerprint))
