"""Warm-start persistence: the crash-safe plan & executable store.

A serving replica restart used to recompile the world — fatal for
rolling restarts of a fleet, and exactly the failure mode behind the
BENCH_r05 cold-start timeouts (docs/BENCH.md "r04 -> r05 verdict").
This package makes the plan cache and the compiled executables
DURABLE: ``evaluate()``'s miss path consults the store before the
optimizer runs, pre-seeds the compile cache with the deserialized AOT
executable on a hit (zero XLA recompiles, bit-equal results), and
persists freshly-compiled plans after the compile; ``ServeEngine.
prewarm(manifest)`` restores a configured plan set at startup off the
request path.

Addressing & safety (fingerprint.py / store.py):

* entries are keyed by a process-stable digest of the SAME raw-DAG
  plan key ``evaluate()`` computes, extended with a full environment
  fingerprint (python/jax/jaxlib versions, platform, device count,
  mesh shape + epoch, ``_opt_flags_key``, ``kernels.policy_key()``) —
  stale or foreign entries can never alias;
* writes are atomic temp-dir + ``os.replace`` with per-file CRC32
  manifests (the PR-5 checkpoint discipline); concurrent replicas
  sharing one directory are lock-free-reader / lease-writer;
* loads validate version + fingerprint + CRC, and EVERY failure —
  corruption, skew, ``io`` chaos, deserialize errors — degrades to a
  normal recompile with the reason surfaced in the ``persist_*``
  metrics family and ``st.explain``. Persistence can never make
  ``evaluate()`` less available than it is with the store off.

``FLAGS.persist_cache_dir`` (default "" = off) turns it on; with it
off the hit path is UNTOUCHED and the miss path pays one flag read
(benchmarks/warm_start.py gates ``warmstart_off_overhead_ratio``).
See docs/WARMSTART.md for the layout, the invalidation matrix and the
rolling-restart runbook.
"""

from __future__ import annotations

import json
import threading
from typing import Any, Dict, List, Optional, Tuple, Union

from ..obs.metrics import METRICS_FLAG as _METRICS_FLAG
from ..obs.metrics import REGISTRY, labeled
from ..utils.config import FLAGS
from ..utils.log import log_debug, log_warn
from .fingerprint import (UnstableKeyError, entry_digest, env_fingerprint,
                          stable_digest)
from .store import Entry, PersistRejected, PersistStore

__all__ = [
    "PersistStore", "PersistRejected", "Entry", "UnstableKeyError",
    "active", "lookup", "maybe_store", "maybe_gc", "evict_stale",
    "prewarm", "write_manifest", "stats", "reset",
]

_DIR_FLAG = FLAGS.define_str(
    "persist_cache_dir", "",
    "Crash-safe on-disk store for plans + compiled executables "
    "(spartan_tpu/persist, docs/WARMSTART.md): evaluate()'s miss path "
    "consults it before optimizing and persists after compile, so a "
    "process restart serves its plan set with zero recompiles. "
    "Entries are fingerprint-keyed (jax/platform/mesh/flags) and "
    "CRC-verified; any mismatch or corruption degrades to a normal "
    "recompile. Empty = off (the default: zero hot-path change).")
FLAGS.define_float(
    "persist_lease_ttl_s", 60.0,
    "Writer-lease time-to-live for a shared persist_cache_dir: a "
    "lease file older than this is considered abandoned (writer "
    "crashed mid-persist) and may be broken by another replica.")
FLAGS.define_float(
    "persist_prewarm_timeout_s", 30.0,
    "Per-entry timeout for ServeEngine.prewarm: one slow or hostile "
    "entry cannot stall the rest of the prewarm set (the load keeps "
    "running in the background and is adopted if it finishes).")
FLAGS.define_int(
    "persist_max_bytes", 0,
    "Size bound on the persist store (long-lived fleets): after each "
    "persisted entry, least-recently-USED entries (manifest mtime — "
    "refreshed on every load) are evicted until the store fits. "
    "0 = unbounded (the default; entries then persist until "
    "fingerprint rotation or dead-epoch eviction).")
FLAGS.define_float(
    "persist_ttl_s", 0.0,
    "Age bound on persist-store entries: an entry not used (loaded) "
    "for longer than this is evicted by the post-store GC sweep. "
    "0 = no TTL.")

# -- process-level store singleton ---------------------------------------

_lock = threading.Lock()
_store: Optional[PersistStore] = None
_store_dir: Optional[str] = None
_failed_dir: Optional[str] = None

# plan_key -> (digest | None) memo: signing is per-request; digesting
# (a full SHA walk of the key) is per-PLAN. Bounded; cleared on reset.
_digest_memo: Dict[Tuple, Optional[str]] = {}
_DIGEST_MEMO_MAX = 1024

# what the last _build_plan on THIS thread did (disk hit vs compile):
# the serve worker stamps it onto the request's flight record
_TLS = threading.local()


def _count(name: str, n: int = 1, **labels: str) -> None:
    if _METRICS_FLAG._value and n:
        full = labeled(name, **labels) if labels else name
        REGISTRY.counter(full, "persistent plan/executable store "
                         "(spartan_tpu/persist)").inc(n)


def active() -> Optional[PersistStore]:
    """The process's store for FLAGS.persist_cache_dir, or None when
    persistence is off (one flag read). A directory that cannot be
    created disables the store for that path (warn once) — an
    unusable disk must not fail evaluations."""
    global _store, _store_dir, _failed_dir
    d = _DIR_FLAG._value
    if not d:
        return None
    if _store is not None and _store_dir == d:
        return _store
    if _failed_dir == d:
        return None
    with _lock:
        if _store is not None and _store_dir == d:
            return _store
        try:
            _store = PersistStore(d)
            _store_dir = d
            _failed_dir = None
        except OSError as e:
            log_warn("persist: cannot open cache dir %r (%s); "
                     "persistence disabled for this path", d, e)
            _count("persist_store_errors", reason="open")
            _failed_dir = d
            _store = None
            _store_dir = None
    return _store


def reset() -> None:
    """Forget the store singleton, digest memo and prewarm table (test
    isolation; the on-disk contents are untouched)."""
    global _store, _store_dir, _failed_dir
    with _lock:
        _store = None
        _store_dir = None
        _failed_dir = None
        _digest_memo.clear()
    _TLS.__dict__.clear()


def digest_for(plan_key: Tuple, mesh: Any) -> Optional[str]:
    """Process-stable on-disk address for one plan key (memoized), or
    None when the key has no stable representation (counted, plan
    simply not persistable)."""
    hit = _digest_memo.get(plan_key, "")
    if hit != "":
        return hit
    try:
        digest = entry_digest(plan_key, env_fingerprint(mesh))
    except UnstableKeyError as e:
        log_debug("persist: unstable plan key (%s)", e)
        _count("persist_unstable_keys")
        digest = None
    if len(_digest_memo) >= _DIGEST_MEMO_MAX:
        _digest_memo.clear()
    _digest_memo[plan_key] = digest
    return digest


# -- evaluate() seams -----------------------------------------------------


def note_build(source: str, digest: Optional[str] = None,
               reason: Optional[str] = None) -> None:
    _TLS.last = {"source": source, "digest": digest, "reason": reason}


def take_build_source() -> Optional[Dict[str, Any]]:
    """Pop this thread's last persist outcome (disk vs compile) — the
    serve worker stamps it onto the request's flight record."""
    last = getattr(_TLS, "last", None)
    _TLS.last = None
    return last


def lookup(plan_key: Optional[Tuple], mesh: Any
           ) -> Tuple[Optional[Entry], Optional[str], Optional[str]]:
    """Consult the store for one plan key (the miss path's first act,
    BEFORE the optimizer). Returns ``(entry, digest, reject_reason)``;
    entry None means recompile (clean miss, store off, unstable key,
    or a rejected/hostile entry — the reason says which, and lands in
    metrics + the plan report)."""
    store = active()
    if store is None or plan_key is None:
        return None, None, None
    digest = digest_for(plan_key, mesh)
    if digest is None:
        return None, None, "unstable_key"
    try:
        entry = store.load(digest, env_fingerprint(mesh))
    except PersistRejected as e:
        log_warn("persist: entry %s rejected (%s); recompiling",
                 digest[:12], e)
        _count("persist_load_errors", reason=e.reason)
        return None, digest, e.reason
    except (OSError, UnstableKeyError) as e:
        log_warn("persist: load failed for %s (%s: %s); recompiling",
                 digest[:12], type(e).__name__, e)
        _count("persist_load_errors", reason="io")
        return None, digest, "io"
    if entry is None:
        _count("persist_misses")
        return None, digest, None
    # the hit is counted by note_hit() once expr.base's belt checks
    # accept the entry (a metadata mismatch flips it to a rejection)
    return entry, digest, None


def note_hit() -> None:
    _count("persist_hits")


def reject_entry(entry: Entry, reason: str) -> None:
    """An entry survived fingerprint + CRC but failed the plan-level
    belt checks: count the reason, purge it (it can never load) and
    recompile."""
    log_warn("persist: entry %s rejected (%s); recompiling and "
             "purging", entry.digest[:12], reason)
    _count("persist_load_errors", reason=reason)
    store = active()
    if store is not None:
        store.purge(entry.digest)


def guarded_callable(entry: Entry, fallback_factory: Any) -> Any:
    """Wrap a restored executable so an argument/sharding mismatch at
    call time (a digest collision, or metadata the belt checks could
    not see) degrades to a fresh jit compile instead of failing the
    dispatch: availability over reuse, always."""
    holder: List[Any] = []

    def run(*args: Any) -> Any:
        if holder:
            return holder[0](*args)
        try:
            return entry.compiled(*args)
        except (TypeError, ValueError) as e:
            # aval / sharding / layout mismatch: this entry does not
            # fit the args this process actually gathers
            log_warn("persist: restored executable %s does not fit "
                     "(%s: %s); recompiling and purging the entry",
                     entry.digest[:12], type(e).__name__,
                     str(e)[:120])
            _count("persist_call_fallbacks")
            store = active()
            if store is not None:
                store.purge(entry.digest)
            holder.append(fallback_factory())
            return holder[0](*args)

    return run


def aot_compile(traced: Any, args: Tuple[Any, ...]) -> Any:
    """Build the base-variant executable ahead-of-time (lower over the
    concrete gathered args, compile once): the resulting
    ``jax.stages.Compiled`` is both the dispatchable executable and
    the serializable artifact — persistence never pays a second XLA
    compile. Only used when the store is active; donation and serve
    batch variants keep the plain ``jax.jit`` path."""
    import jax

    return jax.jit(traced).lower(*args).compile()


def serializable(executable: Any) -> bool:
    import jax

    return isinstance(executable, jax.stages.Compiled)


def maybe_store(plan: Any, executable: Any, mesh: Any) -> bool:
    """Persist a freshly-compiled plan (called by ``_dispatch`` right
    after the first compile+run). No-raise: a failed persist is
    counted, never propagated into the evaluation that produced the
    plan."""
    store = active()
    digest = getattr(plan, "persist_digest", None)
    if store is None or digest is None:
        return False
    if not serializable(executable):
        _count("persist_store_skipped", reason="not_aot")
        return False
    # the raw->optimized arg order is the process-stable calling
    # convention (plan.arg_order is the identity variant's on the very
    # first dispatch); uncacheable plans never get here
    arg_order = (plan.report or {}).get("arg_order")
    if arg_order is None:
        _count("persist_store_skipped", reason="uncacheable")
        return False
    try:
        from jax.experimental import serialize_executable as _se

        payload, in_tree, out_tree = _se.serialize(executable)
        plan_meta = {
            "out_tilings": [[list(ax) if isinstance(ax, tuple) else ax
                             for ax in t.axes]
                            for t in plan.out_tilings],
            "is_tuple": plan.is_tuple,
            "arg_order": list(arg_order),
            "nargs": len(arg_order),
        }
        # the plan-audit verdict (analysis/plan_audit.py) rides the
        # entry when one was computed: a warm restart restores it with
        # the executable and never re-lowers for the audit. JSON-safe
        # by construction (PlanAudit.to_dict).
        verdict = (plan.report or {}).get("audit")
        if verdict is not None:
            plan_meta["audit"] = verdict
        landed = store.save(digest, env_fingerprint(mesh), plan_meta,
                            payload, (in_tree, out_tree))
    except Exception as e:  # noqa: BLE001 - persistence is best-effort
        # by contract: IO errors, chaos faults, unserializable
        # backends all degrade to "this plan is simply not persisted"
        log_warn("persist: store failed for %s (%s: %s)",
                 digest[:12], type(e).__name__, str(e)[:120])
        _count("persist_store_errors", reason="io")
        return False
    if landed:
        _count("persist_stores")
        if plan.report is not None and plan.report.get("persist"):
            plan.report["persist"]["stored"] = True
        maybe_gc(protect=(digest,))
    return landed


def maybe_gc(protect: Tuple[str, ...] = ()) -> int:
    """Apply the store's size/TTL bounds (``FLAGS.persist_max_bytes``
    / ``persist_ttl_s``, LRU-by-mtime) after a store landed. No-raise,
    two flag reads when unbounded; evictions land in the
    ``persist_evictions`` counter."""
    max_bytes = int(FLAGS.persist_max_bytes or 0)
    ttl_s = float(FLAGS.persist_ttl_s or 0.0)
    if not max_bytes and not ttl_s:
        return 0
    store = active()
    if store is None:
        return 0
    try:
        n = store.gc(max_bytes, ttl_s, protect=tuple(protect))
    except Exception as e:  # noqa: BLE001 - GC is hygiene, never a
        # reason to fail the evaluation that triggered it
        log_warn("persist: GC sweep failed (%s: %s)",
                 type(e).__name__, str(e)[:120])
        return 0
    if n:
        _count("persist_evictions", n)
    return n


# -- eviction -------------------------------------------------------------

_last_evicted = 0


def evict_stale() -> int:
    """Purge on-disk entries persisted under a dead mesh epoch; the
    disk half of ``expr.base.evict_stale_plans`` (elastic recovery).
    No-raise; returns entries purged."""
    global _last_evicted
    store = active()
    if store is None:
        _last_evicted = 0
        return 0
    from ..parallel import mesh as mesh_mod

    try:
        n = store.evict_epochs_before(mesh_mod._EPOCH)
    except OSError as e:
        log_warn("persist: eviction scan failed (%s)", e)
        n = 0
    _count("persist_evicted", n)
    _last_evicted = n
    return n


def last_evicted() -> int:
    return _last_evicted


# -- prewarm --------------------------------------------------------------


def _manifest_digests(manifest: Union[str, Dict[str, Any], List[str]],
                      store: PersistStore) -> List[str]:
    if manifest == "all":
        return store.digests()
    if isinstance(manifest, str):
        with open(manifest) as f:
            manifest = json.load(f)
    if isinstance(manifest, dict):
        return [str(d) for d in manifest.get("entries", [])]
    return [str(d) for d in manifest]


def prewarm(manifest: Union[str, Dict[str, Any], List[str]] = "all",
            timeout_s: Optional[float] = None) -> Dict[str, Any]:
    """Restore a configured plan set into the in-memory prewarm table
    (``ServeEngine.prewarm`` calls this at startup, off the request
    path). ``manifest``: a path to a JSON ``{"entries": [digest,...]}``
    file, the dict/list itself, or ``"all"`` (every entry in the
    store). Per-entry timeout + error isolation: one hostile, missing
    or slow entry is counted and skipped, never crashing or stalling
    the rest — each entry loads on its OWN daemon thread, so a load
    that outlives its timeout keeps running in the background (it is
    adopted into the table if it eventually finishes) but can neither
    delay the next entry nor block process exit. Returns
    ``{loaded, missing, errors, skipped, total}``."""
    from ..obs import trace as trace_mod

    stats = {"loaded": 0, "missing": 0, "errors": 0, "skipped": 0,
             "total": 0}
    store = active()
    if store is None:
        stats["skipped"] = -1  # store off: nothing to prewarm
        return stats
    if timeout_s is None:
        timeout_s = FLAGS.persist_prewarm_timeout_s
    try:
        digests = _manifest_digests(manifest, store)
    except (OSError, ValueError) as e:
        log_warn("persist: unreadable prewarm manifest (%s)", e)
        _count("persist_prewarm_errors", reason="manifest")
        stats["errors"] += 1
        return stats
    stats["total"] = len(digests)
    try:
        from ..parallel import mesh as mesh_mod

        fp = env_fingerprint(mesh_mod.get_mesh())
    except Exception as e:  # noqa: BLE001 - an unfingerprintable
        # environment disables the whole prewarm, never the process
        log_warn("persist: prewarm fingerprint failed (%s: %s)",
                 type(e).__name__, e)
        _count("persist_prewarm_errors", reason="fingerprint")
        stats["errors"] = len(digests)
        return stats
    with trace_mod.span("prewarm", entries=len(digests)):
        for digest in digests:
            outcome: Dict[str, Any] = {}

            def _load(digest=digest, outcome=outcome):
                try:
                    outcome["found"] = store.preload(digest, fp)
                except Exception as e:  # noqa: BLE001 - per-entry
                    # isolation: a hostile entry must not sink the set
                    outcome["error"] = e

            t = threading.Thread(target=_load, daemon=True,
                                 name="spartan-prewarm")
            t.start()
            t.join(timeout_s)
            if t.is_alive():
                stats["errors"] += 1
                _count("persist_prewarm_errors", reason="timeout")
                log_warn("persist: prewarm entry %s timed out after "
                         "%.1fs; skipped (its load continues in the "
                         "background)", str(digest)[:12], timeout_s)
            elif "error" in outcome:
                e = outcome["error"]
                stats["errors"] += 1
                _count("persist_prewarm_errors",
                       reason=getattr(e, "reason", "io"))
                log_warn("persist: prewarm entry %s failed (%s: %s); "
                         "skipped", str(digest)[:12],
                         type(e).__name__, str(e)[:120])
            elif outcome.get("found"):
                stats["loaded"] += 1
                _count("persist_prewarm_loaded")
            else:
                stats["missing"] += 1
                _count("persist_prewarm_missing")
                log_warn("persist: prewarm entry %s not in store; "
                         "skipped", str(digest)[:12])
    return stats


def write_manifest(path: str,
                   digests: Optional[List[str]] = None) -> int:
    """Write a prewarm manifest for the current store contents (the
    rolling-restart runbook's capture step). Returns entries listed;
    0 with the store off."""
    store = active()
    if store is None:
        return 0
    return store.write_manifest(path, digests)


def stats() -> Dict[str, Any]:
    """Store-side observability: directory, entry count, prewarm table
    size (the persist_* counters live in st.metrics())."""
    store = active()
    if store is None:
        return {"enabled": False}
    digests = store.digests()
    return {"enabled": True, "dir": store.root,
            "entries": len(digests),
            "preloaded": store.preloaded_count()}
