"""Crash-safe on-disk store for plans and compiled executables.

Layout (``FLAGS.persist_cache_dir``; docs/WARMSTART.md)::

    <dir>/
      entry_<digest>/            one persisted plan
        manifest.json            version + fingerprint + per-file CRC32
        plan.json                plan metadata (out tilings, arg order)
        trees.pkl                pickled (in_tree, out_tree) PyTreeDefs
        exec.bin                 serialized XLA executable (jax AOT)
      entry_<digest>.tmp-<pid>/  in-flight write (atomically promoted)
      entry_<digest>.lease       writer lease (multi-process arbitration)

Write discipline is the PR-5 checkpoint contract: every file lands in
a temp dir next to the final path, the manifest (carrying a CRC32 per
sibling file) is written LAST inside the temp dir, and one
``os.replace`` promotes the whole entry — a reader or a crash can only
ever observe a complete entry or none.

Concurrency is lock-free-reader / lease-writer: readers never take any
lock (atomic promotion means they see old-or-new, and the CRC manifest
catches torn bytes from a non-atomic filesystem); a writer first
creates ``entry_<digest>.lease`` with ``O_EXCL`` — losing the race
means another replica is persisting the same entry, and this writer
simply skips (the winner's entry is equivalent). Stale leases (older
than ``FLAGS.persist_lease_ttl_s`` — a writer crashed mid-persist)
are broken.

EVERY failure mode — missing entry, truncated or corrupt file (CRC
named), version or fingerprint skew, pickle/deserialize errors, an
``io`` chaos fault — surfaces as a :class:`PersistRejected` (or plain
``OSError``) that the :mod:`spartan_tpu.persist` wrapper converts into
"recompile normally": persistence can never make ``evaluate()`` less
available than it is with the store off.
"""

from __future__ import annotations

import json
import os
import pickle
import shutil
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

from ..utils.config import FLAGS
from ..utils.log import log_debug, log_warn
from .fingerprint import FORMAT_VERSION

_MANIFEST = "manifest.json"
_PLAN = "plan.json"
_TREES = "trees.pkl"
_EXEC = "exec.bin"


class PersistRejected(RuntimeError):
    """A store entry was rejected (corrupt / stale / foreign); carries
    the machine-readable ``reason`` surfaced in metrics + st.explain."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"{reason}: {detail}" if detail else reason)
        self.reason = reason


def _fire_io_fault() -> None:
    """Chaos seam: ``io`` tokens (resilience/faults.py) fire on the
    persist load AND store paths, sharing the checkpoint site's
    occurrence counter — one module-attribute read when chaos is
    off."""
    from ..resilience import faults as _faults

    if _faults._ACTIVE is not None:
        _faults.fire("checkpoint")


def _axes_to_json(axes: Tuple) -> List[Any]:
    return [list(a) if isinstance(a, tuple) else a for a in axes]


class Entry:
    """One restored store entry: the deserialized executable plus the
    plan metadata ``expr.base._build_plan`` validates before
    pre-seeding the compile cache."""

    __slots__ = ("digest", "compiled", "out_tilings_json", "is_tuple",
                 "arg_order", "nargs", "audit")

    def __init__(self, digest: str, compiled: Any, plan_meta: Dict[str, Any]):
        self.digest = digest
        self.compiled = compiled
        self.out_tilings_json = plan_meta["out_tilings"]
        self.is_tuple = bool(plan_meta["is_tuple"])
        ao = plan_meta["arg_order"]
        self.arg_order = tuple(int(i) for i in ao) if ao is not None else None
        self.nargs = int(plan_meta["nargs"])
        # plan-audit verdict persisted alongside the executable
        # (analysis/plan_audit.py) — None for pre-audit entries
        self.audit = plan_meta.get("audit")

    def matches(self, out_tilings, is_tuple: bool,
                arg_order: Optional[Tuple[int, ...]], nargs: int) -> bool:
        """Belt check next to the digest + fingerprint: the plan this
        process just derived must agree with the persisted metadata
        before the executable is trusted."""
        return (self.out_tilings_json == [_axes_to_json(t.axes)
                                          for t in out_tilings]
                and self.is_tuple == is_tuple
                and self.arg_order == arg_order
                and self.nargs == nargs)


class PersistStore:
    """One process's handle on a (possibly shared) cache directory."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        os.makedirs(self.root, exist_ok=True)
        # prewarm's in-memory table (serve startup): digest -> Entry,
        # consulted before disk so the request path pays no IO /
        # deserialize for prewarmed plans
        self._preloaded: Dict[str, Entry] = {}

    # -- paths ----------------------------------------------------------

    def _entry_dir(self, digest: str) -> str:
        return os.path.join(self.root, f"entry_{digest}")

    def has(self, digest: str) -> bool:
        return os.path.exists(
            os.path.join(self._entry_dir(digest), _MANIFEST))

    def digests(self) -> List[str]:
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        out = []
        for n in sorted(names):
            if n.startswith("entry_") and "." not in n and os.path.exists(
                    os.path.join(self.root, n, _MANIFEST)):
                out.append(n[len("entry_"):])
        return out

    # -- load (lock-free reader) ---------------------------------------

    def _read_checked(self, edir: str, manifest: Dict[str, Any],
                      fname: str) -> bytes:
        rec = (manifest.get("files") or {}).get(fname)
        if rec is None:
            raise PersistRejected("manifest", f"no CRC record for {fname}")
        with open(os.path.join(edir, fname), "rb") as f:
            data = f.read()
        if zlib.crc32(data) != int(rec.get("crc32", -1)):
            raise PersistRejected(
                "crc", f"{fname} failed CRC32 verification (manifest "
                f"{rec.get('crc32')}, read {zlib.crc32(data)}): the "
                "file is corrupt or truncated")
        return data

    def load(self, digest: str, fingerprint: Dict[str, Any],
             prewarm_ok: bool = True) -> Optional[Entry]:
        """Restore one entry, or None on a clean miss. Raises
        :class:`PersistRejected` / ``OSError`` on anything hostile —
        the caller degrades to a recompile and counts the reason."""
        if prewarm_ok:
            hit = self._preloaded.get(digest)
            if hit is not None:
                return hit
        edir = self._entry_dir(digest)
        mpath = os.path.join(edir, _MANIFEST)
        if not os.path.exists(mpath):
            return None
        # chaos fires only when there IS an entry to read: a clean
        # miss consumes no io occurrence, so 'io@N' specs address the
        # N-th REAL persist/checkpoint IO deterministically
        _fire_io_fault()
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except ValueError as e:
            raise PersistRejected("manifest", f"unparseable: {e}")
        if manifest.get("version") != FORMAT_VERSION:
            raise PersistRejected(
                "version", f"store format {manifest.get('version')} != "
                f"{FORMAT_VERSION}")
        if manifest.get("fingerprint") != fingerprint:
            raise PersistRejected(
                "fingerprint", "environment fingerprint mismatch "
                "(jax/platform/mesh/flags changed since this entry "
                "was written)")
        plan_raw = self._read_checked(edir, manifest, _PLAN)
        trees_raw = self._read_checked(edir, manifest, _TREES)
        exec_raw = self._read_checked(edir, manifest, _EXEC)
        try:
            plan_meta = json.loads(plan_raw.decode())
        except ValueError as e:
            raise PersistRejected("meta", f"plan.json unparseable: {e}")
        try:
            in_tree, out_tree = pickle.loads(trees_raw)
            from jax.experimental import serialize_executable as _se

            compiled = _se.deserialize_and_load(exec_raw, in_tree,
                                                out_tree)
        except PersistRejected:
            raise
        except Exception as e:  # noqa: BLE001 - hostile bytes: any
            # unpickle/XLA-deserialize failure is a rejected entry,
            # never a crashed evaluate
            raise PersistRejected(
                "deserialize", f"{type(e).__name__}: {e}")
        try:
            # refresh recency: the GC policy evicts LRU-by-mtime, so a
            # served (hot) entry must not age out while it is in use
            os.utime(mpath)
        except OSError:
            pass
        return Entry(digest, compiled, plan_meta)

    # -- save (lease writer) -------------------------------------------

    def _acquire_lease(self, digest: str) -> Optional[str]:
        lease = self._entry_dir(digest) + ".lease"
        for attempt in (0, 1):
            try:
                fd = os.open(lease, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                with os.fdopen(fd, "w") as f:
                    f.write(str(os.getpid()))
                return lease
            except FileExistsError:
                try:
                    age = time.time() - os.path.getmtime(lease)
                except OSError:
                    continue  # vanished: retry the O_EXCL create
                if attempt == 0 and age > FLAGS.persist_lease_ttl_s:
                    # a writer died mid-persist: break the stale lease
                    try:
                        os.unlink(lease)
                    except OSError:
                        pass
                    continue
                return None  # live writer elsewhere: skip, it wins
        return None

    def save(self, digest: str, fingerprint: Dict[str, Any],
             plan_meta: Dict[str, Any], exec_bytes: bytes,
             trees: Tuple[Any, Any]) -> bool:
        """Persist one entry atomically; returns True when this
        process's write landed (False: another writer holds the lease,
        or the entry already exists). Raises on IO failure — the
        wrapper counts and swallows (a failed persist never fails the
        evaluation that produced the plan)."""
        final = self._entry_dir(digest)
        if os.path.exists(os.path.join(final, _MANIFEST)):
            return False  # equivalent entry already on disk
        lease = self._acquire_lease(digest)
        if lease is None:
            return False
        tmp = final + f".tmp-{os.getpid()}"
        try:
            _fire_io_fault()
            shutil.rmtree(tmp, ignore_errors=True)
            os.makedirs(tmp)
            blobs = {
                _PLAN: json.dumps(plan_meta, sort_keys=True).encode(),
                _TREES: pickle.dumps(trees),
                _EXEC: exec_bytes,
            }
            files = {}
            for fname, data in blobs.items():
                with open(os.path.join(tmp, fname), "wb") as f:
                    f.write(data)
                files[fname] = {"crc32": zlib.crc32(data),
                                "bytes": len(data)}
            manifest = {
                "version": FORMAT_VERSION,
                "digest": digest,
                "fingerprint": fingerprint,
                "mesh_epoch": fingerprint.get("mesh_epoch", 0),
                "created_unix": time.time(),
                "files": files,
            }
            # the manifest is the commit marker: written LAST, so a
            # promoted entry is complete by construction
            with open(os.path.join(tmp, _MANIFEST), "w") as f:
                json.dump(manifest, f)
            if os.path.isdir(final):  # raced a non-leased writer
                shutil.rmtree(tmp, ignore_errors=True)
                return False
            os.replace(tmp, final)
            log_debug("persist: stored entry %s (%d exec bytes)",
                      digest[:12], len(exec_bytes))
            return True
        finally:
            shutil.rmtree(tmp, ignore_errors=True)
            try:
                os.unlink(lease)
            except OSError:
                pass

    # -- eviction / hygiene --------------------------------------------

    def entry_stats(self) -> List[Tuple[float, int, str]]:
        """(mtime, bytes, digest) per committed entry — the GC's
        LRU-by-mtime view. mtime is the manifest's (touched on every
        successful load, so recency tracks USE, not just creation)."""
        out: List[Tuple[float, int, str]] = []
        try:
            names = os.listdir(self.root)
        except OSError:
            return out
        for name in names:
            if not name.startswith("entry_") or "." in name:
                continue
            edir = os.path.join(self.root, name)
            mpath = os.path.join(edir, _MANIFEST)
            try:
                mtime = os.path.getmtime(mpath)
                size = sum(
                    os.path.getsize(os.path.join(edir, f))
                    for f in os.listdir(edir))
            except OSError:
                continue  # raced an eviction/purge
            out.append((mtime, int(size), name[len("entry_"):]))
        return out

    def total_bytes(self) -> int:
        return sum(b for _, b, _ in self.entry_stats())

    def gc(self, max_bytes: int = 0, ttl_s: float = 0.0,
           protect: Tuple[str, ...] = ()) -> int:
        """Bound the store (long-lived fleets): drop entries older
        than ``ttl_s`` (by manifest mtime — refreshed on use), then
        evict LRU-by-mtime until the store fits ``max_bytes``. 0
        disables either bound; ``protect`` digests (the entry a
        caller just wrote) are never evicted. Returns entries
        evicted. Best-effort: concurrent writers may race individual
        rmtrees, which is fine — eviction of an already-gone entry is
        a no-op."""
        if not max_bytes and not ttl_s:
            return 0
        entries = sorted(self.entry_stats())  # oldest first
        now = time.time()
        evicted = 0
        live: List[Tuple[float, int, str]] = []
        for mtime, size, digest in entries:
            if digest in protect:
                live.append((mtime, size, digest))
                continue
            if ttl_s and now - mtime > ttl_s:
                self.purge(digest)
                evicted += 1
            else:
                live.append((mtime, size, digest))
        if max_bytes:
            total = sum(s for _, s, _ in live)
            for mtime, size, digest in live:
                if total <= max_bytes:
                    break
                if digest in protect:
                    continue
                self.purge(digest)
                total -= size
                evicted += 1
        if evicted:
            log_warn("persist: GC evicted %d entr%s "
                     "(max_bytes=%s, ttl_s=%s)", evicted,
                     "y" if evicted == 1 else "ies", max_bytes, ttl_s)
        return evicted

    def purge(self, digest: str) -> None:
        """Drop one entry (best-effort; used when a restored
        executable turned out not to fit this process's args)."""
        shutil.rmtree(self._entry_dir(digest), ignore_errors=True)
        self._preloaded.pop(digest, None)

    def evict_epochs_before(self, epoch: int) -> int:
        """Purge entries persisted under a dead mesh epoch (and any
        entry whose manifest no longer parses — it could never load
        anyway). Called through ``expr.base.evict_stale_plans`` after
        an elastic ``rebuild_mesh``: without this, a restart would
        resurrect plans for a mesh that no longer exists."""
        n = 0
        try:
            names = os.listdir(self.root)
        except OSError:
            return 0
        for name in names:
            if not name.startswith("entry_"):
                continue
            path = os.path.join(self.root, name)
            if not os.path.isdir(path) or ".tmp-" in name:
                continue
            try:
                with open(os.path.join(path, _MANIFEST)) as f:
                    entry_epoch = int(json.load(f).get("mesh_epoch", 0))
                if entry_epoch >= epoch:
                    continue
            except (OSError, ValueError, TypeError):
                pass  # unreadable manifest: reap it below
            shutil.rmtree(path, ignore_errors=True)
            self._preloaded.pop(name[len("entry_"):], None)
            n += 1
        if n:
            log_warn("persist: evicted %d dead-epoch entr%s from %s",
                     n, "y" if n == 1 else "ies", self.root)
        return n

    # -- prewarm --------------------------------------------------------

    def preload(self, digest: str, fingerprint: Dict[str, Any]) -> bool:
        """Deserialize one entry into the in-memory prewarm table.
        Returns False on a clean miss; raises like :meth:`load`."""
        entry = self.load(digest, fingerprint, prewarm_ok=False)
        if entry is None:
            return False
        self._preloaded[digest] = entry
        return True

    def preloaded_count(self) -> int:
        return len(self._preloaded)

    def write_manifest(self, path: str,
                       digests: Optional[List[str]] = None) -> int:
        """Write a prewarm manifest (the rolling-restart contract:
        docs/WARMSTART.md) listing ``digests`` (default: every entry
        currently in the store). Atomic via temp + replace."""
        entries = list(digests) if digests is not None else self.digests()
        tmp = path + f".tmp-{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump({"version": FORMAT_VERSION, "entries": entries}, f,
                      indent=1)
        os.replace(tmp, path)
        return len(entries)
