"""On-device iteration: ``loop(n, body_fn, *init)`` -> ``lax.fori_loop``.

The reference's iterative drivers (k-means SURVEY.md §3.4, PageRank,
regression SGD) crossed the driver<->worker boundary every iteration —
eval fan-out plus a glom per step set a hard per-iteration latency floor.
This framework already collapses one iteration into one XLA program; a
``LoopExpr`` collapses the *whole driver loop*: the body DAG is traced
once and iterated by ``lax.fori_loop`` entirely on device, so an N-step
k-means/SGD/PageRank run is ONE dispatch and ONE fetch regardless of N.

The iteration count is a traced scalar (``ScalarExpr``), so changing
``num_iter`` between runs does not recompile.

No reference counterpart exists (this is capability the RPC design could
not express); it is the TPU-native answer to SURVEY.md §3.4's
"per-iteration latency floor" note.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import functools

import jax.numpy as jnp
import numpy as np

from ..array import tiling as tiling_mod
from ..array.tiling import Tiling
from ..obs import numerics as obs_numerics
from ..obs import trace as obs_trace
from ..utils import profiling as prof
from ..utils.config import FLAGS
from .base import Expr, ValExpr, as_expr

FLAGS.define_bool(
    "shard_loop_carries", False,
    "Shard large replicated loop carries across the mesh instead of "
    "keeping one full copy per chip (the cross-replica weight-update "
    "sharding construction): a carry whose init is replicated and at "
    "least shard_carry_min_bytes large is constrained to the default "
    "divisible tiling for the whole loop — inits are re-tiled on "
    "entry, every iteration's outputs keep the sharded layout, and "
    "the final carries come back sharded. Opt-in: reduction orders "
    "inside non-elementwise bodies may change; keyed into BOTH the "
    "plan and compile cache keys so sharded and replicated loop "
    "programs never alias.")
FLAGS.define_int(
    "shard_carry_min_bytes", 1 << 16,
    "Minimum carry size (bytes) for FLAGS.shard_loop_carries to "
    "shard it: tiny carries (scalars, small stats) stay replicated — "
    "resharding them costs more than their residency.")


def _carry_shard_tiling(ini: "Expr", shape: Tuple[int, ...],
                        dtype: Any) -> Optional[Tiling]:
    """The sharded layout a loop carry gets under
    ``FLAGS.shard_loop_carries``, or None to keep the init's own
    tiling: only replicated, large-enough carries with a divisible
    axis are sharded (the default_tiling rule — largest divisible
    axis onto the mesh row axis)."""
    if not FLAGS.shard_loop_carries or not shape:
        return None
    try:
        t0 = ini.out_tiling()
    except Exception:  # noqa: BLE001 - advisory: keep the default
        return None
    if any(a is not None for a in t0.axes):
        return None  # already sharded: the user/DP chose a layout
    nbytes = int(np.prod(shape)) * np.dtype(dtype).itemsize
    if nbytes < FLAGS.shard_carry_min_bytes:
        return None
    t = tiling_mod.default_tiling(shape)
    if all(a is None for a in t.axes):
        return None  # nothing divides: replication is all there is
    return t


class CarryExpr(Expr):
    """Symbolic leaf bound to the loop-carried value inside the body DAG.

    Never evaluated on its own: ``LoopExpr._lower`` seeds its id into the
    body environment with the ``fori_loop`` carry."""

    def __init__(self, shape: Tuple[int, ...], dtype: Any, slot: int,
                 tiling: Tiling, sharded: bool = False):
        super().__init__(shape, dtype)
        self.slot = slot
        self._tiling = tiling
        # True when FLAGS.shard_loop_carries overrode a replicated
        # init: the loop constrains this carry to _tiling on entry
        # and on every iteration's output
        self.sharded = sharded

    def children(self) -> Tuple[Expr, ...]:
        return ()

    def replace_children(self, new_children: Tuple[Expr, ...]) -> "CarryExpr":
        return self

    def _lower(self, env: Dict[int, Any]) -> Any:
        raise RuntimeError(
            "loop carry used outside its loop body (exprs built from a "
            "loop body's carry cannot escape the body function)")

    def _sig(self, ctx) -> Tuple:
        # de Bruijn level relative to the enclosing loop binders (frames
        # pushed by LoopExpr._sig): nested loops with same-shaped carries
        # must NOT collide in the structural compile cache
        frames = getattr(ctx, "_loop_binders", ())
        for level, frame in enumerate(reversed(frames)):
            if self._id in frame:
                return ("carry", level, self.slot, self._shape,
                        str(self._dtype))
        # escaped carry: unique per instance so no cache entry can alias
        # it (lowering raises the escape error regardless)
        return ("carry-escaped", self._id)

    def _default_tiling(self) -> Tiling:
        return self._tiling


class LoopIndexExpr(CarryExpr):
    """Symbolic leaf bound to the fori_loop induction variable."""

    def __init__(self) -> None:
        super().__init__((), np.int32, -1, tiling_mod.replicated(0))


class LoopExpr(Expr):
    """Iterates a body DAG ``n`` times on device. Internal node — always
    consumed through ``LoopItemExpr`` projections (multi-carry loops
    evaluate all carries in one program, like ``TupleExpr``)."""

    def __init__(self, n_expr: Expr, init: Tuple[Expr, ...],
                 carries: Tuple[CarryExpr, ...],
                 body_roots: Tuple[Expr, ...],
                 index_expr: Optional[LoopIndexExpr],
                 health: bool = False, early_exit: bool = False,
                 stall_tol: float = 0.0):
        if len(init) != len(body_roots):
            raise ValueError(
                f"loop body returned {len(body_roots)} values for "
                f"{len(init)} carried inputs")
        for i, (ini, b) in enumerate(zip(init, body_roots)):
            if b.shape != ini.shape:
                raise ValueError(
                    f"loop carry {i} must keep its shape: init "
                    f"{ini.shape}, body returned {b.shape}")
        self.n_expr = n_expr
        self.init = init
        self.carries = carries
        self.body_roots = body_roots
        self.index_expr = index_expr
        self.health = bool(health or early_exit)
        self.early_exit = bool(early_exit)
        self.stall_tol = float(stall_tol)
        super().__init__((), body_roots[0].dtype)

    def children(self) -> Tuple[Expr, ...]:
        return (self.n_expr,) + self.init + self.body_roots

    def replace_children(self, new_children: Tuple[Expr, ...]) -> "LoopExpr":
        k = len(self.init)
        return LoopExpr(new_children[0], tuple(new_children[1:1 + k]),
                        self.carries, tuple(new_children[1 + k:]),
                        self.index_expr, self.health, self.early_exit,
                        self.stall_tol)

    def _carry_norm(self, vals: Tuple[Any, ...]) -> Any:
        # inf-norm, not L2: squaring overflows f32 for |carry| > ~2e19
        # and would flag healthy large-magnitude carries as divergence.
        # XLA's reduce-max does NOT reliably propagate NaN, so NaN is
        # detected explicitly and forced into the result.
        m = jnp.zeros((), jnp.float32)
        nan = jnp.zeros((), jnp.bool_)
        for v in vals:
            vf = jnp.asarray(v, jnp.float32)
            m = jnp.maximum(m, jnp.max(jnp.abs(vf)))
            nan = nan | jnp.isnan(vf).any()
        return jnp.where(nan, jnp.asarray(jnp.nan, jnp.float32), m)

    def _lower(self, env: Dict[int, Any]) -> Any:
        import jax
        from jax import lax

        n = self.n_expr.lower(env)
        # cast inits to the body's (stable) carry dtypes so the fori_loop
        # carry is type-invariant even when init was e.g. a Python int
        inits = tuple(
            jnp.asarray(i.lower(env), b.dtype)
            for i, b in zip(self.init, self.body_roots))
        sharded = any(c.sharded for c in self.carries)
        if sharded:
            # cross-replica carry sharding (FLAGS.shard_loop_carries):
            # re-tile the replicated inits once on entry; the matching
            # constraint on every body output below keeps the carry
            # sharded across iterations, so the loop's resident state
            # is 1/p per chip instead of one full copy per chip
            from ..parallel import redistribute as redist_mod

            inits = tuple(
                redist_mod.constrain(v, ce._tiling) if ce.sharded else v
                for v, ce in zip(inits, self.carries))
        trace_steps = FLAGS.trace_loop_steps
        label = f"loop#{self._id}"

        def body_core(i: Any, carry: Tuple[Any, ...]) -> Tuple[Any, ...]:
            benv = dict(env)
            if self.index_expr is not None:
                benv[self.index_expr._id] = i
            for ce, cv in zip(self.carries, carry):
                benv[ce._id] = cv
            if trace_steps:
                # per-iteration host visibility: a debug callback marks
                # the host clock each step; obs/trace turns consecutive
                # marks into "loop_step" spans with REAL per-step times
                # (the flag is part of _sig, so toggling recompiles)
                jax.debug.callback(
                    functools.partial(obs_trace.record_loop_step,
                                      label), i)
            with obs_trace.named_scope("st_loop_body"):
                out = tuple(b.lower(benv) for b in self.body_roots)
            if sharded:
                from ..parallel import redistribute as redist_mod

                out = tuple(
                    redist_mod.constrain(o, ce._tiling)
                    if ce.sharded else o
                    for o, ce in zip(out, self.carries))
            return out

        def health_of(i: Any, old: Tuple[Any, ...],
                      new: Tuple[Any, ...]) -> Tuple[Any, Any]:
            # carry norm + update norm in f32: ||new|| goes NaN/Inf the
            # iteration the carry diverges; ||new - old|| stalls toward
            # 0 as an iterative driver converges. One callback per step
            # feeds the host series (obs/numerics.record_loop_health).
            norm = self._carry_norm(new)
            un = self._carry_norm(tuple(
                jnp.asarray(a, jnp.float32) - jnp.asarray(b, jnp.float32)
                for a, b in zip(new, old)))
            jax.debug.callback(
                functools.partial(obs_numerics.record_loop_health,
                                  label), i, norm, un)
            return norm, un

        if not self.early_exit:
            def body(i: Any, carry: Tuple[Any, ...]) -> Tuple[Any, ...]:
                new = body_core(i, carry)
                if self.health:
                    health_of(i, carry, new)
                return new

            return lax.fori_loop(0, n, body, inits)

        # early-exit: a while_loop whose condition reads the PREVIOUS
        # iteration's health — stop when the carry went non-finite
        # (divergence) or, with stall_tol > 0, when the update norm
        # dropped below it (convergence/stall). The health series
        # records every executed step either way.
        f32 = jnp.float32
        stall = self.stall_tol

        def w_cond(state: Tuple[Any, ...]) -> Any:
            i, _carry, norm, un = state
            ok = i < n
            alive = jnp.isfinite(norm) & jnp.isfinite(un)
            if stall > 0:
                alive = alive & (un >= jnp.asarray(stall, f32))
            return ok & ((i == 0) | alive)

        def w_body(state: Tuple[Any, ...]) -> Tuple[Any, ...]:
            i, carry, _norm, _un = state
            new = body_core(i, carry)
            norm, un = health_of(i, carry, new)
            return (i + 1, new, norm, un)

        state0 = (jnp.zeros((), jnp.int32), inits,
                  jnp.asarray(jnp.inf, f32), jnp.asarray(jnp.inf, f32))
        return lax.while_loop(w_cond, w_body, state0)[1]

    def _sig(self, ctx) -> Tuple:
        # the carry-sharding layout is structural: a loop whose carry
        # is constrained to a sharded tiling compiles a different
        # program than the replicated one, so the chosen layouts are
        # part of the signature (None when carry sharding is off) —
        # plan AND compile keys separate automatically
        shard = tuple(c._tiling.axes if c.sharded else None
                      for c in self.carries) \
            if any(c.sharded for c in self.carries) else None
        head = (("loop", bool(FLAGS.trace_loop_steps), self.health,
                 self.early_exit, self.stall_tol, shard,
                 ctx.of(self.n_expr))
                + tuple(ctx.of(i) for i in self.init))
        # bind the carries for the body traversal (see CarryExpr._sig)
        frames = getattr(ctx, "_loop_binders", None)
        if frames is None:
            frames = []
            ctx._loop_binders = frames
        frame = {c._id: c.slot for c in self.carries}
        if self.index_expr is not None:
            frame[self.index_expr._id] = -1
        frames.append(frame)
        try:
            body = tuple(ctx.of(b) for b in self.body_roots)
        finally:
            frames.pop()
        return head + body

    def _default_tiling(self) -> Tiling:
        return tiling_mod.replicated(0)


class LoopItemExpr(Expr):
    """Projection of one carried value out of a ``LoopExpr``. Forcing any
    item of a multi-carry loop evaluates ALL sibling items through one
    ``TupleExpr`` program (one dispatch, one loop execution) and seeds
    every sibling's result cache."""

    def __init__(self, loop: LoopExpr, idx: int):
        self.loop = loop
        self.idx = idx
        b = loop.body_roots[idx]
        super().__init__(b.shape, b.dtype)

    def evaluate(self, donate=()):
        if self._result is not None:
            return self._result
        # loop-carry donation: with donate_init the init buffers feed
        # only this loop and die with it — release them to the dispatch
        donate = tuple(donate) + getattr(self.loop, "_donate_init", ())
        n = self.loop.n_expr
        static_n = getattr(n, "pyvalue", None)
        label = f"loop#{self.loop._id}"
        if FLAGS.trace_loop_steps:
            obs_trace.loop_steps_begin(label)  # anchor step 0's span
        if self.loop.health:
            obs_numerics.loop_health_begin(label)  # fresh series
        with prof.span("loop", loop=label, n=static_n,
                       carries=len(self.loop.init)):
            siblings = getattr(self.loop, "_items", None)
            # identity check, NOT `in`: Expr.__eq__ builds comparisons
            if (siblings and len(siblings) > 1
                    and any(s is self for s in siblings)):
                from .base import TupleExpr, evaluate as eval_root

                results = eval_root(TupleExpr(siblings), donate=donate)
                for item, res in zip(siblings, results):
                    item._result = res
                return self._result
            from .base import evaluate as eval_root

            return eval_root(self, donate=donate)

    force = evaluate

    def children(self) -> Tuple[Expr, ...]:
        return (self.loop,)

    def replace_children(self, new_children: Tuple[Expr, ...]
                         ) -> "LoopItemExpr":
        return LoopItemExpr(new_children[0], self.idx)

    def _lower(self, env: Dict[int, Any]) -> Any:
        return self.loop.lower(env)[self.idx]

    def _sig(self, ctx) -> Tuple:
        return ("loopitem", self.idx, ctx.of(self.loop))

    def _default_tiling(self) -> Tiling:
        carry = self.loop.carries[self.idx]
        if carry.sharded:
            # the loop constrains this carry's outputs to the sharded
            # layout every iteration — declare it so the plan's out
            # tilings (and anything consuming the result) agree
            return carry._tiling
        return self.loop.body_roots[self.idx].out_tiling()


def loop(n_iters: Any, body_fn: Callable, *init: Any,
         with_index: bool = False, donate_init: bool = False,
         health: bool = False, early_exit: bool = False,
         stall_tol: float = 0.0, checkpoint_every: int = 0,
         checkpoint_path: Optional[str] = None,
         resume: Optional[str] = None):
    """Iterate ``body_fn`` ``n_iters`` times entirely on device.

    ``body_fn`` receives one lazy expr per carried value (prepended with
    the iteration-index expr when ``with_index``) and returns the same
    number of exprs with unchanged shapes. Returns one lazy expr per
    carried value (a single expr for a single carry). Example::

        w = st.loop(100, lambda w: w - 0.1 * grad(x, y, w), w0)

    The whole loop is one XLA program: no per-iteration dispatch, no
    per-iteration fetch (contrast SURVEY.md §3.4's per-iteration
    driver<->worker round trips in the reference).

    ``donate_init``: release the init DistArrays' buffers to the loop
    dispatch (``evaluate(donate=...)`` — the carry re-feed overwrites
    them anyway, so XLA may alias their HBM for the outputs). The
    donated init arrays are invalidated when the loop is forced;
    re-using them afterwards raises.

    ``health``: emit a per-iteration carry-norm / update-norm health
    series through the numerics sentinel (one ``jax.debug.callback``
    per step; read it back via ``st.obs.numerics.loop_health()``) with
    divergence counting in the metrics registry. ``early_exit``
    (implies ``health``) lowers to a ``while_loop`` that stops when
    the carry goes non-finite — a diverged k-means/SGD run ends at the
    iteration it diverged instead of burning the remaining steps —
    or, with ``stall_tol > 0``, when the update norm drops below the
    tolerance (convergence). All three are part of the loop's
    structural signature, so toggling recompiles.

    ``checkpoint_every`` / ``checkpoint_path`` / ``resume``
    (resilience/loop_ckpt.py): split the loop into segments of
    ``checkpoint_every`` iterations, atomically snapshotting the
    carries to ``checkpoint_path`` after each segment and restoring
    the last good snapshot if a segment fails; ``resume=path`` picks
    up a killed run at its last snapshot and reproduces the
    uninterrupted final carry bit-for-bit. Checkpointed loops run
    eagerly (segments must dispatch to snapshot between them) and
    return the final carries as ``Val`` exprs — ``.glom()`` /
    ``.evaluate()`` work unchanged. Composes with ``health`` /
    ``early_exit`` (an early-exited segment ends the loop at that
    snapshot) and with the in-evaluate retry/degradation policy
    engine (docs/RESILIENCE.md).
    """
    if checkpoint_every or resume is not None:
        from ..resilience.loop_ckpt import checkpointed_loop

        return checkpointed_loop(
            n_iters, body_fn, init, with_index=with_index,
            donate_init=donate_init, health=health,
            early_exit=early_exit, stall_tol=stall_tol,
            every=int(checkpoint_every or 0), path=checkpoint_path,
            resume=resume)
    init_exprs = tuple(as_expr(i) for i in init)
    if not init_exprs:
        raise ValueError("loop needs at least one carried value")
    index_expr = LoopIndexExpr() if with_index else None

    def build(carry_specs: Tuple[Tuple[Tuple[int, ...], Any], ...]):
        carries = []
        for slot, ((shape, dtype), ini) in enumerate(
                zip(carry_specs, init_exprs)):
            shard_t = _carry_shard_tiling(ini, shape, dtype)
            carries.append(CarryExpr(
                shape, dtype, slot,
                shard_t if shard_t is not None else ini.out_tiling(),
                sharded=shard_t is not None))
        carries = tuple(carries)
        args = ((index_expr,) if with_index else ()) + carries
        out = body_fn(*args)
        if not isinstance(out, (tuple, list)):
            out = (out,)
        return carries, tuple(as_expr(o) for o in out)

    specs = tuple((i.shape, i.dtype) for i in init_exprs)
    carries, body_roots = build(specs)
    out_specs = tuple((b.shape, b.dtype) for b in body_roots)
    if len(out_specs) == len(specs) and out_specs != specs:
        # dtype promotion in the body (e.g. int init, float update):
        # rebuild with the promoted carry dtypes and require a fixpoint
        carries, body_roots = build(out_specs)
        specs2 = tuple((b.shape, b.dtype) for b in body_roots)
        if specs2 != out_specs:
            raise TypeError(
                f"loop body dtypes do not stabilize: {specs} -> "
                f"{out_specs} -> {specs2}")

    le = LoopExpr(as_expr(n_iters), init_exprs, carries, body_roots,
                  index_expr, health=health, early_exit=early_exit,
                  stall_tol=stall_tol)
    items = tuple(LoopItemExpr(le, i) for i in range(len(init_exprs)))
    le._items = items  # sibling set for one-program multi-carry forcing
    if donate_init:
        le._donate_init = tuple(
            i.value for i in init_exprs if isinstance(i, ValExpr))
    return items[0] if len(items) == 1 else items
