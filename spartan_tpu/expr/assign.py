"""Functional assignment and region writes.

Parity with ``[U] spartan/expr/assign.py`` and ``write_array.py``
(SURVEY.md §2.3: functional ``assign``, ``write_array`` region write ->
new array). The reference's reducer-merge write semantics (overlapping
writers combined by a reducer — SURVEY.md §7 hard part 3) become a
functional scatter-combine: ``x.at[region].op(value)`` traced into the
jit, deterministic by construction.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..array import extent as extent_mod
from ..array.distarray import _canonical_reducer
from ..array.extent import TileExtent
from ..array.tiling import Tiling
from .base import Expr, as_expr


class WriteExpr(Expr):
    """A new array equal to ``dst`` with ``region`` <- reducer(dst, src)."""

    def __init__(self, dst: Expr, region: TileExtent, src: Expr,
                 reducer: Any = None):
        if region.shape != src.shape:
            # numpy-style broadcast of the source into the region
            np.broadcast_shapes(region.shape, src.shape)
        self.dst = dst
        self.region = region
        self.src = src
        self.op = _canonical_reducer(reducer)
        super().__init__(dst.shape, dst.dtype)

    def children(self) -> Tuple[Expr, ...]:
        return (self.dst, self.src)

    def replace_children(self, new_children) -> "WriteExpr":
        e = WriteExpr.__new__(WriteExpr)
        WriteExpr.__init__(e, new_children[0], self.region, new_children[1],
                           None)
        e.op = self.op
        return e

    def _lower(self, env: Dict[int, Any]) -> Any:
        x = self.dst.lower(env)
        v = self.src.lower(env)
        v = jnp.broadcast_to(v.astype(x.dtype), self.region.shape)
        ref = x.at[self.region.to_slice()]
        return getattr(ref, self.op)(v)

    def _sig(self, ctx) -> Tuple:
        return ("write", self.region.ul, self.region.lr, self.op,
                ctx.of(self.dst), ctx.of(self.src))

    def _default_tiling(self) -> Tiling:
        return self.dst.out_tiling()


def assign(dst: Any, idx: Any, value: Any, reducer: Any = None) -> WriteExpr:
    """Functional ``dst[idx] = value`` -> new lazy array."""
    dst = as_expr(dst)
    region = (idx if isinstance(idx, TileExtent)
              else extent_mod.from_slice(idx, dst.shape))
    return WriteExpr(dst, region, as_expr(value), reducer)


def write_array(shape, region: Any, data: Any, dtype: Any = None,
                reducer: Any = None, tile_hint=None) -> WriteExpr:
    """The reference's ``write_array``: a fresh array of ``shape`` with
    ``data`` written at ``region`` (zeros elsewhere)."""
    from .builtins import zeros

    data = as_expr(data)
    dtype = np.dtype(dtype) if dtype is not None else data.dtype
    base = zeros(shape, dtype, tile_hint=tile_hint)
    region = (region if isinstance(region, TileExtent)
              else extent_mod.from_slice(region, base.shape))
    return WriteExpr(base, region, data, reducer)
