"""General two-operand tensor contraction, smart-tiling-planned.

Parity surface: the reference's einsum/tensordot-style contractions ran
through its shuffle GEMM machinery only for 2-D dot; everything else was
local NumPy per tile (SURVEY.md §2.3 builtins). Here the whole
2-operand contraction family — einsum, tensordot, batched matmul,
inner — lowers through one planned node so the smart-tiling pass
(SURVEY.md §2.3 pass (d)) covers it exactly like 2-D GEMMs: candidate
output grids x contraction placements, FLOP-priced compute, operand
reshard and psum bytes (tiling_cost.py). The lowering itself is a
single ``jnp.einsum`` under GSPMD — XLA's dot_general does the actual
blocking; the plan only places data.

Axis vocabulary (einsum labels):
  * batch labels — in both operands and the output,
  * contraction labels — in both operands, not in the output,
  * free labels — in one operand and the output,
  * summed labels — in one operand only (locally reduced by XLA).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..array import tiling as tiling_mod
from ..array.tiling import Tiling
from ..parallel import mesh as mesh_mod
from ..parallel import redistribute as redist_mod
from .base import Expr

_CANON = "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ"


class ContractExpr(Expr):
    """``einsum(a_labels, b_labels -> out_labels)`` over two operands.

    Labels are canonicalized single characters; ``_dot_plan`` (set by
    the smart-tiling pass, same attribute as DotExpr so the pass
    commits both uniformly) is ``(output Tiling, strategy)`` where
    strategy None = gathered contraction and a mesh axis = the largest
    contraction dim sharded there, merged by an output psum.
    """

    def __init__(self, a: Expr, b: Expr,
                 a_labels: Sequence[str], b_labels: Sequence[str],
                 out_labels: Sequence[str],
                 precision: Optional[str] = None):
        self.a = a
        self.b = b
        self.a_labels = tuple(a_labels)
        self.b_labels = tuple(b_labels)
        self.out_labels = tuple(out_labels)
        self.precision = precision
        self._dot_plan = None
        if len(self.a_labels) != a.ndim or len(self.b_labels) != b.ndim:
            raise ValueError("labels must cover every operand axis")
        if len(set(self.a_labels)) != len(self.a_labels) or \
                len(set(self.b_labels)) != len(self.b_labels):
            raise ValueError("repeated labels within one operand "
                             "(diagonals) are not contractions")
        dims: Dict[str, int] = {}
        for labels, op in ((self.a_labels, a), (self.b_labels, b)):
            for lab, d in zip(labels, op.shape):
                if dims.setdefault(lab, int(d)) != int(d):
                    raise ValueError(
                        f"size mismatch for label {lab!r}: "
                        f"{dims[lab]} vs {d}")
        for lab in self.out_labels:
            if lab not in dims:
                raise ValueError(f"output label {lab!r} not in operands")
        self._dims = dims
        shape = tuple(dims[lab] for lab in self.out_labels)
        super().__init__(shape, np.result_type(a.dtype, b.dtype))

    # -- label classification -------------------------------------------

    @property
    def contraction_labels(self) -> Tuple[str, ...]:
        """Labels in both operands but not the output, largest dim
        first (the planner shards the first one)."""
        both = [lab for lab in self.a_labels
                if lab in self.b_labels and lab not in self.out_labels]
        return tuple(sorted(both, key=lambda s: (-self._dims[s], s)))

    def label_size(self, lab: str) -> int:
        return self._dims[lab]

    def flops(self) -> float:
        """2 x (product of every distinct label's size) — the MACs of
        the contraction counted once (batch x free x contraction)."""
        f = 2.0
        for d in self._dims.values():
            f *= d
        return f

    # -- plan application -----------------------------------------------

    def plan_operand_tilings(self, out_t: Tiling,
                             strategy: Optional[str]
                             ) -> Tuple[Tiling, Tiling]:
        """Operand layouts implied by an output grid + contraction
        placement: each operand axis takes the output's mesh axis for
        its label (batch/free), the strategy axis on the primary
        contraction label, and None elsewhere."""
        mesh_of = {lab: ax
                   for lab, ax in zip(self.out_labels, out_t.axes)}
        contraction = self.contraction_labels
        primary = contraction[0] if (contraction and strategy) else None

        def operand(labels: Tuple[str, ...]) -> Tiling:
            axes = []
            for lab in labels:
                if lab == primary:
                    axes.append(strategy)
                else:
                    axes.append(mesh_of.get(lab))
            return Tiling(axes)

        return operand(self.a_labels), operand(self.b_labels)

    # -- Expr protocol --------------------------------------------------

    def children(self) -> Tuple[Expr, ...]:
        return (self.a, self.b)

    def replace_children(self, new_children) -> "ContractExpr":
        return ContractExpr(new_children[0], new_children[1],
                            self.a_labels, self.b_labels,
                            self.out_labels, self.precision)

    def _subscripts(self) -> str:
        return ("".join(self.a_labels) + "," + "".join(self.b_labels)
                + "->" + "".join(self.out_labels))

    def _lower(self, env: Dict[int, Any]) -> Any:
        av = self.a.lower(env)
        bv = self.b.lower(env)
        if self._dot_plan is not None:
            mesh = mesh_mod.get_mesh()
            out_t, strategy = self._dot_plan
            ta, tb = self.plan_operand_tilings(out_t, strategy)
            # planned reshard edges ride the redistribution seam: the
            # DP priced them from the children's committed tilings
            av = redist_mod.constrain(av, ta, mesh,
                                      src=self.a.out_tiling())
            bv = redist_mod.constrain(bv, tb, mesh,
                                      src=self.b.out_tiling())
        return jnp.einsum(self._subscripts(), av, bv,
                          precision=self.precision)

    def _sig(self, ctx) -> Tuple:
        plan = (None if self._dot_plan is None
                else (self._dot_plan[0].axes, self._dot_plan[1]))
        return ("contract", self._subscripts(), self.precision, plan,
                ctx.of(self.a), ctx.of(self.b))

    def _default_tiling(self) -> Tiling:
        if self.ndim >= 2:
            return tiling_mod.block(self.ndim)
        if self.ndim == 1:
            return tiling_mod.row(1)
        return Tiling(())


def contract(a: Expr, b: Expr, a_labels: Sequence[str],
             b_labels: Sequence[str], out_labels: Sequence[str],
             precision: Optional[str] = None) -> Optional[ContractExpr]:
    """Build a planned contraction, or None when the spec falls outside
    the contraction family (repeated labels / size mismatches needing
    broadcast) — callers fall back to a traced einsum then."""
    try:
        return ContractExpr(a, b, a_labels, b_labels, out_labels,
                            precision)
    except ValueError:
        return None


def canonicalize(per_operand: Sequence[Sequence[str]],
                 out: Sequence[str]
                 ) -> Tuple[Tuple[Tuple[str, ...], ...],
                            Tuple[str, ...]]:
    """Rename arbitrary axis labels to canonical letters in first-use
    order — distinct user spellings of the same contraction share one
    compile-cache entry."""
    mapping: Dict[str, str] = {}

    def rename(lab: str) -> str:
        if lab not in mapping:
            if len(mapping) >= len(_CANON):
                raise ValueError("too many distinct contraction labels")
            mapping[lab] = _CANON[len(mapping)]
        return mapping[lab]

    ops = tuple(tuple(rename(lab) for lab in labels)
                for labels in per_operand)
    return ops, tuple(rename(lab) for lab in out)


def contract_chain(operands, per_op_labels, out_labels,
                   precision=None):
    """Decompose an N-operand einsum into a chain of planned pairwise
    ContractExprs along np.einsum_path's greedy contraction order —
    every intermediate GEMM gets a smart-tiling plan, where the traced
    N-operand fallback is planner-invisible. Returns None when the
    chain falls outside the pairwise family (single-operand
    path steps, diagonals, broadcasting), letting the caller fall back
    to the traced einsum. Operands must already be Exprs."""
    ops = list(operands)
    labels = [tuple(ls) for ls in per_op_labels]
    out = tuple(out_labels)
    if len(ops) < 2:
        return None
    spec = ",".join("".join(ls) for ls in labels) + "->" + "".join(out)
    try:
        # zero-copy dummies: einsum_path only reads shapes
        dummies = [np.broadcast_to(np.float32(0), o.shape) for o in ops]
        path = np.einsum_path(spec, *dummies, optimize="greedy")[0]
    except Exception:
        return None
    for step in path[1:]:  # path[0] is the 'einsum_path' marker
        if len(step) != 2:
            return None  # single-operand reduction step: traced path
        j, i = sorted(step, reverse=True)
        a, la = ops.pop(j), labels.pop(j)
        b, lb = ops.pop(i), labels.pop(i)
        if not ops:  # final pair: the caller's output, in order
            inter = out
        else:
            keep = set(out)
            for ls in labels:
                keep.update(ls)
            seen = []
            for lab in lb + la:
                if lab in keep and lab not in seen:
                    seen.append(lab)
            inter = tuple(seen)
        e = contract(b, a, lb, la, inter, precision=precision)
        if e is None:
            return None
        ops.append(e)
        labels.append(inter)
    return ops[0]


def parse_einsum_2op(subscripts: str, a_ndim: int, b_ndim: int
                     ) -> Optional[Tuple[Tuple[str, ...],
                                         Tuple[str, ...],
                                         Tuple[str, ...]]]:
    """Parse a two-operand einsum spec into canonical per-axis label
    tuples, expanding ellipses against the known ranks. Returns None
    for specs outside the planned family (the caller's traced-einsum
    fallback handles those): repeated labels in an operand, or
    ellipsis batch ranks that differ between operands or broadcast."""
    parsed = parse_einsum(subscripts, (a_ndim, b_ndim))
    if parsed is None:
        return None
    (ca, cb), co = parsed
    return ca, cb, co


def parse_einsum(subscripts: str, ndims
                 ) -> Optional[Tuple[Tuple[Tuple[str, ...], ...],
                                     Tuple[str, ...]]]:
    """Parse an N-operand einsum spec into canonical per-axis label
    tuples, expanding ellipses against the known ranks. Returns None
    for specs outside the planned family (the caller's traced-einsum
    fallback handles those): repeated labels within an operand, or
    ellipsis batch ranks that differ between operands (broadcast)."""
    spec = subscripts.replace(" ", "")
    if "->" in spec:
        ins, out = spec.split("->", 1)
    else:
        ins, out = spec, None
    parts = ins.split(",")
    if len(parts) != len(ndims):
        return None

    def expand(part: str, ndim: int) -> Optional[Tuple[str, ...]]:
        if "..." in part:
            head, _, tail = part.partition("...")
            n_ell = ndim - len(head) - len(tail)
            if n_ell < 0:
                return None
            ell = tuple(f"...{i}" for i in range(n_ell))
            return tuple(head) + ell + tuple(tail)
        return tuple(part) if len(part) == ndim else None

    expanded = []
    for part, nd in zip(parts, ndims):
        ls = expand(part, nd)
        if ls is None:
            return None
        expanded.append(ls)
    ell_counts = {len([x for x in ls if x.startswith("...")])
                  for ls in expanded}
    ell_counts.discard(0)
    if len(ell_counts) > 1:
        return None  # broadcasting ellipsis ranks: traced fallback
    n_ell = ell_counts.pop() if ell_counts else 0
    ell = tuple(f"...{i}" for i in range(n_ell))
    if out is None:
        # implicit output: ellipsis dims then once-occurring labels in
        # alphabetical order (NumPy's rule)
        counts: Dict[str, int] = {}
        for part in parts:
            for lab in part.replace(".", ""):
                counts[lab] = counts.get(lab, 0) + 1
        lo = ell + tuple(sorted(
            lab for lab, c in counts.items() if c == 1))
    else:
        if "..." in out:
            head, _, tail = out.partition("...")
            lo = tuple(head) + ell + tuple(tail)
        else:
            if ell:
                return None  # einsum would error; let jnp raise it
            lo = tuple(out)
    try:
        return canonicalize(expanded, lo)
    except ValueError:
        return None  # >52 distinct labels: traced fallback handles it
