"""Reduction expressions.

Parity with the reference's ``[U] spartan/expr/reduce.py`` (SURVEY.md
§2.3: per-tile local reduce + reducer-merged update into a small target).
Per BASELINE.json:5 the reducer-merge RPC pattern becomes an XLA
all-reduce: the whole reduction is traced into the jit and GSPMD emits
``psum``-family collectives over ICI for the sharded axes. The general
form (user ``local_reduce_fn``) keeps the reference's signature; for
associative reducers applying the fn over the global (sharded) array is
semantically identical to local-reduce + merge.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from ..array import tiling as tiling_mod
from ..array.tiling import Tiling
from .base import Expr, as_expr, eval_shape_of
from .local import LocalExpr, LocalInput

Axis = Union[None, int, Tuple[int, ...]]

# name -> jnp reducer accepting (x, axis=..., keepdims=...)
REDUCE_FNS: Dict[str, Callable] = {
    "sum": jnp.sum,
    "prod": jnp.prod,
    "max": jnp.max,
    "min": jnp.min,
    "mean": jnp.mean,
    "all": jnp.all,
    "any": jnp.any,
    "argmax": jnp.argmax,
    "argmin": jnp.argmin,
}

_NO_KEEPDIMS = ("argmax", "argmin")


def _norm_axis(axis: Axis, ndim: int) -> Optional[Tuple[int, ...]]:
    if axis is None:
        return None
    if isinstance(axis, (int, np.integer)):
        axis = (int(axis),)
    return tuple(sorted(a % ndim for a in axis))


class ReduceExpr(Expr):
    """Built-in reduction over axes, with an optional fused pre-reduce
    elementwise tree.

    The plain form reduces a single child.  The fused form — produced by
    the reduce-map fusion pass (SURVEY.md §2.3 pass (b)) — holds the
    producer MapExpr's inputs directly plus its LocalExpr tree as
    ``pre``, so ``(a * b).sum()`` is ONE DAG node whose kernel applies
    the elementwise tree and reduces without materializing the map
    result (the reference folded the map into the reduction's per-tile
    local_reduce the same way)."""

    def __init__(self, input: Optional[Expr], op: str, axis: Axis = None,
                 keepdims: bool = False, dtype: Any = None,
                 _inputs: Optional[Tuple[Expr, ...]] = None,
                 _pre: Optional[LocalExpr] = None):
        if op not in REDUCE_FNS:
            raise ValueError(f"unknown reduction {op!r}")
        if _inputs is not None:
            self.inputs: Tuple[Expr, ...] = tuple(_inputs)
            self.pre: LocalExpr = _pre if _pre is not None else LocalInput(0)
        else:
            self.inputs = (input,)
            self.pre = LocalInput(0)
        self.op = op
        pre_out = eval_shape_of(lambda *xs: self.pre.emit(xs),
                                *self.inputs,
                                cache_key=("reduce_pre", self.pre.key()))
        self._pre_shape = pre_out.shape
        self.axis = _norm_axis(axis, len(pre_out.shape))
        self.keepdims = bool(keepdims)
        self.req_dtype = np.dtype(dtype) if dtype is not None else None
        out = eval_shape_of(lambda *xs: self._emit(xs), *self.inputs,
                            cache_key=("reduce", self.pre.key(), op,
                                       self.axis, self.keepdims,
                                       str(self.req_dtype)))
        super().__init__(out.shape, out.dtype)

    @property
    def input(self) -> Expr:
        """The sole child in the unfused form (API compatibility)."""
        if len(self.inputs) != 1 or not isinstance(self.pre, LocalInput):
            raise AttributeError("fused ReduceExpr has no single .input")
        return self.inputs[0]

    def _emit(self, vals: Sequence[Any]) -> Any:
        fn = REDUCE_FNS[self.op]
        x = self.pre.emit(tuple(vals))
        ax = self.axis if self.axis is None or len(self.axis) > 1 \
            else self.axis[0]
        if self.op in _NO_KEEPDIMS:
            out = fn(x, axis=ax)
        else:
            out = fn(x, axis=ax, keepdims=self.keepdims)
        if self.req_dtype is not None:
            out = out.astype(self.req_dtype)
        return out

    def children(self) -> Tuple[Expr, ...]:
        return self.inputs

    def replace_children(self, new_children: Tuple[Expr, ...]) -> "ReduceExpr":
        return ReduceExpr(None, self.op, self.axis, self.keepdims,
                          self.req_dtype, _inputs=new_children,
                          _pre=self.pre)

    def with_fused(self, inputs: Sequence[Expr],
                   pre: LocalExpr) -> "ReduceExpr":
        """Rebuild with map producers spliced into the pre-reduce tree
        (the reduce-map fusion rewrite)."""
        return ReduceExpr(None, self.op, self.axis, self.keepdims,
                          self.req_dtype, _inputs=tuple(inputs), _pre=pre)

    def _lower(self, env: Dict[int, Any]) -> Any:
        return self._emit([c.lower(env) for c in self.inputs])

    def _sig(self, ctx) -> Tuple:
        return (("reduce", self.op, self.axis, self.keepdims,
                 str(self.req_dtype), self.pre.key())
                + tuple(ctx.of(c) for c in self.inputs))

    def _pre_tiling(self) -> Tiling:
        """Tiling of the (virtual) pre-reduce value: the largest
        same-shaped input donates, mirroring MapExpr._default_tiling."""
        best: Optional[Tiling] = None
        for c in self.inputs:
            if c.shape == self._pre_shape:
                t = c.out_tiling()
                if t.sharded_axes():
                    return t
                best = best or t
        if best is not None:
            return best
        return tiling_mod.default_tiling(self._pre_shape)

    def _default_tiling(self) -> Tiling:
        t = self._pre_tiling()
        if self.axis is None:
            return tiling_mod.replicated(self.ndim)
        if self.keepdims and self.op not in _NO_KEEPDIMS:
            for a in self.axis:
                t = t.with_axis(a, None)
            return t
        for a in reversed(self.axis):
            t = t.drop_axis(a)
        return t


class GeneralReduceExpr(Expr):
    """User reduction: the reference's
    ``ReduceExpr(input, axis, dtype_fn, local_reduce_fn, accumulate_fn)``.

    ``local_reduce_fn(block, axis)`` must be jax-traceable and associative
    with ``accumulate_fn`` as the combiner; it is applied to the sharded
    global array and XLA inserts the cross-shard combine collectives."""

    def __init__(self, input: Expr, axis: Axis,
                 local_reduce_fn: Callable,
                 accumulate_fn: Optional[Callable] = None,
                 dtype: Any = None, keepdims: bool = False):
        self.input = input
        self.axis = _norm_axis(axis, input.ndim)
        self.local_reduce_fn = local_reduce_fn
        self.accumulate_fn = accumulate_fn
        self.keepdims = bool(keepdims)
        ax = self.axis if self.axis is None or len(self.axis) > 1 \
            else self.axis[0]
        out = eval_shape_of(
            lambda x: local_reduce_fn(x, axis=ax), input)
        if dtype is not None:
            out = type(out)(out.shape, np.dtype(dtype))
        super().__init__(out.shape, out.dtype)

    def children(self) -> Tuple[Expr, ...]:
        return (self.input,)

    def replace_children(self, new_children: Tuple[Expr, ...]
                         ) -> "GeneralReduceExpr":
        return GeneralReduceExpr(new_children[0], self.axis,
                                 self.local_reduce_fn, self.accumulate_fn,
                                 self.dtype, self.keepdims)

    def _lower(self, env: Dict[int, Any]) -> Any:
        x = self.input.lower(env)
        ax = self.axis if self.axis is None or len(self.axis) > 1 \
            else self.axis[0]
        out = self.local_reduce_fn(x, axis=ax)
        return out.astype(self.dtype) if out.dtype != self.dtype else out

    def _sig(self, ctx) -> Tuple:
        from .base import fn_key

        return ("greduce", fn_key(self.local_reduce_fn),
                fn_key(self.accumulate_fn) if self.accumulate_fn else None,
                self.axis, str(self.dtype), ctx.of(self.input))

    def _default_tiling(self) -> Tiling:
        t = self.input.out_tiling()
        if self.axis is None:
            return tiling_mod.replicated(self.ndim)
        for a in reversed(self.axis):
            t = t.drop_axis(a)
        return t


def reduce(input: Any, axis: Axis = None, *,
           local_reduce_fn: Callable,
           accumulate_fn: Optional[Callable] = None,
           dtype: Any = None) -> GeneralReduceExpr:
    return GeneralReduceExpr(as_expr(input), axis, local_reduce_fn,
                             accumulate_fn, dtype)


def _make(op: str):
    def builder(input: Any, axis: Axis = None, keepdims: bool = False,
                dtype: Any = None) -> ReduceExpr:
        return ReduceExpr(as_expr(input), op, axis, keepdims, dtype)

    builder.__name__ = op
    return builder


sum = _make("sum")
prod = _make("prod")
mean = _make("mean")


def max(input: Any, axis: Axis = None, keepdims: bool = False) -> ReduceExpr:
    return ReduceExpr(as_expr(input), "max", axis, keepdims)


def min(input: Any, axis: Axis = None, keepdims: bool = False) -> ReduceExpr:
    return ReduceExpr(as_expr(input), "min", axis, keepdims)


def all(input: Any, axis: Axis = None, keepdims: bool = False) -> ReduceExpr:
    return ReduceExpr(as_expr(input), "all", axis, keepdims)


def any(input: Any, axis: Axis = None, keepdims: bool = False) -> ReduceExpr:
    return ReduceExpr(as_expr(input), "any", axis, keepdims)


def argmax(input: Any, axis: Axis = None) -> ReduceExpr:
    return ReduceExpr(as_expr(input), "argmax", axis)


def argmin(input: Any, axis: Axis = None) -> ReduceExpr:
    return ReduceExpr(as_expr(input), "argmin", axis)
