"""Reduction expressions.

Parity with the reference's ``[U] spartan/expr/reduce.py`` (SURVEY.md
§2.3: per-tile local reduce + reducer-merged update into a small target).
Per BASELINE.json:5 the reducer-merge RPC pattern becomes an XLA
all-reduce: the whole reduction is traced into the jit and GSPMD emits
``psum``-family collectives over ICI for the sharded axes. The general
form (user ``local_reduce_fn``) keeps the reference's signature; for
associative reducers applying the fn over the global (sharded) array is
semantically identical to local-reduce + merge.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple, Union

import jax.numpy as jnp
import numpy as np

from ..array import tiling as tiling_mod
from ..array.tiling import Tiling
from .base import Expr, as_expr, eval_shape_of

Axis = Union[None, int, Tuple[int, ...]]

# name -> jnp reducer accepting (x, axis=..., keepdims=...)
REDUCE_FNS: Dict[str, Callable] = {
    "sum": jnp.sum,
    "prod": jnp.prod,
    "max": jnp.max,
    "min": jnp.min,
    "mean": jnp.mean,
    "all": jnp.all,
    "any": jnp.any,
    "argmax": jnp.argmax,
    "argmin": jnp.argmin,
}

_NO_KEEPDIMS = ("argmax", "argmin")


def _norm_axis(axis: Axis, ndim: int) -> Optional[Tuple[int, ...]]:
    if axis is None:
        return None
    if isinstance(axis, (int, np.integer)):
        axis = (int(axis),)
    return tuple(sorted(a % ndim for a in axis))


class ReduceExpr(Expr):
    """Built-in reduction over axes."""

    def __init__(self, input: Expr, op: str, axis: Axis = None,
                 keepdims: bool = False, dtype: Any = None):
        if op not in REDUCE_FNS:
            raise ValueError(f"unknown reduction {op!r}")
        self.input = input
        self.op = op
        self.axis = _norm_axis(axis, input.ndim)
        self.keepdims = bool(keepdims)
        self.req_dtype = np.dtype(dtype) if dtype is not None else None
        out = eval_shape_of(lambda x: self._emit(x), input)
        super().__init__(out.shape, out.dtype)

    def _emit(self, x: Any) -> Any:
        fn = REDUCE_FNS[self.op]
        ax = self.axis if self.axis is None or len(self.axis) > 1 \
            else self.axis[0]
        if self.op in _NO_KEEPDIMS:
            out = fn(x, axis=ax)
        else:
            out = fn(x, axis=ax, keepdims=self.keepdims)
        if self.req_dtype is not None:
            out = out.astype(self.req_dtype)
        return out

    def children(self) -> Tuple[Expr, ...]:
        return (self.input,)

    def replace_children(self, new_children: Tuple[Expr, ...]) -> "ReduceExpr":
        return ReduceExpr(new_children[0], self.op,
                          self.axis, self.keepdims, self.req_dtype)

    def _lower(self, env: Dict[int, Any]) -> Any:
        return self._emit(self.input.lower(env))

    def _sig(self, ctx) -> Tuple:
        return ("reduce", self.op, self.axis, self.keepdims,
                str(self.req_dtype), ctx.of(self.input))

    def _default_tiling(self) -> Tiling:
        t = self.input.out_tiling()
        if self.axis is None:
            return tiling_mod.replicated(self.ndim)
        if self.keepdims and self.op not in _NO_KEEPDIMS:
            for a in self.axis:
                t = t.with_axis(a, None)
            return t
        for a in reversed(self.axis):
            t = t.drop_axis(a)
        return t


class GeneralReduceExpr(Expr):
    """User reduction: the reference's
    ``ReduceExpr(input, axis, dtype_fn, local_reduce_fn, accumulate_fn)``.

    ``local_reduce_fn(block, axis)`` must be jax-traceable and associative
    with ``accumulate_fn`` as the combiner; it is applied to the sharded
    global array and XLA inserts the cross-shard combine collectives."""

    def __init__(self, input: Expr, axis: Axis,
                 local_reduce_fn: Callable,
                 accumulate_fn: Optional[Callable] = None,
                 dtype: Any = None, keepdims: bool = False):
        self.input = input
        self.axis = _norm_axis(axis, input.ndim)
        self.local_reduce_fn = local_reduce_fn
        self.accumulate_fn = accumulate_fn
        self.keepdims = bool(keepdims)
        ax = self.axis if self.axis is None or len(self.axis) > 1 \
            else self.axis[0]
        out = eval_shape_of(
            lambda x: local_reduce_fn(x, axis=ax), input)
        if dtype is not None:
            out = type(out)(out.shape, np.dtype(dtype))
        super().__init__(out.shape, out.dtype)

    def children(self) -> Tuple[Expr, ...]:
        return (self.input,)

    def replace_children(self, new_children: Tuple[Expr, ...]
                         ) -> "GeneralReduceExpr":
        return GeneralReduceExpr(new_children[0], self.axis,
                                 self.local_reduce_fn, self.accumulate_fn,
                                 self.dtype, self.keepdims)

    def _lower(self, env: Dict[int, Any]) -> Any:
        x = self.input.lower(env)
        ax = self.axis if self.axis is None or len(self.axis) > 1 \
            else self.axis[0]
        out = self.local_reduce_fn(x, axis=ax)
        return out.astype(self.dtype) if out.dtype != self.dtype else out

    def _sig(self, ctx) -> Tuple:
        from .base import fn_key

        return ("greduce", fn_key(self.local_reduce_fn),
                fn_key(self.accumulate_fn) if self.accumulate_fn else None,
                self.axis, str(self.dtype), ctx.of(self.input))

    def _default_tiling(self) -> Tiling:
        t = self.input.out_tiling()
        if self.axis is None:
            return tiling_mod.replicated(self.ndim)
        for a in reversed(self.axis):
            t = t.drop_axis(a)
        return t


def reduce(input: Any, axis: Axis = None, *,
           local_reduce_fn: Callable,
           accumulate_fn: Optional[Callable] = None,
           dtype: Any = None) -> GeneralReduceExpr:
    return GeneralReduceExpr(as_expr(input), axis, local_reduce_fn,
                             accumulate_fn, dtype)


def _make(op: str):
    def builder(input: Any, axis: Axis = None, keepdims: bool = False,
                dtype: Any = None) -> ReduceExpr:
        return ReduceExpr(as_expr(input), op, axis, keepdims, dtype)

    builder.__name__ = op
    return builder


sum = _make("sum")
prod = _make("prod")
mean = _make("mean")


def max(input: Any, axis: Axis = None, keepdims: bool = False) -> ReduceExpr:
    return ReduceExpr(as_expr(input), "max", axis, keepdims)


def min(input: Any, axis: Axis = None, keepdims: bool = False) -> ReduceExpr:
    return ReduceExpr(as_expr(input), "min", axis, keepdims)


def all(input: Any, axis: Axis = None, keepdims: bool = False) -> ReduceExpr:
    return ReduceExpr(as_expr(input), "all", axis, keepdims)


def any(input: Any, axis: Axis = None, keepdims: bool = False) -> ReduceExpr:
    return ReduceExpr(as_expr(input), "any", axis, keepdims)


def argmax(input: Any, axis: Axis = None) -> ReduceExpr:
    return ReduceExpr(as_expr(input), "argmax", axis)


def argmin(input: Any, axis: Axis = None) -> ReduceExpr:
    return ReduceExpr(as_expr(input), "argmin", axis)
