"""File IO for distributed arrays (``from_file`` / ``save`` / ``load``).

Parity with the reference's parallel file paths (SURVEY.md §2.3
``write_array.py``: "also from_numpy, parallel from_file"; §5 checkpoint).
``.npy`` files load through NumPy; checkpoint directories (per-shard
blobs + manifest, written by :mod:`spartan_tpu.utils.checkpoint` through
the native C++ IO pool) round-trip DistArrays with their tilings.
"""

from __future__ import annotations

import os
from typing import Any, Optional

import numpy as np

from ..array import distarray as da
from ..array.tiling import Tiling
from ..utils import checkpoint
from .base import Expr, ValExpr


def from_file(path: str, tiling: Optional[Tiling] = None,
              tile_hint=None) -> Expr:
    """Load an array from a ``.npy`` file or a checkpoint directory."""
    if os.path.isdir(path):
        arr = checkpoint.load(path, tiling=tiling)
        return ValExpr(arr)
    data = np.load(path)
    return ValExpr(da.from_numpy(data, tiling=tiling, tile_hint=tile_hint))


def save(path: str, expr: Any) -> None:
    """Save an expr/DistArray as a per-shard checkpoint directory."""
    checkpoint.save(path, expr)


def load(path: str, tiling: Optional[Tiling] = None) -> Expr:
    return from_file(path, tiling=tiling)
