"""map2: generalized map over arrays with different shapes / alignments.

Parity with ``[U] spartan/expr/map2.py`` (SURVEY.md §2.3: kernel over
blocks of multiple differently-shaped arrays, yielding data into a new
array — used by dot / k-means / convnet-style ops). Two lowering paths
(SURVEY.md §7 hard part 1):

* :func:`map2` — the traced fast path: the kernel is jax-traceable and
  receives the *global* (sharded) arrays; GSPMD owner-computes each shard
  and inserts collectives only where the kernel's data flow demands them.
  This is semantically the reference's map2 (its per-tile blocking was a
  runtime detail), with XLA doing the blocking.
* :func:`shard_map2` — the explicit per-tile path: the kernel receives
  the *local block* of each input (the reference's actual kernel calling
  convention) under ``jax.shard_map``, for owner-computes algorithms that
  need block identity (e.g. partial-sum GEMM, per-tile argmin).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import numpy as np

from ..array import tiling as tiling_mod
from ..array.tiling import Tiling
from ..parallel import mesh as mesh_mod
from .base import Expr, as_expr, eval_shape_of


class Map2Expr(Expr):
    """Traced kernel over whole (sharded) arrays."""

    def __init__(self, inputs: Sequence[Expr], fn: Callable,
                 fn_kw: Tuple[Tuple[str, Any], ...] = (),
                 out_tiling: Optional[Tiling] = None):
        self.inputs = tuple(inputs)
        self.fn = fn
        self.fn_kw = fn_kw
        from .base import fn_key

        out = eval_shape_of(lambda *xs: fn(*xs, **dict(fn_kw)),
                            *self.inputs,
                            cache_key=("map2", fn_key(fn), fn_kw))
        super().__init__(out.shape, out.dtype)
        self._map2_tiling = out_tiling

    def children(self) -> Tuple[Expr, ...]:
        return self.inputs

    def replace_children(self, new_children) -> "Map2Expr":
        return Map2Expr(new_children, self.fn, self.fn_kw,
                        self._map2_tiling)

    def _lower(self, env: Dict[int, Any]) -> Any:
        vals = [c.lower(env) for c in self.inputs]
        return self.fn(*vals, **dict(self.fn_kw))

    def _sig(self, ctx) -> Tuple:
        from .base import fn_key

        return (("map2", fn_key(self.fn), self.fn_kw)
                + tuple(ctx.of(c) for c in self.inputs))

    def _default_tiling(self) -> Tiling:
        if self._map2_tiling is not None:
            return self._map2_tiling
        return tiling_mod.default_tiling(self.shape)


def map2(arrays: Sequence[Any], fn: Callable,
         fn_kw: Optional[dict] = None,
         out_tiling: Optional[Tiling] = None) -> Map2Expr:
    inputs = tuple(as_expr(a) for a in arrays)
    kw = tuple(sorted((fn_kw or {}).items()))
    return Map2Expr(inputs, fn, kw, out_tiling)


class ShardMap2Expr(Expr):
    """Per-block kernel under shard_map — the reference's true per-tile
    kernel convention. ``in_specs[i]`` names how input i is blocked;
    ``out_spec`` how the kernel's outputs tile the result. Inputs are
    resharded to their specs before the kernel runs (owner-computes with
    explicit data placement, like smart tiling chose placements)."""

    def __init__(self, inputs: Sequence[Expr], fn: Callable,
                 in_tilings: Sequence[Tiling], out_tiling: Tiling,
                 out_shape: Sequence[int], out_dtype: Any):
        self.inputs = tuple(inputs)
        self.fn = fn
        self.in_tilings = tuple(in_tilings)
        self._out_tiling = out_tiling
        super().__init__(tuple(int(s) for s in out_shape), out_dtype)

    def children(self) -> Tuple[Expr, ...]:
        return self.inputs

    def replace_children(self, new_children) -> "ShardMap2Expr":
        return ShardMap2Expr(new_children, self.fn, self.in_tilings,
                             self._out_tiling, self._shape, self._dtype)

    def _lower(self, env: Dict[int, Any]) -> Any:
        from ..parallel import redistribute as redist_mod
        from ..utils.compat import shard_map

        mesh = mesh_mod.get_mesh()
        vals = []
        for c, t in zip(self.inputs, self.in_tilings):
            v = c.lower(env)
            # constrain operand layout so the kernel sees the blocks the
            # caller named (resharding collective if needed) — via the
            # redistribution seam, planned when the child layout is
            # known and the model predicts an explicit win
            v = redist_mod.constrain(v, t, mesh, src=c.out_tiling())
            vals.append(v)
        mapped = shard_map(
            self.fn, mesh=mesh,
            in_specs=tuple(t.spec() for t in self.in_tilings),
            out_specs=self._out_tiling.spec())
        return mapped(*vals)

    def _sig(self, ctx) -> Tuple:
        from .base import fn_key

        return (("smap2", fn_key(self.fn),
                 tuple(t.axes for t in self.in_tilings),
                 self._out_tiling.axes)
                + tuple(ctx.of(c) for c in self.inputs))

    def _default_tiling(self) -> Tiling:
        return self._out_tiling


def shard_map2(arrays: Sequence[Any], fn: Callable,
               in_tilings: Sequence[Tiling], out_tiling: Tiling,
               out_shape: Sequence[int], out_dtype: Any = np.float32
               ) -> ShardMap2Expr:
    inputs = tuple(as_expr(a) for a in arrays)
    if len(inputs) != len(in_tilings):
        raise ValueError("need one tiling per input")
    return ShardMap2Expr(inputs, fn, in_tilings, out_tiling, out_shape,
                         out_dtype)
