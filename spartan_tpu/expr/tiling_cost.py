"""Smart-tiling: ICI-cost-driven sharding assignment.

The reference's headline optimization (SURVEY.md §2.3 pass (d), ATC'15
"smart tiling"): per-array candidate tilings, edge costs = bytes moved
between producer and consumer tilings, min-cost assignment via a greedy
DP. Re-targeted per SURVEY.md §7 step 6: candidates are mesh shardings
(row / col / block / replicated), an edge's cost is the bytes a
resharding collective moves over ICI, and compute cost rewards sharded
layouts (owner-computes parallelism). The result is written as
``_forced_tiling`` on DAG nodes, which ``Expr.lower`` turns into
``with_sharding_constraint``s — so the choice actually shapes the XLA
program, and the FLAGS toggle (``opt_auto_tiling``) A/Bs it.

Cost model (per-chip bytes, ring collectives over n devices):
  * same tiling, or source replicated: 0
  * sharded -> replicated (all-gather): size * (n-1)/n
  * sharded -> differently sharded (all-to-all): size * (n-1)/n
  * compute: size * C / p, where p = devices the tiling spreads over
    (owner-computes speedup), C weights FLOP cost against ICI bytes.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..array import tiling as tiling_mod
from ..array.tiling import Tiling
from ..parallel import mesh as mesh_mod
from .base import Expr, ScalarExpr, TupleExpr, ValExpr
from .map import MapExpr
from .reduce import GeneralReduceExpr, ReduceExpr
from .reshape import TransposeExpr
from .slice import SliceExpr

_COMPUTE_WEIGHT = 4.0  # bytes-equivalent per element of local compute


def _mesh_n(mesh) -> int:
    return mesh_mod.device_count(mesh)


def _parallelism(t: Tiling, mesh) -> int:
    p = 1
    for n in t.tiles_per_dim(mesh):
        p *= n
    return p


def candidates(node: Expr, mesh) -> List[Tiling]:
    """Candidate output tilings for a node (divisible ones only)."""
    nd = node.ndim
    cands = {tiling_mod.replicated(nd)}
    if nd >= 1:
        cands.add(tiling_mod.row(nd))
    if nd >= 2:
        cands.add(tiling_mod.col(nd))
        cands.add(tiling_mod.block(nd))
    out = []
    for t in cands:
        if tiling_mod.sanitize(t, node.shape, mesh) == t:
            out.append(t)
    return out or [tiling_mod.replicated(nd)]


def reshard_cost(src: Tiling, dst: Tiling, nbytes: float, mesh) -> float:
    if src.axes == dst.axes:
        return 0.0
    if not src.sharded_axes():  # replicated source: local slicing only
        return 0.0
    n = _mesh_n(mesh)
    return nbytes * (n - 1) / max(n, 1)


def _operand_requirement(node: Expr, t: Tiling, child: Expr,
                         child_idx: int) -> Optional[Tiling]:
    """The operand tiling node wants from ``child`` when producing ``t``.
    None = no preference (child keeps its own best; GSPMD negotiates)."""
    if isinstance(node, MapExpr):
        if child.shape == node.shape:
            return t
        return tiling_mod.replicated(child.ndim)  # broadcast operand
    if isinstance(node, (ReduceExpr, GeneralReduceExpr)):
        pre_shape = getattr(node, "_pre_shape", child.shape)
        if child.shape != pre_shape:
            # broadcast operand of a fused pre-reduce tree
            return tiling_mod.replicated(child.ndim)
        if node.axis is None:
            return None  # full reduction reads any layout equally
        t_in = t
        if not (isinstance(node, ReduceExpr) and node.keepdims):
            for a in node.axis:
                t_in = t_in.add_axis(a, None)
        return t_in
    if isinstance(node, TransposeExpr):
        inv = np.argsort(node.perm)
        return t.transpose(tuple(int(i) for i in inv))
    if isinstance(node, SliceExpr):
        return None
    from .dot import DotExpr

    if isinstance(node, DotExpr) and node.a.ndim == 2 and node.b.ndim == 2:
        # the lowering constrains operands itself (row x col)
        return tiling_mod.row(2) if child_idx == 0 else tiling_mod.col(2)
    return None


def assign_tilings(root: Expr) -> Expr:
    mesh = mesh_mod.get_mesh()
    if _mesh_n(mesh) <= 1:
        return root  # single device: everything is replicated anyway

    # cost_table[node_id][tiling] = (cost, per-child chosen tilings)
    table: Dict[int, Dict[Tiling, Tuple[float, Tuple]] ] = {}

    def nbytes(e: Expr) -> float:
        return float(e.size) * e.dtype.itemsize

    def build(node: Expr) -> None:
        if node._id in table:
            return
        for c in node.children():
            build(c)
        entries: Dict[Tiling, Tuple[float, Tuple]] = {}
        if isinstance(node, (ValExpr, ScalarExpr)):
            entries[node.out_tiling()] = (0.0, ())
            table[node._id] = entries
            return
        kids = node.children()
        for t in candidates(node, mesh):
            comm = 0.0
            picks: List[Tiling] = []
            for i, c in enumerate(kids):
                req = _operand_requirement(node, t, c, i)
                best_cost = None
                best_pick = None
                for tc, (ccost, _) in table[c._id].items():
                    move = (0.0 if req is None
                            else reshard_cost(tc, req, nbytes(c), mesh))
                    total = ccost + move
                    if best_cost is None or total < best_cost:
                        best_cost, best_pick = total, tc
                comm += best_cost or 0.0
                picks.append(best_pick)
            compute = (nbytes(node) * _COMPUTE_WEIGHT
                       / _parallelism(t, mesh))
            entries[t] = (comm + compute, tuple(picks))
        table[node._id] = entries

    def commit(node: Expr, t: Tiling) -> None:
        if isinstance(node, (ValExpr, ScalarExpr)):
            return
        if node._forced_tiling is None and t is not None:
            node._forced_tiling = t
        entry = table[node._id].get(t)
        if entry is None:
            return
        for c, tc in zip(node.children(), entry[1]):
            if tc is not None:
                commit(c, tc)

    roots = root.elements if isinstance(root, TupleExpr) else (root,)
    for r in roots:
        build(r)
        best_t = min(table[r._id], key=lambda t: table[r._id][t][0])
        commit(r, best_t)
    return root


def explain(root: Expr) -> str:
    """Debug dump of chosen tilings (for the ablation reports)."""
    from .optimize import dag_nodes

    lines = []
    for n in dag_nodes(root):
        lines.append(f"{type(n).__name__}#{n._id} shape={n.shape} "
                     f"tiling={n.out_tiling().axes}")
    return "\n".join(lines)
