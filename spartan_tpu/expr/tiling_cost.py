"""ICI-cost model for the smart-tiling pass.

Skeleton for SURVEY.md §7 step 6; currently assigns nothing (each node's
``_default_tiling`` propagation stands). The full candidate/cost search
lands with the dot and shuffle layers, where resharding cost actually
bites.
"""

from __future__ import annotations

from .base import Expr


def assign_tilings(root: Expr) -> Expr:
    return root
