"""Smart-tiling: ICI-cost-driven sharding assignment.

The reference's headline optimization (SURVEY.md §2.3 pass (d), ATC'15
"smart tiling"): per-array candidate tilings, edge costs = bytes moved
between producer and consumer tilings, min-cost assignment via a greedy
DP. Re-targeted per SURVEY.md §7 step 6: candidates are mesh shardings
(row / col / block / replicated), an edge's cost is the bytes a
resharding collective moves over ICI, and compute cost rewards sharded
layouts (owner-computes parallelism). The result is written as
``_forced_tiling`` on DAG nodes, which ``Expr.lower`` turns into
``with_sharding_constraint``s — so the choice actually shapes the XLA
program, and the FLAGS toggle (``opt_auto_tiling``) A/Bs it.

Cost model (per-chip bytes, ring collectives over n devices):
  * same tiling, or source replicated: 0
  * sharded -> replicated (all-gather): size * (n-1)/n
  * sharded -> differently sharded (all-to-all): size * (n-1)/n
  * compute: size * C / p, where p = devices the tiling spreads over
    (owner-computes speedup), C weights FLOP cost against ICI bytes.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..array import tiling as tiling_mod
from ..array.tiling import Tiling
from ..parallel import mesh as mesh_mod
from ..parallel import redistribute as redist_mod
from .base import Expr, ScalarExpr, TupleExpr, ValExpr
from .map import MapExpr
from .reduce import GeneralReduceExpr, ReduceExpr
from .reshape import TransposeExpr
from .slice import SliceExpr

# Bytes-equivalent weight of local compute relative to interconnect
# bytes — applied to OUTPUT BYTES of non-contraction nodes only, where
# elementwise work is memory-bound and output bytes are the right
# proxy (~2 reads + 1 write of HBM per output byte, plus epsilon ALU).
# Contractions are priced by FLOPs instead (_flop_weight below) — the
# round-4 model priced GEMM compute by output bytes too, which is
# dimensionally wrong (a 2mnk contraction's cost grows with k at fixed
# output size) and forced a hand-chosen override here.
_COMPUTE_WEIGHT = 4.0

# Bytes-equivalent cost of one contraction FLOP: (sec/FLOP) divided by
# (sec/interconnect-byte). Measured by calibrate_flop_weight — a local
# matmul timed against a ring all-gather on the same mesh — and
# recorded per platform; the cpu value is the committed calibration
# from benchmarks/tiling_sweep.json (regenerate with tiling_ab.py
# --sweep), the tpu value derives from spec ratios (~200 bf16 TFLOP/s
# MXU vs ~4.5e10 B/s ICI per link) pending on-pod calibration.
# Override with --tiling_flop_weight.
_FLOP_WEIGHT_DEFAULTS = {"cpu": 0.005, "tpu": 2.5e-4}
_FLOP_WEIGHT_FALLBACK = 1e-3

# Weight on operand-reshard bytes in contraction plans, relative to
# output psum bytes. Operand gathers sit on the critical path BEFORE
# the matmul and replicate operand memory, while the output all-reduce
# pipelines with the epilogue — so a byte of operand movement costs
# more wall time than a byte of psum. CALIBRATED by the measured-arm
# sweep (benchmarks/tiling_ab.py --sweep, 8 GEMM layout combos + 2
# einsum batched-matmul combos x all candidate plans on the 8-device
# CPU mesh): under receive-bytes reshard pricing, weights 4 and 5 both
# bring EVERY combo's pick within 20% of its best measured arm
# (including row_t x row_t, the round-4 residual — now 1.00); 5
# measured best overall (max pick/best 1.145, within run noise).
# Override with --tiling_operand_move_weight.
_OPERAND_MOVE_WEIGHT = 5.0

# Tie-break epsilon on the same quantity: keeps plan choice
# deterministic on exact byte ties regardless of the weight above.
_OP_MOVE_EPS = 2.0 ** -20


def _mesh_n(mesh) -> int:
    return mesh_mod.device_count(mesh)


def op_class(node: Expr) -> str:
    """The node's cost-model op class — the vocabulary the calibration
    profile's per-class factors are keyed by (obs/ledger.CLASSES):
    contraction nodes are FLOP-priced, everything else is priced by
    output bytes under its class factor; 'reshard' and 'psum' are edge
    classes, not node classes."""
    if _contraction_view(node) is not None:
        return "contraction"
    if isinstance(node, MapExpr):
        return "map"
    if isinstance(node, (ReduceExpr, GeneralReduceExpr)):
        return "reduce"
    if isinstance(node, TransposeExpr):
        return "transpose"
    if isinstance(node, SliceExpr):
        return "slice"
    return "other"


def _cal_factors() -> Optional[Dict[str, float]]:
    """The active calibration profile's per-op-class factors, or None
    when ``FLAGS.cost_calibration`` is off / no profile is installed
    (obs/ledger owns the profile; one read per table build). The
    factor fingerprint is part of ``_opt_flags_key``, so calibrated
    and uncalibrated plans never alias."""
    from ..obs import ledger

    return ledger.factors()


def _parallelism(t: Tiling, mesh) -> int:
    p = 1
    for n in t.tiles_per_dim(mesh):
        p *= n
    return p


def candidates(node: Expr, mesh) -> List[Tiling]:
    """Candidate output tilings for a node (divisible ones only):
    row / col / block plus their mesh-axis-swapped (transposed)
    variants, replicated, and — for rank >= 3 (batched contractions)
    — every single-axis placement on the TRAILING axes too, which the
    leading-axes-only vocabulary above cannot express."""
    nd = node.ndim
    cands = {tiling_mod.replicated(nd)}
    if nd >= 1:
        cands.add(tiling_mod.row(nd))
        if mesh.shape.get(tiling_mod.AXIS_COL, 1) > 1:
            cands.add(tiling_mod.row_t(nd))
    if nd >= 2:
        cands.add(tiling_mod.col(nd))
        cands.add(tiling_mod.block(nd))
        if mesh.shape.get(tiling_mod.AXIS_ROW, 1) > 1:
            cands.add(tiling_mod.col_t(nd))
        if (mesh.shape.get(tiling_mod.AXIS_ROW, 1) > 1
                and mesh.shape.get(tiling_mod.AXIS_COL, 1) > 1):
            cands.add(tiling_mod.block_t(nd))
    rep = tiling_mod.replicated(nd)
    for i in range(2, nd):
        for ax in (tiling_mod.AXIS_ROW, tiling_mod.AXIS_COL):
            if mesh.shape.get(ax, 1) <= 1:
                continue
            cands.add(rep.with_axis(i, ax))
            other = (tiling_mod.AXIS_COL if ax == tiling_mod.AXIS_ROW
                     else tiling_mod.AXIS_ROW)
            if mesh.shape.get(other, 1) > 1:
                # pair placements: batch-row + trailing (dp x tp) AND
                # the two trailing-most axes together (within-batch
                # block — survives an indivisible batch axis)
                cands.add(rep.with_axis(0, other).with_axis(i, ax))
                cands.add(rep.with_axis(nd - 2, other)
                          .with_axis(nd - 1, ax))
    out = []
    for t in cands:
        if tiling_mod.sanitize(t, node.shape, mesh) == t:
            out.append(t)
    # Deterministic order, row-sharded outputs first: exact cost ties
    # resolve to the earlier candidate, and sharding axis 0 wins ties
    # (XLA's row-major layouts make row-sharded outputs cheaper than
    # the cost-equivalent col-sharded ones — measured in the --sweep).
    out.sort(key=lambda t: (not t.axes or t.axes[0] is None,
                            tuple(a is None for a in t.axes),
                            str(t.axes)))
    return out or [tiling_mod.replicated(nd)]


def _axis_size(mesh, ax) -> int:
    if ax is None:
        return 1
    if isinstance(ax, tuple):
        n = 1
        for a in ax:
            n *= mesh.shape.get(a, 1)
        return n
    return mesh.shape.get(ax, 1)


def reshard_cost(src: Tiling, dst: Tiling, nbytes: float, mesh) -> float:
    """Per-chip RECEIVE bytes to move from ``src`` to ``dst`` layout.

    Each chip ends holding ``nbytes / p_dst`` and already holds the
    expected overlap between its source shard and its destination
    shard; the difference is what the interconnect must deliver.
    Per-axis overlap fractions: an axis sharded by the SAME mesh axis
    on both sides is fully aligned (fraction = per-axis dst share); an
    axis whose split changed contributes the product of both shares
    (aligned-grid expected intersection). This prices partial
    replication correctly — e.g. a (y, None) -> (x, y) redistribute of
    a matrix replicated over x receives nbytes/16 per chip, not the
    full-mesh all-to-all the round-4 model charged (the source of its
    documented row_t x row_t mispick)."""
    if src.axes == dst.axes:
        return 0.0
    dst_frac = 1.0
    local_frac = 1.0
    for s_ax, d_ax in zip(src.axes, dst.axes):
        s = _axis_size(mesh, s_ax)
        d = _axis_size(mesh, d_ax)
        dst_frac /= d
        if s_ax == d_ax:
            local_frac /= d
        else:
            local_frac /= s * d
    return nbytes * max(0.0, dst_frac - local_frac)


def _operand_requirement(node: Expr, t: Tiling, child: Expr,
                         child_idx: int) -> Optional[Tiling]:
    """The operand tiling node wants from ``child`` when producing ``t``.
    None = no preference (child keeps its own best; GSPMD negotiates)."""
    if isinstance(node, MapExpr):
        if child.shape == node.shape:
            return t
        return tiling_mod.replicated(child.ndim)  # broadcast operand
    if isinstance(node, (ReduceExpr, GeneralReduceExpr)):
        pre_shape = getattr(node, "_pre_shape", child.shape)
        if child.shape != pre_shape:
            # broadcast operand of a fused pre-reduce tree
            return tiling_mod.replicated(child.ndim)
        if node.axis is None:
            return None  # full reduction reads any layout equally
        t_in = t
        if not (isinstance(node, ReduceExpr) and node.keepdims):
            for a in node.axis:
                t_in = t_in.add_axis(a, None)
        return t_in
    if isinstance(node, TransposeExpr):
        inv = np.argsort(node.perm)
        return t.transpose(tuple(int(i) for i in inv))
    if isinstance(node, SliceExpr):
        return None
    # DotExpr is strategy-searched inline in assign_tilings.build
    return None


def _dot_strategies(t: Tiling, mesh) -> List[Optional[str]]:
    """Contraction placements for an output grid: None = contraction
    replicated (gathered operands); a mesh axis = contraction sharded
    there, merged by an output psum. Only axes the output grid does
    not already use are available."""
    used = {a for a in t.axes if a is not None}
    out: List[Optional[str]] = [None]
    for ax in mesh.axis_names:
        if ax not in used and mesh.shape.get(ax, 1) > 1:
            out.append(ax)
    return out


def _contraction_view(node: Expr):
    """``(flops, reqs_fn)`` for nodes the planner strategy-searches —
    2-D DotExpr GEMMs and every ContractExpr (einsum / tensordot /
    batched matmul / inner). ``reqs_fn(t, s)`` maps an output grid +
    contraction placement to the two operand tilings the lowering will
    constrain; None for non-contraction nodes."""
    from .contract import ContractExpr
    from .dot import DotExpr

    if isinstance(node, DotExpr) and node.a.ndim == 2 \
            and node.b.ndim == 2:
        m, k = node.a.shape
        n = node.b.shape[1]

        def reqs(t: Tiling, s: Optional[str]):
            return Tiling((t.axes[0], s)), Tiling((s, t.axes[1]))

        return 2.0 * m * k * n, reqs, True
    if isinstance(node, ContractExpr):
        return (node.flops(), node.plan_operand_tilings,
                bool(node.contraction_labels))
    return None


def _compute_weight() -> float:
    from ..utils.config import FLAGS

    w = float(getattr(FLAGS, "tiling_compute_weight", 0.0) or 0.0)
    return w if w > 0 else _COMPUTE_WEIGHT


def _flop_weight() -> float:
    from ..utils.config import FLAGS

    w = float(getattr(FLAGS, "tiling_flop_weight", 0.0) or 0.0)
    if w > 0:
        return w
    import jax

    return _FLOP_WEIGHT_DEFAULTS.get(jax.default_backend(),
                                     _FLOP_WEIGHT_FALLBACK)


def _operand_move_weight() -> float:
    from ..utils.config import FLAGS

    w = float(getattr(FLAGS, "tiling_operand_move_weight", 0.0) or 0.0)
    return w if w > 0 else _OPERAND_MOVE_WEIGHT


def _memory_weight() -> float:
    """Soft memory pressure (FLAGS.tiling_memory_weight, default 0):
    bytes-equivalent penalty per byte of a candidate's PER-CHIP output
    residency. Positive values bias the DP toward finer tilings —
    the gentle end of the memory governor's spectrum (docs/MEMORY.md),
    before a budget breach forces a whole degradation rung."""
    from ..utils.config import FLAGS

    return float(getattr(FLAGS, "tiling_memory_weight", 0.0) or 0.0)


def _build_table(root: Expr, mesh) -> Dict:
    """Bottom-up candidate cost table:
    ``table[node_id][tiling] = (cost, per-child picks, strategy)``
    where strategy is the chosen contraction placement for GEMMs."""
    table: Dict[int, Dict[Tiling, Tuple[float, Tuple, Optional[str]]]] = {}
    weight = _compute_weight()
    flop_w = _flop_weight()
    move_w = _operand_move_weight()
    mem_w = _memory_weight()
    # profile-guided calibration (obs/ledger): per-op-class factors
    # multiply the matching cost terms; identity when no profile is
    # active. Applied symmetrically to selection (best_child's move
    # weight) and pricing so the DP stays self-consistent.
    cal = _cal_factors()
    reshard_f = cal.get("reshard", 1.0) if cal else 1.0
    psum_f = cal.get("psum", 1.0) if cal else 1.0
    flop_f = cal.get("contraction", 1.0) if cal else 1.0
    # redistribution planner (parallel/redistribute): edges priced by
    # the modeled collective schedule (per-collective calibrated
    # factors applied INSIDE edge_cost, clamped at the receive-bytes
    # floor) instead of the raw receive-bytes heuristic. The flag is
    # part of _opt_flags_key, so planned and heuristic plans never
    # alias; when on, the per-edge factor weight moves inside the
    # planner (move_unit 1.0) and the psum term is calibrated by its
    # reduce-scatter + all-gather halves, matching class_components.
    planner = redist_mod.planner_on()
    move_unit = 1.0 if planner else reshard_f
    if planner and cal:
        psum_f = 0.5 * (cal.get("reduce_scatter", 1.0)
                        + cal.get("all_gather", 1.0))

    def nbytes(e: Expr) -> float:
        return float(e.size) * e.dtype.itemsize

    def move_cost(tc: Tiling, req: Tiling, nb: float) -> float:
        if planner:
            return redist_mod.edge_cost(tc, req, nb, mesh, cal)
        return reshard_cost(tc, req, nb, mesh)

    def best_child(c: Expr, req: Optional[Tiling], w: float = 1.0
                   ) -> Tuple[float, Optional[Tiling], float]:
        """Cheapest child entry under requirement ``req``, with the
        reshard move charged at weight ``w`` (GEMM operand moves use
        _OPERAND_MOVE_WEIGHT so selection and plan pricing agree —
        otherwise a reshard-heavy child could win selection at weight
        1 and then be priced at w). Returns (total, pick, move)."""
        best_cost = None
        best_pick = None
        best_move = 0.0
        for tc, entry in table[c._id].items():
            move = (0.0 if req is None
                    else move_cost(tc, req, nbytes(c)))
            total = entry[0] + w * move
            # on a total tie prefer the lower-move entry, so the move
            # fed into the _OP_MOVE_EPS tie-break is itself
            # deterministic (not dict-iteration-order dependent)
            if (best_cost is None or total < best_cost
                    or (total == best_cost and move < best_move)):
                best_cost, best_pick, best_move = total, tc, move
        return best_cost or 0.0, best_pick, best_move

    def build(node: Expr) -> None:
        if node._id in table:
            return
        for c in node.children():
            build(c)
        entries: Dict[Tiling, Tuple[float, Tuple, Optional[str]]] = {}
        if isinstance(node, (ValExpr, ScalarExpr)):
            entries[node.out_tiling()] = (0.0, (), None)
            table[node._id] = entries
            return
        kids = node.children()
        cview = _contraction_view(node)
        node_f = cal.get(op_class(node), 1.0) if cal else 1.0
        for t in candidates(node, mesh):
            # soft memory term: per-chip output residency of this
            # candidate, charged on contraction and non-contraction
            # nodes alike (0 when the weight flag is off)
            memcost = (mem_w * nbytes(node) / _parallelism(t, mesh)
                       if mem_w else 0.0)
            compute = (nbytes(node) * weight * node_f
                       / _parallelism(t, mesh))
            if cview is not None:
                # search contraction strategies: s=None gathers the
                # contraction onto the output grid, s=mesh-axis shards
                # it there and pays an output psum — reqs_fn mirrors
                # the node's _lower exactly. Compute is FLOP-priced
                # (2mnk-style, _flop_weight): a sharded contraction
                # multiplies the parallelism by the strategy axis.
                flops, reqs_fn, has_contraction = cview
                best = None
                strategies = (_dot_strategies(t, mesh)
                              if has_contraction else [None])
                for s in strategies:
                    req_a, req_b = reqs_fn(t, s)
                    ca, pa, ma = best_child(kids[0], req_a,
                                            move_w * move_unit)
                    cb, pb, mb = best_child(kids[1], req_b,
                                            move_w * move_unit)
                    psum = 0.0
                    if s is not None:
                        # ring all-reduce of each chip's PARTIAL — the
                        # output shard under grid t, not the full
                        # array: reduce-scatter + all-gather moves
                        # ~2 x shard x (ns-1)/ns per chip
                        ns = _axis_size(mesh, s)
                        psum = (2.0 * nbytes(node) * psum_f
                                / _parallelism(t, mesh)
                                * (ns - 1) / ns)
                    fl = (flops * flop_w * flop_f
                          / (_parallelism(t, mesh) * _axis_size(mesh, s)))
                    # operand movement is charged at move_w inside
                    # best_child (critical path before the matmul —
                    # see _OPERAND_MOVE_WEIGHT); the epsilon keeps
                    # exact ties deterministic
                    tot = (ca + cb + psum + fl + memcost
                           + (ma + mb) * _OP_MOVE_EPS)
                    if best is None or tot < best[0]:
                        best = (tot, (pa, pb), s)
                entries[t] = (best[0], best[1], best[2])
                continue
            comm = 0.0
            picks: List[Tiling] = []
            for i, c in enumerate(kids):
                req = _operand_requirement(node, t, c, i)
                ccost, pick, _ = best_child(c, req, move_unit)
                comm += ccost
                picks.append(pick)
            entries[t] = (comm + compute + memcost, tuple(picks), None)
        table[node._id] = entries

    roots = root.elements if isinstance(root, TupleExpr) else (root,)
    for r in roots:
        build(r)
    return table


def assign_tilings(root: Expr) -> Expr:
    from .contract import ContractExpr
    from .dot import DotExpr, DotShardMapExpr

    mesh = mesh_mod.get_mesh()
    if _mesh_n(mesh) <= 1:
        return root  # single device: everything is replicated anyway
    table = _build_table(root, mesh)

    def commit(node: Expr, t: Tiling, force: bool) -> None:
        if isinstance(node, (ValExpr, ScalarExpr)):
            return
        entry = table[node._id].get(t)
        if entry is not None and getattr(node, "_plan_cost", None) is None:
            # cost-model estimate for the chosen tiling (bytes-equivalent
            # units, subtree-cumulative) — surfaced by st.explain
            node._plan_cost = entry[0]
        # Constrain only MATERIALIZATION points: GEMMs (whose lowering
        # derives operand layouts from the chosen plan) and the root.
        # Forcing every intermediate (e.g. a transpose) pins layouts XLA
        # would otherwise optimize through — measured 25% slower and 2x
        # the collectives on the dot-T-dot chain (benchmarks/tiling_ab).
        # A plan equal to the node's natural behavior is skipped: a
        # redundant with_sharding_constraint is not free, it steers
        # XLA's propagation pass into worse solutions. 2-D GEMMs get
        # their searched plan recorded on a SEPARATE attribute
        # (``_dot_plan`` — operand placement, consumed by
        # DotExpr._lower) so the plan always reaches the lowering
        # without forcing a redundant *output* constraint when the
        # chosen grid equals the default.
        strategy = entry[2] if entry is not None else None
        is_gemm = isinstance(node, (DotExpr, DotShardMapExpr,
                                    ContractExpr))
        plans_operands = (isinstance(node, ContractExpr)
                          or (isinstance(node, DotExpr)
                              and node.a.ndim == 2 and node.b.ndim == 2))
        nondefault = t is not None and t != node._default_tiling()
        if plans_operands:
            # first visit wins (diamond DAGs); the forced output — when
            # non-default — always matches the recorded operand plan
            if entry is not None and node._dot_plan is None:
                node._dot_plan = (t, strategy)
                if nondefault and node._forced_tiling is None:
                    node._forced_tiling = t
        elif node._forced_tiling is None and (
                (force and nondefault) or (is_gemm and nondefault)):
            node._forced_tiling = t
        if entry is None:
            return
        for c, tc in zip(node.children(), entry[1]):
            if tc is not None:
                commit(c, tc, False)

    roots = root.elements if isinstance(root, TupleExpr) else (root,)
    for r in roots:
        best_t = min(table[r._id], key=lambda t: table[r._id][t][0])
        commit(r, best_t, True)
    return root


def gemm_plan_costs(root: Expr) -> Dict:
    """Candidate ``(output tiling, strategy, model cost)`` lists for
    every planned contraction node in ``root`` (2-D GEMMs and
    ContractExpr einsum/tensordot/batched-matmul) — the validation
    surface for the cost model (benchmarks/tiling_ab.py --sweep and
    tests/test_tiling_calibration.py force each candidate as a
    measured arm and compare the model's ranking against wall time).
    Returns ``{node: [(Tiling, strategy, cost), ...]}``."""
    from .optimize import dag_nodes

    mesh = mesh_mod.get_mesh()
    if _mesh_n(mesh) <= 1:
        return {}
    table = _build_table(root, mesh)
    out = {}
    for n in dag_nodes(root):
        if _contraction_view(n) is not None and n._id in table:
            out[n] = sorted(
                ((t, e[2], e[0]) for t, e in table[n._id].items()),
                key=lambda x: x[2])
    return out


def class_components(root: Expr, mesh=None) -> Dict[str, float]:
    """Per-op-class decomposition of the CHOSEN plan's modeled cost.

    Re-prices the optimized DAG at its committed tilings
    (``out_tiling()``, post-assignment) with the same formulas as
    ``_build_table`` — node compute under its class, contraction FLOPs
    under 'contraction', operand moves under 'reshard', output
    all-reduces under 'psum' — WITHOUT the candidate search. This is
    the vector the cost ledger records per plan and ``fit_profile``
    regresses measured dispatch time against: the classes are exactly
    the terms a calibration factor can scale, so a fitted profile's
    corrections mean the same thing here and in the DP. Uncalibrated
    by construction (factors of 1): a profile fitted FROM these
    components corrects the base model, not itself. Empty on a
    single-device mesh (no DP ran)."""
    from .base import ScalarExpr, ValExpr
    from .optimize import dag_nodes

    mesh = mesh or mesh_mod.get_mesh()
    if _mesh_n(mesh) <= 1:
        return {}
    weight = _compute_weight()
    flop_w = _flop_weight()
    move_w = _operand_move_weight()
    # planner on: reshard edges decompose into their chosen schedule's
    # per-collective bytes (all_gather / all_to_all) and psum into its
    # reduce-scatter + all-gather halves, so fit_profile calibrates
    # each collective's factor independently (obs/ledger.CLASSES)
    planner = redist_mod.planner_on()
    comp: Dict[str, float] = {}

    def add(cls: str, v: float) -> None:
        if v:
            comp[cls] = comp.get(cls, 0.0) + float(v)

    def move(child: Expr, req: Optional[Tiling], w: float) -> None:
        if req is None:
            return
        try:
            src = child.out_tiling()
        except Exception:
            return
        nb = float(child.size) * child.dtype.itemsize
        if planner:
            for cls, v in redist_mod.edge_components(src, req, nb,
                                                     mesh).items():
                add(cls, w * v)
            return
        add("reshard", w * reshard_cost(src, req, nb, mesh))

    def add_psum(v: float) -> None:
        if planner:
            # a ring all-reduce is reduce-scatter + all-gather of the
            # shard — split the modeled bytes so each half calibrates
            # under its own collective class
            add("reduce_scatter", 0.5 * v)
            add("all_gather", 0.5 * v)
        else:
            add("psum", v)

    for n in dag_nodes(root):
        if isinstance(n, (ValExpr, ScalarExpr)):
            continue
        try:
            t = n.out_tiling()
        except Exception:
            continue
        nbytes = float(n.size) * n.dtype.itemsize
        kids = n.children()
        cview = _contraction_view(n)
        if cview is not None and len(kids) >= 2:
            flops, reqs_fn, _has = cview
            plan = getattr(n, "_dot_plan", None)
            grid, s = plan if plan is not None else (t, None)
            par = _parallelism(grid, mesh)
            add("contraction", flops * flop_w
                / (par * _axis_size(mesh, s)))
            if s is not None:
                ns = _axis_size(mesh, s)
                add_psum(2.0 * nbytes / par * (ns - 1) / ns)
            try:
                reqs = reqs_fn(grid, s)
            except Exception:
                reqs = None
            if reqs is not None:
                for c, req in zip(kids, reqs):
                    move(c, req, move_w)
            continue
        add(op_class(n), nbytes * weight / _parallelism(t, mesh))
        for i, c in enumerate(kids):
            try:
                req = _operand_requirement(n, t, c, i)
            except Exception:
                req = None
            move(c, req, 1.0)
    return {k: round(v, 3) for k, v in comp.items()}


def calibrate_flop_weight(n: int = 512, iters: int = 5,
                          mesh=None) -> float:
    """Measure the bytes-equivalent cost of one FLOP on this backend.

    Times a single-device ``n x n`` matmul (``2n^3`` FLOPs) against a
    row->replicated all-gather of the same matrix (``n^2 * itemsize *
    (p-1)/p`` per-chip bytes) and returns
    ``(t_mm / flops) / (t_ag / bytes)`` — seconds-per-FLOP over
    seconds-per-interconnect-byte, exactly the units the contraction
    compute term multiplies by 2mnk. Dimensionally consistent, so one
    calibration transfers across shapes (unlike the round-4
    output-bytes weight, which baked n into the constant). Record
    per-platform values via ``--tiling_flop_weight``."""
    import jax
    import jax.numpy as jnp

    from ..utils import profiling as prof

    mesh = mesh or mesh_mod.get_mesh()
    p = _mesh_n(mesh)
    if p <= 1:
        return _flop_weight()
    x = jnp.asarray(np.random.RandomState(0).rand(n, n).astype(np.float32))
    mm = jax.jit(lambda a: a @ a)
    jax.block_until_ready(mm(x))
    with prof.stopwatch() as sw:
        for _ in range(iters):
            jax.block_until_ready(mm(x))
    t_mm = sw.elapsed / iters

    row = tiling_mod.row(2)
    rep = tiling_mod.replicated(2)
    xs = jax.device_put(x, row.sharding(mesh))
    gather = jax.jit(lambda a: a, out_shardings=rep.sharding(mesh))
    jax.block_until_ready(gather(xs))
    with prof.stopwatch() as sw:
        for _ in range(iters):
            jax.block_until_ready(gather(xs))
    t_ag = sw.elapsed / iters
    if t_ag <= 0:
        return _flop_weight()
    flops = 2.0 * n * n * n
    ag_bytes = float(n) * n * x.dtype.itemsize * (p - 1) / p
    return float((t_mm / flops) / (t_ag / ag_bytes))


def explain(root: Expr) -> str:
    """Debug dump of chosen tilings (for the ablation reports)."""
    from .optimize import dag_nodes

    lines = []
    for n in dag_nodes(root):
        lines.append(f"{type(n).__name__}#{n._id} shape={n.shape} "
                     f"tiling={n.out_tiling().axes}")
    return "\n".join(lines)
