"""Smart-tiling: ICI-cost-driven sharding assignment.

The reference's headline optimization (SURVEY.md §2.3 pass (d), ATC'15
"smart tiling"): per-array candidate tilings, edge costs = bytes moved
between producer and consumer tilings, min-cost assignment via a greedy
DP. Re-targeted per SURVEY.md §7 step 6: candidates are mesh shardings
(row / col / block / replicated), an edge's cost is the bytes a
resharding collective moves over ICI, and compute cost rewards sharded
layouts (owner-computes parallelism). The result is written as
``_forced_tiling`` on DAG nodes, which ``Expr.lower`` turns into
``with_sharding_constraint``s — so the choice actually shapes the XLA
program, and the FLAGS toggle (``opt_auto_tiling``) A/Bs it.

Cost model (per-chip bytes, ring collectives over n devices):
  * same tiling, or source replicated: 0
  * sharded -> replicated (all-gather): size * (n-1)/n
  * sharded -> differently sharded (all-to-all): size * (n-1)/n
  * compute: size * C / p, where p = devices the tiling spreads over
    (owner-computes speedup), C weights FLOP cost against ICI bytes.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..array import tiling as tiling_mod
from ..array.tiling import Tiling
from ..parallel import mesh as mesh_mod
from .base import Expr, ScalarExpr, TupleExpr, ValExpr
from .map import MapExpr
from .reduce import GeneralReduceExpr, ReduceExpr
from .reshape import TransposeExpr
from .slice import SliceExpr

# Bytes-equivalent weight of local compute relative to interconnect
# bytes. 4.0 is a HAND-CHOSEN default: the CPU-mesh measurement
# (calibrate_compute_weight, recorded as ~0.9 in
# benchmarks/tiling_sweep.json) produced worse plan picks when applied
# directly — the model's compute term scales with output bytes, not
# FLOPs, so the measured ratio at one shape does not transfer. Override
# per-platform with --tiling_compute_weight after validating with the
# --sweep.
_COMPUTE_WEIGHT = 4.0

# Weight on operand-reshard bytes in GEMM plans, relative to output
# psum bytes. Operand gathers sit on the critical path BEFORE the
# matmul and replicate operand memory, while the output all-reduce
# pipelines with the epilogue — so a byte of operand movement costs
# more wall time than a byte of psum. CALIBRATED by the measured-arm
# sweep (benchmarks/tiling_ab.py --sweep, 8 layout combos x all
# candidate plans on the 8-device CPU mesh): with weight 1 the model
# picked gathered plans measuring up to 2.2x slower than the best
# psum arm (col x row combo); weight 2 brings every combo's pick
# within 20% of the best measured arm EXCEPT row_t x row_t (1.25x —
# the known residual documented in tiling_sweep.json's notes).
# Override with --tiling_operand_move_weight.
_OPERAND_MOVE_WEIGHT = 2.0

# Tie-break epsilon on the same quantity: keeps plan choice
# deterministic on exact byte ties regardless of the weight above.
_OP_MOVE_EPS = 2.0 ** -20


def _mesh_n(mesh) -> int:
    return mesh_mod.device_count(mesh)


def _parallelism(t: Tiling, mesh) -> int:
    p = 1
    for n in t.tiles_per_dim(mesh):
        p *= n
    return p


def candidates(node: Expr, mesh) -> List[Tiling]:
    """Candidate output tilings for a node (divisible ones only):
    row / col / block plus their mesh-axis-swapped (transposed)
    variants, and replicated."""
    nd = node.ndim
    cands = {tiling_mod.replicated(nd)}
    if nd >= 1:
        cands.add(tiling_mod.row(nd))
        if mesh.shape.get(tiling_mod.AXIS_COL, 1) > 1:
            cands.add(tiling_mod.row_t(nd))
    if nd >= 2:
        cands.add(tiling_mod.col(nd))
        cands.add(tiling_mod.block(nd))
        if mesh.shape.get(tiling_mod.AXIS_ROW, 1) > 1:
            cands.add(tiling_mod.col_t(nd))
        if (mesh.shape.get(tiling_mod.AXIS_ROW, 1) > 1
                and mesh.shape.get(tiling_mod.AXIS_COL, 1) > 1):
            cands.add(tiling_mod.block_t(nd))
    out = []
    for t in cands:
        if tiling_mod.sanitize(t, node.shape, mesh) == t:
            out.append(t)
    # Deterministic order, row-sharded outputs first: exact cost ties
    # resolve to the earlier candidate, and sharding axis 0 wins ties
    # (XLA's row-major layouts make row-sharded outputs cheaper than
    # the cost-equivalent col-sharded ones — measured in the --sweep).
    out.sort(key=lambda t: (not t.axes or t.axes[0] is None,
                            tuple(a is None for a in t.axes),
                            str(t.axes)))
    return out or [tiling_mod.replicated(nd)]


def _axis_size(mesh, ax) -> int:
    if ax is None:
        return 1
    if isinstance(ax, tuple):
        n = 1
        for a in ax:
            n *= mesh.shape.get(a, 1)
        return n
    return mesh.shape.get(ax, 1)


def reshard_cost(src: Tiling, dst: Tiling, nbytes: float, mesh) -> float:
    """Per-chip bytes to move from ``src`` to ``dst`` layout.

    Axis-wise: refining an unsharded axis (None -> mesh axis) is a
    local slice (0 bytes); coarsening (mesh axis -> None) all-gathers
    over that axis; moving an axis to a *different* mesh axis is an
    all-to-all over the involved devices."""
    if src.axes == dst.axes:
        return 0.0
    if not src.sharded_axes():  # replicated source: local slicing only
        return 0.0
    cost = 0.0
    a2a = False
    for s_ax, d_ax in zip(src.axes, dst.axes):
        if s_ax == d_ax or s_ax is None:
            continue
        if d_ax is None:
            n = _axis_size(mesh, s_ax)
            cost += nbytes * (n - 1) / max(n, 1)
        else:
            a2a = True
    if a2a:
        n = _mesh_n(mesh)
        cost = max(cost, nbytes * (n - 1) / max(n, 1))
    return cost


def _operand_requirement(node: Expr, t: Tiling, child: Expr,
                         child_idx: int) -> Optional[Tiling]:
    """The operand tiling node wants from ``child`` when producing ``t``.
    None = no preference (child keeps its own best; GSPMD negotiates)."""
    if isinstance(node, MapExpr):
        if child.shape == node.shape:
            return t
        return tiling_mod.replicated(child.ndim)  # broadcast operand
    if isinstance(node, (ReduceExpr, GeneralReduceExpr)):
        pre_shape = getattr(node, "_pre_shape", child.shape)
        if child.shape != pre_shape:
            # broadcast operand of a fused pre-reduce tree
            return tiling_mod.replicated(child.ndim)
        if node.axis is None:
            return None  # full reduction reads any layout equally
        t_in = t
        if not (isinstance(node, ReduceExpr) and node.keepdims):
            for a in node.axis:
                t_in = t_in.add_axis(a, None)
        return t_in
    if isinstance(node, TransposeExpr):
        inv = np.argsort(node.perm)
        return t.transpose(tuple(int(i) for i in inv))
    if isinstance(node, SliceExpr):
        return None
    # DotExpr is strategy-searched inline in assign_tilings.build
    return None


def _dot_strategies(t: Tiling, mesh) -> List[Optional[str]]:
    """Contraction placements for a GEMM with output grid (m_r, m_c):
    None = contraction replicated (gathered operands); a mesh axis =
    contraction sharded there, merged by an output psum."""
    used = {a for a in t.axes[:2] if a is not None}
    out: List[Optional[str]] = [None]
    for ax in mesh.axis_names:
        if ax not in used and mesh.shape.get(ax, 1) > 1:
            out.append(ax)
    return out


def _compute_weight() -> float:
    from ..utils.config import FLAGS

    w = float(getattr(FLAGS, "tiling_compute_weight", 0.0) or 0.0)
    return w if w > 0 else _COMPUTE_WEIGHT


def _operand_move_weight() -> float:
    from ..utils.config import FLAGS

    w = float(getattr(FLAGS, "tiling_operand_move_weight", 0.0) or 0.0)
    return w if w > 0 else _OPERAND_MOVE_WEIGHT


def _build_table(root: Expr, mesh) -> Dict:
    """Bottom-up candidate cost table:
    ``table[node_id][tiling] = (cost, per-child picks, strategy)``
    where strategy is the chosen contraction placement for GEMMs."""
    from .dot import DotExpr

    table: Dict[int, Dict[Tiling, Tuple[float, Tuple, Optional[str]]]] = {}
    weight = _compute_weight()
    move_w = _operand_move_weight()

    def nbytes(e: Expr) -> float:
        return float(e.size) * e.dtype.itemsize

    def best_child(c: Expr, req: Optional[Tiling], w: float = 1.0
                   ) -> Tuple[float, Optional[Tiling], float]:
        """Cheapest child entry under requirement ``req``, with the
        reshard move charged at weight ``w`` (GEMM operand moves use
        _OPERAND_MOVE_WEIGHT so selection and plan pricing agree —
        otherwise a reshard-heavy child could win selection at weight
        1 and then be priced at w). Returns (total, pick, move)."""
        best_cost = None
        best_pick = None
        best_move = 0.0
        for tc, entry in table[c._id].items():
            move = (0.0 if req is None
                    else reshard_cost(tc, req, nbytes(c), mesh))
            total = entry[0] + w * move
            # on a total tie prefer the lower-move entry, so the move
            # fed into the _OP_MOVE_EPS tie-break is itself
            # deterministic (not dict-iteration-order dependent)
            if (best_cost is None or total < best_cost
                    or (total == best_cost and move < best_move)):
                best_cost, best_pick, best_move = total, tc, move
        return best_cost or 0.0, best_pick, best_move

    def build(node: Expr) -> None:
        if node._id in table:
            return
        for c in node.children():
            build(c)
        entries: Dict[Tiling, Tuple[float, Tuple, Optional[str]]] = {}
        if isinstance(node, (ValExpr, ScalarExpr)):
            entries[node.out_tiling()] = (0.0, (), None)
            table[node._id] = entries
            return
        kids = node.children()
        is_gemm = (isinstance(node, DotExpr)
                   and node.a.ndim == 2 and node.b.ndim == 2)
        for t in candidates(node, mesh):
            compute = (nbytes(node) * weight
                       / _parallelism(t, mesh))
            if is_gemm:
                # search contraction strategies: operand layouts are
                # A (m_r, k), B (k, m_c); k=None gathers the
                # contraction, k=mesh-axis shards it and pays an
                # output psum — mirroring DotExpr._lower exactly.
                # A sharded contraction multiplies the compute
                # parallelism: the FLOPs spread over output grid x k.
                m_r, m_c = t.axes[0], t.axes[1]
                best = None
                for s in _dot_strategies(t, mesh):
                    ca, pa, ma = best_child(kids[0], Tiling((m_r, s)),
                                            move_w)
                    cb, pb, mb = best_child(kids[1], Tiling((s, m_c)),
                                            move_w)
                    psum = 0.0
                    if s is not None:
                        ns = _axis_size(mesh, s)
                        psum = nbytes(node) * (ns - 1) / ns
                    flops = (nbytes(node) * weight
                             / (_parallelism(t, mesh)
                                * _axis_size(mesh, s)))
                    # operand movement is charged at move_w inside
                    # best_child (critical path before the matmul —
                    # see _OPERAND_MOVE_WEIGHT); the epsilon keeps
                    # exact ties deterministic
                    tot = (ca + cb + psum + flops
                           + (ma + mb) * _OP_MOVE_EPS)
                    if best is None or tot < best[0]:
                        best = (tot, (pa, pb), s)
                entries[t] = (best[0], best[1], best[2])
                continue
            comm = 0.0
            picks: List[Tiling] = []
            for i, c in enumerate(kids):
                req = _operand_requirement(node, t, c, i)
                ccost, pick, _ = best_child(c, req)
                comm += ccost
                picks.append(pick)
            entries[t] = (comm + compute, tuple(picks), None)
        table[node._id] = entries

    roots = root.elements if isinstance(root, TupleExpr) else (root,)
    for r in roots:
        build(r)
    return table


def assign_tilings(root: Expr) -> Expr:
    from .dot import DotExpr, DotShardMapExpr

    mesh = mesh_mod.get_mesh()
    if _mesh_n(mesh) <= 1:
        return root  # single device: everything is replicated anyway
    table = _build_table(root, mesh)

    def commit(node: Expr, t: Tiling, force: bool) -> None:
        if isinstance(node, (ValExpr, ScalarExpr)):
            return
        entry = table[node._id].get(t)
        # Constrain only MATERIALIZATION points: GEMMs (whose lowering
        # derives operand layouts from the chosen plan) and the root.
        # Forcing every intermediate (e.g. a transpose) pins layouts XLA
        # would otherwise optimize through — measured 25% slower and 2x
        # the collectives on the dot-T-dot chain (benchmarks/tiling_ab).
        # A plan equal to the node's natural behavior is skipped: a
        # redundant with_sharding_constraint is not free, it steers
        # XLA's propagation pass into worse solutions. 2-D GEMMs get
        # their searched plan recorded on a SEPARATE attribute
        # (``_dot_plan`` — operand placement, consumed by
        # DotExpr._lower) so the plan always reaches the lowering
        # without forcing a redundant *output* constraint when the
        # chosen grid equals the default.
        strategy = entry[2] if entry is not None else None
        is_gemm = isinstance(node, (DotExpr, DotShardMapExpr))
        plans_operands = (isinstance(node, DotExpr)
                          and node.a.ndim == 2 and node.b.ndim == 2)
        nondefault = t is not None and t != node._default_tiling()
        if plans_operands:
            # first visit wins (diamond DAGs); the forced output — when
            # non-default — always matches the recorded operand plan
            if entry is not None and node._dot_plan is None:
                node._dot_plan = (t, strategy)
                if nondefault and node._forced_tiling is None:
                    node._forced_tiling = t
        elif node._forced_tiling is None and (
                (force and nondefault) or (is_gemm and nondefault)):
            node._forced_tiling = t
        if entry is None:
            return
        for c, tc in zip(node.children(), entry[1]):
            if tc is not None:
                commit(c, tc, False)

    roots = root.elements if isinstance(root, TupleExpr) else (root,)
    for r in roots:
        best_t = min(table[r._id], key=lambda t: table[r._id][t][0])
        commit(r, best_t, True)
    return root


def gemm_plan_costs(root: Expr) -> Dict:
    """Candidate ``(output tiling, strategy, model cost)`` lists for
    every 2-D GEMM node in ``root`` — the validation surface for the
    cost model (benchmarks/tiling_ab.py --sweep and
    tests/test_tiling_calibration.py force each candidate as a
    measured arm and compare the model's ranking against wall time).
    Returns ``{DotExpr node: [(Tiling, strategy, cost), ...]}``."""
    from .dot import DotExpr
    from .optimize import dag_nodes

    mesh = mesh_mod.get_mesh()
    if _mesh_n(mesh) <= 1:
        return {}
    table = _build_table(root, mesh)
    out = {}
    for n in dag_nodes(root):
        if (isinstance(n, DotExpr) and n.a.ndim == 2 and n.b.ndim == 2
                and n._id in table):
            out[n] = sorted(
                ((t, e[2], e[0]) for t, e in table[n._id].items()),
                key=lambda x: x[2])
    return out


def calibrate_compute_weight(n: int = 512, iters: int = 5,
                             mesh=None) -> float:
    """Measure the compute weight on the current backend.

    The model prices a replicated GEMM's compute at ``nbytes * C`` and
    a full all-gather at ``nbytes * (p-1)/p``; calibrating C so those
    two ratios match the measured single-device matmul time vs the
    measured all-gather time makes the model's compute/communication
    trade-off empirical instead of guessed:
    ``C = (t_matmul / t_allgather) * (p - 1) / p``.
    Record per-platform values via ``--tiling_compute_weight``."""
    import time as _time

    import jax
    import jax.numpy as jnp

    mesh = mesh or mesh_mod.get_mesh()
    p = _mesh_n(mesh)
    if p <= 1:
        return _COMPUTE_WEIGHT
    x = jnp.asarray(np.random.RandomState(0).rand(n, n).astype(np.float32))
    mm = jax.jit(lambda a: a @ a)
    jax.block_until_ready(mm(x))
    t0 = _time.perf_counter()
    for _ in range(iters):
        jax.block_until_ready(mm(x))
    t_mm = (_time.perf_counter() - t0) / iters

    row = tiling_mod.row(2)
    rep = tiling_mod.replicated(2)
    xs = jax.device_put(x, row.sharding(mesh))
    gather = jax.jit(lambda a: a, out_shardings=rep.sharding(mesh))
    jax.block_until_ready(gather(xs))
    t0 = _time.perf_counter()
    for _ in range(iters):
        jax.block_until_ready(gather(xs))
    t_ag = (_time.perf_counter() - t0) / iters
    if t_ag <= 0:
        return _COMPUTE_WEIGHT
    return float(t_mm / t_ag * (p - 1) / p)


def explain(root: Expr) -> str:
    """Debug dump of chosen tilings (for the ablation reports)."""
    from .optimize import dag_nodes

    lines = []
    for n in dag_nodes(root):
        lines.append(f"{type(n).__name__}#{n._id} shape={n.shape} "
                     f"tiling={n.out_tiling().axes}")
    return "\n".join(lines)
