"""Block outer-product (``[U] spartan/expr/outer.py`` — SURVEY.md §2.3:
the tile-pair pattern used with dot). The traced lowering is one einsum;
GSPMD materializes C[i,j] blocks on the (x, y) mesh positions — the tile
pairs of the reference become mesh coordinates."""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..array import tiling as tiling_mod
from ..array.tiling import Tiling
from .base import Expr, as_expr


class OuterExpr(Expr):
    def __init__(self, a: Expr, b: Expr,
                 fn: Optional[Callable] = None):
        if a.ndim != 1 or b.ndim != 1:
            raise ValueError("outer requires 1-D operands")
        self.a = a
        self.b = b
        self.fn = fn
        super().__init__((a.size, b.size), np.result_type(a.dtype, b.dtype))

    def children(self) -> Tuple[Expr, ...]:
        return (self.a, self.b)

    def replace_children(self, new_children) -> "OuterExpr":
        return OuterExpr(new_children[0], new_children[1], self.fn)

    def _lower(self, env: Dict[int, Any]) -> Any:
        av = self.a.lower(env)
        bv = self.b.lower(env)
        if self.fn is not None:
            return self.fn(av[:, None], bv[None, :])
        return jnp.outer(av, bv)

    def _sig(self, ctx) -> Tuple:
        return ("outer", self.fn, ctx.of(self.a), ctx.of(self.b))

    def _default_tiling(self) -> Tiling:
        return tiling_mod.block(2)


def outer(a: Any, b: Any, fn: Optional[Callable] = None) -> OuterExpr:
    return OuterExpr(as_expr(a), as_expr(b), fn)
