"""Lazy array-creation expressions.

Parity with ``[U] spartan/expr/ndarray.py`` (SURVEY.md §2.3: lazy creation
of an empty DistArray with shape/dtype/tile_hint/reducer). Creation is
traced into the consuming jit, so a ``zeros`` feeding a map never
materializes separately — XLA fuses the fill into the consumer.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..array import tiling as tiling_mod
from ..array.tiling import Tiling
from .base import Expr, ValExpr


class CreateExpr(Expr):
    """Lazy fill: zeros/ones/full/arange/eye, traced at lowering time."""

    def __init__(self, shape: Sequence[int], dtype: Any, kind: str,
                 params: Tuple = (),
                 tiling: Optional[Tiling] = None,
                 tile_hint: Optional[Sequence[int]] = None):
        shape = tuple(int(s) for s in shape)
        super().__init__(shape, dtype)
        self.kind = kind
        self.params = params
        if tiling is None and tile_hint is not None:
            tiling = tiling_mod.from_tile_hint(shape, tile_hint)
        self._tiling = tiling

    def children(self) -> Tuple[Expr, ...]:
        return ()

    def replace_children(self, new_children: Tuple[Expr, ...]) -> Expr:
        return self

    def _lower(self, env: Dict[int, Any]) -> Any:
        k = self.kind
        if k == "zeros":
            return jnp.zeros(self.shape, self.dtype)
        if k == "ones":
            return jnp.ones(self.shape, self.dtype)
        if k == "full":
            return jnp.full(self.shape, self.params[0], self.dtype)
        if k == "arange":
            return jnp.arange(*self.params, dtype=self.dtype)
        if k == "eye":
            n, m, k_off = self.params
            return jnp.eye(n, m, k_off, dtype=self.dtype)
        if k == "linspace":
            start, stop, num, endpoint = self.params
            # explicit iota lowering: jnp.linspace's internal pattern
            # mis-partitions under a GSPMD sharding constraint on some
            # jax/XLA:CPU versions (every value uniformly doubled); a
            # plain start + step * iota partitions exactly
            if num == 1:
                return jnp.full((1,), start, self.dtype)
            step = (stop - start) / ((num - 1) if endpoint else num)
            out = (jnp.float32(start)
                   + jnp.float32(step) * jax.lax.iota(jnp.float32, num))
            if endpoint:  # pin the last sample exactly, like np.linspace
                out = out.at[-1].set(jnp.float32(stop))
            return out.astype(self.dtype)
        raise ValueError(f"unknown creation kind {self.kind!r}")

    def _sig(self, ctx) -> Tuple:
        return ("create", self.kind, self._shape, str(self._dtype),
                self.params)

    def _default_tiling(self) -> Tiling:
        if self._tiling is not None:
            return self._tiling
        return tiling_mod.default_tiling(self.shape)


class RandomExpr(Expr):
    """Lazy random fill. The key is derived from a counter at expr build
    time, so re-evaluating the same expr is deterministic (lineage
    recompute stays consistent — SURVEY.md §5 failure recovery)."""

    _counter = [0]

    def __init__(self, shape: Sequence[int], kind: str,
                 seed: Optional[int] = None,
                 dtype: Any = np.float32,
                 tiling: Optional[Tiling] = None,
                 tile_hint: Optional[Sequence[int]] = None):
        shape = tuple(int(s) for s in shape)
        super().__init__(shape, dtype)
        self.kind = kind
        if seed is None:
            RandomExpr._counter[0] += 1
            seed = RandomExpr._counter[0]
        self.seed = int(seed)
        if tiling is None and tile_hint is not None:
            tiling = tiling_mod.from_tile_hint(shape, tile_hint)
        self._tiling = tiling

    def children(self) -> Tuple[Expr, ...]:
        return ()

    def replace_children(self, new_children: Tuple[Expr, ...]) -> Expr:
        return self

    def _lower(self, env: Dict[int, Any]) -> Any:
        key = jax.random.key(self.seed)
        if self.kind == "uniform":
            return jax.random.uniform(key, self.shape, self.dtype)
        if self.kind == "normal":
            return jax.random.normal(key, self.shape, self.dtype)
        if self.kind == "randint":
            lo, hi = self.params_range
            return jax.random.randint(key, self.shape, lo, hi, self.dtype)
        raise ValueError(f"unknown random kind {self.kind!r}")

    def _sig(self, ctx) -> Tuple:
        return ("random", self.kind, self.seed, self._shape,
                str(self._dtype))

    def _default_tiling(self) -> Tiling:
        if self._tiling is not None:
            return self._tiling
        return tiling_mod.default_tiling(self.shape)


def ndarray(shape: Sequence[int], dtype: Any = np.float32,
            tile_hint: Optional[Sequence[int]] = None,
            reducer: Any = None,
            tiling: Optional[Tiling] = None) -> CreateExpr:
    """The reference's ``ndarray``: a new empty (zero) distributed array.

    ``reducer`` is accepted for API parity; functional updates carry their
    reducer per-write (see DistArray.update), so it is advisory here."""
    return CreateExpr(shape, dtype, "zeros", (), tiling, tile_hint)
