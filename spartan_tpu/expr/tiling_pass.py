"""Smart-tiling pass: choose output shardings via an ICI-cost model.

The reference's headline optimization (SURVEY.md §2.3 pass (d), the
ATC'15 "smart tiling"): build candidate tilings per array, edge costs =
bytes moved per op given producer/consumer tilings, pick the min-cost
assignment. Re-targeted for TPU (SURVEY.md §7 step 6): candidates are
mesh shardings (row/col/block/replicated), the cost of an edge is the
bytes a resharding collective must move over ICI, and the output is a
``_forced_tiling`` on each node which ``evaluate`` turns into GSPMD
out-shardings.

The full cost model lands with the dot/shuffle layer; this module wires
the pass into the pipeline so the FLAG ablation surface exists from the
start.

Cost: building the candidate table + the DP over it is the dominant
per-force planning expense (~ the whole optimizer stack). It runs only
on plan-cache MISSES — ``evaluate`` (expr/base.py) keys the complete
plan, this pass's ``_forced_tiling``/``_dot_plan`` choices included,
on the raw DAG's structural signature, so iterative drivers re-run the
cost model once per structure, not once per step.
"""

from __future__ import annotations

from .base import Expr
from .optimize import Pass, register_pass


class SmartTilingPass(Pass):
    name = "auto_tiling"
    flag = "opt_auto_tiling"

    def run(self, root: Expr) -> Expr:
        from ..utils import profiling as prof
        from ..utils.config import FLAGS
        from . import tiling_cost

        # a dedicated "tiling" sub-span (nested under "pass:auto_tiling"
        # in the trace ring): the candidate table + DP is the dominant
        # per-miss planning cost and deserves its own line in traces
        with prof.phase("tiling"):
            root = tiling_cost.assign_tilings(root)
        if FLAGS.verify_passes:
            # surface unresolvable / degenerate forced tilings as
            # warnings at plan time (the choices this pass just wrote
            # are constraints GSPMD must honor; one the mesh/shape
            # cannot express silently degrades to padding or reshards)
            import warnings

            from ..analysis.lints import (LintWarning,
                                          forced_tiling_findings)

            for f in forced_tiling_findings(root):
                if f.severity == "warning":
                    warnings.warn(str(f), LintWarning, stacklevel=2)
        return root


register_pass(SmartTilingPass())
