"""Lazy slicing (``[U] spartan/expr/slice.py`` — SURVEY.md §2.3).

A ``SliceExpr`` is metadata until forced; XLA lowers the slice of a
sharded operand to per-shard slices plus the minimal collective when the
region crosses shard boundaries (the reference issued one RPC per
overlapped tile — SURVEY.md §3.5).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple, Union

import numpy as np

from ..array import tiling as tiling_mod
from ..array.tiling import Tiling
from .base import Expr, as_expr

Index = Union[int, slice, type(Ellipsis), None]


def _normalize_index(idx: Any, shape: Tuple[int, ...]
                     ) -> Tuple[Tuple[Index, ...], Tuple[int, ...],
                                Tuple[int, ...]]:
    """Normalize to a full per-axis index tuple; return (index, out_shape,
    squeezed_axes)."""
    if not isinstance(idx, tuple):
        idx = (idx,)
    # expand Ellipsis
    n_explicit = sum(1 for i in idx if i is not Ellipsis and i is not None)
    out: List[Index] = []
    for i in idx:
        if i is Ellipsis:
            out.extend([slice(None)] * (len(shape) - n_explicit))
        else:
            out.append(i)
    while len([i for i in out if i is not None]) < len(shape):
        out.append(slice(None))
    if len([i for i in out if i is not None]) > len(shape):
        raise IndexError(f"too many indices for shape {shape}")

    norm: List[Index] = []
    out_shape: List[int] = []
    squeezed: List[int] = []
    axis = 0
    res_axis = 0
    for i in out:
        if i is None:  # np.newaxis
            out_shape.append(1)
            norm.append(None)
            res_axis += 1
            continue
        dim = shape[axis]
        if isinstance(i, (int, np.integer)):
            ii = int(i)
            if ii < 0:
                ii += dim
            if not 0 <= ii < dim:
                raise IndexError(
                    f"index {i} out of bounds for axis {axis} (size {dim})")
            norm.append(ii)
            squeezed.append(axis)
        elif isinstance(i, slice):
            start, stop, step = i.indices(dim)
            n = len(range(start, stop, step))
            # a negative stop from .indices() means "past the beginning";
            # storing it verbatim would re-wrap to dim-1 — use None
            stored_stop: Optional[int] = stop
            if step < 0 and stop < 0:
                stored_stop = None
            norm.append(slice(start, stored_stop, step))
            out_shape.append(n)
            res_axis += 1
        else:
            raise TypeError(f"unsupported index component {i!r}")
        axis += 1
    return tuple(norm), tuple(out_shape), tuple(squeezed)


class SliceExpr(Expr):
    """Basic (rectangular, possibly strided) indexing with int-squeeze and
    np.newaxis support."""

    def __init__(self, input: Expr, index: Tuple[Index, ...],
                 out_shape: Tuple[int, ...], squeezed: Tuple[int, ...]):
        self.input = input
        self.index = index
        self.squeezed = squeezed
        super().__init__(out_shape, input.dtype)

    def children(self) -> Tuple[Expr, ...]:
        return (self.input,)

    def replace_children(self, new_children: Tuple[Expr, ...]) -> "SliceExpr":
        return SliceExpr(new_children[0], self.index, self._shape,
                         self.squeezed)

    def _lower(self, env: Dict[int, Any]) -> Any:
        x = self.input.lower(env)
        idx = tuple(i if i is not None else np.newaxis for i in self.index)
        return x[idx]

    def _sig(self, ctx) -> Tuple:
        key = tuple((i.start, i.stop, i.step) if isinstance(i, slice)
                    else i for i in self.index)
        return ("slice", key, ctx.of(self.input))

    def _default_tiling(self) -> Tiling:
        """Keep the input's sharding on axes taken whole; drop it on
        cut/strided/squeezed axes (their shards no longer align)."""
        in_t = self.input.out_tiling()
        in_shape = self.input.shape
        axes: List[Optional[str]] = []
        src_axis = 0
        for i in self.index:
            if i is None:
                axes.append(None)
                continue
            if isinstance(i, int):
                src_axis += 1
                continue
            full = (i.start == 0 and i.step == 1
                    and i.stop == in_shape[src_axis])
            axes.append(in_t.axes[src_axis] if full else None)
            src_axis += 1
        return Tiling(axes)


def make_slice(input: Expr, idx: Any) -> Expr:
    """Entry point for ``Expr.__getitem__``: basic indexing here; boolean /
    integer-array indexing delegates to filter (SURVEY.md §2.3)."""
    input = as_expr(input)
    if isinstance(idx, Expr) or isinstance(idx, np.ndarray):
        from .filter import filter as _filter

        return _filter(input, idx)
    index, out_shape, squeezed = _normalize_index(idx, input.shape)
    return SliceExpr(input, index, out_shape, squeezed)
