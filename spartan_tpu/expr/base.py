"""Lazy expression DAG over DistArrays, evaluated as ONE jitted XLA program.

Parity with the reference's expr layer (SURVEY.md §2.3: ``[U]
spartan/expr/base.py`` — ``Expr`` node with unique id, children,
``evaluate()`` with DAG-level memo cache, ``force``, ``glom``, operator
overloading, ``Val``/``AsArray`` wrappers). The execution model is the
re-design mandated by BASELINE.json:5: instead of shipping per-tile kernels
over RPC, ``force()`` lowers the whole DAG into a single traced function
over the leaf arrays and jit-compiles it with GSPMD out-shardings — the
expr DAG -> jaxpr boundary replaces the expr -> per-tile-kernel boundary
(SURVEY.md §3.2). Compiled executables are cached by DAG structure, so
iterative drivers (k-means, SGD) hit the cache every step.
"""

from __future__ import annotations

import itertools
import os
import threading
import warnings
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..array import distarray as da
from ..array import tiling as tiling_mod
from ..array.distarray import DistArray
from ..array.tiling import Tiling
from ..kernels import registry as kernels_mod
from ..obs import ledger as ledger_mod
from ..obs import monitor as monitor_mod
from ..obs import numerics as numerics_mod
from ..obs import profile as profile_mod
from ..obs.explain import build_plan_report, key_hash, scope_digest_table
from ..parallel import mesh as mesh_mod
from ..parallel import redistribute as redistribute_mod
from .. import persist as persist_mod
from ..resilience import degrade as degrade_mod
from ..resilience import faults as faults_mod
from ..resilience import integrity as integrity_mod
from ..resilience import memory as memory_mod
from ..utils import config as config_mod
from ..utils import profiling as prof
from ..utils.config import FLAGS
from ..utils.log import log_debug

_ids = itertools.count()


def _user_site() -> Optional[Tuple[str, int, str]]:
    """First stack frame outside spartan_tpu — the user line that built
    this expr (the reference's ExprTrace error attribution, SURVEY.md §5).
    """
    import sys

    f = sys._getframe(2)
    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    while f is not None:
        fn = f.f_code.co_filename
        if not fn.startswith(pkg):
            return (fn, f.f_lineno, f.f_code.co_name)
        f = f.f_back
    return None


class ExprError(RuntimeError):
    """Evaluation error annotated with the user line that built the
    failing expression."""


def fn_key(fn: Any) -> Any:
    """Structural identity for a kernel function: code object + captured
    closure values + defaults. Two closures created by the same def with
    the same captures compare equal, so iterative drivers that rebuild
    their kernels every step (the common pattern) still hit the compile
    cache instead of recompiling per iteration."""
    import functools

    if isinstance(fn, functools.partial):
        return ("partial", fn_key(fn.func), fn.args,
                tuple(sorted(fn.keywords.items())))
    code = getattr(fn, "__code__", None)
    if code is None:
        return fn  # builtins / callables: identity is the best we have
    cells: Tuple = ()
    closure = getattr(fn, "__closure__", None)
    if closure:
        vals = []
        for c in closure:
            try:
                v = c.cell_contents
            except ValueError:
                v = "<empty>"
            try:
                hash(v)
            except TypeError:
                v = id(v)
            vals.append(v)
        cells = tuple(vals)
    return (code, cells, getattr(fn, "__defaults__", None) or ())


class Expr:
    """A node in the lazy DAG. Subclasses define children + lowering."""

    def __init__(self, shape: Tuple[int, ...], dtype: Any):
        self._id = next(_ids)
        self._shape = tuple(int(s) for s in shape)
        self._dtype = np.dtype(dtype)
        self._result: Optional[DistArray] = None
        self._forced_tiling: Optional[Tiling] = None
        self._site = _user_site()

    # -- structure ------------------------------------------------------

    @property
    def shape(self) -> Tuple[int, ...]:
        return self._shape

    @property
    def dtype(self) -> np.dtype:
        return self._dtype

    @property
    def ndim(self) -> int:
        return len(self._shape)

    @property
    def size(self) -> int:
        return int(np.prod(self._shape)) if self._shape else 1

    def children(self) -> Tuple["Expr", ...]:
        raise NotImplementedError

    def replace_children(self, new_children: Tuple["Expr", ...]) -> "Expr":
        """Clone this node over rewritten children (optimizer passes)."""
        raise NotImplementedError(
            f"{type(self).__name__} does not support replace_children")

    def _lower(self, env: Dict[int, Any]) -> Any:
        """Emit the traced jnp value for this node (children already in
        env is NOT guaranteed — call self.lower on children)."""
        raise NotImplementedError

    def lower(self, env: Dict[int, Any]) -> Any:
        if self._id not in env:
            try:
                if FLAGS.trace_annotations:
                    # trace-time-only: device profiles (Perfetto /
                    # TensorBoard) attribute XLA ops back to this node;
                    # inside _build_plan's naming session the scope
                    # also carries the node's _sig digest — the join
                    # key st.profile's trace-parse tier matches on
                    with jax.named_scope(profile_mod.scope_name(self)):
                        val = self._lower(env)
                else:
                    val = self._lower(env)
            except Exception as e:
                if self._site and not getattr(e, "_expr_annotated", False):
                    try:
                        e._expr_annotated = True  # annotate innermost only
                        note = (
                            f"while evaluating {type(self).__name__} built "
                            f"at {self._site[0]}:{self._site[1]} "
                            f"(in {self._site[2]})")
                        if hasattr(e, "add_note"):
                            e.add_note(note)
                        else:  # Python < 3.11: emulate PEP 678 notes
                            e.__notes__ = getattr(e, "__notes__", []) + [note]
                    except Exception:
                        pass  # slotted/frozen exceptions: keep the original
                raise
            # numerics sentinel: inside an audited trace (st.audit /
            # FLAGS.audit_numerics) attach a device-side health word +
            # host callback to this node's value; a no-op None check
            # otherwise, and lower() only runs on plan-cache misses
            numerics_mod.probe(self, val)
            if (self._forced_tiling is not None
                    and not profile_mod.shard_local_lowering()):
                # (shard-local lowering — the profiler re-timing one
                # shard's sub-plan per device — must NOT constrain:
                # the value is shard-sized, and resharding it across
                # the mesh is exactly what we're measuring around)
                # smart-tiling chose this node's layout: constrain it
                # so GSPMD materializes the planned resharding points.
                # Through the redistribution seam (parallel/
                # redistribute.constrain): under
                # FLAGS.redistribution_planner, edges where the cost
                # model predicts an explicit collective schedule beats
                # GSPMD's generic lowering are emitted explicitly (the
                # node's natural layout is the source the DP priced
                # this edge from); everything else — planner off, no
                # predicted win, indivisible shapes — stays a plain
                # with_sharding_constraint.
                val = redistribute_mod.constrain(
                    val, self._forced_tiling, mesh_mod.get_mesh(),
                    src=self._default_tiling())
            env[self._id] = val
        return env[self._id]

    def _sig(self, ctx: "_SigCtx") -> Tuple:
        """Structural signature of this node (children via ctx.of)."""
        raise NotImplementedError

    def out_tiling(self) -> Tiling:
        """Sharding of the evaluated result (overridable by the
        auto-tiling pass via ``_forced_tiling``)."""
        if self._forced_tiling is not None:
            return self._forced_tiling
        return self._default_tiling()

    def _default_tiling(self) -> Tiling:
        raise NotImplementedError

    # -- evaluation -----------------------------------------------------

    def evaluate(self, donate: Sequence[Any] = ()) -> DistArray:
        return evaluate(self, donate=donate)

    def evaluate_async(self, donate: Sequence[Any] = (),
                       tenant: Optional[str] = None,
                       deadline_s: Optional[float] = None):
        """Submit this expr to the concurrent serving engine
        (spartan_tpu/serve): returns an ``EvalFuture`` immediately;
        identical-signature requests from concurrent callers coalesce
        into one batched dispatch. See docs/SERVING.md."""
        from ..serve import evaluate_async as _ea

        return _ea(self, donate=donate, tenant=tenant,
                   deadline_s=deadline_s)

    def force(self, donate: Sequence[Any] = ()) -> DistArray:
        return evaluate(self, donate=donate)

    def optimized(self) -> "Expr":
        from .optimize import optimize

        return optimize(self)

    def invalidate(self) -> None:
        """Drop this node's cached result; the next force recomputes from
        lineage (exprs are deterministic — SURVEY.md §5 failure
        recovery: recompute-from-expr-DAG)."""
        self._result = None

    def recompute(self) -> DistArray:
        """Lineage-based recovery: re-evaluate this expr from its
        (deterministic) DAG, ignoring the cached result."""
        self.invalidate()
        return evaluate(self)

    def glom(self) -> np.ndarray:
        return self.evaluate().glom()

    def __array__(self, dtype=None):
        out = self.glom()
        return out.astype(dtype) if dtype is not None else out

    # -- operator overloading (build MapExprs) --------------------------

    def _binop(self, other: Any, name: str, reverse: bool = False) -> "Expr":
        from .map import build_binop

        return build_binop(name, self, other, reverse)

    def __add__(self, o):
        return self._binop(o, "add")

    def __radd__(self, o):
        return self._binop(o, "add", True)

    def __sub__(self, o):
        return self._binop(o, "subtract")

    def __rsub__(self, o):
        return self._binop(o, "subtract", True)

    def __mul__(self, o):
        return self._binop(o, "multiply")

    def __rmul__(self, o):
        return self._binop(o, "multiply", True)

    def __truediv__(self, o):
        return self._binop(o, "divide")

    def __rtruediv__(self, o):
        return self._binop(o, "divide", True)

    def __floordiv__(self, o):
        return self._binop(o, "floor_divide")

    def __rfloordiv__(self, o):
        return self._binop(o, "floor_divide", True)

    def __mod__(self, o):
        return self._binop(o, "mod")

    def __rmod__(self, o):
        return self._binop(o, "mod", True)

    def __pow__(self, o):
        return self._binop(o, "power")

    def __rpow__(self, o):
        return self._binop(o, "power", True)

    def __neg__(self):
        from .map import build_unop

        return build_unop("negative", self)

    def __abs__(self):
        from .map import build_unop

        return build_unop("absolute", self)

    def __eq__(self, o):  # type: ignore[override]
        return self._binop(o, "equal")

    def __ne__(self, o):  # type: ignore[override]
        return self._binop(o, "not_equal")

    def __lt__(self, o):
        return self._binop(o, "less")

    def __le__(self, o):
        return self._binop(o, "less_equal")

    def __gt__(self, o):
        return self._binop(o, "greater")

    def __ge__(self, o):
        return self._binop(o, "greater_equal")

    def __and__(self, o):
        return self._binop(o, "bitwise_and")

    def __or__(self, o):
        return self._binop(o, "bitwise_or")

    def __xor__(self, o):
        return self._binop(o, "bitwise_xor")

    def __invert__(self):
        from .map import build_unop

        # numpy semantics: logical not for bools, bitwise not for ints
        name = "logical_not" if np.dtype(self.dtype) == np.bool_ else "invert"
        return build_unop(name, self)

    def __hash__(self) -> int:  # __eq__ is overloaded; hash by identity
        return id(self)

    def __bool__(self) -> bool:
        # Never truth-test an Expr: __eq__/__lt__/... build lazy
        # element-wise graphs, so `if expr:`, `expr in seq`, and
        # `assert expr == y` would silently build (or worse, force) a
        # graph where the caller expected a Python bool. Raise loudly
        # with both the build site and the remedy.
        here = _user_site()
        built = (f"; the expr was built at {self._site[0]}:"
                 f"{self._site[1]} (in {self._site[2]})"
                 if self._site else "")
        at = (f" at {here[0]}:{here[1]} (in {here[2]})" if here else "")
        raise ExprError(
            f"an Expr has no truth value (truth-tested{at}{built}). "
            "Lazy comparisons build element-wise graphs, so `if "
            "expr:` or `expr in a_list` would silently evaluate or "
            "mis-evaluate. Force explicitly instead: "
            "bool(expr.glom()) for a size-1 result, "
            ".any()/.all() for element-wise tests, or `is` for "
            "object identity.")

    def __getitem__(self, idx) -> "Expr":
        from .slice import make_slice

        return make_slice(self, idx)

    # -- numpy-flavoured conveniences ------------------------------------

    def astype(self, dtype) -> "Expr":
        from .builtins import astype

        return astype(self, dtype)

    def sum(self, axis=None, keepdims=False) -> "Expr":
        from .reduce import sum as _sum

        return _sum(self, axis=axis, keepdims=keepdims)

    def mean(self, axis=None, keepdims=False) -> "Expr":
        from .reduce import mean

        return mean(self, axis=axis, keepdims=keepdims)

    def max(self, axis=None, keepdims=False) -> "Expr":
        from .reduce import max as _max

        return _max(self, axis=axis, keepdims=keepdims)

    def min(self, axis=None, keepdims=False) -> "Expr":
        from .reduce import min as _min

        return _min(self, axis=axis, keepdims=keepdims)

    def argmax(self, axis=None) -> "Expr":
        from .reduce import argmax

        return argmax(self, axis=axis)

    def argmin(self, axis=None) -> "Expr":
        from .reduce import argmin

        return argmin(self, axis=axis)

    def all(self, axis=None, keepdims=False) -> "Expr":
        from .reduce import all as _all

        return _all(self, axis=axis, keepdims=keepdims)

    def any(self, axis=None, keepdims=False) -> "Expr":
        from .reduce import any as _any

        return _any(self, axis=axis, keepdims=keepdims)

    def dot(self, other) -> "Expr":
        from .dot import dot

        return dot(self, other)

    def __matmul__(self, other) -> "Expr":
        from .dot import dot

        return dot(self, other)

    def transpose(self, *axes) -> "Expr":
        from .reshape import transpose

        return transpose(self, *axes)

    @property
    def T(self) -> "Expr":
        return self.transpose()

    def reshape(self, *shape) -> "Expr":
        from .reshape import reshape

        return reshape(self, *shape)

    def ravel(self) -> "Expr":
        from .reshape import ravel

        return ravel(self)

    def __repr__(self) -> str:
        return (f"{type(self).__name__}(id={self._id}, shape={self._shape}, "
                f"dtype={self._dtype})")


# -- leaf nodes ---------------------------------------------------------

# numpy dtype -> canonical string for structural signatures:
# ``str(dtype)`` re-derives the name each call (~3µs), and leaf
# signing is on the per-request serving hot path
_dtype_strs: Dict[Any, str] = {}


def _dtype_str(dt: Any) -> str:
    s = _dtype_strs.get(dt)
    if s is None:
        s = _dtype_strs[dt] = str(dt)
    return s


class ValExpr(Expr):
    """Leaf wrapping an evaluated DistArray (the reference's ``Val``)."""

    def __init__(self, value: DistArray):
        super().__init__(value.shape, value.dtype)
        self.value = value
        self._result = value

    def invalidate(self) -> None:
        pass  # a Val IS its data; there is no lineage to recompute from

    def children(self) -> Tuple[Expr, ...]:
        return ()

    def replace_children(self, new_children: Tuple[Expr, ...]) -> Expr:
        return self

    def _lower(self, env: Dict[int, Any]) -> Any:
        raise RuntimeError("leaf must be seeded into env before lowering")

    def _sig(self, ctx: "_SigCtx") -> Tuple:
        return ("val", ctx.leaf_pos(self), self._shape,
                _dtype_str(self._dtype), self.value.tiling.axes)

    def _default_tiling(self) -> Tiling:
        return self.value.tiling


class ScalarExpr(Expr):
    """Leaf wrapping a Python scalar, passed as a (weakly-typed) traced
    argument so iterative drivers don't recompile when it changes."""

    def __init__(self, value: Any):
        dtype = np.result_type(type(value))
        super().__init__((), dtype)
        self.pyvalue = value
        self.weak_kind = ("b" if isinstance(value, bool) else
                          "i" if isinstance(value, int) else "f")

    def children(self) -> Tuple[Expr, ...]:
        return ()

    def replace_children(self, new_children: Tuple[Expr, ...]) -> Expr:
        return self

    def _lower(self, env: Dict[int, Any]) -> Any:
        raise RuntimeError("leaf must be seeded into env before lowering")

    def _sig(self, ctx: "_SigCtx") -> Tuple:
        # value intentionally NOT in the signature: same-structure DAGs with
        # different scalar constants share one executable.
        return ("scalar", ctx.leaf_pos(self), self.weak_kind)

    def _default_tiling(self) -> Tiling:
        return tiling_mod.replicated(0)


def as_expr(value: Any) -> Expr:
    """The reference's ``AsArray``: coerce anything to an Expr."""
    if isinstance(value, Expr):
        return value
    if isinstance(value, DistArray):
        return ValExpr(value)
    if isinstance(value, (bool, int, float, np.bool_, np.integer,
                          np.floating)):
        if isinstance(value, (np.bool_, np.integer, np.floating)):
            value = value.item()
        return ScalarExpr(value)
    if isinstance(value, (np.ndarray, list, tuple)):
        return ValExpr(da.from_numpy(np.asarray(value)))
    if isinstance(value, jax.Array):
        return ValExpr(da.from_jax(value))
    if type(value).__name__ == "MaskedDistArray":
        raise TypeError(
            "this operation does not support MaskedDistArray operands "
            "(the mask would be silently dropped). Use the mask-aware "
            "ops — elementwise arithmetic / map_expr, dot, sort, "
            "argsort, median, concatenate, and the masked reductions — "
            "or pass .filled(fill) / .data explicitly.")
    raise TypeError(f"cannot lift {type(value).__name__} into an Expr")


def lazify(value: Any) -> Expr:
    return as_expr(value)


class TupleExpr(Expr):
    """Multiple roots evaluated in ONE jitted program (the reference's
    ``TupleExpr``/``ListExpr`` — SURVEY.md §2.3). ``glom()``/``evaluate()``
    return tuples; elements may have different shapes/dtypes/tilings."""

    def __init__(self, elements: Sequence[Expr]):
        self.elements: Tuple[Expr, ...] = tuple(as_expr(e) for e in elements)
        if not self.elements:
            raise ValueError("TupleExpr needs at least one element")
        super().__init__((), self.elements[0].dtype)

    def children(self) -> Tuple[Expr, ...]:
        return self.elements

    def replace_children(self, new_children: Tuple[Expr, ...]) -> "TupleExpr":
        return TupleExpr(new_children)

    def _lower(self, env: Dict[int, Any]) -> Any:
        return tuple(e.lower(env) for e in self.elements)

    def _sig(self, ctx: "_SigCtx") -> Tuple:
        return ("tuple",) + tuple(ctx.of(e) for e in self.elements)

    def out_tilings(self) -> Tuple[Tiling, ...]:
        return tuple(tiling_mod.sanitize(e.out_tiling(), e.shape)
                     for e in self.elements)

    def _default_tiling(self) -> Tiling:
        return tiling_mod.replicated(0)

    def __len__(self) -> int:
        return len(self.elements)

    def evaluate(self, donate: Sequence[Any] = ()
                 ) -> Tuple[DistArray, ...]:  # type: ignore[override]
        return evaluate(self, donate=donate)

    def force(self, donate: Sequence[Any] = ()
              ) -> Tuple[DistArray, ...]:  # type: ignore[override]
        return evaluate(self, donate=donate)

    def glom(self):  # type: ignore[override]
        return tuple(r.glom() for r in evaluate(self))


def tuple_of(*elements: Any) -> TupleExpr:
    return TupleExpr(elements)


class ListExpr(TupleExpr):
    """List-shaped multi-root evaluation (reference's ``ListExpr``)."""

    def glom(self):  # type: ignore[override]
        return [r.glom() for r in evaluate(self)]


class DictExpr(Expr):
    """Dict of exprs evaluated in ONE jitted program (reference's
    ``DictExpr``); ``glom()``/``evaluate()`` return dicts."""

    def __init__(self, items: Dict[str, Any]):
        self._keys = tuple(sorted(items))
        self._tuple = TupleExpr([items[k] for k in self._keys])
        super().__init__((), self._tuple.elements[0].dtype)

    def children(self) -> Tuple[Expr, ...]:
        return (self._tuple,)

    def replace_children(self, new_children: Tuple[Expr, ...]) -> "DictExpr":
        e = DictExpr.__new__(DictExpr)
        Expr.__init__(e, (), new_children[0].elements[0].dtype)
        e._keys = self._keys
        e._tuple = new_children[0]
        return e

    def _lower(self, env: Dict[int, Any]) -> Any:
        raise RuntimeError("DictExpr is evaluated via its tuple")

    def _sig(self, ctx: "_SigCtx") -> Tuple:
        return ("dict", self._keys, ctx.of(self._tuple))

    def evaluate(self, donate: Sequence[Any] = ()):  # type: ignore[override]
        vals = evaluate(self._tuple, donate=donate)
        return dict(zip(self._keys, vals))

    def force(self, donate: Sequence[Any] = ()):  # type: ignore[override]
        return self.evaluate(donate=donate)

    def glom(self):  # type: ignore[override]
        return {k: v.glom() for k, v in self.evaluate().items()}

    def __getitem__(self, key: str) -> Expr:  # type: ignore[override]
        return self._tuple.elements[self._keys.index(key)]


def dict_of(**items: Any) -> DictExpr:
    return DictExpr(items)


# -- evaluation machinery ----------------------------------------------


class _SigCtx:
    """Assigns stable positions to leaves and dedups shared subtrees."""

    def __init__(self) -> None:
        self.leaves: List[Expr] = []
        self._leaf_pos: Dict[int, int] = {}
        self._memo: Dict[int, Tuple] = {}
        self._visit: Dict[int, int] = {}

    def leaf_pos(self, leaf: Expr) -> int:
        pos = self._leaf_pos.get(leaf._id)
        if pos is None:
            pos = len(self.leaves)
            self._leaf_pos[leaf._id] = pos
            self.leaves.append(leaf)
        return pos

    def of(self, node: Expr) -> Tuple:
        if node._id in self._memo:
            # shared subtree: refer to it by visit index, not structure,
            # so diamond DAGs don't blow up exponentially
            return ("ref", self._visit[node._id])
        sig = node._sig(self)
        if node._forced_tiling is not None:
            sig = sig + ("forced", node._forced_tiling.axes)
        self._visit[node._id] = len(self._memo)
        self._memo[node._id] = sig
        return sig


class _PlanSigCtx(_SigCtx):
    """Signs the RAW (pre-optimizer) DAG for the plan cache.

    Nodes carrying a cached ``_result`` sign as Val leaves — exactly
    the rewrite ``CollapseCachedPass`` would perform — because the
    optimizer's output is state-dependent: the same structure with a
    different cached-result frontier optimizes to a different plan.
    ``_forced_tiling`` markers stay in the signature via the base
    class. One traversal produces both the plan key and the raw leaf
    list the cached plan's arguments are gathered from."""

    def of(self, node: Expr) -> Tuple:
        if node._id in self._memo:
            return ("ref", self._visit[node._id])
        if (node._result is not None and not isinstance(node, ValExpr)
                and isinstance(node._result, DistArray)):
            # matches ValExpr._sig for the leaf CollapseCachedPass
            # would substitute (no forced marker: the substituted
            # ValExpr never carries one)
            sig = ("val", self.leaf_pos(node), node._shape,
                   _dtype_str(node._dtype), node._result.tiling.axes)
            self._visit[node._id] = len(self._memo)
            self._memo[node._id] = sig
            return sig
        return super().of(node)


class _Plan:
    """Complete steady-state execution recipe for one raw-DAG
    signature: the compile-cache key, the traced callable (donation
    variants re-jit it with ``donate_argnums``), output tilings, and
    ``arg_order`` mapping each executable argument position to the
    position of the raw leaf that feeds it. ``report`` is the
    introspection dict ``st.explain`` reads (obs/explain.py), built
    once on the miss path and shared between the cached plan and its
    first-run identity variant."""

    # __weakref__: the cost ledger (obs/ledger.py) keeps weak plan
    # references so st.ledger(validate=True) can run the memory
    # validation for live plans without pinning evicted ones
    __slots__ = ("key", "traced", "out_tilings", "is_tuple", "arg_order",
                 "report", "governed_rung", "persist_digest",
                 "__weakref__")

    def __init__(self, key: Tuple, traced: Callable,
                 out_tilings: Tuple[Tiling, ...], is_tuple: bool,
                 arg_order: Tuple[int, ...],
                 report: Optional[Dict[str, Any]] = None):
        self.key = key
        self.traced = traced
        self.out_tilings = out_tilings
        self.is_tuple = is_tuple
        self.arg_order = arg_order
        self.report = report
        # set by the memory governor (resilience/memory.py) when this
        # plan's predicted peak exceeded the HBM budget: hits re-route
        # to the named ladder rung instead of dispatching a doomed
        # executable. One attribute read per cache hit when ungoverned.
        self.governed_rung: Optional[str] = None
        # on-disk address in the warm-start store (spartan_tpu/persist)
        # when FLAGS.persist_cache_dir is set and the plan key has a
        # process-stable digest; None otherwise (one attribute read on
        # the first-compile path decides whether to persist)
        self.persist_digest: Optional[str] = None


class _Exec:
    """A jitted executable plus whether its first (trace + XLA
    compile) call already happened — for compile/dispatch phase
    attribution."""

    __slots__ = ("jitted", "warm")

    def __init__(self, jitted: Callable):
        self.jitted = jitted
        self.warm = False


# -- shared evaluation state + locking discipline ------------------------
#
# Everything below is shared by every thread that evaluates (the serve
# engine's workers, st.explain, plain evaluate() callers). The locking
# discipline, also documented in spartan_tpu/serve/__init__.py:
#
#   * ``_cache_lock`` guards BOTH ``_plan_cache`` and ``_compile_cache``
#     (they evict together). It is held only for dict operations — never
#     across an optimize, trace, compile or dispatch — so a slow miss on
#     one thread cannot stall hits on another; the price is that two
#     threads racing the same miss may both build the plan and the
#     loser's work is discarded (``setdefault`` keeps the winner's).
#   * every OTHER module goes through the accessors (``lookup_plan`` /
#     ``store_plan`` / ``cached_executable`` / the clear/size helpers);
#     ``tools/lint_repo.py`` rule 6 forbids touching ``_plan_cache`` /
#     ``_compile_cache`` / ``_cache_lock`` outside this file.
#   * the metrics registry, trace ring, chaos plan and retry budgets
#     take their own locks (obs/metrics.py, obs/trace.py,
#     resilience/faults.py, resilience/engine.py); none of them is ever
#     held while calling into this module, and ``_cache_lock`` is never
#     held while calling out — the lock graph has no cycles.

# define() returns the Flag; the hot lookup reads ._value directly
# (one attribute load) instead of FLAGS.__getattr__'s dict walk
_PLAN_CACHE_MAX_FLAG = FLAGS.define_int(
    "plan_cache_max", 512,
    "Maximum plans retained in the evaluate() plan cache; beyond it "
    "the least-recently-used plan is evicted together with every "
    "compiled variant keyed under it (donation sets, serve batch "
    "sizes). 0 = unbounded (the pre-serving behavior, and the hot "
    "path skips the LRU reordering). Eviction counts land on the "
    "plan_evictions metric.")

_compile_cache: Dict[Tuple, _Exec] = {}
_plan_cache: "OrderedDict[Tuple, _Plan]" = OrderedDict()
_cache_lock = threading.Lock()

# -- executable-launch serialization -------------------------------------
#
# XLA:CPU's intra-process collective rendezvous is NOT safe under
# concurrent launches: two executables running at once interleave
# their all-reduce participants on the same device set and deadlock
# (observed as "waiting for all participants to arrive at rendezvous"
# stalls). Concurrent evaluate() callers and the serve engine's
# workers therefore serialize the LAUNCH (not the planning) on
# backends that need it; TPU launches are queue-serialized per device
# by PJRT already, so "auto" leaves them unguarded.

_DISPATCH_SERIALIZE_FLAG = FLAGS.define_str(
    "dispatch_serialize", "auto",
    "Serialize executable launches across threads: 'auto' (serialize "
    "on the cpu backend, whose collective rendezvous deadlocks under "
    "concurrent launches; leave other backends unserialized), 'on', "
    "or 'off'. Planning, arg gathering and result wrapping always run "
    "concurrently — only the launch is guarded.")

_launch_lock = threading.Lock()
_serialize_auto: Optional[bool] = None


class _NullLaunchGuard:
    __slots__ = ()

    def __enter__(self) -> "_NullLaunchGuard":
        return self

    def __exit__(self, *exc: Any) -> None:
        pass


_NULL_GUARD = _NullLaunchGuard()
_NULL_PHASE = _NullLaunchGuard()  # untimed _wrap_result epilogues


def launch_guard():
    """The launch-serialization context for one executable run; shared
    by ``_dispatch`` and the serve coalescer. One flag read (+ a
    cached backend probe under 'auto') on the hot path."""
    global _serialize_auto
    v = _DISPATCH_SERIALIZE_FLAG._value
    if v == "off":
        return _NULL_GUARD
    if v != "on":
        if _serialize_auto is None:
            _serialize_auto = jax.default_backend() == "cpu"
        if not _serialize_auto:
            return _NULL_GUARD
    return _launch_lock


def compile_cache_size() -> int:
    return len(_compile_cache)


def plan_cache_size() -> int:
    return len(_plan_cache)


def clear_compile_cache() -> None:
    # the plan cache holds references into the compile cache (its key
    # and traced closure), so the two clear together
    with _cache_lock:
        _compile_cache.clear()
        _plan_cache.clear()


def clear_plan_cache() -> None:
    with _cache_lock:
        _plan_cache.clear()


def lookup_plan(plan_key: Tuple) -> Optional[_Plan]:
    """Plan-cache read (the ONLY read path — obs/explain and serve/
    go through here, not the dict). A hit refreshes LRU recency when
    the cache is bounded; unbounded (plan_cache_max=0) skips the
    reorder so the legacy hot path is untouched."""
    with _cache_lock:
        plan = _plan_cache.get(plan_key)
        if plan is not None and _PLAN_CACHE_MAX_FLAG._value > 0:
            _plan_cache.move_to_end(plan_key)
        return plan


def store_plan(plan_key: Tuple, plan: _Plan) -> _Plan:
    """Plan-cache insert with LRU eviction (FLAGS.plan_cache_max).

    Eviction is donation-variant-aware: the evicted plan's compile
    signature prefixes every executable compiled FOR it (the donation
    variants ``plan.key + (donate_key,)`` and the serve coalescer's
    batch variants ``plan.key + ('serve', B, mode)``), so those leave
    the compile cache with it — an unbounded per-tenant plan stream
    cannot pin its dead executables' HBM/host memory. First writer
    wins on a race (the existing plan is returned)."""
    evicted = 0
    with _cache_lock:
        cur = _plan_cache.get(plan_key)
        if cur is not None:
            return cur
        _plan_cache[plan_key] = plan
        maxn = _PLAN_CACHE_MAX_FLAG._value
        while maxn and maxn > 0 and len(_plan_cache) > maxn:
            _, old = _plan_cache.popitem(last=False)
            pref, plen = old.key, len(old.key)
            for ck in [k for k in _compile_cache if k[:plen] == pref]:
                del _compile_cache[ck]
            evicted += 1
    if evicted:
        prof.count("plan_evictions", evicted)
    return plan


def cached_executable(key: Tuple, make: Callable[[], Callable]) -> _Exec:
    """Get-or-create a jitted executable in the process compile cache
    under its locking discipline (``make()`` builds the ``jax.jit``
    callable on a miss; built outside the lock, first writer wins).
    The serve coalescer keys its batched variants through here so they
    share eviction, locking and the compiles metric."""
    with _cache_lock:
        ex = _compile_cache.get(key)
    if ex is None:
        mine = _Exec(make())
        with _cache_lock:
            ex = _compile_cache.setdefault(key, mine)
        if ex is mine:
            prof.count("compiles")
            log_debug("compiled executable key=%s", hash(key))
    return ex


# mesh object -> its plan-key component. Sorting the axis dict costs
# ~2.5µs per signature; meshes are few and long-lived, so key them by
# identity (the stored mesh reference keeps the id stable). The key
# LEADS with the mesh epoch and the memo entry records the epoch it
# was built under, so after a rebuild_mesh (elastic recovery) a cached
# identity entry for a dead mesh can never resurrect a stale plan key
# — epoch-N plans miss, and evict_stale_plans() reaps them.
_mesh_keys: Dict[int, Tuple[Any, Tuple, int]] = {}


def _mesh_key(mesh) -> Tuple:
    epoch = mesh_mod._EPOCH
    hit = _mesh_keys.get(id(mesh))
    if hit is not None and hit[0] is mesh and hit[2] == epoch:
        return hit[1]
    key = (epoch,) + tuple(sorted(mesh.shape.items()))
    _mesh_keys[id(mesh)] = (mesh, key, epoch)
    return key


def evict_stale_plans() -> int:
    """Drop every plan (and its compiled variants — donation sets,
    serve batches, the degrade rungs) keyed under a mesh epoch older
    than the current one. Called by elastic recovery after
    ``rebuild_mesh``; reuses the LRU eviction's prefix rule, so the
    dead epoch's executables leave the compile cache with their plans
    and nothing can pin a dead mesh's HBM. Returns plans evicted."""
    epoch = mesh_mod._EPOCH
    evicted = 0
    with _cache_lock:
        for pk in [k for k in _plan_cache
                   if isinstance(k, tuple) and len(k) >= 3
                   and k[2] and k[2][0] != epoch]:
            old = _plan_cache.pop(pk)
            pref, plen = old.key, len(old.key)
            for ck in [k for k in _compile_cache if k[:plen] == pref]:
                del _compile_cache[ck]
            evicted += 1
        # orphan executables (explain pre-plans, uncacheable plans):
        # the compile key's third element is the epoch-led mesh item
        # tuple _build_plan wrote
        for ck in [k for k in _compile_cache
                   if isinstance(k, tuple) and len(k) >= 3
                   and isinstance(k[2], tuple) and k[2]
                   and k[2][0] != epoch]:
            del _compile_cache[ck]
    if evicted:
        prof.count("plan_evictions", evicted)
    # the on-disk half (spartan_tpu/persist): purge persisted entries
    # of dead mesh epochs too — without this a process restart would
    # resurrect plans for a mesh that no longer exists. No-op (one
    # flag read) with the store off; never raises.
    persist_mod.evict_stale()
    # the incremental engine's result cache holds device buffers keyed
    # by plan: entries born under the dead epoch go with their plans
    incremental_mod.evict_stale()
    return evicted


def plan_signature(expr: "Expr", mesh=None) -> Tuple[Tuple, "_PlanSigCtx"]:
    """One raw-DAG traversal -> (plan-cache key, signing context) —
    exactly what ``evaluate()`` computes before its cache probe. The
    serve front end signs requests with this at submit time (caller
    thread) so identical-signature requests can coalesce;
    ``plan.arg_order`` indexes into ``ctx.leaves``."""
    if mesh is None:
        mesh = mesh_mod.get_mesh()
    rctx = _PlanSigCtx()
    raw_sig = rctx.of(expr)
    plan_key = (raw_sig, _opt_flags_key(), _mesh_key(mesh))
    return plan_key, rctx


def _leaf_arg(leaf: Expr) -> Any:
    if isinstance(leaf, ValExpr):
        return leaf.value.jax_array
    if isinstance(leaf, ScalarExpr):
        return leaf.pyvalue
    if isinstance(leaf._result, DistArray):
        return leaf._result.jax_array  # cached node signed as a Val leaf
    raise TypeError(f"unknown leaf {leaf!r}")


def _leaf_array(leaf: Expr) -> Optional[DistArray]:
    """The DistArray behind a leaf (None for scalars)."""
    if isinstance(leaf, ValExpr):
        return leaf.value
    if isinstance(leaf, ScalarExpr):
        return None
    return leaf._result if isinstance(leaf._result, DistArray) else None


def _norm_donate(donate: Sequence[Any]) -> List[DistArray]:
    out: List[DistArray] = []
    for d in donate:
        if isinstance(d, DistArray):
            out.append(d)
        elif isinstance(d, ValExpr):
            out.append(d.value)
        elif isinstance(d, Expr) and isinstance(d._result, DistArray):
            out.append(d._result)
        else:
            raise TypeError(
                f"donate expects DistArrays (or evaluated exprs), got "
                f"{type(d).__name__}")
        arr = out[-1]
        if arr._donate_site is None:
            # record the donating call for use-after-donate provenance
            arr._donate_site = _user_site()
    return out


# (flag mutation count, pass-registry size) -> flags key. Every
# plan_signature/evaluate pays this key; re-deriving it walks the
# FLAGS registry ~10 times (≈20µs — measured 10% of a steady-state
# signature), so it is memoized on config.mutation_count(), which any
# flag write bumps. The thread-local degradation rung stays OUT of the
# memo (appended fresh per call).
_opt_key_memo: Tuple[Tuple, Tuple] = ((), ())
_optimize_mod = None  # lazily-bound .optimize (circular import)


def _opt_flags_key() -> Tuple:
    """Everything the optimizer stack reads that the raw signature
    cannot see: a plan is only reusable under the exact pass
    configuration that produced it."""
    global _opt_key_memo, _optimize_mod
    if _optimize_mod is None:  # bind the module once: the per-call
        import importlib  # `from .optimize import ...` machinery was
        _optimize_mod = importlib.import_module(  # ~3µs on the
            ".optimize", __package__)  # per-request signing path
    _PASSES = _optimize_mod._PASSES

    # late-registered passes (smart tiling self-registers on first
    # optimize) must be in the registry BEFORE the key is read, or the
    # very first plan key in a process can never be hit again
    _optimize_mod._ensure_tiling_pass()
    ver = (config_mod.mutation_count(), len(_PASSES))
    memo_ver, key = _opt_key_memo
    if memo_ver != ver:
        # audit_numerics changes the LOWERED program (health probes
        # are compiled in), so audited and plain plans must never
        # share a key; likewise the OOM degradation rung
        # (resilience/degrade.py) forces different tilings/passes, so
        # degraded and normal plans are keyed apart
        # cost calibration re-weights the tiling DP's terms
        # (obs/ledger profile -> tiling_cost._cal_factors), so a
        # calibrated plan must never alias an uncalibrated one: the
        # active profile's fingerprint is part of the key (set_profile
        # writes the fingerprint FLAG, which bumps mutation_count and
        # invalidates this memo)
        cal = ((FLAGS.cost_calibration_fingerprint or "on")
               if FLAGS.cost_calibration else None)
        # the redistribution planner changes BOTH the DP's edge costs
        # and the emitted lowering (explicit schedules vs GSPMD), so
        # planned and implicit plans must never alias
        # the kernel-backend policy (spartan_tpu/kernels) changes the
        # lowering of the irregular ops (Pallas vs GSPMD) for the same
        # structural signature, so native and fallback plans are keyed
        # apart the same way (platform is process-constant; flag
        # writes bump the memo version)
        # carry sharding (expr/loop FLAGS.shard_loop_carries) changes
        # the loop program's layout constraints: sharded-carry and
        # replicated-carry plans must never alias (the chosen layouts
        # are also in LoopExpr._sig — this is the cheap belt)
        key = (tuple(p.name for p in _PASSES if p.enabled()),
               FLAGS.opt_fold_slices, FLAGS.placement,
               FLAGS.tiling_compute_weight, FLAGS.tiling_flop_weight,
               FLAGS.tiling_operand_move_weight,
               FLAGS.tiling_memory_weight,
               bool(FLAGS.audit_numerics), cal,
               bool(FLAGS.redistribution_planner),
               bool(getattr(FLAGS, "shard_loop_carries", False)),
               kernels_mod.policy_key())
        _opt_key_memo = (ver, key)
    return key + (getattr(degrade_mod._TLS, "rung", None),)


def _arg_order(raw_leaves: List[Expr],
               opt_leaves: List[Expr]) -> Optional[Tuple[int, ...]]:
    """Map each optimized-DAG leaf back to the raw-DAG leaf feeding it.

    The passes either keep leaf objects intact (fusion re-plumbs, never
    re-creates, Val/Scalar leaves) or substitute ``ValExpr(n._result)``
    for a cached node — which the raw traversal already signed as a
    leaf — so identity on the Expr or on its DistArray recovers the raw
    position. Returns None (plan not cacheable) if a pass ever
    introduces a leaf with no raw counterpart."""
    pos: Dict[int, int] = {}
    for i, leaf in enumerate(raw_leaves):
        pos.setdefault(id(leaf), i)
        arr = _leaf_array(leaf)
        if arr is not None:
            pos.setdefault(id(arr), i)
    order = []
    for leaf in opt_leaves:
        j = pos.get(id(leaf))
        if j is None and isinstance(leaf, ValExpr):
            j = pos.get(id(leaf.value))
        if j is None:
            return None
        order.append(j)
    return tuple(order)


def _gather_args(leaves: List[Expr], order: Tuple[int, ...],
                 donated: List[DistArray]
                 ) -> Tuple[List[Any], List[DistArray], List[int]]:
    """Gather executable arguments for one dispatch: the leaf buffers
    in ``order``, plus the donation bookkeeping — which DistArrays are
    released (``darrs``) and which argument positions may alias into
    the outputs (``dpos``). Shared by ``_dispatch`` and the serve
    coalescer (which gathers per request and never donates)."""
    ordered = [leaves[i] for i in order]
    args = [_leaf_arg(leaf) for leaf in ordered]

    darrs: List[DistArray] = []
    dpos: List[int] = []
    stale: List[DistArray] = []
    epoch = mesh_mod._EPOCH
    seen: Dict[int, int] = {}
    for j, leaf in enumerate(ordered):
        arr = _leaf_array(leaf)
        if arr is None:
            continue
        if arr._epoch != epoch:
            # born on a mesh that a rebuild_mesh has since replaced:
            # its buffers (may) live on dead devices. Raise the clear
            # error BEFORE XLA sees the buffer; collect every stale
            # leaf so one rehome pass heals the whole dispatch.
            if not any(arr is s for s in stale):
                stale.append(arr)
            continue
        if arr._donate_next or any(arr is d for d in donated):
            if id(arr) in seen:
                # the same buffer feeds two argument slots: aliasing
                # it into the output is unsafe, so don't donate
                # either position (the wrapper is still invalidated
                # by _wrap_result)
                k = seen[id(arr)]
                if k in dpos:
                    dpos.remove(k)
                continue
            seen[id(arr)] = j
            dpos.append(j)
            if not any(arr is d for d in darrs):
                darrs.append(arr)
    if stale:
        raise mesh_mod.StaleMeshError(
            f"{len(stale)} input DistArray(s) belong to mesh epoch "
            f"{stale[0]._epoch} but the mesh was rebuilt (current "
            f"epoch {epoch}, e.g. after device loss): their buffers "
            "live on the previous mesh. Re-create them from source, "
            "or — if the data is still fetchable (replicated, or a "
            "simulated loss) — call .rehome() / "
            "resilience.elastic.rehome() to migrate them.",
            arrays=stale)
    return args, darrs, dpos


def _wrap_result(expr: Expr, plan: _Plan, out: Any,
                 darrs: List[DistArray], dpos: List[int], mesh,
                 timed: bool = True) -> Any:
    """Dispatch epilogue: wrap the raw outputs into DistArrays, release
    donated buffers, update the plan report's donation view, seed the
    root's result cache, and re-check numerics watchpoints. Shared by
    ``_dispatch`` and the serve coalescer, which passes ``timed=False``
    and times ONE build phase around the whole batch instead of paying
    a span per coalesced request."""
    ctx = prof.phase("build") if timed else _NULL_PHASE
    with ctx:
        if plan.is_tuple:
            result: Any = tuple(DistArray(o, t, mesh)
                                for o, t in zip(out, plan.out_tilings))
        else:
            result = DistArray(out, plan.out_tilings[0], mesh)
        for arr in darrs:
            arr._release_donated()
        if darrs:
            prof.count("donated_dispatches")
        if plan.report is not None:
            don = plan.report.get("donation")
            if don is not None:
                don["last_donated_args"] = sorted(dpos)
                if darrs:
                    don["donated_dispatches"] = (
                        don.get("donated_dispatches", 0) + 1)
        expr._result = result
        _maybe_record_write(expr, result)
    if numerics_mod._WATCHPOINTS:
        # persistent data-health watchpoints (st.watch): re-check each
        # after every dispatch; the empty-list read above is the whole
        # hot-path cost when none are installed
        numerics_mod.poll_watchpoints()
    return result


def _dispatch(expr: Expr, plan: _Plan, leaves: List[Expr],
              order: Tuple[int, ...], donated: List[DistArray],
              mesh) -> Any:
    """Run a plan: gather leaf args, (lazily) fetch the right donation
    variant of the executable, execute, wrap, invalidate donated
    buffers, seed the root's result cache."""
    with prof.phase("build"):
        args, darrs, dpos = _gather_args(leaves, order, donated)
        donate_key = frozenset(dpos)

    def _make() -> Callable:
        if dpos:
            return jax.jit(plan.traced,
                           donate_argnums=tuple(sorted(dpos)))
        if plan.persist_digest is not None \
                and persist_mod.active() is not None:
            # warm-start store active: build the base variant AOT so
            # the SAME compile is both dispatchable and serializable
            # (persistence never pays a second XLA compile)
            return persist_mod.aot_compile(plan.traced, args)
        return jax.jit(plan.traced)

    ex = cached_executable(plan.key + (donate_key,), _make)

    def run() -> Any:
        with warnings.catch_warnings():
            if dpos:
                # backends without aliasing support (XLA:CPU) warn per
                # dispatch; donation there is bookkeeping-only
                warnings.filterwarnings(
                    "ignore", message="Some donated buffers were not usable")
            with launch_guard():
                return ex.jitted(*args)

    fresh = not ex.warm
    phase_name = "compile" if fresh else "dispatch"
    phase_ctx = prof.phase(phase_name)
    with phase_ctx as dsp:
        # dispatch watchdog (obs/numerics.py): a run that exceeds
        # FLAGS.dispatch_timeout_s dumps the in-flight span tree +
        # plan report + last health word to a crash file; a shared
        # no-op (one flag read) when the timeout is 0
        with numerics_mod.watchdog(phase_name, plan.report):
            # chaos seam (resilience/faults.py): an installed plan may
            # raise a synthetic compile/OOM/transient fault or stall
            # here — BEFORE the executable runs, so donated buffers
            # are never half-consumed by an injected failure. One
            # attribute read when no plan is installed.
            if faults_mod._ACTIVE is not None:
                faults_mod.fire(phase_name)
            out = run()
        if dpos:
            dsp.set(donated=sorted(dpos))
    if faults_mod._ACTIVE is not None:
        # chaos `sdc` seam: a matching token armed a silent corruption
        # at fire() above; apply the seeded bit-flip to the result the
        # device "computed". Nothing raises here — detection is the
        # integrity sentinel's job below (or nobody's, when the check
        # is off: that IS the threat model). One attribute read when
        # no plan is installed.
        out = faults_mod.corrupt_output(out)
    ex.warm = True
    if fresh and not dpos and plan.persist_digest is not None:
        # first compile of a persistable plan: serialize + store it
        # (atomic, lease-arbitrated, no-raise — a failed persist never
        # fails the evaluation that produced the plan)
        persist_mod.maybe_store(plan, ex.jitted, mesh)
    if ledger_mod._LEDGER_FLAG._value and plan.report is not None:
        # cost ledger: the measured wall time of this run, next to the
        # plan's predicted tiling-DP cost (one flag read when off)
        ledger_mod.note_dispatch(plan.report.get("plan_key"),
                                 phase_name, phase_ctx.seconds)
    if profile_mod._SAMPLE_FLAG._value > 0:
        # sampled continuous profiling (obs/profile.py): every Nth
        # warm dispatch of a plan gets a device-time attribution, off
        # the result path — the served result above came from the
        # unmodified executable (bit-equal to unsampled). The legacy
        # FLAGS.profile whole-dispatch capture migrated here: one
        # profiling entry point, one flag read per dispatch when off.
        profile_mod.maybe_sample(expr, plan, phase_name,
                                 phase_ctx.seconds, leaves, dpos, mesh)

    if integrity_mod._CHECK_FLAG._value:
        # SDC sentinel (resilience/integrity.py): every Nth run of a
        # plan gets a per-shard checksum + redundant re-execution on a
        # rotated device assignment. Raises IntegrityError (class
        # 'sdc') on disagreement — the corrupt `out` is never wrapped,
        # cached, or returned. One flag read when off.
        integrity_mod.maybe_check(expr, plan, phase_name, out, args,
                                  dpos, mesh)

    if FLAGS.check_determinism and not dpos:  # a donated arg is gone
        out2 = run()
        pairs = zip(out, out2) if plan.is_tuple else [(out, out2)]
        for o1, o2 in pairs:
            if not bool(jnp.all(o1 == o2)):
                raise AssertionError("nondeterministic evaluation detected")

    return _wrap_result(expr, plan, out, darrs, dpos, mesh)


_write_expr_cls = None  # lazily-bound assign.WriteExpr (import cycle)


def _maybe_record_write(expr: Expr, result: Any) -> None:
    """The assign-expr mutation seam: evaluating ``st.assign(arr, idx,
    v)`` (a WriteExpr over a concrete array) is a functional update of
    that array — stamp the result into the source's Lineage exactly
    like ``DistArray.update()`` does, so the incremental engine sees
    the written region as the only delta."""
    global _write_expr_cls
    if _write_expr_cls is None:
        if type(expr).__name__ != "WriteExpr":
            return
        from .assign import WriteExpr

        _write_expr_cls = WriteExpr
    if not isinstance(expr, _write_expr_cls):
        return
    dst = expr.dst
    if (isinstance(dst, ValExpr) and isinstance(dst.value, DistArray)
            and isinstance(result, DistArray)
            and result.shape == dst.value.shape):
        dst.value._record_mutation(result, expr.region)


_engine_mod = None  # lazily-bound resilience.engine (cold path only)


def _handle_failure(exc: Exception, expr: Expr, plan: "_Plan",
                    leaves: List[Expr], order: Tuple[int, ...],
                    donated: List[DistArray], mesh) -> Any:
    """Route a failed dispatch into the resilience policy engine
    (classify -> retry / degrade / fail-fast). The engine import is
    deferred: failures are the cold path."""
    global _engine_mod
    if _engine_mod is None:
        from ..resilience import engine as _engine

        _engine_mod = _engine
    return _engine_mod.handle_failure(exc, expr, plan, leaves, order,
                                      donated, mesh)


def evaluate(expr: Expr, donate: Sequence[Any] = ()) -> DistArray:
    """Evaluate one root.

    Steady state (plan-cache hit): ONE raw-DAG traversal -> arg gather
    -> dispatch — no optimizer rewrites, no cost model, no re-signing.
    Miss: optimize -> signature -> (cached) jit -> run, then the
    complete plan (leaf order, out tilings, compiled executable) is
    memoized under the raw structural signature so the next
    structurally-identical evaluate skips the planner entirely.

    ``donate``: DistArrays (or their evaluated exprs) whose buffers the
    caller releases to this evaluation. The executable is compiled as a
    ``donate_argnums`` variant so XLA may reuse their HBM for the
    outputs, and the donated DistArrays are invalidated — any later use
    raises instead of reading freed memory. ``DistArray.donate()``
    marks an array for the same treatment without threading an
    argument."""
    if expr._result is not None:
        return expr._result

    prof.count("evaluations")
    mesh = mesh_mod.get_mesh()
    donated = _norm_donate(donate)

    with prof.span("evaluate") as esp:
        if FLAGS.trace:  # skip the label f-strings when not recording
            site = expr._site
            esp.set(root=f"{type(expr).__name__}#{expr._id}",
                    site=(f"{site[0]}:{site[1]}" if site else None))
        rctx: Optional[_PlanSigCtx] = None
        plan_key: Optional[Tuple] = None
        if FLAGS.plan_cache:
            with prof.phase("sign"):
                rctx = _PlanSigCtx()
                raw_sig = rctx.of(expr)
                plan_key = (raw_sig, _opt_flags_key(),
                            _mesh_key(mesh))
            if FLAGS.trace:  # key_hash re-hashes the signature tuple:
                esp.set(plan_key=key_hash(plan_key))  # skip when off
            plan = lookup_plan(plan_key)
            if plan is not None:
                prof.count("plan_hits")
                esp.set(cache="hit")
                if plan.governed_rung is not None:
                    # the memory governor judged this plan over-budget
                    # at build time: re-route to its rung (a rung-keyed
                    # plan-cache hit) instead of dispatching a doomed
                    # executable
                    gov = memory_mod.redirect_governed(
                        expr, plan, donated, mesh)
                    if gov is not memory_mod.NOT_HANDLED:
                        return gov
                if incremental_mod._INC_FLAG._value:
                    # delta-aware path (expr/incremental.py): serve
                    # from the result cache + a dirty sub-plan when
                    # lineage proves most tiles clean; NOT_HANDLED
                    # falls through to the ordinary full dispatch
                    inc = incremental_mod.intercept(
                        expr, plan, rctx.leaves, plan.arg_order,
                        donated, mesh)
                    if inc is not incremental_mod.NOT_HANDLED:
                        expr._result = inc
                        return inc
                try:
                    result = _dispatch(expr, plan, rctx.leaves,
                                       plan.arg_order, donated, mesh)
                except Exception as e:
                    result = _handle_failure(e, expr, plan, rctx.leaves,
                                             plan.arg_order, donated,
                                             mesh)
                if incremental_mod._INC_FLAG._value:
                    incremental_mod.note_result(
                        plan, rctx.leaves, plan.arg_order, result,
                        donated, mesh)
                return result
            prof.count("plan_misses")
            esp.set(cache="miss")

        if FLAGS.verify_evaluate:
            # static sanity on the MISS path only (hits above stay
            # dispatch-bound): well-formedness + donation/tiling lints,
            # raising with user-site provenance before anything compiles
            from ..analysis import check as _check

            with prof.phase("verify"):
                _check(expr, donate=donated)

        plan, dag, leaves = _build_plan(expr, mesh, rctx, plan_key)
        if plan is None:
            # the optimizer collapsed the root onto an already-held
            # result (cached sub-DAG frontier covered everything)
            expr._result = dag._result
            return dag._result

        if FLAGS.verify_evaluate and plan.report is not None:
            # static communication audit of the lowered program
            # (analysis/plan_audit.py), miss path only like the DAG
            # check above: findings (full-operand gathers, replicated
            # intermediates) are logged + counted, never raised. A
            # persist-restored verdict (report["audit"] pre-seeded)
            # makes this a dict read — warm restarts don't re-audit.
            from ..analysis import plan_audit as plan_audit_mod

            with prof.phase("audit_plan"):
                plan_audit_mod.audit_on_miss(plan, mesh)

        if plan.report is not None:
            # predictive memory governor (resilience/memory.py): if the
            # modeled peak exceeds the HBM budget, pick the cheapest
            # sufficient ladder rung NOW — before this plan's first
            # (doomed) compile+dispatch. NOT_HANDLED = within budget,
            # no budget known, or governor off.
            gov = memory_mod.maybe_degrade(expr, plan, plan_key,
                                           donated, mesh)
            if gov is not memory_mod.NOT_HANDLED:
                dag._result = gov
                return gov

        # this first run dispatches through the same path a hit takes,
        # with identity arg order over the OPTIMIZED leaves
        try:
            result = _dispatch(expr, plan, leaves, plan.arg_order,
                               donated, mesh)
        except Exception as e:
            result = _handle_failure(e, expr, plan, leaves,
                                     plan.arg_order, donated, mesh)
        dag._result = result
        if incremental_mod._INC_FLAG._value:
            incremental_mod.note_result(plan, leaves, plan.arg_order,
                                        result, donated, mesh)
        return result


def _build_plan(expr: Expr, mesh, rctx: Optional[_PlanSigCtx],
                plan_key: Optional[Tuple]
                ) -> Tuple[Optional[_Plan], Expr, Optional[List[Expr]]]:
    """The plan-cache MISS pipeline, shared by ``evaluate()`` and
    ``st.explain`` (obs/explain.py): optimize -> sign the optimized DAG
    -> build the traced function + output tilings -> memoize the plan
    (with its introspection report) under the raw signature.

    Returns ``(plan, dag, leaves)`` where ``plan.arg_order`` is the
    identity over the OPTIMIZED leaves (the first dispatch's order);
    ``(None, dag, None)`` when the optimized DAG already carries a
    result and there is nothing to compile."""
    from .optimize import optimize

    # warm-start store consult (spartan_tpu/persist): BEFORE the
    # optimizer runs, probe the on-disk store for this raw signature +
    # environment fingerprint. A hit skips the XLA compile below (the
    # deserialized executable is pre-seeded into the compile cache); a
    # rejected entry (corrupt / stale / foreign / io fault) degrades
    # to this normal recompile with the reason on the plan report.
    # One flag read when the store is off.
    p_entry = p_digest = p_reason = None
    if rctx is not None and plan_key is not None:
        p_entry, p_digest, p_reason = persist_mod.lookup(plan_key, mesh)

    passes_report: List[Dict[str, Any]] = []
    with prof.phase("optimize"):
        dag = optimize(expr, report=passes_report)
    if dag._result is not None:
        return None, dag, None

    degrade_rung = getattr(degrade_mod._TLS, "rung", None)
    if degrade_rung in ("finer_tiling", "fusion_off"):
        # OOM degradation (resilience/degrade.py): override the cost
        # model's choices with the finest divisible shardings — the
        # dag here is a private clone, and the forced markers land in
        # the compile signature below
        degrade_mod.force_finer(dag, mesh)

    with prof.phase("sign"):
        ctx = _SigCtx()
        root_sig = ctx.of(dag)
    leaves = ctx.leaves
    is_tuple = isinstance(dag, TupleExpr)
    if is_tuple:
        out_tilings = dag.out_tilings()
    else:
        out_tilings = (tiling_mod.sanitize(dag.out_tiling(), dag.shape,
                                           mesh),)
    # the audit flag is captured at plan-build time and keyed into the
    # compile signature: an audited trace compiles health probes in,
    # and must never alias a probe-free executable (or vice versa).
    # The degradation rung is keyed the same way: a fusion-off or
    # finer-tiling replan must never alias the normal executable.
    audit = bool(FLAGS.audit_numerics)
    # the mesh component leads with the epoch (elastic recovery): a
    # plan compiled for a dead mesh must never alias a post-rebuild
    # executable of the same structure, and evict_stale_plans reaps
    # old-epoch entries by this element. The redistribution-planner
    # flag is keyed like audit: a planner-on trace emits explicit
    # collective schedules where the planner-off trace emits
    # with_sharding_constraint, for the same structural signature.
    # the kernel-backend policy is keyed like audit/planner: a
    # Pallas-lowered executable must never alias the GSPMD executable
    # of the same structure (or an interpret-mode one a Mosaic one)
    key = (root_sig, tuple(t.axes for t in out_tilings),
           (mesh_mod._EPOCH,) + tuple(sorted(mesh.shape.items())),
           audit, degrade_rung, redistribute_mod.planner_on(),
           kernels_mod.policy_key())

    leaf_ids = tuple(l._id for l in leaves)
    out_shardings = tuple(t.sharding(mesh) for t in out_tilings)

    def traced(*args: Any) -> Any:
        env: Dict[int, Any] = dict(zip(leaf_ids, args))
        # naming session (obs/profile.py): every named_scope emitted
        # under this trace carries the node's _sig digest, so device
        # profiler captures of THIS executable join back to expr nodes
        # (one memoized signing traversal; trace time only; no-op when
        # FLAGS.trace_annotations is off)
        with profile_mod.naming_session():
            if audit:
                # probe session: leaves first (a poisoned input names
                # the LEAF, not its first consumer), then every node as
                # Expr.lower emits it — attach order is topological
                with numerics_mod.probe_session():
                    for leaf, arg in zip(leaves, args):
                        numerics_mod.probe(leaf, arg, kind="leaf")
                    out = dag.lower(env)
            else:
                out = dag.lower(env)
        # a constraint (not jit out_shardings) so GSPMD propagation can
        # negotiate ops like reverse that hard-fail on output overrides.
        # Resolved against the ambient mesh at TRACE time: a retrace
        # under a same-shape substitute assignment (integrity's rotated
        # redundant execution pins one via use_mesh) must bind its
        # constraints to that assignment — XLA rejects programs mixing
        # two device orders. Normal dispatch traces under the build
        # mesh, where this is exactly the prebuilt tuple.
        osh = out_shardings
        amb = mesh_mod.get_mesh()
        if amb is not mesh:
            osh = tuple(t.sharding(amb) for t in out_tilings)
        if is_tuple:
            return tuple(
                jax.lax.with_sharding_constraint(o, s)
                for o, s in zip(out, osh))
        return jax.lax.with_sharding_constraint(out, osh[0])

    identity = tuple(range(len(leaves)))
    raw_order: Optional[Tuple[int, ...]] = None
    if rctx is not None and plan_key is not None:
        raw_order = _arg_order(rctx.leaves, leaves)
    report = build_plan_report(expr, dag, leaves, plan_key,
                               passes_report, out_tilings, raw_order)
    with prof.phase("memory_model"):
        # the predictive memory governor's input: the modeled per-chip
        # peak of THIS plan (resilience/memory.py), on the miss path
        # only — one DAG walk next to an optimizer run + XLA compile
        report["memory"] = memory_mod.estimate_report(dag, out_tilings,
                                                      mesh)
    plan = _Plan(key, traced, out_tilings, is_tuple, identity, report)

    if p_digest is not None or p_reason is not None:
        # persist outcome onto the report (st.explain names disk-hit
        # vs compile; the serve worker stamps it onto flight records)
        rec: Dict[str, Any] = {"source": "compile", "digest": p_digest}
        if p_reason:
            rec["reason"] = p_reason
        if p_entry is not None:
            if p_entry.matches(out_tilings, is_tuple, raw_order,
                               len(raw_order or ())):
                # pre-seed the compile cache with the restored AOT
                # executable under the base (no-donation) variant key:
                # the dispatch below finds it warm — ZERO recompiles.
                # A call-time aval/sharding mismatch inside the guard
                # degrades to a fresh jit of the traced fn just built.
                ex = _Exec(persist_mod.guarded_callable(
                    p_entry, lambda: jax.jit(traced)))
                ex.warm = True
                with _cache_lock:
                    _compile_cache.setdefault(key + (frozenset(),), ex)
                persist_mod.note_hit()
                rec = {"source": "disk", "digest": p_digest}
                if getattr(p_entry, "audit", None) is not None:
                    # the audit verdict persisted next to the
                    # executable: a warm restart under
                    # FLAGS.verify_evaluate reads it instead of
                    # re-lowering + re-compiling for the audit
                    report["audit"] = p_entry.audit
            else:
                persist_mod.reject_entry(p_entry, "meta_mismatch")
                rec["reason"] = "meta_mismatch"
        if raw_order is not None and p_digest is not None:
            plan.persist_digest = p_digest
        report["persist"] = rec
        persist_mod.note_build(rec["source"], p_digest,
                               rec.get("reason"))

    ledger_plan = plan
    if rctx is not None and plan_key is not None:
        if raw_order is not None:
            stored = _Plan(key, traced, out_tilings, is_tuple, raw_order,
                           report)
            # hits dispatch the stored plan: it must carry the same
            # on-disk address so a later recompile re-persists
            stored.persist_digest = plan.persist_digest
            # the winner of a store race is what later lookups (and
            # st.ledger's validation) see — ledger the same object
            ledger_plan = store_plan(plan_key, stored)
        else:
            prof.count("plan_uncacheable")
    # cost ledger (obs/ledger.py): record this plan's predictions
    # (DP cost + per-class components, modeled peak HBM) so measured
    # dispatch times land next to them. Miss-path only.
    ledger_mod.note_plan(ledger_plan)
    # autotune hot-plan templates (obs/monitor.py): under the
    # re-calibration daemon, remember a result-free clone of this
    # miss's raw DAG keyed by its ledger digest so drift-triggered
    # replans run off the hot path. One flag read when the daemon is
    # off — and miss-path only, like the ledger hook above.
    if monitor_mod._AUTOTUNE_FLAG._value:
        monitor_mod.note_plan_built(ledger_plan, expr)
    # the auditor's digest -> node join table, computed LAST: the
    # memory/ledger walks above stamp tiling decisions onto nodes, and
    # the digest must hash the same node state the trace-time naming
    # session will (obs/explain.scope_digest_table)
    report["scope_digests"] = scope_digest_table(dag)
    return plan, dag, leaves


_eval_shape_cache: Dict[Tuple, Any] = {}


def eval_shape_of(fn: Callable, *inputs: Expr, cache_key: Any = None,
                  **kw) -> jax.ShapeDtypeStruct:
    """Exact result shape/dtype via abstract evaluation (no FLOPs).

    With ``cache_key`` (a hashable identity for ``fn``), results are
    memoized on input shapes/dtypes — iterative drivers rebuild
    identical DAG structures every step and abstract evaluation is the
    dominant Python-side cost."""
    key = None
    if cache_key is not None:
        key = (cache_key,
               tuple((i.shape, str(i.dtype),
                      i.weak_kind if isinstance(i, ScalarExpr) else None)
                     for i in inputs))
        hit = _eval_shape_cache.get(key)
        if hit is not None:
            return hit
    specs = []
    for i in inputs:
        if isinstance(i, ScalarExpr):
            specs.append(i.pyvalue)
        else:
            specs.append(jax.ShapeDtypeStruct(i.shape, i.dtype))
    out = jax.eval_shape(fn, *specs, **kw)
    if key is not None and len(_eval_shape_cache) < 4096:
        _eval_shape_cache[key] = out
    return out


# Bottom-bound seam (the persist_mod pattern): the incremental engine
# (expr/incremental.py) needs every Expr type above to exist, and its
# own expr imports are lazy, so binding it here closes the cycle. The
# evaluate() paths read incremental_mod._INC_FLAG._value — one
# attribute-chain read when FLAGS.incremental is off — and
# benchmarks/incremental.py swaps this module binding for its
# null-shim overhead arm (the warm_start.py persist_mod pattern).
from . import incremental as incremental_mod  # noqa: E402
