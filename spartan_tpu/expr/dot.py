"""Distributed matmul.

Parity with ``[U] spartan/expr/dot.py`` (SURVEY.md §3.3: shuffle-based
tile GEMM — per A-tile kernel fetches matching B tiles over RPC, partial
``np.dot`` products reducer-merged into the target; O(#tile-pairs)
point-to-point transfers). TPU-native lowering per BASELINE.json:5/8: the
operands get 2-D mesh shardings and ``jnp.dot`` under GSPMD emits
all-gather / reduce-scatter over ICI; the MXU does the FLOPs in one fused
kernel per shard. An explicit shard_map variant (:func:`dot_shardmap`,
psum-based) exists for A/B benchmarking against GSPMD.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..array import tiling as tiling_mod
from ..array.tiling import Tiling
from ..parallel import mesh as mesh_mod
from ..parallel import redistribute as redist_mod
from ..parallel.mesh import AXIS_COL, AXIS_ROW
from .base import Expr, as_expr


class DotExpr(Expr):
    """a @ b for 1-D/2-D operands (NumPy dot semantics)."""

    def __init__(self, a: Expr, b: Expr, precision: Optional[str] = None):
        if a.ndim > 2 or b.ndim > 2:
            raise ValueError("dot supports 1-D and 2-D operands")
        if a.shape[-1] != (b.shape[0] if b.ndim else 1):
            raise ValueError(f"dot shape mismatch {a.shape} x {b.shape}")
        self.a = a
        self.b = b
        self.precision = precision
        # smart-tiling plan (tiling_cost): (output Tiling, strategy)
        # where strategy None = gathered contraction and a mesh axis =
        # contraction sharded there, merged by an output psum.
        # Recorded even when the chosen grid equals the default, so the
        # operand placement always reaches _lower without a redundant
        # output constraint.
        self._dot_plan = None
        if a.ndim == 1 and b.ndim == 1:
            shape: Tuple[int, ...] = ()
        elif a.ndim == 1:
            shape = (b.shape[1],)
        elif b.ndim == 1:
            shape = (a.shape[0],)
        else:
            shape = (a.shape[0], b.shape[1])
        super().__init__(shape, np.result_type(a.dtype, b.dtype))

    @property
    def _dot_strategy(self):
        """Contraction placement from the plan (None = gathered)."""
        return self._dot_plan[1] if self._dot_plan is not None else None

    def children(self) -> Tuple[Expr, ...]:
        return (self.a, self.b)

    def replace_children(self, new_children) -> "DotExpr":
        return DotExpr(new_children[0], new_children[1], self.precision)

    def _lower(self, env: Dict[int, Any]) -> Any:
        av = self.a.lower(env)
        bv = self.b.lower(env)
        mesh = mesh_mod.get_mesh()
        if (self.a.ndim == 2 and self.b.ndim == 2
                and self._dot_plan is not None):
            # Smart tiling chose this GEMM's plan: output grid
            # (m_r, m_c) with the contraction on mesh axis k (or
            # gathered when k is None) — A sharded (m_r, k),
            # B (k, m_c); for sharded k GSPMD inserts the merging
            # all-reduce. The cost model prices operand resharding and
            # the psum with exactly this rule (tiling_cost.py). Without
            # a plan (pass off) GSPMD negotiates from the operands' own
            # shardings — the reference's no-smart-tiling behavior
            # (tiles computed where they live).
            plan_t, k = self._dot_plan
            m_r, m_c = plan_t.axes[:2]
            # operand reshard edges go through the redistribution seam
            # (src = the committed child tiling the DP priced this
            # edge from): explicit collective schedules where the
            # planner predicts a win, with_sharding_constraint else
            av = redist_mod.constrain(av, Tiling((m_r, k)), mesh,
                                      src=self.a.out_tiling())
            bv = redist_mod.constrain(bv, Tiling((k, m_c)), mesh,
                                      src=self.b.out_tiling())
        return jnp.dot(av, bv, precision=self.precision)

    def _sig(self, ctx) -> Tuple:
        # the plan changes the lowering, so it must key the cache
        plan = (None if self._dot_plan is None
                else (self._dot_plan[0].axes, self._dot_plan[1]))
        return ("dot", self.precision, plan,
                ctx.of(self.a), ctx.of(self.b))

    def _default_tiling(self) -> Tiling:
        if self.ndim == 2:
            return tiling_mod.block(2)
        if self.ndim == 1:
            return tiling_mod.row(1)
        return tiling_mod.replicated(0)


def dot(a: Any, b: Any, precision: Optional[str] = None):
    """``a @ b``; masked operands route through the mask-aware GEMM
    (numpy.ma.dot semantics — see array/masked.py masked_dot)."""
    from ..array.masked import MaskedDistArray, masked_dot

    if isinstance(a, MaskedDistArray) or isinstance(b, MaskedDistArray):
        return masked_dot(a, b, precision=precision)
    return DotExpr(as_expr(a), as_expr(b), precision)


class DotShardMapExpr(Expr):
    """Explicit blocked GEMM under shard_map: A sharded (x, y) on
    (rows, contraction), B sharded (y,) on rows; each device computes its
    partial product on the MXU and ``psum`` over y reduces — the literal
    all-reduce lowering of the reference's reducer-merge (SURVEY.md §3.3).
    """

    def __init__(self, a: Expr, b: Expr):
        if a.ndim != 2 or b.ndim != 2:
            raise ValueError("dot_shardmap requires 2-D operands")
        if a.shape[1] != b.shape[0]:
            raise ValueError(f"shape mismatch {a.shape} x {b.shape}")
        self.a = a
        self.b = b
        super().__init__((a.shape[0], b.shape[1]),
                         np.result_type(a.dtype, b.dtype))

    def children(self) -> Tuple[Expr, ...]:
        return (self.a, self.b)

    def replace_children(self, new_children) -> "DotShardMapExpr":
        return DotShardMapExpr(new_children[0], new_children[1])

    def _lower(self, env: Dict[int, Any]) -> Any:
        from ..utils.compat import shard_map

        mesh = mesh_mod.get_mesh()
        av = self.a.lower(env)
        bv = self.b.lower(env)
        a_t = tiling_mod.Tiling((AXIS_ROW, AXIS_COL))
        b_t = tiling_mod.Tiling((AXIS_COL, None))
        av = redist_mod.constrain(av, a_t, mesh,
                                  src=self.a.out_tiling())
        bv = redist_mod.constrain(bv, b_t, mesh,
                                  src=self.b.out_tiling())

        def kernel(ab, bb):
            partial = jnp.dot(ab, bb)
            return jax.lax.psum(partial, AXIS_COL)

        mapped = shard_map(kernel, mesh=mesh,
                           in_specs=(a_t.spec(), b_t.spec()),
                           out_specs=tiling_mod.row(2).spec())
        return mapped(av, bv)

    def _sig(self, ctx) -> Tuple:
        return ("dot_smap", ctx.of(self.a), ctx.of(self.b))

    def _default_tiling(self) -> Tiling:
        return tiling_mod.row(2)


def dot_shardmap(a: Any, b: Any) -> DotShardMapExpr:
    return DotShardMapExpr(as_expr(a), as_expr(b))
