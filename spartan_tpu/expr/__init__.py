"""Public lazy-expression API (the reference's ``spartan.expr`` surface)."""

from .base import (Expr, ScalarExpr, ValExpr, as_expr, clear_compile_cache,
                   compile_cache_size, evaluate, lazify)
from .builtins import *  # noqa: F401,F403
from .builtins import __all__ as _builtin_all
from .map import MapExpr, map, map_with_location
from .ndarray import CreateExpr, RandomExpr
from .optimize import dag_nodes, optimize
from .reduce import GeneralReduceExpr, ReduceExpr

__all__ = ["Expr", "ValExpr", "ScalarExpr", "as_expr", "lazify", "evaluate",
           "optimize", "dag_nodes", "map", "map_with_location", "MapExpr",
           "ReduceExpr", "GeneralReduceExpr", "CreateExpr", "RandomExpr",
           "compile_cache_size", "clear_compile_cache"] + list(_builtin_all)
