"""Public lazy-expression API (the reference's ``spartan.expr`` surface)."""

from .base import (DictExpr, Expr, ListExpr, ScalarExpr, TupleExpr, ValExpr,
                   as_expr, clear_compile_cache, clear_plan_cache,
                   compile_cache_size, dict_of, evaluate, lazify,
                   plan_cache_size, tuple_of)
from .fio import from_file, load, save
from .builtins import *  # noqa: F401,F403
from .builtins import __all__ as _builtin_all
from .assign import WriteExpr, assign, write_array
from .dot import DotExpr, dot, dot_shardmap
from .filter import GatherExpr, filter
from .loop import LoopExpr, LoopItemExpr, loop
from .map import MapExpr, map, map_with_location
from .map2 import Map2Expr, ShardMap2Expr, map2, shard_map2
from .ndarray import CreateExpr, RandomExpr
from .optimize import dag_nodes, optimize
from .outer import OuterExpr, outer
from .reduce import GeneralReduceExpr, ReduceExpr
from .reshape import (ConcatExpr, ReshapeExpr, TransposeExpr, concatenate,
                      ravel, reshape, transpose)
from .shuffle import shuffle
from .slice import SliceExpr, make_slice

__all__ = ["Expr", "ValExpr", "ScalarExpr", "TupleExpr", "tuple_of",
           "ListExpr", "DictExpr", "dict_of", "from_file", "load", "save",
           "as_expr", "lazify", "evaluate",
           "optimize", "dag_nodes", "map", "map_with_location", "MapExpr",
           "ReduceExpr", "GeneralReduceExpr", "CreateExpr", "RandomExpr",
           "compile_cache_size", "clear_compile_cache",
           "plan_cache_size", "clear_plan_cache",
           "assign", "write_array", "WriteExpr", "dot", "dot_shardmap",
           "DotExpr", "filter", "GatherExpr", "map2", "shard_map2",
           "Map2Expr", "ShardMap2Expr", "outer", "OuterExpr", "shuffle",
           "loop", "LoopExpr", "LoopItemExpr",
           "transpose", "reshape", "ravel", "concatenate", "SliceExpr",
           "TransposeExpr", "ReshapeExpr", "ConcatExpr",
           ] + list(_builtin_all)
