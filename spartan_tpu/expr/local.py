"""Local (fused per-shard) expression trees — the map-fusion unit.

Parity with the reference's inner DAG (SURVEY.md §2.3: ``[U]
spartan/expr/local.py`` — ``LocalInput``/``LocalMapExpr``/``FnCallExpr``,
"what map-fusion fuses"). In the reference a fused local tree was executed
by NumPy (or Parakeet-JITted) inside one tile kernel; here it is *traced*
into the enclosing XLA computation, so fusion serves to (a) keep the expr
DAG small, (b) preserve the reference's optimizer-pass API, while XLA does
the actual loop fusion on the MXU/VPU.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

# ufunc registry: name -> (jnp fn, numpy oracle fn, arity)
UFUNCS: Dict[str, Tuple[Callable, Callable, int]] = {
    # binary arithmetic
    "add": (jnp.add, np.add, 2),
    "subtract": (jnp.subtract, np.subtract, 2),
    "multiply": (jnp.multiply, np.multiply, 2),
    "divide": (jnp.divide, np.divide, 2),
    "true_divide": (jnp.true_divide, np.true_divide, 2),
    "floor_divide": (jnp.floor_divide, np.floor_divide, 2),
    "mod": (jnp.mod, np.mod, 2),
    "power": (jnp.power, np.power, 2),
    "maximum": (jnp.maximum, np.maximum, 2),
    "minimum": (jnp.minimum, np.minimum, 2),
    "arctan2": (jnp.arctan2, np.arctan2, 2),
    "hypot": (jnp.hypot, np.hypot, 2),
    # comparisons / logic
    "equal": (jnp.equal, np.equal, 2),
    "not_equal": (jnp.not_equal, np.not_equal, 2),
    "less": (jnp.less, np.less, 2),
    "less_equal": (jnp.less_equal, np.less_equal, 2),
    "greater": (jnp.greater, np.greater, 2),
    "greater_equal": (jnp.greater_equal, np.greater_equal, 2),
    "logical_and": (jnp.logical_and, np.logical_and, 2),
    "logical_or": (jnp.logical_or, np.logical_or, 2),
    "logical_xor": (jnp.logical_xor, np.logical_xor, 2),
    "bitwise_and": (jnp.bitwise_and, np.bitwise_and, 2),
    "bitwise_or": (jnp.bitwise_or, np.bitwise_or, 2),
    "bitwise_xor": (jnp.bitwise_xor, np.bitwise_xor, 2),
    # unary
    "negative": (jnp.negative, np.negative, 1),
    "absolute": (jnp.absolute, np.absolute, 1),
    "exp": (jnp.exp, np.exp, 1),
    "log": (jnp.log, np.log, 1),
    "log2": (jnp.log2, np.log2, 1),
    "log10": (jnp.log10, np.log10, 1),
    "log1p": (jnp.log1p, np.log1p, 1),
    "expm1": (jnp.expm1, np.expm1, 1),
    "sqrt": (jnp.sqrt, np.sqrt, 1),
    "square": (jnp.square, np.square, 1),
    "sign": (jnp.sign, np.sign, 1),
    "sin": (jnp.sin, np.sin, 1),
    "cos": (jnp.cos, np.cos, 1),
    "tan": (jnp.tan, np.tan, 1),
    "arcsin": (jnp.arcsin, np.arcsin, 1),
    "arccos": (jnp.arccos, np.arccos, 1),
    "arctan": (jnp.arctan, np.arctan, 1),
    "sinh": (jnp.sinh, np.sinh, 1),
    "cosh": (jnp.cosh, np.cosh, 1),
    "tanh": (jnp.tanh, np.tanh, 1),
    "floor": (jnp.floor, np.floor, 1),
    "ceil": (jnp.ceil, np.ceil, 1),
    "rint": (jnp.rint, np.rint, 1),
    "logical_not": (jnp.logical_not, np.logical_not, 1),
    "invert": (jnp.invert, np.invert, 1),
    "isnan": (jnp.isnan, np.isnan, 1),
    "isinf": (jnp.isinf, np.isinf, 1),
    "isfinite": (jnp.isfinite, np.isfinite, 1),
    "reciprocal": (jnp.reciprocal, np.reciprocal, 1),
    "conjugate": (jnp.conjugate, np.conjugate, 1),
    # ternary
    "where": (jnp.where, np.where, 3),
    "clip": (jnp.clip, np.clip, 3),
}


class LocalExpr:
    """Node of a fused elementwise tree. Immutable; hashable via key()."""

    def emit(self, inputs: Sequence[Any]) -> Any:
        """Trace this tree over jnp input values."""
        raise NotImplementedError

    def emit_np(self, inputs: Sequence[Any]) -> Any:
        """Oracle evaluation with NumPy (tests / host fallback)."""
        raise NotImplementedError

    def key(self) -> Tuple:
        """Structural cache key."""
        raise NotImplementedError

    def remap(self, mapping: Dict[int, "LocalExpr"]) -> "LocalExpr":
        """Substitute LocalInput indices (fusion splicing)."""
        raise NotImplementedError

    def max_input(self) -> int:
        raise NotImplementedError


class LocalInput(LocalExpr):
    __slots__ = ("idx",)

    def __init__(self, idx: int):
        self.idx = idx

    def emit(self, inputs):
        return inputs[self.idx]

    emit_np = emit

    def key(self):
        return ("in", self.idx)

    def remap(self, mapping):
        return mapping.get(self.idx, self)

    def max_input(self):
        return self.idx

    def __repr__(self):
        return f"$i{self.idx}"


class LocalConst(LocalExpr):
    """A compile-time constant folded into the kernel (python scalar)."""

    __slots__ = ("value",)

    def __init__(self, value: Any):
        self.value = value

    def emit(self, inputs):
        return self.value

    emit_np = emit

    def key(self):
        return ("const", type(self.value).__name__, float(self.value)
                if isinstance(self.value, (int, float, bool)) else
                repr(self.value))

    def remap(self, mapping):
        return self

    def max_input(self):
        return -1

    def __repr__(self):
        return f"{self.value!r}"


class LocalUfunc(LocalExpr):
    __slots__ = ("name", "args")

    def __init__(self, name: str, args: Sequence[LocalExpr]):
        if name not in UFUNCS:
            raise ValueError(f"unknown ufunc {name!r}")
        self.name = name
        self.args = tuple(args)

    def emit(self, inputs):
        fn = UFUNCS[self.name][0]
        return fn(*[a.emit(inputs) for a in self.args])

    def emit_np(self, inputs):
        fn = UFUNCS[self.name][1]
        return fn(*[a.emit_np(inputs) for a in self.args])

    def key(self):
        return ("uf", self.name) + tuple(a.key() for a in self.args)

    def remap(self, mapping):
        return LocalUfunc(self.name, [a.remap(mapping) for a in self.args])

    def max_input(self):
        return max((a.max_input() for a in self.args), default=-1)

    def __repr__(self):
        return f"{self.name}({', '.join(map(repr, self.args))})"


class LocalCall(LocalExpr):
    """A user-supplied traceable function over the inputs (the reference's
    ``FnCallExpr``). The function must be jax-traceable; its identity is
    part of the compile-cache key."""

    __slots__ = ("fn", "args", "fn_kw")

    def __init__(self, fn: Callable, args: Sequence[LocalExpr],
                 fn_kw: Tuple[Tuple[str, Any], ...] = ()):
        self.fn = fn
        self.args = tuple(args)
        self.fn_kw = tuple(fn_kw)

    def emit(self, inputs):
        return self.fn(*[a.emit(inputs) for a in self.args],
                       **dict(self.fn_kw))

    def emit_np(self, inputs):
        return self.fn(*[a.emit_np(inputs) for a in self.args],
                       **dict(self.fn_kw))

    def key(self):
        from .base import fn_key

        return (("call", fn_key(self.fn), self.fn_kw)
                + tuple(a.key() for a in self.args))

    def remap(self, mapping):
        return LocalCall(self.fn, [a.remap(mapping) for a in self.args],
                         self.fn_kw)

    def max_input(self):
        return max((a.max_input() for a in self.args), default=-1)

    def __repr__(self):
        name = getattr(self.fn, "__name__", "fn")
        return f"{name}({', '.join(map(repr, self.args))})"


def count_ops(tree: LocalExpr) -> int:
    """Number of op nodes (for optimizer tests asserting fusion shape)."""
    if isinstance(tree, (LocalInput, LocalConst)):
        return 0
    if isinstance(tree, (LocalUfunc, LocalCall)):
        return 1 + sum(count_ops(a) for a in tree.args)
    raise TypeError(type(tree))
