"""Boolean-mask and integer-array (fancy) indexing.

Parity with ``[U] spartan/expr/filter.py`` (SURVEY.md §2.3 "boolean/fancy
FilterExpr"). Two regimes, per SURVEY.md §7 hard part 2 (dynamic shapes
are hostile to XLA):

* **Integer-array gather** — static output shape, fully traced (one XLA
  gather over the sharded operand).
* **Boolean mask** — output size is data-dependent. The mask is forced
  eagerly (it is tiny relative to the data), its nonzero indices computed
  on host, and the gather then traced with a static index set. This
  mirrors the reference's semantics exactly (it too materialized the
  compacted result eagerly through tile RPCs).
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax.numpy as jnp
import numpy as np

from ..array import tiling as tiling_mod
from ..array.tiling import Tiling
from .base import Expr, ValExpr, as_expr


class GatherExpr(Expr):
    """x[indices] (or x[i_idx, j_idx, ...]) with static index arrays."""

    def __init__(self, input: Expr, indices: Tuple[np.ndarray, ...]):
        self.input = input
        self.indices = indices
        out = np.broadcast_shapes(*[ix.shape for ix in indices])
        shape = out + input.shape[len(indices):]
        super().__init__(shape, input.dtype)

    def children(self) -> Tuple[Expr, ...]:
        return (self.input,)

    def replace_children(self, new_children) -> "GatherExpr":
        return GatherExpr(new_children[0], self.indices)

    def _lower(self, env: Dict[int, Any]) -> Any:
        x = self.input.lower(env)
        return x[tuple(jnp.asarray(ix) for ix in self.indices)]

    def _sig(self, ctx) -> Tuple:
        key = tuple((ix.shape, ix.tobytes()) for ix in self.indices)
        return ("gather", key, ctx.of(self.input))

    def _default_tiling(self) -> Tiling:
        return tiling_mod.default_tiling(self.shape)


def filter(x: Any, mask_or_indices: Any) -> Expr:
    x = as_expr(x)
    idx = mask_or_indices
    if isinstance(idx, Expr):
        if idx.dtype == np.bool_:
            mask = idx.glom()
            nz = np.nonzero(mask)
            return GatherExpr(x, tuple(np.asarray(i) for i in nz))
        idx = idx.glom()
    idx = np.asarray(idx)
    if idx.dtype == np.bool_:
        nz = np.nonzero(idx)
        return GatherExpr(x, tuple(np.asarray(i) for i in nz))
    if not np.issubdtype(idx.dtype, np.integer):
        raise TypeError(f"unsupported index dtype {idx.dtype}")
    dim = x.shape[0]
    idx = np.where(idx < 0, idx + dim, idx)
    if (idx < 0).any() or (idx >= dim).any():
        raise IndexError("fancy index out of bounds")
    return GatherExpr(x, (idx,))
