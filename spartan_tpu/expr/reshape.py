"""Shape ops: transpose / reshape / ravel / concatenate.

Parity with ``[U] spartan/expr/reshape.py`` and ``transpose.py``
(SURVEY.md §2.3: "lazy reshape/transpose implemented via shuffle/map2 —
data movement, not views"). Here the data movement is XLA's: the op is
traced, the output sharding differs from the input's, and GSPMD emits the
all-to-all / collective-permute that the reference's shuffle performed
(SURVEY.md §2.6 'Shuffle / all-to-all redistribution').
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..array import tiling as tiling_mod
from ..array.tiling import Tiling
from .base import Expr, as_expr


class TransposeExpr(Expr):
    def __init__(self, input: Expr, perm: Tuple[int, ...]):
        self.input = input
        self.perm = perm
        shape = tuple(input.shape[p] for p in perm)
        super().__init__(shape, input.dtype)

    def children(self) -> Tuple[Expr, ...]:
        return (self.input,)

    def replace_children(self, new_children) -> "TransposeExpr":
        return TransposeExpr(new_children[0], self.perm)

    def _lower(self, env: Dict[int, Any]) -> Any:
        return jnp.transpose(self.input.lower(env), self.perm)

    def _sig(self, ctx) -> Tuple:
        return ("transpose", self.perm, ctx.of(self.input))

    def _default_tiling(self) -> Tiling:
        return self.input.out_tiling().transpose(self.perm)


def transpose(x: Any, *axes) -> TransposeExpr:
    x = as_expr(x)
    if len(axes) == 1 and isinstance(axes[0], (tuple, list)):
        axes = tuple(axes[0])
    if not axes:
        axes = tuple(reversed(range(x.ndim)))
    if sorted(axes) != list(range(x.ndim)):
        raise ValueError(f"invalid permutation {axes} for rank {x.ndim}")
    return TransposeExpr(x, tuple(int(a) for a in axes))


class ReshapeExpr(Expr):
    def __init__(self, input: Expr, new_shape: Tuple[int, ...]):
        self.input = input
        super().__init__(new_shape, input.dtype)

    def children(self) -> Tuple[Expr, ...]:
        return (self.input,)

    def replace_children(self, new_children) -> "ReshapeExpr":
        return ReshapeExpr(new_children[0], self._shape)

    def _lower(self, env: Dict[int, Any]) -> Any:
        return jnp.reshape(self.input.lower(env), self._shape)

    def _sig(self, ctx) -> Tuple:
        return ("reshape", self._shape, ctx.of(self.input))

    def _default_tiling(self) -> Tiling:
        # a reshape generally invalidates the input tiling; re-place on
        # the mesh (GSPMD moves the bytes)
        return tiling_mod.default_tiling(self.shape)


def reshape(x: Any, *shape) -> ReshapeExpr:
    x = as_expr(x)
    if len(shape) == 1 and isinstance(shape[0], (tuple, list)):
        shape = tuple(shape[0])
    shape = tuple(int(s) for s in shape)
    if -1 in shape:
        known = int(np.prod([s for s in shape if s != -1]))
        if shape.count(-1) != 1 or x.size % known:
            raise ValueError(f"cannot reshape {x.shape} into {shape}")
        shape = tuple(x.size // known if s == -1 else s for s in shape)
    if int(np.prod(shape)) != x.size:
        raise ValueError(f"cannot reshape {x.shape} into {shape}")
    return ReshapeExpr(x, shape)


def ravel(x: Any) -> ReshapeExpr:
    x = as_expr(x)
    return ReshapeExpr(x, (x.size,))


class ConcatExpr(Expr):
    def __init__(self, inputs: Sequence[Expr], axis: int):
        self.inputs = tuple(inputs)
        self.axis = axis
        first = self.inputs[0]
        for c in self.inputs[1:]:
            if (c.shape[:axis] + c.shape[axis + 1:]
                    != first.shape[:axis] + first.shape[axis + 1:]):
                raise ValueError("concatenate shapes incompatible")
        shape = list(first.shape)
        shape[axis] = sum(c.shape[axis] for c in self.inputs)
        dtype = np.result_type(*[c.dtype for c in self.inputs])
        super().__init__(tuple(shape), dtype)

    def children(self) -> Tuple[Expr, ...]:
        return self.inputs

    def replace_children(self, new_children) -> "ConcatExpr":
        return ConcatExpr(new_children, self.axis)

    def _lower(self, env: Dict[int, Any]) -> Any:
        return jnp.concatenate([c.lower(env) for c in self.inputs],
                               axis=self.axis)

    def _sig(self, ctx) -> Tuple:
        return (("concat", self.axis)
                + tuple(ctx.of(c) for c in self.inputs))

    def _default_tiling(self) -> Tiling:
        # keep the first input's sharding on non-concat axes
        t = self.inputs[0].out_tiling()
        return t.with_axis(self.axis, None)


def concatenate(arrays: Sequence[Any], axis: int = 0):
    """Join arrays along an axis; masked operands keep their masks
    (numpy.ma.concatenate — see array/masked.py)."""
    from ..array.masked import MaskedDistArray, masked_concatenate

    arrays = list(arrays)
    if any(isinstance(a, MaskedDistArray) for a in arrays):
        return masked_concatenate(arrays, axis)
    inputs = [as_expr(a) for a in arrays]
    if not inputs:
        raise ValueError("need at least one array")
    axis = axis % inputs[0].ndim
    return ConcatExpr(inputs, axis)
