"""Delta-aware incremental evaluation (ROADMAP open item 2).

The plan cache (expr/base.py) made "same DAG" skip planning; this layer
makes "same DAG + mostly-same data" skip most of the *compute*. The
mutation seam is ``DistArray.update()`` / the ``assign`` expr route
(array/distarray.py): a functional update returns a new handle that
SHARES its parent's :class:`~..array.distarray.Lineage` with the
written extent logged, so the raw-DAG plan key — leaf signatures are
positional shape/dtype/tiling, not data identity — still hits, and
this module can tell exactly which tiles moved since the result it
cached.

On a warm ``evaluate()`` whose plan is cached (and only there —
``intercept`` is called from the plan-cache hit path, behind one flag
read when ``FLAGS.incremental`` is off):

1. Per-leaf dirty extents come from comparing each leaf against the
   snapshot the result-cache entry recorded (same handle = clean; same
   lineage at a later version = the logged extents; anything else =
   whole-leaf dirty).
2. Dirty boxes propagate bottom-up through the RAW DAG with per-node
   access-pattern rules: map = identity under broadcast, axis-reduce =
   the box with reduced axes collapsed, dot = dirty rows/cols of the
   non-contracted dims (dirt along the contracted dim feeds every
   output it touches), reduce_all / loop / shuffle / anything unknown
   = whole-node (conservative is always correct — over-recompute of a
   deterministic program is bit-equal).
3. If the root's dirty box is a small-enough sub-region
   (``FLAGS.incremental_max_dirty_frac``), the engine rebuilds a
   RESTRICTED sub-DAG computing just that region. Preferred leaf
   form: when every dirty leaf's delta is a single write whose
   post-write values the mutation seam stashed
   (``Lineage.stashed_between``), the restriction uses the EXACT root
   box and the stash becomes a materialized ValExpr leaf — no slicing
   of sharded parents at all (GSPMD can only lower a traced-start
   dynamic-slice on a sharded dim by gathering the sliced operand,
   ~30x the restricted compute), and streaming deltas that repeat
   their batch shape share one plan (leaf sigs are positional).
   Otherwise leaves become dynamic slices with traced (ScalarExpr)
   starts and power-of-two-quantized static sizes, so consecutive
   deltas of similar size still share one plan and one executable.
   Either way the sub-DAG dispatches through the ordinary
   ``evaluate()`` and splices into the cached previous result with
   a dynamic-update-slice under the committed output sharding.
   Bit-equality with a full recompute holds because the restricted
   program runs the same per-element contractions (contracted dims
   are never cut; the stash keeps the parent's sharding on un-cut
   axes, so even the partial-sum structure of sharded contractions
   matches) and the clean region is byte-identical by induction.
4. Anything the rules can't prove clean falls back to the ordinary
   full dispatch with the reason recorded in metrics and
   ``st.explain`` — the honest-fallback contract every prior layer
   uses.

Cached results live in a bounded LRU under ``FLAGS.result_cache_bytes``
(reported to the memory governor's ledger via :func:`cache_bytes` and
the ``incremental_cache_bytes`` gauge). Entries are mesh-epoch fenced:
``evict_stale()`` (called from ``evict_stale_plans()`` after elastic
recovery) reaps entries born under a dead mesh, and an entry whose
result or leaves were donated is dropped on first touch.

Expr-layer imports happen lazily inside functions: expr/base.py binds
this module at import time (``incremental_mod``, swappable by the
null-shim arm of benchmarks/incremental.py) and map/reduce/dot import
base themselves.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

from ..array.extent import TileExtent
from ..obs.metrics import REGISTRY
from ..parallel import mesh as mesh_mod
from ..utils import profiling as prof
from ..utils.config import FLAGS

_INC_FLAG = FLAGS.define_bool(
    "incremental", False,
    "Delta-aware evaluation: on a plan-cache hit, recompute only the "
    "tiles dirtied by DistArray.update()/assign since the cached "
    "result, splicing them into the cached output (bit-equal to a "
    "full recompute; falls back to one whenever cleanliness can't be "
    "proven, with the reason in incremental_* metrics + st.explain). "
    "Off by default: the hit path then pays exactly one flag read.")
_CACHE_FLAG = FLAGS.define_int(
    "result_cache_bytes", 256 << 20,
    "Budget for the incremental engine's per-plan result cache "
    "(bounded LRU, host-held references to device buffers). Each "
    "entry is charged for its cached result PLUS the leaf snapshots "
    "it pins; residency (incl. the mutation-seam stash kept alive "
    "by cached snapshots) is visible to the memory governor's ledger "
    "via the incremental_cache_bytes gauge / "
    "expr.incremental.cache_bytes(). A single entry larger than the "
    "budget is never cached.")
_FRAC_FLAG = FLAGS.define_float(
    "incremental_max_dirty_frac", 0.25,
    "Dirty-fraction ceiling for the incremental path: when the root's "
    "propagated dirty box exceeds this fraction of the output, a full "
    "recompute is cheaper than restrict+splice and the engine falls "
    "back (reason 'dirty-frac').")

NOT_HANDLED = object()  # sentinel: caller proceeds with the full path

_MISS = object()


class _Full:
    """Whole-node dirty (the conservative propagation sentinel)."""

    __repr__ = __str__ = lambda self: "FULL"


FULL = _Full()


class Unsupported(Exception):
    """A DAG construct the restriction builder has no rule for — the
    caller degrades to a full recompute with this as the reason."""


# -- the bounded result cache -------------------------------------------


class _Entry:
    """One cached result. IMMUTABLE after construction: a warm splice
    publishes a fresh entry with a compare-and-swap on the entry object
    (see ``intercept``), so concurrent intercepts for the same plan key
    can never observe — or double-account — a half-updated entry.

    ``nbytes`` is the device residency attributable to the entry: the
    cached result PLUS the leaf snapshots ``slots`` keeps alive (each
    is a strong reference pinning that leaf's buffers), so the LRU
    budget and the memory governor see what the entry actually pins.
    Lineage stash bytes are shared across entries and accounted
    separately in :func:`cache_bytes`."""

    __slots__ = ("result", "slots", "epoch", "nbytes")

    def __init__(self, result: Any, slots: Tuple, epoch: int,
                 nbytes: int):
        self.result = result
        self.slots = slots
        self.epoch = epoch
        self.nbytes = nbytes


_lock = threading.RLock()
_cache: "OrderedDict[Tuple, _Entry]" = OrderedDict()
_total_bytes = 0
_tls = threading.local()  # re-entry guard for the inner evaluates


def cache_bytes() -> int:
    """Current result-cache residency — the number the memory
    governor's ledger sees: cached results, the leaf snapshots the
    entries pin, and the mutation-seam stash of every Lineage a cached
    snapshot keeps alive (deduplicated — lineages are shared across
    handles and entries)."""
    with _lock:
        total = _total_bytes
        seen: set = set()
        for e in _cache.values():
            for s in e.slots:
                if s[0] != "a":
                    continue
                lin = s[1]._lineage
                if lin is not None and id(lin) not in seen:
                    seen.add(id(lin))
                    total += lin.stash_bytes
    return total


def cache_entries() -> int:
    return len(_cache)


def clear() -> int:
    """Drop every cached result (tests/benchmarks). Returns entries
    dropped."""
    global _total_bytes
    with _lock:
        n = len(_cache)
        _cache.clear()
        _total_bytes = 0
    _gauge()
    return n


def evict_stale() -> int:
    """Reap entries born under a dead mesh epoch — called from
    ``evict_stale_plans()`` (elastic recovery) next to the plan/compile
    cache purge, so a rebuilt mesh can never be served buffers that
    lived on its predecessor's devices."""
    global _total_bytes
    epoch = mesh_mod._EPOCH
    with _lock:
        dead = [k for k, e in _cache.items() if e.epoch != epoch]
        for k in dead:
            _total_bytes -= _cache.pop(k).nbytes
    if dead:
        prof.count("incremental_evictions", len(dead))
        _gauge()
    return len(dead)


def _drop(key: Tuple) -> None:
    global _total_bytes
    with _lock:
        e = _cache.pop(key, None)
        if e is not None:
            _total_bytes -= e.nbytes
    _gauge()


def _gauge() -> None:
    REGISTRY.gauge(
        "incremental_cache_bytes",
        "incremental result-cache residency, bytes").set(cache_bytes())


def _slots_nbytes(slots: Tuple) -> int:
    """Device bytes pinned by an entry's leaf snapshots (deduplicated:
    the same DistArray may fill several arg slots)."""
    seen: set = set()
    total = 0
    for s in slots:
        if s[0] != "a" or id(s[1]) in seen:
            continue
        seen.add(id(s[1]))
        arr = s[1]
        total += int(arr.size) * arr.dtype.itemsize
    return total


def _snapshot_slots(ordered: List[Any]) -> Optional[Tuple]:
    """Per-arg-slot leaf snapshot: ('s', value) for scalars, ('a',
    array, version) for DistArray-backed leaves; None when a leaf is
    outside the model (nothing to compare against next time)."""
    from .base import ScalarExpr, _leaf_array

    slots = []
    for leaf in ordered:
        if isinstance(leaf, ScalarExpr):
            slots.append(("s", leaf.pyvalue))
            continue
        arr = _leaf_array(leaf)
        if arr is None or arr.is_donated:
            return None
        slots.append(("a", arr, arr._version))
    return tuple(slots)


def note_result(plan: Any, leaves: List[Any], order: Tuple[int, ...],
                result: Any, donated: List[Any], mesh: Any) -> None:
    """Seed/refresh the result cache after an ordinary (full) dispatch.
    Called from both evaluate paths behind the FLAGS.incremental read;
    skips anything outside the model (tuple roots, donated buffers,
    oversized results) — those evaluations simply stay full."""
    global _total_bytes
    if getattr(_tls, "active", False) or donated:
        return
    from ..array.distarray import DistArray

    if not isinstance(result, DistArray):
        return  # tuple roots (multi-output plans) are not modeled
    try:
        ordered = [leaves[i] for i in order]
    except (IndexError, TypeError):
        return
    slots = _snapshot_slots(ordered)
    if slots is None:
        return
    # charge the whole entry: result + the leaf snapshots it pins
    nbytes = (int(result.size) * result.dtype.itemsize
              + _slots_nbytes(slots))
    budget = _CACHE_FLAG._value
    if nbytes > budget:
        return
    with _lock:
        old = _cache.pop(plan.key, None)
        if old is not None:
            _total_bytes -= old.nbytes
        _cache[plan.key] = _Entry(result, slots, mesh_mod._EPOCH, nbytes)
        _total_bytes += nbytes
        evicted = 0
        while _total_bytes > budget and len(_cache) > 1:
            _, e = _cache.popitem(last=False)
            _total_bytes -= e.nbytes
            evicted += 1
    if evicted:
        prof.count("incremental_evictions", evicted)
    _gauge()


# -- per-leaf dirt -------------------------------------------------------


def _leaf_dirt(leaf: Any, slot: Tuple) -> Tuple[Any, Any]:
    """(dirt, stash) for one arg slot: dirt is None (clean) |
    TileExtent | FULL; stash is the lineage's (extent, post-write
    values) pair when the whole delta is a single stashed write."""
    from .base import ScalarExpr, _leaf_array

    if isinstance(leaf, ScalarExpr):
        if slot[0] == "s" and slot[1] == leaf.pyvalue:
            return None, None
        return FULL, None  # a changed scalar feeds everything downstream
    arr = _leaf_array(leaf)
    if arr is None or slot[0] != "a":
        return FULL, None
    rec_arr, rec_ver = slot[1], slot[2]
    if arr is rec_arr and arr._version == rec_ver:
        return None, None
    lin = arr._lineage
    if (lin is None or rec_arr._lineage is not lin
            or arr._version <= rec_ver):
        return FULL, None  # new identity / rewound handle: no delta
    # same lineage at a higher version IS the ancestor chain:
    # _record_mutation gives a branching update (child cut from a
    # non-tip handle) a fresh Lineage, so each log stays linear and
    # dirty_between() is exactly the delta between the two handles
    box = lin.dirty_between(rec_ver, arr._version, arr.shape)
    if box is None:
        return FULL, None
    return (TileExtent(box.ul, box.lr, arr.shape),
            lin.stashed_between(rec_ver, arr._version))


# -- dirty propagation ---------------------------------------------------


def _bbox(a: TileExtent, b: TileExtent, shape: Tuple[int, ...]
          ) -> TileExtent:
    return TileExtent(tuple(min(x, y) for x, y in zip(a.ul, b.ul)),
                      tuple(max(x, y) for x, y in zip(a.lr, b.lr)),
                      shape)


def _covers(box: TileExtent, shape: Tuple[int, ...]) -> bool:
    return (all(u == 0 for u in box.ul)
            and tuple(box.lr) == tuple(shape))


def _union_children(node: Any, children: Tuple, shape: Tuple[int, ...],
                    dirt: Dict, memo: Dict, details: List) -> Any:
    """The broadcast-map rule: a same-shaped dirty child passes its box
    through; a dirty broadcast child (shape differs) dirties the whole
    node."""
    out: Any = None
    for c in children:
        d = _propagate(c, dirt, memo, details)
        if d is None:
            continue
        if d is FULL or tuple(c.shape) != tuple(shape):
            return FULL
        box = TileExtent(d.ul, d.lr, shape)
        out = box if out is None else _bbox(out, box, shape)
    return out


def _propagate(n: Any, dirt: Dict[int, Any], memo: Dict[int, Any],
               details: List[Tuple[Any, Any]]) -> Any:
    """Dirty region of ``n`` in its own coordinates: None | box | FULL."""
    hit = memo.get(n._id, _MISS)
    if hit is not _MISS:
        return hit
    from .base import ScalarExpr, ValExpr
    from .dot import DotExpr
    from .map import MapExpr
    from .reduce import ReduceExpr, _NO_KEEPDIMS

    r: Any
    if n._id in dirt:
        r = dirt[n._id]
    elif (isinstance(n, (ValExpr, ScalarExpr))
          or n._result is not None):
        r = None  # an un-arged leaf / cached sub-DAG: data unchanged
    elif isinstance(n, MapExpr):
        r = _union_children(n, n.inputs, n.shape, dirt, memo, details)
    elif isinstance(n, ReduceExpr):
        pre = _union_children(n, n.inputs, n._pre_shape, dirt, memo,
                              details)
        if pre is None:
            r = None
        elif pre is FULL or n.axis is None:
            r = FULL  # reduce_all: every output element sees the dirt
        elif n.keepdims and n.op not in _NO_KEEPDIMS:
            ul = list(pre.ul)
            lr = list(pre.lr)
            for a in n.axis:
                ul[a], lr[a] = 0, 1
            r = TileExtent(ul, lr, n.shape)
        else:
            box = pre
            for a in sorted(n.axis, reverse=True):
                box = box.drop_axis(a)
            r = TileExtent(box.ul, box.lr, n.shape)
    elif isinstance(n, DotExpr):
        r = _dot_dirt(n, dirt, memo, details)
    else:
        # unknown access pattern (slice, shuffle, loop, transpose,
        # general reduce, shard_map nodes, ...): whole-node dirty —
        # always correct, and the root-level fallback keeps it honest
        r = None
        for c in n.children():
            if _propagate(c, dirt, memo, details) is not None:
                r = FULL
                break
    memo[n._id] = r
    if r is not None:
        details.append((n, r))
    return r


def _dot_dirt(n: Any, dirt: Dict, memo: Dict, details: List) -> Any:
    a, b = n.children()
    da = _propagate(a, dirt, memo, details)
    db = _propagate(b, dirt, memo, details)
    if da is None and db is None:
        return None
    if da is not None and db is not None:
        return FULL
    an, bn = a.ndim, b.ndim
    if da is not None:
        if da is FULL or an != 2:
            return FULL  # dirt on the contracted dim feeds every output
        if bn == 2:  # (n,k)@(k,m): dirty rows -> those output rows
            return TileExtent((da.ul[0], 0), (da.lr[0], n.shape[1]),
                              n.shape)
        return TileExtent((da.ul[0],), (da.lr[0],), n.shape)  # (n,k)@(k,)
    if db is FULL or bn != 2:
        return FULL
    if an == 2:  # (n,k)@(k,m): dirty cols -> those output cols
        return TileExtent((0, db.ul[1]), (n.shape[0], db.lr[1]), n.shape)
    return TileExtent((db.ul[1],), (db.lr[1],), n.shape)  # (k,)@(k,m)


# -- restriction (the dirty sub-plan) ------------------------------------


class DynSliceExpr:
    """``lax.dynamic_slice`` with traced starts and static sizes — the
    restriction leaf. Starts are ScalarExprs (value-free signatures),
    sizes are quantized to powers of two at the root, so successive
    deltas of similar size share one plan and one executable."""


class DynUpdateExpr:
    """``lax.dynamic_update_slice`` splicing the recomputed dirty
    region into the cached previous result, under the destination's
    committed tiling."""


def _build_expr_types():
    """Define the real expr subclasses lazily (base import cycle)."""
    global DynSliceExpr, DynUpdateExpr
    from ..array import tiling as tiling_mod
    from ..array.tiling import Tiling
    from .base import Expr

    class _DynSliceExpr(Expr):
        __doc__ = DynSliceExpr.__doc__

        def __init__(self, input: Expr, starts: Tuple[Expr, ...],
                     sizes: Tuple[int, ...]):
            self.input = input
            self.starts = tuple(starts)
            self.sizes = tuple(int(s) for s in sizes)
            super().__init__(self.sizes, input.dtype)

        def children(self) -> Tuple[Expr, ...]:
            return (self.input,) + self.starts

        def replace_children(self, new_children: Tuple[Expr, ...]):
            return _DynSliceExpr(new_children[0],
                                 tuple(new_children[1:]), self.sizes)

        def _lower(self, env: Dict[int, Any]) -> Any:
            import jax.numpy as jnp
            from jax import lax

            x = self.input.lower(env)
            starts = [jnp.asarray(s.lower(env), jnp.int32)
                      for s in self.starts]
            return lax.dynamic_slice(x, starts, self.sizes)

        def _sig(self, ctx) -> Tuple:
            return (("dynslice", self.sizes)
                    + tuple(ctx.of(c) for c in self.children()))

        def _default_tiling(self) -> Tiling:
            # keep the input's sharding on axes taken whole; cut axes
            # lose alignment with the shard grid (SliceExpr's rule)
            t = self.input.out_tiling()
            for d, sz in enumerate(self.sizes):
                if sz != self.input.shape[d]:
                    t = t.with_axis(d, None)
            return t

    class _DynUpdateExpr(Expr):
        __doc__ = DynUpdateExpr.__doc__

        def __init__(self, dst: Expr, src: Expr,
                     starts: Tuple[Expr, ...]):
            self.dst = dst
            self.src = src
            self.starts = tuple(starts)
            super().__init__(dst.shape, dst.dtype)

        def children(self) -> Tuple[Expr, ...]:
            return (self.dst, self.src) + self.starts

        def replace_children(self, new_children: Tuple[Expr, ...]):
            return _DynUpdateExpr(new_children[0], new_children[1],
                                  tuple(new_children[2:]))

        def _lower(self, env: Dict[int, Any]) -> Any:
            import jax.numpy as jnp
            from jax import lax

            dst = self.dst.lower(env)
            src = jnp.asarray(self.src.lower(env), dst.dtype)
            starts = [jnp.asarray(s.lower(env), jnp.int32)
                      for s in self.starts]
            return lax.dynamic_update_slice(dst, src, starts)

        def _sig(self, ctx) -> Tuple:
            return ("dynupdate",) + tuple(
                ctx.of(c) for c in self.children())

        def _default_tiling(self) -> Tiling:
            return self.dst.out_tiling()  # the committed sharding

    DynSliceExpr = _DynSliceExpr
    DynUpdateExpr = _DynUpdateExpr


_types_built = False


def _types() -> None:
    global _types_built
    if not _types_built:
        _build_expr_types()
        _types_built = True


def _quantize(box: TileExtent, shape: Tuple[int, ...]) -> TileExtent:
    """Round the root's dirty box up to power-of-two sizes (clamped to
    the dim), sliding the start so the box stays covered and in
    bounds: distinct deltas collapse onto ~log2(dim) compiled shapes
    per axis instead of one per delta."""
    ul, lr = [], []
    for u, l, d in zip(box.ul, box.lr, shape):
        size = max(1, l - u)
        q = 1
        while q < size:
            q <<= 1
        q = min(q, d)
        start = min(u, d - q)
        ul.append(start)
        lr.append(start + q)
    return TileExtent(ul, lr, shape)


def _restrict(n: Any, box: TileExtent, memo: Dict,
              stashes: Optional[Dict[int, Tuple]] = None) -> Any:
    """An expr computing ``n[box]`` — same contractions, restricted
    output region. Raises :class:`Unsupported` for nodes without a
    restriction rule. ``stashes`` maps leaf ids to (extent, values)
    pairs from the mutation seam: a leaf whose needed box equals its
    stashed extent is served as a materialized value instead of a
    traced-start dynamic slice of the sharded parent (which GSPMD can
    only lower to a gather of the sliced dim)."""
    key = (n._id, box.ul, box.lr)
    hit = memo.get(key)
    if hit is not None:
        return hit
    from .base import ScalarExpr, ValExpr
    from .dot import DotExpr
    from .map import MapExpr
    from .reduce import ReduceExpr

    if _covers(box, n.shape):
        out = n
    elif (isinstance(n, (ValExpr, ScalarExpr))
          or n._result is not None):
        sv = stashes.get(n._id) if stashes else None
        if (sv is not None and tuple(sv[0].ul) == tuple(box.ul)
                and tuple(sv[0].lr) == tuple(box.lr)):
            from ..array import distarray as da_mod

            out = ValExpr(da_mod.from_jax(sv[1]))
        else:
            out = _dyn_slice(n, box)
    elif isinstance(n, MapExpr):
        out = MapExpr(
            tuple(_restrict_bcast(c, box, n.shape, memo, stashes)
                  for c in n.inputs), n.op)
    elif isinstance(n, ReduceExpr):
        if n.axis is None:
            raise Unsupported("restrict:reduce_all")
        ps = n._pre_shape
        if n.keepdims and n.op not in ("argmax", "argmin"):
            ul = list(box.ul)
            lr = list(box.lr)
            for a in n.axis:
                ul[a], lr[a] = 0, ps[a]
        else:
            ul, lr = [], []
            kept = [d for d in range(len(ps)) if d not in n.axis]
            pos = {d: i for i, d in enumerate(kept)}
            for d in range(len(ps)):
                if d in pos:
                    ul.append(box.ul[pos[d]])
                    lr.append(box.lr[pos[d]])
                else:
                    ul.append(0)
                    lr.append(ps[d])
        pre_box = TileExtent(ul, lr, ps)
        out = ReduceExpr(
            None, n.op, n.axis, n.keepdims, n.req_dtype,
            _inputs=tuple(_restrict_bcast(c, pre_box, ps, memo, stashes)
                          for c in n.inputs),
            _pre=n.pre)
    elif isinstance(n, DotExpr):
        a, b = n.children()
        if a.ndim == 2 and b.ndim == 2:
            abox = TileExtent((box.ul[0], 0), (box.lr[0], a.shape[1]),
                              a.shape)
            bbox = TileExtent((0, box.ul[1]), (b.shape[0], box.lr[1]),
                              b.shape)
        elif a.ndim == 2 and b.ndim == 1:
            abox = TileExtent((box.ul[0], 0), (box.lr[0], a.shape[1]),
                              a.shape)
            bbox = TileExtent((0,), (b.shape[0],), b.shape)
        elif a.ndim == 1 and b.ndim == 2:
            abox = TileExtent((0,), (a.shape[0],), a.shape)
            bbox = TileExtent((0, box.ul[0]), (b.shape[0], box.lr[0]),
                              b.shape)
        else:
            raise Unsupported("restrict:dot-rank")
        out = DotExpr(_restrict(a, abox, memo, stashes),
                      _restrict(b, bbox, memo, stashes), n.precision)
    else:
        raise Unsupported(f"restrict:{type(n).__name__}")
    memo[key] = out
    return out


def _restrict_bcast(c: Any, box: TileExtent,
                    target_shape: Tuple[int, ...], memo: Dict,
                    stashes: Optional[Dict[int, Tuple]] = None) -> Any:
    """Restrict a broadcast-aligned child: slice axes that match the
    target, keep broadcast (size-1 / missing) axes whole."""
    cs = tuple(c.shape)
    off = len(target_shape) - len(cs)
    if off < 0:
        raise Unsupported("restrict:broadcast-rank")
    ul, lr = [], []
    for i, d in enumerate(cs):
        td = i + off
        if d == target_shape[td]:
            ul.append(box.ul[td])
            lr.append(box.lr[td])
        elif d == 1:
            ul.append(0)
            lr.append(1)
        else:
            raise Unsupported("restrict:broadcast-shape")
    return _restrict(c, TileExtent(ul, lr, cs), memo, stashes)


def _dyn_slice(n: Any, box: TileExtent) -> Any:
    from .base import ScalarExpr

    _types()
    starts = tuple(ScalarExpr(int(u)) for u in box.ul)
    return DynSliceExpr(n, starts, box.shape)


# -- tile accounting / reporting ----------------------------------------


def _tile_counts(n: Any, r: Any, mesh: Any) -> Tuple[int, int]:
    """(total tiles, dirty tiles) of node ``n`` under its committed
    tiling — the per-node dirty/clean view st.explain shows."""
    try:
        tiles = n.out_tiling().tiles_per_dim(mesh)
    except Exception:  # noqa: BLE001 - accounting is advisory
        tiles = tuple(1 for _ in n.shape)
    total = 1
    for t in tiles:
        total *= max(1, t)
    if r is FULL:
        return total, total
    dirty = 1
    for u, l, d, t in zip(r.ul, r.lr, n.shape, tiles):
        t = max(1, t)
        ts = -(-d // t)  # ceil tile size
        lo = u // ts
        hi = -(-l // ts)
        dirty *= max(1, hi - lo)
    return total, min(total, dirty)


def _report(plan: Any, **fields: Any) -> None:
    if plan is not None and plan.report is not None:
        inc = {"cache_bytes": cache_bytes(), "entries": len(_cache)}
        inc.update(fields)
        plan.report["incremental"] = inc


def degrade_to_full(plan: Any, reason: str) -> Any:
    prof.count("incremental_fallbacks")
    _report(plan, mode="full", fallback=reason)
    from ..obs import flight as flight_mod

    flight_mod.note(0, "incremental", mode="full", reason=reason)
    return NOT_HANDLED


# -- the intercept (plan-cache hit path) ---------------------------------


def intercept(expr: Any, plan: Any, leaves: List[Any],
              order: Tuple[int, ...], donated: List[Any],
              mesh: Any) -> Any:
    """Try to serve a warm evaluate from the result cache + a dirty
    sub-plan. Returns the result, or NOT_HANDLED to let the ordinary
    full dispatch run (which then refreshes the cache via
    ``note_result``)."""
    global _total_bytes
    if getattr(_tls, "active", False):
        return NOT_HANDLED  # inner restricted/splice evaluate
    if donated:
        return degrade_to_full(plan, "donation")
    with _lock:
        entry = _cache.get(plan.key)
        if entry is not None:
            _cache.move_to_end(plan.key)
    if entry is None:
        return NOT_HANDLED  # cold: seeded by note_result after dispatch
    if entry.epoch != mesh_mod._EPOCH:
        _drop(plan.key)
        return NOT_HANDLED
    if entry.result.is_donated:
        _drop(plan.key)
        return degrade_to_full(plan, "result-donated")
    try:
        ordered = [leaves[i] for i in order]
    except (IndexError, TypeError):
        return degrade_to_full(plan, "leaf-mismatch")
    if len(ordered) != len(entry.slots):
        _drop(plan.key)
        return degrade_to_full(plan, "leaf-mismatch")
    from .base import _leaf_array

    for leaf in ordered:
        arr = _leaf_array(leaf)
        if arr is not None and arr._donate_next:
            # a .donate()-marked leaf: the caller is owed a buffer
            # release that only the real dispatch performs — serving
            # from the cache would silently skip the donation
            return degrade_to_full(plan, "donation")

    with prof.phase("incremental"):
        try:
            dirt: Dict[int, Any] = {}
            stashes: Dict[int, Tuple] = {}
            for leaf, slot in zip(ordered, entry.slots):
                d, sv = _leaf_dirt(leaf, slot)
                if d is not None:
                    dirt[leaf._id] = d
                    if sv is not None:
                        stashes[leaf._id] = sv
            if not dirt:
                # every leaf byte-identical to the cached evaluation:
                # the cached result IS the answer — zero dispatches
                prof.count("incremental_hits")
                _report(plan, mode="cache-hit", fallback=None)
                return entry.result

            details: List[Tuple[Any, Any]] = []
            root_dirt = _propagate(expr, dirt, {}, details)
            if root_dirt is None:
                prof.count("incremental_hits")
                _report(plan, mode="cache-hit", fallback=None)
                return entry.result
            if root_dirt is FULL:
                return degrade_to_full(plan, "dirty-full")
            frac = root_dirt.size / max(1, expr.size)
            if frac > _FRAC_FLAG._value:
                return degrade_to_full(plan, f"dirty-frac:{frac:.3f}")
            use_box = _quantize(root_dirt, expr.shape)
        except Exception as e:  # noqa: BLE001 - honest-fallback: dirt
            # computation/propagation errors degrade exactly like
            # dispatch errors instead of failing the whole evaluate()
            return degrade_to_full(plan, f"error:{type(e).__name__}")
        try:
            _tls.active = True
            sub_expr = None
            # exact-box pass: when every dirty leaf's delta is a single
            # stashed write, restrict to the UN-quantized root box so
            # each leaf's needed box lines up with its stashed extent
            # and the sub-plan takes the materialized delta as a leaf —
            # no traced-start slice of a sharded parent, no gather.
            # Plan sharing survives because streaming deltas repeat
            # their batch shape (positional leaf sigs).
            if stashes and all(
                    d is not FULL and lid in stashes
                    and tuple(stashes[lid][0].ul) == tuple(d.ul)
                    and tuple(stashes[lid][0].lr) == tuple(d.lr)
                    for lid, d in dirt.items()):
                try:
                    sub_expr = _restrict(expr, root_dirt, {}, stashes)
                    use_box = root_dirt
                except Unsupported:
                    sub_expr = None
            if sub_expr is None:
                use_box = _quantize(root_dirt, expr.shape)
                sub_expr = _restrict(expr, use_box, {})
            from .base import ScalarExpr, ValExpr, evaluate

            sub = evaluate(sub_expr)
            _types()
            starts = tuple(ScalarExpr(int(u)) for u in use_box.ul)
            combined = evaluate(
                DynUpdateExpr(ValExpr(entry.result), ValExpr(sub),
                              starts))
        except Unsupported as e:
            return degrade_to_full(plan, str(e))
        except Exception as e:  # noqa: BLE001 - the honest-fallback
            # contract: ANY failure mid-incremental-dispatch (chaos
            # faults included) degrades to the ordinary full path
            return degrade_to_full(plan, f"error:{type(e).__name__}")
        finally:
            _tls.active = False

        slots = _snapshot_slots(ordered)
        if slots is not None:
            nbytes = (int(combined.size) * combined.dtype.itemsize
                      + _slots_nbytes(slots))
            budget = _CACHE_FLAG._value
            fresh = _Entry(combined, slots, entry.epoch, nbytes)
            evicted = 0
            with _lock:
                # CAS on the entry object: publish only if the slot
                # still holds the entry this splice was derived from. A
                # racing intercept that loses the race keeps (and
                # returns) its own correct result but doesn't publish,
                # so the cache never mixes two splices' deltas and
                # _total_bytes swaps exactly one entry's accounting.
                if nbytes <= budget and _cache.get(plan.key) is entry:
                    _cache[plan.key] = fresh
                    _cache.move_to_end(plan.key)
                    _total_bytes += fresh.nbytes - entry.nbytes
                    while _total_bytes > budget and len(_cache) > 1:
                        _, e = _cache.popitem(last=False)
                        _total_bytes -= e.nbytes
                        evicted += 1
            if evicted:
                prof.count("incremental_evictions", evicted)
        root_total, root_dirty = _tile_counts(expr, use_box, mesh)
        prof.count("incremental_hits")
        prof.count("incremental_recomputed_tiles", root_dirty)
        _report(plan, mode="incremental", fallback=None,
                dirty_frac=round(frac, 6),
                dirty_box=[list(use_box.ul), list(use_box.lr)],
                nodes=[{"node": f"{type(n).__name__}#{n._id}",
                        "tiles": _tile_counts(n, r, mesh)[0],
                        "dirty_tiles": _tile_counts(n, r, mesh)[1]}
                       for n, r in details[-8:]])
        from ..obs import flight as flight_mod

        flight_mod.note(0, "incremental", mode="incremental",
                        dirty_frac=round(frac, 6),
                        recomputed_tiles=root_dirty)
        _gauge()
        return combined
