"""Elementwise map expressions — the workhorse (SURVEY.md §2.3: ``[U]
spartan/expr/map.py``; BASELINE.json:7 config 1 is "element-wise map +
global sum").

The reference picked the largest input and ran a fused NumPy kernel per
tile, fetching matching extents of other inputs over RPC. Here the whole
map (with broadcasting) is traced into the enclosing jit; GSPMD aligns the
operand shardings (resharding the small ones — the broadcast wrapper of
SURVEY.md §2.6) and XLA fuses the elementwise chain into the surrounding
computation.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..array import tiling as tiling_mod
from ..array.tiling import Tiling
from . import local as local_mod
from .base import Expr, ScalarExpr, as_expr, eval_shape_of
from .local import LocalCall, LocalExpr, LocalInput, LocalUfunc


class MapExpr(Expr):
    """Fused elementwise expression over broadcast-aligned inputs."""

    def __init__(self, inputs: Sequence[Expr], op: LocalExpr):
        self.inputs: Tuple[Expr, ...] = tuple(inputs)
        self.op = op
        out = eval_shape_of(lambda *xs: op.emit(xs), *self.inputs,
                            cache_key=("map", op.key()))
        super().__init__(out.shape, out.dtype)

    def children(self) -> Tuple[Expr, ...]:
        return self.inputs

    def replace_children(self, new_children: Tuple[Expr, ...]) -> "MapExpr":
        return MapExpr(new_children, self.op)

    def _lower(self, env: Dict[int, Any]) -> Any:
        vals = [c.lower(env) for c in self.inputs]
        return self.op.emit(vals)

    def _sig(self, ctx) -> Tuple:
        return (("map", self.op.key())
                + tuple(ctx.of(c) for c in self.inputs))

    def _default_tiling(self) -> Tiling:
        # the largest same-shaped input donates its tiling (the reference
        # evaluated on the owner of the largest input's tiles)
        best: Optional[Tiling] = None
        for c in self.inputs:
            if c.shape == self.shape:
                t = c.out_tiling()
                if t.sharded_axes():
                    return t
                best = best or t
        if best is not None:
            return best
        return tiling_mod.default_tiling(self.shape)


def build_binop(name: str, a: Any, b: Any, reverse: bool = False) -> MapExpr:
    a = as_expr(a)
    b = as_expr(b)
    if reverse:
        a, b = b, a
    return MapExpr((a, b), LocalUfunc(name, (LocalInput(0), LocalInput(1))))


def build_unop(name: str, a: Any) -> MapExpr:
    return MapExpr((as_expr(a),), LocalUfunc(name, (LocalInput(0),)))


def map(fn: Callable, *args: Any, fn_kw: Optional[dict] = None):
    """User map: ``fn`` is a jax-traceable function applied elementwise /
    blockwise to the broadcast-aligned inputs (the reference shipped it as
    a pickled closure per tile; here it is traced into the jit).

    Masked operands (MaskedDistArray) propagate: ``fn`` runs on the
    data and the result's mask is the OR of the operands' masks
    (numpy.ma's ufunc rule), broadcast to the output shape."""
    from ..array import masked as masked_mod

    if any(isinstance(a, masked_mod.MaskedDistArray) for a in args):
        import jax.numpy as jnp

        out = map(fn, *(masked_mod._data_of(a) for a in args),
                  fn_kw=fn_kw)
        masks = [a.mask for a in args
                 if isinstance(a, masked_mod.MaskedDistArray)]
        mask = masks[0]
        for m in masks[1:]:
            mask = mask | m
        if mask.shape != out.shape:
            mask = map(lambda o, m: jnp.broadcast_to(
                m.astype(bool), o.shape), out, mask)
        return masked_mod.MaskedDistArray(out, mask)
    inputs = tuple(as_expr(a) for a in args)
    kw = tuple(sorted((fn_kw or {}).items()))
    op = LocalCall(fn, tuple(LocalInput(i) for i in range(len(inputs))), kw)
    return MapExpr(inputs, op)


class MapWithLocationExpr(Expr):
    """Map where the kernel also receives the block's global offset
    (SURVEY.md §2.3 ``map_with_location``: index-dependent ops).

    ``fn(block, ul)`` runs per shard under shard_map; ``ul`` is the global
    upper-left coordinate of the shard (a tuple of traced scalars computed
    from mesh axis indices) — the TPU-native replacement for handing the
    kernel its TileExtent.
    """

    def __init__(self, input: Expr, fn: Callable,
                 fn_kw: Tuple[Tuple[str, Any], ...] = ()):
        self.input = input
        self.fn = fn
        self.fn_kw = fn_kw
        # fn must preserve the block shape; dtype may change
        out = eval_shape_of(
            lambda x: fn(x, tuple(0 for _ in input.shape),
                         **dict(fn_kw)), input)
        if out.shape != input.shape:
            raise ValueError(
                "map_with_location kernels must preserve shape; got "
                f"{out.shape} from {input.shape}")
        super().__init__(out.shape, out.dtype)

    def children(self) -> Tuple[Expr, ...]:
        return (self.input,)

    def replace_children(self, new_children: Tuple[Expr, ...]
                         ) -> "MapWithLocationExpr":
        return MapWithLocationExpr(new_children[0], self.fn, self.fn_kw)

    def _lower(self, env: Dict[int, Any]) -> Any:
        import jax
        from ..utils.compat import shard_map

        from ..parallel import mesh as mesh_mod

        x = self.input.lower(env)
        mesh = mesh_mod.get_mesh()
        t = self.input.out_tiling()
        if not t.divisible(self.shape, mesh):
            # replicated / uneven fallback: single logical block at (0,..)
            return self.fn(x, tuple(0 for _ in self.shape),
                           **dict(self.fn_kw))
        tiles = t.tiles_per_dim(mesh)
        shard_shape = tuple(d // n for d, n in zip(self.shape, tiles))
        axes = t.axes

        def kernel(block):
            ul = []
            for d in range(len(axes)):
                a = axes[d]
                if a is None:
                    ul.append(0)
                else:
                    idx = jax.lax.axis_index(a)
                    ul.append(idx * shard_shape[d])
            return self.fn(block, tuple(ul), **dict(self.fn_kw))

        mapped = shard_map(kernel, mesh=mesh, in_specs=(t.spec(),),
                           out_specs=t.spec())
        return mapped(x)

    def _sig(self, ctx) -> Tuple:
        from .base import fn_key

        return ("maploc", fn_key(self.fn), self.fn_kw,
                self.input.out_tiling().axes, ctx.of(self.input))

    def _default_tiling(self) -> Tiling:
        return self.input.out_tiling()


def map_with_location(array: Any, fn: Callable,
                      fn_kw: Optional[dict] = None) -> MapWithLocationExpr:
    return MapWithLocationExpr(as_expr(array), fn,
                               tuple(sorted((fn_kw or {}).items())))
