"""Optimizer passes over the expr DAG.

Parity with the reference's ``[U] spartan/expr/optimize.py`` (SURVEY.md
§2.3: pass framework with per-pass FLAGS, map-fusion, reduce-map fusion,
cached-expr collapsing, smart tiling). In the TPU build XLA performs the
actual kernel fusion, so map-fusion here serves the reference's *observable*
role — collapsing chained MapExprs into one LocalExpr tree (shrinking the
DAG and trace) with the same FLAGS ablation surface. The smart-tiling pass
(ICI-cost sharding chooser) lives in ``tiling_pass.py`` and is registered
here.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..utils.config import FLAGS
from .base import Expr, ValExpr
from .local import LocalExpr, LocalInput
from .map import MapExpr


def rewrite(root: Expr, visit: Callable[[Expr, Tuple[Expr, ...]], Expr]
            ) -> Expr:
    """Bottom-up DAG rewrite preserving sharing (memoized by node id)."""
    memo: Dict[int, Expr] = {}

    def go(n: Expr) -> Expr:
        if n._id in memo:
            return memo[n._id]
        new_kids = tuple(go(k) for k in n.children())
        out = visit(n, new_kids)
        memo[n._id] = out
        return out

    return go(root)


def default_visit(n: Expr, new_kids: Tuple[Expr, ...]) -> Expr:
    # identity comparison: Expr overloads __eq__ to build lazy MapExprs
    old_kids = n.children()
    if len(new_kids) == len(old_kids) and all(
            a is b for a, b in zip(new_kids, old_kids)):
        return n
    return n.replace_children(new_kids)


class Pass:
    name = "base"
    flag = ""
    # Invariant declaration for the pass checker (analysis/passes.py,
    # FLAGS.verify_passes): a pass that prunes sub-DAGs (and with them
    # their leaves) must opt out of strict leaf preservation. New
    # passes inherit the strict default; see docs/ARCHITECTURE.md
    # ("Adding an invariant to a new Pass").
    preserves_leaves = True

    def enabled(self) -> bool:
        return not self.flag or getattr(FLAGS, self.flag)

    def run(self, root: Expr) -> Expr:
        raise NotImplementedError


class CollapseCachedPass(Pass):
    """Replace already-evaluated sub-DAGs with Val leaves (the reference's
    cached-expr collapsing): iterative drivers re-use prior results
    without re-tracing their history."""

    name = "collapse_cached"
    flag = "opt_collapse_cached"
    # collapsing a cached node prunes its whole sub-DAG — the leaves
    # below it legitimately disappear (their data is baked into the
    # substituted Val leaf)
    preserves_leaves = False

    def run(self, root: Expr) -> Expr:
        def visit(n: Expr, kids: Tuple[Expr, ...]) -> Expr:
            from ..array.distarray import DistArray

            if (isinstance(n._result, DistArray)
                    and not isinstance(n, ValExpr)):
                return ValExpr(n._result)
            return default_visit(n, kids)

        return rewrite(root, visit)


class MapFusionPass(Pass):
    """Fold MapExpr children into their MapExpr parents: ``(a+b)*c``
    becomes one LocalExpr tree evaluated by one kernel (SURVEY.md §3.2)."""

    name = "map_fusion"
    flag = "opt_map_fusion"

    def run(self, root: Expr) -> Expr:
        def visit(n: Expr, kids: Tuple[Expr, ...]) -> Expr:
            n = default_visit(n, kids)
            if not isinstance(n, MapExpr):
                return n
            if not any(isinstance(c, MapExpr) and c._result is None
                       for c in n.inputs):
                return n
            new_inputs: List[Expr] = []
            pos: Dict[int, int] = {}

            def input_slot(e: Expr) -> int:
                if e._id not in pos:
                    pos[e._id] = len(new_inputs)
                    new_inputs.append(e)
                return pos[e._id]

            mapping: Dict[int, LocalExpr] = {}
            for i, c in enumerate(n.inputs):
                if isinstance(c, MapExpr) and c._result is None:
                    sub: Dict[int, LocalExpr] = {
                        j: LocalInput(input_slot(sc))
                        for j, sc in enumerate(c.inputs)}
                    mapping[i] = c.op.remap(sub)
                else:
                    mapping[i] = LocalInput(input_slot(c))
            return MapExpr(new_inputs, n.op.remap(mapping))

        return rewrite(root, visit)


class ReduceFusionPass(Pass):
    """Fold MapExpr producers into the reduction's pre-reduce tree:
    ``(a*b).sum()`` becomes one fused ReduceExpr node whose kernel
    applies the elementwise tree before reducing (SURVEY.md §2.3 pass
    (b)), shrinking the DAG exactly like MapFusion does for map chains.
    XLA would fuse the producer anyway at the HLO level; the observable
    effect here — and what the ablation toggles — is the DAG/trace
    shape."""

    name = "reduce_fusion"
    flag = "opt_reduce_fusion"

    def run(self, root: Expr) -> Expr:
        from .reduce import ReduceExpr

        def visit(n: Expr, kids: Tuple[Expr, ...]) -> Expr:
            n = default_visit(n, kids)
            if not isinstance(n, ReduceExpr):
                return n
            if not any(isinstance(c, MapExpr) and c._result is None
                       for c in n.inputs):
                return n
            new_inputs: List[Expr] = []
            pos: Dict[int, int] = {}

            def input_slot(e: Expr) -> int:
                if e._id not in pos:
                    pos[e._id] = len(new_inputs)
                    new_inputs.append(e)
                return pos[e._id]

            mapping: Dict[int, LocalExpr] = {}
            for i, c in enumerate(n.inputs):
                if isinstance(c, MapExpr) and c._result is None:
                    sub: Dict[int, LocalExpr] = {
                        j: LocalInput(input_slot(sc))
                        for j, sc in enumerate(c.inputs)}
                    mapping[i] = c.op.remap(sub)
                else:
                    mapping[i] = LocalInput(input_slot(c))
            return n.with_fused(new_inputs, n.pre.remap(mapping))

        return rewrite(root, visit)


_PASSES: List[Pass] = []


def register_pass(p: Pass) -> None:
    _PASSES.append(p)


register_pass(CollapseCachedPass())
register_pass(MapFusionPass())
register_pass(ReduceFusionPass())


_tiling_pass_loaded = False


def _ensure_tiling_pass() -> None:
    global _tiling_pass_loaded
    if _tiling_pass_loaded:  # skip sys.modules machinery on the hot
        return               # per-signature path
    from . import tiling_pass  # noqa: F401  (self-registers on import)
    _tiling_pass_loaded = True


def optimize(root: Expr, report: Optional[List[Dict]] = None) -> Expr:
    """Run the enabled pass stack. Only plan-cache MISSES reach this
    (expr/base.py evaluate): steady-state iterative drivers skip it
    entirely. Per-pass wall time accumulates under ``pass:<name>`` in
    utils/profiling (span + histogram) for the dispatch-overhead
    benchmark and the trace ring.

    ``report``: optional list; one dict per enabled pass is appended
    (``name`` / ``nodes_before`` / ``nodes_after`` / ``seconds``) —
    the per-pass node-delta record ``st.explain`` shows.

    With ``FLAGS.verify_passes`` (``SPARTAN_VERIFY_PASSES=1``; the
    test suite's default) every pass is bracketed by the invariant
    checker (analysis/passes.py): shape/dtype/leaf preservation and
    full DAG well-formedness, failures naming the offending pass."""
    from ..utils import profiling as prof

    _ensure_tiling_pass()
    verify = FLAGS.verify_passes
    snap = None
    if verify:
        from ..analysis import passes as checkmod

        with prof.phase("verify"):
            snap = checkmod.snapshot(root)
    for p in _PASSES:
        if p.enabled():
            before = len(dag_nodes(root)) if report is not None else 0
            with prof.phase("pass:" + p.name) as psp:
                new_root = p.run(root)
            if report is not None:
                report.append({"name": p.name, "nodes_before": before,
                               "nodes_after": len(dag_nodes(new_root)),
                               "seconds": psp.seconds})
            if verify:
                with prof.phase("verify"):
                    snap = checkmod.check_pass(p, snap, new_root)
            root = new_root
    return root


def dag_nodes(root: Expr) -> List[Expr]:
    """All nodes, post-order, deduped (for optimizer tests)."""
    out: List[Expr] = []
    seen = set()

    def go(n: Expr) -> None:
        if n._id in seen:
            return
        seen.add(n._id)
        for k in n.children():
            go(k)
        out.append(n)

    go(root)
    return out
