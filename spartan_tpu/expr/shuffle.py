"""shuffle: arbitrary tile redistribution with a user kernel.

Parity with ``[U] spartan/expr/shuffle.py`` (SURVEY.md §2.3: per-source-
tile kernel emits ``(target_extent, data)`` updates into a (possibly new)
target array with a combiner — Spartan's all-to-all). Lowering strategy
per SURVEY.md §7 hard part 1 (dual paths):

* Structured redistributions (transpose / reshape / retile / slice-write)
  never come here — they are traced exprs whose sharding change makes
  GSPMD emit the all-to-all (see reshape.py, DistArray.retile).
* The *general* shuffle — an arbitrary Python kernel emitting variable
  extents — is not traceable.  On BOTH modes the kernel runs once per
  source tile with that tile's block (the reference's owner-computes
  granularity).  The default ``mode='sharded'`` fetches each source
  shard's block to host *individually*, routes the kernel's emissions
  by extent intersection into per-target-shard blocks as they are
  produced, and constructs the result shard-by-shard
  (``jax.make_array_from_single_device_arrays``).  The full *source* is
  never materialized on the host and emissions are folded into target
  blocks immediately — peak host residency is one source block plus the
  target's shards (transiently, while they are assembled).
* ``mode='host'`` is the whole-array fallback: it gloms the source once
  and scatters into a single host target buffer — simpler, and the
  right choice when the target tiling is replicated anyway.  Nothing in
  the package uses it.

Combiner semantics match the reference's reducer-merge updates
(SURVEY.md §7 hard part 3): updates are applied in deterministic order —
source-tile order, then emission order — on both paths.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional, Sequence, Tuple

import jax
import numpy as np

from ..array import distarray as da
from ..array import extent as extent_mod
from ..array import tiling as tiling_mod
from ..array.extent import TileExtent
from ..array.tiling import Tiling
from .base import Expr, ValExpr, as_expr, evaluate

_COMBINERS = {
    None: lambda tgt, sl, v: tgt.__setitem__(sl, v),
    "set": lambda tgt, sl, v: tgt.__setitem__(sl, v),
    "add": lambda tgt, sl, v: tgt.__setitem__(sl, tgt[sl] + v),
    "mul": lambda tgt, sl, v: tgt.__setitem__(sl, tgt[sl] * v),
    "max": lambda tgt, sl, v: tgt.__setitem__(sl, np.maximum(tgt[sl], v)),
    "min": lambda tgt, sl, v: tgt.__setitem__(sl, np.minimum(tgt[sl], v)),
}


def _combiner_name(combiner: Any) -> str:
    if isinstance(combiner, np.ufunc) or callable(combiner):
        name = {np.add: "add", np.multiply: "mul", np.maximum: "max",
                np.minimum: "min"}.get(combiner)
        if name is None:
            raise ValueError(f"unsupported combiner {combiner!r}")
        combiner = name
    if combiner not in _COMBINERS:
        raise ValueError(f"unsupported combiner {combiner!r}")
    return combiner


def shuffle(source: Any,
            kernel: Callable[[TileExtent, np.ndarray],
                             Iterable[Tuple[TileExtent, np.ndarray]]],
            target_shape: Optional[Sequence[int]] = None,
            target: Optional[Any] = None,
            dtype: Any = None,
            combiner: Any = "add",
            tile_hint: Optional[Sequence[int]] = None,
            tiling: Optional[Tiling] = None,
            kw: Optional[dict] = None,
            mode: str = "sharded") -> Expr:
    """Run ``kernel(extent, block, **kw)`` over every source tile; scatter
    its emitted ``(target_extent, data)`` pairs into the target with
    ``combiner``. Returns a ValExpr over the new DistArray (evaluated
    eagerly — the kernel is arbitrary Python).

    ``mode='sharded'`` (default) never materializes the full source on
    the host and builds the target shard-by-shard; ``mode='host'``
    gloms the source and scatters into one host buffer.  The kernel is
    invoked per source tile on both paths.
    """
    source = as_expr(source)
    src = evaluate(source)
    name = _combiner_name(combiner)
    kw = kw or {}

    if target is not None:
        tgt = evaluate(as_expr(target))
        out_shape = tgt.shape
        out_dtype = tgt.dtype
        out_tiling = tgt.tiling
    else:
        if target_shape is None:
            raise ValueError("shuffle needs target_shape or target")
        tgt = None
        out_shape = tuple(int(s) for s in target_shape)
        out_dtype = np.dtype(dtype) if dtype is not None else src.dtype
        if tiling is not None:
            out_tiling = tiling
        elif tile_hint is not None:
            out_tiling = tiling_mod.from_tile_hint(out_shape, tile_hint,
                                                   src.mesh)
        else:
            out_tiling = tiling_mod.default_tiling(out_shape, src.mesh)
        out_tiling = tiling_mod.sanitize(out_tiling, out_shape, src.mesh)

    if mode == "sharded":
        result = _shuffle_sharded(src, kernel, kw, out_shape, out_dtype,
                                  out_tiling, name, tgt)
    elif mode == "host":
        result = _shuffle_host(src, kernel, kw, out_shape, out_dtype,
                               out_tiling, name, tgt)
    else:
        raise ValueError(f"unknown shuffle mode {mode!r}")
    return ValExpr(result)


def _normalize(t_ext, data, out_shape, out_dtype):
    if not isinstance(t_ext, TileExtent):
        t_ext = TileExtent(t_ext[0], t_ext[1], out_shape)
    data = np.asarray(data, dtype=out_dtype)
    if data.shape != t_ext.shape:
        data = np.broadcast_to(data, t_ext.shape)
    return t_ext, data


def _emissions(blocks_iter, kernel, kw, out_shape, out_dtype):
    """Yield normalized (target_extent, data) pairs in deterministic
    order: source-tile order, then emission order."""
    for s_ext, block in blocks_iter:
        for t_ext, data in kernel(s_ext, block, **kw):
            yield _normalize(t_ext, data, out_shape, out_dtype)


def _fetched_blocks(src):
    """One source tile at a time — only that region crosses to host."""
    for s_ext in src.extents():
        yield s_ext, src.fetch(s_ext)


def _shuffle_sharded(src, kernel, kw, out_shape, out_dtype, out_tiling,
                     combiner_name, tgt) -> da.DistArray:
    """Distributed scatter-combine: fold emissions into per-target-shard
    blocks as they stream out of the kernel, then place each shard."""
    apply_update = _COMBINERS[combiner_name]
    mesh = src.mesh
    sharding = out_tiling.sharding(mesh)
    # device -> region it stores (jax's ground truth, handles uneven
    # splits and replicated axes — regions may repeat across devices)
    idx_map = sharding.addressable_devices_indices_map(tuple(out_shape))
    region_of = {dev: extent_mod.from_slice(idx, out_shape)
                 for dev, idx in idx_map.items()}
    blocks = {
        r_ext: (tgt.fetch(r_ext).astype(out_dtype, copy=True) if tgt
                else np.zeros(r_ext.shape, out_dtype))
        for r_ext in set(region_of.values())}

    # Emissions are applied immediately (nothing pins kernel outputs);
    # deterministic because the emission stream is ordered and each
    # target cell belongs to exactly one region block.
    for t_ext, data in _emissions(_fetched_blocks(src), kernel, kw,
                                  out_shape, out_dtype):
        for r_ext, base in blocks.items():
            isect = t_ext.intersection(r_ext)
            if isect is None:
                continue
            piece = data[t_ext.offset_slice(isect)]
            apply_update(base, isect.offset_from(r_ext).to_slice(), piece)

    arrs = [jax.device_put(blocks[region_of[dev]], dev)
            for dev in idx_map]
    jarr = jax.make_array_from_single_device_arrays(
        tuple(out_shape), sharding, arrs)
    return da.DistArray(jarr, out_tiling, mesh)


def _shuffle_host(src, kernel, kw, out_shape, out_dtype, out_tiling,
                  combiner_name, tgt) -> da.DistArray:
    """Whole-array fallback: glom the source once, scatter into a single
    host target buffer."""
    apply_update = _COMBINERS[combiner_name]
    tgt_np = (tgt.glom().astype(out_dtype, copy=True) if tgt
              else np.zeros(out_shape, out_dtype))
    src_np = src.glom()
    blocks_iter = ((s_ext, src_np[s_ext.to_slice()])
                   for s_ext in src.extents())
    for t_ext, data in _emissions(blocks_iter, kernel, kw, out_shape,
                                  out_dtype):
        apply_update(tgt_np, t_ext.to_slice(), data)
    return da.from_numpy(tgt_np, tiling=out_tiling, mesh=src.mesh)
