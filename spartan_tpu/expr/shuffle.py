"""shuffle: arbitrary tile redistribution with a user kernel.

Parity with ``[U] spartan/expr/shuffle.py`` (SURVEY.md §2.3: per-source-
tile kernel emits ``(target_extent, data)`` updates into a (possibly new)
target array with a combiner — Spartan's all-to-all). Lowering strategy
per SURVEY.md §7 hard part 1 (dual paths):

* Structured redistributions (transpose / reshape / retile / slice-write)
  never come here — they are traced exprs whose sharding change makes
  GSPMD emit the all-to-all (see reshape.py, DistArray.retile).
* The *general* shuffle — an arbitrary Python kernel emitting variable
  extents — is not traceable. It runs as a host-side scatter-combine over
  the source tiles (exactly the reference's semantics, which also ran
  Python per tile), then re-enters the device world as a new DistArray.
  The combiner is applied in deterministic source-tile order.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Optional, Sequence, Tuple

import numpy as np

from ..array import distarray as da
from ..array import tiling as tiling_mod
from ..array.extent import TileExtent
from ..array.tiling import Tiling
from .base import Expr, ValExpr, as_expr, evaluate

_COMBINERS = {
    None: lambda tgt, sl, v: tgt.__setitem__(sl, v),
    "set": lambda tgt, sl, v: tgt.__setitem__(sl, v),
    "add": lambda tgt, sl, v: tgt.__setitem__(sl, tgt[sl] + v),
    "mul": lambda tgt, sl, v: tgt.__setitem__(sl, tgt[sl] * v),
    "max": lambda tgt, sl, v: tgt.__setitem__(sl, np.maximum(tgt[sl], v)),
    "min": lambda tgt, sl, v: tgt.__setitem__(sl, np.minimum(tgt[sl], v)),
}


def shuffle(source: Any,
            kernel: Callable[[TileExtent, np.ndarray],
                             Iterable[Tuple[TileExtent, np.ndarray]]],
            target_shape: Optional[Sequence[int]] = None,
            target: Optional[Any] = None,
            dtype: Any = None,
            combiner: Any = "add",
            tile_hint: Optional[Sequence[int]] = None,
            kw: Optional[dict] = None) -> Expr:
    """Run ``kernel(extent, block, **kw)`` over every source tile; scatter
    its emitted ``(target_extent, data)`` pairs into the target with
    ``combiner``. Returns a ValExpr over the new DistArray (evaluated
    eagerly — the kernel is arbitrary Python)."""
    source = as_expr(source)
    src = evaluate(source)
    src_np = src.glom()

    if isinstance(combiner, np.ufunc) or callable(combiner):
        name = {np.add: "add", np.multiply: "mul", np.maximum: "max",
                np.minimum: "min"}.get(combiner)
        if name is None and combiner is not None:
            raise ValueError(f"unsupported combiner {combiner!r}")
        combiner = name
    if combiner not in _COMBINERS:
        raise ValueError(f"unsupported combiner {combiner!r}")
    apply_update = _COMBINERS[combiner]

    if target is not None:
        target = as_expr(target)
        tgt_np = evaluate(target).glom().copy()
        out_shape = tgt_np.shape
        out_dtype = tgt_np.dtype
        out_tiling = evaluate(target).tiling
    else:
        if target_shape is None:
            raise ValueError("shuffle needs target_shape or target")
        out_shape = tuple(int(s) for s in target_shape)
        out_dtype = np.dtype(dtype) if dtype is not None else src.dtype
        tgt_np = np.zeros(out_shape, out_dtype)
        out_tiling = None

    kw = kw or {}
    for ext in src.extents():
        block = src_np[ext.to_slice()]
        for t_ext, data in kernel(ext, block, **kw):
            if not isinstance(t_ext, TileExtent):
                t_ext = TileExtent(t_ext[0], t_ext[1], out_shape)
            data = np.asarray(data, dtype=out_dtype)
            if data.shape != t_ext.shape:
                data = np.broadcast_to(data, t_ext.shape)
            apply_update(tgt_np, t_ext.to_slice(), data)

    result = da.from_numpy(tgt_np, tiling=out_tiling, tile_hint=tile_hint)
    return ValExpr(result)
