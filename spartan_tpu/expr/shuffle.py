"""shuffle: arbitrary tile redistribution with a user kernel.

Parity with ``[U] spartan/expr/shuffle.py`` (SURVEY.md §2.3: per-source-
tile kernel emits ``(target_extent, data)`` updates into a (possibly new)
target array with a combiner — Spartan's all-to-all). Lowering strategy
per SURVEY.md §7 hard part 1 (dual paths):

* Structured redistributions (transpose / reshape / retile / slice-write)
  never come here — they are traced exprs whose sharding change makes
  GSPMD emit the all-to-all (see reshape.py, DistArray.retile).
* The *general* shuffle — an arbitrary Python kernel emitting variable
  extents — is not traceable.  On BOTH modes the kernel runs once per
  source tile with that tile's block (the reference's owner-computes
  granularity).  The default ``mode='sharded'`` mirrors the reference's
  *concurrent worker fan-out* (SURVEY.md §3.2: RunKernelReq to each
  owning worker): fetch + kernel run in a THREAD POOL with a bounded
  submission window, one task per source tile; each task routes its
  emissions through a per-dimension interval index (bisect over the
  target region grid — O(log g) per emission instead of a linear scan
  over all shards) and cuts out the per-region pieces.  The main
  thread consumes task results in source-tile order and folds each
  piece immediately into its (lazily allocated) region block, so peak
  host residency is the TOUCHED region blocks plus a window's worth of
  in-flight pieces — bounded by O(target), and far below it for
  shuffles that write only part of the target (untouched shards are
  materialized one at a time during placement, after the touched
  blocks have been placed and released).
* ``mode='host'`` is the whole-array fallback: it gloms the source once
  and scatters into a single host target buffer — simpler, and the
  right choice when the target tiling is replicated anyway.  Nothing in
  the package uses it.

Combiner semantics match the reference's reducer-merge updates
(SURVEY.md §7 hard part 3): updates are applied in deterministic order —
source-tile order, then emission order — on both paths.  Concurrency
does not break this: only the fetch + kernel + routing run in pool
threads; all combiner applications happen on the main thread, which
consumes task results strictly in source-tile order.
"""

from __future__ import annotations

import bisect
import os
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Iterable, Optional, Sequence, Tuple

import jax
import numpy as np

from ..array import distarray as da
from ..array import extent as extent_mod
from ..array import tiling as tiling_mod
from ..array.extent import TileExtent
from ..array.tiling import Tiling
from ..utils.log import log_debug
from .base import Expr, ValExpr, as_expr, evaluate

_COMBINERS = {
    None: lambda tgt, sl, v: tgt.__setitem__(sl, v),
    "set": lambda tgt, sl, v: tgt.__setitem__(sl, v),
    "add": lambda tgt, sl, v: tgt.__setitem__(sl, tgt[sl] + v),
    "mul": lambda tgt, sl, v: tgt.__setitem__(sl, tgt[sl] * v),
    "max": lambda tgt, sl, v: tgt.__setitem__(sl, np.maximum(tgt[sl], v)),
    "min": lambda tgt, sl, v: tgt.__setitem__(sl, np.minimum(tgt[sl], v)),
}


def _combiner_name(combiner: Any) -> str:
    if isinstance(combiner, np.ufunc) or callable(combiner):
        name = {np.add: "add", np.multiply: "mul", np.maximum: "max",
                np.minimum: "min"}.get(combiner)
        if name is None:
            raise ValueError(f"unsupported combiner {combiner!r}")
        combiner = name
    if combiner not in _COMBINERS:
        raise ValueError(f"unsupported combiner {combiner!r}")
    return combiner


def shuffle(source: Any,
            kernel: Callable[[TileExtent, np.ndarray],
                             Iterable[Tuple[TileExtent, np.ndarray]]],
            target_shape: Optional[Sequence[int]] = None,
            target: Optional[Any] = None,
            dtype: Any = None,
            combiner: Any = "add",
            tile_hint: Optional[Sequence[int]] = None,
            tiling: Optional[Tiling] = None,
            kw: Optional[dict] = None,
            mode: str = "sharded",
            workers: Optional[int] = None) -> Expr:
    """Run ``kernel(extent, block, **kw)`` over every source tile; scatter
    its emitted ``(target_extent, data)`` pairs into the target with
    ``combiner``. Returns a ValExpr over the new DistArray (evaluated
    eagerly — the kernel is arbitrary Python).

    ``mode='sharded'`` (default) never materializes the full source on
    the host and builds the target shard-by-shard; ``mode='host'``
    gloms the source and scatters into one host buffer.  The kernel is
    invoked per source tile on both paths.

    On the sharded path kernels run CONCURRENTLY in a thread pool (the
    reference's worker fan-out) — a kernel must be thread-safe with
    respect to any shared state it touches (combiner application
    itself stays serialized and deterministic).  Pass ``workers=1``
    for the serial-invocation contract.  The pool defaults to
    ``min(32, 4 x cpu_count, n_source_tiles)``.  Note the kernels
    execute on the driver host under the CPython GIL: pure-Python
    kernel bodies serialize regardless of pool size and only NumPy /
    IO sections (which release the GIL) actually overlap — the pool
    buys fetch/compute overlap and NumPy parallelism, not Python
    parallelism.
    """
    source = as_expr(source)
    src = evaluate(source)
    name = _combiner_name(combiner)
    kw = kw or {}

    if target is not None:
        tgt = evaluate(as_expr(target))
        out_shape = tgt.shape
        out_dtype = tgt.dtype
        out_tiling = tgt.tiling
    else:
        if target_shape is None:
            raise ValueError("shuffle needs target_shape or target")
        tgt = None
        out_shape = tuple(int(s) for s in target_shape)
        out_dtype = np.dtype(dtype) if dtype is not None else src.dtype
        if tiling is not None:
            out_tiling = tiling
        elif tile_hint is not None:
            out_tiling = tiling_mod.from_tile_hint(out_shape, tile_hint,
                                                   src.mesh)
        else:
            out_tiling = tiling_mod.default_tiling(out_shape, src.mesh)
        out_tiling = tiling_mod.sanitize(out_tiling, out_shape, src.mesh)

    if mode == "sharded":
        result = _shuffle_sharded(src, kernel, kw, out_shape, out_dtype,
                                  out_tiling, name, tgt, workers=workers)
    elif mode == "host":
        result = _shuffle_host(src, kernel, kw, out_shape, out_dtype,
                               out_tiling, name, tgt)
    else:
        raise ValueError(f"unknown shuffle mode {mode!r}")
    return ValExpr(result)


def _normalize(t_ext, data, out_shape, out_dtype):
    if not isinstance(t_ext, TileExtent):
        t_ext = TileExtent(t_ext[0], t_ext[1], out_shape)
    data = np.asarray(data, dtype=out_dtype)
    if data.shape != t_ext.shape:
        data = np.broadcast_to(data, t_ext.shape)
    return t_ext, data


def _emissions(blocks_iter, kernel, kw, out_shape, out_dtype):
    """Yield normalized (target_extent, data) pairs in deterministic
    order: source-tile order, then emission order."""
    for s_ext, block in blocks_iter:
        for t_ext, data in kernel(s_ext, block, **kw):
            yield _normalize(t_ext, data, out_shape, out_dtype)


class _RegionIndex:
    """Interval index over the target region grid.

    The distinct regions of a NamedSharding form a Cartesian grid of
    per-dimension intervals; routing an emission is a bisect per
    dimension (O(log g)) plus the product of hit intervals — the
    replacement for intersecting every emission against every shard
    (round-3 verdict Weak #3).  Falls back to a linear scan if the
    regions ever stop forming a perfect grid."""

    def __init__(self, regions):
        self.regions = list(regions)
        ndim = len(self.regions[0].ul) if self.regions else 0
        per_dim = [sorted({(r.ul[d], r.lr[d]) for r in self.regions})
                   for d in range(ndim)]
        grid = 1
        for iv in per_dim:
            grid *= len(iv)
        if grid == len(self.regions):
            self._starts = [[iv[0] for iv in dim_ivs]
                            for dim_ivs in per_dim]
            self._ivs = per_dim
            self._by_coord = {
                tuple(bisect.bisect_right(self._starts[d], r.ul[d]) - 1
                      for d in range(ndim)): r
                for r in self.regions}
        else:  # not a grid (shouldn't happen for mesh shardings)
            log_debug(
                "shuffle: %d target regions do not form a grid; "
                "routing degrades to O(emissions x shards) linear scan",
                len(self.regions))
            self._by_coord = None

    def hits(self, ext):
        if self._by_coord is None:
            return [r for r in self.regions
                    if ext.intersection(r) is not None]
        hit_ranges = []
        for d, (starts, ivs) in enumerate(zip(self._starts, self._ivs)):
            lo = bisect.bisect_right(starts, ext.ul[d]) - 1
            lo = max(lo, 0)
            hi = bisect.bisect_left(starts, ext.lr[d])
            idxs = [i for i in range(lo, hi) if ivs[i][1] > ext.ul[d]]
            if not idxs:
                return []
            hit_ranges.append(idxs)
        out = []

        def rec(d, coord):
            if d == len(hit_ranges):
                r = self._by_coord.get(tuple(coord))
                if r is not None:
                    out.append(r)
                return
            for i in hit_ranges[d]:
                coord.append(i)
                rec(d + 1, coord)
                coord.pop()

        rec(0, [])
        return out


# Optional observability hook for tests: called as hook(event, nbytes)
# with event in {'alloc', 'release'} around each region block's host
# lifetime during sharded assembly.
_block_lifecycle_hook: Optional[Callable[[str, int], None]] = None


def _shuffle_sharded(src, kernel, kw, out_shape, out_dtype, out_tiling,
                     combiner_name, tgt, workers=None) -> da.DistArray:
    """Distributed scatter-combine with concurrent kernel fan-out.

    Pool tasks (one per source tile, submitted through a bounded
    window) fetch the tile block, run the kernel, and route each
    emission through the region interval index into per-region piece
    copies.  The main thread consumes results strictly in source-tile
    order and folds each piece into its lazily-allocated region block
    — deterministic (all combiner applications are ordered, on one
    thread) and memory-bounded (in-flight pieces are capped by the
    submission window; resident blocks are only the touched ones).
    Placement then streams: touched blocks first (placed + released),
    untouched ones allocated/placed/released one at a time."""
    apply_update = _COMBINERS[combiner_name]
    mesh = src.mesh
    sharding = out_tiling.sharding(mesh)
    # device -> region it stores (jax's ground truth, handles uneven
    # splits and replicated axes — regions may repeat across devices)
    idx_map = sharding.addressable_devices_indices_map(tuple(out_shape))
    region_of = {dev: extent_mod.from_slice(idx, out_shape)
                 for dev, idx in idx_map.items()}
    regions = sorted(set(region_of.values()), key=lambda r: r.ul)
    index = _RegionIndex(regions)
    hook = _block_lifecycle_hook

    def run_tile(tile_idx, s_ext):
        """Fetch + kernel + route for one source tile (pool worker)."""
        block = src.fetch(s_ext)
        routed = []  # (region, isect, piece-copy) in emission order
        for t_ext, data in kernel(s_ext, block, **kw):
            t_ext, data = _normalize(t_ext, data, out_shape, out_dtype)
            for r_ext in index.hits(t_ext):
                isect = t_ext.intersection(r_ext)
                if isect is None:
                    continue
                # copy: never pin the kernel's full output via a view
                piece = np.ascontiguousarray(
                    data[t_ext.offset_slice(isect)])
                routed.append((r_ext, isect, piece))
        return routed

    blocks: dict = {}  # touched regions only, allocated on first piece

    def block_of(r_ext):
        base = blocks.get(r_ext)
        if base is None:
            base = (tgt.fetch(r_ext).astype(out_dtype, copy=True) if tgt
                    else np.zeros(r_ext.shape, out_dtype))
            if hook:
                hook("alloc", base.nbytes)
            blocks[r_ext] = base
        return base

    src_extents = list(src.extents())
    if workers is None:
        # scale with the machine and the work, capped: more threads than
        # source tiles idle, and past ~4x cores they only add contention.
        # A single-core host gets NO pool at all (workers=1 runs inline
        # below): the fan-out can't overlap anything there, and
        # concurrent execute/fetch against the XLA:CPU client has shown
        # lost-wakeup deadlocks on 1-vCPU VMs (every thread parked in
        # futex_wait) — serial invocation sidesteps the fragile path.
        cores = os.cpu_count() or 1
        workers = min(32, 4 * cores) if cores > 1 else 1
    n_workers = max(1, min(workers, len(src_extents)))
    if n_workers == 1:
        # inline: same semantics (source-tile order, ordered combiner
        # application), no pool thread
        for i, e in enumerate(src_extents):
            for r_ext, isect, piece in run_tile(i, e):
                apply_update(block_of(r_ext),
                             isect.offset_from(r_ext).to_slice(), piece)
    else:
        # slack over the pool size keeps workers fed at the tile
        # boundary; growing it 2x with the pool would scale peak
        # buffered piece-copies with core count, so the prefetch margin
        # stays small and fixed
        window = n_workers + 4
        with ThreadPoolExecutor(max_workers=n_workers) as pool:
            pending = deque()
            todo = iter(enumerate(src_extents))

            def submit_next():
                for i, e in todo:
                    pending.append(pool.submit(run_tile, i, e))
                    return

            for _ in range(window):
                submit_next()
            while pending:
                routed = pending.popleft().result()  # source-tile order
                submit_next()
                for r_ext, isect, piece in routed:
                    apply_update(block_of(r_ext),
                                 isect.offset_from(r_ext).to_slice(), piece)

    per_device: dict = {}
    placed = set()

    def place(r_ext, base):
        for dev, r in region_of.items():
            if r == r_ext:
                per_device[dev] = jax.device_put(base, dev)
        placed.add(r_ext)
        if hook:
            hook("release", base.nbytes)

    for r_ext in [r for r in regions if r in blocks]:
        place(r_ext, blocks.pop(r_ext))
    for r_ext in regions:
        if r_ext in placed:
            continue
        base = (tgt.fetch(r_ext).astype(out_dtype, copy=True) if tgt
                else np.zeros(r_ext.shape, out_dtype))
        if hook:
            hook("alloc", base.nbytes)
        place(r_ext, base)
        del base

    arrs = [per_device[dev] for dev in idx_map]
    jarr = jax.make_array_from_single_device_arrays(
        tuple(out_shape), sharding, arrs)
    return da.DistArray(jarr, out_tiling, mesh)


def _shuffle_host(src, kernel, kw, out_shape, out_dtype, out_tiling,
                  combiner_name, tgt) -> da.DistArray:
    """Whole-array fallback: glom the source once, scatter into a single
    host target buffer."""
    apply_update = _COMBINERS[combiner_name]
    tgt_np = (tgt.glom().astype(out_dtype, copy=True) if tgt
              else np.zeros(out_shape, out_dtype))
    src_np = src.glom()
    blocks_iter = ((s_ext, src_np[s_ext.to_slice()])
                   for s_ext in src.extents())
    for t_ext, data in _emissions(blocks_iter, kernel, kw, out_shape,
                                  out_dtype):
        apply_update(tgt_np, t_ext.to_slice(), data)
    return da.from_numpy(tgt_np, tiling=out_tiling, mesh=src.mesh)
