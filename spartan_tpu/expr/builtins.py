"""NumPy-surface builtins over the expr DAG.

Parity with ``[U] spartan/expr/builtins.py`` (SURVEY.md §2.3: ``zeros ones
rand randn arange astype ravel sum mean max min argmin argmax diag diagonal
norm concatenate bincount tril triu scan``) — mostly thin wrappers over
map/reduce/creation exprs, exactly as in the reference.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from ..array import distarray as da
from .base import Expr, ScalarExpr, ValExpr, as_expr
from .map import MapExpr, build_unop, map as map_expr
from .ndarray import CreateExpr, RandomExpr, ndarray
from .reduce import (all, any, argmax, argmin, max, mean, min, prod,
                     reduce, sum)

__all__ = [
    "zeros", "ones", "full", "arange", "eye", "identity", "rand", "randn",
    "randint", "astype", "absolute", "exp", "log", "sqrt", "square", "abs",
    "sign", "sin", "cos", "tan", "tanh", "maximum", "minimum", "where",
    "clip", "sum", "mean", "max", "min", "prod", "all", "any", "argmax",
    "argmin", "reduce", "ndarray", "norm", "diag", "diagonal", "tril",
    "triu", "bincount", "concatenate", "ravel", "sqrt", "dot", "power",
    "equal", "from_numpy", "count_nonzero", "count_zero", "size", "scan",
    "sort", "argsort", "median", "percentile", "quantile", "histogram",
    "unique_counts", "unique", "topk",
    "isnan", "isinf",
    "isfinite", "logical_not", "var", "std", "ptp", "cumsum", "cumprod",
    "take", "linspace", "log1p", "expm1", "log2", "log10", "floor", "ceil",
    "rint", "negative", "reciprocal", "add", "subtract", "multiply",
    "divide", "true_divide", "mod", "not_equal", "greater", "greater_equal",
    "less", "less_equal", "logical_and", "logical_or", "logical_xor",
    "outer_product", "einsum", "tensordot", "matmul", "trace", "inner",
]


# -- creation -----------------------------------------------------------


def zeros(shape, dtype=np.float32, tile_hint=None, tiling=None) -> Expr:
    return CreateExpr(shape, dtype, "zeros", (), tiling, tile_hint)


def ones(shape, dtype=np.float32, tile_hint=None, tiling=None) -> Expr:
    return CreateExpr(shape, dtype, "ones", (), tiling, tile_hint)


def full(shape, fill_value, dtype=np.float32, tile_hint=None,
         tiling=None) -> Expr:
    return CreateExpr(shape, dtype, "full", (fill_value,), tiling, tile_hint)


def arange(*args, dtype=None, tile_hint=None, tiling=None) -> Expr:
    probe = np.arange(*args, dtype=dtype)
    if probe.dtype == np.float64:
        probe = probe.astype(np.float32)
    if probe.dtype == np.int64:
        probe = probe.astype(np.int32)
    return CreateExpr(probe.shape, probe.dtype, "arange", tuple(args),
                      tiling, tile_hint)


def eye(n, m=None, k=0, dtype=np.float32, tile_hint=None) -> Expr:
    m = n if m is None else m
    return CreateExpr((n, m), dtype, "eye", (n, m, k), None, tile_hint)


def identity(n, dtype=np.float32) -> Expr:
    return eye(n, dtype=dtype)


def rand(*shape, seed=None, tile_hint=None, tiling=None) -> Expr:
    return RandomExpr(shape, "uniform", seed, np.float32, tiling, tile_hint)


def randn(*shape, seed=None, tile_hint=None, tiling=None) -> Expr:
    return RandomExpr(shape, "normal", seed, np.float32, tiling, tile_hint)


def randint(*shape, low=0, high=10, seed=None, tile_hint=None) -> Expr:
    e = RandomExpr(shape, "randint", seed, np.int32, None, tile_hint)
    e.params_range = (low, high)
    return e


def from_numpy(arr, tiling=None, tile_hint=None) -> Expr:
    return ValExpr(da.from_numpy(arr, tiling=tiling, tile_hint=tile_hint))


# -- elementwise wrappers ----------------------------------------------


def _unary(name):
    def fn(x) -> Expr:
        return build_unop(name, x)

    fn.__name__ = name
    return fn


absolute = _unary("absolute")
abs = absolute
exp = _unary("exp")
log = _unary("log")
sqrt = _unary("sqrt")
square = _unary("square")
sign = _unary("sign")
sin = _unary("sin")
cos = _unary("cos")
tan = _unary("tan")
tanh = _unary("tanh")
isnan = _unary("isnan")
isinf = _unary("isinf")
isfinite = _unary("isfinite")
logical_not = _unary("logical_not")
log1p = _unary("log1p")
expm1 = _unary("expm1")
log2 = _unary("log2")
log10 = _unary("log10")
floor = _unary("floor")
ceil = _unary("ceil")
rint = _unary("rint")
negative = _unary("negative")
reciprocal = _unary("reciprocal")


def _binary(name):
    def fn(a, b) -> Expr:
        from .map import build_binop

        return build_binop(name, a, b)

    fn.__name__ = name
    return fn


add = _binary("add")
subtract = _binary("subtract")
multiply = _binary("multiply")
true_divide = _binary("true_divide")
divide = true_divide
mod = _binary("mod")
not_equal = _binary("not_equal")
greater = _binary("greater")
greater_equal = _binary("greater_equal")
less = _binary("less")
less_equal = _binary("less_equal")
logical_and = _binary("logical_and")
logical_or = _binary("logical_or")
logical_xor = _binary("logical_xor")


def maximum(a, b) -> Expr:
    from .map import build_binop

    return build_binop("maximum", a, b)


def minimum(a, b) -> Expr:
    from .map import build_binop

    return build_binop("minimum", a, b)


def power(a, b) -> Expr:
    from .map import build_binop

    return build_binop("power", a, b)


def equal(a, b) -> Expr:
    from .map import build_binop

    return build_binop("equal", a, b)


def where(cond, a, b) -> Expr:
    from .local import LocalInput, LocalUfunc

    inputs = (as_expr(cond), as_expr(a), as_expr(b))
    return MapExpr(inputs, LocalUfunc(
        "where", (LocalInput(0), LocalInput(1), LocalInput(2))))


def clip(x, lo, hi) -> Expr:
    from .local import LocalInput, LocalUfunc

    inputs = (as_expr(x), as_expr(lo), as_expr(hi))
    return MapExpr(inputs, LocalUfunc(
        "clip", (LocalInput(0), LocalInput(1), LocalInput(2))))


def astype(x, dtype) -> Expr:
    dtype = np.dtype(dtype)
    return map_expr(lambda v: v.astype(dtype), as_expr(x))


# -- shape-flavoured / misc builtins -----------------------------------


def ravel(x) -> Expr:
    from .reshape import ravel as _ravel

    return _ravel(x)


def concatenate(arrays, axis=0) -> Expr:
    from .reshape import concatenate as _concat

    return _concat(arrays, axis)


def dot(a, b, precision=None) -> Expr:
    from .dot import dot as _dot

    return _dot(a, b, precision=precision)


def norm(x, ord=2) -> Expr:
    x = as_expr(x)
    if ord == 2:
        return sqrt(sum(x * x))
    if ord == 1:
        return sum(absolute(x))
    raise ValueError(f"unsupported norm order {ord}")


def diag(x) -> Expr:
    """1-D -> diagonal matrix; 2-D -> its diagonal (NumPy semantics)."""
    x = as_expr(x)
    if x.ndim == 1:
        return map_expr(lambda v: jnp.diag(v), x)
    if x.ndim == 2:
        return diagonal(x)
    raise ValueError("diag requires 1-D or 2-D input")


def diagonal(x) -> Expr:
    x = as_expr(x)
    if x.ndim != 2:
        raise ValueError("diagonal requires a 2-D input")
    from .map import MapExpr
    from .local import LocalCall, LocalInput

    return MapExpr((x,), LocalCall(jnp.diagonal, (LocalInput(0),)))


def tril(x, k=0) -> Expr:
    return map_expr(lambda v: jnp.tril(v, k), as_expr(x))


def triu(x, k=0) -> Expr:
    return map_expr(lambda v: jnp.triu(v, k), as_expr(x))


class BincountExpr(Expr):
    """Counts of ints in ``[0, length)`` — the histogram family's
    reduction. Lowers through the kernel layer (docs/KERNELS.md): when
    ``kernels.select`` picks Pallas, each row shard counts its entries
    with the blocked one-hot kernel (spartan_tpu/kernels/histogram.py)
    and the count rows merge with one psum; otherwise the traced
    ``jnp.bincount`` (XLA scatter-add, GSPMD-partitioned). Negative
    ids clip to bucket 0 and ids >= length are dropped on both
    backends (jnp.bincount parity)."""

    def __init__(self, x: Expr, length: int):
        self.x = x
        self.length = int(length)
        super().__init__((self.length,), np.int32)

    def children(self):
        return (self.x,)

    def replace_children(self, new_children) -> "BincountExpr":
        return BincountExpr(new_children[0], self.length)

    def _lower(self, env) -> Any:
        from ..kernels import registry as kernels_mod

        v = self.x.lower(env)
        sel = kernels_mod.node_selection(self)
        if sel is not None and sel.pallas:
            from ..kernels import histogram as khist

            return khist.bincount_sharded(v, self.length, sel)
        return jnp.bincount(v.ravel(), length=self.length)

    def _sig(self, ctx):
        return ("bincount", self.length, ctx.of(self.x))

    def _default_tiling(self):
        from ..array import tiling as tiling_mod

        return tiling_mod.replicated(1)


def bincount(x, minlength: Optional[int] = None,
             length: Optional[int] = None) -> Expr:
    """Counts of nonnegative ints. A static ``length``/``minlength`` keeps
    the output shape static for XLA (dynamic shapes are TPU-hostile); it
    defaults to ``x.max()+1`` computed eagerly (one small collective)."""
    x = as_expr(x)
    n = length or minlength
    if n is None:
        n = int(max(x).glom()) + 1
    return BincountExpr(x, n)


def count_nonzero(x) -> Expr:
    x = as_expr(x)
    return sum(astype(x != 0, np.int32))


def count_zero(x) -> Expr:
    x = as_expr(x)
    return sum(astype(x == 0, np.int32))


def size(x) -> int:
    return as_expr(x).size


class SampleSortExpr(Expr):
    """Distributed sample sort (SURVEY.md §2.3 misc ops: the
    reference's sampling-based distributed sort). Lowers to the
    static-shape shard_map program in ``ops/sort.py``: local sort,
    gathered splitter samples, all_to_all bucket exchange, local
    merge, all_to_all rebalance to even row shards. Any length (a
    validity channel carries ragged tails); N-d arrays sort along
    ``axis`` with the 1-D kernel vmapped over the other axes — the
    sharded sort axis is never gathered. With ``indices=True`` it is
    the distributed argsort (source indices ride the pipeline as a
    sort payload)."""

    def __init__(self, x: Expr, indices: bool = False, axis: int = -1):
        self.x = x
        self.indices = indices
        self.axis = _checked_axis(axis, x.ndim)
        super().__init__(x.shape, np.int32 if indices else x.dtype)

    def children(self):
        return (self.x,)

    def replace_children(self, new_children) -> "SampleSortExpr":
        return SampleSortExpr(new_children[0], self.indices, self.axis)

    def _moved_in_tiling(self):
        """The operand's tiling with the sort axis moved last — what
        the lowering's moveaxis produces; lets the kernel keep batch
        shardings and follow the sort axis's existing placement."""
        t = self.x.out_tiling()
        axes = list(t.axes)
        axes.append(axes.pop(self.axis))
        from ..array.tiling import Tiling

        return Tiling(axes)

    def _lower(self, env) -> Any:
        from ..ops import sort as sort_ops

        v = self.x.lower(env)
        if self.x.ndim <= 1:
            fn = (sort_ops.sample_argsort if self.indices
                  else sort_ops.sample_sort)
            return fn(v)
        last = self.x.ndim - 1
        if self.axis != last:
            v = jnp.moveaxis(v, self.axis, last)
        out = sort_ops.sample_sort_axis(
            v, with_indices=self.indices,
            in_tiling=self._moved_in_tiling())
        if self.axis != last:
            out = jnp.moveaxis(out, last, self.axis)
        return out

    def _sig(self, ctx):
        return ("sample_sort", self.indices, self.axis, ctx.of(self.x))

    def _default_tiling(self):
        from ..array import tiling as tiling_mod
        from ..ops import sort as sort_ops

        if self.ndim <= 1:
            return tiling_mod.row(1)
        # batch axes keep the operand's shardings; the sort axis comes
        # back sharded where the kernel ran it. Axis selection and
        # batch clearing are the SAME helpers _run uses, so this
        # declared tiling cannot diverge from the kernel's out_specs
        # (ADVICE round 5, finding 1).
        moved = self._moved_in_tiling()
        name = sort_ops.collective_axis(moved)
        axes = list(sort_ops.batch_axes(moved, name, self.ndim))
        axes.insert(self.axis, name)
        return tiling_mod.Tiling(axes)


def _checked_axis(axis: int, ndim: int) -> int:
    nd = ndim if ndim else 1
    if not -nd <= axis < nd:
        raise ValueError(
            f"sort axis {axis} out of range for ndim {ndim}")
    return axis % nd


def _distributed_sortable(x: Expr, axis: int) -> bool:
    """True when the distributed sample sort beats the traced
    ``jnp.sort``: a multi-device row axis, and (for N-d operands) the
    sort axis actually sharded — an unsharded sort axis sorts locally
    under GSPMD with zero communication, which no collective pipeline
    can beat."""
    from ..array import tiling as tiling_mod
    from ..parallel import mesh as mesh_mod

    p = int(mesh_mod.get_mesh().shape.get(tiling_mod.AXIS_ROW, 1))
    if p <= 1 or x.ndim == 0 or x.size == 0:
        return False
    if x.ndim == 1:
        return True
    return x.out_tiling().axes[axis % x.ndim] is not None


def sort(x, axis: int = -1) -> Expr:
    """Sorted copy along an axis.

    Arrays sharded along the sort axis on a multi-device mesh run the
    distributed sample sort — splitter sampling + all_to_all bucket
    exchange under shard_map (ops/sort.py), the reference's algorithm
    in collective form; any length (ragged tails ride a validity
    channel) and any rank (the kernel vmaps over non-sort axes).
    Everything else is a single traced ``jnp.sort`` over the sharded
    operand (XLA bitonic sort; right when the sort axis is local).
    Masked operands sort valid-first, masked-last (numpy.ma).

    Note: when the sorted length does not divide the mesh the RESULT
    materializes replicated (the DistArray layer's shard grid needs
    even splits) — the sort itself still runs distributed; only the
    final layout is replicated."""
    from ..array.masked import MaskedDistArray, masked_sort

    if isinstance(x, MaskedDistArray):
        return masked_sort(x, axis=axis)
    x = as_expr(x)
    ax = _checked_axis(axis, x.ndim)
    if _distributed_sortable(x, ax):
        return SampleSortExpr(x, axis=ax)
    return map_expr(lambda v: jnp.sort(v, axis=ax), x)


def argsort(x, axis: int = -1) -> Expr:
    """Indices that sort ``x``; arrays sharded along the sort axis run
    the distributed sample argsort (see :func:`sort`). Masked operands
    order valid elements first (numpy.ma semantics)."""
    from ..array.masked import MaskedDistArray, masked_argsort

    if isinstance(x, MaskedDistArray):
        return masked_argsort(x, axis=axis)
    x = as_expr(x)
    ax = _checked_axis(axis, x.ndim)
    if _distributed_sortable(x, ax):
        return SampleSortExpr(x, indices=True, axis=ax)
    return map_expr(lambda v: jnp.argsort(v, axis=ax), x)


def _nan_poison(x: Expr, rdt, axis=None) -> Any:
    """0 when ``x`` is NaN-free, NaN otherwise (per slice of ``axis``
    when given) — added to distributed order statistics so
    median/percentile propagate NaN exactly like the traced jnp
    fallbacks (the sample sort orders NaN to one end, which would
    otherwise silently hide it).

    Derived from NaN-ness alone: counting ``isnan`` per element keeps
    inf inputs and f32 sum overflow (both of which poisoned the old
    ``sum(x) * 0.0`` formulation with spurious NaN) out of the result."""
    if not np.issubdtype(np.dtype(rdt), np.floating) or \
            not np.issubdtype(np.dtype(x.dtype), np.floating):
        return 0.0  # int inputs can't hold NaN: skip the scan entirely
    cnt = sum(map_expr(lambda v: jnp.isnan(v).astype(jnp.float32), x),
              axis=axis)
    return map_expr(
        lambda c: jnp.where(c > 0, jnp.nan, 0.0).astype(rdt), cnt)


def _axis_order_stat_path(x: Expr, axis) -> Any:
    """The normalized axis when an order statistic (median /
    percentile along ``axis``) should ride the distributed sort — the
    operand is sharded along that axis, so the traced fallback would
    all-gather it. None otherwise. 1-D arrays sort on axis 0 for
    ``axis`` in (None, 0, -1); N-d arrays need an integer axis."""
    if x.ndim == 0 or x.size == 0:
        return None
    if x.ndim == 1:
        if axis not in (None, 0, -1):
            return None
        return 0 if _distributed_sortable(x, 0) else None
    if axis is None or not isinstance(axis, (int, np.integer)):
        return None
    ax = _checked_axis(int(axis), x.ndim)
    return ax if _distributed_sortable(x, ax) else None


def _order_stat_interp(x: Expr, ax: int, positions, rdt):
    """Linearly-interpolated order statistics of ``x`` along ``ax``
    at fractional ``positions``, read off ONE distributed sort
    (SampleSortExpr); each result drops ``ax``. The shared kernel of
    median and scalar-q percentile, 1-D and N-d alike. Operands are
    promoted to ``rdt`` BEFORE combining: int middles could overflow."""
    n = x.shape[ax]
    s = SampleSortExpr(x, axis=ax)
    pre = (slice(None),) * ax
    outs = []
    for pos in positions:
        lo = int(np.floor(pos))
        hi = lo + 1 if lo + 1 <= n - 1 else n - 1
        fr = float(pos - lo)
        outs.append((1.0 - fr) * astype(s[pre + (lo,)], rdt)
                    + fr * astype(s[pre + (hi,)], rdt))
    return outs


def median(x, axis=None) -> Expr:
    """Median; arrays sharded along the reduction axis (1-D arrays,
    and any N-d axis) route through the distributed sample sort (two
    order statistics of the sorted result) instead of gathering the
    axis. Matches the traced path's dtype promotion and NaN
    propagation. Masked operands take the median of the UNMASKED
    elements (numpy.ma; fully-masked slices come out NaN)."""
    from ..array.masked import MaskedDistArray, masked_median

    if isinstance(x, MaskedDistArray):
        return masked_median(x, axis=axis)
    x = as_expr(x)
    ax = _axis_order_stat_path(x, axis)
    if ax is not None:
        rdt = jnp.result_type(x.dtype, jnp.float32)
        n = x.shape[ax]
        (out,) = _order_stat_interp(x, ax, [(n - 1) / 2.0], rdt)
        return out + _nan_poison(x, rdt, axis=ax)
    return map_expr(lambda v: jnp.median(v, axis=axis), x)


def percentile(x, q, axis=None) -> Expr:
    """Percentile (linear interpolation), scalar or 1-D vector ``q``;
    the 1-D multi-device case rides the distributed sample sort like
    :func:`median` — ONE sort feeds every quantile (vector ``q``
    gathers the needed order statistics from the sorted result)."""
    x = as_expr(x)
    scalar_q = np.ndim(q) == 0
    qa = np.atleast_1d(np.asarray(q, dtype=np.float64))
    if qa.ndim != 1:
        raise NotImplementedError(
            "spartan_tpu.percentile supports scalar or 1-D q only; "
            f"got q with shape {qa.shape}")
    if qa.size == 0 or np.any(qa < 0.0) or np.any(qa > 100.0) or \
            np.any(np.isnan(qa)):
        raise ValueError(f"percentile q={q} outside [0, 100]")
    ax = _axis_order_stat_path(x, axis)
    if ax is not None and scalar_q:
        rdt = jnp.result_type(x.dtype, jnp.float32)
        n = x.shape[ax]
        (out,) = _order_stat_interp(
            x, ax, [float(qa[0]) / 100.0 * (n - 1)], rdt)
        return out + _nan_poison(x, rdt, axis=ax)
    if ax is not None and x.ndim == 1:
        # vector q: gather every quantile's order statistics from ONE
        # distributed sort
        n = x.shape[0]
        rdt = jnp.result_type(x.dtype, jnp.float32)
        pos = qa / 100.0 * (n - 1)
        lo = np.floor(pos).astype(np.int64)
        # NB: this module shadows builtin min() with the reduce op
        hi = np.minimum(lo + 1, n - 1)
        frac = pos - lo
        s = SampleSortExpr(x)
        w = as_expr(frac.astype(np.float64))
        out = (1.0 - w) * astype(take(s, lo), rdt) \
            + w * astype(take(s, hi), rdt)
        return astype(out, rdt) + _nan_poison(x, rdt)
    # hashable closure capture: the compile cache keys kernels by
    # captured values, and tuples (unlike ndarrays) compare by content
    qq = float(qa[0]) if scalar_q else tuple(qa.tolist())
    return map_expr(
        lambda v: jnp.percentile(v, jnp.asarray(qq), axis=axis), x)


class TopKExpr(Expr):
    """INDICES of the distributed top-k (ops/sort.py
    distributed_topk): per-shard ``lax.top_k`` candidates + one k*p
    all_gather + final top-k — only candidates cross the wire. Values
    are a k-element gather on top (builtins.topk), so one kernel
    serves both outputs."""

    def __init__(self, x: Expr, k: int, largest: bool):
        self.x = x
        self.k = int(k)
        self.largest = bool(largest)
        super().__init__((self.k,), np.dtype(np.int32))

    def children(self):
        return (self.x,)

    def replace_children(self, new_children) -> "TopKExpr":
        return TopKExpr(new_children[0], self.k, self.largest)

    def _lower(self, env) -> Any:
        from ..ops.sort import distributed_topk

        return distributed_topk(self.x.lower(env), self.k,
                                largest=self.largest)[1]

    def _sig(self, ctx):
        return ("topk", self.k, self.largest, ctx.of(self.x))

    def _default_tiling(self):
        from ..array import tiling as tiling_mod

        return tiling_mod.replicated(1)


def topk(x, k: int, largest: bool = True):
    """(values, indices) of the k largest (or smallest) elements of a
    1-D array, values best-first — ``lax.top_k`` at mesh scale. On a
    multi-device mesh with ``k <= ceil(n/p)`` only ``k*p`` candidates
    cross the wire (per-shard top-k + one gather); bigger k rides the
    distributed sample argsort. Values are gathered through the
    indices, so each variant runs ONE distributed kernel. Ties resolve
    to any valid winner set (like ``lax.top_k``)."""
    from ..parallel import mesh as mesh_mod

    x = as_expr(x)
    if x.ndim != 1:
        raise ValueError(f"topk needs a 1-D operand, got {x.shape}")
    k = int(k)
    n = x.shape[0]
    if not 1 <= k <= n:
        raise ValueError(f"topk needs 1 <= k <= {n}, got {k}")
    from ..array import tiling as tiling_mod
    p = int(mesh_mod.get_mesh().shape.get(tiling_mod.AXIS_ROW, 1))
    if p > 1 and k > -(-n // p):
        # k exceeds the per-shard candidate budget: distributed
        # argsort, then slice the winning end (best-first)
        si = SampleSortExpr(x, indices=True)
        if largest:
            idx = map_expr(lambda v: v[::-1], si[n - k:])
        else:
            idx = si[:k]
    else:
        idx = TopKExpr(x, k, largest)
    vals = map_expr(lambda v, i: v[i], x, idx)
    return vals, idx


def quantile(x, q, axis=None) -> Expr:
    """``np.quantile``: :func:`percentile` with q in [0, 1]."""
    qa = np.asarray(q, dtype=np.float64)
    if qa.size and (np.any(qa < 0.0) or np.any(qa > 1.0)):
        raise ValueError(f"quantile q={q} outside [0, 1]")
    return percentile(x, qa * 100.0 if np.ndim(q) else float(qa) * 100.0,
                      axis=axis)


def _hist_edges(lo, hi, bins: int):
    """The bin-edge formula BOTH the bucketing kernels and the
    returned-edges exprs evaluate (in f32, on device) — one source, so
    counts can never disagree with the edges the caller receives."""
    lo = jnp.asarray(lo, jnp.float32)
    hi = jnp.asarray(hi, jnp.float32)
    # jnp.linspace pins BOTH endpoints exactly (it concatenates stop),
    # so a value equal to the range max never rounds out of the
    # closed last bin
    return jnp.linspace(lo, hi, bins + 1)


def _hist_expand(lo, hi):
    """np.histogram's degenerate-range rule: all-equal data (or an
    explicit lo == hi range) spans value +/- 0.5."""
    return (jnp.where(hi > lo, lo, lo - 0.5),
            jnp.where(hi > lo, hi, hi + 0.5))


def _hist_guard_range(lo, hi):
    """np.histogram raises on a non-finite autodetected range; the
    detection happens on device, so the check rides the numerics
    sentinel: compiled in (and raised by ``st.audit``) only under
    ``FLAGS.audit_numerics``, free otherwise (ADVICE r5 #2). Module
    level on purpose — a per-call closure cell would break the
    kernels' ``fn_key`` compile-cache stability."""
    from ..obs import numerics as _numerics

    _numerics.guard_finite(
        "histogram.range", jnp.stack([lo, hi]),
        "autodetected range of [%g, %g] is not finite")


def histogram(x, bins: int = 10, range=None):
    """``np.histogram`` with STATIC bin count: (counts, edges).

    Distributed as bucketing (a searchsorted map over the sharded
    operand) + the bincount reduction; ``range`` defaults to the
    operand's (min, max) — computed in the same program when not
    given. With an explicit ``range`` values outside it are dropped
    (np.histogram semantics); a degenerate range or constant data
    expands value +/- 0.5 like numpy. Edges are f32 (no x64 on
    device) and are computed by the same formula the bucketing kernel
    uses, so exact-edge values land where the returned edges say.

    np.histogram parity on non-finite data (ADVICE round 5, finding
    2): with ``range=None`` the (min, max) autodetection runs ON
    DEVICE inside the same traced program — there is no host round
    trip at which a non-finite range could raise eagerly. The
    autodetected range therefore carries a numerics-sentinel
    finiteness guard (``obs/numerics.guard_finite``): evaluating
    through ``st.audit`` raises ``ValueError("autodetected range of
    [nan, nan] is not finite")`` exactly like ``np.histogram``, and
    the audit report names the node that produced the NaN. The guard
    is compiled in only under ``FLAGS.audit_numerics``, so the plain
    dispatch-bound path costs nothing — there, non-finite data still
    yields non-finite edges; pass an explicit finite ``range`` (which
    validates eagerly) for data that may contain non-finite values."""
    from .map2 import map2

    x = as_expr(x)
    bins = int(bins)
    if bins <= 0:
        raise ValueError(f"histogram needs bins >= 1, got {bins}")
    if range is not None:
        lo, hi = float(range[0]), float(range[1])
        if not (np.isfinite(lo) and np.isfinite(hi)) or hi < lo:
            raise ValueError(
                f"histogram range {range}: bounds must be finite "
                f"with max >= min")
        if lo == hi:  # numpy expands the degenerate explicit range
            lo, hi = lo - 0.5, hi + 0.5
    if x.size == 0:
        lo0, hi0 = (lo, hi) if range is not None else (0.0, 1.0)
        return (zeros((bins,), np.int32),
                as_expr(np.linspace(lo0, hi0, bins + 1)
                        .astype(np.float32)))
    if range is not None:
        # lo/hi captured as SCALARS so the kernels' compile-cache keys
        # repeat across calls (an ndarray capture would key by id and
        # recompile every call)
        def bucket(v, lo=lo, hi=hi, bins=bins):
            e = _hist_edges(lo, hi, bins)
            vv = v.astype(e.dtype)
            idx = jnp.searchsorted(e, vv, side="right") - 1
            # np.histogram: the last bin is closed on the right
            idx = jnp.where(vv == e[-1], bins - 1, idx)
            oob = (vv < e[0]) | (vv > e[-1])
            return jnp.where(oob, bins, idx).astype(jnp.int32)

        counts = bincount(map_expr(bucket, x), length=bins)
        edges = map2([as_expr(0.0)],
                     lambda _z, lo=lo, hi=hi, bins=bins:
                     _hist_edges(lo, hi, bins))
        return counts, edges
    # data-dependent range: min/max reductions feed the bucketing map
    # inside one traced program (no host round trip)
    from .reduce import max as _rmax
    from .reduce import min as _rmin

    lo_e, hi_e = _rmin(x), _rmax(x)

    def bucket2(v, lo, hi):
        lo = lo.astype(jnp.float32)
        hi = hi.astype(jnp.float32)
        _hist_guard_range(lo, hi)
        lo, hi = _hist_expand(lo, hi)
        e = _hist_edges(lo, hi, bins)
        idx = jnp.searchsorted(e, v.astype(e.dtype), side="right") - 1
        return jnp.clip(idx, 0, bins - 1).astype(jnp.int32)

    counts = bincount(map_expr(bucket2, x, lo_e, hi_e), length=bins)

    def edges_fn(lo, hi):
        lo = lo.astype(jnp.float32)
        hi = hi.astype(jnp.float32)
        _hist_guard_range(lo, hi)
        lo, hi = _hist_expand(lo, hi)
        return _hist_edges(lo, hi, bins)

    edges = map_expr(edges_fn, lo_e, hi_e)
    return counts, edges


def unique_counts(x, size: int) -> Expr:
    """Counts of each value in [0, size) — static-shape unique()."""
    return bincount(x, length=size)


def unique(x, size: int, fill_value=0.0, return_counts: bool = False):
    """Sorted unique values with STATIC output size (``jnp.unique``'s
    ``size=`` convention: the output is padded with ``fill_value``
    past the distinct count, and distinct values beyond ``size`` are
    dropped — XLA needs static shapes).

    One pipeline serves every mesh size and rank (N-d flattens, like
    np.unique): sort (the distributed sample sort where the operand is
    sharded, a local traced sort otherwise) -> boundary flags (a
    shifted compare GSPMD resolves with a halo exchange) -> prefix
    scan for dense ranks -> scatter into the static output; counts are
    the bincount reduction over ranks, sharing the single sort. NaNs
    compare unequal, so each NaN counts as its own value (the
    sort-based convention)."""
    from .map2 import map2

    x = as_expr(x)
    size = int(size)
    if size <= 0:
        raise ValueError(f"unique needs size >= 1, got {size}")
    if x.ndim != 1:
        x = ravel(x)
    if x.size == 0:
        vals = full((size,), fill_value, x.dtype)
        if not return_counts:
            return vals
        return vals, zeros((size,), np.int32)
    s = sort(x)
    # boundary flags via roll + where, NOT concatenate([ones(1), ...]):
    # the uneven-concat halo pattern mis-partitions under GSPMD on some
    # jax/XLA:CPU versions (every boundary double-counted — same bug
    # family as the linspace lowering note in ndarray.py); roll lowers
    # to a collective-permute that partitions exactly. Slot 0's rolled
    # neighbor is the LAST element, masked off by the where.
    flags = map_expr(
        lambda v: jnp.where(
            jnp.arange(v.shape[0]) == 0, 1,
            (v != jnp.roll(v, 1)).astype(jnp.int32)).astype(jnp.int32), s)
    rank = cumsum(flags) - 1
    vals = map2(
        [s, rank, flags],
        lambda v, r, f, size, fill: jnp.full(
            (size,), fill, v.dtype)
        .at[jnp.where(f == 1, r, size)].set(v, mode="drop"),
        fn_kw={"size": size, "fill": fill_value})
    if not return_counts:
        return vals
    return vals, bincount(rank, length=size)


def linspace(start, stop, num=50, endpoint=True, dtype=np.float32,
             tile_hint=None, tiling=None) -> Expr:
    return CreateExpr((int(num),), dtype, "linspace",
                      (float(start), float(stop), int(num), bool(endpoint)),
                      tiling, tile_hint)


def take(x, indices, axis=None) -> Expr:
    """Gather elements by integer index (NumPy ``take`` semantics).

    Indices enter the DAG as an input (not a closure capture) so the
    structural compile cache keys them by shape/dtype and the gather
    program is reused across different index arrays. Out-of-range
    indices raise up front, numpy-style (the traced gather would
    silently clamp them)."""
    x = as_expr(x)
    idx_np = np.asarray(indices)
    if axis is not None and x.ndim == 0:
        raise ValueError(
            f"take axis {axis} out of range for a 0-d operand")
    bound = x.size if axis is None else \
        x.shape[_checked_axis(int(axis), x.ndim)]
    if idx_np.size and (idx_np.min() < -bound or idx_np.max() >= bound):
        raise IndexError(
            f"take indices out of bounds for axis size {bound}: "
            f"range [{idx_np.min()}, {idx_np.max()}]")
    idx = as_expr(idx_np)
    return map_expr(lambda v, i: jnp.take(v, i, axis=axis), x, idx)


def var(x, axis=None, ddof: int = 0, keepdims: bool = False) -> Expr:
    """Variance: two-pass (mean, then mean of squared deviations), both
    passes fused into one XLA program by the single-jit lowering."""
    x = as_expr(x)
    m = mean(x, axis=axis, keepdims=True)
    d = x - m
    n = x.size if axis is None else _axis_count(x.shape, axis)
    return sum(d * d, axis=axis, keepdims=keepdims) / float(n - ddof)


def std(x, axis=None, ddof: int = 0, keepdims: bool = False) -> Expr:
    return sqrt(var(x, axis=axis, ddof=ddof, keepdims=keepdims))


def ptp(x, axis=None) -> Expr:
    return max(x, axis=axis) - min(x, axis=axis)


def _axis_count(shape, axis) -> int:
    if isinstance(axis, (int, np.integer)):
        axis = (int(axis),)
    n = 1
    for a in axis:
        n *= shape[a % len(shape)]
    return n


def cumsum(x, axis: int = 0) -> Expr:
    return scan(x, axis=axis, op="add")


def cumprod(x, axis: int = 0) -> Expr:
    return scan(x, axis=axis, op="mul")


def einsum(subscripts: str, *operands, precision=None) -> Expr:
    """NumPy-style einsum over lazy operands.

    Two-operand contractions (incl. ellipsis batching) build a planned
    ``ContractExpr`` — the smart-tiling pass searches output grids and
    contraction placements for them exactly as for 2-D GEMMs
    (SURVEY.md §2.3 pass (d)). 3+ operands decompose into a CHAIN of
    planned pairwise contractions along np.einsum_path's greedy order,
    so every intermediate GEMM is planner-visible too. Specs outside
    the family (diagonals, broadcasting ellipses, single-operand
    reductions in the path) stay a single traced ``jnp.einsum``
    sharded by GSPMD from the operands' tilings."""
    from .contract import contract, contract_chain, parse_einsum
    from .map2 import map2

    exprs = [as_expr(o) for o in operands]
    parsed = parse_einsum(subscripts, tuple(e.ndim for e in exprs))
    if parsed is not None:
        per_op, out_labels = parsed
        if len(exprs) == 2:
            e = contract(exprs[0], exprs[1], per_op[0], per_op[1],
                         out_labels, precision=precision)
            if e is not None:
                return e
        elif len(exprs) > 2:
            e = contract_chain(exprs, per_op, out_labels,
                               precision=precision)
            if e is not None:
                return e
    return map2(exprs,
                lambda *xs, subscripts, precision: jnp.einsum(
                    subscripts, *xs, precision=precision),
                fn_kw={"subscripts": subscripts, "precision": precision})


def tensordot(a, b, axes=2) -> Expr:
    """NumPy ``tensordot``: lowered as a planned contraction (free axes
    of ``a``, then of ``b``; contracted pairs share labels), so the
    smart-tiling pass plans it like any GEMM."""
    from .contract import _CANON, contract
    from .map2 import map2

    a, b = as_expr(a), as_expr(b)
    if isinstance(axes, (list, tuple)):
        ax0, ax1 = axes

        def _norm(xs, nd):
            out = []
            for x in np.atleast_1d(xs):
                x = int(x)
                if not -nd <= x < nd:
                    raise ValueError(
                        f"tensordot axis {x} out of range for "
                        f"ndim {nd}")
                out.append(x % nd)
            return tuple(out)

        ax_a = _norm(ax0, a.ndim)
        ax_b = _norm(ax1, b.ndim)
        if len(ax_a) != len(ax_b):
            raise ValueError(
                f"tensordot axes lists differ in length: "
                f"{len(ax_a)} vs {len(ax_b)}")
    else:
        k = int(axes)
        if k > a.ndim or k > b.ndim:
            raise ValueError(
                f"tensordot axes={k} exceeds operand ranks "
                f"{a.ndim} and {b.ndim}")
        ax_a = tuple(range(a.ndim - k, a.ndim))
        ax_b = tuple(range(k))
    la = [_CANON[i] for i in range(a.ndim)]
    lb = [_CANON[a.ndim + i] for i in range(b.ndim)]
    for i, j in zip(ax_a, ax_b):
        lb[j] = la[i]
    out = tuple(la[i] for i in range(a.ndim) if i not in ax_a) + \
        tuple(lb[j] for j in range(b.ndim) if j not in ax_b)
    e = contract(a, b, tuple(la), tuple(lb), out)
    if e is not None:
        return e
    axes_n = (ax_a, ax_b)
    return map2([a, b],
                lambda x, y, axes: jnp.tensordot(x, y, axes=axes),
                fn_kw={"axes": axes_n})


def matmul(a, b, precision=None) -> Expr:
    """``a @ b``: 1-D/2-D operands route through the smart-tiling
    DotExpr; batched (>2-D) operands become a planned batched
    contraction (traced ``jnp.matmul`` only when batch dims need
    broadcasting)."""
    from .contract import _CANON, contract
    from .dot import dot as dot_expr
    from .map2 import map2

    a, b = as_expr(a), as_expr(b)
    if a.ndim <= 2 and b.ndim <= 2:
        return dot_expr(a, b, precision=precision)
    e = None
    if a.ndim >= 2 and b.ndim >= 2:
        nb = _size_max(a.ndim, b.ndim) - 2
        batch = [_CANON[i] for i in range(nb)]
        la = tuple(batch[nb - (a.ndim - 2):]) + (_CANON[nb],
                                                 _CANON[nb + 1])
        lb = tuple(batch[nb - (b.ndim - 2):]) + (_CANON[nb + 1],
                                                 _CANON[nb + 2])
        out = tuple(batch) + (_CANON[nb], _CANON[nb + 2])
        e = contract(a, b, la, lb, out, precision=precision)
    if e is not None:
        return e
    return map2([a, b],
                lambda x, y, precision: jnp.matmul(
                    x, y, precision=precision),
                fn_kw={"precision": precision})


def _size_max(a: int, b: int) -> int:
    return a if a >= b else b


def trace(x, offset: int = 0) -> Expr:
    from .map2 import map2

    return map2([as_expr(x)],
                lambda v, offset: jnp.trace(v, offset=offset),
                fn_kw={"offset": offset})


def inner(a, b) -> Expr:
    """NumPy ``inner``: 1-D operands contract (a dot); otherwise the
    last-axis contraction as a planned ContractExpr."""
    from .contract import _CANON, contract
    from .map2 import map2

    a, b = as_expr(a), as_expr(b)
    if a.ndim == 1 and b.ndim == 1:
        return dot(a, b)
    if a.ndim >= 1 and b.ndim >= 1:
        la = tuple(_CANON[i] for i in range(a.ndim - 1)) + ("z",)
        lb = tuple(_CANON[a.ndim - 1 + i]
                   for i in range(b.ndim - 1)) + ("z",)
        out = la[:-1] + lb[:-1]
        e = contract(a, b, la, lb, out)
        if e is not None:
            return e
    return map2([a, b], lambda x, y: jnp.inner(x, y))


def outer_product(a, b) -> Expr:
    """NumPy ``np.outer``: flattened outer product (distinct from the
    tile-pair ``outer`` primitive in ``expr/outer.py``)."""
    return map_expr(lambda u, v: u.ravel()[:, None] * v.ravel()[None, :],
                    as_expr(a), as_expr(b))


class BlockedScanExpr(Expr):
    """Distributed prefix scan over the sharded leading axis
    (ops/scan.py): local scan, all_gather of per-shard totals,
    exclusive offset combine — ONE shard_map program instead of the
    all-gathered replicated scan GSPMD emits for a traced cumsum on a
    sharded axis (measured minutes vs milliseconds at 4M elements)."""

    def __init__(self, x: Expr, op: str):
        self.x = x
        self.op = op
        super().__init__(x.shape, x.dtype)

    def children(self):
        return (self.x,)

    def replace_children(self, new_children) -> "BlockedScanExpr":
        return BlockedScanExpr(new_children[0], self.op)

    def _lower(self, env) -> Any:
        from ..ops import scan as scan_ops

        return scan_ops.blocked_scan(self.x.lower(env), self.op,
                                     in_axes=self.x.out_tiling().axes)

    def _sig(self, ctx):
        # trailing-axis sharding changes the lowered program
        return ("blocked_scan", self.op, self.x.out_tiling().axes,
                ctx.of(self.x))

    def _default_tiling(self):
        from ..array import tiling as tiling_mod
        from ..ops import scan as scan_ops

        t = scan_ops.scan_axes(self.x.out_tiling().axes, self.ndim)
        return tiling_mod.sanitize(t, self.shape)


def _blocked_scannable(x: Expr, axis: int, op: str) -> bool:
    """Dispatch guard for the distributed blocked scan: leading axis,
    divisible nonempty length, dtype preserved by the cumulative op
    (bool cumsum promotes to int32 — the map path infers that
    correctly), and not a layout where axis 0 is already unsharded
    while another axis carries the sharding (there the local per-shard
    scan is collective-free; resharding to row tiling would regress)."""
    from ..ops import scan as scan_ops
    from ..parallel import mesh as mesh_mod
    from ..array import tiling as tiling_mod

    if x.ndim < 1 or axis not in (0, -x.ndim):
        return False
    p = int(mesh_mod.get_mesh().shape.get(tiling_mod.AXIS_ROW, 1))
    if p <= 1 or x.shape[0] == 0 or x.shape[0] % p != 0:
        return False
    out = jax.eval_shape(lambda v: scan_ops._LOCAL[op](v, axis=0),
                         jax.ShapeDtypeStruct(x.shape, x.dtype))
    if out.dtype != x.dtype:
        return False
    t = x.out_tiling()
    if (x.ndim >= 2 and t.mesh_axis_of(0) is None
            and t.sharded_axes()):
        return False
    return True


def scan(x, axis: int = 0, op: str = "add") -> Expr:
    """Prefix scan along an axis (exercised by SSVD per BASELINE.json:11).

    The leading axis of any-rank arrays on a multi-device mesh (row
    axis dividing the length) runs the distributed blocked scan
    (ops/scan.py), trailing-axis sharding preserved; other axes lower
    to ``jnp.cumsum``-family ops — local per shard when the scan axis
    is unsharded."""
    from ..ops import scan as scan_ops

    x = as_expr(x)
    if op not in scan_ops._LOCAL:
        raise ValueError(f"unknown scan op {op!r}")
    if _blocked_scannable(x, axis, op):
        return BlockedScanExpr(x, op)
    fn = scan_ops._LOCAL[op]
    return map_expr(lambda v: fn(v, axis=axis), x)


import jax  # noqa: E402  (used inside scan closures)
