"""Alternating least squares matrix factorization (reference:
``[U] spartan/examples/als.py`` / netflix SGD — SURVEY.md §2.4).

R (users x items) ≈ U @ V^T. Each half-step solves all users' (or
items') k x k normal equations in one batched traced computation —
``vmap`` over the row dimension replaces the reference's per-tile
kernel fan-out.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

import spartan_tpu as st
from ..array import tiling as tiling_mod
from ..expr.base import as_expr
from ..expr.map2 import map2


def _solve_side(r, other, reg):
    """For each row i of r: solve (O^T W_i O + reg I) f_i = O^T r_i where
    W_i masks observed entries (r != 0)."""

    def per_row(r_row):
        w = (r_row != 0).astype(r_row.dtype)
        a = (other.T * w) @ other + reg * jnp.eye(other.shape[1],
                                                 dtype=r_row.dtype)
        b = other.T @ (w * r_row)
        return jnp.linalg.solve(a, b)

    return jax.vmap(per_row)(r)


def als(ratings, k: int = 8, num_iter: int = 10, reg: float = 0.1,
        seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Factor a (possibly zero-masked) ratings matrix; zeros = missing."""
    ratings = as_expr(ratings)
    m, n = ratings.shape
    rng = np.random.RandomState(seed)
    v = rng.rand(n, k).astype(np.float32) * 0.1

    r_rows = ratings  # (m, n) row-sharded
    r_cols = ratings.T  # lazy transpose -> (n, m)

    u = None
    for _ in range(num_iter):
        ev = st.from_numpy(v, tiling=tiling_mod.replicated(2))
        u = map2([r_rows, ev],
                 lambda rv, vv: _solve_side(rv, vv, reg),
                 out_tiling=tiling_mod.row(2)).glom()
        eu = st.from_numpy(u, tiling=tiling_mod.replicated(2))
        v = map2([r_cols, eu],
                 lambda rv, uv: _solve_side(rv, uv, reg),
                 out_tiling=tiling_mod.row(2)).glom()
    return u, v
