"""Stochastic (randomized) SVD (config 5, BASELINE.json:11; reference:
``[U] spartan/examples/ssvd.py``, after Halko-Martinsson-Tropp).

The reference built the sketch Y = A @ Omega with shuffle-GEMM and ran
per-tile QR assembly. Here the sketch, power iterations, projection and
the small final SVD are traced dense ops: the big GEMMs ride the sharded
dot path (MXU) and the (n, k) panel QR runs replicated (k is small).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

import spartan_tpu as st
from ..expr.base import as_expr
from ..expr.map2 import map2
from ..array import tiling as tiling_mod


def ssvd(a, rank: int, n_oversample: int = 10, n_power_iter: int = 2,
         seed: int = 0) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Approximate truncated SVD: returns (U, s, Vt) with U (m, rank)."""
    a = as_expr(a)
    m, n = a.shape
    k = min(rank + n_oversample, min(m, n))

    rng = np.random.RandomState(seed)
    omega = st.from_numpy(rng.randn(n, k).astype(np.float32),
                          tiling=tiling_mod.replicated(2))

    # sketch + power iterations, QR-stabilized each hop
    def qr_q(x):
        return jnp.linalg.qr(x)[0]

    y = st.dot(a, omega)
    q = map2([y], qr_q, out_tiling=tiling_mod.row(2))
    for _ in range(n_power_iter):
        z = st.dot(a.T, q)
        qz = map2([z], qr_q, out_tiling=tiling_mod.row(2))
        y = st.dot(a, qz)
        q = map2([y], qr_q, out_tiling=tiling_mod.row(2))

    # project to the small space and decompose there
    b = st.dot(q.T, a)  # (k, n)

    def small_svd(bv):
        u_b, s, vt = jnp.linalg.svd(bv, full_matrices=False)
        return jnp.concatenate([u_b, s[None, :], vt.T], axis=0)

    packed = map2([b], small_svd,
                  out_tiling=tiling_mod.replicated(2)).glom()
    u_b = packed[:k]
    s = packed[k]
    vt = packed[k + 1:].T

    u = st.dot(q, st.from_numpy(u_b)).glom()
    return u[:, :rank], s[:rank], vt[:rank]
