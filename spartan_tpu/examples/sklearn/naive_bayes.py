"""MultinomialNB estimator (reference: ``[U]
spartan/examples/sklearn/``)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from ...expr.base import as_expr
from ..naive_bayes import fit as nb_fit
from ..naive_bayes import predict as nb_predict


class MultinomialNB:
    def __init__(self, alpha: float = 1.0):
        self.alpha = alpha
        self.class_log_prior_: Optional[np.ndarray] = None
        self.feature_log_prob_: Optional[np.ndarray] = None

    def fit(self, x, y, n_classes: Optional[int] = None) -> "MultinomialNB":
        y_arr = np.asarray(as_expr(y).glom(), np.int32)
        if n_classes is None:
            n_classes = int(y_arr.max()) + 1
        self.class_log_prior_, self.feature_log_prob_ = nb_fit(
            as_expr(x), as_expr(y_arr), n_classes, self.alpha)
        return self

    def predict(self, x) -> np.ndarray:
        return nb_predict(as_expr(x), self.class_log_prior_,
                          self.feature_log_prob_).glom()
