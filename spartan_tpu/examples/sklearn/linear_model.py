"""Linear estimators (reference: ``[U] spartan/examples/sklearn/``)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from ...expr.base import as_expr
from ..regression import (linear_regression, logistic_regression,
                          predict_logistic, ridge_regression)
from ..svm import predict as svm_predict
from ..svm import svm


class LinearRegression:
    def __init__(self, max_iter: int = 100, lr: float = 1e-2):
        self.max_iter = max_iter
        self.lr = lr
        self.coef_: Optional[np.ndarray] = None

    def fit(self, x, y) -> "LinearRegression":
        self.coef_ = linear_regression(as_expr(x), as_expr(y),
                                       num_iter=self.max_iter, lr=self.lr)
        return self

    def predict(self, x) -> np.ndarray:
        return as_expr(x).dot(as_expr(self.coef_)).glom()


class Ridge(LinearRegression):
    def __init__(self, alpha: float = 1.0, max_iter: int = 100,
                 lr: float = 1e-2):
        super().__init__(max_iter, lr)
        self.alpha = alpha

    def fit(self, x, y) -> "Ridge":
        self.coef_ = ridge_regression(as_expr(x), as_expr(y),
                                      num_iter=self.max_iter, lr=self.lr,
                                      alpha=self.alpha)
        return self


class LogisticRegression:
    def __init__(self, max_iter: int = 100, lr: float = 0.1):
        self.max_iter = max_iter
        self.lr = lr
        self.coef_: Optional[np.ndarray] = None

    def fit(self, x, y) -> "LogisticRegression":
        self.coef_ = logistic_regression(as_expr(x), as_expr(y),
                                         num_iter=self.max_iter,
                                         lr=self.lr)
        return self

    def predict_proba(self, x) -> np.ndarray:
        return predict_logistic(as_expr(x), as_expr(self.coef_)).glom()

    def predict(self, x) -> np.ndarray:
        return (self.predict_proba(x) > 0.5).astype(np.int32)


class SGDSVC:
    """Linear SVM via primal sub-gradient descent."""

    def __init__(self, max_iter: int = 100, lr: float = 0.1,
                 reg: float = 1e-3):
        self.max_iter = max_iter
        self.lr = lr
        self.reg = reg
        self.coef_: Optional[np.ndarray] = None

    def fit(self, x, y) -> "SGDSVC":
        self.coef_ = svm(as_expr(x), as_expr(y), num_iter=self.max_iter,
                         lr=self.lr, reg=self.reg)
        return self

    def predict(self, x) -> np.ndarray:
        return svm_predict(as_expr(x), as_expr(self.coef_)).glom()
