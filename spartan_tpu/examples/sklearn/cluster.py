"""KMeans estimator (reference: ``[U] spartan/examples/sklearn/cluster``)."""

from __future__ import annotations

from typing import Optional

import numpy as np

from ...expr.base import as_expr
from ..kmeans import assign_points, kmeans


class KMeans:
    def __init__(self, n_clusters: int = 8, max_iter: int = 10,
                 random_state: int = 0):
        self.n_clusters = n_clusters
        self.max_iter = max_iter
        self.random_state = random_state
        self.cluster_centers_: Optional[np.ndarray] = None
        self.labels_: Optional[np.ndarray] = None

    def fit(self, x) -> "KMeans":
        centers, labels = kmeans(as_expr(x), self.n_clusters,
                                 num_iter=self.max_iter,
                                 seed=self.random_state)
        self.cluster_centers_ = centers
        self.labels_ = labels
        return self

    def predict(self, x) -> np.ndarray:
        if self.cluster_centers_ is None:
            raise RuntimeError("call fit first")
        return assign_points(as_expr(x),
                             as_expr(self.cluster_centers_)).glom()

    def fit_predict(self, x) -> np.ndarray:
        return self.fit(x).labels_
