"""sklearn-compatible estimator wrappers (reference:
``[U] spartan/examples/sklearn/`` — SURVEY.md §2.4: "a small
sklearn-compatible wrapper subpackage").

Thin fit/predict classes over the example drivers; inputs may be NumPy
arrays, DistArrays or exprs.
"""

from .cluster import KMeans
from .linear_model import LinearRegression, LogisticRegression, Ridge, SGDSVC
from .naive_bayes import MultinomialNB

__all__ = ["KMeans", "LinearRegression", "LogisticRegression", "Ridge",
           "SGDSVC", "MultinomialNB"]
