"""Linear SVM via primal sub-gradient descent (reference:
``[U] spartan/examples/svm.py`` — SURVEY.md §2.4).

Hinge-loss gradient over the batch-sharded data; one step = one traced
computation with a psum'd gradient (the DP pattern of SURVEY.md §2.6).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

import spartan_tpu as st
from ..array import tiling as tiling_mod
from ..expr.base import Expr, ValExpr, as_expr
from ..expr.map2 import map2

_REPL1 = tiling_mod.replicated(1)


def svm_grad(x: Expr, y: Expr, w: Expr, reg: float) -> Expr:
    """y in {-1, +1}; sub-gradient of mean hinge loss + L2."""

    def kern(xv, yv, wv):
        margin = yv * (xv @ wv)
        active = (margin < 1.0).astype(xv.dtype)
        g = -(xv.T @ (active * yv)) / xv.shape[0]
        return g + reg * wv

    return map2([x, y, w], kern, out_tiling=_REPL1)


def svm(x, y, num_iter: int = 100, lr: float = 0.1, reg: float = 1e-3
        ) -> np.ndarray:
    x, y = as_expr(x), as_expr(y)
    w: Expr = st.zeros((x.shape[1],), np.float32, tiling=_REPL1)
    for _ in range(num_iter):
        g = svm_grad(x, y, w, reg)
        w = ValExpr((w - lr * g).evaluate())
    return w.glom()


def predict(x, w) -> Expr:
    x, w = as_expr(x), as_expr(w)
    return map2([x, w], lambda xv, wv: jnp.sign(xv @ wv),
                out_tiling=tiling_mod.Tiling((x.out_tiling().axes[0],)))
