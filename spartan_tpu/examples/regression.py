"""Linear / ridge / logistic regression (config 4, BASELINE.json:10;
reference: ``[U] spartan/examples/`` linear_regression, ridge_regression,
logistic_regression).

The reference computed per-tile gradients with map + reduce (the gradient
all-reduce analogue, SURVEY.md §2.6 DP row). Here each SGD step is one
traced computation over batch-sharded X, y: local matmul + psum gradient
— the canonical data-parallel pattern.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

import spartan_tpu as st
from ..array import tiling as tiling_mod
from ..expr.base import Expr, ValExpr, as_expr
from ..expr.map2 import map2

_REPL1 = tiling_mod.replicated(1)


def linear_grad(x: Expr, y: Expr, w: Expr) -> Expr:
    """d/dw of 0.5*||Xw - y||^2 / n  (lazy)."""

    def kern(xv, yv, wv):
        err = xv @ wv - yv
        return xv.T @ err / xv.shape[0]

    return map2([x, y, w], kern, out_tiling=_REPL1)


def logistic_grad(x: Expr, y: Expr, w: Expr) -> Expr:
    """Gradient of mean logistic loss, y in {0,1}."""

    def kern(xv, yv, wv):
        p = jax.nn.sigmoid(xv @ wv)
        return xv.T @ (p - yv) / xv.shape[0]

    return map2([x, y, w], kern, out_tiling=_REPL1)


def linear_regression(x, y, num_iter: int = 10, lr: float = 1e-2,
                      ridge: float = 0.0, fused: bool = True) -> np.ndarray:
    x, y = as_expr(x), as_expr(y)
    w: Expr = st.zeros((x.shape[1],), np.float32, tiling=_REPL1)

    def step(w: Expr) -> Expr:
        g = linear_grad(x, y, w)
        if ridge:
            g = g + ridge * w
        return w - lr * g

    if fused:
        # whole SGD run = ONE program (st.loop -> fori_loop): no
        # per-iteration dispatch (contrast SURVEY.md §3.4)
        return st.loop(num_iter, step, w).glom()
    for _ in range(num_iter):
        w = ValExpr(step(w).evaluate())
    return w.glom()


def ridge_regression(x, y, num_iter: int = 10, lr: float = 1e-2,
                     alpha: float = 1.0) -> np.ndarray:
    return linear_regression(x, y, num_iter, lr, ridge=alpha)


def logistic_regression(x, y, num_iter: int = 10, lr: float = 1e-1,
                        fused: bool = True) -> np.ndarray:
    x, y = as_expr(x), as_expr(y)
    w: Expr = st.zeros((x.shape[1],), np.float32, tiling=_REPL1)
    step = lambda w: w - lr * logistic_grad(x, y, w)  # noqa: E731
    if fused:
        return st.loop(num_iter, step, w).glom()
    for _ in range(num_iter):
        w = ValExpr(step(w).evaluate())
    return w.glom()


def predict_logistic(x, w) -> Expr:
    x, w = as_expr(x), as_expr(w)
    return map2([x, w], lambda xv, wv: jax.nn.sigmoid(xv @ wv),
                out_tiling=tiling_mod.Tiling((x.out_tiling().axes[0],)))

