"""Topic modeling by EM (reference family: ``[U]
spartan/examples/lda.py`` — SURVEY.md §2.4 application tier).

Multinomial-mixture / pLSI-style EM with Dirichlet pseudocount
smoothing (the collapsed-variational flavor of LDA's update without
per-token sampling — samplers are hostile to XLA; this formulation is
pure matmuls + elementwise). The (D, W, K) responsibility tensor is
never materialized: the K loop builds each topic's (D, W)
responsibility slice as a lazy expr chain, so one fused XLA program
per topic per iteration does the E and M contributions together,
owner-computes on the doc-sharded count matrix.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

import spartan_tpu as st
from ..expr.base import ValExpr, as_expr, tuple_of


def lda(counts, k: int, num_iter: int = 30, alpha: float = 0.1,
        beta: float = 0.01, seed: int = 0
        ) -> Tuple[np.ndarray, np.ndarray]:
    """Fit a k-topic model to a (D, W) document-term count matrix.

    Returns (theta, phi): theta (D, k) per-document topic mixtures and
    phi (k, W) topic-word distributions, both row-normalized.
    """
    counts = as_expr(counts)
    d, w = counts.shape
    rng = np.random.RandomState(seed)
    theta = rng.rand(d, k).astype(np.float32) + 0.5
    theta /= theta.sum(axis=1, keepdims=True)
    phi = rng.rand(k, w).astype(np.float32) + 0.5
    phi /= phi.sum(axis=1, keepdims=True)

    for _ in range(num_iter):
        theta_e = as_expr(theta)
        phi_e = as_expr(phi)
        # denom[d, w] = sum_k theta[d, k] phi[k, w] — one sharded GEMM
        denom = ValExpr(st.dot(theta_e, phi_e).evaluate())
        new_theta = np.empty_like(theta)
        new_phi = np.empty_like(phi)
        for t in range(k):
            # responsibility slice r_t = C * (theta_t phi_t) / denom;
            # both reductions evaluate as ONE multi-output program so
            # the (D, W) elementwise chain runs once per topic
            outer_t = st.outer_product(theta_e[:, t], phi_e[t, :])
            r_t = counts * outer_t / st.maximum(denom, 1e-30)
            phi_row, theta_col = tuple_of(
                r_t.sum(axis=0), r_t.sum(axis=1)).evaluate()
            new_phi[t, :] = np.asarray(phi_row.glom())
            new_theta[:, t] = np.asarray(theta_col.glom())
        theta = new_theta + alpha
        theta /= theta.sum(axis=1, keepdims=True)
        phi = new_phi + beta
        phi /= phi.sum(axis=1, keepdims=True)
    return theta, phi


def log_likelihood(counts, theta: np.ndarray, phi: np.ndarray) -> float:
    """Observed-data log likelihood sum_dw C[d,w] log(theta phi)[d,w]."""
    counts = as_expr(counts)
    mix = st.dot(as_expr(theta), as_expr(phi))
    ll = (counts * st.log(st.maximum(mix, 1e-30))).sum()
    return float(ll.glom())
