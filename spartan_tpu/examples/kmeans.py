"""k-means clustering (config 3, BASELINE.json:9; reference:
``[U] spartan/examples/kmeans.py``, call stack SURVEY.md §3.4).

TPU-first re-design: the reference crossed driver<->worker per iteration
(map2 argmin per tile, shuffle/reduce of k x d partials, glom of the new
centers). Here one whole iteration — distances, argmin, segment-sum,
count, center update — is a single traced computation: the argmin runs
owner-computes on the point shards, the k x d partial sums become an XLA
all-reduce over the batch mesh axis, and the loop stays on device.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

import spartan_tpu as st
from ..array import tiling as tiling_mod
from ..expr.base import Expr, ValExpr, as_expr
from ..expr.map2 import map2


def _assign_and_accumulate(k: int):
    """Kernel: points (n, d), centers (k, d) -> (k, d+1) [sums | counts].

    Chunked over points so the (n, k) distance matrix never materializes
    for huge n; XLA fuses the distance + argmin + segment-sum chain."""

    def kern(points, centers):
        # HIGHEST so assignments match the f32 oracle (default-precision
        # MXU rounds through bf16: measured 1e-2 center error after one
        # iteration vs 1e-7 at highest)
        d2 = (jnp.sum(points * points, axis=1, keepdims=True)
              - 2.0 * jnp.matmul(points, centers.T, precision="highest")
              + jnp.sum(centers * centers, axis=1)[None, :])
        assign = jnp.argmin(d2, axis=1)
        sums = jax.ops.segment_sum(points, assign, num_segments=k)
        counts = jax.ops.segment_sum(
            jnp.ones((points.shape[0],), points.dtype), assign,
            num_segments=k)
        return jnp.concatenate([sums, counts[:, None]], axis=1)

    return kern


def kmeans_step(points: Expr, centers: Expr, k: int) -> Expr:
    """One iteration: returns the new (k, d) centers as a lazy expr."""
    acc = map2([points, centers], _assign_and_accumulate(k),
               out_tiling=tiling_mod.replicated(2))
    sums = acc[:, :-1]
    counts = acc[:, -1:]
    return sums / st.maximum(counts, 1.0)


def assign_points(points: Expr, centers: Expr) -> Expr:
    """Cluster id per point (owner-computes on the point shards)."""

    def kern(p, c):
        d2 = (jnp.sum(p * p, axis=1, keepdims=True)
              - 2.0 * jnp.matmul(p, c.T, precision="highest")
              + jnp.sum(c * c, axis=1)[None, :])
        return jnp.argmin(d2, axis=1)

    return map2([points, centers], kern,
                out_tiling=tiling_mod.Tiling((points.out_tiling().axes[0],)))


def _kernel_pad(n: int) -> int:
    """Pad rows so every mesh row shard holds whole 1024-point blocks
    (the kernel is per-shard now — docs/KERNELS.md)."""
    from ..ops import kmeans as kmeans_kernel
    from ..parallel import mesh as mesh_mod

    p = max(int(mesh_mod.get_mesh().shape.get(
        tiling_mod.AXIS_ROW, 1)), 1)
    q = p * kmeans_kernel._BLOCK
    return -(-n // q) * q


def _kernel_supports(n: int, d: int, k: int) -> bool:
    from ..ops import kmeans as kmeans_kernel

    return kmeans_kernel.supports(_kernel_pad(n), d, k)


def kmeans(points, k: int, num_iter: int = 10,
           centers: Optional[np.ndarray] = None, seed: int = 0,
           fused: bool = True) -> Tuple[np.ndarray, np.ndarray]:
    """Full driver loop.

    ``fused`` (default) runs ALL iterations as one on-device
    ``st.loop``/fori_loop program — one dispatch, one fetch, removing
    the reference's per-iteration driver<->worker round trips
    (SURVEY.md §3.4). ``fused=False`` keeps the
    'python-loop-over-jit' shape; each step then hits the expr compile
    cache after the first iteration."""
    points = as_expr(points)
    n, d = points.shape
    if centers is None:
        rng = np.random.RandomState(seed)
        idx = rng.choice(n, size=k, replace=False)
        first = points[np.sort(idx)].glom()
        centers_e: Expr = as_expr(first)
    else:
        centers_e = as_expr(np.asarray(centers, np.float32))
    if fused and _kernel_supports(n, d, k):
        # fused Pallas iteration kernel: distances + argmin + one-hot
        # accumulate stream through VMEM once per iteration; 4 ms/iter
        # at 1M x 128, k=64 on v5e vs 18.6 ms for the XLA-fused loop
        from ..ops import kmeans as kmeans_kernel

        pts = points.evaluate().jax_array
        npad = _kernel_pad(n)
        if npad != n:
            pts = jnp.concatenate(
                [pts, jnp.zeros((npad - n, d), pts.dtype)])
        out = kmeans_kernel.run(pts, centers_e.evaluate().jax_array, k,
                                jnp.int32(num_iter),
                                valid_rows=n if npad != n else None)
        centers_e = as_expr(out)
    elif fused:
        centers_e = ValExpr(st.loop(
            num_iter, lambda c: kmeans_step(points, c, k),
            centers_e).evaluate())
    else:
        for _ in range(num_iter):
            centers_e = kmeans_step(points, centers_e, k)
            # force so the next iteration starts from a Val leaf (the
            # collapse-cached pass keeps the DAG constant-size)
            centers_e = ValExpr(centers_e.evaluate())
    final = centers_e.glom()
    assign = assign_points(points, centers_e).glom()
    return final, assign
