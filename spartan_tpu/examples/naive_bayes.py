"""Multinomial naive Bayes (reference: ``[U]
spartan/examples/naive_bayes.py`` — SURVEY.md §2.4).

Fitting is one segment-sum of feature counts by class (the reference's
shuffle/reduce merge) + log-prior/likelihood tables; prediction is a
replicated table matmul over the batch-sharded features.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

import spartan_tpu as st
from ..array import tiling as tiling_mod
from ..expr.base import Expr, as_expr
from ..expr.map2 import map2
from ..ops.segment import segment_count, segment_sum


def fit(x, y, n_classes: int, alpha: float = 1.0
        ) -> Tuple[np.ndarray, np.ndarray]:
    """x: (n, d) nonnegative counts; y: (n,) int labels.
    Returns (log_prior (c,), log_likelihood (c, d))."""
    x, y = as_expr(x), as_expr(y)

    def kern(xv, yv):
        counts = segment_sum(xv, yv, n_classes)
        class_n = segment_count(yv, n_classes, dtype=xv.dtype)
        return jnp.concatenate([counts, class_n[:, None]], axis=1)

    packed = map2([x, y], kern,
                  out_tiling=tiling_mod.replicated(2)).glom()
    counts = packed[:, :-1]
    class_n = packed[:, -1]
    smoothed = counts + alpha
    log_lik = np.log(smoothed / smoothed.sum(axis=1, keepdims=True))
    log_prior = np.log(np.maximum(class_n, 1e-12) / class_n.sum())
    return log_prior.astype(np.float32), log_lik.astype(np.float32)


def predict(x, log_prior: np.ndarray, log_lik: np.ndarray) -> Expr:
    x = as_expr(x)
    ep = st.from_numpy(log_prior, tiling=tiling_mod.replicated(1))
    el = st.from_numpy(log_lik, tiling=tiling_mod.replicated(2))
    return map2([x, ep, el],
                lambda xv, pv, lv: jnp.argmax(xv @ lv.T + pv[None, :],
                                              axis=1),
                out_tiling=tiling_mod.Tiling((x.out_tiling().axes[0],)))
