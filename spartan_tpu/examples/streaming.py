"""Streaming drivers over the delta-aware incremental engine
(docs/INCREMENTAL.md; ISSUE 16's consumer layer).

Each driver keeps its state in :class:`DistArray` handles and applies
new data through the mutation seam — ``DistArray.update()`` /
``st.assign`` — so the per-step DAGs keep hitting the plan cache AND
the incremental engine (``FLAGS.incremental``) can serve warm steps
from the per-plan result cache, recomputing only the tiles each batch
actually dirtied. The drivers compose with the rest of the stack:
multi-step refinements run through ``st.loop`` (one on-device program,
checkpoint/resume), and every ``*_async`` entry point submits through
``serve/`` (``evaluate_async``: admission control, coalescing, flight
recording; solo serve dispatches route through ``evaluate()`` and so
stay incremental).

What is (and is not) delta-scaled — the honest contract:

* :class:`IncrementalPageRank` — the per-batch correction step after
  ``insert_edges`` IS delta-scaled: the base rank vector is held fixed
  for a rebase window, so only the transition matrix's dirty columns
  changed since the cached step and the engine restricts the matvec to
  them (the acceptance benchmark's ≥5x warm-step speedup at ≤1% dirty).
  Every ``rebase_every`` batches the driver folds the estimate into a
  new base (a full recompute) — a standard streaming rebase window.
* :class:`OnlineKMeans` — every batch is new data (whole-batch dirty),
  so assignment steps are full dispatches; the wins here are the plan
  cache (fixed batch shape -> zero recompiles) and ``st.loop`` refine.
* :class:`SlidingWindowStats` — a windowed reduction needs every
  element, so ``stats()`` after a push is a full (cheap, small-output)
  dispatch; but repeated ``stats()``/``normalized()`` calls BETWEEN
  pushes are all-clean result-cache hits (zero dispatch), which is the
  common read-heavy monitoring pattern.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np

from ..array import distarray as da_mod
from ..array.distarray import DistArray
from ..expr import base as expr_base
from ..expr.base import lazify


def _dist(x: Any) -> DistArray:
    if isinstance(x, DistArray):
        return x
    return da_mod.from_numpy(np.asarray(x))


class IncrementalPageRank:
    """Dense-transition PageRank over edge-insert batches.

    Holds a column-stochastic transition matrix ``A`` (n, n) where
    ``A[i, j]`` is the probability of moving from page i to page j,
    and a rank estimate. ``insert_edges(col, new_column)`` replaces one
    or more pages' in-link columns through ``DistArray.update`` — the
    lineage-recorded delta. ``step()`` evaluates one damped power-
    iteration correction ``r' = d * (r0 @ A) + (1-d)/n`` against the
    FIXED base vector ``r0``: with ``FLAGS.incremental`` on, the warm
    step recomputes only ``r0 @ A[:, dirty]`` and splices it into the
    cached product, so per-batch cost scales with the edge delta, not
    the graph. (For the sparse/Pallas batch path see
    examples/pagerank.py — this driver is the dense streaming
    counterpart the incremental engine can see through.)
    """

    def __init__(self, transition: Any, damping: float = 0.85,
                 rebase_every: int = 8):
        self.A = _dist(transition)
        n = self.A.shape[0]
        if self.A.shape != (n, n):
            raise ValueError(f"transition must be square, got "
                             f"{self.A.shape}")
        self.n = n
        self.damping = float(damping)
        self.rebase_every = int(rebase_every)
        self._base = da_mod.from_numpy(
            np.full((n,), 1.0 / n, self.A.dtype))  # r0, held fixed
        self.ranks: DistArray = self._base
        self._batches_since_rebase = 0

    def _step_expr(self):
        from ..expr.dot import DotExpr

        prod = DotExpr(lazify(self._base), lazify(self.A))
        return prod * self.damping + (1.0 - self.damping) / self.n

    def insert_edges(self, cols: slice, values: Any) -> None:
        """Replace the in-link columns ``A[:, cols]`` (already
        column-stochastic) — one lineage-logged region write."""
        self.A = self.A.update((slice(0, self.n), cols),
                               np.asarray(values, self.A.dtype))
        self._batches_since_rebase += 1

    def step(self) -> DistArray:
        """One damped correction against the fixed base vector —
        the delta-scaled warm step. Rebases when the window is up."""
        if self._batches_since_rebase >= self.rebase_every:
            self.rebase()
        self.ranks = expr_base.evaluate(self._step_expr())
        return self.ranks

    def step_async(self, tenant: Optional[str] = None):
        """The serve/ route: submit the correction step to the
        concurrent engine (admission, flight recording); solo serve
        dispatches evaluate() underneath and stay incremental."""
        return self._step_expr().evaluate_async(tenant=tenant)

    def rebase(self, iters: int = 4) -> DistArray:
        """Fold the current estimate into a new base with ``iters``
        full power iterations in ONE on-device program (st.loop) —
        the full-recompute end of the streaming window."""
        from ..expr.loop import loop as st_loop

        A = lazify(self.A)
        d, n = self.damping, self.n
        out = st_loop(
            iters, lambda r: r.dot(A) * d + (1.0 - d) / n,
            lazify(self.ranks))
        self._base = expr_base.evaluate(out)
        self.ranks = self._base
        self._batches_since_rebase = 0
        return self._base


class OnlineKMeans:
    """Mini-batch k-means (Sculley 2010 style) over streaming batches.

    ``partial_fit(batch)`` assigns the batch to the current centers and
    moves each center toward its batch mean with a per-center learning
    rate 1/count — one dispatched program per batch, plan-cached across
    batches of the same shape. ``refine`` runs full Lloyd iterations
    over a reference point set through ``st.loop``.
    """

    def __init__(self, centers: Any):
        self.centers = _dist(centers)
        self.k, self.d = self.centers.shape
        self._counts = da_mod.from_numpy(
            np.ones((self.k,), self.centers.dtype))

    def partial_fit(self, batch: Any) -> DistArray:
        import jax
        import jax.numpy as jnp

        from ..expr.map2 import map2

        pts = _dist(np.asarray(batch, self.centers.dtype))
        k = self.k

        def kern(points, centers, counts):
            d2 = (jnp.sum(points * points, axis=1, keepdims=True)
                  - 2.0 * jnp.matmul(points, centers.T,
                                     precision="highest")
                  + jnp.sum(centers * centers, axis=1)[None, :])
            assign = jnp.argmin(d2, axis=1)
            sums = jax.ops.segment_sum(points, assign, num_segments=k)
            cnt = jax.ops.segment_sum(
                jnp.ones((points.shape[0],), points.dtype), assign,
                num_segments=k)
            new_counts = counts + cnt
            lr = (cnt / new_counts)[:, None]
            mean = sums / jnp.maximum(cnt, 1.0)[:, None]
            moved = jnp.where(cnt[:, None] > 0,
                              centers * (1.0 - lr) + mean * lr,
                              centers)
            return jnp.concatenate([moved, new_counts[:, None]], axis=1)

        from ..array import tiling as tiling_mod

        packed = expr_base.evaluate(map2(
            [lazify(pts), lazify(self.centers), lazify(self._counts)],
            kern, out_tiling=tiling_mod.replicated(2)))
        host = np.asarray(packed.jax_array)
        self.centers = da_mod.from_numpy(host[:, :-1])
        self._counts = da_mod.from_numpy(host[:, -1])
        return self.centers

    def refine(self, points: Any, iters: int = 5) -> DistArray:
        """Full Lloyd iterations over ``points`` as ONE on-device
        st.loop program (checkpoint/resume-capable)."""
        from ..examples.kmeans import kmeans_step
        from ..expr.loop import loop as st_loop

        pts = lazify(_dist(points))
        out = st_loop(
            iters, lambda c: kmeans_step(pts, c, self.k),
            lazify(self.centers))
        self.centers = expr_base.evaluate(out)
        return self.centers


class SlidingWindowStats:
    """Per-feature mean/std over a ring-buffer window (w, d).

    ``push(rows)`` overwrites the oldest slots through
    ``DistArray.update`` (lineage-logged); ``stats()`` reduces the
    window. A windowed reduction touches every element, so the
    post-push ``stats()`` is a full (small) dispatch — but every
    read between pushes is an all-clean result-cache hit with zero
    dispatch, and ``normalized()`` (elementwise over the window) IS
    delta-scaled to the rows the last push dirtied.
    """

    def __init__(self, window: int, dim: int, dtype: Any = np.float32):
        self.window = int(window)
        self.dim = int(dim)
        self.buf = da_mod.from_numpy(
            np.zeros((self.window, self.dim), dtype))
        self._head = 0
        self._filled = 0

    def push(self, rows: Any) -> None:
        rows = np.asarray(rows, self.buf.dtype)
        if rows.ndim == 1:
            rows = rows[None, :]
        r = 0
        while r < len(rows):
            take = min(len(rows) - r, self.window - self._head)
            self.buf = self.buf.update(
                (slice(self._head, self._head + take),
                 slice(0, self.dim)),
                rows[r:r + take])
            self._head = (self._head + take) % self.window
            r += take
        self._filled = min(self.window, self._filled + len(rows))

    def stats(self) -> Tuple[DistArray, DistArray]:
        """(mean, std) per feature over the window — one plan-cached
        dispatch after a push, a zero-dispatch cache hit otherwise."""
        x = lazify(self.buf)
        mean = expr_base.evaluate(x.mean(axis=0))
        var = expr_base.evaluate(((x - lazify(mean)) ** 2).mean(axis=0))
        std = expr_base.evaluate(lazify(var) ** 0.5)
        return mean, std

    def stats_async(self, tenant: Optional[str] = None):
        """serve/ route for read-heavy monitors: mean through the
        concurrent engine (coalesces identical concurrent readers)."""
        return lazify(self.buf).mean(axis=0).evaluate_async(
            tenant=tenant)

    def normalized(self, mean: DistArray, std: DistArray) -> DistArray:
        """(window - mean) / std — elementwise over the big buffer, so
        a warm call after a push recomputes only the pushed rows."""
        x = lazify(self.buf)
        return expr_base.evaluate(
            (x - lazify(mean)) / (lazify(std) + 1e-12))
