"""Lanczos bidiagonalization SVD (reference family: ``[U]
spartan/examples/lanczos.py`` — the iterative large-matrix SVD beside
SSVD in SURVEY.md §2.4's application tier).

TPU-first shape: the two matrix products per Lanczos step (``A @ v``
and ``A.T @ u``) run as sharded ``st.dot`` programs over the mesh —
the only O(mn) work — while the O(k2) bidiagonal bookkeeping
(orthogonalization coefficients, the small SVD of B) stays on the
driver in NumPy, exactly the big/small split the reference's
master/worker version had (workers did the matvecs, the master the
recurrence). Matvecs run at HIGHEST precision: the recurrence
amplifies bf16-multiply rounding into loss of orthogonality.
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

import spartan_tpu as st
from ..expr.base import as_expr


def lanczos_bidiag(a, k: int, seed: int = 0
                   ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """k-step Golub-Kahan bidiagonalization of A (m, n).

    Returns (U, B, V): U (m, k+1) and V (n, k) with orthonormal
    columns (full reorthogonalization — numerically safe at the small
    k this is meant for) and B (k+1, k) lower-bidiagonal with
    A @ V ~= U @ B.
    """
    a = as_expr(a)
    m, n = a.shape
    k = min(k, min(m, n))
    rng = np.random.RandomState(seed)
    u = rng.randn(m).astype(np.float32)
    u /= np.linalg.norm(u)
    us = [u]
    vs = []
    alphas = []
    betas = []
    for j in range(k):
        # v_j = A^T u_j - beta_{j-1} v_{j-1}, reorthogonalized
        v = np.array(st.dot(a.T, as_expr(us[-1]),
                     precision="highest").glom())
        for prev in vs:  # full reorth (k is small)
            v -= prev * float(prev @ v)
        alpha = float(np.linalg.norm(v))
        if alpha < 1e-12:
            break
        v /= alpha
        vs.append(v)
        alphas.append(alpha)
        # u_{j+1} = A v_j - alpha_j u_j, reorthogonalized
        u = np.array(st.dot(a, as_expr(v),
                     precision="highest").glom())
        for prev in us:
            u -= prev * float(prev @ u)
        beta = float(np.linalg.norm(u))
        if beta < 1e-12:
            betas.append(0.0)
            break
        u /= beta
        us.append(u)
        betas.append(beta)
    if not vs:
        raise ValueError(
            "Lanczos breakdown at step 0: A^T u is (numerically) zero "
            "— the matrix has no Krylov direction (all-zero input?)")
    k_eff = len(alphas)
    B = np.zeros((len(us), k_eff), np.float32)
    for j in range(k_eff):
        B[j, j] = alphas[j]
        if j + 1 < len(us):
            B[j + 1, j] = betas[j]
    return (np.stack(us, axis=1).astype(np.float32), B,
            np.stack(vs, axis=1).astype(np.float32))


def lanczos_svd(a, rank: int, extra: int = 6, seed: int = 0
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Top-``rank`` singular triplets via ``rank + extra`` Lanczos
    steps and the small SVD of the bidiagonal B."""
    a = as_expr(a)
    U, B, V = lanczos_bidiag(a, rank + extra, seed=seed)
    ub, s, vbt = np.linalg.svd(B, full_matrices=False)
    r = min(rank, s.size)
    return ((U @ ub[:, :r]).astype(np.float32), s[:r].astype(np.float32),
            (V @ vbt.T[:, :r]).astype(np.float32))
