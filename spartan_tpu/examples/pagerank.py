"""Sparse PageRank (config 5, BASELINE.json:11; reference:
``[U] spartan/examples/pagerank.py``).

The reference iterated rank = d * A^T rank + (1-d)/n with per-tile sparse
kernels and shuffle merges. Here A^T is a :class:`SparseDistArray`; each
power iteration is one jitted SpMV (gather on the entry shards +
segment-merge) plus the teleport term.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..array.sparse import SparseDistArray


def pagerank(links: SparseDistArray, damping: float = 0.85,
             num_iter: int = 20, tol: float = 0.0) -> np.ndarray:
    """links[i, j] != 0 means page i links to page j. Returns ranks."""
    n = links.shape[0]
    # column-stochastic transition: T = (A / outdegree)^T
    out_deg = np.asarray(jax.device_get(links.rsums()))
    inv = np.where(out_deg > 0, 1.0 / np.maximum(out_deg, 1e-30), 0.0)
    T = links.scale_rows(inv.astype(np.float32)).transpose()

    rank = jnp.full((n,), 1.0 / n, jnp.float32)
    teleport = (1.0 - damping) / n
    for _ in range(num_iter):
        new = damping * T.spmv(rank) + teleport
        # dangling mass: pages with no outlinks redistribute uniformly
        dangling = 1.0 - float(new.sum())
        new = new + dangling / n
        if tol > 0 and float(jnp.abs(new - rank).sum()) < tol:
            rank = new
            break
        rank = new
    return np.asarray(jax.device_get(rank))
