"""Sparse PageRank (config 5, BASELINE.json:11; reference:
``[U] spartan/examples/pagerank.py``).

The reference iterated rank = d * A^T rank + (1-d)/n with per-tile sparse
kernels and shuffle merges. Here A^T is a :class:`SparseDistArray`; each
power iteration is one jitted SpMV (gather on the entry shards +
segment-merge) plus the teleport term.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..array.sparse import SparseDistArray


@functools.partial(jax.jit, static_argnames=("n",))
def _teleport(y, damping, *, n):
    """Teleport + dangling-mass correction. Kept in a SEPARATE jit from
    the SpMV: fusing elementwise ops into the BCOO matvec program makes
    XLA drop the fast sparse lowering (measured 294 -> 1705 ms at 16M
    entries on v5e)."""
    new = damping * y + (1.0 - damping) / n
    dangling = 1.0 - jnp.sum(new)
    return new + dangling / n


def pagerank(links: SparseDistArray, damping: float = 0.85,
             num_iter: int = 20, tol: float = 0.0) -> np.ndarray:
    """links[i, j] != 0 means page i links to page j. Returns ranks."""
    n = links.shape[0]
    # column-stochastic transition: T = (A / outdegree)^T
    out_deg = np.asarray(jax.device_get(links.rsums()))
    inv = np.where(out_deg > 0, 1.0 / np.maximum(out_deg, 1e-30), 0.0)
    T = links.scale_rows(inv.astype(np.float32)).transpose()

    rank = jnp.full((n,), 1.0 / n, jnp.float32)
    damp = jnp.float32(damping)
    for _ in range(num_iter):
        new = _teleport(T.spmv(rank), damp, n=n)
        if tol > 0:
            # convergence check costs one host fetch per iteration
            delta = float(jnp.abs(new - rank).sum())
            rank = new
            if delta < tol:
                break
        else:
            rank = new
    return np.asarray(jax.device_get(rank))
