"""Sparse PageRank (config 5, BASELINE.json:11; reference:
``[U] spartan/examples/pagerank.py``).

The reference iterated rank = d * A^T rank + (1-d)/n with per-tile sparse
kernels and shuffle merges. Here A^T is a :class:`SparseDistArray`; each
power iteration is one jitted SpMV (gather on the entry shards +
segment-merge) plus the teleport term.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..array.sparse import SparseDistArray


def _teleport_body(y, damping, n):
    new = damping * y + (1.0 - damping) / n
    dangling = 1.0 - jnp.sum(new)
    return new + dangling / n


@functools.partial(jax.jit, static_argnames=("n",))
def _teleport(y, damping, *, n):
    """Teleport + dangling-mass correction. Kept in a SEPARATE jit from
    the SpMV on the BCOO fallback path: fusing elementwise ops into the
    BCOO matvec program makes XLA drop the fast sparse lowering
    (measured 294 -> 1705 ms at 16M entries on v5e)."""
    return _teleport_body(y, damping, n)


def pagerank(links: SparseDistArray, damping: float = 0.85,
             num_iter: int = 20, tol: float = 0.0,
             transition: Optional[SparseDistArray] = None) -> np.ndarray:
    """links[i, j] != 0 means page i links to page j. Returns ranks.

    On TPU (windowed spmv available, no convergence checks) the whole
    power iteration runs as ONE dispatched program: a ``lax.fori_loop``
    of windowed-spmv + teleport steps. This is only possible because the
    windowed kernel keeps its speed inside ``fori_loop`` — XLA's own
    sparse lowerings degrade ~10x there — and it removes the per-
    iteration dispatch round trip (~50 ms on a tunneled platform).

    ``transition`` lets callers pass a precomputed column-stochastic
    matrix; by default ``links.transition()`` builds it once and caches
    it on ``links`` (host-side restructuring — the transpose re-sorts
    all entries; see SparseDistArray.transition / clear_cache)."""
    n = links.shape[0]
    T = transition if transition is not None else links.transition()

    rank = jnp.full((n,), 1.0 / n, jnp.float32)
    damp = jnp.float32(damping)
    if tol == 0 and T._default_windowed():
        return np.asarray(jax.device_get(
            _pagerank_fused(T, rank, damp, num_iter)))
    for _ in range(num_iter):
        new = _teleport(T.spmv(rank), damp, n=n)
        if tol > 0:
            # convergence check costs one host fetch per iteration
            delta = float(jnp.abs(new - rank).sum())
            rank = new
            if delta < tol:
                break
        else:
            rank = new
    return np.asarray(jax.device_get(rank))


@functools.partial(jax.jit, static_argnames=(
    "n", "num_segments", "rows_pad", "nsteps", "outblk", "sub"))
def _pagerank_loop(pdata, pcols, ids2d, wb, rank, damp, iters, *,
                   n, num_segments, rows_pad, nsteps, outblk, sub):
    """Module-level jit: plan buffers are traced arguments, so matrices
    with the same plan dimensions share one compile (the Pallas-in-loop
    program costs ~2 min to build) and nothing pins device memory."""
    from ..ops.segment import _windowed_segsum

    def body(_, r):
        out2d = _windowed_segsum(pdata * r[pcols], ids2d, wb,
                                 rows_pad=rows_pad, nsteps=nsteps,
                                 outblk=outblk, sub=sub)
        return _teleport_body(out2d.reshape(-1)[:num_segments], damp, n)

    return jax.lax.fori_loop(0, iters, body, rank)


def _pagerank_fused(T: SparseDistArray, rank, damp, num_iter: int):
    """One dispatch for the whole power iteration; the iteration count
    is a traced loop bound so every num_iter shares one compile."""
    plan = T._ensure_plan()
    return _pagerank_loop(
        T._pdata, T._pcols, plan._ids2d, plan._wb, rank, damp,
        jnp.int32(num_iter), n=T.shape[0],
        num_segments=plan.num_segments, rows_pad=plan.rows_pad,
        nsteps=plan.nsteps, outblk=plan.outblk, sub=plan.SUB)
