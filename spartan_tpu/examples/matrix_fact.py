"""SGD matrix factorization on sparse ratings (reference:
``[U] spartan/examples/netflix.py`` — the "netflix SGD matrix
factorization" example of SURVEY.md §2.4).

R (users x items, sparse COO) ~= U @ V^T, trained by minibatch SGD over
the observed entries. TPU-first design: the reference runs per-tile
Hogwild-style SGD kernels over rating blocks with factor rows shipped by
RPC; here one epoch is a single traced computation — ``lax.fori_loop``
over static-size entry batches, factor rows gathered with ``take`` and
updated with scatter-add (``.at[].add``), so the whole epoch is one
device dispatch with no host round trips. Padded entries carry
``row == n_users`` (see :class:`SparseDistArray`) and fall out of every
scatter via out-of-bounds drop semantics.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..array.sparse import SparseDistArray


@functools.partial(jax.jit, static_argnames=("batch", "lr", "reg"))
def _sgd_epoch(u, v, rows, cols, vals, *, batch, lr, reg):
    n_batches = rows.shape[0] // batch

    def body(i, uv):
        uu, vv = uv
        r = jax.lax.dynamic_slice_in_dim(rows, i * batch, batch)
        c = jax.lax.dynamic_slice_in_dim(cols, i * batch, batch)
        x = jax.lax.dynamic_slice_in_dim(vals, i * batch, batch)
        # mode='fill' zeroes gathers of padding entries (row >= n_users)
        ur = uu.at[r].get(mode="fill", fill_value=0.0)
        vr = vv.at[c].get(mode="fill", fill_value=0.0)
        # padding entries may carry an in-range col (SparseDistArray pads
        # row out-of-range only), so zero their gradients entirely —
        # otherwise their reg term would shrink real factor rows
        w = ((r < uu.shape[0]) & (c < vv.shape[0])).astype(uu.dtype)
        err = (jnp.sum(ur * vr, axis=1) - x) * w
        gu = err[:, None] * vr + reg * ur * w[:, None]
        gv = err[:, None] * ur + reg * vr * w[:, None]
        # out-of-bounds scatter targets (padding) drop under jit
        uu = uu.at[r].add(-lr * gu)
        vv = vv.at[c].add(-lr * gv)
        return uu, vv

    return jax.lax.fori_loop(0, n_batches, body, (u, v))


@jax.jit
def _rmse(u, v, rows, cols, vals, nnz):
    ur = u.at[rows].get(mode="fill", fill_value=0.0)
    vr = v.at[cols].get(mode="fill", fill_value=0.0)
    pred = jnp.sum(ur * vr, axis=1)
    valid = rows < u.shape[0]
    se = jnp.sum(jnp.where(valid, (pred - vals) ** 2, 0.0))
    return jnp.sqrt(se / nnz)


def sgd_matrix_factorization(
        ratings: SparseDistArray, k: int = 16, num_epochs: int = 10,
        lr: float = 0.02, reg: float = 0.02, batch: int = 4096,
        seed: int = 0) -> Tuple[np.ndarray, np.ndarray]:
    """Factor sparse ``ratings`` into (U, V) with U @ V^T ~= R.

    Returns dense (n_users, k) and (n_items, k) NumPy factors."""
    n_users, n_items = ratings.shape
    rng = np.random.RandomState(seed)
    scale = 1.0 / np.sqrt(k)
    u = jnp.asarray(rng.rand(n_users, k).astype(np.float32) * scale)
    v = jnp.asarray(rng.rand(n_items, k).astype(np.float32) * scale)

    # epoch order: one fixed shuffle of the entry stream (padding rides
    # along; its gathers/scatters are dropped)
    perm = rng.permutation(ratings.nse)
    rows_h = np.asarray(jax.device_get(ratings.rows))[perm]
    cols_h = np.asarray(jax.device_get(ratings.cols))[perm]
    vals_h = np.asarray(jax.device_get(ratings.data))[perm]
    batch = min(batch, max(int(rows_h.shape[0]), 1))
    # pad the stream to a batch multiple with fully out-of-range entries
    # so the tail is trained on rather than silently dropped
    pad = -rows_h.shape[0] % batch
    if pad:
        rows_h = np.concatenate(
            [rows_h, np.full(pad, n_users, rows_h.dtype)])
        cols_h = np.concatenate(
            [cols_h, np.full(pad, n_items, cols_h.dtype)])
        vals_h = np.concatenate([vals_h, np.zeros(pad, vals_h.dtype)])
    rows, cols, vals = (jnp.asarray(rows_h), jnp.asarray(cols_h),
                        jnp.asarray(vals_h))

    for _ in range(num_epochs):
        u, v = _sgd_epoch(u, v, rows, cols, vals,
                          batch=batch, lr=lr, reg=reg)
    return np.asarray(jax.device_get(u)), np.asarray(jax.device_get(v))


def rmse(ratings: SparseDistArray, u, v) -> float:
    """Root-mean-square error over the observed entries."""
    return float(_rmse(jnp.asarray(u), jnp.asarray(v), ratings.rows,
                       ratings.cols, ratings.data, ratings.nnz))
