"""Locality-sensitive hashing (reference family: ``[U]
spartan/examples/lsh.py`` — SURVEY.md §2.4 application tier).

Random-hyperplane (SimHash) signatures for cosine similarity: the
O(n·d·b) signature computation is one sharded GEMM against a
replicated projection matrix plus an elementwise sign/bit-pack —
owner-computes on the row-sharded points, the classic Spartan shape.
Banding and candidate-pair extraction work on the (n, bands) packed
signatures, which are tiny next to the data.
"""

from __future__ import annotations

from typing import Dict, List, Set, Tuple

import numpy as np

import spartan_tpu as st
from ..expr.base import as_expr


def signatures(points, n_bits: int = 64, seed: int = 0) -> np.ndarray:
    """(n, n_bits) sign bits of X @ R for random Gaussian R."""
    points = as_expr(points)
    d = points.shape[1]
    rng = np.random.RandomState(seed)
    r = rng.randn(d, n_bits).astype(np.float32)
    proj = st.dot(points, as_expr(r))  # sharded GEMM, R replicated
    bits = st.astype(proj > 0.0, np.int32)
    return np.asarray(bits.glom()).astype(np.uint8)


def band_signatures(bits: np.ndarray, bands: int) -> np.ndarray:
    """Pack each band's bit-slice into one uint64 per (row, band)."""
    n, nb = bits.shape
    if nb % bands:
        raise ValueError(f"{nb} bits not divisible into {bands} bands")
    rows_per = nb // bands
    if rows_per > 64:
        raise ValueError("band width > 64 bits")
    weights = (1 << np.arange(rows_per, dtype=np.uint64))
    return (bits.reshape(n, bands, rows_per).astype(np.uint64)
            * weights[None, None, :]).sum(axis=2)


def candidate_pairs(points, n_bits: int = 64, bands: int = 8,
                    seed: int = 0) -> Set[Tuple[int, int]]:
    """Pairs sharing at least one band hash (the LSH candidates for
    high cosine similarity)."""
    packed = band_signatures(signatures(points, n_bits, seed), bands)
    out: Set[Tuple[int, int]] = set()
    for b in range(bands):
        buckets: Dict[int, List[int]] = {}
        for i, h in enumerate(packed[:, b]):
            buckets.setdefault(int(h), []).append(i)
        for members in buckets.values():
            for x in range(len(members)):
                for y in range(x + 1, len(members)):
                    out.add((members[x], members[y]))
    return out


def hamming_similarity(points, i: int, j: int, n_bits: int = 256,
                       seed: int = 0) -> float:
    """Estimated cosine similarity of rows i, j from signature
    agreement: cos(pi * (1 - agree_frac)). Projects ONLY the two rows
    (fetching one shard row each) — never the whole dataset."""
    points = as_expr(points)
    d = points.shape[1]
    rng = np.random.RandomState(seed)
    r = rng.randn(d, n_bits).astype(np.float32)
    two = np.stack([np.asarray(points[i].glom()),
                    np.asarray(points[j].glom())])
    bits = (two @ r) > 0.0
    agree = float((bits[0] == bits[1]).mean())
    return float(np.cos(np.pi * (1.0 - agree)))
