"""Fuzzy k-means (reference: ``[U] spartan/examples/fuzzy_kmeans.py`` —
SURVEY.md §2.4). Soft assignments with fuzziness m; each iteration is one
traced computation: membership weights + weighted center accumulation
(the reducer-merge becomes a psum over the batch axis)."""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

import spartan_tpu as st
from ..array import tiling as tiling_mod
from ..expr.base import Expr, ValExpr, as_expr
from ..expr.map2 import map2


def fuzzy_kmeans_step(points: Expr, centers: Expr, k: int,
                      m: float = 2.0) -> Expr:
    def kern(p, c):
        d2 = (jnp.sum(p * p, 1, keepdims=True) - 2.0 * p @ c.T
              + jnp.sum(c * c, 1)[None, :])
        d2 = jnp.maximum(d2, 1e-12)
        inv = d2 ** (-1.0 / (m - 1.0))
        u = inv / inv.sum(axis=1, keepdims=True)  # memberships (n, k)
        um = u ** m
        sums = um.T @ p  # (k, d) weighted sums
        wsum = um.sum(axis=0)  # (k,)
        return jnp.concatenate([sums, wsum[:, None]], axis=1)

    acc = map2([points, centers], kern,
               out_tiling=tiling_mod.replicated(2))
    sums = acc[:, :-1]
    w = acc[:, -1:]
    return sums / st.maximum(w, 1e-12)


def fuzzy_kmeans(points, k: int, num_iter: int = 10, m: float = 2.0,
                 seed: int = 0) -> np.ndarray:
    points = as_expr(points)
    n, d = points.shape
    rng = np.random.RandomState(seed)
    centers: Expr = as_expr(
        points[np.sort(rng.choice(n, k, replace=False))].glom())
    for _ in range(num_iter):
        centers = ValExpr(
            fuzzy_kmeans_step(points, centers, k, m).evaluate())
    return centers.glom()
