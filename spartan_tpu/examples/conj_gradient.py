"""Conjugate gradient solver (reference: ``[U]
spartan/examples/conj_gradient.py`` — SURVEY.md §2.4).

Each CG step is a handful of lazy exprs (one SpMV-shaped dot + axpys +
two inner products); the whole update forces as one compiled program and
the driver loop hits the structural cache.
"""

from __future__ import annotations

import numpy as np

import spartan_tpu as st
from ..expr.base import Expr, ValExpr, as_expr


def conj_gradient(a, b, num_iter: int = 20, tol: float = 1e-6
                  ) -> np.ndarray:
    """Solve A x = b for SPD A."""
    a = as_expr(a)
    b = as_expr(b)
    n = b.shape[0]
    x = st.zeros((n,), np.float32)
    r = ValExpr((b - st.dot(a, x)).evaluate())
    p = r
    rs_old = float((r * r).sum().glom())
    for _ in range(num_iter):
        ap = st.dot(a, p)
        denom = float((p * ap).sum().glom())
        if abs(denom) < 1e-30:
            break
        alpha = rs_old / denom
        x = ValExpr((x + alpha * p).evaluate())
        r = ValExpr((r - alpha * ap).evaluate())
        rs_new = float((r * r).sum().glom())
        if np.sqrt(rs_new) < tol:
            break
        p = ValExpr((r + (rs_new / rs_old) * p).evaluate())
        rs_old = rs_new
    return x.glom()
