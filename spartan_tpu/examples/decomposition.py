"""Blocked matrix decompositions: Cholesky and QR (reference:
``[U] spartan/examples/`` cholesky, qr — SURVEY.md §2.4).

The reference ran blocked right-looking Cholesky / TSQR with per-tile
kernels and shuffle updates. TPU-first: the factorizations are traced
``jnp.linalg`` calls over the sharded operand — XLA's blocked
implementations run on the MXU, and a TSQR variant demonstrates the
explicit tree reduction over row shards for tall-skinny inputs.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

import spartan_tpu as st
from ..array import tiling as tiling_mod
from ..expr.base import Expr, as_expr
from ..expr.map2 import map2


def cholesky(a) -> Expr:
    """Lower-triangular factor of an SPD matrix."""
    a = as_expr(a)
    return map2([a], jnp.linalg.cholesky,
                out_tiling=tiling_mod.replicated(2))


def qr(a) -> Tuple[np.ndarray, np.ndarray]:
    """Thin QR of a (possibly row-sharded) matrix."""
    a = as_expr(a)

    def kern(x):
        q, r = jnp.linalg.qr(x)
        return jnp.concatenate([q, r], axis=0)  # pack (m+n, n)

    packed = map2([a], kern, out_tiling=tiling_mod.replicated(2)).glom()
    m = a.shape[0]
    return packed[:m], packed[m:]


def tsqr(a) -> Tuple[np.ndarray, np.ndarray]:
    """Tall-skinny QR: local QR per row shard, tree-reduced R factors —
    the owner-computes algorithm the reference's per-tile QR performed,
    expressed as one shard_map program."""
    from ..utils.compat import shard_map

    from ..parallel import mesh as mesh_mod

    a = as_expr(a)
    arr = a.evaluate()
    mesh = mesh_mod.get_mesh()
    n_x = mesh.shape[mesh_mod.AXIS_ROW]
    m, n = a.shape
    if m % max(n_x, 1) or m // max(n_x, 1) < n:
        # fall back to the plain path when shards would be wide
        return qr(a)

    row_t = tiling_mod.row(2)
    x = jax.device_put(arr.jax_array, row_t.sharding(mesh))

    def kern(block):
        q1, r1 = jnp.linalg.qr(block)  # local (m/p, n), (n, n)
        # gather all R factors, QR the stack, correct local Q
        rs = jax.lax.all_gather(r1, mesh_mod.AXIS_ROW)  # (p, n, n)
        stacked = rs.reshape(-1, n)
        q2, r = jnp.linalg.qr(stacked)
        my = jax.lax.axis_index(mesh_mod.AXIS_ROW)
        q2_mine = jax.lax.dynamic_slice_in_dim(q2, my * n, n, axis=0)
        return jnp.concatenate([q1 @ q2_mine, r], axis=0)

    packed = jax.jit(shard_map(
        kern, mesh=mesh, in_specs=(row_t.spec(),),
        out_specs=tiling_mod.Tiling((mesh_mod.AXIS_ROW, None)).spec()))(x)
    packed = np.asarray(jax.device_get(packed))
    shard_rows = m // n_x + n
    qs, r = [], None
    for p in range(n_x):
        blk = packed[p * shard_rows:(p + 1) * shard_rows]
        qs.append(blk[:m // n_x])
        r = blk[m // n_x:]
    return np.concatenate(qs, axis=0), r


def netflix_sgd(ratings, k: int = 16, num_iter: int = 10,
                lr: float = 0.01, reg: float = 0.05, seed: int = 0
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Netflix-style SGD matrix factorization (reference:
    ``[U] spartan/examples/netflix.py``): full-gradient descent on the
    observed entries, one traced step per iteration over the
    batch-sharded ratings."""
    ratings = as_expr(ratings)
    m, n = ratings.shape
    rng = np.random.RandomState(seed)
    u = rng.rand(m, k).astype(np.float32) * 0.1
    v = rng.rand(n, k).astype(np.float32) * 0.1

    def step(rv, uv, vv):
        pred = uv @ vv.T
        mask = (rv != 0).astype(rv.dtype)
        err = (pred - rv) * mask
        gu = err @ vv / jnp.maximum(mask.sum(), 1.0) + reg * uv
        gv = err.T @ uv / jnp.maximum(mask.sum(), 1.0) + reg * vv
        return jnp.concatenate([uv - lr * gu,
                                vv - lr * gv], axis=0)

    for _ in range(num_iter):
        eu = st.from_numpy(u, tiling=tiling_mod.replicated(2))
        ev = st.from_numpy(v, tiling=tiling_mod.replicated(2))
        packed = map2([ratings, eu, ev], step,
                      out_tiling=tiling_mod.replicated(2)).glom()
        u, v = packed[:m], packed[m:]
    return u, v
