"""OOM degradation ladder: trade speed for memory instead of dying.

GSPMD-style single-program execution means one chip's
``RESOURCE_EXHAUSTED`` kills the whole step — yet the fix, re-planning
the same DAG at a finer tiling (smaller per-chip shards), is exactly
what the tiling cost model already knows how to do, and redistribution
cost is plannable (PAPERS.md, memory-efficient array redistribution).
On an OOM-classified dispatch failure the policy engine walks this
ladder, rung by rung, until one fits:

1. ``finer_tiling`` — re-plan the (cloned) DAG forcing the
   finest divisible sharding the mesh can express on every interior
   node and the outputs: per-chip shard bytes drop by the added
   parallelism (halved/quartered tile extents).
2. ``fusion_off`` — additionally disable the map/reduce fusion passes:
   smaller fused kernels bound XLA's per-fusion live range (keeps the
   finer tiling of rung 1).
3. ``chunked`` — last resort: evaluate the root in row blocks
   (slices along axis 0), fetching each block to host and
   re-assembling — peak device memory is one block's worth. Only
   applies to array-shaped single roots.

Every rung evaluates under a *degrade context* whose rung name is
keyed into BOTH the plan-cache key (via ``_opt_flags_key``) and the
compile-cache key, so degraded and normal executables never collide;
the rung taken is recorded on the plan report (``st.explain``) and in
the ``resilience_degrade_<rung>`` counters.

The re-plan works on a CLONE of the raw DAG (fresh interior nodes,
shared leaves, cached frontiers collapsed to Val leaves), so forcing
tilings never mutates the user's expression objects or pollutes the
normal plan's signature.
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional

from ..obs.metrics import METRICS_FLAG as _METRICS_FLAG
from ..obs.metrics import REGISTRY
from ..utils import profiling as prof
from ..utils.config import FLAGS
from ..utils.log import log_warn

FLAGS.define_bool(
    "oom_degrade", True,
    "On a RESOURCE_EXHAUSTED dispatch failure, walk the degradation "
    "ladder (replan at finer tiling -> fusion passes off -> chunked "
    "row-block evaluation) instead of raising. Each rung is keyed "
    "into the plan/compile caches so degraded and normal executables "
    "never collide.")
FLAGS.define_int(
    "degrade_chunks", 0,
    "Row-block count for the 'chunked' ladder rung (0 = one block "
    "per mesh device, min 2).")

RUNGS = ("finer_tiling", "fusion_off", "chunked")

# Thread-local degrade context. expr/base reads ``_TLS.rung`` on every
# evaluate (one getattr) to key plans; only the ladder ever sets it.
_TLS = threading.local()


def active_rung() -> Optional[str]:
    return getattr(_TLS, "rung", None)


class _RungCtx:
    """Set/restore the degrade rung (and, for ``fusion_off``+, the
    fusion pass flags) around one degraded re-plan."""

    __slots__ = ("rung", "_prev", "_flags")

    def __init__(self, rung: str):
        self.rung = rung
        self._prev = None
        self._flags = None

    def __enter__(self) -> "_RungCtx":
        self._prev = getattr(_TLS, "rung", None)
        _TLS.rung = self.rung
        if self.rung in ("fusion_off", "chunked"):
            self._flags = (FLAGS.opt_map_fusion, FLAGS.opt_reduce_fusion)
            FLAGS.opt_map_fusion = False
            FLAGS.opt_reduce_fusion = False
        return self

    def __exit__(self, *exc: Any) -> None:
        _TLS.rung = self._prev
        if self._flags is not None:
            FLAGS.opt_map_fusion, FLAGS.opt_reduce_fusion = self._flags


class NotApplicable(Exception):
    """A rung that cannot apply to this root (e.g. chunking a scalar)."""


# -- DAG cloning ---------------------------------------------------------


def clone_for_replan(root: Any) -> Any:
    """Deep-copy the interior of a DAG (fresh ``_id``s, no forced
    tilings, no cached results) while SHARING leaves, and collapsing
    any interior node that already carries a result into a Val leaf —
    the same frontier the plan signature sees. Mutating the clone
    (``force_finer``) can then never touch user-held expression
    objects."""
    from ..array.distarray import DistArray
    from ..expr.base import ValExpr

    memo = {}

    def go(n):
        out = memo.get(n._id)
        if out is not None:
            return out
        if (n._result is not None and not isinstance(n, ValExpr)
                and isinstance(n._result, DistArray)):
            out = ValExpr(n._result)
        else:
            kids = n.children()
            if not kids:
                out = n  # leaves (Val/Scalar/Carry) are shared
            else:
                out = n.replace_children(tuple(go(k) for k in kids))
        memo[n._id] = out
        return out

    return go(root)


# -- autotune replan/warm (obs/monitor's daemon) ------------------------


def replan_for_profile(template: Any, mesh) -> Optional[Any]:
    """Re-plan a result-free template DAG under the CURRENTLY
    installed calibration profile — optimizer-only (the governor
    pattern): sign a fresh clone, look the key up, build on a miss.
    No compile, no dispatch. The fingerprint flag is part of the plan
    key, so the challenger lands in the plan cache WITHOUT touching
    the incumbent. Returns the (possibly cached) plan, or None when
    the structure is uncacheable."""
    from ..expr import base

    clone = clone_for_replan(template)
    plan_key, rctx = base.plan_signature(clone, mesh)
    plan = base.lookup_plan(plan_key)
    if plan is None:
        plan, _dag, _leaves = base._build_plan(clone, mesh, rctx,
                                               plan_key)
    return plan


def warm_evaluate(template: Any, mesh) -> bool:
    """Speculatively evaluate a fresh clone of ``template`` off the
    hot path (the autotune daemon's challenger warm-up): the dispatch
    compiles the re-planned executable so the first re-keyed hot-path
    request is a pure cache hit. Advisory — any failure is swallowed
    (counted) and the swap stands on the modeled win alone."""
    from ..expr import base
    from ..parallel import mesh as mesh_mod

    clone = clone_for_replan(template)
    try:
        with mesh_mod.use_mesh(mesh):
            base.evaluate(clone)
        return True
    except Exception:  # noqa: BLE001 - warm-up is advisory; the
        # resilience engine already classified/retried inside evaluate
        if _METRICS_FLAG._value:
            REGISTRY.counter(
                "monitor_warm_failures",
                "autotune challenger warm-up evaluations that failed "
                "(advisory; the hot-swap decision is model-based)").inc()
        return False


# -- rung 1/2: forced finer tiling --------------------------------------


def force_finer(dag: Any, mesh) -> int:
    """Force the finest divisible candidate tiling on every interior
    node of ``dag`` (call on a clone only). Returns how many nodes
    were re-forced. Runs inside ``_build_plan`` between the optimizer
    and the signature, so the forced markers land in the compile key."""
    from ..array import tiling as tiling_mod
    from ..expr import tiling_cost
    from ..expr.base import ScalarExpr, ValExpr
    from ..expr.optimize import dag_nodes

    forced = 0
    for n in dag_nodes(dag):
        if isinstance(n, (ValExpr, ScalarExpr)) or not n.children():
            continue
        if n.ndim == 0:
            continue
        cands = tiling_cost.candidates(n, mesh)
        if not cands:
            continue
        best = max(cands, key=lambda t: tiling_cost._parallelism(t, mesh))
        try:
            cur = tiling_mod.sanitize(n.out_tiling(), n.shape, mesh)
        except Exception:
            cur = tiling_mod.replicated(n.ndim)
        if (tiling_cost._parallelism(best, mesh)
                > tiling_cost._parallelism(cur, mesh)):
            n._forced_tiling = best
            forced += 1
    return forced


def _replan_evaluate(expr: Any, donated: List[Any], rung: str) -> Any:
    """Clone the raw DAG and evaluate it under the degrade context;
    the plan caches key on the rung, so repeated degradations of the
    same structure are plan-cache hits."""
    from ..expr import base

    clone = clone_for_replan(expr)
    with _RungCtx(rung):
        return base.evaluate(clone, donate=donated)


def rung_predicted_bytes(expr: Any, rung: str, mesh) -> Optional[int]:
    """The memory governor's modeled peak for ``rung``'s re-plan of
    ``expr`` — recorded on the resilience record next to the rung so a
    PREDICTIVE pick is distinguishable from a REACTIVE one in bug
    reports (``st.explain`` prints both). A plan-cache read in the
    common case (the rung's clone was just evaluated)."""
    from ..expr import base

    clone = clone_for_replan(expr)
    with _RungCtx(rung):
        plan_key, _rctx = base.plan_signature(clone, mesh)
        plan = base.lookup_plan(plan_key)
    if plan is None or plan.report is None:
        return None
    mem = plan.report.get("memory")
    if not mem:
        return None
    return int(mem["peak_bytes_per_chip"])


# -- rung 3: chunked row-block evaluation -------------------------------


def _chunk_bounds(n_rows: int, chunks: int) -> List[int]:
    chunks = max(2, min(chunks, n_rows))
    step = -(-n_rows // chunks)
    bounds = list(range(0, n_rows, step)) + [n_rows]
    return bounds


def _chunked_evaluate(expr: Any, mesh) -> Any:
    """Evaluate ``expr`` in row blocks: slice the root along axis 0,
    force each block separately (peak device memory ~ one block), fetch
    to host and re-assemble into a fresh DistArray. The spill rung —
    slow, but it completes."""
    import numpy as np

    from ..array import distarray as da
    from ..expr.base import TupleExpr
    from ..parallel import mesh as mesh_mod

    if isinstance(expr, TupleExpr) or expr.ndim == 0:
        raise NotApplicable(
            "chunked evaluation needs a single array-shaped root")
    n_rows = int(expr.shape[0])
    if n_rows < 2:
        raise NotApplicable("root has fewer than 2 rows to chunk")
    chunks = FLAGS.degrade_chunks or mesh_mod.device_count(mesh)
    bounds = _chunk_bounds(n_rows, chunks)
    out = np.empty(expr.shape, expr.dtype)
    with _RungCtx("chunked"):
        for lo, hi in zip(bounds[:-1], bounds[1:]):
            part = expr[lo:hi]
            out[lo:hi] = np.asarray(part.evaluate().glom())
    return da.from_numpy(out)


# -- the ladder ----------------------------------------------------------


def _count(rung: str) -> None:
    if _METRICS_FLAG._value:
        REGISTRY.counter(
            "resilience_degrades",
            "OOM degradations that produced a result").inc()
        REGISTRY.counter(
            f"resilience_degrade_{rung}",
            f"degradations resolved at the {rung} rung").inc()


def run_ladder(exc: BaseException, expr: Any, donated: List[Any],
               mesh, plan: Any) -> Any:
    """Walk the degradation ladder for an OOM-classified failure.

    Returns the evaluated result (also seeded onto ``expr._result``
    and recorded on the plan report / ``expr._resilience``); raises
    the last OOM (annotated) if every rung also OOMs or none applies.
    """
    from . import classify as classify_mod
    from .engine import _attach_note, _resilience_record

    if not FLAGS.oom_degrade:
        raise exc
    rec = _resilience_record(expr, plan)
    rec.setdefault("oom_events", 0)
    rec["oom_events"] += 1
    if _METRICS_FLAG._value:
        REGISTRY.counter(
            "resilience_oom_events",
            "dispatch failures classified as OOM").inc()
    last = exc
    for rung in RUNGS:
        log_warn("resilience: OOM (%s) — degrading to rung %r",
                 str(last)[:120], rung)
        try:
            with prof.span("degrade", rung=rung,
                           error=type(last).__name__):
                if rung == "chunked":
                    result = _chunked_evaluate(expr, mesh)
                else:
                    result = _replan_evaluate(expr, donated, rung)
        except NotApplicable:
            continue
        except Exception as e:  # noqa: BLE001 - ladder advance decision
            if classify_mod.classify(e) != classify_mod.OOM:
                _attach_note(
                    e, f"while degrading to rung {rung!r} after: {last}")
                raise
            last = e
            continue
        rec["rung"] = rung
        rec["degraded"] = True
        rec["origin"] = "reactive"  # vs "predictive" (memory governor)
        if rung != "chunked":
            try:  # the rung's modeled peak, next to the rung taken
                predicted = rung_predicted_bytes(expr, rung, mesh)
                if predicted is not None:
                    rec["rung_predicted_bytes"] = predicted
            except Exception:
                pass  # advisory: never mask a successful degradation
        _count(rung)
        expr._result = result
        expr._resilience = rec
        return result
    _attach_note(
        last, "OOM degradation ladder exhausted (rungs tried: "
        f"{', '.join(RUNGS)}); see docs/RESILIENCE.md")
    from ..obs import numerics as numerics_mod

    try:
        path = numerics_mod.dump_crash(
            reason="resilience: OOM degradation ladder exhausted",
            plan_report=plan.report if plan is not None else None,
            extra={"resilience": dict(rec)})
        log_warn("resilience: ladder exhausted; crash dump at %s", path)
    except Exception:
        pass  # forensics must never mask the real failure
    raise last
