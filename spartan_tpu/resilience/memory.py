"""Predictive memory governor: model peak HBM per plan, gate admission,
degrade BEFORE dying.

Every memory defense before this module was reactive: the OOM ladder
(:mod:`resilience.degrade`) fires only after XLA throws
RESOURCE_EXHAUSTED — wasting a compile + dispatch per rung — and the
serve engine admitted requests with no idea whether their combined
working sets fit in HBM. This module makes the plan's memory
high-water a *modeled* quantity (ROADMAP item 3's "predict instead of
react") with three consumers:

1. **The model** (:func:`estimate_report`, run at ``_build_plan`` time
   and stored on ``_Plan.report["memory"]``): a per-chip live-set
   schedule over the optimized DAG's topological order. Per node:
   output bytes under its chosen (sanitized) tiling, freed when its
   last consumer has been emitted; leaf arguments resident throughout;
   reshard staging priced by the same layout fractions as
   ``expr/tiling_cost.reshard_cost`` (a resharded operand materializes
   a destination-shard copy); reduces charge a pre-reduce
   operand-sized intermediate (the fused map->reduce tree is
   materialized at input size); contractions charge
   ``max(psum partial, reshard staging)`` — XLA overlaps the gathered
   operand with the partial's buffer, so summing both double-counts;
   ``lax.while_loop`` carries are double-buffered (old + new live
   across the condition read) while plain ``fori_loop`` map-bodies
   alias in place. Donation credits (aliasable donated-argument bytes)
   subtract at enforcement time. Validated against XLA's
   ``compiled.memory_analysis()`` (:func:`validate_plan`), with
   predicted-vs-actual recorded in the ``memory_prediction_error_ratio``
   metric.

2. **Predictive degradation** (:func:`maybe_degrade`, called by
   ``evaluate()`` before the FIRST dispatch of a plan-cache miss; plus
   :func:`redirect_governed` on hits of a plan already judged
   over-budget): if the predicted peak exceeds the budget
   (``FLAGS.hbm_budget_bytes``, auto-detected from device
   ``memory_stats`` when 0), the cheapest sufficient ladder rung is
   chosen UP FRONT by re-running the estimator against each rung's
   cloned re-plan — the happy path never burns a doomed compile, and
   the result is bit-identical to the reactively-degraded path (both
   evaluate the same rung-forced clone). Reactive retry
   (:func:`degrade.run_ladder`) stays as the fallback when the model
   was wrong.

3. **Memory-aware admission** (:func:`request_bytes`, consumed by
   ``serve/engine.py``'s reservation ledger): each in-flight dispatch
   reserves its predicted peak; submissions whose prediction would
   overflow the budget are rejected with ``Backpressure`` instead of
   an OOM that trips the whole engine.

Known blind spots (docs/MEMORY.md): XLA's fusion/rematerialization
decisions are approximated, serve-coalesced batch variants scale the
reservation linearly with batch size rather than re-modeling the
vmapped program, and auto-detected budgets require a backend that
implements ``memory_stats`` (TPU does; CPU returns None, leaving the
governor inert unless ``FLAGS.hbm_budget_bytes`` is set).

Imports only config/obs/resilience layers at module level (expr/array
load lazily inside functions), mirroring :mod:`resilience.degrade` —
``expr/base`` binds this module at import time.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from ..obs import ledger as ledger_mod
from ..obs.metrics import METRICS_FLAG as _METRICS_FLAG
from ..obs.metrics import REGISTRY
from ..utils import profiling as prof
from ..utils.config import FLAGS
from ..utils.log import log_debug, log_warn
from . import degrade

_GOVERNOR_FLAG = FLAGS.define_bool(
    "memory_governor", True,
    "Master switch for the predictive memory governor: estimate every "
    "plan's peak per-chip HBM at build time, pick an OOM-ladder rung "
    "BEFORE the first dispatch when the prediction exceeds the budget, "
    "and gate serve admission on the in-flight reservation ledger. "
    "Inert when no budget is known (hbm_budget_bytes=0 on a backend "
    "without memory_stats, e.g. CPU). Off = the PR-5 reactive ladder "
    "only.")
_BUDGET_FLAG = FLAGS.define_int(
    "hbm_budget_bytes", 0,
    "Per-chip HBM budget the governor enforces. 0 = auto-detect from "
    "the smallest bytes_limit across local devices' memory_stats "
    "(None on backends without memory_stats: governor inert). "
    "Override for tests or to leave headroom below the physical "
    "limit.")

# sentinel: the governor declined to act; evaluate() proceeds normally
NOT_HANDLED = object()

# (mutation_count, mesh epoch) -> budget. Auto-detection probes every
# local device; memoize on flag state + mesh epoch so the hot path
# pays two int compares.
_budget_lock = threading.Lock()
_budget_memo: Tuple[Optional[Tuple[int, int]], Optional[int]] = (None, None)


def _detect_budget() -> Optional[int]:
    """Smallest bytes_limit across local devices (the chip that OOMs
    first bounds the single-program step), or None when the backend
    exposes no memory_stats."""
    try:
        import jax

        limits = []
        for d in jax.local_devices():
            stats = d.memory_stats() or {}
            if "bytes_limit" in stats:
                limits.append(int(stats["bytes_limit"]))
        return min(limits) if limits else None
    except Exception:
        return None


def hbm_budget_bytes() -> Optional[int]:
    """The enforced per-chip budget: ``FLAGS.hbm_budget_bytes`` when
    set, else the auto-detected device limit, else None (no governing
    possible)."""
    global _budget_memo
    from ..parallel import mesh as mesh_mod
    from ..utils import config as config_mod

    ver = (config_mod.mutation_count(), mesh_mod._EPOCH)
    memo_ver, budget = _budget_memo
    if memo_ver == ver:
        return budget
    explicit = _BUDGET_FLAG._value
    budget = int(explicit) if explicit else _detect_budget()
    with _budget_lock:
        _budget_memo = (ver, budget)
    return budget


# -- the estimator -------------------------------------------------------


def _shard_bytes(shape, dtype, tiling, mesh) -> float:
    """Per-chip bytes of ``shape``/``dtype`` laid out as ``tiling``."""
    import numpy as np

    from ..array import tiling as tiling_mod
    from ..expr.tiling_cost import _parallelism

    nbytes = float(int(np.prod(shape)) if shape else 1) \
        * np.dtype(dtype).itemsize
    t = tiling_mod.sanitize(tiling, shape, mesh)
    return nbytes / _parallelism(t, mesh)


def _node_shard_bytes(n: Any, mesh) -> float:
    from ..array import tiling as tiling_mod

    try:
        t = n.out_tiling()
    except Exception:
        t = tiling_mod.replicated(n.ndim)
    return _shard_bytes(n.shape, n.dtype, t, mesh)


def _staging_bytes(child: Any, req, mesh) -> float:
    """Destination-shard bytes a reshard edge materializes: the same
    per-axis layout fractions as ``tiling_cost.reshard_cost`` (zero
    when no wire traffic moves — same layout, or replicated source
    already covering the destination). Under
    ``FLAGS.redistribution_planner`` the edge has a CHOSEN collective
    schedule, so staging is the schedule's actual peak intermediate
    (``redistribute.staging_frac``) — e.g. a gather-then-slice route
    stages the gathered axis, an all_to_all route only its final
    shard — instead of the destination-shard approximation."""
    import numpy as np

    from ..expr.tiling_cost import reshard_cost
    from ..parallel import redistribute as redist_mod

    try:
        src = child.out_tiling()
    except Exception:
        return 0.0
    if src.axes == req.axes:
        return 0.0
    nbytes = float(child.size) * np.dtype(child.dtype).itemsize
    if reshard_cost(src, req, nbytes, mesh) <= 0.0:
        return 0.0  # e.g. replicated source: shards carved locally
    if redist_mod.planner_on():
        frac = redist_mod.staging_frac(src, req, mesh)
        if frac is not None:
            return frac * nbytes
    return _shard_bytes(child.shape, child.dtype, req, mesh)


def estimate_dag(dag: Any, out_tilings, mesh) -> Dict[str, Any]:
    """The per-chip live-set schedule (module docstring, consumer 1).

    Walks the optimized DAG in topological (post-) order simulating
    buffer lifetimes: a node's output shard is allocated at its emit
    and freed when its last consumer has been emitted; per-node
    transients (reduce intermediates, contraction partials, reshard
    staging, while-loop double buffers) are live only across the emit.
    Returns the peak, its components, and the top contributors at the
    peak step (the ``st.explain`` surface)."""
    from ..expr.base import ScalarExpr, TupleExpr, ValExpr
    from ..expr.loop import CarryExpr, LoopExpr
    from ..expr.map import MapExpr
    from ..expr.map2 import Map2Expr
    from ..expr.optimize import dag_nodes
    from ..expr.reduce import GeneralReduceExpr, ReduceExpr
    from ..expr.tiling_cost import _contraction_view, _operand_requirement

    nodes = dag_nodes(dag)
    roots = dag.elements if isinstance(dag, TupleExpr) else (dag,)
    root_ids = {r._id for r in roots}

    # bytes each node's output occupies once emitted (0 for nodes whose
    # storage is accounted elsewhere: leaves ride args, a TupleExpr is
    # its elements, a LoopExpr's carries ride its init args, and a
    # fori_loop's elementwise body root computes in place)
    alias_free: set = set()
    for n in nodes:
        if isinstance(n, LoopExpr) and not n.early_exit:
            for b in n.body_roots:
                if isinstance(b, MapExpr):
                    alias_free.add(b._id)

    args_bytes = 0.0
    leaf_entries: List[Tuple[str, float]] = []
    out_map: Dict[int, float] = {}
    for r, t in zip(roots, out_tilings):
        out_map[r._id] = _shard_bytes(r.shape, r.dtype, t, mesh)
    out_bytes = sum(out_map.values())

    size_of: Dict[int, float] = {}
    for n in nodes:
        if isinstance(n, (ValExpr, ScalarExpr)):
            b = _node_shard_bytes(n, mesh)
            args_bytes += b
            leaf_entries.append((f"{type(n).__name__}#{n._id} "
                                 f"{n.shape}", b))
            size_of[n._id] = 0.0  # resident via args_bytes
        elif isinstance(n, (CarryExpr, TupleExpr, LoopExpr)):
            size_of[n._id] = 0.0
        elif n._id in alias_free:
            size_of[n._id] = 0.0
        elif n._id in out_map:
            size_of[n._id] = out_map[n._id]
        else:
            size_of[n._id] = _node_shard_bytes(n, mesh)

    def transient(n: Any) -> float:
        kids = n.children()
        if isinstance(n, LoopExpr):
            if not n.early_exit:
                return 0.0
            # while_loop: old + new carry live across the condition
            return 2.0 * sum(
                _node_shard_bytes(b, mesh) for b in n.body_roots)
        if isinstance(n, Map2Expr):
            # opaque user kernel: the DAG cannot see its internal
            # temporaries (e.g. k-means' (n, k) distance matrix), so
            # charge the defensible FLOOR — the kernel at least reads
            # every operand. A known under-estimation class
            # (docs/MEMORY.md "blind spots").
            return sum(_node_shard_bytes(c, mesh) for c in kids)
        if isinstance(n, (ReduceExpr, GeneralReduceExpr)) and kids:
            # the fused pre-reduce tree materializes at operand size
            pre = getattr(n, "_pre_shape", None) or kids[0].shape
            best = 0.0
            for c in kids:
                try:
                    t = c.out_tiling()
                except Exception:
                    continue
                best = max(best, _shard_bytes(pre, c.dtype, t, mesh))
            return best
        cview = _contraction_view(n)
        if cview is not None and len(kids) >= 2:
            partial = _node_shard_bytes(n, mesh)
            staging = 0.0
            plan = getattr(n, "_dot_plan", None)
            reqs = None
            if plan is not None:
                try:
                    reqs = cview[1](plan[0], plan[1])
                except Exception:
                    reqs = None
            if reqs is not None:
                for c, req in zip(kids, reqs):
                    staging += _staging_bytes(c, req, mesh)
            # XLA reuses the gathered operand's buffer for the partial
            return max(partial, staging)
        staging = 0.0
        try:
            t = n.out_tiling()
        except Exception:
            return 0.0
        for i, c in enumerate(kids):
            try:
                req = _operand_requirement(n, t, c, i)
            except Exception:
                req = None
            if req is not None:
                staging += _staging_bytes(c, req, mesh)
        return staging

    refs: Dict[int, int] = {}
    for n in nodes:
        for c in n.children():
            refs[c._id] = refs.get(c._id, 0) + 1

    live: Dict[int, Tuple[str, float]] = {}
    live_sum = 0.0
    peak = 0.0
    peak_top: List[Tuple[str, float]] = []
    for n in nodes:
        tr = transient(n)
        here = live_sum + size_of[n._id] + tr
        if args_bytes + here > args_bytes + peak:
            peak = here
            peak_top = sorted(
                [(f"{type(n).__name__}#{n._id} {n.shape}",
                  size_of[n._id] + tr)]
                + list(live.values()) + leaf_entries,
                key=lambda kv: -kv[1])[:5]
        if size_of[n._id] > 0:
            live[n._id] = (f"{type(n).__name__}#{n._id} {n.shape}",
                           size_of[n._id])
            live_sum += size_of[n._id]
        for c in n.children():
            refs[c._id] -= 1
            if refs[c._id] == 0 and c._id in live and \
                    c._id not in root_ids:
                live_sum -= live.pop(c._id)[1]

    total = args_bytes + peak
    return {
        "peak_bytes_per_chip": int(total),
        "args_bytes": int(args_bytes),
        "out_bytes": int(out_bytes),
        "temp_bytes": int(max(0.0, total - args_bytes - out_bytes)),
        "top": [{"node": k, "bytes": int(v)} for k, v in peak_top],
    }


def estimate_report(dag: Any, out_tilings, mesh) -> Optional[Dict]:
    """``_build_plan``'s entry point: the estimate dict stored on
    ``_Plan.report["memory"]`` (plus budget context and the
    ``memory_predicted_bytes`` gauge). Advisory — a modeling failure
    on an exotic DAG returns None rather than failing the plan."""
    try:
        mem = estimate_dag(dag, out_tilings, mesh)
    except Exception as e:  # noqa: BLE001 - the model is advisory
        log_debug("memory governor: estimate failed (%s: %s)",
                  type(e).__name__, e)
        return None
    mem["budget_bytes"] = hbm_budget_bytes()
    mem["governed_rung"] = None
    if _METRICS_FLAG._value:
        REGISTRY.gauge(
            "memory_predicted_bytes",
            "modeled peak per-chip bytes of the most recently built "
            "plan (high-water tracked)").set(
                float(mem["peak_bytes_per_chip"]))
    return mem


def donation_credit(mem: Dict[str, Any], donated: List[Any],
                    mesh) -> float:
    """Bytes the budget check may discount when the dispatch donates
    buffers: XLA can alias donated argument HBM into the outputs, so
    up to ``out_bytes`` of donated-shard bytes never double-occupy."""
    if not donated:
        return 0.0
    credit = 0.0
    for arr in donated:
        try:
            credit += _shard_bytes(arr.shape, arr.dtype, arr.tiling,
                                   mesh)
        except Exception:
            continue
    return min(credit, float(mem.get("out_bytes", 0)))


# -- validation against XLA ---------------------------------------------


def _sharded_specs(plan: Any, mesh) -> Optional[List[Any]]:
    """Abstract args matching what ``_dispatch`` actually feeds: the
    report's arg specs with each array leaf's sharding attached (the
    plain specs compile an unsharded program whose memory bears no
    relation to the distributed dispatch)."""
    import jax

    from ..array import tiling as tiling_mod

    report = plan.report or {}
    raw = report.get("arg_specs")
    leaves = report.get("leaves")
    if raw is None or leaves is None or len(raw) != len(leaves):
        return None
    specs: List[Any] = []
    for spec, leaf in zip(raw, leaves):
        if leaf.get("kind") == "scalar":
            specs.append(spec)  # the recorded python scalar
            continue
        axes = leaf.get("tiling")
        if axes is None:
            return None
        t = tiling_mod.sanitize(
            tiling_mod.Tiling(axes), leaf["shape"], mesh)
        specs.append(jax.ShapeDtypeStruct(
            spec.shape, spec.dtype, sharding=t.sharding(mesh)))
    return specs


def validate_plan(plan: Any, mesh=None,
                  donate_pos: Tuple[int, ...] = ()) -> Optional[Dict]:
    """Compare the model against XLA's ``compiled.memory_analysis()``.

    AOT-compiles the plan's traced function over SHARDED arg specs
    (one extra compile — validation is a test/benchmark/debug surface,
    never on the dispatch path) and records
    ``memory_prediction_error_ratio`` = predicted / actual. Returns
    None when the backend exposes no memory analysis."""
    import jax

    from ..parallel import mesh as mesh_mod

    if plan is None or plan.report is None:
        return None
    mem = plan.report.get("memory")
    if not mem:
        return None
    if mesh is None:
        mesh = mesh_mod.get_mesh()
    specs = _sharded_specs(plan, mesh)
    if specs is None:
        return None
    try:
        jitted = (jax.jit(plan.traced,
                          donate_argnums=tuple(sorted(donate_pos)))
                  if donate_pos else jax.jit(plan.traced))
        with prof.phase("memory_validate"):
            compiled = jitted.lower(*specs).compile()
            ma = compiled.memory_analysis()
    except Exception as e:  # backend without AOT memory analysis
        log_debug("memory governor: validation unavailable (%s)", e)
        return None
    if ma is None:
        return None
    try:
        actual = int(ma.argument_size_in_bytes
                     + ma.output_size_in_bytes
                     + ma.temp_size_in_bytes
                     - ma.alias_size_in_bytes)
        alias = int(ma.alias_size_in_bytes)
    except AttributeError:
        return None
    predicted = int(mem["peak_bytes_per_chip"]) - int(min(
        alias, mem.get("out_bytes", 0)))
    ratio = (predicted / actual) if actual > 0 else None
    result = {
        "xla_peak_bytes": actual,
        "xla_argument_bytes": int(ma.argument_size_in_bytes),
        "xla_output_bytes": int(ma.output_size_in_bytes),
        "xla_temp_bytes": int(ma.temp_size_in_bytes),
        "xla_alias_bytes": alias,
        "predicted_bytes": predicted,
        "error_ratio": (round(ratio, 4) if ratio is not None else None),
    }
    mem["validation"] = result
    # cost ledger: the peak-HBM model's actuals feed — predicted vs
    # XLA-reported peak per plan digest (st.ledger closes the loop)
    ledger_mod.note_memory_actual(plan.report.get("plan_key"),
                                  predicted, actual)
    if _METRICS_FLAG._value and ratio is not None:
        REGISTRY.counter(
            "memory_validations",
            "plans validated against XLA memory_analysis").inc()
        REGISTRY.gauge(
            "memory_prediction_error_ratio",
            "predicted / XLA-reported peak bytes of the last validated "
            "plan (1.0 = exact; high-water tracks the worst "
            "overprediction)").set(float(ratio))
    return result


def predict(expr: Any, mesh=None) -> Optional[Dict]:
    """Public helper: the memory estimate for ``expr``'s plan (builds
    and caches the plan without dispatching, like ``st.explain``)."""
    from ..expr import base
    from ..parallel import mesh as mesh_mod

    if mesh is None:
        mesh = mesh_mod.get_mesh()
    root = expr if isinstance(expr, base.Expr) else base.as_expr(expr)
    if root._result is not None:
        return None
    plan_key, rctx = base.plan_signature(root, mesh)
    plan = base.lookup_plan(plan_key)
    if plan is None:
        plan, _dag, _ = base._build_plan(root, mesh, rctx, plan_key)
    if plan is None or plan.report is None:
        return None
    return plan.report.get("memory")


# -- predictive degradation (consumer 2) ---------------------------------


def _count(name: str, help_: str) -> None:
    if _METRICS_FLAG._value:
        REGISTRY.counter(name, help_).inc()


def _rung_estimate(expr: Any, rung: str, mesh
                   ) -> Tuple[Optional[Any], Optional[int]]:
    """Build (or look up) the rung's re-planned clone WITHOUT
    compiling or dispatching, and read its modeled peak. The plan is
    cached under the rung-keyed signature, so the follow-up
    ``_replan_evaluate`` hits it — choosing a rung costs one optimizer
    pass stack per rung, never a doomed XLA compile."""
    from ..expr import base

    clone = degrade.clone_for_replan(expr)
    with degrade._RungCtx(rung):
        plan_key, rctx = base.plan_signature(clone, mesh)
        plan = base.lookup_plan(plan_key)
        if plan is None:
            plan, _dag, _ = base._build_plan(clone, mesh, rctx,
                                             plan_key)
    if plan is None or plan.report is None:
        return None, None
    mem = plan.report.get("memory")
    if not mem:
        return plan, None
    return plan, int(mem["peak_bytes_per_chip"])


def choose_rung(expr: Any, mesh, budget: int
                ) -> Tuple[Optional[str], Optional[int]]:
    """The cheapest sufficient ladder rung for ``expr`` under
    ``budget``: the estimator re-runs against each rung's cloned
    re-plan, in ladder order (each rung trades more speed away), and
    the first rung predicted to fit wins. ``chunked`` is the
    unmodeled last resort (peak ~ one row block) when it applies."""
    from ..expr.base import TupleExpr

    for rung in ("finer_tiling", "fusion_off"):
        _plan, peak = _rung_estimate(expr, rung, mesh)
        if peak is not None and peak <= budget:
            return rung, peak
    if (not isinstance(expr, TupleExpr) and expr.ndim > 0
            and int(expr.shape[0]) >= 2):
        return "chunked", None
    return None, None


def _record_predictive(expr: Any, plan: Any, rung: str,
                       rung_peak: Optional[int]) -> Dict[str, Any]:
    from .engine import _resilience_record

    rec = _resilience_record(expr, plan)
    rec["rung"] = rung
    rec["degraded"] = True
    rec["origin"] = "predictive"
    if rung_peak is not None:
        rec["rung_predicted_bytes"] = int(rung_peak)
    mem = (plan.report or {}).get("memory")
    if mem:
        mem["governed_rung"] = rung
        if rung_peak is not None:
            mem["governed_peak_bytes"] = int(rung_peak)
    return rec


def _evaluate_rung(expr: Any, rung: str, donated: List[Any], mesh,
                   plan: Any) -> Any:
    """Dispatch the chosen rung; an OOM despite the model (the
    prediction was wrong) falls back to the REACTIVE ladder."""
    from . import classify as classify_mod

    try:
        if rung == "chunked":
            with prof.span("degrade", rung=rung, origin="predictive"):
                return degrade._chunked_evaluate(expr, mesh)
        with prof.span("degrade", rung=rung, origin="predictive"):
            return degrade._replan_evaluate(expr, donated, rung)
    except degrade.NotApplicable:
        raise
    except Exception as e:  # noqa: BLE001 - fall back to the ladder
        if classify_mod.classify(e) != classify_mod.OOM:
            raise
        log_warn("memory governor: predicted rung %r still OOMed; "
                 "falling back to the reactive ladder", rung)
        return degrade.run_ladder(e, expr, donated, mesh, plan)


def maybe_degrade(expr: Any, plan: Any, plan_key: Any,
                  donated: List[Any], mesh) -> Any:
    """The plan-cache-MISS enforcement point (``evaluate()`` calls
    this after ``_build_plan``, before the first dispatch). Returns
    the evaluated result when the governor degraded predictively, or
    :data:`NOT_HANDLED` to proceed with the normal dispatch."""
    if not _GOVERNOR_FLAG._value or not FLAGS.oom_degrade:
        return NOT_HANDLED
    if degrade.active_rung() is not None:
        return NOT_HANDLED  # already inside a degraded re-plan
    mem = plan.report.get("memory") if plan.report else None
    if not mem:
        return NOT_HANDLED
    budget = hbm_budget_bytes()
    if not budget:
        return NOT_HANDLED
    need = mem["peak_bytes_per_chip"] - donation_credit(
        mem, donated, mesh)
    need += resident_cache_bytes_per_chip(mesh)
    if need <= budget:
        return NOT_HANDLED
    rung, rung_peak = choose_rung(expr, mesh, budget)
    if rung is None:
        # nothing the ladder can express fits the budget: dispatch and
        # let the reactive path fight the (possibly real) OOM
        _count("memory_governor_unsatisfiable",
               "over-budget plans no ladder rung could bring under "
               "the budget (dispatched anyway)")
        return NOT_HANDLED
    log_warn("memory governor: predicted peak %.1f MiB > budget "
             "%.1f MiB; degrading to rung %r BEFORE dispatch",
             need / 2 ** 20, budget / 2 ** 20, rung)
    _count("resilience_predictive_degrades",
           "plans degraded predictively (before any dispatch/OOM)")
    rec = _record_predictive(expr, plan, rung, rung_peak)
    # later structurally-identical evaluates hit the UNGOVERNED plan:
    # mark both the identity plan and its cached twin so the hit path
    # redirects without re-estimating
    from ..expr import base

    plan.governed_rung = rung
    if plan_key is not None:
        stored = base.lookup_plan(plan_key)
        if stored is not None:
            stored.governed_rung = rung
    try:
        result = _evaluate_rung(expr, rung, donated, mesh, plan)
    except degrade.NotApplicable:
        return NOT_HANDLED
    expr._result = result
    expr._resilience = rec
    return result


def redirect_governed(expr: Any, plan: Any, donated: List[Any],
                      mesh) -> Any:
    """The plan-cache-HIT enforcement point: a plan already judged
    over-budget (``plan.governed_rung``) re-routes to its rung —
    steady state costs one clone + signature (a rung-keyed plan-cache
    hit), never a doomed dispatch. Falls through when the governor or
    budget has since been turned off."""
    if not _GOVERNOR_FLAG._value or not FLAGS.oom_degrade:
        return NOT_HANDLED
    if degrade.active_rung() is not None:
        return NOT_HANDLED
    if not hbm_budget_bytes():
        return NOT_HANDLED
    rung = plan.governed_rung
    _count("memory_governor_redirects",
           "plan-cache hits re-routed to their governed rung")
    rec = _record_predictive(expr, plan, rung, (plan.report or {}).get(
        "memory", {}).get("governed_peak_bytes"))
    try:
        result = _evaluate_rung(expr, rung, donated, mesh, plan)
    except degrade.NotApplicable:
        return NOT_HANDLED
    expr._result = result
    expr._resilience = rec
    return result


def resident_cache_bytes_per_chip(mesh) -> int:
    """Per-chip HBM pinned by the incremental engine's result cache
    (expr/incremental.py, FLAGS.result_cache_bytes): cached results
    hold live device buffers a new dispatch cannot reuse, so the
    governor charges them against the budget like any other resident
    set. Results are sharded, so the per-chip share is the cache total
    over the device count. Zero when the cache is empty/off."""
    from ..expr import incremental as inc_mod

    total = inc_mod.cache_bytes()
    if not total:
        return 0
    try:
        ndev = 1
        for v in dict(mesh.shape).values():
            ndev *= int(v)
    except Exception:
        ndev = 1
    return int(total / max(1, ndev))


# -- serve admission (consumer 3) ----------------------------------------


def request_bytes(plan: Any, leaves: List[Any], mesh) -> int:
    """Predicted per-chip peak for one serve request: the plan
    report's effective peak (the governed rung's, when one was
    chosen) — or, before the plan exists, the leaf-argument floor
    (every dispatch at least holds its inputs)."""
    mem = None
    if plan is not None and plan.report is not None:
        mem = plan.report.get("memory")
    if mem:
        return int(mem.get("governed_peak_bytes")
                   or mem["peak_bytes_per_chip"])
    from ..expr.base import _leaf_array

    floor = 0.0
    for leaf in leaves:
        arr = _leaf_array(leaf)
        if arr is None:
            continue
        try:
            floor += _shard_bytes(arr.shape, arr.dtype, arr.tiling,
                                  mesh)
        except Exception:
            continue
    return int(floor)
