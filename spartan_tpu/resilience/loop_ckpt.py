"""Checkpointed ``st.loop``: periodic carry snapshots + resume.

``st.loop(..., checkpoint_every=N, checkpoint_path=p, resume=p)``
splits the on-device loop into segments of N iterations. Each segment
is one ``lax.fori_loop`` dispatch (the plan caches make every segment
after the first a cache hit, and the iteration count is a traced
scalar, so a short final segment reuses the same executable); after
each segment the carries are snapshotted ATOMICALLY through
``utils/checkpoint`` (temp dir + ``os.replace``, per-shard CRC32 —
a killed process can never leave a half-written snapshot as the
latest). On a failed segment — after the in-evaluate policy engine
has already exhausted its retries — the driver restores the last good
snapshot and re-runs from there; ``resume=path`` does the same across
process restarts: a killed 20-iteration run resumed from its last
snapshot reproduces the uninterrupted final carry bit-for-bit
(segmentation does not change per-iteration math).

Composes with the PR-4 loop sentinel: ``health=True`` /
``early_exit=True`` / ``stall_tol`` are forwarded to every segment,
and an early-exited segment (divergence or convergence stall) ends
the whole loop at that snapshot.

Layout under ``checkpoint_path``::

    step_00000005/           carry snapshots after iteration 5
        carry0/  carry1/...  per-carry shard blobs + CRC manifests
        loop_meta.json       {"step": 5, "carries": k}
    step_00000010/
    LATEST.json              {"step": 10, "dir": "step_00000010"}

Only the last two snapshots are kept (the latest plus one fallback).
"""

from __future__ import annotations

import json
import os
import shutil
from typing import Any, Dict, List, Optional, Tuple

from ..obs.metrics import METRICS_FLAG as _METRICS_FLAG
from ..obs.metrics import REGISTRY
from ..utils import profiling as prof
from ..utils.config import FLAGS
from ..utils.log import log_info, log_warn
from . import classify as cls

FLAGS.define_int(
    "loop_restore_max", 3,
    "Max checkpoint restores per checkpointed st.loop run before the "
    "failure propagates (guards against a persistently-failing "
    "segment looping forever).")

_LATEST = "LATEST.json"
_KEEP_SNAPSHOTS = 2


def _count(name: str, help_: str) -> None:
    if _METRICS_FLAG._value:
        REGISTRY.counter(name, help_).inc()


def _step_dir(path: str, step: int) -> str:
    return os.path.join(path, f"step_{step:08d}")


def save_snapshot(path: str, step: int, carries: List[Any]) -> str:
    """Atomically snapshot the carries after iteration ``step``.

    Multi-process (an N-process ``jax.distributed`` mesh running one
    SPMD loop): every process writes its LOCAL shards straight into
    the final step dir (``utils/checkpoint.save`` barriers per array
    and rank 0 writes each array manifest), then rank 0 alone writes
    ``loop_meta.json`` and the ``LATEST.json`` commit marker — the
    single-process temp-dir + ``os.replace`` protocol would have every
    rank promote a private temp dir holding only its own shards."""
    import jax

    from ..utils import checkpoint as ckpt

    os.makedirs(path, exist_ok=True)
    multi = jax.process_count() > 1
    final = _step_dir(path, step)
    with prof.span("loop_checkpoint", step=step):
        if multi:
            ckpt.save_tree(final, {f"carry{i}": c
                                   for i, c in enumerate(carries)})
            if jax.process_index() == 0:
                with open(os.path.join(final, "loop_meta.json"),
                          "w") as f:
                    json.dump({"step": int(step),
                               "carries": len(carries)}, f)
                ltmp = os.path.join(path, f".{_LATEST}.{os.getpid()}")
                with open(ltmp, "w") as f:
                    json.dump({"step": int(step),
                               "dir": os.path.basename(final)}, f)
                os.replace(ltmp, os.path.join(path, _LATEST))
        else:
            tmp = os.path.join(path, f".tmp_step_{step}_{os.getpid()}")
            shutil.rmtree(tmp, ignore_errors=True)
            ckpt.save_tree(tmp, {f"carry{i}": c
                                 for i, c in enumerate(carries)})
            with open(os.path.join(tmp, "loop_meta.json"), "w") as f:
                json.dump({"step": int(step),
                           "carries": len(carries)}, f)
            shutil.rmtree(final, ignore_errors=True)
            os.replace(tmp, final)
            # LATEST.json is the commit marker: written (atomically)
            # only after the snapshot dir landed, so a reader never
            # sees a LATEST pointing at a partial snapshot
            ltmp = os.path.join(path, f".{_LATEST}.{os.getpid()}")
            with open(ltmp, "w") as f:
                json.dump({"step": int(step),
                           "dir": os.path.basename(final)}, f)
            os.replace(ltmp, os.path.join(path, _LATEST))
    _count("resilience_loop_checkpoints",
           "carry snapshots written by checkpointed st.loop")
    if not multi or jax.process_index() == 0:
        _prune(path, keep=_KEEP_SNAPSHOTS)
    return final


def _prune(path: str, keep: int) -> None:
    dirs = sorted(d for d in os.listdir(path) if d.startswith("step_"))
    for d in dirs[:-keep]:
        shutil.rmtree(os.path.join(path, d), ignore_errors=True)


def load_latest(path: str) -> Optional[Tuple[int, List[Any]]]:
    """(step, carries) of the last committed snapshot, or None.

    A snapshot written on a different mesh grid restores through the
    cross-mesh migration planner (``utils/checkpoint.load`` stamps a
    ``_migration`` record per carry); :func:`_note_restore_migrations`
    folds those into the loop record and the ``elastic_*`` metrics."""
    from ..utils import checkpoint as ckpt

    marker = os.path.join(path, _LATEST)
    if not os.path.exists(marker):
        return None
    with open(marker) as f:
        latest = json.load(f)
    snap = os.path.join(path, latest["dir"])
    with open(os.path.join(snap, "loop_meta.json")) as f:
        meta = json.load(f)
    tree = ckpt.load_tree(snap)
    carries = [tree[f"carry{i}"] for i in range(int(meta["carries"]))]
    return int(meta["step"]), carries


def _note_restore_migrations(carries: List[Any],
                             rec: Dict[str, Any]) -> None:
    """Fold the restored carries' planned cross-mesh migrations (the
    snapshot was written on a different grid) into the loop's
    resilience record and the elastic metrics family."""
    migs = [getattr(c, "_migration", None) for c in carries]
    migs = [m for m in migs if m]
    if not migs:
        return
    from . import elastic

    rec.setdefault("migrations", []).extend(migs)
    elastic.note_migrations(migs)
    log_info("st.loop restore: %d carr%s re-tiled through the "
             "migration planner (%d modeled wire bytes)", len(migs),
             "y" if len(migs) == 1 else "ies",
             sum(int(m.get("bytes", 0)) for m in migs))


def checkpointed_loop(n_iters: Any, body_fn: Any, init: Tuple[Any, ...],
                      *, with_index: bool, donate_init: bool,
                      health: bool, early_exit: bool, stall_tol: float,
                      every: int, path: Optional[str],
                      resume: Optional[str]) -> Any:
    """The driver behind ``st.loop(..., checkpoint_every=...)``.

    Runs eagerly (segments must dispatch to snapshot between them) and
    returns the final carries wrapped as ``ValExpr``s, so the call
    site keeps the lazy-loop surface (``.glom()`` / ``.evaluate()``).
    """
    from ..expr.base import ScalarExpr, ValExpr, as_expr
    from ..expr.loop import loop as _loop
    from ..obs import numerics as obs_numerics

    n_expr = as_expr(n_iters)
    if not isinstance(n_expr, ScalarExpr):
        raise TypeError(
            "st.loop(..., checkpoint_every=/resume=) needs a static "
            "(Python int) iteration count — segmentation happens on "
            "the host")
    n = int(n_expr.pyvalue)
    if path is None:
        path = resume
    every = int(every) if every and every > 0 else n
    if every < n and path is None:
        raise ValueError(
            "st.loop(checkpoint_every=...) needs checkpoint_path= "
            "(or resume=) to write snapshots to")

    start = 0
    carries: Optional[List[Any]] = None
    restore_migs: List[Any] = []
    if resume is not None:
        latest = load_latest(resume) if os.path.isdir(resume) else None
        if latest is not None:
            start, carries = latest
            _count("resilience_loop_resumes",
                   "checkpointed loops resumed from a snapshot")
            log_info("st.loop resume: restored iteration %d from %s",
                     start, resume)
            restore_migs = [c for c in carries
                            if getattr(c, "_migration", None)]
        else:
            log_info("st.loop resume: no snapshot under %r; starting "
                     "fresh", resume)
    if carries is None:
        carries = [as_expr(i).evaluate() for i in init]

    track_health = bool(health or early_exit)  # early_exit implies it
    rec: Dict[str, Any] = {
        "loop": True, "n": n, "checkpoint_every": every,
        "resumed_from": start if start else None,
        "restores": 0, "segments": 0, "retries": 0, "rung": None,
    }
    if restore_migs:
        _note_restore_migrations(restore_migs, rec)
    step = start
    restores = 0
    rehome_passes = 0
    stopped_early = False
    with prof.span("ckpt_loop", n=n, every=every, start=start):
        while step < n and not stopped_early:
            seg = min(every, n - step)
            offset = step

            if with_index:
                def body(i, *cs, _off=offset):
                    # per-segment offset rides a traced scalar, so the
                    # global index is right and the plan still caches
                    return body_fn(i + _off, *cs)
            else:
                body = body_fn

            try:
                with prof.span("loop_segment", step=step, seg=seg):
                    args = [ValExpr(c) for c in carries]
                    items = _loop(
                        seg, body, *args, with_index=with_index,
                        donate_init=donate_init, health=health,
                        early_exit=early_exit, stall_tol=stall_tol)
                    tup = (items,) if not isinstance(items, tuple) \
                        else items
                    results = [it.evaluate() for it in tup]
                    if track_health:
                        # health callbacks drain asynchronously; the
                        # early-exit decision below reads the series
                        obs_numerics._flush_effects(tuple(results))
            except Exception as e:
                kind = cls.classify(e)
                if kind == cls.DETERMINISTIC:
                    raise
                if kind == cls.STALE_MESH:
                    # after an elastic mesh rebuild, leaves captured
                    # by the body closure (the k-means points) still
                    # sit on the dead epoch: rehome them onto the new
                    # mesh and re-run the segment — the carries are
                    # already current (restored from snapshot or
                    # rehomed themselves). Each pass heals every array
                    # the error names, so this converges in one or two
                    # passes; the guard bounds pathological cases.
                    from . import elastic

                    rehome_passes += 1
                    if rehome_passes > 8:
                        raise
                    try:
                        healed = elastic.rehome(getattr(e, "arrays",
                                                        ()))
                    except Exception as re_exc:  # noqa: BLE001
                        # chaos injected INSIDE the rehome pass (the
                        # `recover` seam): a transient recovery fault
                        # re-enters — the segment re-runs, raises
                        # StaleMeshError again, and the next rehome
                        # pass (fault consumed) heals. Anything
                        # deterministic propagates.
                        if cls.classify(re_exc) == cls.DETERMINISTIC:
                            raise
                        log_warn("st.loop: rehome pass failed (%s); "
                                 "re-entering recovery",
                                 str(re_exc)[:120])
                        continue
                    if not healed:
                        raise
                    rec["rehomed"] = (rec.get("rehomed", 0)
                                      + len(e.arrays))
                    log_warn("st.loop: rehomed %d stale leaf "
                             "array(s) onto mesh epoch; re-running "
                             "segment at iteration %d",
                             len(e.arrays), step)
                    continue
                if kind == cls.FATAL_MESH:
                    # the policy engine already ran elastic recovery
                    # (drain -> rebuild_mesh -> evict); what is left
                    # is OUR rung: restore the carries from the last
                    # committed snapshot and re-enter the loop on the
                    # shrunken mesh. Falls through to the shared
                    # restore path below — load_latest lands the
                    # carries on the CURRENT (rebuilt) mesh, and held
                    # old-epoch carries are healed by the stale-mesh
                    # branch above on the re-run.
                    rec["mesh_rebuilt"] = True
                    _count("resilience_loop_elastic_resumes",
                           "checkpointed loops re-entered on a "
                           "rebuilt mesh after device loss")
                restores += 1
                rec["restores"] = restores
                _count("resilience_loop_restores",
                       "failed loop segments restored from the last "
                       "good snapshot")
                if restores > FLAGS.loop_restore_max:
                    try:
                        e.add_note(
                            f"checkpointed st.loop: "
                            f"{FLAGS.loop_restore_max} restores "
                            f"exhausted at iteration {step}")
                    except Exception:
                        pass
                    raise
                try:
                    latest = (load_latest(path)
                              if path and os.path.isdir(path) else None)
                except OSError as load_exc:
                    # mid-restore IO fault (an `io` chaos token, or a
                    # flaky filesystem): the snapshot on disk is still
                    # intact (atomic-commit protocol) — fall through
                    # to the held-carries re-run; the NEXT restore
                    # attempt reads it again
                    latest = None
                    log_warn("st.loop: snapshot restore failed (%s); "
                             "re-entering from held carries",
                             str(load_exc)[:120])
                if latest is not None:
                    step, carries = latest
                    # carries written on the pre-loss grid restore as
                    # planned migrations onto the rebuilt mesh
                    _note_restore_migrations(carries, rec)
                    log_warn("st.loop: segment failed (%s); restored "
                             "iteration %d from checkpoint",
                             str(e)[:120], step)
                    continue
                if any(getattr(c, "is_donated", False)
                       for c in carries):
                    try:
                        e.add_note(
                            "checkpointed st.loop: no snapshot to "
                            "restore and the segment donated its "
                            "carries — cannot safely re-run")
                    except Exception:
                        pass
                    raise
                log_warn("st.loop: segment failed (%s); no snapshot "
                         "yet — re-running from held carries",
                         str(e)[:120])
                continue

            # merge segment-level resilience records (retry/degrade
            # done by the policy engine inside evaluate)
            for it in tup:
                r = getattr(it, "_resilience", None)
                if r:
                    rec["retries"] += r.get("retries", 0)
                    if r.get("rung"):
                        rec["rung"] = r["rung"]
            carries = results
            rec["segments"] += 1
            if track_health:
                label = f"loop#{tup[0].loop._id}"
                series = obs_numerics.loop_health(label)
                executed = len(series)
                if early_exit and executed and executed < seg:
                    step += executed
                    stopped_early = True
                else:
                    step += seg
            else:
                step += seg
            if path is not None and (every < n or resume is not None):
                try:
                    save_snapshot(path, step, carries)
                except OSError as e:
                    # a failed snapshot must not kill a healthy run:
                    # the carries live on, and the next boundary
                    # retries the write (the atomic-swap protocol
                    # guarantees the previous snapshot is still good)
                    rec["checkpoint_failures"] = (
                        rec.get("checkpoint_failures", 0) + 1)
                    _count("resilience_checkpoint_failures",
                           "loop snapshot writes that failed "
                           "(non-fatal; previous snapshot intact)")
                    log_warn("st.loop: snapshot at iteration %d "
                             "failed (%s); continuing — previous "
                             "snapshot remains the restore point",
                             step, str(e)[:120])

    out = []
    for c in carries:
        v = ValExpr(c)
        v._resilience = rec
        out.append(v)
    return out[0] if len(out) == 1 else tuple(out)
