"""Elastic mesh recovery: the terminal rung of the resilience ladder.

Retry (PR 5) assumes the failing dispatch can succeed on the SAME
mesh; the OOM ladder assumes the mesh fits a smaller plan. Persistent
device/host death breaks both assumptions — the reference Spartan's
answer was lineage-based worker-death recovery (PAPER.md §5: the
master re-tiles over the survivors and the computation continues), and
this module is that answer rebuilt at GSPMD scale:

1. **detect** — ``resilience.classify`` maps persistent device-death
   statuses (``DATA_LOSS``, halted-client errors, ``INTERNAL: ...
   device``) and the injected ``device_loss`` chaos fault to
   ``fatal_mesh``; the policy engine routes that class here instead of
   retrying.
2. **drain** — the serve engine stops admitting (submissions and the
   queued backlog fail with a retryable
   :class:`~spartan_tpu.serve.future.MeshReconfiguring` carrying a
   retry-after), so no new dispatch can land on the dead mesh.
3. **rebuild** — ``parallel.mesh.rebuild_mesh(exclude_devices=...)``
   shrinks the mesh to the survivors and bumps the **mesh epoch**.
4. **invalidate** — every mesh-bound artifact is fenced by the epoch:
   plan/compile-cache keys carry it (stale plans miss;
   ``expr.base.evict_stale_plans`` reaps them here), DistArrays record
   their birth epoch (cross-epoch use raises ``StaleMeshError``), and
   ``get_mesh``'s thread-local pins are epoch-fenced.
5. **resume** — ``st.loop`` restores its carries from the latest
   ``LATEST.json`` snapshot (host-side restore sidesteps live
   redistribution: the planner's re-tile on the shrunken mesh is just
   a fresh ``_build_plan``) and re-enters the loop on the new mesh;
   serve clients resubmit after the retry-after.

What is recoverable: checkpointed loops (carries restored from disk),
serve traffic (resubmission), and any DistArray whose data is still
fetchable (replicated, or a simulated loss) via :func:`rehome`. What
is NOT: un-checkpointed state whose shards died with the device — the
``StaleMeshError`` says to re-create it from source.

Recovery is idempotent per epoch: concurrent fatal failures from
several serve workers trigger ONE drain/rebuild/evict (the losers
observe the bumped epoch and return). ``FLAGS.elastic_recovery=False``
turns the rung off — fatal mesh errors then fail fast like
deterministic ones.
"""

from __future__ import annotations

import re
import threading
from typing import Any, List, Optional, Sequence

from .. import persist as persist_mod
from ..obs.metrics import METRICS_FLAG as _METRICS_FLAG
from ..obs.metrics import REGISTRY
from ..parallel import mesh as mesh_mod
from ..utils import profiling as prof
from ..utils.config import FLAGS
from ..utils.log import log_warn

FLAGS.define_bool(
    "elastic_recovery", True,
    "Master switch for elastic mesh recovery: on a fatal_mesh "
    "failure, drain the serve engine, rebuild the mesh over the "
    "surviving devices (bumping the mesh epoch), evict the dead "
    "epoch's plans, and let checkpointed loops resume. Off = fatal "
    "mesh errors fail fast like deterministic ones.")
FLAGS.define_float(
    "elastic_retry_after_s", 0.1,
    "retry-after carried by MeshReconfiguring rejections during a "
    "mesh rebuild: the drain-and-rebuild is host-side work, so "
    "clients can resubmit almost immediately.")

_lock = threading.Lock()

# "device 3", "device: 3", "TPU_4" etc. in real status messages
_DEV_RE = re.compile(r"device[:\s#]*(\d+)", re.IGNORECASE)


def _count(name: str, help_: str, n: int = 1) -> None:
    if _METRICS_FLAG._value:
        REGISTRY.counter(name, help_).inc(n)


def infer_failed_devices(exc: BaseException) -> List[int]:
    """Which devices died, from the failure itself: an explicit
    ``failed_devices`` attribute (injected faults, FatalMeshError),
    else ``device N`` parsed from the status message, else the
    highest-ordinal device still in the mesh (a loss the runtime did
    not attribute must still shrink the mesh to make progress)."""
    ids = [int(d) for d in getattr(exc, "failed_devices", ()) or ()]
    if not ids:
        seen = getattr(exc, "__cause__", None)
        if seen is not None:
            ids = [int(d) for d in getattr(seen, "failed_devices", ())
                   or ()]
    if not ids:
        m = _DEV_RE.search(str(exc))
        if m:
            ids = [int(m.group(1))]
    if not ids:
        mesh = mesh_mod.get_mesh()
        ids = [max(d.id for d in mesh.devices.flat)]
    return ids


def _drain_serve(retry_after_s: float) -> int:
    """Stop the default serve engine admitting and fail its queued
    backlog with MeshReconfiguring (in-flight dispatches fail on
    their own and are mapped by the worker). No-op without a running
    engine. Returns requests drained."""
    from ..serve import engine as serve_engine

    eng = serve_engine.peek_default()
    if eng is None or not eng.running:
        return 0
    return eng.drain_reconfiguring(retry_after_s)


def on_fatal_mesh(exc: BaseException, mesh: Any = None) -> Optional[Any]:
    """Executed by the policy engine when a dispatch failure classifies
    ``fatal_mesh``: drain → rebuild → evict, idempotent per epoch.

    Returns the rebuilt mesh (or the current one, when another thread
    already recovered this epoch); None when elastic recovery is
    disabled. The caller still raises — the failed evaluation itself
    is not replayable (its inputs live on the dead mesh); recovery
    makes the NEXT dispatch (a loop's restored segment, a client's
    resubmission) land on a live mesh."""
    if not FLAGS.elastic_recovery:
        return None
    seen_epoch = mesh_mod._EPOCH
    with _lock:
        if mesh_mod._EPOCH != seen_epoch:
            # another worker's recovery already rebuilt past the epoch
            # this failure was dispatched under
            return mesh_mod.get_mesh()
        lost = infer_failed_devices(exc)
        retry_after = FLAGS.elastic_retry_after_s
        with prof.span("elastic_recover", epoch=seen_epoch,
                       lost=tuple(lost)) as sp:
            with prof.phase("drain"):
                drained = _drain_serve(retry_after)
            with prof.phase("rebuild"):
                new_mesh = mesh_mod.rebuild_mesh(exclude_devices=lost)
            from ..expr import base as expr_base

            with prof.phase("evict"):
                # in-memory plans AND the warm-start store's on-disk
                # entries of the dead epoch (spartan_tpu/persist) —
                # without the disk half, a later restart would
                # resurrect plans for the mesh that just died
                evicted = expr_base.evict_stale_plans()
                persisted = persist_mod.last_evicted()
            sp.set(drained=drained, evicted=evicted,
                   persist_evicted=persisted,
                   survivors=int(new_mesh.devices.size))
        _count("elastic_recoveries",
               "fatal mesh failures recovered by drain/rebuild/evict")
        _count("elastic_plans_evicted",
               "dead-epoch plans evicted during elastic recovery",
               evicted)
        _resume_serve()
        log_warn(
            "elastic: mesh epoch %d -> %d after device loss %s — %d "
            "survivor(s), %d plan(s) evicted (+%d persisted entr%s), "
            "%d serve request(s) drained; resume loops from "
            "checkpoint, resubmit serve requests", seen_epoch,
            mesh_mod._EPOCH, lost, int(new_mesh.devices.size), evicted,
            persisted, "y" if persisted == 1 else "ies", drained)
        return new_mesh


def _resume_serve() -> None:
    from ..serve import engine as serve_engine

    eng = serve_engine.peek_default()
    if eng is not None:
        eng.resume_admission()


def rehome(arrays: Sequence[Any]) -> int:
    """Migrate stale-epoch DistArrays onto the current mesh (host
    round-trip, in place — see ``DistArray.rehome``). The loop driver
    calls this with ``StaleMeshError.arrays`` after a recovery, so a
    body closure's captured leaves (the k-means points) follow the
    carries onto the shrunken mesh. Returns arrays migrated."""
    n = 0
    for arr in arrays:
        if getattr(arr, "_epoch", None) != mesh_mod._EPOCH:
            arr.rehome()
            n += 1
    if n:
        _count("elastic_rehomed",
               "stale-epoch DistArrays migrated onto the rebuilt "
               "mesh", n)
    return n
